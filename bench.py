"""Benchmark: amorphous-plasticity set-transformer beta-sweep on TPU.

This is the BASELINE.json north-star workload: the full per-particle DIB
set-transformer configuration from the reference (amorphous notebook cell 8
— encoder MLP 128x2 -> 2x32, 6 attention blocks x 12 heads x key_dim 128,
batch 32 neighborhoods x 50 particles, 25,000 steps) swept over a grid of
beta endpoints as ONE jitted vmapped program.

It times the steady-state sweep throughput on the available device and
projects the wall-clock of the complete north-star run (R replicas x 25k
steps). ``vs_baseline`` is the projection divided by the 10-minute target
the driver set for a v4-8 (BASELINE.json ``north_star``); < 1.0 beats the
target.

Prints exactly ONE JSON line to stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NUM_REPLICAS = 8
FULL_SWEEP_STEPS = 25_000          # reference run length per protocol
BASELINE_MINUTES = 10.0            # driver-set north-star target (v4-8)
STEPS_PER_EPOCH = 50
MEASURE_EPOCHS = 6                 # 6 * 50 * 8 replicas = 2400 sweep steps


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _subprocess_probe(timeout_s: int) -> str | None:
    """Probe backend init in a KILLABLE child process.

    A dead TPU tunnel makes ``jax.devices()`` HANG indefinitely rather than
    raise (observed: multi-hour hangs that SIGALRM cannot interrupt — the
    block never yields to Python signal handlers). Probing in a subprocess
    with a hard timeout turns the hang into a retryable failure without
    wedging the benchmark process. Returns None on success, else a reason.
    """
    import subprocess

    code = (
        "import os, jax, jax.numpy as jnp\n"
        "d = jax.devices()\n"
        "assert d[0].platform != 'cpu' or os.environ.get('DIB_BENCH_ALLOW_CPU'), \\\n"
        "    'backend resolved to CPU'\n"
        "jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return f"probe hung > {timeout_s}s (tunnel down?)"
    if proc.returncode != 0:
        stderr = (proc.stderr or "").strip()
        return stderr.splitlines()[-1] if stderr else "probe failed"
    return None


def _wait_for_device(retries: int = 6, delay_s: float = 60.0,
                     probe_timeout_s: int = 150):
    """Wait for a usable accelerator: a freshly restarted TPU worker (or a
    tunnel recovering from a crash) can be unavailable — or hanging — for
    minutes. Only after a subprocess probe succeeds does THIS process
    initialize its backend (avoiding an un-killable in-process hang)."""
    import jax
    import jax.numpy as jnp

    last_error: Exception | None = None
    for attempt in range(retries):
        reason = _subprocess_probe(probe_timeout_s)
        if reason is None:
            # the parent's own init can still hit a transient transport
            # error in the window after the probe — keep it retryable
            try:
                devices = jax.devices()
                if devices[0].platform == "cpu" and not os.environ.get(
                    "DIB_BENCH_ALLOW_CPU"
                ):
                    # a swallowed TPU-init failure silently falls back to
                    # CPU; a CPU number against the 10-min TPU target is
                    # meaningless
                    raise RuntimeError(
                        "benchmark backend resolved to CPU (TPU init failed "
                        "or JAX_PLATFORMS unset); set DIB_BENCH_ALLOW_CPU=1 "
                        "to force a CPU run"
                    )
                jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
                return devices
            except Exception as e:
                reason, last_error = str(e), e
                try:
                    # drop the dead client so the next attempt re-inits
                    import jax.extend as jex

                    jex.backend.clear_backends()
                except Exception:
                    pass
        log(f"device probe {attempt + 1}/{retries} failed: {reason}")
        if attempt == retries - 1:
            raise last_error or RuntimeError(
                f"no usable device after {retries} probes: {reason}"
            )
        time.sleep(delay_s)


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dib_tpu.data import get_dataset
    from dib_tpu.models import PerParticleDIBModel
    from dib_tpu.parallel import BetaSweepTrainer
    from dib_tpu.train import TrainConfig

    devices = _wait_for_device()
    log(f"devices: {devices}")

    bundle = get_dataset("amorphous_particles", num_synthetic_neighborhoods=2048)
    # Full paper architecture; attention/FF matmuls in bfloat16 (MXU-native,
    # ~1.5x over f32 on v5e) — KL, sampling, and logits stay float32.
    model = PerParticleDIBModel(num_particles=50, compute_dtype="bfloat16")
    config = TrainConfig(
        learning_rate=1e-4,
        batch_size=32,
        num_pretraining_epochs=0,
        num_annealing_epochs=FULL_SWEEP_STEPS // STEPS_PER_EPOCH,
        steps_per_epoch=STEPS_PER_EPOCH,
        max_val_points=256,
        warmup_steps=500,
    )
    # Grid of annealing end-betas around the paper's 2e-1, shared start 2e-6.
    beta_ends = np.logspace(-2, 0, NUM_REPLICAS)
    sweep = BetaSweepTrainer(model, bundle, config, 2e-6, beta_ends)

    init_keys = jax.random.split(jax.random.key(0), NUM_REPLICAS)
    warm_keys = jax.random.split(jax.random.key(1), NUM_REPLICAS)
    meas_keys = jax.random.split(jax.random.key(2), NUM_REPLICAS)
    t0 = time.time()
    states, histories = sweep.init(init_keys)

    # Warmup chunk: triggers compile of the full epoch scan (num_epochs is a
    # static arg, so warm with the same value the measurement uses).
    states, histories = sweep.run_chunk(states, histories, warm_keys, MEASURE_EPOCHS)
    jax.block_until_ready(states.params)
    compile_s = time.time() - t0
    log(f"init+compile+first epoch: {compile_s:.1f}s")

    t1 = time.time()
    states, histories = sweep.run_chunk(
        states, histories, meas_keys, MEASURE_EPOCHS
    )
    jax.block_until_ready(states.params)
    measure_s = time.time() - t1

    sweep_steps = MEASURE_EPOCHS * STEPS_PER_EPOCH * NUM_REPLICAS
    steps_per_s = sweep_steps / measure_s
    # Validation runs once per epoch inside the measured chunk, so the
    # projection includes instrumentation overhead, as the north star does.
    projected_s = FULL_SWEEP_STEPS * NUM_REPLICAS / steps_per_s + compile_s
    projected_min = projected_s / 60.0

    log(
        f"measured {sweep_steps} sweep steps in {measure_s:.2f}s "
        f"({steps_per_s:.0f} steps/s); projected full sweep "
        f"({NUM_REPLICAS} replicas x {FULL_SWEEP_STEPS} steps): "
        f"{projected_min:.2f} min"
    )
    # Sanity: training must not have gone non-finite anywhere in the run.
    kl = np.asarray(histories["kl_per_feature"])
    assert np.isfinite(kl).all(), "non-finite KL in benchmark run"

    print(
        json.dumps(
            {
                "metric": "amorphous_set_transformer_beta_sweep_projected",
                "value": round(projected_min, 3),
                "unit": "minutes",
                "vs_baseline": round(projected_min / BASELINE_MINUTES, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
