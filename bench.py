"""Benchmark: amorphous-plasticity set-transformer beta-sweep on TPU.

This is the BASELINE.json north-star workload: the full per-particle DIB
set-transformer configuration from the reference (amorphous notebook cell 8
— encoder MLP 128x2 -> 2x32, 6 attention blocks x 12 heads x key_dim 128,
batch 32 neighborhoods x 50 particles, 25,000 steps) swept over a grid of
beta endpoints as ONE jitted vmapped program.

It times the steady-state sweep throughput on the available device, projects
the wall-clock of the complete north-star run (R replicas x 25k steps), and
reports conventional MFU (analytic model matmul FLOPs, fwd + bwd, vs the
chip's bf16 peak — see docs/performance.md; the unreliable-on-this-backend
``mfu_hlo`` was dropped in round 4). ``vs_baseline`` is the projection
divided by the 10-minute target the driver set for a v4-8 (BASELINE.json
``north_star``); < 1.0 beats the target. A persistent XLA compilation
cache is enabled by default (``DIB_COMPILE_CACHE`` to override) so warm
invocations skip the ~146 s cold compile.

Architecture (hardened after round 1, where a dead TPU tunnel burned the
whole perf round): a PARENT process that never initializes an accelerator
backend orchestrates a CHILD (``bench.py --child``) that does all device
work. A dead tunnel makes backend init HANG un-killably in-process (signals
never fire), so every device interaction lives in a killable subprocess.
The parent retries within a total time budget and ALWAYS prints exactly one
JSON line and exits 0: a fresh measurement when the device cooperates,
otherwise a ``degraded`` record embedding the last good measurement from
the committed ``BENCH_CACHE.json``.

Environment knobs:
  DIB_BENCH_TOTAL_BUDGET_S  total parent budget, default 1050 (round 1's
                            driver captured a ~20-min bench run; the last
                            child attempt can overrun the deadline by up
                            to ~90s, so the default leaves real margin
                            under that envelope — the degraded JSON must
                            be emitted before any external timeout)
  DIB_BENCH_ALLOW_CPU       permit a CPU measurement (testing only)
  DIB_BENCH_FRESH           ignore the cache (degraded output has value null)

Prints exactly ONE JSON line to stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

CACHE_PATH = os.path.join(REPO, "BENCH_CACHE.json")
METRIC = "amorphous_set_transformer_beta_sweep_projected"

DEFAULT_REPLICAS = 8
DEFAULT_STEPS_PER_EPOCH = 50
DEFAULT_MEASURE_EPOCHS = 6
NUM_REPLICAS = int(os.environ.get("DIB_BENCH_REPLICAS", DEFAULT_REPLICAS))
FULL_SWEEP_STEPS = 25_000          # reference run length per protocol
BASELINE_MINUTES = 10.0            # driver-set north-star target (v4-8)
STEPS_PER_EPOCH = int(
    os.environ.get("DIB_BENCH_STEPS_PER_EPOCH", DEFAULT_STEPS_PER_EPOCH)
)
MEASURE_EPOCHS = int(
    os.environ.get("DIB_BENCH_MEASURE_EPOCHS", DEFAULT_MEASURE_EPOCHS)
)
BENCH_BATCH_SIZE = 32              # reference batch (amorphous nb cell 8)

def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def peak_tflops_for(device_kind: str) -> float | None:
    """bf16 matmul peak from the shared per-backend capability table
    (``dib_tpu/telemetry/xla_stats.py`` — the one copy the profiler and
    the run reports read too)."""
    from dib_tpu.telemetry.xla_stats import backend_peaks

    peaks = backend_peaks(device_kind)
    return peaks["bf16_tflops"] if peaks else None


def analytic_model_flops_per_step(model, batch_size: int) -> float:
    """Matmul FLOPs of one train step (fwd + 2x bwd), conventional-MFU style.

    Counts only the dense/attention matmuls (2*M*N*K each) of the
    per-particle DIB model — encoder MLP, QKV/out projections, the two
    [P, P] attention matmuls, feed-forward, head — exactly the FLOPs the
    standard MFU definition uses (elementwise ops, LayerNorms, optimizer
    update excluded). The HLO ``cost_analysis`` number is reported
    separately: it covers the whole chunk program (training + per-epoch
    validation + history bookkeeping) and its availability/semantics vary
    by backend, so it is not comparable across rounds (ADVICE round 2).
    """
    P = model.num_particles
    F = model.particle_feature_dim
    d = model.embedding_dim
    qkv = model.num_heads * model.key_dim

    def mlp_flops(dims):
        return 2 * sum(a * b for a, b in zip(dims[:-1], dims[1:]))

    enc = P * mlp_flops([F, *model.encoder_hidden, 2 * d])
    attn = (
        3 * 2 * P * d * qkv          # Q, K, V projections
        + 2 * 2 * P * P * qkv        # scores + attention-weighted values
        + 2 * P * qkv * d            # output projection
    )
    ff = P * mlp_flops([d, *model.ff_hidden, d])
    head = mlp_flops([d, *model.head_hidden, model.output_dim])
    forward = batch_size * (enc + model.num_blocks * (attn + ff) + head)
    return 3.0 * forward             # backward ~= 2x forward for matmuls


# ==========================================================================
# CHILD: all device work happens here, killable from the parent.
# ==========================================================================

def _honor_platform_env() -> None:
    """Re-apply JAX_PLATFORMS after import: this box's sitecustomize
    pre-imports jax with the tunnel backend baked into jax.config, so the
    env var alone is read too early to take effect (same workaround as
    tests/conftest.py)."""
    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)


def child_main() -> None:
    _honor_platform_env()
    from dib_tpu.utils.compile_cache import enable_persistent_cache

    # Persistent XLA cache (VERDICT round 3 item 4b): cold compiles cost
    # ~146 s of the bench envelope; warm runs come up in ~25 s. Opt out
    # with DIB_COMPILE_CACHE=''.
    cache_status = enable_persistent_cache()
    log(f"compile cache: {cache_status}")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dib_tpu.data import get_dataset
    from dib_tpu.models import PerParticleDIBModel
    from dib_tpu.parallel import BetaSweepTrainer
    from dib_tpu.parallel.context import _dense_score_dtype
    from dib_tpu.train import TrainConfig

    score_dtype_name = _dense_score_dtype().__name__

    devices = jax.devices()
    if devices[0].platform == "cpu" and not os.environ.get("DIB_BENCH_ALLOW_CPU"):
        # a swallowed TPU-init failure silently falls back to CPU; a CPU
        # number against the 10-min TPU target is meaningless
        raise RuntimeError(
            "benchmark backend resolved to CPU (TPU init failed or "
            "JAX_PLATFORMS unset); set DIB_BENCH_ALLOW_CPU=1 to force"
        )
    device_kind = devices[0].device_kind
    log(f"devices: {devices}")

    t_init = time.time()
    bundle = get_dataset("amorphous_particles", num_synthetic_neighborhoods=2048)
    # Full paper architecture; attention/FF matmuls in bfloat16 (MXU-native)
    # — KL, sampling, and logits stay float32. DIB_BENCH_FUSED_QKV=1 A/Bs the
    # fused QKV projection (roofline remedy, scripts/roofline.py).
    model = PerParticleDIBModel(
        num_particles=50, compute_dtype="bfloat16",
        fuse_qkv=bool(os.environ.get("DIB_BENCH_FUSED_QKV")),
    )
    config = TrainConfig(
        learning_rate=1e-4,
        batch_size=BENCH_BATCH_SIZE,
        num_pretraining_epochs=0,
        num_annealing_epochs=FULL_SWEEP_STEPS // STEPS_PER_EPOCH,
        steps_per_epoch=STEPS_PER_EPOCH,
        max_val_points=256,
        warmup_steps=500,
        # A/B knob for the per-step-gather experiment (VERDICT r3 item 4a);
        # non-default values do not refresh the cache (save_cache)
        batch_sampling=os.environ.get("DIB_BENCH_SAMPLING", "replacement"),
    )
    # Grid of annealing end-betas around the paper's 2e-1, shared start 2e-6.
    beta_ends = np.logspace(-2, 0, NUM_REPLICAS)
    sweep = BetaSweepTrainer(model, bundle, config, 2e-6, beta_ends)

    # Event stream for the measurement itself (docs/observability.md): the
    # child appends run_start/compile/chunk/run_end and the printed record
    # embeds the rolled-up summary, so every bench line is comparable to a
    # full run's events.jsonl via `dib_tpu telemetry compare`.
    import tempfile

    from dib_tpu.telemetry import (
        EventWriter,
        Tracer,
        runtime_manifest,
        summarize,
        xla_stats,
    )
    from dib_tpu.telemetry.events import device_memory_stats, host_memory_stats

    persistent_dir = os.environ.get("DIB_BENCH_TELEMETRY_DIR")
    telemetry_dir = persistent_dir or tempfile.mkdtemp(prefix="bench_events_")
    telemetry = EventWriter(telemetry_dir)
    tracer = Tracer(telemetry)
    telemetry.run_start(runtime_manifest(
        config=config,
        extra={"bench": METRIC, "replicas": NUM_REPLICAS,
               "compile_cache": cache_status,
               "score_dtype": score_dtype_name},
    ))

    init_keys = jax.random.split(jax.random.key(0), NUM_REPLICAS)
    warm_keys = jax.random.split(jax.random.key(1), NUM_REPLICAS)
    meas_keys = jax.random.split(jax.random.key(2), NUM_REPLICAS)
    t0 = time.time()
    log(f"dataset+trainer build: {t0 - t_init:.1f}s (before timed window)")
    with tracer.span("init") as ph:
        states, histories = sweep.init(init_keys)
        ph.block_on(states.params)
    t_after_init = time.time()

    # Warmup chunk: triggers compile of the full epoch scan (num_epochs is a
    # static arg, so warm with the same value the measurement uses).
    with tracer.span("compile_and_warm") as ph:
        states, histories = sweep.run_chunk(
            states, histories, warm_keys, MEASURE_EPOCHS)
        ph.block_on(states.params)
    compile_s = time.time() - t0
    # breakdown: with the persistent cache warm, 'chunk' is dominated by
    # cache deserialization + one real 2400-step execution (~4 s), not XLA
    # compilation — the floor of compile_s is mostly not compile
    log(f"init+compile+first chunk: {compile_s:.1f}s "
        f"(model init {t_after_init - t0:.1f}s, "
        f"chunk compile+exec {time.time() - t_after_init:.1f}s)")

    t1 = time.time()
    with tracer.span("sweep_chunk") as ph:
        states, histories = sweep.run_chunk(
            states, histories, meas_keys, MEASURE_EPOCHS)
        ph.block_on(states.params)
    measure_s = time.time() - t1

    # FLOPs/bytes of the chunk program (DIB_XLA_COST_ANALYSIS=0 opts out) —
    # AFTER both timed windows: the AOT lower().compile() is not shared
    # with jit's dispatch cache, so running it inside the t0..compile_s
    # window would inflate compile_s (and the projected-minutes headline)
    # with instrumentation cost. Lowering only reads shapes.
    cost = xla_stats.compiled_cost_stats(
        type(sweep).run_chunk, sweep, states, histories, meas_keys,
        MEASURE_EPOCHS,
    ) if xla_stats.cost_analysis_enabled() else None
    telemetry.compile(
        name="sweep_chunk", seconds=compile_s, cache=cache_status,
        cost_source="xla_cost_analysis" if cost else None, **(cost or {}))

    sweep_steps = MEASURE_EPOCHS * STEPS_PER_EPOCH * NUM_REPLICAS
    steps_per_s = sweep_steps / measure_s
    telemetry.chunk(epoch=2 * MEASURE_EPOCHS, steps=sweep_steps,
                    seconds=measure_s, replicas=NUM_REPLICAS,
                    memory=device_memory_stats(),
                    host_memory=host_memory_stats())
    # Validation runs once per epoch inside the measured chunk, so the
    # projection includes instrumentation overhead, as the north star does.
    projected_s = FULL_SWEEP_STEPS * NUM_REPLICAS / steps_per_s + compile_s
    projected_min = projected_s / 60.0

    # Conventional MFU: analytic model matmul FLOPs (fwd + bwd) per replica
    # step vs chip peak. The round-2/3 auxiliary ``mfu_hlo`` (whole-program
    # XLA cost_analysis) was dropped in round 4: on this backend
    # cost_analysis undercounts ~150x, and a number shipped with a
    # "don't read this" disclaimer is worse than none (VERDICT r3 item 7).
    model_flops_per_step = analytic_model_flops_per_step(model, BENCH_BATCH_SIZE)
    achieved_tflops = model_flops_per_step * steps_per_s / 1e12
    peak = peak_tflops_for(device_kind)
    mfu = achieved_tflops / peak if peak else None

    log(
        f"measured {sweep_steps} sweep steps in {measure_s:.2f}s "
        f"({steps_per_s:.0f} steps/s); projected full sweep "
        f"({NUM_REPLICAS} replicas x {FULL_SWEEP_STEPS} steps): "
        f"{projected_min:.2f} min; "
        f"model flops/step={model_flops_per_step:.3e}, "
        f"achieved_tflops={achieved_tflops:.2f}, mfu={mfu}"
    )
    # Sanity: training must not have gone non-finite anywhere in the run.
    kl = np.asarray(histories["kl_per_feature"])
    assert np.isfinite(kl).all(), "non-finite KL in benchmark run"

    telemetry.run_end(status="ok", projected_minutes=round(projected_min, 3))
    telemetry.close()
    run_summary = summarize(telemetry_dir, run_id=telemetry.run_id)
    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": round(projected_min, 3),
                "unit": "minutes",
                "vs_baseline": round(projected_min / BASELINE_MINUTES, 4),
                "steps_per_s": round(steps_per_s, 1),
                "compile_s": round(compile_s, 1),
                "flops_per_step_model": model_flops_per_step,
                "achieved_tflops": round(achieved_tflops, 2),
                "mfu": round(mfu, 4) if mfu else None,
                # where the measured window's time went (span self-time) and
                # the whole-program XLA cost view — BENCH_*.json lines carry
                # a utilization trajectory across rounds
                "span_hotspots": run_summary.get("span_hotspots"),
                "xla_cost_analysis": cost,
                "compile_cache": cache_status,
                "score_dtype": score_dtype_name,
                "device_kind": device_kind,
                "num_replicas": NUM_REPLICAS,
                "full_sweep_steps": FULL_SWEEP_STEPS,
                # the run's own event stream, rolled up (same shape as
                # `dib_tpu telemetry summarize`) — makes every bench line
                # comparable/gateable against any run's events.jsonl.
                # run_id-scoped: a reused DIB_BENCH_TELEMETRY_DIR appends
                # runs, and the summary must cover THIS one only
                "telemetry": run_summary,
                # a lasting path only when the caller asked for one — the
                # unnamed tmpdir is deleted below once rolled up
                "events_path": telemetry.path if persistent_dir else None,
            }
        ),
        flush=True,
    )
    if not persistent_dir:
        import shutil

        shutil.rmtree(telemetry_dir, ignore_errors=True)


# ==========================================================================
# PARENT: orchestration only. Never initializes jax.
# ==========================================================================

def probe_device(timeout_s: int) -> str | None:
    """Backend-init probe in a killable child. None on success, else reason."""
    code = (
        "import os, jax, jax.numpy as jnp\n"
        "p = os.environ.get('JAX_PLATFORMS')\n"
        "p and jax.config.update('jax_platforms', p)\n"
        "d = jax.devices()\n"
        "assert d[0].platform != 'cpu' or os.environ.get('DIB_BENCH_ALLOW_CPU'), \\\n"
        "    'backend resolved to CPU'\n"
        "jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired as e:
        if e.stderr:
            sys.stderr.write(
                e.stderr if isinstance(e.stderr, str) else e.stderr.decode()
            )
        return f"probe hung > {timeout_s}s (tunnel down?)"
    if proc.returncode != 0:
        stderr = (proc.stderr or "").strip()
        return stderr.splitlines()[-1] if stderr else "probe failed"
    return None


def run_child(timeout_s: int) -> tuple[dict | None, str]:
    """Run the measurement child; returns (parsed result, reason)."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            timeout=timeout_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired as e:
        # keep the child's partial diagnostics (device list, compile log):
        # for a hang they are the only forensic record
        if e.stderr:
            sys.stderr.write(
                e.stderr if isinstance(e.stderr, str) else e.stderr.decode()
            )
        return None, f"measurement hung > {timeout_s}s"
    sys.stderr.write(proc.stderr or "")
    if proc.returncode != 0:
        stderr = (proc.stderr or "").strip()
        return None, stderr.splitlines()[-1] if stderr else "child failed"
    for line in reversed((proc.stdout or "").strip().splitlines()):
        try:
            parsed = json.loads(line)
            if isinstance(parsed, dict) and "value" in parsed:
                return parsed, "ok"
        except json.JSONDecodeError:
            continue
    return None, "child printed no JSON result"


def load_cache() -> dict | None:
    if os.environ.get("DIB_BENCH_FRESH"):
        return None
    try:
        with open(CACHE_PATH) as f:
            cached = json.load(f)
        return cached if isinstance(cached, dict) and "value" in cached else None
    except (OSError, json.JSONDecodeError):
        return None


def save_cache(result: dict) -> None:
    # Never let a test configuration masquerade as the last good north-star
    # measurement: the degraded path reports the cache against the 10-min
    # TPU target, so only default-config accelerator runs may refresh it.
    # Compare EFFECTIVE values against the defaults (not env-var presence):
    # an operator exporting the default values must still refresh the cache
    # (ADVICE round 2, bench.py:280).
    # The effective score-dtype default is bfloat16 (context.py, adopted
    # round 3): only runs at that default may refresh — re-validating the
    # f32 fallback must not overwrite the cache with the slower variant.
    if os.environ.get("DIB_BENCH_ALLOW_CPU") or (
        NUM_REPLICAS != DEFAULT_REPLICAS
        or MEASURE_EPOCHS != DEFAULT_MEASURE_EPOCHS
        or STEPS_PER_EPOCH != DEFAULT_STEPS_PER_EPOCH
        or os.environ.get("DIB_BENCH_SAMPLING", "replacement") != "replacement"
        or os.environ.get("DIB_BENCH_FUSED_QKV")
        or os.environ.get("DIB_ATTN_SCORE_DTYPE", "bfloat16").lower()
        not in ("bfloat16", "bf16")
    ):
        log("cache not refreshed: non-default benchmark configuration")
        return
    record = dict(result)
    record["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    try:
        with open(CACHE_PATH, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    except OSError as e:
        log(f"cache write failed: {e}")


def emit(result: dict) -> None:
    print(json.dumps(result), flush=True)


def register_bench(result: dict) -> None:
    """Append this invocation's headline numbers to the fleet run registry
    (docs/observability.md): ``DIB_RUNS_ROOT`` if set (empty disables),
    else the repo's committed ``runs/`` root — `telemetry runs trajectory`
    and the index report render the resulting perf trajectory. Degraded
    records register only under an EXPLICIT root: a dead-tunnel retry loop
    (or the degraded-path tests) must not grow the committed index with
    no-signal rows. Registry failure never fails the bench."""
    root = os.environ.get("DIB_RUNS_ROOT")
    if root is None:
        if result.get("degraded"):
            return
        root = os.path.join(REPO, "runs")
    if not root:
        return
    try:
        from dib_tpu.telemetry.registry import RunRegistry, bench_entry

        record = RunRegistry(root).append(bench_entry(result))
        log(f"run registry: bench entry appended under {root} "
            f"(kind={record['kind']})")
    except Exception as exc:
        log(f"run registry append failed: {exc}")


def parent_main() -> None:
    budget_s = float(os.environ.get("DIB_BENCH_TOTAL_BUDGET_S", "1050"))
    deadline = time.time() + budget_s
    probe_timeout = 150
    measure_timeout = 900    # a TPU measurement is ~2-4 min incl. compile;
                             # must fit INSIDE the default budget
    backoff = 30.0
    # A dead tunnel fails every probe the same way; burning the whole
    # budget on identical 150 s hangs (BENCH_r05: four of them) buys
    # nothing over a few. Bounded retries + the exponential backoff below
    # cap the worst case; the count is configurable for tests/operators.
    max_probe_failures = int(os.environ.get("DIB_BENCH_MAX_PROBE_ATTEMPTS", "4"))

    attempt = 0
    probe_failures = 0   # consecutive; reset when a probe succeeds
    device_ever_up = False
    last_failure = "no probe attempted"
    while True:
        attempt += 1
        remaining = deadline - time.time()
        if remaining < probe_timeout + 60:
            break
        if probe_failures >= max_probe_failures:
            log(f"giving up after {probe_failures} consecutive probe "
                f"failures (cap {max_probe_failures})")
            break
        reason = probe_device(min(probe_timeout, int(remaining - 30)))
        if reason is None:
            probe_failures = 0
            device_ever_up = True
            remaining = deadline - time.time()
            child_budget = int(min(measure_timeout, max(remaining - 10, 60)))
            log(f"attempt {attempt}: device up, measuring (budget {child_budget}s)")
            result, why = run_child(child_budget)
            if result is not None:
                save_cache(result)
                register_bench(result)
                emit(result)
                return
            failure = f"measurement failed: {why}"
            # Two consecutive identical child failures = deterministic crash
            # (dataset/import bug), not a flaky tunnel: stop burning the
            # budget on retries that cannot succeed.
            if failure == last_failure and "hung" not in why:
                log(f"attempt {attempt}: {failure} (repeated; giving up)")
                break
            last_failure = failure
            log(f"attempt {attempt}: {last_failure}")
        else:
            probe_failures += 1
            last_failure = reason
            log(f"attempt {attempt}: {reason} "
                f"({probe_failures}/{max_probe_failures} probe failures)")
        sleep_s = min(backoff, max(deadline - time.time() - probe_timeout, 0))
        if sleep_s > 0:
            time.sleep(sleep_s)
        backoff = min(backoff * 2, 240.0)

    # Budget exhausted: degrade, embedding the last good measurement so the
    # round still carries a parseable perf record (VERDICT round 1, item 1).
    # Distinguish a dead tunnel from a live device whose measurement kept
    # failing — they send the operator to entirely different bugs.
    cached = load_cache()
    degraded = {
        "metric": METRIC,
        "value": cached.get("value") if cached else None,
        "unit": "minutes",
        "vs_baseline": cached.get("vs_baseline") if cached else None,
        "degraded": "measurement_failed" if device_ever_up else "no_device",
        "detail": (
            f"budget {budget_s:.0f}s exhausted; last failure: {last_failure}; "
            + (
                "value is the last good measurement (see cache_measured_at)"
                if cached
                else "no cached measurement available"
            )
        ),
        # Structured failure record (machine-readable, unlike the free-text
        # stderr tail BENCH_r05 had to be forensically read from): how many
        # attempts ran, how many probes failed in a row, and why.
        "probe_failure": {
            "attempts": attempt,
            "consecutive_probe_failures": probe_failures,
            "max_probe_attempts": max_probe_failures,
            "probe_timeout_s": probe_timeout,
            "last_reason": last_failure,
            "device_ever_up": device_ever_up,
        },
    }
    if cached:
        for key in ("steps_per_s", "mfu", "achieved_tflops", "device_kind",
                    "measured_at"):
            if key in cached:
                degraded["cache_" + key if key == "measured_at" else key] = (
                    cached[key]
                )
        # How stale the embedded measurement is, loudly and at top level
        # (VERDICT round 4 weak #2): consumers must see at a glance that
        # the value is N hours old, not a live number.
        try:
            import calendar

            measured = calendar.timegm(time.strptime(
                cached.get("measured_at", ""), "%Y-%m-%dT%H:%M:%SZ"))
            degraded["stale_seconds"] = int(time.time() - measured)
        except (ValueError, TypeError):
            degraded["stale_seconds"] = None
    register_bench(degraded)
    emit(degraded)


if __name__ == "__main__":
    if "--child" in sys.argv:
        try:
            child_main()
        except BaseException as exc:
            # crash-path terminal record: the child's event stream must
            # not end on a dangling chunk (docs/observability.md) — e.g.
            # the non-finite-KL assert fires before run_end. The path is
            # logged because an unnamed tmpdir is otherwise undiscoverable
            # (it is NOT cleaned up on failure: it's the crash forensics).
            from dib_tpu.telemetry import finalize_crashed

            finalize_crashed(exc, log=log)
            raise
    else:
        parent_main()
