"""Multi-host helpers on the single-process virtual-device backend.

True multi-process runs need N hosts; what CAN be pinned here is the
single-process degenerate path (which pod code shares) and the sharding
semantics of the global-batch builder on the 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dib_tpu.parallel.mesh import DATA_AXIS, make_sweep_mesh
from dib_tpu.parallel.multihost import fetch_to_host, initialize, process_local_batch


def test_initialize_single_process_is_noop():
    assert initialize() is False
    assert jax.process_count() == 1


def test_initialize_warns_on_malformed_cluster_spec(monkeypatch):
    # A real cluster-spec error (not the benign missing-coordinator case)
    # must warn loudly: silently degrading a pod to N uncoordinated
    # single-process trainers is the failure mode the RuntimeError branch
    # already guards against.
    import warnings as warnings_mod

    import dib_tpu.parallel.multihost as mh

    def boom():
        raise ValueError("malformed TPU cluster metadata: worker 3 missing")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    with warnings_mod.catch_warnings(record=True) as caught:
        warnings_mod.simplefilter("always")
        assert mh.initialize() is False
    assert any("uncoordinated" in str(w.message) for w in caught)


def test_initialize_quiet_on_reworded_coordinator_error(monkeypatch):
    # A JAX upgrade may reword the "coordinator_address should be defined"
    # internal message; with no cluster env vars set, any coordinator_address
    # complaint is still the benign single-host outcome and must stay quiet.
    import warnings as warnings_mod

    import dib_tpu.parallel.multihost as mh

    for var in mh._CLUSTER_ENV_VARS:
        monkeypatch.delenv(var, raising=False)

    def boom():
        raise ValueError("coordinator_address must be set for multi-process")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    with warnings_mod.catch_warnings(record=True) as caught:
        warnings_mod.simplefilter("always")
        assert mh.initialize() is False
    assert not caught


def test_initialize_warns_on_coordinator_error_with_cluster_env(monkeypatch):
    # Same coordinator_address complaint, but cluster config IS present in
    # the environment: that is a malformed spec on a real pod — warn loudly.
    import warnings as warnings_mod

    import dib_tpu.parallel.multihost as mh

    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")

    def boom():
        raise ValueError("coordinator_address must be set for multi-process")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    with warnings_mod.catch_warnings(record=True) as caught:
        warnings_mod.simplefilter("always")
        assert mh.initialize() is False
    assert any("uncoordinated" in str(w.message) for w in caught)


def test_process_local_batch_shards_rows(rng):
    mesh = make_sweep_mesh(1, 8)
    sharding = NamedSharding(mesh, P(None, DATA_AXIS))
    rows = rng.standard_normal((4, 16)).astype(np.float32)
    arr = process_local_batch(rows, sharding)
    assert arr.shape == (4, 16)
    assert len(arr.addressable_shards) == 8
    np.testing.assert_array_equal(np.asarray(arr), rows)


def test_fetch_to_host_roundtrip(rng):
    tree = {"a": jnp.arange(8.0), "b": [jnp.ones((2, 3))]}
    host = fetch_to_host(tree)
    assert isinstance(host["a"], np.ndarray)
    np.testing.assert_array_equal(host["a"], np.arange(8.0))
