"""Overlapped-measurement + prefetch pipeline tests (docs/performance.md).

The overlap contract is BIT-IDENTITY: dispatching a measurement on a
donation-decoupled snapshot and collecting it a boundary later must
produce exactly the values the serial schedule produces — overlap is a
scheduling change, never a numerics change. These tests pin that for
every overlapped site (boolean fit loop, measurement trainer's
speculative pipeline, serial + sweep MI hooks), the prefetching epoch
pipeline, the host-staging double buffer, and the telemetry accounting
(`overlap` rollup + the compare gate).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dib_tpu.train.overlap import PendingDispatch, snapshot_params
from dib_tpu.train.prefetch import HostStager


# ------------------------------------------------------------ primitives
def test_snapshot_params_is_a_real_copy():
    tree = {"w": jnp.arange(8.0), "b": {"x": jnp.ones((3, 2))}}
    snap = snapshot_params(tree)
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(snap)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a is not b
        # distinct device buffers: donation of the original cannot touch
        # the snapshot
        assert (a.unsafe_buffer_pointer() != b.unsafe_buffer_pointer())


def test_snapshot_survives_donation_of_source():
    donating_fn = jax.jit(lambda t: jax.tree.map(lambda x: x * 2.0, t),
                          donate_argnums=0)
    tree = {"w": jnp.arange(8.0)}
    snap = snapshot_params(tree)
    out = donating_fn(tree)
    jax.block_until_ready(out)
    # the snapshot still reads the PRE-donation values
    np.testing.assert_array_equal(np.asarray(snap["w"]), np.arange(8.0))


def test_pending_dispatch_collects_device_outputs():
    pending = PendingDispatch(outputs={"x": jnp.arange(4) * 3},
                              meta={"epoch": 7})
    fetched = pending.collect()
    np.testing.assert_array_equal(fetched["x"], np.arange(4) * 3)
    assert pending.meta["epoch"] == 7


def test_collect_tolerates_hand_built_dispatch_without_token():
    """Review regression: a PendingDispatch built directly (token=None)
    must collect cleanly — the span just omits queued_s."""
    from dib_tpu.train.overlap import collect_overlapped

    pending = PendingDispatch(outputs={"x": jnp.arange(3)})
    fetched = collect_overlapped(pending)
    np.testing.assert_array_equal(fetched["x"], np.arange(3))


def test_collect_after_tracer_context_still_emits_the_span(tmp_path):
    """Review regression: the FINAL checkpoint's pending measurement is
    flushed by a post-fit ``records`` read — after the fit's use_tracer
    context has exited. The span must still land on the run's stream (it
    is the one boundary that pays the full wait; dropping it biased
    overlap_exposed_frac low), so the dispatch captures the tracer."""
    from dib_tpu.telemetry import EventWriter, Tracer, use_tracer
    from dib_tpu.train.overlap import begin_overlapped, collect_overlapped

    writer = EventWriter(str(tmp_path))
    tracer = Tracer(writer)
    with use_tracer(tracer):
        pending = begin_overlapped({"x": jnp.arange(3)}, epoch=5)
    # tracer binding gone: a naive current_tracer() here would be the
    # no-op fallback and the span would vanish
    collect_overlapped(pending)
    writer.close()
    spans = [e for e in _read_events(tmp_path) if e.get("type") == "span"]
    assert len(spans) == 1
    assert spans[0]["name"] == "mi_bounds"
    assert spans[0]["overlapped"] is True
    assert spans[0]["epoch"] == 5
    assert "queued_s" in spans[0]


def test_host_stager_order_and_values():
    items = [np.full((4,), i, np.float32) for i in range(5)]
    staged = list(HostStager(items))
    assert len(staged) == 5
    for i, arr in enumerate(staged):
        assert isinstance(arr, jax.Array)
        np.testing.assert_array_equal(np.asarray(arr), items[i])
    assert list(HostStager([])) == []


# ------------------------------------------- boolean fit loop (inline site)
def test_boolean_overlapped_fit_matches_serial_replay():
    """The overlapped _fit_loop must reproduce, bit for bit, the history a
    hand-rolled serial schedule (same key chain) produces."""
    from dib_tpu.data import get_dataset
    from dib_tpu.workloads.boolean import BooleanTrainer, BooleanWorkloadConfig

    bundle = get_dataset("boolean_circuit", number_inputs=4, seed=0)
    config = BooleanWorkloadConfig(num_steps=30, mi_every=10, batch_size=32,
                                   integration_hidden=(16,))
    trainer = BooleanTrainer(bundle, config)
    state, history = trainer.fit(jax.random.key(0))

    # serial replay of the exact same key schedule
    key = jax.random.key(0)
    key, k_init = jax.random.split(key)
    s = trainer.init(k_init)
    steps, lowers = [], []
    step = 0
    while step < config.num_steps:
        chunk = min(config.mi_cadence, config.num_steps - step)
        key, k_chunk, k_mi = jax.random.split(key, 3)
        s, stats = trainer.run_chunk(s, k_chunk, chunk)
        lower, upper = trainer.channel_mi_bounds(s, k_mi)
        step += chunk
        steps.append(step)
        from dib_tpu.ops.entropy import LN2

        lowers.append(np.asarray(lower) / LN2)
    np.testing.assert_array_equal(history["mi_steps"], np.asarray(steps))
    np.testing.assert_array_equal(history["mi_lower_bits"],
                                  np.stack(lowers))
    for a, b in zip(jax.tree.leaves(jax.device_get(state.params)),
                    jax.tree.leaves(jax.device_get(s.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -------------------------------------- measurement trainer (speculative)
@pytest.fixture(scope="module")
def measurement_setup():
    from dib_tpu.models import MeasurementStack
    from dib_tpu.train.measurement import make_state_windows

    rng = np.random.default_rng(0)
    windows = make_state_windows(rng.normal(size=(300,)).astype(np.float32), 3)
    stack = MeasurementStack(ib_embedding_dim=2, alphabet_size=3,
                             num_states=3, infonce_dim=4,
                             encoder_hidden=(8,), vq_hidden=(8,),
                             aggregator_hidden=(8,), reference_hidden=(8,))
    return stack, windows


@pytest.mark.parametrize("stop_bits", [1e9, -1.0])
def test_measurement_overlap_is_bit_identical(measurement_setup, stop_bits):
    """overlap=True (speculative next chunk + snapshot measurement) must
    match the serial fit exactly: history, stop step, final state, AND the
    published resume_key chain (a resumed run replays the speculated
    chunk identically)."""
    from dib_tpu.train.measurement import MeasurementConfig, MeasurementTrainer

    stack, windows = measurement_setup
    cfg = MeasurementConfig(batch_size=32, num_steps=30, check_every=10,
                            mi_eval_batch_size=32, mi_eval_batches=1,
                            mi_stop_bits=stop_bits)

    def run(overlap):
        t = MeasurementTrainer(stack, windows, cfg)
        state, hist = t.fit(jax.random.key(0), overlap=overlap)
        return jax.device_get(state), hist, t.resume_key

    s_serial, h_serial, k_serial = run(False)
    s_overlap, h_overlap, k_overlap = run(True)
    assert h_serial["stopped_early"] == h_overlap["stopped_early"]
    assert h_serial["mi_bounds"] == h_overlap["mi_bounds"]
    for name in ("loss", "match", "kl", "beta"):
        np.testing.assert_array_equal(h_serial[name], h_overlap[name])
    for a, b in zip(jax.tree.leaves(s_serial), jax.tree.leaves(s_overlap)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(k_serial)),
        np.asarray(jax.random.key_data(k_overlap)))


# ------------------------------------------------- MI hooks (serial+sweep)
def _tiny_dib_trainer():
    from dib_tpu.data import get_dataset
    from dib_tpu.models import DistributedIBModel
    from dib_tpu.train import DIBTrainer, TrainConfig

    bundle = get_dataset("boolean_circuit", number_inputs=4, seed=1)
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=bundle.output_dimensionality, embedding_dim=2,
        output_activation=bundle.output_activation,
    )
    config = TrainConfig(batch_size=16, num_pretraining_epochs=1,
                         num_annealing_epochs=3, steps_per_epoch=2,
                         max_val_points=16)
    return DIBTrainer(model, bundle, config)


def test_info_hook_overlap_matches_serial():
    from dib_tpu.train.hooks import InfoPerFeatureHook

    trainer = _tiny_dib_trainer()

    def run(overlap):
        hook = InfoPerFeatureHook(evaluation_batch_size=32,
                                  number_evaluation_batches=1,
                                  overlap=overlap)
        trainer.fit(jax.random.key(0), hooks=[hook], hook_every=2)
        return hook.records   # property: flushes the last pending

    serial = run(False)
    overlapped = run(True)
    assert [r["epoch"] for r in serial] == [r["epoch"] for r in overlapped]
    np.testing.assert_allclose(
        np.asarray([r["bounds"] for r in serial]),
        np.asarray([r["bounds"] for r in overlapped]), rtol=0, atol=0)


@pytest.mark.slow
def test_sweep_info_hook_overlap_matches_serial(tmp_path):
    from dib_tpu.data import get_dataset
    from dib_tpu.models import DistributedIBModel
    from dib_tpu.parallel import BetaSweepTrainer
    from dib_tpu.parallel.sweep_hooks import SweepInfoPerFeatureHook
    from dib_tpu.train import TrainConfig

    bundle = get_dataset("boolean_circuit", number_inputs=4, seed=1)
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=bundle.output_dimensionality, embedding_dim=2,
        output_activation=bundle.output_activation,
    )
    config = TrainConfig(batch_size=16, num_pretraining_epochs=1,
                         num_annealing_epochs=3, steps_per_epoch=2,
                         max_val_points=16)

    def run(overlap, persist):
        sweep = BetaSweepTrainer(model, bundle, config, 1e-3, [0.5, 1.0])
        hook = SweepInfoPerFeatureHook(
            evaluation_batch_size=32, number_evaluation_batches=1,
            overlap=overlap, persist=persist)
        keys = jax.random.split(jax.random.key(0), 2)
        sweep.fit(keys, hooks=[hook], hook_every=2)
        return hook

    serial = run(False, None)
    overlapped = run(True, str(tmp_path / "mi"))
    assert list(serial.epochs) == list(overlapped.epochs)
    np.testing.assert_array_equal(
        np.stack([r["bounds"] for r in serial.records]),
        np.stack([r["bounds"] for r in overlapped.records]))
    # the persist mirror carries the flushed trajectory too
    mirrored = sorted(os.listdir(tmp_path / "mi"))
    assert len(mirrored) == len(overlapped.records)


# --------------------------------------------------- prefetch epoch pipeline
def test_permutation_prefetch_is_bit_identical():
    import dataclasses

    trainer_on = _tiny_dib_trainer()
    cfg = dataclasses.replace(trainer_on.config,
                              batch_sampling="permutation",
                              prefetch_epochs=True)
    cfg_off = dataclasses.replace(cfg, prefetch_epochs=False)
    from dib_tpu.train import DIBTrainer

    def run(config):
        t = DIBTrainer(trainer_on.model, trainer_on.bundle, config)
        state, history = t.init(jax.random.key(0))
        state, history = t.run_chunk(state, history, jax.random.key(1), 3)
        return jax.device_get((state.params, history))

    for a, b in zip(jax.tree.leaves(run(cfg)), jax.tree.leaves(run(cfg_off))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------------- telemetry accounting
def test_summarize_overlap_rollup_and_compare_gate(tmp_path):
    from dib_tpu.telemetry import EventWriter
    from dib_tpu.telemetry.summary import compare, summarize

    def write_run(directory, exposed):
        writer = EventWriter(str(directory))
        writer.run_start({"config_hash": "x"})
        writer.chunk(epoch=1, steps=100, seconds=2.0)
        writer.chunk(epoch=2, steps=100, seconds=2.0)
        writer.span(name="mi_bounds", path="mi_bounds", span_id=1,
                    parent_id=None, seconds=exposed, overlapped=True,
                    queued_s=2.0)
        writer.run_end(status="ok")
        writer.close()

    write_run(tmp_path / "a", exposed=0.1)
    write_run(tmp_path / "b", exposed=1.8)
    summary_a = summarize(str(tmp_path / "a"))
    assert summary_a["overlap"]["spans"] == 1
    assert summary_a["overlap"]["exposed_s"] == 0.1
    assert summary_a["overlap"]["queued_s"] == 2.0
    assert summary_a["overlap"]["hidden_s"] == 1.9
    assert summary_a["overlap_exposed_frac"] == 0.05
    summary_b = summarize(str(tmp_path / "b"))
    # the candidate's measurement re-serialized its boundary: gated
    report, regressed = compare(summary_a, summary_b)
    assert regressed
    assert report["fields"]["overlap_exposed_frac"]["regressed"]
    # reverse direction (overlap improved) is not a regression
    _, regressed_rev = compare(summary_b, summary_a)
    assert not regressed_rev


def test_overlapped_spans_land_on_the_boolean_stream(tmp_path):
    """End-to-end: a telemetry-on boolean fit emits overlapped mi_bounds
    spans and summarize rolls them up (the hotspots table no longer
    charges the boundary for the measurement's device time)."""
    from dib_tpu.data import get_dataset
    from dib_tpu.telemetry import EventWriter
    from dib_tpu.telemetry.summary import summarize
    from dib_tpu.workloads.boolean import BooleanTrainer, BooleanWorkloadConfig

    bundle = get_dataset("boolean_circuit", number_inputs=4, seed=0)
    config = BooleanWorkloadConfig(num_steps=20, mi_every=10, batch_size=32,
                                   integration_hidden=(16,))
    trainer = BooleanTrainer(bundle, config)
    writer = EventWriter(str(tmp_path))
    from dib_tpu.telemetry import runtime_manifest

    writer.run_start(runtime_manifest())
    trainer.fit(jax.random.key(0), telemetry=writer)
    writer.run_end(status="ok")
    writer.close()
    summary = summarize(str(tmp_path))
    assert summary["overlap"]["spans"] == 2          # one per MI boundary
    assert summary["overlap"]["queued_s"] >= summary["overlap"]["exposed_s"]
    mi_spans = [e for e in _read_events(tmp_path)
                if e.get("type") == "span" and e.get("name") == "mi_bounds"]
    assert all(e.get("overlapped") for e in mi_spans)
    assert all("queued_s" in e for e in mi_spans)
    # mi_bounds events still land at the step they MEASURED
    mi_events = [e for e in _read_events(tmp_path)
                 if e.get("type") == "mi_bounds"]
    assert [e["epoch"] for e in mi_events] == [10, 20]


def _read_events(directory):
    with open(os.path.join(str(directory), "events.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


# ----------------------------------------------------- bench staleness SLO
def test_slo_check_gates_stale_bench_records(tmp_path):
    from dib_tpu.telemetry.slo import check_run

    slo = {
        "slo_version": 1,
        "rules": [{"name": "bench_cache_staleness_ceiling",
                   "metric": "stale_seconds", "max": 86400.0,
                   "severity": "warn"}],
    }
    slo_path = tmp_path / "SLO.json"
    slo_path.write_text(json.dumps(slo))

    def bench(stale):
        record = {"metric": "m", "value": 1.0, "unit": "minutes",
                  "degraded": "no_device"}
        if stale is not None:
            record["stale_seconds"] = stale
        path = tmp_path / f"bench_{stale}.json"
        path.write_text(json.dumps(record) + "\n")
        return str(path)

    fresh = check_run(bench(None), str(slo_path))
    assert fresh["violations"] == 0          # no stale_seconds: skipped
    ok = check_run(bench(3600), str(slo_path))
    assert ok["violations"] == 0
    stale = check_run(bench(200_000), str(slo_path))
    assert stale["violations"] == 1
    assert stale["rules"][0]["status"] == "violated"
