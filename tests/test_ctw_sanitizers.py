"""Builds the CTW native core under ASan/UBSan and runs its self-test.

SURVEY.md section 5 (race detection / sanitizers): the reference has no
sanitizer story; here the C++ component is compiled with
-fsanitize=address,undefined (no-recover) and exercised across allocation-
and tree-logic-heavy regimes. Any leak, overflow, or UB fails the test via
a nonzero exit.
"""

import os
import shutil
import subprocess

import pytest

CTW_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "dib_tpu", "ctw")


@pytest.mark.slow
def test_ctw_under_asan_ubsan(tmp_path):
    if shutil.which("g++") is None:
        pytest.skip("g++ not available")
    binary = tmp_path / "ctw_sanitize_check"
    build = subprocess.run(
        [
            "g++", "-O1", "-g", "-std=c++17",
            "-fsanitize=address,undefined",
            "-fno-sanitize-recover=all",
            "-fno-omit-frame-pointer",
            os.path.join(CTW_DIR, "ctw.cpp"),
            os.path.join(CTW_DIR, "sanitize_check.cpp"),
            "-o", str(binary),
        ],
        capture_output=True, text=True,
    )
    assert build.returncode == 0, f"sanitizer build failed:\n{build.stderr}"
    run = subprocess.run(
        [str(binary)],
        capture_output=True, text=True,
        env={**os.environ,
             "ASAN_OPTIONS": "detect_leaks=1:abort_on_error=0",
             "UBSAN_OPTIONS": "print_stacktrace=1"},
    )
    assert run.returncode == 0, (
        f"sanitized CTW self-test failed (exit {run.returncode}):\n"
        f"stdout:\n{run.stdout}\nstderr:\n{run.stderr}"
    )
    assert "sanitize_check OK" in run.stdout
