"""Tests for the chaos measurement-optimization workload (training loop,
symbolization, entropy-rate scaling, end-to-end pipeline)."""

from __future__ import annotations

import jax
import numpy as np
import pytest

from dib_tpu.data.chaos_maps import generate_data
from dib_tpu.models.measurement import MeasurementStack
from dib_tpu.train.measurement import (
    MeasurementConfig,
    MeasurementTrainer,
    make_state_windows,
)
from dib_tpu.workloads.chaos import (
    KNOWN_ENTROPY_RATES,
    entropy_rate_scaling_curve,
    fit_entropy_rate,
    run_chaos_workload,
)


class TestWindows:
    def test_shapes_and_content(self):
        traj = np.arange(10, dtype=np.float32)
        w = make_state_windows(traj, 4)
        assert w.shape == (7, 4, 1)
        np.testing.assert_array_equal(w[0, :, 0], [0, 1, 2, 3])
        np.testing.assert_array_equal(w[-1, :, 0], [6, 7, 8, 9])

    def test_2d_trajectory(self):
        traj = np.random.default_rng(0).random((20, 2)).astype(np.float32)
        w = make_state_windows(traj, 5)
        assert w.shape == (16, 5, 2)
        np.testing.assert_array_equal(w[3], traj[3:8])

    def test_too_short_raises(self):
        with pytest.raises(ValueError):
            make_state_windows(np.zeros(3), 5)


@pytest.fixture(scope="module")
def tiny_setup():
    traj = generate_data("logistic", number_iterations=4000,
                         number_skip_iterations=500, seed=0)
    windows = make_state_windows(traj, 3)
    stack = MeasurementStack(
        alphabet_size=2, num_states=3, ib_embedding_dim=4,
        encoder_hidden=(32,), vq_hidden=(32,), aggregator_hidden=(32,),
        reference_hidden=(32,), infonce_dim=8, num_posenc_frequencies=4,
    )
    cfg = MeasurementConfig(
        batch_size=128, num_steps=60, check_every=30,
        mi_eval_batch_size=128, mi_eval_batches=1, mi_stop_bits=50.0,
    )
    return stack, windows, cfg, traj


class TestMeasurementTrainer:
    @pytest.mark.slow
    def test_loss_decreases_and_beta_descends(self, tiny_setup):
        stack, windows, cfg, _ = tiny_setup
        trainer = MeasurementTrainer(stack, windows, cfg)
        state, history = trainer.fit(jax.random.key(0))
        assert int(state.step) == cfg.num_steps
        assert history["beta"][0] > history["beta"][-1]  # downward anneal
        assert np.isfinite(history["loss"]).all()
        # InfoNCE match improves from its log(B)-ish start
        assert history["match"][-5:].mean() < history["match"][:5].mean()
        assert len(history["mi_bounds"]) == 2

    @pytest.mark.slow
    def test_mi_early_stop(self, tiny_setup):
        stack, windows, cfg, _ = tiny_setup
        import dataclasses

        eager = dataclasses.replace(cfg, mi_stop_bits=1e-6)
        trainer = MeasurementTrainer(stack, windows, eager)
        state, history = trainer.fit(jax.random.key(0))
        assert history["stopped_early"]
        assert int(state.step) == eager.check_every  # stopped at first check

    def test_symbolization_deterministic_and_chunked(self, tiny_setup):
        stack, windows, cfg, traj = tiny_setup
        trainer = MeasurementTrainer(stack, windows, cfg)
        state = trainer.init(jax.random.key(1))
        s1 = trainer.symbolize_trajectory(state, traj[:1000], jax.random.key(7),
                                          num_noise_draws=10, chunk_size=300)
        s2 = trainer.symbolize_trajectory(state, traj[:1000], jax.random.key(7),
                                          num_noise_draws=10, chunk_size=1000)
        assert s1.shape == (1000,)
        assert s1.dtype == np.uint8
        # same key + params -> identical partition regardless of chunking
        np.testing.assert_array_equal(s1, s2)
        assert set(np.unique(s1)) <= {0, 1}

    def test_window_mismatch_raises(self, tiny_setup):
        stack, windows, cfg, _ = tiny_setup
        bad = windows[:, :2]  # 2 states, stack expects 3
        with pytest.raises(ValueError):
            MeasurementTrainer(stack, bad, cfg)


class TestEntropyScaling:
    def test_curve_monotone_lengths_and_fit(self):
        rng = np.random.default_rng(0)
        symbols = rng.integers(0, 2, size=30_000).astype(np.uint8)
        lengths = [2000, 8000, 30_000]
        rates = entropy_rate_scaling_curve(symbols, lengths, 2, num_draws=3, seed=0)
        assert rates.shape == (3, 3)
        # iid uniform: every estimate near 1 bit, tighter with length
        assert np.all(rates > 0.9)
        fit = fit_entropy_rate(lengths, rates)
        assert fit["h_inf"] == pytest.approx(1.0, abs=0.05)

    def test_length_validation(self):
        with pytest.raises(ValueError):
            entropy_rate_scaling_curve(np.zeros(10, np.uint8), [100], 2)


@pytest.mark.slow
class TestEndToEnd:
    def test_logistic_pipeline_recovers_entropy_rate(self):
        res = run_chaos_workload(
            system="logistic", alphabet_size=2, num_states=4,
            train_iterations=20_000, characterization_iterations=60_000,
            config=MeasurementConfig(
                batch_size=256, num_steps=300, check_every=100,
                mi_eval_batch_size=256, mi_eval_batches=2,
            ),
            scaling_lengths=[5_000, 15_000, 30_000, 60_000],
            num_scaling_draws=2, num_noise_draws=20,
            include_random_baseline=False, seed=0, chunk_size=20_000,
        )
        assert res["symbols"].shape == (60_000,)
        # trained partition must land in the physical ballpark of the
        # literature rate (0.5203). The longest-length CTW estimate is the
        # robust check for a tiny run; the Schurmann-Grassberger
        # extrapolation is only required to be sane (it amplifies noise
        # when given few lengths).
        longest_rate = res["scaling_rates"].mean(0)[-1]
        assert longest_rate == pytest.approx(
            KNOWN_ENTROPY_RATES["logistic"], abs=0.12
        )
        assert np.isfinite(res["fit"]["h_inf"])
        assert 0.0 < res["fit"]["h_inf"] < 1.0
        # and both symbols must actually be used
        counts = np.bincount(res["symbols"], minlength=2)
        assert counts.min() > 0.05 * counts.sum()
