"""Tests for the per-particle DIB model (amorphous set-transformer workload)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.models import PerParticleDIBModel
from dib_tpu.train import DIBTrainer, TrainConfig


def tiny_model(num_particles=8):
    return PerParticleDIBModel(
        num_particles=num_particles,
        encoder_hidden=(16,),
        embedding_dim=4,
        num_blocks=1,
        num_heads=2,
        key_dim=8,
        ff_hidden=(16,),
        head_hidden=(16,),
    )


@pytest.fixture(scope="module")
def bundle():
    return get_dataset(
        "amorphous_particles",
        num_synthetic_neighborhoods=96,
        number_particles_to_use=8,
    )


class TestModel:
    def test_forward_shapes(self, bundle):
        m = tiny_model()
        x = jnp.asarray(bundle.x_train[:5])
        params = m.init(jax.random.key(0), x, jax.random.key(1))
        pred, aux = m.apply(params, x, jax.random.key(2))
        assert pred.shape == (5, 1)
        assert aux["kl_per_feature"].shape == (8,)
        assert aux["mus"].shape == (8, 5, 4)
        assert aux["logvars"].shape == (8, 5, 4)
        assert np.isfinite(np.asarray(pred)).all()

    def test_kl_matches_reference_convention(self, bundle):
        # total KL == sum over (latent dim, particle), mean over batch
        # (amorphous notebook cell 8 train_step).
        from dib_tpu.ops.gaussian import kl_diagonal_gaussian

        m = tiny_model()
        x = jnp.asarray(bundle.x_train[:6])
        params = m.init(jax.random.key(0), x, jax.random.key(1))
        _, aux = m.apply(params, x, jax.random.key(2))
        mus, logvars = aux["mus"], aux["logvars"]  # [P, B, d]
        manual = jnp.mean(
            jnp.sum(kl_diagonal_gaussian(mus, logvars, axis=-1), axis=0)
        )
        assert float(jnp.sum(aux["kl_per_feature"])) == pytest.approx(
            float(manual), rel=1e-5
        )

    def test_logvar_offset_applied(self, bundle):
        m = tiny_model()
        x = jnp.asarray(bundle.x_train[:4])
        params = m.init(jax.random.key(0), x, jax.random.key(1))
        _, aux = m.apply(params, x, jax.random.key(2))
        # fresh init with offset -3: logvars should sit near -3
        assert float(jnp.median(aux["logvars"])) == pytest.approx(-3.0, abs=1.0)

    def test_permutation_invariance(self, bundle):
        # The aggregator is a set transformer: shuffling particle slots must
        # not change the prediction (deterministic path, sample=False).
        m = tiny_model()
        x = jnp.asarray(bundle.x_train[:4])
        params = m.init(jax.random.key(0), x, jax.random.key(1))
        pred1, _ = m.apply(params, x, jax.random.key(2), sample=False)
        sets = x.reshape(4, 8, -1)
        perm = jax.random.permutation(jax.random.key(3), 8)
        x_perm = sets[:, perm].reshape(4, -1)
        pred2, _ = m.apply(params, x_perm, jax.random.key(2), sample=False)
        np.testing.assert_allclose(np.asarray(pred1), np.asarray(pred2), atol=1e-5)

    def test_encode_paths_consistent(self, bundle):
        m = tiny_model()
        x = jnp.asarray(bundle.x_valid[:6])
        params = m.init(jax.random.key(0), x, jax.random.key(1))
        _, aux = m.apply(params, x, jax.random.key(2))
        mus_all, logvars_all = m.encode(params, x)
        np.testing.assert_allclose(
            np.asarray(mus_all), np.asarray(aux["mus"]), atol=1e-6
        )
        # encode_feature on slot f's raw columns == slot f of the full encode
        sets = np.asarray(x).reshape(6, 8, -1)
        mus_f, logvars_f = m.encode_feature(params, 3, jnp.asarray(sets[:, 3]))
        np.testing.assert_allclose(
            np.asarray(mus_f), np.asarray(mus_all[3]), atol=1e-6
        )


@pytest.mark.slow
class TestTraining:
    def test_trains_and_hooks_work(self, bundle, tmp_path):
        from dib_tpu.train import InfoPerFeatureHook

        m = tiny_model()
        cfg = TrainConfig(
            batch_size=16,
            beta_start=2e-6,
            beta_end=2e-1,
            num_pretraining_epochs=2,
            num_annealing_epochs=6,
            steps_per_epoch=2,
            max_val_points=32,
            warmup_steps=4,
        )
        tr = DIBTrainer(m, bundle, cfg)
        hook = InfoPerFeatureHook(64, 1)
        state, hist = tr.fit(jax.random.key(0), hooks=[hook], hook_every=4)
        h = hist.to_bits()
        assert np.isfinite(h.loss).all()
        assert h.kl_per_feature.shape == (8, 8)
        # hook ran twice, once per chunk, over all 8 particle slots
        assert hook.bounds_bits.shape == (2, 8, 2)
        lower, upper = hook.bounds_bits[..., 0], hook.bounds_bits[..., 1]
        assert (lower <= upper + 1e-6).all()


@pytest.mark.slow
def test_remat_preserves_values_and_grads(rng):
    import optax
    from dib_tpu.models.per_particle import PerParticleDIBModel

    model = PerParticleDIBModel(
        num_particles=8, particle_feature_dim=3, encoder_hidden=(16,),
        embedding_dim=8, num_blocks=2, num_heads=2, key_dim=8,
        ff_hidden=(16,), head_hidden=(16,),
    )
    x = jnp.asarray(rng.standard_normal((4, 8 * 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, 4), jnp.float32)
    key = jax.random.key(1)
    params = model.init(jax.random.key(0), x, key)
    remat = model.clone(remat=True)

    def loss(m):
        def inner(p):
            pred, aux = m.apply(p, x, key, sample=False)
            return (
                jnp.mean(optax.sigmoid_binary_cross_entropy(pred.squeeze(-1), y))
                + 1e-3 * jnp.sum(aux["kl_per_feature"])
            )
        return inner

    l0, g0 = jax.value_and_grad(loss(model))(params)
    l1, g1 = jax.value_and_grad(loss(remat))(params)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-6)
    f0, _ = jax.flatten_util.ravel_pytree(g0)
    f1, _ = jax.flatten_util.ravel_pytree(g1)
    np.testing.assert_allclose(np.asarray(f1), np.asarray(f0), rtol=1e-5, atol=1e-6)


def test_fused_qkv_trains_and_matches_unfused_math(rng):
    """fuse_qkv=True (roofline remedy) is the same computation with a
    different parameter layout: stitching the unfused q/k/v kernels into
    the fused [in, 3, H, D] kernel must reproduce the unfused forward
    exactly, and the fused model must take a finite grad step."""
    import optax
    from dib_tpu.models.per_particle import PerParticleDIBModel

    model = PerParticleDIBModel(
        num_particles=8, particle_feature_dim=3, encoder_hidden=(16,),
        embedding_dim=8, num_blocks=2, num_heads=2, key_dim=8,
        ff_hidden=(16,), head_hidden=(16,),
    )
    fused = model.clone(fuse_qkv=True)
    x = jnp.asarray(rng.standard_normal((4, 8 * 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, 4), jnp.float32)
    key = jax.random.key(1)
    params = model.init(jax.random.key(0), x, key)

    # unfused params -> fused layout: kernel [in, H, D] x 3 -> [in, 3, H, D]
    import flax

    fused_params = flax.core.unfreeze(params)   # rebuilds every dict level
    for name, block in fused_params["params"]["aggregator"].items():
        if not name.startswith("SetAttentionBlock"):
            continue
        mha = block["MultiHeadSelfAttention_0"]
        mha["qkv"] = {
            "kernel": jnp.stack(
                [mha[k]["kernel"] for k in ("query", "key", "value")], axis=1
            ),
            "bias": jnp.stack(
                [mha[k]["bias"] for k in ("query", "key", "value")], axis=0
            ),
        }
        for k in ("query", "key", "value"):
            del mha[k]

    pred0, aux0 = model.apply(params, x, key, sample=False)
    pred1, aux1 = fused.apply(fused_params, x, key, sample=False)
    np.testing.assert_allclose(np.asarray(pred1), np.asarray(pred0),
                               rtol=1e-5, atol=1e-6)

    def loss(p):
        pred, aux = fused.apply(p, x, key, sample=False)
        return (jnp.mean(optax.sigmoid_binary_cross_entropy(pred.squeeze(-1), y))
                + 1e-3 * jnp.sum(aux["kl_per_feature"]))

    l, g = jax.value_and_grad(loss)(fused_params)
    flat, _ = jax.flatten_util.ravel_pytree(g)
    assert np.isfinite(float(l)) and np.isfinite(np.asarray(flat)).all()
