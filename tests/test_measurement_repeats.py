"""Repeat-ensemble training of the chaos measurement stack.

The paper's protocol is N repeats per configuration (chaos notebook cell 10
header); the ensemble trainer runs them as one vmapped program. Pins: replica
parity with the serial trainer, per-replica early-stop freezing, and the
mesh-sharded path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from dib_tpu.data.chaos_maps import generate_data
from dib_tpu.models.measurement import MeasurementStack
from dib_tpu.train.measurement import (
    MeasurementConfig,
    MeasurementRepeatTrainer,
    MeasurementTrainer,
    make_state_windows,
)


def _setup(mi_stop_bits=10.0, num_steps=40):
    traj = generate_data("logistic", number_iterations=2000, seed=0)
    windows = make_state_windows(traj, 4)
    stack = MeasurementStack(alphabet_size=2, num_states=4)
    config = MeasurementConfig(
        batch_size=64, num_steps=num_steps, check_every=20,
        mi_eval_batch_size=128, mi_eval_batches=1, mi_stop_bits=mi_stop_bits,
    )
    return stack, windows, config


def test_repeat_replica_matches_serial():
    stack, windows, config = _setup()
    key = jax.random.key(7)
    serial = MeasurementTrainer(stack, windows, config)
    s_state, s_hist = serial.fit(key)

    repeats = MeasurementRepeatTrainer(stack, windows, config, num_repeats=2)
    keys = jnp.stack([key, jax.random.key(8)])
    r_states, r_hist = repeats.fit(keys)

    # same key chain and schedule; XLA reorders float32 reductions under
    # vmap, so agreement is to accumulated-float tolerance over 40 steps
    # (the BetaSweepTrainer.recover_replica caveat)
    flat_s, _ = jax.flatten_util.ravel_pytree(s_state.params)
    flat_r, _ = jax.flatten_util.ravel_pytree(repeats.replica_state(r_states, 0).params)
    np.testing.assert_allclose(np.asarray(flat_r), np.asarray(flat_s),
                               rtol=1e-2, atol=2e-3)
    np.testing.assert_allclose(r_hist["loss"][0], s_hist["loss"],
                               rtol=1e-2, atol=2e-3)
    # second replica is a genuinely different sample
    assert not np.allclose(r_hist["loss"][1], s_hist["loss"])


def test_repeat_early_stop_freezes_replicas():
    stack, windows, config = _setup(mi_stop_bits=0.0, num_steps=100)
    repeats = MeasurementRepeatTrainer(stack, windows, config, num_repeats=2)
    states, hist = repeats.fit(jax.random.split(jax.random.key(0), 2))
    # every replica crosses a 0-bit threshold at the first check
    assert bool(hist["stopped_early"].all())
    assert hist["loss"].shape == (2, config.check_every)
    assert len(hist["mi_bounds"]) == 1


def test_repeat_sharded_over_mesh():
    from dib_tpu.parallel.mesh import make_sweep_mesh

    stack, windows, config = _setup(num_steps=20)
    mesh = make_sweep_mesh(2, 1, devices=jax.devices()[:2])
    repeats = MeasurementRepeatTrainer(stack, windows, config, num_repeats=2,
                                       mesh=mesh)
    states, hist = repeats.fit(jax.random.split(jax.random.key(1), 2))
    assert hist["loss"].shape == (2, 20)
    assert np.isfinite(hist["loss"]).all()


def test_repeat_mixed_active_mask_freezes_only_inactive():
    """Direct run_chunk with active=[True, False]: the frozen replica's
    params must be bit-identical before/after; the live one must move; the
    frozen replica's stats must be NaN-masked."""
    stack, windows, config = _setup()
    repeats = MeasurementRepeatTrainer(stack, windows, config, num_repeats=2)
    keys = jax.random.split(jax.random.key(3), 2)
    states = repeats.init(keys)
    before = jax.device_get(states.params)
    new_states, stats = repeats.run_chunk(
        states, jax.random.split(jax.random.key(4), 2),
        jnp.asarray([True, False]), 5,
    )
    after = jax.device_get(new_states.params)
    f_before, _ = jax.flatten_util.ravel_pytree(
        jax.tree.map(lambda a: a[1], before))
    f_after, _ = jax.flatten_util.ravel_pytree(
        jax.tree.map(lambda a: a[1], after))
    np.testing.assert_array_equal(f_after, f_before)  # frozen: bit-identical
    l_before, _ = jax.flatten_util.ravel_pytree(
        jax.tree.map(lambda a: a[0], before))
    l_after, _ = jax.flatten_util.ravel_pytree(
        jax.tree.map(lambda a: a[0], after))
    assert not np.array_equal(l_after, l_before)      # live: trained
    assert np.isnan(np.asarray(stats["loss"])[1]).all()
    assert np.isfinite(np.asarray(stats["loss"])[0]).all()


def test_repeat_rejects_wrong_key_count():
    stack, windows, config = _setup()
    repeats = MeasurementRepeatTrainer(stack, windows, config, num_repeats=3)
    with pytest.raises(ValueError, match="3 repeat keys"):
        repeats.fit(jax.random.split(jax.random.key(0), 2))


def test_chaos_workload_with_repeats():
    from dib_tpu.workloads import run_chaos_workload

    result = run_chaos_workload(
        system="logistic", num_states=4, train_iterations=2000,
        characterization_iterations=30_000,
        config=MeasurementConfig(batch_size=64, num_steps=40, check_every=20,
                                 mi_eval_batch_size=128, mi_eval_batches=1),
        scaling_lengths=[5_000, 10_000, 20_000], num_scaling_draws=1,
        num_noise_draws=8, include_random_baseline=False, chunk_size=5_000,
        num_repeats=2,
    )
    assert result["num_repeats"] == 2
    assert result["repeat_history"]["loss"].shape[0] == 2
    assert "best_repeat" in result["history"]
    assert np.isfinite(result["fit"]["h_inf"])


def test_chaos_state_sweep(tmp_path):
    from dib_tpu.workloads import run_chaos_state_sweep

    result = run_chaos_state_sweep(
        system="logistic", state_counts=(2, 4), num_repeats=2,
        outdir=str(tmp_path),
        train_iterations=2000, characterization_iterations=30_000,
        config=MeasurementConfig(batch_size=64, num_steps=40, check_every=20,
                                 mi_eval_batch_size=128, mi_eval_batches=1),
        scaling_lengths=[5_000, 10_000, 20_000], num_scaling_draws=1,
        num_noise_draws=8, include_random_baseline=False, chunk_size=5_000,
    )
    curve = result["curve"]
    assert list(curve["state_counts"]) == [2, 4]
    assert np.isfinite(curve["h_inf"]).all()
    assert (tmp_path / "logistic_state_sweep.png").exists()
    assert set(result["per_state"]) == {2, 4}


def test_measurement_checkpoint_bitwise_resume(tmp_path):
    """Checkpoint mid-run; the resumed run must match an uninterrupted one
    bit-for-bit (same chunk boundaries, same key chain)."""
    from dib_tpu.train.measurement import MeasurementCheckpointer

    stack, windows, config = _setup(num_steps=40)  # check_every=20 -> 2 chunks
    tr_full = MeasurementTrainer(stack, windows, config)
    s_full, _ = tr_full.fit(jax.random.key(5))

    ckpt = MeasurementCheckpointer(str(tmp_path / "ck"))
    saved = []

    def hook(trainer, state, step):
        if step == 20 and not saved:
            ckpt.save(step, state, trainer.resume_key, trainer.latest_history)
            saved.append(step)

    tr_a = MeasurementTrainer(stack, windows, config)
    tr_a.fit(jax.random.key(5), hooks=[hook])
    assert saved == [20]

    tr_b = MeasurementTrainer(stack, windows, config)
    state, key, history = ckpt.restore(tr_b)
    assert int(state.step) == 20
    s_resumed, _ = tr_b.fit(key, state=state)
    f_full, _ = jax.flatten_util.ravel_pytree(jax.device_get(s_full.params))
    f_res, _ = jax.flatten_util.ravel_pytree(jax.device_get(s_resumed.params))
    np.testing.assert_array_equal(np.asarray(f_res), np.asarray(f_full))
    ckpt.close()


def test_measurement_checkpoint_repeats_resume(tmp_path):
    """Checkpoint a repeat run mid-way and resume: the continuation must
    match the uninterrupted run bit-for-bit (same widths, same key chain)."""
    from dib_tpu.train.measurement import MeasurementCheckpointer

    stack, windows, config = _setup(num_steps=40)  # 2 chunks of 20
    keys = jax.random.split(jax.random.key(9), 2)

    full = MeasurementRepeatTrainer(stack, windows, config, num_repeats=2)
    s_full, _ = full.fit(keys)

    ckpt = MeasurementCheckpointer(str(tmp_path / "ck"))
    saved = []

    def hook(trainer, states, step):
        if step == 20 and not saved:
            ckpt.save(step, states, trainer.resume_key,
                      active=trainer.latest_active,
                      stop_steps=trainer.latest_stop_steps)
            saved.append(step)

    interrupted = MeasurementRepeatTrainer(stack, windows, config, num_repeats=2)
    interrupted.fit(keys, hooks=[hook])
    assert saved == [20]

    resumed_tr = MeasurementRepeatTrainer(stack, windows, config, num_repeats=2)
    states, r_keys, history, active, stop_steps = ckpt.restore(resumed_tr)
    assert history is None
    assert active.shape == (2,)
    s_resumed, _ = resumed_tr.fit(
        r_keys, states=states, active=active, stop_steps=stop_steps
    )
    f_full, _ = jax.flatten_util.ravel_pytree(jax.device_get(s_full.params))
    f_res, _ = jax.flatten_util.ravel_pytree(jax.device_get(s_resumed.params))
    np.testing.assert_array_equal(np.asarray(f_res), np.asarray(f_full))
    ckpt.close()
