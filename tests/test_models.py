"""Model-layer tests: vmapped encoder bank equivalence, DIB model contract,
set transformer invariances, measurement stack shapes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dib_tpu.models import (
    DistributedIBModel,
    FeatureEncoderBank,
    SimpleBinaryEncoderBank,
    SetTransformer,
    MeasurementStack,
    pad_and_stack_features,
)


def test_pad_and_stack_ragged(rng):
    x = jnp.array(rng.normal(size=(5, 6)).astype(np.float32))
    stacked = pad_and_stack_features(x, [2, 1, 2, 1])
    assert stacked.shape == (4, 5, 2)
    np.testing.assert_array_equal(np.asarray(stacked[0]), np.asarray(x[:, :2]))
    np.testing.assert_array_equal(np.asarray(stacked[1, :, 0]), np.asarray(x[:, 2]))
    np.testing.assert_array_equal(np.asarray(stacked[1, :, 1]), 0.0)  # padding
    np.testing.assert_array_equal(np.asarray(stacked[3, :, 0]), np.asarray(x[:, 5]))


def test_encoder_bank_shapes_and_independence(rng):
    """Each feature must have its OWN parameters: encoding feature i must not
    change when another feature's input changes."""
    bank = FeatureEncoderBank(feature_dimensionalities=(2, 1), hidden=(16,), embedding_dim=4)
    key = jax.random.key(0)
    x = jnp.array(rng.normal(size=(6, 3)).astype(np.float32))
    params = bank.init(key, x)
    mus, logvars = bank.apply(params, x)
    assert mus.shape == (2, 6, 4) and logvars.shape == (2, 6, 4)

    x2 = x.at[:, 2].set(99.0)  # perturb only feature 1
    mus2, _ = bank.apply(params, x2)
    np.testing.assert_array_equal(np.asarray(mus[0]), np.asarray(mus2[0]))
    assert not np.allclose(np.asarray(mus[1]), np.asarray(mus2[1]))


def test_encoder_bank_params_differ_across_features(rng):
    """Stacked init must give each feature different weights (split rngs)."""
    bank = FeatureEncoderBank(feature_dimensionalities=(1, 1), hidden=(8,), embedding_dim=2)
    params = bank.init(jax.random.key(0), jnp.ones((2, 2)))
    leaves = jax.tree.leaves(params)
    kernels = [l for l in leaves if l.ndim >= 3]  # stacked kernels [F, in, out]
    assert kernels
    for leaf in kernels:
        assert not np.allclose(np.asarray(leaf[0]), np.asarray(leaf[1]))


def test_encode_single_matches_bank(rng):
    bank = FeatureEncoderBank(feature_dimensionalities=(2, 1, 3), hidden=(8,), embedding_dim=4)
    x = jnp.array(rng.normal(size=(5, 6)).astype(np.float32))
    params = bank.init(jax.random.key(0), x)
    mus_all, logvars_all = bank.apply(params, x)
    for f, (start, dim) in enumerate([(0, 2), (2, 1), (3, 3)]):
        mus_f, logvars_f = bank.encode_single(params, f, x[:, start : start + dim])
        np.testing.assert_allclose(np.asarray(mus_f), np.asarray(mus_all[f]), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(logvars_f), np.asarray(logvars_all[f]), rtol=1e-6)


def test_dib_model_contract(rng):
    model = DistributedIBModel(
        feature_dimensionalities=(2, 1, 2, 1),
        encoder_hidden=(16,),
        integration_hidden=(32,),
        output_dim=6,
        embedding_dim=8,
    )
    key = jax.random.key(0)
    x = jnp.array(rng.normal(size=(4, 6)).astype(np.float32))
    params = model.init(key, x, key)
    pred, aux = model.apply(params, x, key)
    assert pred.shape == (4, 6)
    assert aux["kl_per_feature"].shape == (4,)
    assert aux["mus"].shape == (4, 4, 8)
    assert aux["embeddings"].shape == (4, 32)
    assert np.all(np.asarray(aux["kl_per_feature"]) >= 0)


def test_dib_model_sample_flag(rng):
    model = DistributedIBModel(
        feature_dimensionalities=(1, 1), encoder_hidden=(8,),
        integration_hidden=(8,), output_dim=1, embedding_dim=2,
    )
    key = jax.random.key(0)
    x = jnp.ones((3, 2))
    params = model.init(key, x, key)
    det1, _ = model.apply(params, x, jax.random.key(1), sample=False)
    det2, _ = model.apply(params, x, jax.random.key(2), sample=False)
    np.testing.assert_array_equal(np.asarray(det1), np.asarray(det2))
    s1, _ = model.apply(params, x, jax.random.key(1))
    s2, _ = model.apply(params, x, jax.random.key(2))
    assert not np.allclose(np.asarray(s1), np.asarray(s2))


def test_logvar_offset_shifts_output(rng):
    kw = dict(feature_dimensionalities=(1,), hidden=(8,), embedding_dim=2)
    x = jnp.ones((3, 1))
    bank0 = FeatureEncoderBank(**kw, logvar_offset=0.0)
    bank3 = FeatureEncoderBank(**kw, logvar_offset=-3.0)
    params = bank0.init(jax.random.key(0), x)
    _, lv0 = bank0.apply(params, x)
    _, lv3 = bank3.apply(params, x)
    np.testing.assert_allclose(np.asarray(lv3), np.asarray(lv0) - 3.0, rtol=1e-6)


def test_simple_binary_encoder_bank():
    bank = SimpleBinaryEncoderBank(num_features=3)
    x = jnp.array([[1.0, -1.0, 1.0], [-1.0, 1.0, -1.0]])
    params = bank.init(jax.random.key(0), x)
    mus, logvars = bank.apply(params, x)
    assert mus.shape == (3, 2, 1)
    # init: mu_scale = 1 => mus == inputs; logvar == -3
    np.testing.assert_allclose(np.asarray(mus[:, :, 0]), np.asarray(x.T), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(logvars), -3.0, rtol=1e-6)


def test_set_transformer_permutation_invariance(rng):
    st = SetTransformer(num_blocks=2, num_heads=2, key_dim=8, model_dim=8,
                        ff_hidden=(16,), head_hidden=(16,), output_dim=1)
    x = jnp.array(rng.normal(size=(2, 10, 8)).astype(np.float32))
    params = st.init(jax.random.key(0), x)
    out = st.apply(params, x)
    perm = jnp.array(rng.permutation(10))
    out_perm = st.apply(params, x[:, perm])
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_perm), rtol=1e-4, atol=1e-5)


def test_measurement_stack_contract(rng):
    ms = MeasurementStack(ib_embedding_dim=4, alphabet_size=3, num_states=5, infonce_dim=8,
                          encoder_hidden=(16,), vq_hidden=(16,),
                          aggregator_hidden=(16,), reference_hidden=(16,))
    key = jax.random.key(0)
    states = jnp.array(rng.normal(size=(4, 5, 2)).astype(np.float32))
    params = ms.init(key, states, key)
    seq_emb, ref_emb, kl, soft = ms.apply(params, states, key)
    assert seq_emb.shape == (4, 8) and ref_emb.shape == (4, 8)
    assert float(kl) >= 0
    assert soft.shape == (4, 5, 3)
    np.testing.assert_allclose(np.asarray(soft.sum(-1)), 1.0, rtol=1e-5)


def test_measurement_symbolize_deterministic(rng):
    ms = MeasurementStack(ib_embedding_dim=4, alphabet_size=2, num_states=3, infonce_dim=8,
                          encoder_hidden=(8,), vq_hidden=(8,),
                          aggregator_hidden=(8,), reference_hidden=(8,))
    key = jax.random.key(0)
    states = jnp.array(rng.normal(size=(2, 3, 2)).astype(np.float32))
    params = ms.init(key, states, key)
    flat = jnp.array(rng.normal(size=(20, 2)).astype(np.float32))
    s1 = ms.apply(params, flat, jax.random.key(5), num_noise_draws=16, method="symbolize")
    s2 = ms.apply(params, flat, jax.random.key(5), num_noise_draws=16, method="symbolize")
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    assert s1.shape == (20,) and s1.dtype == np.uint8
