"""Flash (blockwise Pallas) attention vs the dense oracle.

Runs in interpreter mode on the CPU backend (same idiom as
tests/test_pallas_density.py); the math — online softmax over key blocks,
padding masks, non-divisible shapes — is identical to what the TPU lowering
executes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dib_tpu.ops.pallas_attention import flash_self_attention
from dib_tpu.parallel.context import dense_self_attention


def _qkv(rng, batch=2, seq=64, heads=3, dim=16):
    return tuple(
        jnp.asarray(rng.standard_normal((batch, seq, heads, dim)), jnp.float32)
        for _ in range(3)
    )


@pytest.mark.parametrize("seq,block", [(64, 32), (64, 64), (50, 16), (37, 32)])
def test_flash_matches_dense(rng, seq, block):
    q, k, v = _qkv(rng, seq=seq)
    got = flash_self_attention(q, k, v, block_q=block, block_k=block)
    want = dense_self_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_flash_single_block_degenerate(rng):
    q, k, v = _qkv(rng, seq=8)
    got = flash_self_attention(q, k, v, block_q=256, block_k=256)
    want = dense_self_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_flash_large_scores_stay_finite(rng):
    # the flagship failure mode: huge activations -> huge scores
    q, k, v = _qkv(rng, seq=64)
    got = flash_self_attention(q * 100.0, k * 100.0, v, block_q=32, block_k=32)
    want = dense_self_attention(q * 100.0, k * 100.0, v)
    assert bool(jnp.isfinite(got).all())
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)


def test_set_transformer_flash_matches_dense(rng):
    from dib_tpu.models.set_transformer import SetTransformer

    x = jnp.asarray(rng.standard_normal((2, 40, 8)), jnp.float32)
    dense = SetTransformer(num_blocks=2, num_heads=4, key_dim=8, model_dim=8,
                           ff_hidden=(16,), head_hidden=(16,), output_dim=1)
    params = dense.init(jax.random.key(0), x)
    want = dense.apply(params, x)
    got = dense.clone(use_flash=True).apply(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_flash_grads_match_dense(rng):
    q, k, v = _qkv(rng, seq=48, heads=2, dim=8)

    def loss_flash(q, k, v):
        return jnp.sum(flash_self_attention(q, k, v, block_q=16, block_k=16) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_self_attention(q, k, v) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=1e-4, atol=1e-4, err_msg=name
        )


def test_flash_bfloat16_matches_dense(rng):
    """Mixed-precision composition: bf16 q/k/v through the kernel tracks the
    dense oracle to bf16 rounding tolerance, stays finite."""
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(rng, seq=64, heads=2, dim=16))
    got = flash_self_attention(q, k, v, block_q=32, block_k=32)
    want = dense_self_attention(q, k, v)
    assert got.dtype == jnp.float32
    assert bool(jnp.isfinite(got).all())
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)
