"""Streaming sources + the online trainer's atomic publish protocol
(``dib_tpu/stream``, docs/streaming.md).

The load-bearing contracts:

  - sources are pure functions of ``(seed, index)``: a snapshot/restore
    across a preempt boundary is BIT-IDENTICAL to never stopping;
  - the publish protocol (stage -> fsync -> rename -> journal) never
    leaves a journal record pointing at torn bytes — a kill before the
    rename leaves only staging litter, a kill after it only an orphaned
    complete checkpoint the resumed trainer republishes;
  - a resumed online trainer continues the EXACT run the dead one was
    in: same publish ids, steps, betas, and source offsets as an
    uninterrupted run;
  - scripted drift trips the detector, lands durable drift records, and
    re-anneals β.
"""

import json
import os

import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.models import DistributedIBModel
from dib_tpu.stream.online import (
    OnlineConfig,
    OnlineDIBTrainer,
    read_publishes,
)
from dib_tpu.stream.source import (
    DriftSpec,
    ReservoirSource,
    RowStream,
    SlidingWindowSource,
    make_source,
    parse_drift_specs,
)
from dib_tpu.train import TrainConfig

WINDOW, STRIDE, CHUNK_EPOCHS, BATCH = 32, 8, 1, 16


@pytest.fixture(scope="module")
def bundle():
    return get_dataset("boolean_circuit")


@pytest.fixture(scope="module")
def model(bundle):
    return DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )


def _config():
    return TrainConfig(batch_size=BATCH, num_pretraining_epochs=1,
                       num_annealing_epochs=2)


def _online(**overrides) -> OnlineConfig:
    spec = dict(window=WINDOW, stride=STRIDE, chunk_epochs=CHUNK_EPOCHS,
                publish_every=1, rounds=3, seed=0)
    spec.update(overrides)
    return OnlineConfig(**spec)


def _trainer(model, bundle, stream_dir, telemetry=None, **overrides):
    return OnlineDIBTrainer(model, bundle, _config(), _online(**overrides),
                            str(stream_dir), telemetry=telemetry)


# ------------------------------------------------------------------ sources
def test_parse_drift_specs_grammar():
    specs = parse_drift_specs(["512:mean_shift:2.0", "128", "256:scale"])
    assert [s.at for s in specs] == [128, 256, 512]     # sorted
    assert specs[0].kind == "mean_shift" and specs[0].magnitude == 1.0
    assert specs[1].kind == "scale"
    with pytest.raises(ValueError, match="unknown drift kind"):
        DriftSpec(at=0, kind="rotate")
    with pytest.raises(ValueError, match="must be >= 0"):
        DriftSpec(at=-1)


def test_row_stream_is_a_pure_function_of_the_index(rng):
    x = rng.normal(size=(20, 3)).astype(np.float32)
    y = rng.integers(0, 2, size=20).astype(np.float32)
    a = RowStream(x, y, seed=7)
    b = RowStream(x, y, seed=7)
    xa, ya = a.rows(13, 10)
    xb, yb = b.rows(13, 10)
    np.testing.assert_array_equal(xa, xb)
    np.testing.assert_array_equal(ya, yb)
    # each epoch-sized block is a permutation of the data
    x0, _ = a.rows(0, 20)
    np.testing.assert_array_equal(np.sort(x0, axis=0), np.sort(x, axis=0))
    # a different seed reorders
    assert not np.array_equal(RowStream(x, y, seed=8).rows(0, 20)[0], x0)


def test_drift_applies_per_row_at_its_own_index(rng):
    x = rng.normal(size=(16, 2)).astype(np.float32)
    y = np.zeros(16, np.float32)
    clean = RowStream(x, y, seed=1)
    drifted = RowStream(x, y, seed=1,
                        drift=(DriftSpec(at=10, magnitude=5.0),))
    x_pre, _ = drifted.take(range(0, 10))
    np.testing.assert_array_equal(x_pre, clean.take(range(0, 10))[0])
    x_post, _ = drifted.take(range(10, 16))
    np.testing.assert_allclose(
        x_post, clean.take(range(10, 16))[0] + 5.0, rtol=1e-6)
    # a reservoir holding pre-drift rows keeps them pre-drift: mixed
    # index sets transform only the post-drift rows
    x_mix, _ = drifted.take([3, 12])
    np.testing.assert_array_equal(x_mix[0], clean.take([3])[0][0])


@pytest.mark.parametrize("kind", ["sliding", "reservoir"])
def test_source_resume_is_bit_identical_to_never_stopping(kind, rng):
    x = rng.normal(size=(40, 3)).astype(np.float32)
    y = rng.integers(0, 2, size=40).astype(np.float32)

    def fresh():
        return make_source(kind, RowStream(x, y, seed=3), window=8,
                           stride=4)

    straight = fresh()
    for _ in range(3):
        straight.advance()

    preempted = fresh()
    preempted.advance()
    state = json.loads(json.dumps(preempted.snapshot()))   # journal trip
    resumed = fresh()
    resumed.restore(state)
    for _ in range(2):
        resumed.advance()

    for _ in range(4):   # the windows stay identical forever after
        xs, ys = straight.window()
        xr, yr = resumed.window()
        np.testing.assert_array_equal(xs, xr)
        np.testing.assert_array_equal(ys, yr)
        assert straight.rows_consumed == resumed.rows_consumed
        straight.advance()
        resumed.advance()


def test_source_restore_rejects_mismatched_configuration(rng):
    x = rng.normal(size=(16, 2)).astype(np.float32)
    y = np.zeros(16, np.float32)
    sliding = SlidingWindowSource(RowStream(x, y), window=8)
    reservoir = ReservoirSource(RowStream(x, y), window=8)
    with pytest.raises(ValueError, match="--stream-source"):
        sliding.restore(reservoir.snapshot())
    small = ReservoirSource(RowStream(x, y), window=4)
    with pytest.raises(ValueError, match="--window"):
        small.restore(reservoir.snapshot())
    with pytest.raises(ValueError, match="unknown source kind"):
        make_source("ring", RowStream(x, y), window=8)


# ----------------------------------------------------- the publish protocol
def test_online_resume_continues_the_exact_run(model, bundle, tmp_path):
    """An online trainer killed after round 1 and relaunched publishes
    the same ids, steps, betas, and source snapshots an uninterrupted
    run publishes — the continuation is bit-identical, checkpoint bytes
    included."""
    import jax

    straight_dir = tmp_path / "straight"
    resumed_dir = tmp_path / "resumed"
    _trainer(model, bundle, straight_dir, rounds=3).run(jax.random.key(0))

    _trainer(model, bundle, resumed_dir, rounds=2).run(jax.random.key(0))
    _trainer(model, bundle, resumed_dir, rounds=3).run(jax.random.key(0))

    straight, torn_a = read_publishes(str(straight_dir))
    resumed, torn_b = read_publishes(str(resumed_dir))
    assert torn_a == torn_b == 0
    assert len(straight) == len(resumed) == 3
    for a, b in zip(straight, resumed):
        for key in ("publish_id", "index", "step", "round", "path",
                    "source", "chunk_epochs", "drifts"):
            assert a[key] == b[key], key
        assert a["beta"] == pytest.approx(b["beta"], rel=1e-6)
    assert [r["index"] for r in straight] == [0, 1, 2]

    # the published params are bit-identical too
    from dib_tpu.train import DIBCheckpointer, DIBTrainer

    final = straight[-1]["path"]
    states = []
    for root in (straight_dir, resumed_dir):
        template = DIBTrainer(model, bundle, _config())
        ckpt = DIBCheckpointer(str(root / final))
        try:
            state, _, _ = ckpt.restore(template)
        finally:
            ckpt.close()
        states.append(state)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        states[0].params, states[1].params)

    # no staging litter survives a clean run
    staging = straight_dir / "staging"
    assert not (staging.exists() and os.listdir(staging))


def test_kill_before_rename_leaves_staging_never_a_record(
        model, bundle, tmp_path, monkeypatch):
    """A trainer dying mid-publish (after fsync, before rename) leaves
    staging litter and NO journal record — the deployer can never
    promote torn bytes — and the relaunch sweeps staging and publishes
    the full run."""
    import jax

    import dib_tpu.stream.online as online_mod

    class Boom(BaseException):
        """SIGKILL steals the process; BaseException-shaped on purpose."""

    real_kill = online_mod.maybe_kill
    hits = {"n": 0}

    def kill_second_publish(point, telemetry=None):
        if point == "mid_publish":
            hits["n"] += 1
            if hits["n"] == 2:
                raise Boom()
        return real_kill(point, telemetry)

    monkeypatch.setattr(online_mod, "maybe_kill", kill_second_publish)
    stream_dir = tmp_path / "stream"
    with pytest.raises(Boom):
        _trainer(model, bundle, stream_dir).run(jax.random.key(0))

    staging = stream_dir / "staging"
    assert staging.is_dir() and os.listdir(staging), \
        "the kill point must leave torn staging bytes"
    records, torn = read_publishes(str(stream_dir))
    assert torn == 0 and len(records) == 1, \
        "no record may reference the torn staging checkpoint"

    monkeypatch.setattr(online_mod, "maybe_kill", real_kill)
    _trainer(model, bundle, stream_dir).run(jax.random.key(0))
    records, _ = read_publishes(str(stream_dir))
    assert [r["index"] for r in records] == [0, 1, 2]
    assert not (staging.exists() and os.listdir(staging)), \
        "the relaunch sweeps staging litter"


def test_kill_after_rename_republishes_the_orphan_exactly_once(
        model, bundle, tmp_path, monkeypatch):
    """A trainer dying between the rename and the journal append leaves
    an orphaned COMPLETE checkpoint no record references; the resumed
    (bit-identical) trainer republishes the same step with exactly one
    record — never a duplicate index."""
    import jax

    import dib_tpu.stream.online as online_mod

    class Boom(BaseException):
        pass

    real_kill = online_mod.maybe_kill
    hits = {"n": 0}

    def kill_second_rename(point, telemetry=None):
        if point == "post_rename":
            hits["n"] += 1
            if hits["n"] == 2:
                raise Boom()
        return real_kill(point, telemetry)

    monkeypatch.setattr(online_mod, "maybe_kill", kill_second_rename)
    stream_dir = tmp_path / "stream"
    with pytest.raises(Boom):
        _trainer(model, bundle, stream_dir).run(jax.random.key(0))

    records, _ = read_publishes(str(stream_dir))
    orphans = set(os.listdir(stream_dir / "checkpoints")) \
        - {r["publish_id"] for r in records}
    assert len(records) == 1 and len(orphans) == 1, \
        "the kill leaves one complete checkpoint with no record"

    monkeypatch.setattr(online_mod, "maybe_kill", real_kill)
    _trainer(model, bundle, stream_dir).run(jax.random.key(0))
    records, _ = read_publishes(str(stream_dir))
    indices = [r["index"] for r in records]
    assert indices == sorted(set(indices)) == [0, 1, 2]
    ids = [r["publish_id"] for r in records]
    assert len(ids) == len(set(ids)), "never a duplicate publish record"


def test_scripted_drift_trips_detector_and_reanneals(
        model, bundle, tmp_path):
    """Scripted drift past the baseline window lands a durable drift
    record, a drift telemetry event, and rewinds the β schedule to the
    anneal start (the published β drops back toward beta_start)."""
    import jax

    from dib_tpu.sched.journal import read_journal
    from dib_tpu.telemetry import EventWriter

    stream_dir = tmp_path / "stream"
    writer = EventWriter(str(tmp_path / "run"))
    trainer = _trainer(
        model, bundle, stream_dir, telemetry=writer, rounds=4,
        drift=(DriftSpec(at=WINDOW + 2 * STRIDE, magnitude=25.0),),
        drift_threshold=2.0)
    summary = trainer.run(jax.random.key(0))
    writer.close()
    assert summary["drifts"] >= 1

    records, _ = read_journal(str(stream_dir / "publishes.jsonl"))
    drift_recs = [r for r in records if r.get("kind") == "drift"]
    assert drift_recs and drift_recs[0]["action"] == "reanneal"
    assert drift_recs[0]["shift"] > 2.0
    drift_round = drift_recs[0]["round"]

    publishes = [r for r in records if r.get("kind") == "publish"]
    betas = {r["round"]: r["beta"] for r in publishes}
    assert betas[drift_round] < betas[drift_round - 1], \
        "re-anneal must rewind β toward beta_start"

    events = [json.loads(line) for line in open(writer.path)]
    drift_events = [e for e in events if e.get("type") == "drift"]
    assert drift_events and drift_events[0]["detector"] == "window_mean"
    assert drift_events[0]["action"] == "reanneal"


def test_window_must_cover_a_batch(model, bundle, tmp_path):
    with pytest.raises(ValueError, match="batch_size"):
        OnlineDIBTrainer(model, bundle, _config(),
                         _online(window=BATCH // 2), str(tmp_path))


# ---------------------------------------------------------------- watchdog
def test_watchdog_reexec_preserves_the_action_token(tmp_path, monkeypatch):
    """``--watchdog`` re-execs ``python -m dib_tpu.cli stream <worker
    argv>``: the worker argv must keep ``run``/``deploy`` in first
    position (the subparser action token) and must NOT keep
    ``--watchdog`` — a worker argv that fails to parse exits 2
    immediately and the supervisor burns its whole restart budget
    against the crash loop without ever doing work."""
    import dib_tpu.train.watchdog as watchdog
    from dib_tpu.stream.cli import build_stream_parser, stream_main

    captured = {}

    def fake_supervise_pool(cmd, config=None, telemetry=None,
                            journal_path=None, terminal_kinds=()):
        captured["cmd"] = list(cmd)
        captured["journal_path"] = journal_path
        captured["terminal_kinds"] = tuple(terminal_kinds)
        return {"returncode": 0, "restarts": 0}

    monkeypatch.setattr(watchdog, "supervise_pool", fake_supervise_pool)
    monkeypatch.setenv("DIB_TELEMETRY_RUN_ID", "pre-existing")

    stream_dir = tmp_path / "stream"
    stream_dir.mkdir()
    rc = stream_main(["run", "--watchdog", "--stream-dir",
                      str(stream_dir), "--telemetry-dir", ""])
    assert rc == 0
    worker = captured["cmd"][captured["cmd"].index("stream") + 1:]
    assert worker[0] == "run" and "--watchdog" not in worker
    # the worker argv must actually parse — rc-2 crash-loops otherwise
    args = build_stream_parser().parse_args(worker)
    assert args.action == "run" and args.watchdog is False
    assert captured["journal_path"].endswith("publishes.jsonl")
    assert captured["terminal_kinds"] == ("publish",)

    deploy_dir = tmp_path / "deploy"
    deploy_dir.mkdir()
    rc = stream_main(["deploy", "--watchdog", "--stream-dir",
                      str(stream_dir), "--deploy-dir", str(deploy_dir),
                      "--telemetry-dir", ""])
    assert rc == 0
    worker = captured["cmd"][captured["cmd"].index("stream") + 1:]
    assert worker[0] == "deploy" and "--watchdog" not in worker
    args = build_stream_parser().parse_args(worker)
    assert args.action == "deploy" and args.watchdog is False
    assert captured["journal_path"].endswith("deploys.jsonl")
    assert captured["terminal_kinds"] == ("deploy",)


def test_keep_publishes_bounds_disk_and_resume_survives(
        model, bundle, tmp_path):
    """``keep_publishes`` prunes all but the newest N checkpoint dirs —
    an always-on stream must not fill the disk with one resume payload
    per cadence. The journal keeps every record (the durable ledger) and
    the kept tail always contains the newest publish, so a relaunch
    still resumes."""
    import jax

    stream_dir = tmp_path / "stream"
    _trainer(model, bundle, stream_dir, rounds=4,
             keep_publishes=2).run(jax.random.key(0))

    records, torn = read_publishes(str(stream_dir))
    assert torn == 0 and len(records) == 4          # ledger: everything
    kept = sorted(os.listdir(stream_dir / "checkpoints"))
    assert kept == [os.path.basename(r["path"]) for r in records[-2:]]

    # the resume anchor (newest publish) is in the kept tail
    summary = _trainer(model, bundle, stream_dir, rounds=6,
                       keep_publishes=2).run(jax.random.key(0))
    assert summary["publishes"] == 6
    assert len(read_publishes(str(stream_dir))[0]) == 6


def test_zero_round_resume_summary_is_json_safe(model, bundle, tmp_path):
    """A relaunch already past ``rounds`` runs zero rounds; its summary
    must carry None finals — not NaN, which json.dumps would emit as a
    bare token strict parsers reject."""
    import jax

    stream_dir = tmp_path / "stream"
    _trainer(model, bundle, stream_dir, rounds=2).run(jax.random.key(0))
    summary = _trainer(model, bundle, stream_dir,
                       rounds=2).run(jax.random.key(0))
    assert summary["rounds"] == 2 and summary["epochs"] == 2
    assert summary["publishes"] == 2
    assert summary["final_loss"] is None
    assert summary["final_val_loss"] is None
    assert summary["final_beta"] is None
    parsed = json.loads(json.dumps(summary, allow_nan=False))
    assert parsed["final_loss"] is None


def test_row_stream_take_is_stable_across_perm_cache_eviction(rng):
    """Arbitrary index sets spanning more blocks than the permutation
    cache holds stay a pure function of the index: eviction (one oldest
    entry, never a full clear) must not change what any index maps to,
    and interleaved revisits of early blocks re-derive bit-identically."""
    x = rng.normal(size=(10, 3)).astype(np.float32)
    y = rng.integers(0, 2, size=10).astype(np.float32)
    stream = RowStream(x, y, seed=5)
    # 14 indices interleaved over 7 blocks — beyond the 4-entry cache
    indices = [block * 10 + offset
               for offset in (3, 8) for block in range(7)]
    first_x, first_y = stream.take(indices)
    again_x, again_y = stream.take(indices)
    np.testing.assert_array_equal(first_x, again_x)
    np.testing.assert_array_equal(first_y, again_y)
    # per-row reference from a fresh stream (cold cache, one block each)
    for pos, index in enumerate(indices):
        ref_x, ref_y = RowStream(x, y, seed=5).take([index])
        np.testing.assert_array_equal(first_x[pos], ref_x[0])
        np.testing.assert_array_equal(first_y[pos], ref_y[0])
