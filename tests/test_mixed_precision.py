"""Mixed precision: bfloat16 compute path keeps float32 params and
precision-critical outputs (channel parameters, KL, logits)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dib_tpu.models import DistributedIBModel, PerParticleDIBModel


@pytest.mark.slow
def test_distributed_ib_bf16_contract():
    model = DistributedIBModel(
        feature_dimensionalities=(2, 1), encoder_hidden=(16,),
        integration_hidden=(16,), output_dim=3, embedding_dim=4,
        compute_dtype="bfloat16",
    )
    x = jnp.ones((8, 3), jnp.float32)
    key = jax.random.key(0)
    params = model.init(jax.random.key(1), x, key)
    # params stay float32
    for leaf in jax.tree.leaves(params):
        assert leaf.dtype == jnp.float32
    prediction, aux = model.apply(params, x, key)
    assert prediction.dtype == jnp.float32
    assert aux["mus"].dtype == jnp.float32
    assert aux["logvars"].dtype == jnp.float32
    assert np.isfinite(np.asarray(prediction)).all()
    assert np.isfinite(np.asarray(aux["kl_per_feature"])).all()


@pytest.mark.slow
def test_per_particle_bf16_matches_f32_loosely():
    """bf16 compute must stay within bf16 rounding of the f32 forward pass
    (same params => same function up to precision)."""
    kwargs = dict(
        num_particles=6, particle_feature_dim=12, encoder_hidden=(16,),
        embedding_dim=8, num_blocks=1, num_heads=2, key_dim=8,
        ff_hidden=(8,), head_hidden=(16,),
    )
    m32 = PerParticleDIBModel(**kwargs)
    m16 = PerParticleDIBModel(**kwargs, compute_dtype="bfloat16")
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 6 * 12)), jnp.float32)
    key = jax.random.key(0)
    params = m32.init(jax.random.key(1), x, key)
    p32, aux32 = m32.apply(params, x, key)
    p16, aux16 = m16.apply(params, x, key)
    assert p16.dtype == jnp.float32
    # channel parameters come from the (shallow) encoder: tight agreement
    np.testing.assert_allclose(
        np.asarray(aux16["mus"]), np.asarray(aux32["mus"]), atol=0.05, rtol=0.05
    )
    # logits pass through the attention stack: looser, but same ballpark
    np.testing.assert_allclose(np.asarray(p16), np.asarray(p32), atol=0.5, rtol=0.5)
    np.testing.assert_allclose(
        np.asarray(aux16["kl_per_feature"]),
        np.asarray(aux32["kl_per_feature"]), rtol=0.1, atol=0.05,
    )
