"""The autopilot chaos-suite artifact contract
(``scripts/chaos_autopilot.py``, docs/streaming.md "Closed loop").

The committed ``CHAOS_AUTOPILOT.json`` must exist, validate against the
artifact schema (all five drills, the three closed-loop invariants per
row, the record-level zero-duplicate gate), and evaluate clean against
the committed ``SLO.json`` — "exactly-once drift→study, poison-proof
seeding, bit-identical applies" are only as good as the committed
evidence. The schema's reject shapes are pinned here too: a validator
that cannot refuse a doctored record protects nothing.
"""

import copy
import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "CHAOS_AUTOPILOT.json")
COMMITTED_SLO = os.path.join(REPO, "SLO.json")

EXPECTED_DRILLS = {
    "study_kill_adopt", "poisoned_seed", "apply_kill", "flap_debounce",
    "breaker_trip_recovery",
}
INVARIANTS = ("exactly_once_study", "zero_poisoned_seeds",
              "apply_bit_identical")


def _checker():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import check_run_artifacts

    return check_run_artifacts


def _record():
    with open(ARTIFACT) as f:
        return json.load(f)


def test_committed_chaos_autopilot_artifact_validates():
    assert os.path.exists(ARTIFACT), (
        "CHAOS_AUTOPILOT.json missing — run `python "
        "scripts/chaos_autopilot.py --out CHAOS_AUTOPILOT.json` and "
        "commit the record")
    assert _checker().check_file(ARTIFACT) == []


def test_committed_chaos_autopilot_matrix_is_complete_and_green():
    record = _record()
    assert record["metric"] == "chaos_autopilot_matrix"
    assert record["unit"] == "drills_passed"
    drills = {d["drill"]: d for d in record["matrix"]}
    assert set(drills) == EXPECTED_DRILLS
    failed = [name for name, d in drills.items() if not d["ok"]]
    assert not failed, f"committed chaos record shows failures: {failed}"
    assert record["all_passed"] is True
    assert record["value"] == record["total"] == len(EXPECTED_DRILLS)
    # the committed record must be the FULL matrix
    assert record["quick"] is False
    # every drill holds the three closed-loop invariants, and no drift
    # round anywhere minted a second study
    for name, d in drills.items():
        for invariant in INVARIANTS:
            assert d[invariant] is True, (name, invariant)
        assert d["duplicate_studies"] == 0, name
    assert record["duplicate_studies"] == 0


def test_committed_chaos_autopilot_drill_evidence():
    """Each drill's own mechanism actually fired: the kill landed in the
    intended journal window, the poison was refused (not missed), the
    interrupted apply reproduced the oracle's bytes, the debounce held,
    and the breaker tripped once then recovered to a converged study."""
    by_name = {d["drill"]: d for d in _record()["matrix"]}

    adopt = by_name["study_kill_adopt"]
    assert adopt["killed_by_sigkill"] is True
    assert adopt["kill_window_state"]["round_kinds"] == ["intent",
                                                         "submitted"]
    assert adopt["kill_window_state"]["jobs_under_round0_name"] == 1
    assert adopt["verdict"] == "converged"
    assert adopt["intents"] == 1 and adopt["applies"] == 1

    poison = by_name["poisoned_seed"]
    assert poison["intents"] == 0 and poison["applies"] == 0
    assert poison["schedule_written"] is False
    assert poison["poisoned_seed_mitigations"] >= 1
    assert poison["skip_reasons"].get("poisoned_seed", 0) >= 1

    apply_kill = by_name["apply_kill"]
    assert apply_kill["killed_by_sigkill"] is True
    assert apply_kill["kill_window_state"]["schedule_on_disk"] is False
    assert "apply_intent" in apply_kill["kill_window_state"]["round_kinds"]
    assert apply_kill["schedule_bit_identical_to_uninterrupted"] is True

    flap = by_name["flap_debounce"]
    assert flap["intents"] == 1
    assert flap["cooldown_skips"] == len(flap["drift_rounds"]) - 1

    breaker = by_name["breaker_trip_recovery"]
    assert breaker["tripped_state"]["breaker"]["open"] is True
    assert breaker["recovered_verdict"] == "converged"
    assert breaker["breaker"] == {"open": False, "trips": 1, "resets": 1,
                                  "consecutive": 0, "skips_since_trip": 0}

    # the telemetry-plane join agrees with the journal bookkeeping
    for name, d in by_name.items():
        rollup = (d.get("evidence") or {}).get("autopilot")
        assert rollup is not None, name
        assert rollup["duplicate_studies"] == 0, name
        assert rollup["intents"] == d["intents"], name


# ============================================================ reject shapes
def _problems(record):
    problems: list[str] = []
    _checker()._check_chaos_autopilot_matrix(record, problems)
    return problems


def test_chaos_autopilot_schema_rejects_doctored_records():
    committed = _record()
    assert _problems(committed) == []

    missing = copy.deepcopy(committed)
    missing["matrix"] = [d for d in missing["matrix"]
                         if d["drill"] != "poisoned_seed"]
    assert any("poisoned_seed" in p for p in _problems(missing))

    failed = copy.deepcopy(committed)
    failed["matrix"][0]["ok"] = False
    assert any("fail" in p for p in _problems(failed))

    broken_invariant = copy.deepcopy(committed)
    broken_invariant["matrix"][2]["apply_bit_identical"] = False
    assert any("apply_bit_identical" in p
               for p in _problems(broken_invariant))

    double_spend = copy.deepcopy(committed)
    double_spend["duplicate_studies"] = 1
    assert any("duplicate_studies" in p for p in _problems(double_spend))

    unmarked = copy.deepcopy(committed)
    del unmarked["duplicate_studies"]
    assert any("duplicate_studies" in p for p in _problems(unmarked))


# ================================================================= SLO pair
def test_committed_chaos_autopilot_record_passes_committed_slo():
    """CHAOS_AUTOPILOT.json is a valid `telemetry check` operand: the
    three autopilot rules all evaluate (none skipped) and pass — in
    process and through the real CLI."""
    from dib_tpu.telemetry.slo import check_run

    report = check_run(ARTIFACT, COMMITTED_SLO, write=False)
    assert report["violations"] == 0
    by_rule = {r["rule"]: r for r in report["rules"]}
    for rule in ("autopilot_duplicate_study_max",
                 "autopilot_breaker_trip_ceiling",
                 "drift_to_apply_p99_ceiling"):
        assert by_rule[rule]["status"] == "ok", rule
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "check",
         ARTIFACT, "--slo", COMMITTED_SLO],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_duplicate_study_breach_pages_via_subprocess(tmp_path):
    """A doctored record with one double-spent drift round exits 1
    against the committed SLO.json through the real CLI — the
    page-severity exactly-once gate."""
    doctored = _record()
    doctored["duplicate_studies"] = 1
    doctored["autopilot"]["duplicate_studies"] = 1
    path = tmp_path / "doctored.json"
    path.write_text(json.dumps(doctored))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "check",
         str(path), "--slo", COMMITTED_SLO, "--no-write"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert proc.returncode == 1, proc.stderr
    report = json.loads(proc.stdout)
    violated = [r["rule"] for r in report["rules"]
                if r["status"] == "violated"]
    assert violated == ["autopilot_duplicate_study_max"]


# ================================================================= registry
def test_chaos_autopilot_registers_in_fleet_registry(tmp_path):
    """Drill records land in the fleet registry only under an EXPLICIT
    runs root — ad-hoc local runs must not grow the committed index."""
    from dib_tpu.telemetry.registry import (
        RunRegistry,
        register_drill_record,
        validate_index_entry,
    )

    record = _record()
    root = str(tmp_path / "runs")
    assert register_drill_record(
        record, root=root,
        extra={"duplicate_studies": record["duplicate_studies"]}) is not None
    entries = RunRegistry(root).bench_history()
    assert len(entries) == 1
    assert entries[0]["metric"] == "chaos_autopilot_matrix"
    assert entries[0]["all_passed"] is True
    assert entries[0]["duplicate_studies"] == 0
    assert validate_index_entry(entries[0]) == []
    os.environ.pop("DIB_RUNS_ROOT", None)
    assert register_drill_record(record, root=None) is None
    assert len(RunRegistry(root).bench_history()) == 1


def test_committed_registry_carries_autopilot_history():
    from dib_tpu.telemetry.registry import RunRegistry

    entries = RunRegistry(os.path.join(REPO, "runs")).bench_history()
    autopilot = [e for e in entries
                 if e.get("metric") == "chaos_autopilot_matrix"]
    assert len(autopilot) == 1
    assert autopilot[0]["all_passed"] is True
    assert autopilot[0]["value"] == autopilot[0]["total"] == 5
    assert autopilot[0]["duplicate_studies"] == 0
