"""Fleet causal tracing (telemetry/context.py + telemetry/fleet.py):
trace-context minting/inheritance, the ctx envelope on events and
journal records, deterministic multi-writer merge ordering under clock
skew and torn lines, durable kill/resume with zero duplicate / zero
lost entries, orphan surfacing, burn-rate evaluation and alert landing,
and the `telemetry fleet` CLI exit codes.
"""

import json
import os

import pytest

from dib_tpu.telemetry.context import (
    TRACE_ENV,
    TRACE_ORIGIN_ENV,
    TRACE_PARENT_ENV,
    TraceContext,
    child_context,
    ensure_context,
    from_env,
    mint,
    parse_parent_ref,
)
from dib_tpu.telemetry.events import EventWriter, read_events
from dib_tpu.telemetry.fleet import (
    FleetAggregator,
    discover_sources,
    fleet_main,
    fleet_prometheus,
    merge_key,
    timeline_digest,
    write_fleet_report,
)
from dib_tpu.telemetry.summary import telemetry_main


@pytest.fixture(autouse=True)
def _clean_trace_env():
    # purge on teardown too: activate() writes os.environ directly, and
    # monkeypatch.delenv records no undo for a var absent at setup — a
    # test that activates a ctx would otherwise leak lineage into every
    # later test file's EventWriter
    def _purge():
        for var in (TRACE_ENV, TRACE_PARENT_ENV, TRACE_ORIGIN_ENV):
            os.environ.pop(var, None)
    _purge()
    yield
    _purge()


# ============================================================ trace context
def test_mint_child_and_parent_ref_grammar():
    ctx = mint("study", trace_id="trace-abc")
    assert ctx.trace_id == "trace-abc" and ctx.origin == ("study",)
    child = ctx.child("study:s1", origin="sched")
    assert child.trace_id == "trace-abc"
    assert child.parent == "study:s1"
    assert child.origin == ("study", "sched")
    # same entry point does not stutter the chain
    assert child.child("sched:job:j1", origin="sched").origin == \
        ("study", "sched")
    assert parse_parent_ref("sched:unit:job-1/u0s0") == \
        ("sched", "unit:job-1/u0s0")
    assert child_context(None, "study:s1") is None
    generated = mint("study")
    assert generated.trace_id.startswith("trace-")


def test_env_roundtrip_and_ensure_context(monkeypatch):
    assert from_env() is None
    ctx = TraceContext("trace-env", parent="study:s1",
                       origin=("study", "sched"))
    ctx.activate()
    assert from_env() == ctx
    # inheriting entry point extends the origin chain, keeps the id
    inherited = ensure_context("run")
    assert inherited.trace_id == "trace-env"
    assert inherited.origin == ("study", "sched", "run")
    # same trailing origin: unchanged
    assert ensure_context("sched").origin == ("study", "sched")
    # an explicit non-matching --trace-id wins with a fresh root
    explicit = ensure_context("study", trace_id="trace-other")
    assert explicit.trace_id == "trace-other"
    assert explicit.parent is None and explicit.origin == ("study",)
    # a matching --trace-id keeps the inherited lineage
    assert ensure_context("sched", trace_id="trace-env").parent == "study:s1"


def test_event_writer_stamps_ctx_envelope(tmp_path):
    ctx = mint("study", trace_id="trace-ev")
    with EventWriter(str(tmp_path), run_id="r1", ctx=ctx) as w:
        w.emit("metrics", counters={})
        w.link(target="publish:p1", relation="gates")
    events = list(read_events(str(tmp_path)))
    assert events and all(
        e["ctx"]["trace_id"] == "trace-ev" for e in events)
    link = [e for e in events if e["type"] == "link"][0]
    assert link["target"] == "publish:p1"


def test_event_writer_inherits_ctx_from_env(tmp_path, monkeypatch):
    mint("deploy", trace_id="trace-envw").activate()
    with EventWriter(str(tmp_path), run_id="r1") as w:
        w.emit("metrics", counters={})
    (event,) = read_events(str(tmp_path))
    assert event["ctx"]["trace_id"] == "trace-envw"


def test_scheduler_journal_carries_child_ctx(tmp_path):
    from dib_tpu.sched.journal import read_journal
    from dib_tpu.sched.scheduler import JobSpec, Scheduler

    ctx = mint("study", trace_id="trace-sched").child("study:s1",
                                                      origin="study")
    sched = Scheduler(str(tmp_path), ctx=ctx)
    job_id = sched.submit(JobSpec(name="j", betas=(0.1,), seeds=(0,)))
    records, torn = read_journal(str(tmp_path))
    assert torn == 0
    jobs = [r for r in records if r.get("kind") == "job"]
    units = [r for r in records if r.get("kind") == "unit"]
    # the job record carries the CALLER's ctx verbatim...
    assert jobs[0]["ctx"]["parent"] == "study:s1"
    # ...and every unit is a child of its job
    assert units and all(
        u["ctx"]["parent"] == f"sched:job:{job_id}"
        and u["ctx"]["trace_id"] == "trace-sched" for u in units)


# ========================================================== merge ordering
def _write_events(directory, run_id, ts, ctx=None, torn_tail=None):
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, "events.jsonl")
    with open(path, "a") as f:
        for i, t in enumerate(ts):
            record = {"v": 1, "run": run_id, "proc": 0, "seq": i, "t": t,
                      "type": "metrics", "counters": {"i": i}}
            if ctx:
                record["ctx"] = ctx
            f.write(json.dumps(record) + "\n")
        if torn_tail:
            f.write(torn_tail)
    return path


def test_skewed_clocks_and_torn_line_merge_deterministically(tmp_path):
    """Two writers with skewed clocks plus a torn final line in one
    source merge into one deterministic order: (t, source, n) — a skewed
    clock can never reorder one writer against itself, and the torn line
    is held back, counted, and never parsed into garbage."""
    a = tmp_path / "a"
    b = tmp_path / "b"
    # b's clock runs 100 s behind; its records interleave among a's
    _write_events(str(a), "a", [1000.0, 1001.0, 1002.0])
    _write_events(str(b), "b", [900.5, 1000.5, 1001.5],
                  torn_tail='{"v": 1, "run": "b", "t": 99')
    agg = FleetAggregator([str(a), str(b)])
    agg.poll()
    merged = agg.merged()
    order = [(e["source"].split("/")[0], e["n"]) for e in merged]
    assert order == [("b", 0), ("a", 0), ("b", 1), ("a", 1), ("b", 2),
                     ("a", 2)]
    # per-source n is monotone in file order no matter the clock
    assert [n for s, n in order if s == "b"] == [0, 1, 2]
    assert agg.torn == 0  # an INCOMPLETE final line is in-flight, not torn
    digest_once = timeline_digest(agg.entries())
    agg.close()

    # identical digest when the same sources are polled incrementally
    # (batching must not leak into the merged view)
    c = tmp_path / "c"
    d = tmp_path / "d"
    _write_events(str(c), "a", [1000.0, 1001.0])
    _write_events(str(d), "b", [900.5, 1000.5])
    agg2 = FleetAggregator([str(c), str(d)])
    agg2.poll()
    _write_events(str(c), "a", [1002.0])
    _write_events(str(d), "b", [1001.5])
    agg2.poll()
    assert sorted(agg2.merged(), key=merge_key) == \
        [dict(e, source=e["source"]) for e in agg2.merged()]
    agg2.close()
    assert digest_once  # 64-hex canonical digest
    assert len(digest_once) == 64


def test_merged_view_is_stable_under_arrival_order(tmp_path):
    a, b = tmp_path / "a", tmp_path / "b"
    _write_events(str(a), "a", [10.0, 20.0])
    _write_events(str(b), "b", [15.0])
    one = FleetAggregator([str(a), str(b)])
    one.poll()
    all_at_once = timeline_digest(one.entries())
    one.close()

    # second fleet: b exists from the start but a arrives later
    c, d = tmp_path / "c", tmp_path / "d"
    _write_events(str(d), "b", [15.0])
    os.makedirs(c, exist_ok=True)
    two = FleetAggregator([str(c), str(d)])
    two.poll()
    _write_events(str(c), "a", [10.0, 20.0])
    two.poll()
    incremental = timeline_digest(two.entries())
    two.close()
    # source ids differ (c/d vs a/b) so raw digests differ — compare the
    # RECORDS in merged order instead
    assert [e["record"] for e in sorted(one.merged(), key=merge_key)] == \
        [e["record"] for e in sorted(two.merged(), key=merge_key)]
    assert all_at_once and incremental


# ============================================================= kill/resume
def test_durable_resume_zero_dup_zero_lost(tmp_path):
    """The durable timeline IS the resume cursor: an aggregator that
    dies mid-merge (simulated by abandoning it between polls) re-attaches
    with zero duplicate and zero lost entries and a bit-identical merged
    digest vs an uninterrupted merge."""
    src = tmp_path / "src"
    out = tmp_path / "out"
    baseline_out = tmp_path / "baseline"
    _write_events(str(src), "w", [float(i) for i in range(50)])

    first = FleetAggregator([str(src)], out_dir=str(out))
    first.poll()
    # the writer keeps writing while the (killed) aggregator is away
    first.close()
    _write_events(str(src), "w", [float(50 + i) for i in range(30)])

    resumed = FleetAggregator([str(src)], out_dir=str(out))
    resumed.poll()
    entries = resumed.entries()
    keys = [(e["source"], e["n"]) for e in entries]
    assert len(keys) == len(set(keys)) == 80          # zero duplicates
    assert [e["record"]["t"] for e in sorted(entries, key=merge_key)] \
        == [float(i) for i in range(80)]               # zero lost
    resumed_digest = timeline_digest(entries)
    resumed.close()

    baseline = FleetAggregator([str(src)], out_dir=str(baseline_out))
    baseline.poll()
    assert timeline_digest(baseline.entries()) == resumed_digest
    baseline.close()

    # a third attach with nothing new appends nothing
    again = FleetAggregator([str(src)], out_dir=str(out))
    assert again.poll() == []
    assert timeline_digest(again.entries()) == resumed_digest
    again.close()


def test_resume_seals_torn_timeline_line(tmp_path):
    src = tmp_path / "src"
    out = tmp_path / "out"
    _write_events(str(src), "w", [1.0, 2.0])
    agg = FleetAggregator([str(src)], out_dir=str(out))
    agg.poll()
    agg.close()
    # the aggregator was killed mid-append: tear the final durable line
    timeline = os.path.join(str(out), "timeline.jsonl")
    with open(timeline, "rb+") as f:
        f.seek(0, os.SEEK_END)
        f.truncate(f.tell() - 10)
    resumed = FleetAggregator([str(src)], out_dir=str(out))
    resumed.poll()
    # the torn entry was not replayed, so its record re-appends whole
    assert [e["record"]["seq"] for e in sorted(resumed.entries(),
                                               key=merge_key)] == [0, 1]
    resumed.close()


# ================================================================= orphans
def _ctx(trace_id, parent=None, origin=("study",)):
    out = {"trace_id": trace_id, "origin": list(origin)}
    if parent:
        out["parent"] = parent
    return out


def test_orphans_surfaced_not_dropped(tmp_path):
    run = tmp_path / "run"
    _write_events(str(run), "r1",
                  [1.0], ctx=_ctx("trace-x", parent="study:ghost"))
    agg = FleetAggregator([str(run)])
    agg.poll()
    analysis = agg.analyze()
    assert len(analysis["orphans"]) == 1
    orphan = analysis["orphans"][0]
    assert orphan["parent"] == "study:ghost"
    assert analysis["traces"][0]["orphans"] == 1
    summary = agg.summary()
    assert summary["orphan_events"] == 1
    assert summary["metric"] == "fleet_trace"
    agg.close()


def test_run_parent_resolves_against_run_records(tmp_path):
    run = tmp_path / "run"
    _write_events(str(run), "r1", [1.0],
                  ctx=_ctx("trace-y", parent="run:r1"))
    agg = FleetAggregator([str(run)])
    agg.poll()
    assert agg.summary()["orphan_events"] == 0
    agg.close()


def test_fleet_summarize_cli_exit_codes(tmp_path, capsys):
    clean = tmp_path / "clean"
    _write_events(str(clean), "r1", [1.0], ctx=_ctx("trace-z",
                                                    parent="run:r1"))
    assert telemetry_main(["fleet", "summarize", str(clean)]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["value"] == 1 and summary["orphan_events"] == 0

    orphaned = tmp_path / "orphaned"
    _write_events(str(orphaned), "r2", [1.0],
                  ctx=_ctx("trace-w", parent="study:ghost"))
    assert fleet_main(["summarize", str(orphaned)]) == 1
    captured = capsys.readouterr()
    assert "ORPHAN" in captured.err and "study:ghost" in captured.err


def test_fleet_report_and_prometheus(tmp_path, capsys):
    run = tmp_path / "run"
    _write_events(str(run), "r1", [1.0], ctx=_ctx("trace-r",
                                                  parent="run:r1"))
    with open(os.path.join(str(run), "events.jsonl"), "a") as f:
        f.write(json.dumps({
            "v": 1, "run": "r1", "proc": 0, "seq": 9, "t": 2.0,
            "type": "metrics",
            "snapshots": [{"counters.requests": 7,
                           "gauges.inflight": 2.0}]}) + "\n")
    out = tmp_path / "fleet.html"
    write_fleet_report([str(run)], str(out))
    html = out.read_text()
    assert "trace-r" in html and "run" in html

    agg = FleetAggregator([str(run)])
    agg.poll()
    text = fleet_prometheus(agg)
    agg.close()
    assert "dib_fleet_sources" in text
    assert "dib_fleet_orphan_events 0" in text
    assert "dib_requests 7" in text
    assert "dib_inflight 2" in text


def test_fleet_prometheus_merges_native_buckets_by_addition(tmp_path):
    """Two workers' fixed-bound ``le_*`` bucket counts sum into ONE
    fleet ``_bucket`` series (exact — same BUCKET_BOUNDS everywhere);
    windowed percentiles are dropped, the buckets carry the quantiles."""
    for worker, (count, total) in enumerate([(3, 0.03), (5, 0.05)]):
        run = tmp_path / f"w{worker}"
        _write_events(str(run), f"rw{worker}", [1.0],
                      ctx=_ctx(f"trace-{worker}",
                               parent=f"run:rw{worker}"))
        with open(os.path.join(str(run), "events.jsonl"), "a") as f:
            f.write(json.dumps({
                "v": 1, "run": f"rw{worker}", "proc": 0, "seq": 9,
                "t": 2.0, "type": "metrics", "snapshots": [{
                    "histograms.serve.request_latency_s.count": count,
                    "histograms.serve.request_latency_s.sum": total,
                    "histograms.serve.request_latency_s.le_032": count,
                    "histograms.serve.request_latency_s.p99": 0.01,
                }]}) + "\n")
    agg = FleetAggregator([str(tmp_path / "w0"), str(tmp_path / "w1")])
    agg.poll()
    text = fleet_prometheus(agg)
    agg.close()
    assert 'dib_serve_request_latency_s_hist_bucket{le="+Inf"} 8' in text
    assert "dib_serve_request_latency_s_hist_count 8" in text
    # the merged finite bucket holds both workers' counts
    bucket_lines = [l for l in text.splitlines()
                    if "_hist_bucket" in l and "+Inf" not in l]
    assert any(l.endswith(" 8") for l in bucket_lines), bucket_lines
    # per-worker windowed percentiles never merge — they are dropped
    assert "quantile" not in text


# ============================================================== burn rates
def _entries(rows):
    return [{"plane": p, "t": t, "record": r, "source": "s", "n": i}
            for i, (p, t, r) in enumerate(rows)]


def test_burn_rate_fires_only_when_both_windows_burn():
    from dib_tpu.telemetry.slo import evaluate_burn_rates

    rule = {"name": "b", "bad": {"type": "alert"}, "total": {},
            "budget": 0.1, "fast_window_s": 10.0, "slow_window_s": 100.0,
            "threshold": 2.0, "severity": "page"}
    # cliff in the fast window AND sustained in the slow window: fires
    rows = [("run", 100.0 - i, {"type": "alert" if i % 4 == 0 else "m"})
            for i in range(100)]
    (row,) = evaluate_burn_rates([rule], _entries(rows), now=100.0)
    assert row["status"] == "firing"
    assert row["burn_fast"] >= 2.0 and row["burn_slow"] >= 2.0

    # a brief blip: fast window burns, slow window does not → ok
    rows = ([("run", 99.0 - 0.1 * k, {"type": "alert"}) for k in range(4)]
            + [("run", 100.0 - i, {"type": "m"}) for i in range(100)])
    (row,) = evaluate_burn_rates([rule], _entries(rows), now=100.0)
    assert row["status"] == "ok"
    assert row["burn_fast"] > 2.0 > row["burn_slow"]

    # no traffic in the slow window: skipped, never fired
    (row,) = evaluate_burn_rates([rule], [], now=100.0)
    assert row["status"] == "skipped"


def test_burn_alerts_land_on_originating_run_stream(tmp_path):
    """`fleet tail --slo` semantics in-process: a firing burn rule lands
    ONE durable alert event on the originating run's own stream — where
    the existing check/compare gates already look — idempotently."""
    from dib_tpu.telemetry.fleet import _BurnAlerter
    from dib_tpu.telemetry.slo import evaluate_burn_rates

    run = tmp_path / "run"
    ts = [float(i) for i in range(20)]
    _write_events(str(run), "r1", ts)
    with open(os.path.join(str(run), "events.jsonl"), "a") as f:
        for t in (18.5, 19.5):
            f.write(json.dumps({"v": 1, "run": "r1", "proc": 0, "seq": 99,
                                "t": t, "type": "alert",
                                "rule": "preexisting"}) + "\n")
    agg = FleetAggregator([str(run)])
    agg.poll()
    rule = {"name": "fleet_alert_burn", "bad": {"type": "alert"},
            "total": {"plane": "run"}, "budget": 0.01,
            "fast_window_s": 5.0, "slow_window_s": 50.0,
            "threshold": 2.0, "severity": "page"}
    rows = evaluate_burn_rates([rule], agg.entries(), now=19.5)
    assert rows[0]["status"] == "firing"
    alerter = _BurnAlerter(agg)
    alerter.land({rule["name"]: rule}, rows, now=19.5)
    alerter.land({rule["name"]: rule}, rows, now=19.5)  # idempotent
    alerter.close()
    agg.close()
    alerts = [e for e in read_events(str(run))
              if e["type"] == "alert" and e.get("rule") == rule["name"]]
    assert len(alerts) == 1
    assert alerts[0]["source"] == "fleet"
    assert alerts[0]["burn_fast"] >= 2.0
    assert alerts[0]["windows_s"] == [5.0, 50.0]
    assert alerter.written == [{"rule": "fleet_alert_burn",
                                "dir": str(run)}]


def test_fleet_tail_cli_once_with_slo(tmp_path, capsys):
    run = tmp_path / "run"
    _write_events(str(run), "r1", [1.0, 2.0])
    slo = tmp_path / "slo.json"
    slo.write_text(json.dumps({
        "rules": [{"name": "r", "metric": "m", "min": 0.0}],
        "burn_rates": [{"name": "quiet", "bad": {"type": "alert"},
                        "budget": 0.5, "fast_window_s": 1.0,
                        "slow_window_s": 10.0, "threshold": 2.0}],
    }))
    rc = fleet_main(["tail", str(run), "--out", str(tmp_path / "out"),
                     "--slo", str(slo), "--once"])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.splitlines()[0])
    assert line["entries"] == 2 and line["firing"] == []
    assert os.path.exists(tmp_path / "out" / "timeline.jsonl")


# ============================================================== discovery
def test_discover_sources_labels_and_planes(tmp_path):
    root = tmp_path / "root"
    _write_events(str(root / "runA"), "a", [1.0])
    os.makedirs(root / "study")
    for name in ("journal.jsonl", "study.jsonl", "publishes.jsonl"):
        with open(root / "study" / name, "w") as f:
            f.write(json.dumps({"v": 1, "t": 1.0, "kind": "x"}) + "\n")
    sources = discover_sources([str(root)])
    by_plane = {s["plane"] for s in sources}
    assert by_plane == {"run", "sched", "study", "stream"}
    assert all(s["source"].startswith("root/") for s in sources)
