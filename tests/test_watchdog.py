"""Fault-injection tests for the stall watchdog (dib_tpu/train/watchdog.py).

VERDICT round-4 item 1: the tunneled v5e shows discrete ~280 s device
stalls; the framework must detect a wedged chunk and re-dispatch from the
last checkpoint WITHOUT human intervention. These tests inject the fault:

  - supervisor-level: scripted workers that stall (stop heartbeating) or
    crash; ``supervise`` must kill/restart them and record each mitigation;
  - end-to-end: a real ``BetaSweepTrainer`` worker whose hook sleeps
    mid-run on its FIRST launch only — the supervised result must be
    bit-identical to an uninterrupted run (the ``DIBCheckpointer``
    chunk-size contract carried through a SIGKILL).
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from dib_tpu.train.watchdog import HeartbeatHook, WatchdogConfig, supervise

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _worker_env():
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "DIB_COMPILE_CACHE": "",
        "JAX_COMPILATION_CACHE_DIR": "/root/.cache/jax_comp_cache_cpu",
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0.2",
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "0",
    })
    return env


# ------------------------------------------------------- supervisor logic
def _scripted_worker(tmp_path, body: str) -> list:
    path = tmp_path / "worker.py"
    path.write_text(textwrap.dedent(body))
    return [sys.executable, str(path)]


def test_supervise_kills_stalled_worker_and_relaunches(tmp_path):
    hb = str(tmp_path / "hb.json")
    marker = str(tmp_path / "stalled_once")
    # first launch: beat twice, then wedge (no further beats); relaunch:
    # beat to completion
    cmd = _scripted_worker(tmp_path, f"""
        import json, os, time
        hb, marker = {hb!r}, {marker!r}
        def beat(n, t0):
            payload = {{"pid": os.getpid(), "epoch": n, "beat": n,
                        "time": time.time(),
                        "intervals_s": [0.2] * n}}
            with open(hb + ".tmp", "w") as f:
                json.dump(payload, f)
            os.replace(hb + ".tmp", hb)
        t0 = time.time()
        for n in range(1, 3):
            time.sleep(0.2); beat(n, t0)
        if not os.path.exists(marker):
            open(marker, "w").close()
            time.sleep(600)          # the injected stall
        for n in range(3, 6):
            time.sleep(0.2); beat(n, t0)
    """)
    t0 = time.time()
    result = supervise(
        cmd, hb,
        WatchdogConfig(first_beat_timeout_s=60.0, floor_s=1.0, k=3.0,
                       poll_s=0.1, max_restarts=2),
    )
    assert result["returncode"] == 0
    assert result["launches"] == 2
    kinds = [m["type"] for m in result["mitigations"]]
    assert kinds == ["stall_kill"]
    assert result["mitigations"][0]["beats"] == 2
    # detection must be prompt: the 600 s sleep must NOT be waited out
    assert time.time() - t0 < 60


def test_supervise_restarts_crashed_worker(tmp_path):
    hb = str(tmp_path / "hb.json")
    marker = str(tmp_path / "crashed_once")
    cmd = _scripted_worker(tmp_path, f"""
        import os, sys
        marker = {marker!r}
        if not os.path.exists(marker):
            open(marker, "w").close()
            sys.exit(3)              # simulated tunnel crash
        sys.exit(0)
    """)
    result = supervise(cmd, hb, WatchdogConfig(poll_s=0.05, max_restarts=2))
    assert result["returncode"] == 0
    assert [m["type"] for m in result["mitigations"]] == ["crash_restart"]
    assert result["mitigations"][0]["returncode"] == 3


def test_supervise_gives_up_after_max_restarts(tmp_path):
    hb = str(tmp_path / "hb.json")
    cmd = _scripted_worker(tmp_path, "import sys; sys.exit(7)")
    result = supervise(cmd, hb, WatchdogConfig(poll_s=0.05, max_restarts=1))
    assert result["returncode"] == 7
    assert "error" in result
    assert result["launches"] == 2


def test_heartbeat_hook_writes_atomic_beats(tmp_path):
    hb = str(tmp_path / "hb.json")
    hook = HeartbeatHook(hb)

    class S:
        params = {"w": np.zeros(3)}

    hook(None, S(), 2)
    time.sleep(0.05)
    hook(None, S(), 4)
    with open(hb) as f:
        beat = json.load(f)
    assert beat["beat"] == 2 and beat["epoch"] == 4
    assert len(beat["intervals_s"]) == 2
    assert beat["intervals_s"][1] >= 0.05


# ------------------------------------------- end-to-end: bit-identical
_TRAIN_WORKER = """
    import os, sys, time
    sys.path.insert(0, {repo!r})
    import jax, numpy as np
    from dib_tpu.data import get_dataset
    from dib_tpu.models import DistributedIBModel
    from dib_tpu.parallel import BetaSweepTrainer
    from dib_tpu.train import TrainConfig
    from dib_tpu.train.checkpoint import CheckpointHook, DIBCheckpointer
    from dib_tpu.train.watchdog import HeartbeatHook

    outdir, stall_epoch, stall_s = sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
    marker = os.path.join(outdir, "stalled_once")
    bundle = get_dataset("boolean_circuit", number_inputs=6, seed=1)
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(16,), integration_hidden=(32,),
        output_dim=bundle.output_dimensionality, embedding_dim=4,
        output_activation=bundle.output_activation,
    )
    cfg = TrainConfig(batch_size=64, beta_start=1e-3, beta_end=1.0,
                      num_pretraining_epochs=2, num_annealing_epochs=6,
                      steps_per_epoch=2, max_val_points=128)
    sweep = BetaSweepTrainer(model, bundle, cfg, 1e-3, [0.1, 1.0])
    keys = jax.random.split(jax.random.key(5), 2)
    ckpt = DIBCheckpointer(os.path.join(outdir, "ckpt"))

    def stall(trainer, states, epoch):
        if epoch == stall_epoch and not os.path.exists(marker):
            open(marker, "w").close()
            time.sleep(stall_s)      # wedged device, as seen from the host

    hooks = [HeartbeatHook(os.path.join(outdir, "hb.json")), stall,
             CheckpointHook(ckpt)]
    total, chunk = 8, 2
    states = histories = None
    remaining = None
    if ckpt.latest_step is not None:
        states, histories, keys = ckpt.restore(sweep, chunk_size=chunk)
        remaining = total - int(np.max(jax.device_get(states.epoch)))
    final, records = sweep.fit(
        keys, num_epochs=remaining if remaining is not None else total,
        hooks=hooks, hook_every=chunk, states=states, histories=histories,
    )
    ckpt.close()
    out = {{}}
    for r, rec in enumerate(records):
        out[f"kl_{{r}}"] = np.asarray(rec.kl_per_feature)
        out[f"loss_{{r}}"] = np.asarray(rec.loss)
        out[f"val_loss_{{r}}"] = np.asarray(rec.val_loss)
    np.savez(os.path.join(outdir, "hist.npz"), **out)
"""


@pytest.mark.slow
def test_supervised_stall_recovery_is_bit_identical(tmp_path):
    worker = tmp_path / "train_worker.py"
    worker.write_text(textwrap.dedent(_TRAIN_WORKER.format(repo=REPO)))
    env = _worker_env()

    # uninterrupted baseline (stall_epoch = -1 never fires)
    base_dir = tmp_path / "base"
    base_dir.mkdir()
    subprocess.run(
        [sys.executable, str(worker), str(base_dir), "-1", "0"],
        env=env, check=True, timeout=600,
    )

    # victim: hook wedges for 300 s at epoch 6 on the first launch only;
    # the supervisor must SIGKILL it and the relaunch must resume from the
    # epoch-4 checkpoint (epoch-6's save runs after the stalling hook)
    vic_dir = tmp_path / "victim"
    vic_dir.mkdir()
    hb = str(vic_dir / "hb.json")
    t0 = time.time()
    result = supervise(
        [sys.executable, str(worker), str(vic_dir), "6", "300"],
        hb,
        WatchdogConfig(first_beat_timeout_s=300.0, floor_s=8.0, k=3.0,
                       poll_s=0.25, max_restarts=2),
        env=env,
    )
    wall = time.time() - t0
    assert result["returncode"] == 0, result
    assert [m["type"] for m in result["mitigations"]] == ["stall_kill"], result
    assert result["launches"] == 2
    assert wall < 300, "the 300 s injected stall must not be waited out"
    assert os.path.exists(vic_dir / "stalled_once")

    a = np.load(base_dir / "hist.npz")
    b = np.load(vic_dir / "hist.npz")
    assert sorted(a.files) == sorted(b.files)
    for k in a.files:
        np.testing.assert_array_equal(
            a[k], b[k],
            err_msg=f"{k}: supervised kill+resume diverged from baseline",
        )


@pytest.mark.slow
def test_cli_watchdog_supervised_run(tmp_path):
    """`python -m dib_tpu.cli train --watchdog`: the CLI re-execs itself as
    a heartbeating, checkpointing worker under supervise() and reports the
    watchdog result. (Stall/crash mitigation logic is covered by the unit
    tests above; this pins the CLI wiring end to end.)"""
    outdir = tmp_path / "art"
    cmd = [
        sys.executable, "-m", "dib_tpu.cli", "train",
        "--watchdog",
        "--dataset", "boolean_circuit",
        "--artifact_outdir", str(outdir),
        "--number_pretraining_epochs", "5",
        "--number_annealing_epochs", "10",
        "--batch_size", "64",
        "--feature_encoder_architecture", "16",
        "--integration_network_architecture", "32",
        "--feature_embedding_dimension", "4",
        "--max_val_points", "256",
        "--checkpoint_frequency", "5",
        "--watchdog_first_timeout_s", "420",
    ]
    proc = subprocess.run(cmd, env=_worker_env(), capture_output=True,
                          text=True, timeout=540, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])["watchdog"]
    assert result["returncode"] == 0
    assert result["launches"] == 1 and result["mitigations"] == []
    assert os.path.exists(outdir / "history.npz")
    assert os.path.exists(outdir / "heartbeat.json")
    with open(outdir / "heartbeat.json") as f:
        beat = json.load(f)
    assert beat["beat"] >= 3          # one per 5-epoch chunk over 15 epochs


@pytest.mark.fault
def test_crash_loop_backoff_spaces_relaunches(tmp_path):
    """A worker dying instantly on every launch must not burn max_restarts
    in milliseconds: with restart_backoff_s the supervisor sleeps
    (linearly growing) between quick deaths, buying wall-clock for a
    transient cause to clear."""
    hb = str(tmp_path / "hb.json")
    cmd = _scripted_worker(tmp_path, "import sys; sys.exit(5)")
    t0 = time.time()
    result = supervise(
        cmd, hb,
        WatchdogConfig(poll_s=0.05, max_restarts=2,
                       restart_backoff_s=0.4, min_uptime_s=10.0),
    )
    elapsed = time.time() - t0
    assert result["returncode"] == 5 and result["launches"] == 3
    # two backoffs: 0.4s after launch 1, 0.8s after launch 2
    assert elapsed >= 1.2, f"backoff not applied (elapsed {elapsed:.2f}s)"


def test_supervisor_termination_kills_worker(tmp_path):
    """SIGTERM to the supervisor must take the worker down with it —
    otherwise a timed-out supervisor leaves an orphan training against the
    same checkpoint dir as its replacement."""
    hb = str(tmp_path / "hb.json")
    pidfile = str(tmp_path / "worker.pid")
    worker_body = f"""
        import os, time
        with open({pidfile!r}, "w") as f:
            f.write(str(os.getpid()))
        time.sleep(600)
    """
    sup_body = f"""
        import sys
        sys.path.insert(0, {REPO!r})
        from dib_tpu.train.watchdog import WatchdogConfig, supervise
        supervise([sys.executable, {str(tmp_path / 'inner.py')!r}], {hb!r},
                  WatchdogConfig(first_beat_timeout_s=500, poll_s=0.1))
    """
    (tmp_path / "inner.py").write_text(textwrap.dedent(worker_body))
    (tmp_path / "sup.py").write_text(textwrap.dedent(sup_body))
    sup = subprocess.Popen([sys.executable, str(tmp_path / "sup.py")])
    worker_pid = None
    try:
        deadline = time.time() + 30
        while not os.path.exists(pidfile) and time.time() < deadline:
            time.sleep(0.1)
        assert os.path.exists(pidfile), "worker never started"
        worker_pid = int(open(pidfile).read())
        sup.terminate()                       # what `timeout` sends
        assert sup.wait(timeout=15) != 0
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                os.kill(worker_pid, 0)        # still alive?
            except ProcessLookupError:
                break
            time.sleep(0.2)
        else:
            pytest.fail("worker survived the supervisor's SIGTERM")
    finally:
        # never leak the supervisor or its sleeping worker into the session
        if sup.poll() is None:
            sup.kill()
            sup.wait()
        if worker_pid is not None:
            try:
                os.kill(worker_pid, 9)
            except ProcessLookupError:
                pass


# ------------------------------------------- event-stream liveness probe
def test_supervise_consumes_heartbeat_events(tmp_path):
    """Where a telemetry dir is configured, liveness comes from the
    stream's heartbeat events: a worker that stops emitting boundary
    beats is stall-killed (with worker_alive_s forensics — its mid-chunk
    beats kept landing), and the relaunch runs to completion. The
    side-channel heartbeat FILE never exists in this drill."""
    events_dir = str(tmp_path / "run")
    hb = str(tmp_path / "hb.json")   # passed but never written
    marker = str(tmp_path / "stalled_once")
    cmd = _scripted_worker(tmp_path, f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        from dib_tpu.telemetry.events import EventWriter
        marker = {marker!r}
        w = EventWriter({events_dir!r}, run_id="drill")
        for n in range(1, 3):
            time.sleep(0.2)
            w.heartbeat(beat=n, epoch=n, phase="boundary",
                        intervals_s=[0.2] * n)
        if not os.path.exists(marker):
            open(marker, "w").close()
            # device "stalls": boundary progress stops, but the process
            # stays alive and keeps emitting mid-chunk beats
            for n in range(3, 2000):
                time.sleep(0.1)
                w.heartbeat(beat=n, epoch=2, phase="chunk",
                            interval_s=0.1, phase_elapsed_s=n * 0.1)
        for n in range(3, 6):
            time.sleep(0.2)
            w.heartbeat(beat=n, epoch=n, phase="boundary",
                        intervals_s=[0.2] * 3)
    """)
    t0 = time.time()
    result = supervise(
        cmd, hb,
        WatchdogConfig(first_beat_timeout_s=60.0, floor_s=1.0, k=3.0,
                       poll_s=0.1, max_restarts=2),
        env=_worker_env(),
        events_path=os.path.join(events_dir, "events.jsonl"),
    )
    assert result["returncode"] == 0
    assert result["launches"] == 2
    (kill,) = [m for m in result["mitigations"]
               if m["type"] == "stall_kill"]
    assert kill["beats"] == 2 and kill["epoch"] == 2
    # the process-vs-device distinction: mid-chunk beats kept landing
    assert kill["worker_alive_s"] < 2.0
    assert not os.path.exists(hb)    # file probe never involved
    assert time.time() - t0 < 60


def test_events_beats_reader_filters_stale_launches(tmp_path):
    """A relaunch must not credit the killed worker's final beats: only
    beats stamped after the launch count (the stream-probe equivalent of
    the file probe's stale-beat unlink)."""
    from dib_tpu.train.watchdog import _EventStreamBeats

    from dib_tpu.telemetry.events import EventWriter

    with EventWriter(str(tmp_path), run_id="r") as w:
        old = w.heartbeat(beat=1, epoch=5, phase="boundary",
                          intervals_s=[0.2])
    reader = _EventStreamBeats(os.path.join(str(tmp_path), "events.jsonl"))
    assert reader.read(min_t=0.0)["epoch"] == 5
    reader.reset()
    launched = old["t"] + 0.05        # "relaunch" strictly after the beat
    assert reader.read(min_t=launched) is None
    time.sleep(0.1)                   # the fresh worker's beat is newer
    with EventWriter(str(tmp_path), run_id="r") as w:
        w.heartbeat(beat=1, epoch=7, phase="boundary",
                    intervals_s=[0.3])
    assert reader.read(min_t=launched)["epoch"] == 7
