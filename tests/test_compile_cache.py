"""Persistent-compile-cache helper contract (round 4, VERDICT item 4b)."""

import os

import jax
import pytest

from dib_tpu.utils.compile_cache import enable_persistent_cache


_TOUCHED_KEYS = (
    # every config key enable_persistent_cache mutates — all must be
    # restored or they leak into the rest of the pytest session
    "jax_compilation_cache_dir",
    "jax_persistent_cache_min_entry_size_bytes",
    "jax_persistent_cache_min_compile_time_secs",
)


@pytest.fixture
def restore_cache_config():
    before = {k: getattr(jax.config, k) for k in _TOUCHED_KEYS}
    yield
    for k, v in before.items():
        jax.config.update(k, v)


def test_disabled_by_empty_env(monkeypatch, restore_cache_config):
    monkeypatch.setenv("DIB_COMPILE_CACHE", "")
    assert enable_persistent_cache() == "off"


def test_explicit_empty_path_is_off(restore_cache_config):
    assert enable_persistent_cache("") == "off"


def test_cold_then_warm(tmp_path, restore_cache_config):
    target = tmp_path / "cache"
    # nonexistent dir: enabled but cold
    assert enable_persistent_cache(str(target)) == "cold-populating"
    assert jax.config.jax_compilation_cache_dir == str(target)
    # dir with an entry: warm
    target.mkdir()
    (target / "entry").write_bytes(b"x")
    assert enable_persistent_cache(str(target)) == "warm"


def test_env_default_expands_user(monkeypatch, tmp_path, restore_cache_config):
    monkeypatch.setenv("HOME", str(tmp_path))
    monkeypatch.setenv("DIB_COMPILE_CACHE", "~/jcache")
    assert enable_persistent_cache() == "cold-populating"
    assert jax.config.jax_compilation_cache_dir == os.path.join(
        str(tmp_path), "jcache"
    )
