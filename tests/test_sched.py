"""Scheduler-layer unit tests (dib_tpu/sched): journal durability, lease
semantics, work-stealing, retry budgets, pool degradation, CLI surface,
telemetry rollups, and the SLO scheduler budgets.

Everything here is host-side and fast: training-free fake runners, an
injectable clock for lease expiry, and torn-journal bytes written by
hand. The real-training end-to-end paths (bit-identical resume under
chaos) live in tests/test_sched_chaos.py.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from dib_tpu.sched import (  # noqa: E402
    JOURNAL_FILENAME,
    JobJournal,
    JobSpec,
    Scheduler,
    WorkerKilled,
    WorkerPool,
    dense_beta_grid,
    read_journal,
    refine_beta_grid,
)
from dib_tpu.sched.cli import sched_main  # noqa: E402
from dib_tpu.telemetry import EventWriter  # noqa: E402
from dib_tpu.telemetry.events import read_events  # noqa: E402
from dib_tpu.telemetry.summary import scheduler_rollup, summarize  # noqa: E402


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _sched(tmp_path, name="s", telemetry=None, clock=None, **kwargs):
    return Scheduler(str(tmp_path / name), telemetry=telemetry,
                     clock=clock or time.time, **kwargs)


# ------------------------------------------------------------------ grids
def test_dense_beta_grid_log_spaced():
    grid = dense_beta_grid(1e-2, 1.0, 3)
    assert grid == pytest.approx([0.01, 0.1, 1.0])
    assert dense_beta_grid(0.5, 0.5, 1) == [0.5]
    with pytest.raises(ValueError):
        dense_beta_grid(1.0, 0.1, 4)


def test_refine_beta_grid_brackets_centers():
    grid = refine_beta_grid([0.1], num=4, span_decades=0.25)
    assert len(grid) == 4
    assert min(grid) < 0.1 < max(grid)
    assert grid == sorted(grid)
    with pytest.raises(ValueError):
        refine_beta_grid([0.0])


# ---------------------------------------------------------------- journal
def test_journal_round_trip_and_torn_final_line(tmp_path):
    journal = JobJournal(str(tmp_path))
    journal.append("job", job_id="j1", spec={"betas": [0.1]})
    journal.append("unit", unit_id="u1", job_id="j1", beta=0.1, seed=0)
    journal.close()
    # a writer SIGKILLed mid-append leaves half a line, no newline
    with open(journal.path, "ab") as f:
        f.write(b'{"v": 1, "kind": "lease", "unit')
    records, torn = read_journal(str(tmp_path))
    assert [r["kind"] for r in records] == ["job", "unit"]
    assert torn == 1


def test_journal_seals_torn_line_before_appending(tmp_path):
    """A fresh journal on a torn file must seal the torn bytes with a
    newline, or its own first append would glue onto them and be lost to
    every future replay."""
    j1 = JobJournal(str(tmp_path))
    j1.append("job", job_id="j1", spec={})
    j1.close()
    with open(j1.path, "ab") as f:
        f.write(b'{"kind": "torn')
    j2 = JobJournal(str(tmp_path))
    j2.append("unit", unit_id="u1", job_id="j1", beta=0.1, seed=0)
    j2.close()
    records, torn = read_journal(str(tmp_path))
    assert torn == 1
    assert [r["kind"] for r in records] == ["job", "unit"]


# ------------------------------------------------------------- scheduler
def test_submit_decomposes_grid_times_seeds(tmp_path):
    s = _sched(tmp_path)
    job = s.submit(JobSpec(betas=(0.1, 1.0), seeds=(0, 1)))
    st = s.status()
    assert st["counts"] == {"pending": 4, "leased": 0, "done": 0,
                            "failed": 0}
    assert st["jobs"][job]["units"] == 4
    betas = {(row["beta"], row["seed"]) for row in st["units"]}
    assert betas == {(0.1, 0), (0.1, 1), (1.0, 0), (1.0, 1)}
    s.close()


def test_acquire_fifo_lease_complete_drains(tmp_path):
    s = _sched(tmp_path)
    s.submit(JobSpec(betas=(0.1, 1.0)))
    l1 = s.acquire("w0")
    l2 = s.acquire("w0")
    assert l1.unit_id.endswith("u000s0") and l2.unit_id.endswith("u001s0")
    assert s.acquire("w0") is None
    assert s.renew(l1) is True
    assert s.complete(l1, {"ok": 1}) is True
    assert s.complete(l2) is True
    assert s.drained()
    s.close()


def test_double_lease_prevention_after_forced_expiry(tmp_path):
    """A presumed-dead worker that returns must not double-execute: its
    superseded lease's renewal AND completion are rejected."""
    s = _sched(tmp_path)
    s.submit(JobSpec(betas=(0.5,)))
    stale = s.acquire("w0")
    assert s.force_expire(stale.unit_id, "test") is True
    thief = s.acquire("w1")
    assert thief is not None and thief.lease_id != stale.lease_id
    assert s.renew(stale) is False
    assert s.complete(stale, {"stale": True}) is False
    assert s.fail(stale, "stale failure") is False
    assert s.complete(thief, {"thief": True}) is True
    # the journal holds exactly one done for the unit
    records, _ = read_journal(s.directory)
    dones = [r for r in records if r["kind"] == "done"]
    assert len(dones) == 1 and dones[0]["result"] == {"thief": True}
    s.close()


def test_retry_backoff_and_budget_exhaustion_marks_job_failed(tmp_path):
    clock = Clock()
    s = _sched(tmp_path, clock=clock, backoff_base_s=2.0)
    job = s.submit(JobSpec(betas=(0.5,), retry_budget=1))
    lease = s.acquire("w0")
    assert s.fail(lease, "boom") == "requeued"
    # exponential backoff holds the unit until not_before passes
    assert s.acquire("w0") is None
    clock.t += 100.0
    lease = s.acquire("w0")
    assert lease is not None and lease.attempt == 2
    assert s.fail(lease, "boom again") == "exhausted"
    st = s.status()
    assert st["jobs"][job]["status"] == "failed"
    assert st["counts"]["failed"] == 1
    # the final, non-requeued failure is the budget being ENFORCED, not a
    # retry: the spend must read budget, not budget+1 (the SLO
    # sched_retry_ceiling would otherwise page on correct fail-fast)
    assert st["jobs"][job]["retries_used"] == 1
    # not retried forever: nothing left to acquire, ever
    clock.t += 10_000.0
    assert s.acquire("w0") is None
    assert s.drained()
    s.close()


def test_release_requeues_budget_free(tmp_path):
    clock = Clock()
    s = _sched(tmp_path, clock=clock)
    job = s.submit(JobSpec(betas=(0.5,), retry_budget=0))
    lease = s.acquire("w0")
    assert s.release(lease, reason="preempt") is True
    # immediately acquirable (no backoff), no retry burned even with a
    # zero budget — the exit-75 contract at the scheduling layer
    lease2 = s.acquire("w0")
    assert lease2 is not None
    assert s.status()["jobs"][job]["retries_used"] == 0
    s.complete(lease2)
    s.close()


def test_wall_clock_reap_steals_expired_lease(tmp_path):
    clock = Clock()
    s = _sched(tmp_path, clock=clock, lease_s=10.0)
    s.submit(JobSpec(betas=(0.5,)))
    lease = s.acquire("w0")
    assert s.reap() == []
    clock.t += 11.0
    assert s.reap() == [lease.unit_id]
    thief = s.acquire("w1")
    assert thief is not None
    # renewal keeps a live lease out of the reaper's hands
    s.renew(thief)
    clock.t += 5.0
    assert s.reap() == []
    s.complete(thief)
    s.close()


# --------------------------------------------------------- crash recovery
def test_scheduler_restart_replays_exact_queue(tmp_path):
    s = _sched(tmp_path)
    s.submit(JobSpec(betas=(0.1, 1.0)))
    lease = s.acquire("w0")
    s.complete(lease)
    s.acquire("w1")      # left in flight at "crash" time
    s.close()
    s2 = _sched(tmp_path)
    st = s2.status()
    assert st["counts"] == {"pending": 0, "leased": 1, "done": 1,
                            "failed": 0}
    assert s2.replayed_torn == 0
    s2.close()


def test_journal_replay_after_sigkill_mid_append(tmp_path):
    """The satellite edge: scheduler SIGKILLed mid-append leaves a torn
    final line; the restart replays the surviving records, reports the
    torn line as a journal_recovered mitigation, and the in-flight lease
    is still re-leasable."""
    clock = Clock()
    s = _sched(tmp_path, clock=clock, lease_s=5.0)
    s.submit(JobSpec(betas=(0.1, 1.0)))
    lease = s.acquire("w0")
    s.close()
    path = str(tmp_path / "s" / JOURNAL_FILENAME)
    with open(path, "ab") as f:
        f.write(b'{"v": 1, "kind": "done", "unit_id": "half-writ')
    writer = EventWriter(str(tmp_path / "s"), run_id="replay")
    clock.t += 6.0
    s2 = Scheduler(str(tmp_path / "s"), telemetry=writer, clock=clock)
    assert s2.replayed_torn == 1
    assert s2.status()["counts"]["leased"] == 1
    # the un-journaled transition is re-derived: the lease expires and
    # the unit is stolen like any straggler's
    assert s2.reap() == [lease.unit_id]
    thief = s2.acquire("w1")
    assert s2.complete(thief) is True
    s2.close()
    writer.close()
    events = list(read_events(str(tmp_path / "s")))
    kinds = [e.get("mtype") for e in events if e["type"] == "mitigation"]
    assert "journal_recovered" in kinds


def test_double_lease_prevention_across_scheduler_restart(tmp_path):
    """A lease granted by a DEAD scheduler instance and superseded by the
    restarted one must still be rejected when its holder returns."""
    clock = Clock()
    s = _sched(tmp_path, clock=clock, lease_s=5.0)
    s.submit(JobSpec(betas=(0.5,)))
    stale = s.acquire("ghost")
    s.close()
    clock.t += 6.0
    s2 = _sched(tmp_path, clock=clock)
    assert s2.reap() == [stale.unit_id]
    thief = s2.acquire("w1")
    assert s2.complete(stale, {"stale": True}) is False
    assert s2.complete(thief, {"thief": True}) is True
    records, _ = read_journal(s2.directory)
    assert sum(r["kind"] == "done" for r in records) == 1
    s2.close()


# ------------------------------------------------------------------- pool
def test_pool_worker_death_shrinks_pool_unit_stolen(tmp_path):
    s = _sched(tmp_path)
    s.submit(JobSpec(betas=(0.1, 1.0), seeds=(0, 1)))
    first = threading.Event()

    def runner(unit, heartbeat=None):
        heartbeat()
        if unit.seed == 1 and unit.beta == 0.1 and not first.is_set():
            first.set()
            raise WorkerKilled("chaos")
        return {"unit": unit.unit_id}

    pool = WorkerPool(s, runner, num_workers=2, poll_s=0.01,
                      reap_every_s=0.02)
    stats = pool.run()
    assert stats["workers_died"] == 1
    assert stats["stolen"] >= 1
    assert stats["drained"] is True
    assert s.status()["counts"]["done"] == 4
    s.close()


def test_pool_unit_exception_retries_then_fails_job(tmp_path):
    s = _sched(tmp_path, backoff_base_s=0.01)
    job = s.submit(JobSpec(betas=(0.5,), retry_budget=1))

    def runner(unit, heartbeat=None):
        raise RuntimeError("always broken")

    pool = WorkerPool(s, runner, num_workers=1, poll_s=0.01)
    stats = pool.run()
    assert stats["failed"] == 2          # initial attempt + one retry
    assert stats["drained"] is True
    st = s.status()
    assert st["jobs"][job]["status"] == "failed"
    assert st["counts"]["failed"] == 1
    s.close()


def test_pool_preempted_unit_requeued_lease_free(tmp_path):
    from dib_tpu.train.preempt import TrainingPreempted

    s = _sched(tmp_path)
    job = s.submit(JobSpec(betas=(0.5,), retry_budget=0))
    fired = threading.Event()

    def runner(unit, heartbeat=None):
        if not fired.is_set():
            fired.set()
            raise TrainingPreempted(2, checkpoint_saved=True)
        return {}

    pool = WorkerPool(s, runner, num_workers=1, poll_s=0.01)
    stats = pool.run()
    assert stats["released"] == 1 and stats["completed"] == 1
    assert s.status()["jobs"][job]["retries_used"] == 0
    s.close()


def test_pool_worker_names_are_instance_unique(tmp_path):
    """A relaunched pool must not alias the dead pool's lease holders in
    the journal (same process name + worker index), or the dead-worker
    steal would mistake an orphaned lease for its own live worker's."""
    s = _sched(tmp_path)
    p1 = WorkerPool(s, lambda u, heartbeat=None: {}, num_workers=1)
    p2 = WorkerPool(s, lambda u, heartbeat=None: {}, num_workers=1)
    assert p1.name != p2.name
    s.close()


def test_pool_steals_previous_pool_instances_lease_immediately(tmp_path):
    """The 'holder not in this pool' reap path: a lease granted to a
    previous (dead) pool's worker is force-expired on the first reap tick
    — no waiting out the wall-clock deadline."""
    s = _sched(tmp_path, lease_s=3600.0)
    s.submit(JobSpec(betas=(0.5,)))
    dead_pool = WorkerPool(s, lambda u, heartbeat=None: {}, num_workers=1)
    orphan = s.acquire(f"{dead_pool.name}-w0")
    assert orphan is not None
    pool = WorkerPool(s, lambda u, heartbeat=None: {"ok": 1},
                      num_workers=1, poll_s=0.01, reap_every_s=0.02)
    stats = pool.run()
    assert stats["drained"] and stats["stolen"] == 1
    assert s.status()["counts"]["done"] == 1
    s.close()


# ------------------------------------------------------ telemetry surface
def _run_instrumented_pool(tmp_path):
    d = str(tmp_path / "run")
    writer = EventWriter(d, run_id="sched-run")
    from dib_tpu.telemetry import runtime_manifest

    writer.run_start(runtime_manifest(device_info=False))
    s = Scheduler(d, telemetry=writer, backoff_base_s=0.01)
    s.submit(JobSpec(betas=(0.1, 1.0), retry_budget=2))
    flaky = threading.Event()

    def runner(unit, heartbeat=None):
        heartbeat()
        if unit.beta == 0.1 and not flaky.is_set():
            flaky.set()
            raise RuntimeError("transient")
        return {}

    stats = WorkerPool(s, runner, num_workers=2, telemetry=writer,
                       poll_s=0.01).run()
    s.close()
    writer.run_end(status="ok")
    writer.close()
    return d, stats


def test_scheduler_rollup_from_stream(tmp_path):
    d, stats = _run_instrumented_pool(tmp_path)
    assert stats["drained"]
    summary = summarize(d)
    sched = summary["scheduler"]
    assert sched["jobs"] == {"submitted": 1, "done": 1, "failed": 0}
    assert sched["units"]["submitted"] == 2
    assert sched["units"]["done"] == 2
    assert sched["units"]["failed_attempts"] == 1
    assert sched["retries_max"] == 1
    assert sched["queue_wait_p99_s"] >= 0
    # strict mode accepted every event kind the scheduler emitted
    assert summary["status"] == "ok"


def test_scheduler_rollup_absent_without_sched_events():
    assert scheduler_rollup([{"type": "chunk", "epoch": 1}]) is None


def test_tail_queue_view_renders_sched_line(tmp_path):
    from dib_tpu.telemetry.live import LiveRunState, render_dashboard

    d, _ = _run_instrumented_pool(tmp_path)
    state = LiveRunState()
    for event in read_events(d):
        state.update(event)
    frame = render_dashboard(state)
    assert "queue" in frame
    assert "2 done" in frame
    assert "workers" in frame


def test_slo_scheduler_budgets_check_exit_codes(tmp_path):
    """The SLO scheduler rows (sched_retry_ceiling et al.) gate real
    streams through `telemetry check`: a violating stream exits 1 with a
    durable alert, a clean one exits 0, streams without scheduler events
    skip the rules."""
    from dib_tpu.telemetry.summary import telemetry_main

    slo = os.path.join(REPO, "SLO.json")
    d, _ = _run_instrumented_pool(tmp_path)
    rc = telemetry_main(["check", d, "--slo", slo, "--no-write"])
    assert rc == 0

    # a stream whose retries_max blows the ceiling must violate
    bad = str(tmp_path / "bad")
    writer = EventWriter(bad, run_id="bad-sched")
    from dib_tpu.telemetry import runtime_manifest

    writer.run_start(runtime_manifest(device_info=False))
    writer.job(job_id="j", action="submitted", units=1)
    for retries in (1, 2, 3, 4):
        writer.job(job_id="j", action="unit_failed", unit="j/u0",
                   retries=retries, retry_budget=4, error="x")
    writer.run_end(status="ok")
    writer.close()
    rc = telemetry_main(["check", bad, "--slo", slo, "--no-write"])
    assert rc == 1
    # and the violation names the scheduler rule, in-process and via CLI
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "check", bad,
         "--slo", slo, "--no-write"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=120)
    assert proc.returncode == 1
    assert "sched_retry_ceiling" in proc.stdout


# --------------------------------------------------------------- lint cov
def test_sched_modules_are_lint_covered():
    """Satellite: the host-sync pass targets the scheduler's hot modules
    and the thread-shared-state pass (tree-wide) sees them — the
    thread-heavy scheduler must be lintable from day one."""
    from dib_tpu.analysis import run_passes
    from dib_tpu.analysis.passes.host_sync import HostSyncPass

    for rel in ("dib_tpu/sched/runner.py", "dib_tpu/sched/pool.py",
                "dib_tpu/sched/scheduler.py"):
        assert rel in HostSyncPass.target_modules
    files = [(os.path.join(REPO, rel), rel) for rel in (
        "dib_tpu/sched/journal.py", "dib_tpu/sched/scheduler.py",
        "dib_tpu/sched/pool.py", "dib_tpu/sched/runner.py",
        "dib_tpu/sched/cli.py")]
    findings = run_passes(
        root=REPO, select=["host-sync", "thread-shared-state"],
        files=files)
    assert findings == [], [f.format() for f in findings]


# -------------------------------------------------------------------- CLI
def test_cli_submit_and_status_round_trip(tmp_path, capsys):
    d = str(tmp_path / "cli")
    rc = sched_main(["submit", "--sched-dir", d, "--grid", "0.01", "1.0",
                     "3", "--seeds", "0", "1", "--name", "grid-job"])
    assert rc == 0
    submitted = json.loads(capsys.readouterr().out)
    assert submitted["units"] == 6
    rc = sched_main(["status", "--sched-dir", d, "--json"])
    assert rc == 0
    snapshot = json.loads(capsys.readouterr().out)
    assert snapshot["counts"]["pending"] == 6
    assert list(snapshot["jobs"].values())[0]["name"] == "grid-job"


def test_cli_submit_requires_exactly_one_grid_source(tmp_path):
    d = str(tmp_path / "cli2")
    with pytest.raises(SystemExit):
        sched_main(["submit", "--sched-dir", d])
    with pytest.raises(SystemExit):
        sched_main(["submit", "--sched-dir", d, "--betas", "0.1",
                    "--grid", "0.1", "1.0", "2"])


def test_cli_run_pool_survives_value_spelled_like_action(tmp_path):
    """An argument VALUE that happens to spell the action token must not
    be stripped from the pool's argv (positional strip, not value
    filter): run-pool on an empty queue in a dir literally named
    'run-pool' drains immediately with rc 0."""
    d = str(tmp_path / "run-pool")
    rc = sched_main(["run-pool", "--sched-dir", d, "--workers", "1",
                     "--telemetry-dir", ""])
    assert rc == 0


def test_cli_run_pool_watchdog_accepts_abbreviated_flag(tmp_path):
    """argparse accepts unambiguous prefixes (--watch), so the supervised
    re-exec must strip the flag by prefix match, not exact spelling —
    an empty queue under --watch must supervise cleanly to rc 0."""
    d = str(tmp_path / "wd")
    rc = sched_main(["submit", "--sched-dir", d, "--betas", "0.5"])
    assert rc == 0
    # empty the queue first so the supervised child needs no training
    from dib_tpu.sched import Scheduler

    s = Scheduler(d)
    lease = s.acquire("w0")
    s.complete(lease)
    s.close()
    try:
        rc = sched_main(["run-pool", "--sched-dir", d, "--workers", "1",
                         "--watch", "--telemetry-dir", ""])
    finally:
        # the supervised path pins the run id into the environment for
        # its worker; don't leak it into later tests' shared_run_id()
        os.environ.pop("DIB_TELEMETRY_RUN_ID", None)
    assert rc == 0


def test_cli_sched_subcommand_ordering_guard():
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "--seed", "1", "sched"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=120)
    assert proc.returncode == 2
    assert "sched" in proc.stderr and "must come first" in proc.stderr


# --------------------------------------------------------- supervise_pool
def test_supervise_pool_relaunches_preempt_and_crash(tmp_path):
    """rc-75 exits relaunch budget-free while TERMINAL journal records
    (unit done/fail) land; crashes burn the restart budget; rc 0
    finishes."""
    from dib_tpu.train.watchdog import WatchdogConfig, supervise_pool

    journal = tmp_path / "journal.jsonl"
    marker = tmp_path / "phase"
    script = (
        "import os, sys\n"
        f"marker = {str(marker)!r}\n"
        f"journal = {str(journal)!r}\n"
        "n = int(open(marker).read()) if os.path.exists(marker) else 0\n"
        "open(marker, 'w').write(str(n + 1))\n"
        "with open(journal, 'a') as f:\n"
        "    f.write('{\"kind\": \"done\", \"unit_id\": \"u%d\"}\\n' % n)\n"
        "sys.exit([75, 1, 0][n])\n"
    )
    result = supervise_pool(
        [sys.executable, "-c", script],
        config=WatchdogConfig(max_restarts=1),
        journal_path=str(journal),
    )
    assert result["returncode"] == 0
    assert result["launches"] == 3
    kinds = [m["type"] for m in result["mitigations"]]
    # the preempt relaunch was FREE (a done record landed): with
    # max_restarts=1 only the crash burned budget and the run still won
    assert kinds == ["preempt_restart", "crash_restart"]


def test_supervise_pool_zero_progress_preempt_burns_budget(tmp_path):
    """A rc-75 spinner that never FINISHES a unit is a preemption-shaped
    stall and must burn the restart budget — even when each cycle's
    lease/release bookkeeping grows the journal file (the flapping-
    preemption shape: growth is not progress)."""
    from dib_tpu.train.watchdog import WatchdogConfig, supervise_pool

    journal = tmp_path / "journal.jsonl"
    journal.write_text('{"kind": "job"}\n')
    script = (
        f"journal = {str(journal)!r}\n"
        "with open(journal, 'a') as f:\n"
        "    f.write('{\"kind\": \"lease\", \"unit_id\": \"u0\"}\\n')\n"
        "    f.write('{\"kind\": \"release\", \"unit_id\": \"u0\"}\\n')\n"
        "import sys; sys.exit(75)\n"
    )
    result = supervise_pool(
        [sys.executable, "-c", script],
        config=WatchdogConfig(max_restarts=1),
        journal_path=str(journal),
    )
    assert result["returncode"] == 75
    assert "gave up" in result["error"]
    assert result["launches"] == 2
