"""Pallas log-density kernel: differential parity vs the XLA broadcast path.

On the CPU test backend the kernel runs in interpreter mode — slow but
semantically identical, so these are true differential tests of the tiling,
padding, and fusion logic.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dib_tpu.ops.gaussian import gaussian_log_density_mat
from dib_tpu.ops.info_bounds import mi_sandwich_from_params, set_density_backend
from dib_tpu.ops.pallas_density import gaussian_log_density_mat_pallas


def random_params(rng, n, m, d):
    u = rng.normal(scale=2.0, size=(n, d)).astype(np.float32)
    mus = rng.normal(scale=2.0, size=(m, d)).astype(np.float32)
    logvars = rng.normal(scale=0.7, size=(m, d)).astype(np.float32) - 1.0
    return jnp.array(u), jnp.array(mus), jnp.array(logvars)


@pytest.mark.parametrize("n,m,d,bm,bn", [
    (64, 64, 8, 32, 32),       # exact tiling
    (50, 70, 12, 32, 32),      # both axes ragged -> padding path
    (8, 8, 4, 128, 128),       # single tile larger than the problem
    (130, 33, 16, 64, 32),     # ragged rows and cols
])
def test_kernel_matches_xla(rng, n, m, d, bm, bn):
    u, mus, logvars = random_params(rng, n, m, d)
    want = gaussian_log_density_mat(u, mus, logvars)
    got = gaussian_log_density_mat_pallas(
        u, mus, logvars, block_rows=bm, block_cols=bn, interpret=True
    )
    assert got.shape == (n, m)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_kernel_preserves_diagonal_precision(rng):
    """Diagonal entries (u ~= mu, small variance) are the cancellation-prone
    ones; the direct-difference kernel must match XLA exactly there."""
    n, d = 96, 16
    mus = rng.normal(scale=3.0, size=(n, d)).astype(np.float32)
    logvars = np.full((n, d), -6.0, dtype=np.float32)
    u = mus + rng.normal(scale=np.exp(-3.0), size=(n, d)).astype(np.float32)
    want = gaussian_log_density_mat(jnp.array(u), jnp.array(mus), jnp.array(logvars))
    got = gaussian_log_density_mat_pallas(
        jnp.array(u), jnp.array(mus), jnp.array(logvars),
        block_rows=32, block_cols=32, interpret=True,
    )
    np.testing.assert_allclose(
        np.diag(np.asarray(got)), np.diag(np.asarray(want)), rtol=1e-6, atol=1e-6
    )


@pytest.mark.slow
def test_backend_dispatch_roundtrip(rng):
    """Forcing the pallas backend must give the same sandwich bounds as the
    XLA path (end-to-end through the jitted estimator), and restore cleanly."""
    u, mus, logvars = random_params(rng, 64, 64, 8)
    key = jax.random.key(0)
    want = mi_sandwich_from_params(key, mus, logvars)
    try:
        set_density_backend("pallas")
        got = mi_sandwich_from_params(key, mus, logvars)
    finally:
        set_density_backend("auto")
    np.testing.assert_allclose(float(got[0]), float(want[0]), rtol=1e-5)
    np.testing.assert_allclose(float(got[1]), float(want[1]), rtol=1e-5)


def test_backend_rejects_unknown():
    with pytest.raises(ValueError):
        set_density_backend("cuda")
