"""Pallas log-density kernel: differential parity vs the XLA broadcast path.

On the CPU test backend the kernel runs in interpreter mode — slow but
semantically identical, so these are true differential tests of the tiling,
padding, and fusion logic.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dib_tpu.ops.gaussian import gaussian_log_density_mat
from dib_tpu.ops.info_bounds import mi_sandwich_from_params, set_density_backend
from dib_tpu.ops.pallas_density import gaussian_log_density_mat_pallas


def random_params(rng, n, m, d):
    u = rng.normal(scale=2.0, size=(n, d)).astype(np.float32)
    mus = rng.normal(scale=2.0, size=(m, d)).astype(np.float32)
    logvars = rng.normal(scale=0.7, size=(m, d)).astype(np.float32) - 1.0
    return jnp.array(u), jnp.array(mus), jnp.array(logvars)


@pytest.mark.parametrize("n,m,d,bm,bn", [
    (64, 64, 8, 32, 32),       # exact tiling
    (50, 70, 12, 32, 32),      # both axes ragged -> padding path
    (8, 8, 4, 128, 128),       # single tile larger than the problem
    (130, 33, 16, 64, 32),     # ragged rows and cols
])
def test_kernel_matches_xla(rng, n, m, d, bm, bn):
    u, mus, logvars = random_params(rng, n, m, d)
    want = gaussian_log_density_mat(u, mus, logvars)
    got = gaussian_log_density_mat_pallas(
        u, mus, logvars, block_rows=bm, block_cols=bn, interpret=True
    )
    assert got.shape == (n, m)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_kernel_preserves_diagonal_precision(rng):
    """Diagonal entries (u ~= mu, small variance) are the cancellation-prone
    ones; the direct-difference kernel must match XLA exactly there."""
    n, d = 96, 16
    mus = rng.normal(scale=3.0, size=(n, d)).astype(np.float32)
    logvars = np.full((n, d), -6.0, dtype=np.float32)
    u = mus + rng.normal(scale=np.exp(-3.0), size=(n, d)).astype(np.float32)
    want = gaussian_log_density_mat(jnp.array(u), jnp.array(mus), jnp.array(logvars))
    got = gaussian_log_density_mat_pallas(
        jnp.array(u), jnp.array(mus), jnp.array(logvars),
        block_rows=32, block_cols=32, interpret=True,
    )
    np.testing.assert_allclose(
        np.diag(np.asarray(got)), np.diag(np.asarray(want)), rtol=1e-6, atol=1e-6
    )


@pytest.mark.slow
def test_backend_dispatch_roundtrip(rng):
    """Forcing the pallas backend must give the same sandwich bounds as the
    XLA path (end-to-end through the jitted estimator), and restore cleanly."""
    u, mus, logvars = random_params(rng, 64, 64, 8)
    key = jax.random.key(0)
    want = mi_sandwich_from_params(key, mus, logvars)
    try:
        set_density_backend("pallas")
        got = mi_sandwich_from_params(key, mus, logvars)
    finally:
        set_density_backend("auto")
    np.testing.assert_allclose(float(got[0]), float(want[0]), rtol=1e-5)
    np.testing.assert_allclose(float(got[1]), float(want[1]), rtol=1e-5)


def test_backend_rejects_unknown():
    with pytest.raises(ValueError):
        set_density_backend("cuda")


# ----------------------------------------------------------------------
# Fused one-pass MI-sandwich row statistics (interpreter-mode tier-1 gate:
# CPU CI exercises the Pallas code path on every run)
# ----------------------------------------------------------------------

def _reference_stats(u, mus, logvars):
    log_p = gaussian_log_density_mat(u, mus, logvars)
    n = log_p.shape[0]
    diag = jnp.diagonal(log_p)
    lse_full = jax.scipy.special.logsumexp(log_p, axis=1)
    lse_off = jax.scipy.special.logsumexp(
        jnp.where(jnp.eye(n, dtype=bool), -1e30, log_p), axis=1)
    return diag, lse_full, lse_off


@pytest.mark.parametrize("n,d,bm,bn", [
    (64, 8, 32, 32),       # exact tiling
    (50, 12, 32, 32),      # ragged -> padding/masking path
    (130, 16, 64, 32),     # ragged, different block shapes
    (8, 4, 128, 128),      # single tile larger than the problem
])
def test_fused_row_stats_match_reduced_matrix(rng, n, d, bm, bn):
    from dib_tpu.ops.pallas_density import mi_row_stats_pallas

    u, mus, logvars = random_params(rng, n, n, d)
    want = _reference_stats(u, mus, logvars)
    got = mi_row_stats_pallas(u, mus, logvars, block_rows=bm, block_cols=bn,
                              interpret=True)
    for g, w in zip(got, want):
        assert g.shape == (n,)
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-5, atol=2e-5)


def test_fused_row_stats_probe_variant(rng):
    """diagonal=False (the [M, N] probe map): only the full-row lse, no
    own-density entry anywhere in the matrix."""
    from dib_tpu.ops.pallas_density import mi_row_stats_pallas

    u, mus, logvars = random_params(rng, 30, 70, 8)
    want = jax.scipy.special.logsumexp(
        gaussian_log_density_mat(u, mus, logvars), axis=1)
    _, full, _ = mi_row_stats_pallas(u, mus, logvars, block_rows=16,
                                     block_cols=32, interpret=True,
                                     diagonal=False)
    np.testing.assert_allclose(np.asarray(full), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_fused_row_stats_bfloat16_inputs(rng):
    """bf16 channel params accumulate in f32 inside the kernel; parity vs
    the f32-cast XLA reference at bf16-rounding tolerance."""
    from dib_tpu.ops.pallas_density import mi_row_stats_pallas

    u, mus, logvars = random_params(rng, 48, 48, 8)
    u16 = u.astype(jnp.bfloat16)
    m16 = mus.astype(jnp.bfloat16)
    l16 = logvars.astype(jnp.bfloat16)
    want = _reference_stats(u16.astype(jnp.float32),
                            m16.astype(jnp.float32),
                            l16.astype(jnp.float32))
    got = mi_row_stats_pallas(u16, m16, l16, block_rows=32, block_cols=32,
                              interpret=True)
    for g, w in zip(got, want):
        assert g.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-2, atol=2e-2)


def test_fused_backend_sandwich_bounds_parity(rng):
    """End-to-end through the jitted estimator: forcing 'pallas' routes
    mi_sandwich_from_params through the FUSED one-pass kernel; the bounds
    must match the XLA path — including the LOO reference semantics
    (diagonal excluded from the logsumexp, denominator still /B)."""
    u, mus, logvars = random_params(rng, 96, 96, 8)
    key = jax.random.key(3)
    want = mi_sandwich_from_params(key, mus, logvars)
    want_blocked = mi_sandwich_from_params(key, mus, logvars, row_block=32)
    # XLA row-blocked streaming path == unblocked path (rowwise reductions
    # cannot see the blocking)
    np.testing.assert_allclose(np.asarray(want), np.asarray(want_blocked),
                               rtol=1e-6, atol=1e-6)
    try:
        set_density_backend("pallas")
        got = mi_sandwich_from_params(key, mus, logvars)
    finally:
        set_density_backend("auto")
    np.testing.assert_allclose(float(got[0]), float(want[0]), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(float(got[1]), float(want[1]), rtol=1e-5,
                               atol=1e-5)
    # LOO /B semantics: upper bound differs from a /(B-1) denominator by
    # exactly log(B/(B-1)) — pin the fused path to the reference's /B
    b = mus.shape[0]
    assert abs(float(got[1] - want[1])) < 1e-4 * abs(float(want[1])) + 1e-5
    assert float(want[1]) != pytest.approx(
        float(want[1]) + np.log(b / (b - 1)), abs=1e-6)


def test_fused_backend_probe_parity(rng):
    """mi_sandwich_probe through the fused kernel (logaddexp own-density
    fold-in) matches the XLA concatenate-and-logsumexp path."""
    from dib_tpu.ops.info_bounds import mi_sandwich_probe

    key = jax.random.key(5)
    pm, dm, dl = random_params(rng, 40, 120, 8)
    pl_ = jnp.asarray(
        np.float32(np.random.default_rng(7).normal(size=(40, 8)) * 0.4 - 1.0))
    want = mi_sandwich_probe(key, pm, pl_, dm, dl)
    try:
        set_density_backend("pallas")
        got = mi_sandwich_probe(key, pm, pl_, dm, dl)
    finally:
        set_density_backend("auto")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
