"""Live monitor (`telemetry tail`): the incremental follower, the rolling
dashboard state (steps/s, live MFU, liveness), and the end-to-end live
drill of the acceptance criteria — a real training run followed MID-RUN
by a concurrent tail, with heartbeats, a seeded SLO violation leaving a
durable alert, and the run landing in the fleet registry/index.
"""

import io
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from dib_tpu.telemetry.events import EventWriter, read_events
from dib_tpu.telemetry.live import (
    LiveRunState,
    StreamFollower,
    liveness,
    render_dashboard,
    tail,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ============================================================ StreamFollower
def test_follower_reads_incrementally(tmp_path):
    path = tmp_path / "events.jsonl"
    follower = StreamFollower(str(tmp_path))   # run-dir form
    assert follower.poll() == []               # file does not exist yet
    with open(path, "a") as f:
        f.write(json.dumps({"type": "chunk", "epoch": 1}) + "\n")
    (first,) = follower.poll()
    assert first["epoch"] == 1
    assert follower.poll() == []               # nothing new
    with open(path, "a") as f:
        f.write(json.dumps({"type": "chunk", "epoch": 2}) + "\n")
        f.write(json.dumps({"type": "chunk", "epoch": 3}) + "\n")
    assert [e["epoch"] for e in follower.poll()] == [2, 3]


def test_follower_buffers_torn_final_line(tmp_path):
    """An in-progress append (no trailing newline yet) must be BUFFERED,
    not mis-parsed — and parse once its newline arrives."""
    path = tmp_path / "events.jsonl"
    whole = json.dumps({"type": "chunk", "epoch": 7})
    with open(path, "w") as f:
        f.write(json.dumps({"type": "run_start"}) + "\n")
        f.write(whole[:10])                    # torn mid-append
    follower = StreamFollower(str(path))
    events = follower.poll()
    assert [e["type"] for e in events] == ["run_start"]
    with open(path, "a") as f:
        f.write(whole[10:] + "\n")
    (done,) = follower.poll()
    assert done == {"type": "chunk", "epoch": 7}
    assert follower.torn == 0                  # never counted as torn


def test_follower_skips_torn_interior_line(tmp_path):
    """A COMPLETE line that does not parse (writer killed mid-append
    earlier in the file, survivors appended after) is skipped + counted."""
    path = tmp_path / "events.jsonl"
    with open(path, "w") as f:
        f.write('{"type": "chunk", "epo\n')    # killed writer's torn line
        f.write(json.dumps({"type": "chunk", "epoch": 2}) + "\n")
    follower = StreamFollower(str(path))
    events = follower.poll()
    assert [e.get("epoch") for e in events] == [2]
    assert follower.torn == 1


def test_follower_resets_on_truncation(tmp_path):
    path = tmp_path / "events.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"type": "chunk", "epoch": 1}) + "\n")
    follower = StreamFollower(str(path))
    assert len(follower.poll()) == 1
    with open(path, "w") as f:                 # rotated/truncated under us
        f.write(json.dumps({"type": "x"}) + "\n")   # strictly shorter
    (again,) = follower.poll()
    assert again["type"] == "x"


def test_follower_handles_concurrent_writer(tmp_path):
    """Poll races a thread appending real EventWriter lines; every event
    arrives exactly once, in order, with no torn parses."""
    stop = threading.Event()

    def write_events():
        with EventWriter(str(tmp_path)) as w:
            for i in range(200):
                w.emit("chunk", epoch=i)
        stop.set()

    thread = threading.Thread(target=write_events)
    follower = StreamFollower(str(tmp_path))
    seen = []
    thread.start()
    # NB: poll() CONSUMES — every call's result must land in `seen`
    # (a poll inside the loop condition would silently eat events when
    # the writer finishes before the first condition check).
    while not stop.is_set():
        seen.extend(follower.poll())
    thread.join()
    seen.extend(follower.poll())
    assert [e["epoch"] for e in seen] == list(range(200))
    assert follower.torn == 0


# ============================================================= LiveRunState
def _feed(state, events):
    for e in events:
        state.update(e)


def test_live_state_rollups_and_mfu():
    state = LiveRunState()
    _feed(state, [
        {"type": "run_start", "run": "r1", "t": 100.0,
         "manifest": {"device_kind": "TPU v5 lite", "device_count": 1}},
        {"type": "compile", "name": "run_chunk", "flops": 4e12,
         "bytes_accessed": 4e10, "epochs": 10, "t": 101.0},
        {"type": "chunk", "epoch": 10, "steps": 500, "seconds": 2.0,
         "epochs": 10, "loss": 1.5, "val_loss": 1.6,
         "kl_per_feature": [0.5, 0.1, 0.0], "steps_per_s": 250.0,
         "t": 103.0},
        {"type": "heartbeat", "beat": 1, "epoch": 10, "phase": "boundary",
         "intervals_s": [2.0], "t": 103.0},
        {"type": "mitigation", "mtype": "stall_kill", "t": 104.0},
        {"type": "run_end", "status": "ok", "t": 105.0},
    ])
    assert state.run_id == "r1"
    assert state.status == "ok"
    assert state.total_steps == 500
    assert state.steps_per_s == pytest.approx(250.0)
    mfu = state.mfu()
    # 4e12 flops / 2 s = 2 TFLOP/s over the 197 TFLOP/s v5e peak
    assert mfu["flops_frac_of_peak"] == pytest.approx(2.0 / 197.0, rel=1e-6)
    assert state.counts["mitigation"] == 1
    frame = render_dashboard(state, now=106.0)
    assert "steps/s" in frame and "250.0" in frame
    assert "MFU" in frame and "197" in frame
    assert "stall_kill" in frame


def test_live_mfu_scales_partial_chunk():
    """A final partial chunk (fewer epochs than the compiled program)
    scales the program FLOPs down by epochs ratio."""
    state = LiveRunState()
    _feed(state, [
        {"type": "run_start", "run": "r", "t": 0.0,
         "manifest": {"device_kind": "TPU v5 lite"}},
        {"type": "compile", "name": "run_chunk", "flops": 1e13,
         "epochs": 10, "t": 0.0},
        {"type": "chunk", "epoch": 12, "steps": 100, "seconds": 1.0,
         "epochs": 2, "t": 1.0},
    ])
    mfu = state.mfu()
    assert mfu["achieved_gflops"] == pytest.approx(2e12 / 1e9)


def test_liveness_silent_detection():
    state = LiveRunState()
    _feed(state, [
        {"type": "heartbeat", "beat": 1, "epoch": 0, "phase": "chunk",
         "interval_s": 1.0, "t": 100.0},
    ])
    fresh = liveness(state, now=101.0)
    assert fresh["silent"] is False and fresh["in_chunk"] is True
    stale = liveness(state, now=110.0)
    assert stale["silent"] is True
    assert "SILENT" in render_dashboard(state, now=110.0)


def test_dashboard_renders_sweep_kl_totals():
    state = LiveRunState()
    _feed(state, [
        {"type": "chunk", "epoch": 5, "steps": 10, "seconds": 1.0,
         "loss": [1.0, 2.0], "val_loss": [1.1, 2.1],
         "kl_total": [3.0, 4.0], "t": 1.0},
    ])
    frame = render_dashboard(state)
    assert "KL total" in frame and "2 replicas" in frame
    assert "loss      1.5" in frame   # [R] lists render as means


# ===================================================================== tail
def test_tail_follows_concurrent_writer_to_preempted_end(tmp_path):
    """tail attaches BEFORE the stream exists, follows a writer thread,
    and detaches on the terminal run_end — here a preempted run."""

    def write():
        time.sleep(0.1)
        with EventWriter(str(tmp_path), run_id="p1") as w:
            w.run_start({"device_kind": "cpu"})
            for i in range(3):
                w.chunk(epoch=i + 1, steps=10, seconds=0.01)
                time.sleep(0.05)
            w.run_end(status="preempted", epoch=3)

    thread = threading.Thread(target=write)
    thread.start()
    out = io.StringIO()
    state = tail(str(tmp_path), refresh_s=0.02, duration_s=30,
                 out=out, ansi=False)
    thread.join()
    assert state.status == "preempted"
    assert state.num_chunks == 3
    assert "preempted" in out.getvalue()


def test_tail_detaches_on_duration_for_incomplete_stream(tmp_path):
    """A stream whose run never ended (killed writer — status stays
    'running') must not hang tail: the duration bound detaches."""
    with EventWriter(str(tmp_path)) as w:
        w.run_start({})
        w.chunk(epoch=1, steps=5, seconds=0.01)
    state = tail(str(tmp_path), refresh_s=0.02, duration_s=0.2,
                 out=io.StringIO(), ansi=False)
    assert state.status == "running"
    assert state.num_chunks == 1


def test_tail_cli_once_frame(tmp_path, capsys):
    from dib_tpu.telemetry.summary import telemetry_main

    with EventWriter(str(tmp_path), run_id="cli-run") as w:
        w.run_start({"device_kind": "cpu"})
        w.chunk(epoch=1, steps=50, seconds=0.5)
        w.run_end(status="ok")
    rc = telemetry_main(["tail", str(tmp_path), "--once", "--no-ansi"])
    frame = capsys.readouterr().out
    assert rc == 0
    assert "cli-run" in frame and "steps/s" in frame


# ==================================================== acceptance live drill
def test_live_drill_end_to_end(tmp_path, monkeypatch):
    """THE acceptance criterion: a real CPU training run with `tail`
    attached mid-run rendering steps/s + live MFU from real events; a
    seeded SLO violation leaves a durable alert and a nonzero check
    exit; the run shows in `runs list` and the `--index` page."""
    import jax

    from dib_tpu.telemetry.registry import RunRegistry, register_run
    from dib_tpu.telemetry.report import write_index
    from dib_tpu.telemetry.slo import check_run
    from dib_tpu.workloads.boolean import (
        BooleanTrainer,
        BooleanWorkloadConfig,
        fetch_boolean_circuit,
    )

    monkeypatch.setenv("DIB_HEARTBEAT_S", "0.2")
    run_dir = tmp_path / "live_run"
    config = BooleanWorkloadConfig(num_steps=60, mi_every=20)
    trainer = BooleanTrainer(fetch_boolean_circuit(), config)

    def train():
        with EventWriter(str(run_dir), run_id="drill") as w:
            w.run_start({"device_kind": jax.devices()[0].device_kind,
                         "device_platform": jax.devices()[0].platform})
            trainer.fit(jax.random.key(0), telemetry=w)
            w.run_end(status="ok")

    thread = threading.Thread(target=train)
    thread.start()
    out = io.StringIO()
    state = tail(str(run_dir), refresh_s=0.05, duration_s=120,
                 out=out, ansi=False)
    thread.join()

    # tail attached mid-run and rendered real throughput + the MFU gauge
    assert state.status == "ok"
    assert state.num_chunks == 3
    assert state.steps_per_s and state.steps_per_s > 0
    frames = out.getvalue()
    assert "steps/s" in frames and "MFU" in frames
    assert state.mfu() is not None           # live gauge armed from real
    assert state.last_beat_t is not None     # heartbeats observed live

    # seeded SLO violation -> durable alert event + nonzero check
    slo_path = tmp_path / "slo.json"
    slo_path.write_text(json.dumps({
        "slo_version": 1,
        "rules": [{"name": "impossible_floor", "metric": "steps_per_s",
                   "min": 1e12}],
        "transitions": {"kl_threshold_nats": 0.05},
    }))
    report = check_run(str(run_dir), str(slo_path))
    assert report["violations"] == 1
    alerts = list(read_events(str(run_dir), types=("alert",)))
    assert [a["rule"] for a in alerts] == ["impossible_floor"]
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "check",
         str(run_dir), "--slo", str(slo_path)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 1
    assert "SLO violation" in proc.stderr

    # registry + index page show the run (alert count included)
    register_run(str(run_dir), root=str(tmp_path / "runsroot"))
    latest = RunRegistry(str(tmp_path / "runsroot")).latest()
    assert "drill" in latest
    assert latest["drill"]["metrics"]["alerts"] == 1
    from dib_tpu.telemetry.report import write_report

    write_report(str(run_dir))
    index = write_index(str(tmp_path / "runsroot"))
    html = open(index).read()
    assert "drill" in html and "report.html" in html
