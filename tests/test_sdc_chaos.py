"""The SDC chaos-suite artifact contract + the canary KL gate
(``scripts/chaos_sdc.py``, docs/robustness.md "Numerical integrity").

Fast tier (``-m fault``): the committed ``CHAOS_SDC.json`` must
validate against the artifact schema (per-row SDC invariants + the
record-level zero-undetected gate), cover every drill family, and show
all of them passing; ``telemetry check CHAOS_SDC.json`` must evaluate
the ``sdc_undetected_max`` SLO rule green against the committed pair
and exit 1 on a seeded violation. The deployer's widened canary — per-
channel KL against the publisher's recorded boundary stats, the gate
that catches FINITE garbage — runs end to end in-process. The full
drill matrix re-run is exercised by the committed record's generator
and stays out of tier 1 (each family trains real models).
"""

import copy
import json
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "CHAOS_SDC.json")

EXPECTED_DRILLS = {"payload_bitflip", "finite_spike_sdc",
                   "poisoned_publish"}
INVARIANTS = ("corruption_detected", "rollback_parity",
              "zero_corrupt_responses")


# ------------------------------------------------------------- contract
def test_committed_chaos_sdc_artifact_validates():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from check_run_artifacts import check_file

    assert os.path.exists(ARTIFACT), (
        "CHAOS_SDC.json missing — run `python scripts/chaos_sdc.py "
        "--out CHAOS_SDC.json` and commit the record")
    assert check_file(ARTIFACT) == []


def test_committed_chaos_sdc_matrix_is_complete_and_green():
    record = json.load(open(ARTIFACT))
    assert record["metric"] == "chaos_sdc_matrix"
    assert record["unit"] == "drills_passed"
    drills = {d["drill"]: d for d in record["matrix"]}
    assert set(drills) == EXPECTED_DRILLS
    assert record["all_passed"] is True
    assert record["value"] == record["total"] == len(EXPECTED_DRILLS)
    assert record["undetected_corruptions"] == 0
    for name, d in drills.items():
        for invariant in INVARIANTS:
            assert d[invariant] is True, (name, invariant)
    # the headline evidence per family
    bitflip = drills["payload_bitflip"]
    assert bitflip["scrub_rc"] == 1 and bitflip["scrub_found_step"]
    assert bitflip["quarantined_steps"] == [12]
    spike = drills["finite_spike_sdc"]
    assert spike["all_verdicts_finite_spikes"] is True
    assert spike["anomaly_events"] >= 1
    poison = drills["poisoned_publish"]
    assert poison["victim_decision"]["action"] == "rolled_back"
    assert "corrupt" in poison["victim_decision"]["error"].lower()
    assert poison["deployer_status"]["rollbacks"] == 1
    assert poison["deployer_status"]["promoted"] == 2


def test_committed_chaos_sdc_evidence_detection_and_recovery():
    """Every drill's embedded telemetry evidence agrees with the suite's
    bookkeeping: injected == detected, nothing undetected."""
    record = json.load(open(ARTIFACT))
    for drill in record["matrix"]:
        faults = (drill.get("evidence") or {}).get("faults") or {}
        assert faults.get("injected", 0) >= 1, drill["drill"]
        assert faults.get("detected") == faults.get("injected"), \
            drill["drill"]
        assert faults.get("undetected") == [], drill["drill"]


def test_check_run_artifacts_rejects_broken_sdc_shapes(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from check_run_artifacts import check_file

    record = json.load(open(ARTIFACT))

    def write(mutate):
        bad = copy.deepcopy(record)
        mutate(bad)
        path = tmp_path / "CHAOS_SDC_BAD.json"
        path.write_text(json.dumps(bad))
        return check_file(str(path))

    # a missing drill family on a full record
    problems = write(lambda r: r["matrix"].pop(0))
    assert any("missing drill" in p for p in problems)
    # a failed drill
    problems = write(
        lambda r: r["matrix"][0].__setitem__("ok", False))
    assert any("failures" in p for p in problems)
    # a dropped invariant
    problems = write(
        lambda r: r["matrix"][1].__setitem__("rollback_parity", False))
    assert any("rollback_parity" in p for p in problems)
    # a nonzero undetected count
    problems = write(
        lambda r: r.__setitem__("undetected_corruptions", 1))
    assert any("undetected_corruptions" in p for p in problems)


# ---------------------------------------------------------- SLO pairing
def test_telemetry_check_gates_the_committed_pair():
    from dib_tpu.telemetry.slo import check_run

    report = check_run(ARTIFACT, os.path.join(REPO, "SLO.json"),
                       write=False)
    rules = {r["rule"]: r for r in report["rules"]}
    assert rules["sdc_undetected_max"]["status"] == "ok"
    assert rules["sdc_undetected_max"]["value"] == 0
    assert report["violations"] == 0


def test_telemetry_check_pages_on_seeded_undetected(tmp_path):
    record = json.load(open(ARTIFACT))
    record["undetected_corruptions"] = 1
    bad = tmp_path / "CHAOS_SDC_SEEDED.json"
    bad.write_text(json.dumps(record))
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "check",
         str(bad), "--slo", os.path.join(REPO, "SLO.json")],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 1, proc.stdout[-500:] + proc.stderr[-500:]
    assert "sdc_undetected_max" in proc.stdout


def test_committed_registry_carries_sdc_history():
    entries = [json.loads(line)
               for line in open(os.path.join(REPO, "runs", "index.jsonl"))
               if line.strip()]
    sdc = [e for e in entries if e.get("metric") == "chaos_sdc_matrix"]
    assert sdc, "runs/index.jsonl must carry the CHAOS_SDC evidence"
    assert sdc[-1]["all_passed"] is True
    assert sdc[-1]["undetected_corruptions"] == 0


# ----------------------------------------------------- canary KL gate
@pytest.fixture(scope="module")
def bundle():
    from dib_tpu.data import get_dataset

    return get_dataset("boolean_circuit")


def _model(bundle):
    from dib_tpu.models import DistributedIBModel

    return DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=bundle.output_dimensionality, embedding_dim=2,
    )


def test_canary_kl_gate_refuses_finite_garbage(bundle, tmp_path):
    """End to end: a published checkpoint whose params are FINITE
    garbage (valid digests, finite predictions — every pre-ISSUE-14
    gate green) fails promotion on the per-channel KL check against the
    publisher's recorded boundary stats; the previous checkpoint keeps
    answering, and a clean publish promotes normally."""
    import jax

    from dib_tpu.faults import scale_params
    from dib_tpu.serve.zoo import ModelZoo
    from dib_tpu.stream.deployer import Deployer, read_deploys
    from dib_tpu.stream.online import (
        OnlineConfig,
        OnlineDIBTrainer,
        read_publishes,
    )
    from dib_tpu.train import DIBCheckpointer, DIBTrainer, TrainConfig

    stream_dir = tmp_path / "stream"
    deploy_dir = tmp_path / "deploy"
    config = TrainConfig(batch_size=16, num_pretraining_epochs=1,
                         num_annealing_epochs=2)
    online = OnlineConfig(window=32, stride=8, chunk_epochs=1,
                          publish_every=1, rounds=2, seed=0)
    template = DIBTrainer(_model(bundle), bundle, config)
    zoo = ModelZoo(exec_capacity=8, response_capacity=16)
    deployer = Deployer(str(stream_dir), str(deploy_dir), template, zoo,
                        router_kwargs=dict(batch_buckets=(1, 8)))

    OnlineDIBTrainer(_model(bundle), bundle, config, online,
                     str(stream_dir)).run(jax.random.key(0), rounds=2)
    publishes, _ = read_publishes(str(stream_dir))
    assert len(publishes) == 2
    # every publish record carries the publisher's boundary stats
    assert all(p["boundary"]["kl_per_feature"] for p in publishes)

    # promote publish 1 only, then poison publish 2's params IN PLACE
    # with finite garbage re-saved under valid digests
    victim = publishes[-1]
    victim_dir = os.path.join(str(stream_dir), victim["path"])
    ckpt = DIBCheckpointer(victim_dir)
    try:
        state, history, key = ckpt.restore(template)
        poisoned = state._replace(
            params=scale_params(state.params, 16.0))
        ckpt.save(int(victim["step"]) + 1, poisoned, history, key)
    finally:
        ckpt.close()

    assert deployer.catch_up() == 2
    deploys, _ = read_deploys(str(deploy_dir))
    by_pub = {d["publish_id"]: d for d in deploys}
    first = by_pub[publishes[0]["publish_id"]]
    assert first["action"] == "promoted"
    refused = by_pub[victim["publish_id"]]
    assert refused["action"] == "rolled_back"
    assert "KL disagrees" in refused["error"]
    # the fleet still answers from the promoted (clean) checkpoint
    probe = np.asarray(bundle.x_valid[:4], np.float32)
    _, router = zoo.resolve()
    out = router.entries[0].engine.predict(probe)
    assert np.all(np.isfinite(np.asarray(out["prediction"])))
    zoo.close()


def test_canary_without_recorded_stats_is_vacuous(bundle, tmp_path):
    """Rolling upgrade: a publish record from a pre-ISSUE-14 trainer
    (no boundary stats) canaries on the finite gates only."""
    import jax

    from dib_tpu.serve.zoo import ModelZoo
    from dib_tpu.stream.deployer import Deployer, read_deploys
    from dib_tpu.stream.online import (
        OnlineConfig,
        OnlineDIBTrainer,
        publishes_path,
        read_publishes,
    )
    from dib_tpu.train import DIBTrainer, TrainConfig

    stream_dir = tmp_path / "stream"
    deploy_dir = tmp_path / "deploy"
    config = TrainConfig(batch_size=16, num_pretraining_epochs=1,
                         num_annealing_epochs=2)
    online = OnlineConfig(window=32, stride=8, chunk_epochs=1,
                          publish_every=1, rounds=1, seed=0)
    OnlineDIBTrainer(_model(bundle), bundle, config, online,
                     str(stream_dir)).run(jax.random.key(0), rounds=1)
    # strip the boundary stats from the journal, old-publisher style
    records = [json.loads(line)
               for line in open(publishes_path(str(stream_dir)))]
    for rec in records:
        rec.pop("boundary", None)
    with open(publishes_path(str(stream_dir)), "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    assert read_publishes(str(stream_dir))[0][0].get("boundary") is None

    template = DIBTrainer(_model(bundle), bundle, config)
    zoo = ModelZoo(exec_capacity=8, response_capacity=16)
    deployer = Deployer(str(stream_dir), str(deploy_dir), template, zoo,
                        router_kwargs=dict(batch_buckets=(1, 8)))
    assert deployer.catch_up() == 1
    deploys, _ = read_deploys(str(deploy_dir))
    assert deploys[0]["action"] == "promoted"
    zoo.close()
