"""Fixture: the fleet-aggregator race shape (ISSUE 16) — a background
merge thread rebinds the shared timeline and advances the consumed
cursor while readers snapshot them, and the class holds no lock."""

import threading


class UnlockedAggregator:
    def __init__(self, sources):
        self.sources = sources
        self.timeline = []
        self.consumed = 0
        threading.Thread(target=self._merge_loop, daemon=True).start()

    def _merge_loop(self):
        while True:
            for source in self.sources:
                for record in source.poll():
                    # BUG: readers snapshot timeline/consumed concurrently
                    # — no lock anywhere in the class
                    self.timeline = self.timeline + [record]
                    self.consumed += 1
                    self.last_t = record.get("t")

    def merged(self):
        return sorted(self.timeline, key=lambda e: e.get("t", 0.0))
