"""async-blocking PRAGMA fixture: a reviewed exception with a reason —
a startup-only coroutine that deliberately sleeps before the loop
serves traffic (no in-flight requests exist yet to stall)."""

import time


async def warmup_once():
    # lint-ok(async-blocking): startup-only coroutine, runs to completion
    # before the listener accepts its first connection — nothing in
    # flight can stall behind this deliberate settle delay
    time.sleep(0.2)
    return True
