"""Fixture: unguarded self-mutation from a thread target (the
EventWriter.emit race shape, both method- and closure-target forms)."""

import threading


class Emitter:
    def __init__(self):
        self.seq = 0
        self.thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while True:
            self.seq += 1   # BUG: racing the main thread, no lock held

    def start_closure(self):
        def beat_loop():
            self.last_beat = "now"   # BUG: same race, closure form

        threading.Thread(target=beat_loop, daemon=True).start()
