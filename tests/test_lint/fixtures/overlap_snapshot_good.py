"""Fixture: the blessed overlap idiom — a REAL on-device copy
(`snapshot_params`) taken before the donating call decouples the
measurement from the donation, and a fresh alias taken AFTER the rebind
points at live buffers."""

from functools import partial

import jax

from dib_tpu.train.overlap import snapshot_params


@partial(jax.jit, donate_argnames=("state", "history"))
def run_chunk(state, history, key, num_epochs):
    return state, history


def measure(params, key):
    return params, key


def good_overlap(state, history, key):
    snap = snapshot_params(state.params)   # real copy: survives donation
    state, history = run_chunk(state, history, key, 8)
    lower = measure(snap, key)
    fresh_view = state.params              # alias of the REBOUND state: live
    return state, history, lower, fresh_view
