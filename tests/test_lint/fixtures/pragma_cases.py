"""Fixture: pragma grammar — suppression, missing reasons, unknown ids.

Line numbers matter to tests/test_lint/test_framework.py; edit with care.
"""

import time


def suppressed_trailing():
    t0 = time.time()   # lint-ok(timing-hygiene): host-only fixture clock
    return t0


def suppressed_comment_line():
    # lint-ok(timing-hygiene): comment-only pragma applies to the
    # next code line — long reasons live up here
    t1 = time.time()
    return t1


def reasonless():
    t2 = time.time()   # lint-ok(timing-hygiene):
    return t2


def unknown_pass():
    t3 = time.time()   # lint-ok(not-a-pass): suppresses nothing real
    return t3


def legacy_pragma():
    t4 = time.time()   # timing-ok: legacy spelling still honored
    return t4


def unsuppressed():
    t5 = time.time()
    return t5
