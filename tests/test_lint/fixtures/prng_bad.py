"""Fixture: PRNG-key reuse — two consumers, and a loop without rebind."""

import jax


def double_consume(x):
    key = jax.random.PRNGKey(0)
    noise = jax.random.normal(key, x.shape)
    more = jax.random.normal(key, x.shape)   # BUG: same key, same draws
    return noise + more


def loop_reuse(xs):
    key = jax.random.PRNGKey(0)
    out = []
    for x in xs:
        out.append(jax.random.normal(key, x.shape))   # BUG: every pass
    return out
