"""Fixture: implicit device→host coercions on jitted results."""

from functools import partial

import jax
import numpy as np


@partial(jax.jit, static_argnames=("n",))
def run_chunk(state, key, n):
    return state, {"loss": state}


def chunk_loop(state, key, steps):
    series = []
    for _ in range(steps):
        state, stats = run_chunk(state, key, 8)
        series.append(float(stats["loss"]))       # BUG: blocking fetch
        series.append(np.asarray(stats["loss"]))  # BUG: blocking fetch
        step = int(state)                          # BUG: blocking fetch
    return series, step
