"""Fixture: the blocking-fetch idiom — one device_get per boundary."""

from functools import partial

import jax
import numpy as np


@partial(jax.jit, static_argnames=("n",))
def run_chunk(state, key, n):
    return state, {"loss": state}


def chunk_loop(state, key, steps):
    series = []
    for _ in range(steps):
        state, stats = run_chunk(state, key, 8)
        fetched = jax.device_get({"stats": stats, "step": state})
        series.append(float(fetched["stats"]["loss"]))
        series.append(np.asarray(fetched["stats"]["loss"]))
        step = int(fetched["step"])
    return series, step
