"""async-blocking BAD fixture: every loop-stall shape the pass must trip.

The incident this family pins is the PR 10 serving plane: every
connection is a coroutine on ONE event loop, so a single synchronous
sleep/subprocess/device fetch stalls every in-flight request at once.
"""

import asyncio
import subprocess
import time

import jax


def _drain_queue(batch):
    """A sync helper that blocks — the interprocedural chain target."""
    time.sleep(0.01)
    return batch


async def handler_direct(request):
    time.sleep(0.05)                       # BAD: sleep on the loop
    return request


async def handler_via_helper(batch):
    out = _drain_queue(batch)              # BAD: blocking chain (helper sleeps)
    return out


async def handler_subprocess(cmd):
    return subprocess.run(cmd)             # BAD: child-wait on the loop


async def handler_device_fetch(outputs):
    fetched = jax.device_get(outputs)      # BAD: implicit device sync
    return fetched


async def handler_future(fut):
    value = fut.result()                   # BAD: Future.result deadlock shape
    return value


async def _probe(replica):
    return replica


async def handler_discarded_coroutine(replica):
    _probe(replica)                        # BAD: coroutine object discarded
    return replica
