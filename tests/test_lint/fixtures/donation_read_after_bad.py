"""Fixture: read of a donated argument after the donating call."""

from functools import partial

import jax


@partial(jax.jit, donate_argnames=("state", "history"))
def run_chunk(state, history, key, num_epochs):
    return state, history


def bad_read(state, history, key):
    new_state, new_history = run_chunk(state, history, key, 8)
    loss = history["loss"]   # BUG: history's buffer was donated above
    return new_state, new_history, loss
