"""Fixture: thread-target mutation in a class that holds a lock — the
pass trusts lock-holding classes (locking correctness is not decidable)."""

import threading


class LockedEmitter:
    def __init__(self):
        self.seq = 0
        self._lock = threading.Lock()
        self.thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while True:
            with self._lock:
                self.seq += 1
