"""Committed fixture: the EXACT PR 4 async-save/donated-buffer bug shape.

``train/checkpoint.py`` used to do this before the fault drills caught
it (docs/robustness.md "Async save vs. donation"): an orbax-style
manager's async ``save`` reads the chunk's output buffers zero-copy in a
background thread, while the NEXT ``run_chunk`` call's ``donate_argnames``
donation reuses those same buffers for its outputs — the step lands on
disk holding a later epoch's bytes. The donation-safety pass must flag
the ``manager.save`` line (see tests/test_lint/test_passes.py).
"""

from functools import partial

import jax


@partial(jax.jit, donate_argnames=("state", "history"))
def run_chunk(state, history, key, num_epochs):
    return state, history


def train(manager, state, history, key, steps):
    for step in range(steps):
        state, history = run_chunk(state, history, key, 64)
        # BUG: async save reads `state`/`history` zero-copy while the next
        # iteration's donation reuses the same memory
        manager.save(step, args={"state": state, "history": history})
    return state, history
