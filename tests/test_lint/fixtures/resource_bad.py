"""resource-lifecycle BAD fixture: leaked handles in every shape the
pass must trip — the fd-exhaustion bug class the chaos drills find
hours later as EMFILE.
"""

import socket
import subprocess
import threading
import multiprocessing


def leaky_popen(cmd):
    proc = subprocess.Popen(cmd)           # BAD: never waited/terminated
    return 0                               # (and not returned either)


def leaky_pipe():
    parent, child = multiprocessing.Pipe()  # BAD x2: neither side closed
    return 0


def leaky_socket(host):
    sock = socket.create_connection((host, 80))   # BAD: never closed
    sock.sendall(b"ping")
    return 0


def leaky_thread(target):
    worker = threading.Thread(target=target)      # BAD: non-daemon, no join
    worker.start()
    return 0


def factory(cmd):
    """Returns a LIVE resource — the caller owns it now (summary)."""
    return subprocess.Popen(cmd)


def leaky_via_factory(cmd):
    proc = factory(cmd)                    # BAD: factory's resource dropped
    return 0


class LeakyOwner:
    """The self-attribute shape: a class that creates a worker process
    and has NO method that could ever end it."""

    def __init__(self, ctx, spec):
        self._proc = ctx.Process(target=spec)     # BAD: no closer anywhere
        self._proc.start()

    def alive(self):
        return self._proc.is_alive()
