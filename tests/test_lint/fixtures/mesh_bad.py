"""mesh-consistency BAD fixture: every shape the pass must trip.

Line numbers matter to tests only by content (conftest.line_of); each
bad site is labeled. The mesh here is the 2D sweep mesh the ROADMAP's
pjit refactor builds — ``Mesh(devices, ("sweep", "data"))`` — so the
pass has project-local mesh facts to check specs against.
"""

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

import numpy as np


def make_mesh():
    devices = np.asarray(jax.devices()).reshape(-1, 1)
    return Mesh(devices, ("sweep", "data"))


def make_dup_mesh():
    devices = np.asarray(jax.devices()).reshape(-1, 1)
    return Mesh(devices, ("sweep", "sweep"))            # BAD: duplicate axis


def shard_states(mesh, states):
    # BAD: 'model' is not an axis of any mesh this project builds
    return jax.device_put(states, NamedSharding(mesh, P("model")))


def shard_axis_twice(mesh, states):
    # BAD: one mesh axis cannot shard two array dimensions
    return jax.device_put(states, NamedSharding(mesh, P("sweep", "sweep")))


def two_arg_kernel(block, scale):
    return block * scale


def bad_shard_map(mesh, x):
    # BAD: one in_spec for a two-argument function
    mapped = shard_map(two_arg_kernel, mesh=mesh,
                       in_specs=(P("sweep"),),
                       out_specs=P("sweep"))
    return mapped(x)


def step(states, batch):
    return states


# BAD: `states` is donated but its in_sharding P("sweep") != out P("data")
bad_donating_step = jax.jit(
    step,
    donate_argnums=(0,),
    in_shardings=(P("sweep"), P("data")),
    out_shardings=(P("data"),),
)


class SweepCheckpointer:
    """The reshard-on-restore bug shape: save constrains the stacked tree
    over 'sweep', restore constrains it over 'data'."""

    def __init__(self, mesh):
        self.mesh = mesh

    def save(self, manager, step_index, states):
        placed = jax.device_put(states, NamedSharding(self.mesh, P("sweep")))
        manager.save(step_index, placed)

    def restore(self, manager, step_index):                 # BAD: spec drift
        states = manager.restore(step_index)
        return jax.device_put(states, NamedSharding(self.mesh, P("data")))
