"""mesh-consistency GOOD fixture: the same shapes done right — axes the
mesh defines, arity-matched shard_map specs, donation with aligned
shardings, save/restore reading ONE spec."""

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

import numpy as np

SWEEP_AXIS = "sweep"
DATA_AXIS = "data"

#: The one spec both checkpoint directions read (the fix for the
#: reshard-on-restore drift shape).
REPLICA_SPEC = P(SWEEP_AXIS)


def make_mesh():
    devices = np.asarray(jax.devices()).reshape(-1, 1)
    return Mesh(devices, (SWEEP_AXIS, DATA_AXIS))


def shard_states(mesh, states):
    return jax.device_put(states, NamedSharding(mesh, P(SWEEP_AXIS)))


def shard_batches(mesh, batch):
    return jax.device_put(batch, NamedSharding(mesh, P(SWEEP_AXIS, DATA_AXIS)))


def shard_stacked(mesh, stacked):
    # a 3D array on the 2D mesh: spec length is the ARRAY's rank — the
    # trailing None (replicated dim) must not trip a rank check
    return jax.device_put(
        stacked, NamedSharding(mesh, P(SWEEP_AXIS, DATA_AXIS, None)))


def two_arg_kernel(block, scale):
    return block * scale


def good_shard_map(mesh, x, scale):
    mapped = shard_map(two_arg_kernel, mesh=mesh,
                       in_specs=(P(SWEEP_AXIS), P()),
                       out_specs=P(SWEEP_AXIS))
    return mapped(x, scale)


def step(states, batch):
    return states


good_donating_step = jax.jit(
    step,
    donate_argnums=(0,),
    in_shardings=(P(SWEEP_AXIS), P(DATA_AXIS)),
    out_shardings=(P(SWEEP_AXIS),),
)


class SweepCheckpointer:
    def __init__(self, mesh):
        self.mesh = mesh

    def save(self, manager, step_index, states):
        placed = jax.device_put(states, NamedSharding(self.mesh, REPLICA_SPEC))
        manager.save(step_index, placed)

    def restore(self, manager, step_index):
        states = manager.restore(step_index)
        return jax.device_put(states, NamedSharding(self.mesh, REPLICA_SPEC))
