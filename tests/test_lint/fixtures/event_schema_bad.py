"""Fixture: event-schema drift at emit call sites."""


def emit_bad(telemetry, writer):
    telemetry.emit("chnk", epoch=1, steps=10, seconds=0.5)  # typo'd kind
    writer.emit("chunk", epoch=1)                  # missing steps/seconds
    telemetry.mitigation(mtype="x", mtyp="typo")   # unknown field
    writer.heartbeat(beat=1, epoch=0, phase="boundary",
                     chunk_elapsed_s=1.0)          # field docs invented
