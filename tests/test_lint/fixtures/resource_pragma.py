"""resource-lifecycle PRAGMA fixture: a reviewed exception with a
reason — an intentionally orphaned double-fork daemon whose handle the
parent must NOT hold."""

import subprocess


def detach_daemon(cmd):
    # lint-ok(resource-lifecycle): deliberate double-fork detach — the
    # intermediate child exits immediately and init adopts the daemon;
    # holding (or waiting) the handle would defeat the detach
    subprocess.Popen(cmd, start_new_session=True)
    return 0
