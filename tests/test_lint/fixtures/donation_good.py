"""Fixture: the SAFE donation idioms — must lint clean.

Rebinding the donated names to the call's results (the ``x, y = f(x, y)``
idiom) resurrects them, and a ``jax.device_get`` host copy before the
save clears the device-buffer taint.
"""

from functools import partial

import jax


@partial(jax.jit, donate_argnames=("state", "history"))
def run_chunk(state, history, key, num_epochs):
    return state, history


def good_rebind(state, history, key):
    state, history = run_chunk(state, history, key, 8)
    return state, history["loss"]


def good_save(manager, state, history, key, steps):
    for step in range(steps):
        state, history = run_chunk(state, history, key, 64)
        snapshot = jax.device_get({"state": state, "history": history})
        manager.save(step, args=snapshot)
    return state, history


def good_fetch_before(state, history, key):
    # a REAL fetch (host copy) before the donating call — a bare
    # `history["loss"]` alias would die with the donation (the
    # overlap-alias shape, see overlap_alias_bad.py)
    last_loss = jax.device_get(history["loss"])
    state, history = run_chunk(state, history, key, 8)
    return state, history, last_loss
