"""mesh-consistency PRAGMA fixture: a reviewed exception suppressed with
a reason — an axis name that genuinely lives in another repo's mesh
(cross-repo serving import), which this project cannot see."""

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import numpy as np


def make_mesh():
    devices = np.asarray(jax.devices()).reshape(-1, 1)
    return Mesh(devices, ("sweep", "data"))


def shard_foreign(mesh, states):
    # lint-ok(mesh-consistency): 'tensor' is an axis of the upstream
    # serving repo's mesh; this helper only forwards the spec verbatim
    return jax.device_put(states, NamedSharding(mesh, P("tensor")))
