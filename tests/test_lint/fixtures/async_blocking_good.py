"""async-blocking GOOD fixture: the blessed escapes — await the async
equivalent, park blocking callables on an executor (passed, not
called), schedule coroutines as tasks."""

import asyncio
import functools
import time


def _drain_queue(batch):
    time.sleep(0.01)        # blocking, but only ever called OFF the loop
    return batch


async def handler_async_sleep(request):
    await asyncio.sleep(0.05)
    return request


async def handler_executor(batch):
    loop = asyncio.get_running_loop()
    # the blocking callable is PASSED to the executor, never called here
    out = await loop.run_in_executor(
        None, functools.partial(_drain_queue, batch))
    return out


async def _probe(replica):
    return replica


async def handler_task(replica):
    task = asyncio.create_task(_probe(replica))
    return await task


async def handler_awaited_chain(replica):
    return await _probe(replica)
