"""Committed regression fixture: the PR 10 prefork re-exec supervisor
bug shape (docs/serving.md, "Review hardening").

The incident: ``serve/prefork.py`` re-execs ``python -m dib_tpu serve``
workers with ``--prefork`` stripped from argv; the first strip_flag
missed argparse's prefix abbreviations, so ``--prefor 3`` survived into
the worker command and every worker became a supervisor of N more — a
recursive fork bomb. The decidable residue of that incident is the
supervisor's RESPWN loop: each heal cycle builds a re-exec command and
spawns a replacement worker. Binding the replacement to a bare local
and dropping it (instead of storing it where the shutdown fan-out can
reach it) leaks one pid + stdout pipe per respawn — under a crash loop
(exactly the fork-bomb aftermath) that is the fd-exhaustion curve the
chaos drills read as EMFILE. ``resource-lifecycle`` must keep flagging
this shape; ``tests/test_lint/test_passes.py`` pins it.
"""

import subprocess
import sys


def worker_cmd(argv, port):
    # the re-exec command: argv with the supervisor flag stripped (the
    # strip itself is prefork.strip_flag's job; this fixture pins what
    # the supervisor does with the spawned handle)
    return [sys.executable, "-m", "dib_tpu", "serve", *argv,
            "--port", str(port), "--reuse_port"]


def respawn_loop(argv, port, dead_indices):
    respawned = 0
    for _k in dead_indices:
        # BAD: the replacement worker's Popen handle is dropped on the
        # floor — SIGTERM fan-out and the final wait() can never reach
        # it, and each heal cycle leaks a pid + a stdout pipe fd
        proc = subprocess.Popen(worker_cmd(argv, port),
                                stdout=subprocess.PIPE, text=True)
        respawned += 1
    return respawned
