"""Fixture: disciplined key handling — must lint clean."""

import jax


def split_consumers(x):
    key = jax.random.PRNGKey(0)
    k_a, k_b = jax.random.split(key)
    return jax.random.normal(k_a, x.shape) + jax.random.normal(k_b, x.shape)


def loop_rebind(key, xs):
    out = []
    for i, x in enumerate(xs):
        key, k_draw = jax.random.split(key)
        out.append(jax.random.normal(k_draw, x.shape))
    return out


def fold_in_loop(key, xs):
    return [jax.random.normal(jax.random.fold_in(key, i), x.shape)
            for i, x in enumerate(xs)]
