"""Fixture: overlapped measurement dispatched on a bare ALIAS of donated
params — the raw-speed-PR bug shape (docs/performance.md "Overlapped
measurement"). `snap` is a view, not a copy: after `run_chunk` donates
`state`, the measurement reads buffers XLA is already reusing."""

from functools import partial

import jax


@partial(jax.jit, donate_argnames=("state", "history"))
def run_chunk(state, history, key, num_epochs):
    return state, history


def measure(params, key):
    return params, key


def bad_overlap(state, history, key):
    snap = state.params            # bare alias, NOT a copy
    state, history = run_chunk(state, history, key, 8)
    lower = measure(snap, key)     # BUG: snap aliases donated buffers
    return state, history, lower
