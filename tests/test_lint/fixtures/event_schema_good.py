"""Fixture: schema-conformant emission — must lint clean."""


def emit_good(telemetry, writer, other):
    telemetry.emit("chunk", epoch=1, steps=10, seconds=0.5, loss=0.1)
    writer.mitigation(mtype="divergence_rollback", epoch=3,
                      restored_epoch=2)
    telemetry.heartbeat(beat=1, epoch=0, phase="chunk", interval_s=10.0,
                        phase_elapsed_s=3.2)
    fields = {"loss": 0.1}
    writer.chunk(epoch=1, steps=10, seconds=0.5, **fields)  # splat: defer
    other.alert(rule=1, metric="x", wrong_field=True)  # not a writer name
