"""resource-lifecycle GOOD fixture: the blessed lifecycles — close in a
finally, hand the handle to an owner, join the worker, daemonize the
fire-and-forget beat thread, close the class's resources in close()."""

import socket
import subprocess
import threading
import multiprocessing


def waited_popen(cmd):
    proc = subprocess.Popen(cmd)
    try:
        return proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()


def handed_off_popen(cmd, registry):
    proc = subprocess.Popen(cmd)
    registry.adopt(proc)                   # escaped: the registry owns it
    return 0


def closed_pipe():
    parent, child = multiprocessing.Pipe()
    child.close()
    try:
        return parent.recv()
    finally:
        parent.close()


def with_socket(host):
    with socket.create_connection((host, 80)) as sock:
        sock.sendall(b"ping")
    return 0


def joined_thread(target):
    worker = threading.Thread(target=target)
    worker.start()
    worker.join(timeout=5.0)
    return 0


def daemon_beat_thread(target):
    # fire-and-forget by declared intent: the interpreter reaps daemons
    beat = threading.Thread(target=target, daemon=True)
    beat.start()
    return 0


def factory(cmd):
    return subprocess.Popen(cmd)


def caller_closes_factory_resource(cmd):
    proc = factory(cmd)
    try:
        return proc.wait(timeout=60)
    finally:
        proc.terminate()


class ManagedOwner:
    """The serve/pool.py WorkerReplica contract: the class that creates
    the process/pipe is the class whose close() ends them."""

    def __init__(self, ctx, spec):
        parent, child = ctx.Pipe()
        self._proc = ctx.Process(target=spec, args=(child,))
        self._proc.start()
        child.close()
        self._conn = parent

    def close(self):
        self._conn.close()
        self._proc.terminate()
        self._proc.join(timeout=5.0)
