"""CLI contract (exit codes, JSON shape) and THE tier-1 gates: the full
tree lints clean, and the committed PR 4 fixture still trips the
donation-safety pass."""

import json
import os
import subprocess
import sys

from tests.test_lint.conftest import FIXTURES, REPO

BAD_FIXTURE = os.path.join(FIXTURES, "donation_async_save_bad.py")
GOOD_FIXTURE = os.path.join(FIXTURES, "donation_good.py")


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "dib_tpu", "lint", *args],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


# ----------------------------------------------------------- tier-1 gates
def test_full_tree_lints_clean():
    """The zero-findings baseline (ISSUE 7 acceptance): every pass over
    dib_tpu/ + scripts/, every suppression carrying a reason. The
    committed pytest gate mirroring the old hygiene-script gates."""
    from dib_tpu.analysis import run_passes

    findings = run_passes(root=REPO)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_committed_pr4_fixture_still_trips_the_pass():
    """Regression: the committed bug-shape fixture must keep tripping
    donation-safety — if a refactor of the pass stops flagging it, the
    exact incident the pass exists for has gone invisible again."""
    from dib_tpu.analysis.core import load_module, get_pass

    module = load_module(
        BAD_FIXTURE, "tests/test_lint/fixtures/donation_async_save_bad.py")
    findings = get_pass("donation-safety").check_module(module)
    assert findings, "the PR 4 fixture no longer trips donation-safety"


# -------------------------------------------------------- subprocess CLI
def test_cli_exit_0_on_clean_path():
    proc = _run_cli(GOOD_FIXTURE)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "dib-lint: ok" in proc.stdout


def test_cli_exit_1_on_findings():
    proc = _run_cli(BAD_FIXTURE)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "[donation-safety]" in proc.stdout
    assert "donation_async_save_bad.py" in proc.stdout


def test_cli_exit_2_on_bad_usage():
    proc = _run_cli("--select", "no-such-pass")
    assert proc.returncode == 2
    assert "no-such-pass" in proc.stderr
    proc = _run_cli("does/not/exist.py")
    assert proc.returncode == 2
    assert "no such path" in proc.stderr
    # subcommand displaced by a flag: the cli.py ordering guard
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "--seed", "1", "lint"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 2
    assert "must come first" in proc.stderr


def test_cli_json_shape_is_stable():
    proc = _run_cli("--json", BAD_FIXTURE)
    assert proc.returncode == 1
    report = json.loads(proc.stdout)
    assert report["version"] == 1
    assert sorted(report) == ["findings", "passes", "summary", "version"]
    assert report["summary"]["findings"] == len(report["findings"]) >= 1
    finding = report["findings"][0]
    assert sorted(finding) == ["line", "message", "pass", "path"]
    assert finding["pass"] == "donation-safety"
    assert finding["path"].endswith("donation_async_save_bad.py")
    assert isinstance(finding["line"], int)
    ids = [p["id"] for p in report["passes"]]
    assert ids == sorted(ids) and "donation-safety" in ids
    for p in report["passes"]:
        assert sorted(p) == ["description", "id", "incident", "scope"]


def test_cli_select_filters_passes():
    # the bad donation fixture is clean under the prng pass alone
    proc = _run_cli("--select", "prng-reuse", BAD_FIXTURE)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_list_names_every_pass():
    proc = _run_cli("--list")
    assert proc.returncode == 0
    for pass_id in ("donation-safety", "prng-reuse", "host-sync",
                    "thread-shared-state", "event-schema",
                    "timing-hygiene", "exception-hygiene",
                    "mesh-consistency", "async-blocking",
                    "resource-lifecycle"):
        assert f"{pass_id}:" in proc.stdout
    assert "prevents:" in proc.stdout
