"""Framework tests: pragma grammar, allowlists, registry, runner."""

import os

import pytest

from tests.test_lint.conftest import FIXTURES, REPO, line_of


# ------------------------------------------------------------- registry
def test_registry_has_the_ten_passes():
    from dib_tpu.analysis import all_passes

    ids = [p.id for p in all_passes()]
    assert ids == sorted(ids)
    for expected in ("donation-safety", "prng-reuse", "host-sync",
                     "thread-shared-state", "event-schema",
                     "timing-hygiene", "exception-hygiene",
                     # the ISSUE 11 pass family for the upcoming
                     # mesh/asyncio subsystems
                     "mesh-consistency", "async-blocking",
                     "resource-lifecycle"):
        assert expected in ids


def test_every_pass_names_its_incident():
    from dib_tpu.analysis import all_passes

    for lint in all_passes():
        assert lint.description
        assert lint.incident, f"{lint.id}: a pass must name the runtime " \
                              "incident it prevents"


def test_register_rejects_reasonless_allowlist_and_dup_ids():
    from dib_tpu.analysis.core import LintPass, register

    with pytest.raises(ValueError, match="justification"):
        @register
        class BadAllowlist(LintPass):
            id = "tmp-bad-allowlist"
            description = "x"
            incident = "y"
            allowlist = {"dib_tpu/foo.py": ""}

    with pytest.raises(ValueError, match="duplicate"):
        @register
        class DupId(LintPass):
            id = "timing-hygiene"
            description = "x"
            incident = "y"

    with pytest.raises(ValueError, match="reserved"):
        @register
        class ReservedId(LintPass):
            id = "pragma"
            description = "x"
            incident = "y"


# -------------------------------------------------------------- pragmas
def test_pragma_trailing_and_comment_line_suppress(load_fixture):
    from dib_tpu.analysis.core import get_pass

    module = load_fixture("pragma_cases.py")
    lint = get_pass("timing-hygiene")
    flagged = {f.line for f in lint.check_module(module)
               if not module.suppressed(lint.id, f.line)}
    lines = {name: line_of(module, name) for name in
             ("t0 =", "t1 =", "t2 =", "t3 =", "t4 =", "t5 =")}
    assert lines["t0 ="] not in flagged      # trailing pragma
    assert lines["t1 ="] not in flagged      # comment-line pragma
    assert lines["t4 ="] not in flagged      # legacy timing-ok
    assert lines["t2 ="] in flagged          # reasonless: NOT suppressed
    assert lines["t3 ="] in flagged          # wrong pass id: NOT suppressed
    assert lines["t5 ="] in flagged          # no pragma at all


def test_reasonless_and_unknown_pragmas_are_findings(load_fixture):
    module = load_fixture("pragma_cases.py")
    assert any("reason" in f.message for f in module.pragma_findings)
    from dib_tpu.analysis.core import run_passes

    findings = run_passes(
        root=REPO,
        files=[(os.path.join(FIXTURES, "pragma_cases.py"),
                "tests/test_lint/fixtures/pragma_cases.py")],
        select=["exception-hygiene"],   # pragma findings ignore select
    )
    pragma = [f for f in findings if f.pass_id == "pragma"]
    assert any("reason" in f.message for f in pragma)
    assert any("unknown pass 'not-a-pass'" in f.message for f in pragma)


def test_stacked_comment_pragmas_merge_at_one_anchor(tmp_path):
    """Review regression: two comment-only pragmas above one code line
    both apply — the later must not silently overwrite the earlier."""
    from dib_tpu.analysis.core import load_module

    src = (
        "import time\n"
        "def f():\n"
        "    # lint-ok(timing-hygiene): host-only clock\n"
        "    # lint-ok(exception-hygiene): also justified\n"
        "    t = time.time()\n"
        "    return t\n"
    )
    path = tmp_path / "stacked.py"
    path.write_text(src)
    module = load_module(str(path), "stacked.py")
    assert module.suppressed("timing-hygiene", 5)
    assert module.suppressed("exception-hygiene", 5)


def test_docstring_mention_of_grammar_is_not_a_pragma():
    """core.py's own docstrings spell the grammar; tokenize-based comment
    extraction must not read them as suppressions."""
    from dib_tpu.analysis.core import load_module

    path = os.path.join(REPO, "dib_tpu", "analysis", "core.py")
    module = load_module(path, "dib_tpu/analysis/core.py")
    assert not module.pragma_findings
    for pragma in module.pragmas.values():
        assert "<pass>" not in pragma.passes


# ----------------------------------------------------------- the runner
def test_run_passes_unknown_select_raises():
    from dib_tpu.analysis.core import run_passes

    with pytest.raises(KeyError, match="no-such-pass"):
        run_passes(root=REPO, select=["no-such-pass"], files=[])


def test_scope_and_target_modules():
    from dib_tpu.analysis.core import get_pass

    timing = get_pass("timing-hygiene")
    assert timing.applies_to("dib_tpu/train/loop.py")
    assert not timing.applies_to("scripts/bench_driver.py")
    host = get_pass("host-sync")
    assert host.applies_to("dib_tpu/train/loop.py")
    # the serving hot path joined the target set with ISSUE 10
    assert host.applies_to("dib_tpu/serve/engine.py")
    assert not host.applies_to("dib_tpu/telemetry/report.py")


def test_statement_linearization_and_assigned_names():
    import ast

    from dib_tpu.analysis.core import (
        assigned_names,
        statements_in_order,
        stmt_expr_roots,
    )

    src = (
        "def f(x):\n"
        "    while x > 0:\n"
        "        a, b = g(x)\n"
        "        with h() as c:\n"
        "            d = i(c)\n"
        "    return a\n"
    )
    fn = ast.parse(src).body[0]
    stmts = statements_in_order(fn)
    kinds = [type(s).__name__ for s in stmts]
    assert kinds == ["While", "Assign", "With", "Assign", "Return"]
    # compound statements own only their headers
    assert [type(r).__name__ for r in stmt_expr_roots(stmts[0])] == ["Compare"]
    assert assigned_names(stmts[1]) == {"a", "b"}
    assert assigned_names(stmts[2]) == {"c"}
