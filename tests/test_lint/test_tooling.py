"""CI-grade tooling tests: the incremental cache (`lint --changed`),
SARIF 2.1.0 output, and the suppression-budget gate (`lint --stats` vs
LINT_BUDGET.json). THE tier-1 acceptance pins live here:

- touch one file → only its reverse-dependency closure re-analyzes, and
  the findings are BIT-IDENTICAL to a cold full run;
- an incremental re-lint analyzes measurably fewer files than the cold
  run (asserted via analyzed-file counts, never wall clock);
- the SARIF report carries every 2.1.0 required property;
- `--stats` exits 1 when a pass exceeds its committed budget, and when
  budget slack is held without a justification row (the shrink-only
  ratchet); the committed LINT_BUDGET.json is green.
"""

import json
import os
import subprocess
import sys

import pytest

from tests.test_lint.conftest import FIXTURES, REPO

BAD_FIXTURE = os.path.join(FIXTURES, "donation_async_save_bad.py")


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "dib_tpu", "lint", *args],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


# A tiny synthetic lint tree: `a` is imported by `b`, which is imported
# by `c`; `lone` imports nothing and nothing imports it. The bad sleep
# inside a coroutine in `lone` proves cached findings replay verbatim.
_TREE = {
    "dib_tpu/__init__.py": "",
    "dib_tpu/a.py": "def fa(x):\n    return x\n",
    "dib_tpu/b.py": ("from dib_tpu.a import fa\n"
                     "def fb(x):\n    return fa(x)\n"),
    "dib_tpu/c.py": ("from dib_tpu.b import fb\n"
                     "def fc(x):\n    return fb(x)\n"),
    "dib_tpu/lone.py": ("import time\n"
                        "async def handler(x):\n"
                        "    time.sleep(0.1)\n"
                        "    return x\n"),
}


def _write_tree(root, files):
    for rel, src in files.items():
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(src)


@pytest.fixture
def tree_root(tmp_path):
    _write_tree(str(tmp_path), _TREE)
    return str(tmp_path)


def _findings_key(findings):
    return [(f.pass_id, f.path, f.line, f.message) for f in findings]


# --------------------------------------------------------------- cache
def test_cold_run_analyzes_everything_and_primes_cache(tree_root):
    from dib_tpu.analysis.cache import cache_path, run_tree

    result = run_tree(root=tree_root)
    assert result.analyzed_count == result.total_files == len(_TREE)
    assert result.cached == []
    # project-level checks (event-schema docs drift) also run on the
    # synthetic tree; the per-module finding is the coroutine sleep
    per_module = [f for f in result.findings
                  if not f.path.startswith("docs/")]
    assert [f.pass_id for f in per_module] == ["async-blocking"]
    assert os.path.exists(cache_path(tree_root))


def test_warm_run_analyzes_nothing_and_replays_bit_identical(tree_root):
    from dib_tpu.analysis.cache import run_tree

    cold = run_tree(root=tree_root)
    warm = run_tree(root=tree_root, changed=True)
    assert warm.analyzed_count == 0
    assert len(warm.cached) == len(_TREE)
    assert _findings_key(warm.findings) == _findings_key(cold.findings)


def test_touch_one_file_reanalyzes_exactly_the_reverse_closure(tree_root):
    """THE incremental acceptance pin: touching `a` re-analyzes a, b, c
    (the reverse-dependency closure) and nothing else; results are
    bit-identical to a fresh cold run; the analyzed-file count is
    measurably smaller than the cold run's."""
    from dib_tpu.analysis.cache import cache_path, run_tree

    cold = run_tree(root=tree_root)
    with open(os.path.join(tree_root, "dib_tpu/a.py"), "a") as f:
        f.write("\n# a trailing comment changes the content hash\n")
    incremental = run_tree(root=tree_root, changed=True)
    assert set(incremental.analyzed) == {
        "dib_tpu/a.py", "dib_tpu/b.py", "dib_tpu/c.py"}
    assert "dib_tpu/lone.py" in incremental.cached
    assert incremental.analyzed_count < cold.analyzed_count
    # bit-identity vs a fresh cold run over the SAME (touched) tree
    os.remove(cache_path(tree_root))
    fresh = run_tree(root=tree_root)
    assert _findings_key(incremental.findings) == _findings_key(
        fresh.findings)


def test_edit_that_changes_findings_propagates_through_cache(tree_root):
    from dib_tpu.analysis.cache import run_tree

    run_tree(root=tree_root)   # prime
    with open(os.path.join(tree_root, "dib_tpu/lone.py"), "w") as f:
        f.write("import asyncio\n"
                "async def handler(x):\n"
                "    await asyncio.sleep(0.1)\n"
                "    return x\n")
    incremental = run_tree(root=tree_root, changed=True)
    assert incremental.analyzed == ["dib_tpu/lone.py"]
    assert [f for f in incremental.findings
            if not f.path.startswith("docs/")] == []


def test_analyzer_change_invalidates_cache(tree_root, monkeypatch):
    from dib_tpu.analysis import cache as cache_mod

    cache_mod.run_tree(root=tree_root)   # prime
    monkeypatch.setattr(cache_mod, "analyzer_fingerprint",
                        lambda root=None: "a-different-analyzer")
    result = cache_mod.run_tree(root=tree_root, changed=True)
    assert result.analyzed_count == len(_TREE)   # cold: cache discarded


def test_select_never_reads_or_writes_cache(tree_root):
    from dib_tpu.analysis.cache import cache_path, run_tree

    run_tree(root=tree_root, select=["timing-hygiene"])
    assert not os.path.exists(cache_path(tree_root))


def test_real_tree_incremental_matches_run_passes():
    """run_tree over the committed repo agrees with run_passes (the
    zero-findings gate reads either), and a warm --changed run
    re-analyzes nothing."""
    from dib_tpu.analysis import run_passes
    from dib_tpu.analysis.cache import run_tree

    cold = run_tree(root=REPO)
    assert _findings_key(cold.findings) == _findings_key(
        run_passes(root=REPO))
    warm = run_tree(root=REPO, changed=True)
    assert warm.analyzed_count == 0
    assert _findings_key(warm.findings) == _findings_key(cold.findings)


def test_cli_changed_flags_usage(tree_root):
    proc = _run_cli("--changed", BAD_FIXTURE)
    assert proc.returncode == 2
    assert "full-tree" in proc.stderr
    proc = _run_cli("--changed", "--select", "prng-reuse")
    assert proc.returncode == 2
    assert "--select" in proc.stderr


# --------------------------------------------------------------- SARIF
def test_sarif_report_carries_required_properties():
    proc = _run_cli("--sarif", BAD_FIXTURE)
    assert proc.returncode == 1
    report = json.loads(proc.stdout)
    # SARIF 2.1.0 required properties (the subset consumers validate)
    assert report["version"] == "2.1.0"
    assert report["$schema"].endswith("sarif-schema-2.1.0.json")
    assert isinstance(report["runs"], list) and report["runs"]
    run = report["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "dib-lint"
    rule_ids = {rule["id"] for rule in driver["rules"]}
    assert "donation-safety" in rule_ids
    assert "pragma" in rule_ids          # grammar findings surface too
    for rule in driver["rules"]:
        assert rule["shortDescription"]["text"]
    assert run["results"], "the bad fixture must yield results"
    for result in run["results"]:
        assert result["ruleId"] in rule_ids
        assert result["message"]["text"]
        loc = result["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith(".py")
        assert isinstance(loc["region"]["startLine"], int)


def test_sarif_and_json_are_exclusive():
    proc = _run_cli("--sarif", "--json", BAD_FIXTURE)
    assert proc.returncode == 2


# --------------------------------------------------------------- stats
def _stats_root(tmp_path, budget: dict | None, pragmas: int = 2):
    lines = ["import time", "def f():"]
    for i in range(pragmas):
        lines.append(f"    t{i} = time.time()   "
                     "# lint-ok(timing-hygiene): host-only driver clock")
    lines.append("    return 0")
    _write_tree(str(tmp_path), {"dib_tpu/__init__.py": "",
                                "dib_tpu/mod.py": "\n".join(lines) + "\n"})
    if budget is not None:
        with open(os.path.join(str(tmp_path), "LINT_BUDGET.json"), "w") as f:
            json.dump(budget, f)
    return str(tmp_path)


def _budget(rows, justifications=None):
    return {"version": 1, "budget": rows,
            "justifications": justifications or {}}


def test_stats_green_at_budget(tmp_path):
    from dib_tpu.analysis.cli import lint_main

    root = _stats_root(tmp_path, _budget({"timing-hygiene": 2}))
    assert lint_main(["--stats", "--root", root]) == 0


def test_stats_exit_1_over_budget(tmp_path, capsys):
    from dib_tpu.analysis.cli import lint_main

    root = _stats_root(tmp_path, _budget({"timing-hygiene": 1}))
    assert lint_main(["--stats", "--root", root]) == 1
    assert "BUDGET VIOLATION" in capsys.readouterr().out


def test_stats_exit_1_on_unjustified_slack(tmp_path):
    """The shrink-only ratchet: a budget held ABOVE the actual count
    with no justification row fails — removing a pragma must ratchet
    the budget down in the same commit."""
    from dib_tpu.analysis.cli import lint_main

    root = _stats_root(tmp_path, _budget({"timing-hygiene": 5}))
    assert lint_main(["--stats", "--root", root]) == 1
    root2 = _stats_root(tmp_path / "justified", _budget(
        {"timing-hygiene": 5},
        {"timing-hygiene": "headroom for the planned bench refactor"}))
    assert lint_main(["--stats", "--root", root2]) == 0


def test_stats_exit_2_on_malformed_budget(tmp_path):
    from dib_tpu.analysis.cli import lint_main

    root = _stats_root(tmp_path, {"version": 99, "budget": {}})
    assert lint_main(["--stats", "--root", root]) == 2
    root2 = _stats_root(tmp_path / "unknown",
                        _budget({"not-a-pass": 1}))
    assert lint_main(["--stats", "--root", root2]) == 2


def test_stats_json_shape(tmp_path, capsys):
    from dib_tpu.analysis.cli import lint_main

    root = _stats_root(tmp_path, _budget({"timing-hygiene": 2}))
    assert lint_main(["--stats", "--json", "--root", root]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["suppressions"] == {"timing-hygiene": 2}
    assert report["total"] == 2
    assert report["budget"] == {"timing-hygiene": 2}
    assert report["violations"] == []


def test_committed_budget_is_green_and_exact():
    """The committed LINT_BUDGET.json matches the tree's actual counts
    exactly (no over-budget pass, no unjustified slack) — the
    telemetry-check-style subprocess gate."""
    proc = _run_cli("--stats")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "suppression budget: ok" in proc.stdout


def test_stats_is_its_own_mode():
    proc = _run_cli("--stats", "--changed")
    assert proc.returncode == 2
    proc = _run_cli("--stats", BAD_FIXTURE)
    assert proc.returncode == 2


# ------------------------------------------------- check_run_artifacts
def test_check_run_artifacts_runs_incremental_lint_and_budget(tmp_path):
    """The standalone gate path uses the --changed engine and folds the
    suppression budget in (one command covers lint + stats)."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import check_run_artifacts

        problems, detail = check_run_artifacts.run_lint(REPO)
        assert problems == []
        assert "analyzed" in detail and "cache" in detail
    finally:
        sys.path.remove(os.path.join(REPO, "scripts"))


# ------------------------------------------- review-hardening regressions
def test_global_mesh_fact_change_invalidates_whole_cache(tmp_path):
    """Review regression: mesh axis facts are PROJECT-GLOBAL (collected
    from every module, no import edge required), so renaming an axis in
    one module must not let an unrelated module replay stale spec
    findings — the whole cache is discarded instead."""
    from dib_tpu.analysis.cache import run_tree

    files = dict(_TREE)
    files["dib_tpu/meshes.py"] = (
        "from jax.sharding import Mesh\n"
        "def make(devices):\n"
        "    return Mesh(devices, ('sweep', 'data'))\n")
    files["dib_tpu/user.py"] = (   # does NOT import meshes.py
        "from jax.sharding import NamedSharding, PartitionSpec as P\n"
        "def place(mesh, states):\n"
        "    import jax\n"
        "    return jax.device_put(states, NamedSharding(mesh, P('sweep')))\n")
    _write_tree(str(tmp_path), files)
    root = str(tmp_path)
    clean = run_tree(root=root)
    assert not any(f.pass_id == "mesh-consistency" for f in clean.findings)
    # rename the axis out from under user.py's spec
    with open(os.path.join(root, "dib_tpu/meshes.py"), "w") as f:
        f.write("from jax.sharding import Mesh\n"
                "def make(devices):\n"
                "    return Mesh(devices, ('beta', 'data'))\n")
    incremental = run_tree(root=root, changed=True)
    assert incremental.analyzed_count == incremental.total_files  # cold
    mesh_findings = [f for f in incremental.findings
                     if f.pass_id == "mesh-consistency"]
    assert any("'sweep'" in f.message and f.path == "dib_tpu/user.py"
               for f in mesh_findings)


def test_no_cache_disables_reads_too(tree_root):
    """Review regression: --no-cache must IGNORE an existing (possibly
    stale/corrupt) cache, not just skip writing one."""
    import json as json_mod

    from dib_tpu.analysis.cache import cache_path, run_tree

    run_tree(root=tree_root)   # prime
    with open(cache_path(tree_root)) as f:
        payload = json_mod.load(f)
    some_rel = "dib_tpu/a.py"
    payload["files"][some_rel]["findings"] = [
        ["pragma", some_rel, 1, "planted stale finding"]]
    with open(cache_path(tree_root), "w") as f:
        json_mod.dump(payload, f)
    poisoned = run_tree(root=tree_root, changed=True)
    assert any("planted" in f.message for f in poisoned.findings)
    bypassed = run_tree(root=tree_root, changed=True,
                        read_cache=False, write_cache=False)
    assert bypassed.analyzed_count == len(_TREE)
    assert not any("planted" in f.message for f in bypassed.findings)


def test_malformed_cache_rows_degrade_to_fresh_analysis(tree_root):
    """Review regression: a cache that parses as JSON but carries a
    mangled finding row re-analyzes that file instead of crashing the
    run (the corrupt-cache contract)."""
    import json as json_mod

    from dib_tpu.analysis.cache import cache_path, run_tree

    cold = run_tree(root=tree_root)
    with open(cache_path(tree_root)) as f:
        payload = json_mod.load(f)
    payload["files"]["dib_tpu/lone.py"]["findings"] = [["wrong-arity"]]
    with open(cache_path(tree_root), "w") as f:
        json_mod.dump(payload, f)
    recovered = run_tree(root=tree_root, changed=True)
    assert "dib_tpu/lone.py" in recovered.analyzed
    assert _findings_key(recovered.findings) == _findings_key(
        cold.findings)


def test_check_run_artifacts_reports_malformed_budget_as_violation(
        tmp_path):
    """Review regression: a malformed committed LINT_BUDGET.json is a
    formatted gate violation from run_lint, not a traceback."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import check_run_artifacts

        _write_tree(str(tmp_path), _TREE)
        with open(os.path.join(str(tmp_path), "LINT_BUDGET.json"),
                  "w") as f:
            json.dump({"version": 99, "budget": {}}, f)
        problems, _detail = check_run_artifacts.run_lint(str(tmp_path))
        assert any("version" in p for p in problems)
    finally:
        sys.path.remove(os.path.join(REPO, "scripts"))


def test_mangled_files_payload_degrades_to_cold_run(tree_root):
    """Review regression: a JSON-valid cache whose `files` field is null
    (or holds non-dict entries) is corruption like any other — a cold
    run, never a traceback."""
    import json as json_mod

    from dib_tpu.analysis.cache import cache_path, run_tree

    run_tree(root=tree_root)   # prime
    for mangle in (None, {"dib_tpu/a.py": "not-a-dict"}):
        with open(cache_path(tree_root)) as f:
            payload = json_mod.load(f)
        payload["files"] = mangle
        with open(cache_path(tree_root), "w") as f:
            json_mod.dump(payload, f)
        result = run_tree(root=tree_root, changed=True)
        assert result.analyzed_count == len(_TREE)   # cold
