"""Shared helpers for the lint-suite tests: fixture loading by name and
line lookup by substring (fixtures document that their line numbers
matter, but tests locate lines by content so edits don't silently
invalidate assertions)."""

import os

import pytest

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")
REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture
def load_fixture():
    from dib_tpu.analysis.core import load_module

    def _load(name: str):
        path = os.path.join(FIXTURES, name)
        return load_module(path, f"tests/test_lint/fixtures/{name}")

    return _load


def line_of(module, substring: str, nth: int = 0) -> int:
    """1-based line number of the nth line containing ``substring``."""
    hits = [i for i, line in enumerate(module.lines, 1)
            if substring in line]
    assert hits, f"{module.rel}: no line contains {substring!r}"
    return hits[nth]
