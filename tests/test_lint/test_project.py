"""Interprocedural engine tests (analysis/project.py): import/symbol
resolution, call resolution with bound/unbound argument mapping, and the
donation / device-fresh / key-consumption fixpoint summaries."""

import ast
import os

import pytest


def _write_tree(tmp_path, files: dict):
    """Write a {rel: source} tree and return (root, Project, modules)."""
    from dib_tpu.analysis.core import load_module
    from dib_tpu.analysis.project import Project

    modules = []
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
        modules.append(load_module(str(path), rel))
    by_rel = {m.rel: m for m in modules}
    return Project(modules), by_rel


# ------------------------------------------------------- bind_call_args
def test_bind_call_args_bound_unbound_and_keywords():
    from dib_tpu.analysis.jaxutil import bind_call_args

    params = ("self", "state", "key")
    bound = ast.parse("x.run(state, key)").body[0].value
    mapping = bind_call_args(bound, params, is_method=True)
    assert mapping["state"].id == "state" and mapping["key"].id == "key"
    unbound = ast.parse("T.run(self, state, key)").body[0].value
    mapping = bind_call_args(unbound, params, is_method=True)
    assert mapping["self"].id == "self" and mapping["state"].id == "state"
    kw = ast.parse("run(key=k2, state=s)").body[0].value
    mapping = bind_call_args(kw, ("state", "key"), is_method=False)
    assert mapping["state"].id == "s" and mapping["key"].id == "k2"


# ------------------------------------------------------------ resolution
def test_symbol_resolution_follows_reexport_chain(tmp_path):
    project, modules = _write_tree(tmp_path, {
        "pkg/__init__.py": "from pkg.inner import helper\n",
        "pkg/inner.py": "def helper(x):\n    return x\n",
        "pkg/user.py": (
            "from pkg import helper\n"
            "def use(x):\n"
            "    return helper(x)\n"
        ),
    })
    resolved = project.resolve_symbol("pkg/user.py", "helper")
    assert resolved is not None and resolved[0] == "func"
    assert resolved[1].rel == "pkg/inner.py"


def test_relative_import_and_module_alias_resolution(tmp_path):
    project, modules = _write_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/a.py": "def fa(x):\n    return x\n",
        "pkg/b.py": (
            "from . import a\n"
            "from .a import fa\n"
            "def use(x):\n"
            "    return a.fa(x)\n"
        ),
    })
    module = modules["pkg/b.py"]
    call = None
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            call = node
    info = project.resolve_call(module, call)
    assert info is not None and info.qualname == "pkg/a.py::fa"


def test_self_method_and_typed_local_resolution(tmp_path):
    project, modules = _write_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/t.py": (
            "class Trainer:\n"
            "    def fit(self, key):\n"
            "        return self.step(key)\n"
            "    def step(self, key):\n"
            "        return key\n"
        ),
        "pkg/driver.py": (
            "from pkg.t import Trainer\n"
            "def run(key):\n"
            "    trainer = Trainer()\n"
            "    return trainer.fit(key)\n"
        ),
    })
    t = modules["pkg/t.py"]
    self_call = next(n for n in ast.walk(t.tree)
                     if isinstance(n, ast.Call))
    assert project.resolve_call(t, self_call).qualname \
        == "pkg/t.py::Trainer.step"
    driver = modules["pkg/driver.py"]
    fn = driver.tree.body[1]
    fit_call = next(n for n in ast.walk(fn) if isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute))
    info = project.resolve_call(driver, fit_call, scope=fn)
    assert info is not None and info.qualname == "pkg/t.py::Trainer.fit"


def test_dynamic_dispatch_stays_unresolved(tmp_path):
    """The documented boundary: `for hook in hooks: hook(...)` and
    attribute-of-attribute calls never resolve."""
    project, modules = _write_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/m.py": (
            "def run(self, hooks, state):\n"
            "    for hook in hooks:\n"
            "        hook(state)\n"
            "    return self.zoo.resolve(state)\n"
        ),
    })
    m = modules["pkg/m.py"]
    fn = m.tree.body[0]
    for call in (n for n in ast.walk(fn) if isinstance(n, ast.Call)):
        assert project.resolve_call(m, call, scope=fn) is None


# ------------------------------------------------------------ summaries
_DONATING_TREE = {
    "pkg/__init__.py": "",
    "pkg/chunks.py": (
        "from functools import partial\n"
        "import jax\n"
        "@partial(jax.jit, donate_argnames=('state',))\n"
        "def run_chunk(state, key):\n"
        "    return state\n"
        "def train_step(state, key):\n"
        "    out = run_chunk(state, key)\n"
        "    return out\n"
        "def safe_step(state, key):\n"
        "    state = prepare(state)\n"      # rebound BEFORE the donation:
        "    out = run_chunk(state, key)\n"  # the param itself is safe
        "    return out\n"
        "def prepare(state):\n"
        "    return state\n"
    ),
    "pkg/driver.py": (
        "from pkg.chunks import train_step\n"
        "def outer(state, key):\n"
        "    out = train_step(state, key)\n"
        "    return out\n"
    ),
}


def test_donation_summary_crosses_module_boundaries(tmp_path):
    project, _ = _write_tree(tmp_path, _DONATING_TREE)
    summaries = project.donation_summaries()
    assert "state" in summaries["pkg/chunks.py::train_step"]
    assert "run_chunk" in summaries["pkg/chunks.py::train_step"]["state"]
    # two-hop chain: outer -> train_step -> run_chunk, chain named
    assert "state" in summaries["pkg/driver.py::outer"]
    assert "train_step" in summaries["pkg/driver.py::outer"]["state"]
    # a param rebound before the donating call is NOT donated by the fn
    # (absent from the facts map means the empty summary)
    assert summaries.get("pkg/chunks.py::safe_step", {}) == {}


def test_fresh_returner_summary(tmp_path):
    project, _ = _write_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/m.py": (
            "from functools import partial\n"
            "import jax\n"
            "@partial(jax.jit, donate_argnames=('state',))\n"
            "def run_chunk(state, key):\n"
            "    return state\n"
            "def step(state, key):\n"
            "    return run_chunk(state, key)\n"   # fresh: un-copied
            "def fetched_step(state, key):\n"
            "    out = run_chunk(state, key)\n"
            "    out = jax.device_get(out)\n"      # host copy clears it
            "    return out\n"
        ),
    })
    fresh = project.fresh_returners()
    assert "pkg/m.py::step" in fresh
    assert "pkg/m.py::fetched_step" not in fresh


def test_key_consumption_summary_distinguishes_deriving_helpers(tmp_path):
    project, modules = _write_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/keys.py": (
            "import jax\n"
            "def derive_only(key):\n"
            "    k1, k2 = jax.random.split(key)\n"
            "    return k1, k2\n"
            "def sampler(key):\n"
            "    return jax.random.normal(key, (3,))\n"
            "def chained(key):\n"
            "    return sampler(key)\n"
        ),
    })
    consumers = project.key_consumers()
    assert consumers.get("pkg/keys.py::derive_only", set()) == set()
    assert consumers["pkg/keys.py::sampler"] == {"key"}
    assert consumers["pkg/keys.py::chained"] == {"key"}   # transitive


def test_reverse_deps_follow_imports(tmp_path):
    project, _ = _write_tree(tmp_path, _DONATING_TREE)
    assert "pkg/driver.py" in project.reverse_deps["pkg/chunks.py"]
    assert project.module_deps["pkg/driver.py"] == {"pkg/chunks.py"}


def test_import_submodule_binds_root_package_name(tmp_path):
    """Review regression: `import a.b` binds `a` (the root package) in
    the namespace — `a.func(...)` must resolve against a/__init__.py,
    not a/b.py — while the dep edge to a/b.py is kept for the
    reverse-dependency closure."""
    project, modules = _write_tree(tmp_path, {
        "pkg/__init__.py": "def root_fn(x):\n    return x\n",
        "pkg/sub.py": "def root_fn(x):\n    return -x\n",
        "pkg/user.py": (
            "import pkg.sub\n"
            "def use(x):\n"
            "    return pkg.root_fn(x)\n"
        ),
    })
    user = modules["pkg/user.py"]
    fn = user.tree.body[1]
    call = next(n for n in ast.walk(fn) if isinstance(n, ast.Call))
    info = project.resolve_call(user, call, scope=fn)
    assert info is not None and info.rel == "pkg/__init__.py"
    assert "pkg/sub.py" in project.module_deps["pkg/user.py"]


def test_relative_import_inside_package_init_resolves(tmp_path):
    """Review regression: `from .x import f` inside a package __init__
    must resolve (the old guard kept the '__init__' segment and built
    lookups like 'pkg.__init__.x' that matched nothing — dropping both
    the re-export facts and the cache's dep edge)."""
    project, modules = _write_tree(tmp_path, {
        "pkg/__init__.py": "from .inner import helper\n",
        "pkg/inner.py": "def helper(x):\n    return x\n",
        "pkg/user.py": (
            "from pkg import helper\n"
            "def use(x):\n"
            "    return helper(x)\n"
        ),
    })
    resolved = project.resolve_symbol("pkg/user.py", "helper")
    assert resolved is not None and resolved[0] == "func"
    assert resolved[1].rel == "pkg/inner.py"
    assert "pkg/inner.py" in project.module_deps["pkg/__init__.py"]
