"""Per-pass fixture tests: bad snippet flagged with the right pass id and
line, good snippet clean — the contract docs/static-analysis.md's catalog
describes."""

import os

from tests.test_lint.conftest import REPO, line_of


def _findings(module, pass_id):
    from dib_tpu.analysis.core import get_pass

    lint = get_pass(pass_id)
    return [f for f in lint.check_module(module)
            if not module.suppressed(pass_id, f.line)]


# ------------------------------------------------------ donation-safety
def test_donation_flags_the_pr4_async_save_shape(load_fixture):
    """THE acceptance fixture: run_chunk's donated outputs handed to an
    async checkpoint save inside the chunk loop (docs/robustness.md,
    'Async save vs. donation')."""
    module = load_fixture("donation_async_save_bad.py")
    findings = _findings(module, "donation-safety")
    assert len(findings) == 1
    f = findings[0]
    assert f.pass_id == "donation-safety"
    assert f.line == line_of(module, "manager.save(")
    assert "async checkpoint" in f.message
    assert "run_chunk" in f.message


def test_donation_flags_read_after_donation(load_fixture):
    module = load_fixture("donation_read_after_bad.py")
    findings = _findings(module, "donation-safety")
    assert len(findings) == 1
    assert findings[0].line == line_of(module, 'history["loss"]')
    assert "`history` was donated" in findings[0].message


def test_donation_good_idioms_are_clean(load_fixture):
    module = load_fixture("donation_good.py")
    assert _findings(module, "donation-safety") == []


def test_donation_flags_overlap_alias_shape(load_fixture):
    """The raw-speed-PR bug shape: a bare alias (`snap = state.params`)
    taken BEFORE the donating call, read (by an overlapped measurement)
    after it — the buffers belong to the next chunk by then."""
    module = load_fixture("overlap_alias_bad.py")
    findings = _findings(module, "donation-safety")
    assert len(findings) == 1
    assert findings[0].line == line_of(module, "measure(snap, key)")
    assert "alias" in findings[0].message
    assert "snapshot_params" in findings[0].message


def test_donation_overlap_snapshot_idiom_is_clean(load_fixture):
    """`snapshot_params(state.params)` is a Call, not an alias — clean;
    an alias taken AFTER the rebind points at live buffers — clean."""
    module = load_fixture("overlap_snapshot_good.py")
    assert _findings(module, "donation-safety") == []


def test_donation_alias_orphaned_by_root_rebind_is_clean(tmp_path):
    """Review regression: a NON-donating rebind of the root orphans the
    alias (it views the previous, never-donated tree) — a later donation
    of the NEW binding must not flag reads of it."""
    from dib_tpu.analysis.core import load_module

    src = (
        "from functools import partial\n"
        "import jax\n"
        "@partial(jax.jit, donate_argnames=('state',))\n"
        "def run_chunk(state, key):\n"
        "    return state\n"
        "def g(state):\n"
        "    return state\n"
        "def f(state, key):\n"
        "    snap = state.params\n"
        "    state = g(state)\n"          # non-donating rebind: snap views
        "    state = run_chunk(state, key)\n"   # the OLD tree, not this one
        "    return snap, state\n"
    )
    path = tmp_path / "snippet.py"
    path.write_text(src)
    module = load_module(str(path), "snippet.py")
    assert _findings(module, "donation-safety") == []


def test_donation_pragma_suppresses(tmp_path):
    from dib_tpu.analysis.core import load_module

    src = (
        "from functools import partial\n"
        "import jax\n"
        "@partial(jax.jit, donate_argnames=('state',))\n"
        "def run_chunk(state, key):\n"
        "    return state\n"
        "def f(manager, state, key):\n"
        "    out = run_chunk(state, key)\n"
        "    # lint-ok(donation-safety): CPU-only path, save is synchronous\n"
        "    manager.save(0, args=out)\n"
        "    return out\n"
    )
    path = tmp_path / "snippet.py"
    path.write_text(src)
    module = load_module(str(path), "snippet.py")
    assert _findings(module, "donation-safety") == []


def test_donation_unbound_attribute_call_maps_args_correctly(tmp_path):
    """Review regression: `T.run_chunk(self, state, key)` passes self
    explicitly — positional mapping must not shift by one (which both
    missed the real read-after-donation of `state` and falsely marked
    `self` as donated)."""
    from dib_tpu.analysis.core import load_module

    src = (
        "from functools import partial\n"
        "import jax\n"
        "class T:\n"
        "    @partial(jax.jit, donate_argnames=('state',))\n"
        "    def run_chunk(self, state, key):\n"
        "        return state\n"
        "    def f(self, state, key):\n"
        "        out = T.run_chunk(self, state, key)\n"
        "        leak = state\n"
        "        ok = self.f\n"
        "        return out, leak, ok\n"
    )
    path = tmp_path / "unbound.py"
    path.write_text(src)
    module = load_module(str(path), "unbound.py")
    findings = _findings(module, "donation-safety")
    assert [f.line for f in findings] == [9]          # the `state` read
    assert "`state` was donated" in findings[0].message
    assert not any("`self`" in f.message for f in findings)


# ----------------------------------------------------------- prng-reuse
def test_prng_flags_double_consumption_and_loop_reuse(load_fixture):
    module = load_fixture("prng_bad.py")
    findings = _findings(module, "prng-reuse")
    lines = {f.line for f in findings}
    assert line_of(module, "more = jax.random.normal(key") in lines
    assert line_of(module, "out.append(jax.random.normal(key") in lines


def test_prng_good_is_clean(load_fixture):
    module = load_fixture("prng_good.py")
    assert _findings(module, "prng-reuse") == []


# ------------------------------------------------------------ host-sync
def test_host_sync_flags_implicit_coercions(load_fixture):
    module = load_fixture("host_sync_bad.py")
    findings = _findings(module, "host-sync")
    lines = {f.line for f in findings}
    assert line_of(module, 'float(stats["loss"])') in lines
    assert line_of(module, 'np.asarray(stats["loss"])') in lines
    assert line_of(module, "int(state)") in lines


def test_host_sync_device_get_idiom_is_clean(load_fixture):
    module = load_fixture("host_sync_good.py")
    assert _findings(module, "host-sync") == []


def test_host_sync_targets_only_chunk_loop_modules():
    from dib_tpu.analysis.core import get_pass

    host = get_pass("host-sync")
    # the fit chunk loops, the scheduler's hot modules (the worker pool
    # runs MANY units' chunk loops concurrently — a hidden blocking fetch
    # there serializes the whole pool), and the overlap/prefetch plumbing
    # (an implicit sync there re-serializes the boundary it exists to
    # hide)
    # ...and (ISSUE 10) the async serving hot path, where one implicit
    # device fetch stalls every in-flight request on the event loop
    assert set(host.target_modules) == {
        "dib_tpu/train/loop.py",
        "dib_tpu/train/measurement.py",
        "dib_tpu/train/overlap.py",
        "dib_tpu/train/prefetch.py",
        "dib_tpu/parallel/sweep.py",
        "dib_tpu/workloads/boolean.py",
        "dib_tpu/sched/runner.py",
        "dib_tpu/sched/pool.py",
        "dib_tpu/sched/scheduler.py",
        "dib_tpu/serve/engine.py",
        "dib_tpu/serve/batcher.py",
        "dib_tpu/serve/server.py",
        "dib_tpu/serve/pool.py",
        "dib_tpu/serve/zoo.py",
    }


def test_thread_state_covers_the_async_serving_modules():
    """thread-shared-state is TREE-WIDE (no target_modules), so the new
    async serving modules are covered by construction — this pins that
    they are not allowlisted away and that every serve class mutating
    state from a thread target holds a lock (zero findings on the real
    tree is asserted by the full-tree gate; here we pin the coverage
    contract itself)."""
    from dib_tpu.analysis.core import get_pass

    thread_pass = get_pass("thread-shared-state")
    assert not getattr(thread_pass, "target_modules", None)
    for module in ("dib_tpu/serve/server.py", "dib_tpu/serve/pool.py",
                   "dib_tpu/serve/zoo.py", "dib_tpu/serve/batcher.py"):
        assert module not in getattr(thread_pass, "allowlist", {})


# -------------------------------------------------- thread-shared-state
def test_thread_flags_method_and_closure_targets(load_fixture):
    module = load_fixture("thread_bad.py")
    findings = _findings(module, "thread-shared-state")
    lines = {f.line for f in findings}
    assert line_of(module, "self.seq += 1") in lines
    assert line_of(module, 'self.last_beat = "now"') in lines
    assert all("Emitter" in f.message for f in findings)


def test_thread_locked_class_is_trusted(load_fixture):
    module = load_fixture("thread_good.py")
    assert _findings(module, "thread-shared-state") == []


def test_thread_target_resolves_in_the_spawning_class(tmp_path):
    """Review regression: `target=self._run` must resolve to the
    SPAWNING class's method — a later same-named method on a
    lock-holding class must not shadow it and hide the race."""
    from dib_tpu.analysis.core import load_module

    src = (
        "import threading\n"
        "class Unlocked:\n"
        "    def spawn(self):\n"
        "        threading.Thread(target=self._run).start()\n"
        "    def _run(self):\n"
        "        self.count = 0\n"
        "class Locked:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def _run(self):\n"
        "        self.count = 0\n"
    )
    path = tmp_path / "shadow.py"
    path.write_text(src)
    module = load_module(str(path), "shadow.py")
    findings = _findings(module, "thread-shared-state")
    assert len(findings) == 1
    assert findings[0].line == 6
    assert "Unlocked" in findings[0].message


# --------------------------------------------------------- event-schema
def test_event_schema_flags_drift(load_fixture):
    module = load_fixture("event_schema_bad.py")
    findings = _findings(module, "event-schema")
    messages = "\n".join(f.message for f in findings)
    assert "'chnk'" in messages                       # unknown kind
    assert "missing required" in messages             # emit without fields
    assert "mtyp" in messages                         # unknown field
    assert "chunk_elapsed_s" in messages              # documented-but-fake


def test_event_schema_good_is_clean(load_fixture):
    module = load_fixture("event_schema_good.py")
    assert _findings(module, "event-schema") == []


def test_event_schema_docs_in_sync_with_registry():
    """The committed docs/observability.md record-type table matches
    EVENT_SCHEMA exactly (the satellite's docs-cannot-drift guarantee)."""
    from dib_tpu.analysis.core import get_pass

    assert get_pass("event-schema").check_project(REPO) == []


def test_event_schema_docs_drift_detected(tmp_path):
    from dib_tpu.analysis.core import get_pass

    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "Record types and their payloads:\n\n"
        "- **`chunk`** — per-chunk signal.\n"
        "- **`made_up_kind`** — not in the registry.\n"
    )
    findings = get_pass("event-schema").check_project(str(tmp_path))
    messages = "\n".join(f.message for f in findings)
    assert "made_up_kind" in messages          # documented, no schema row
    assert "'mitigation'" in messages          # schema row, undocumented


def test_strict_mode_rejects_unknown_kind(tmp_path, monkeypatch):
    from dib_tpu.telemetry.events import EventWriter

    monkeypatch.setenv("DIB_TELEMETRY_STRICT", "1")
    writer = EventWriter(str(tmp_path))
    try:
        writer.emit("chunk", epoch=0, steps=1, seconds=0.1)  # known: fine
        import pytest

        with pytest.raises(ValueError, match="unknown event kind"):
            writer.emit("chnk", epoch=0)
    finally:
        writer.close()


def test_schema_registry_covers_every_typed_helper():
    """Every typed EventWriter helper is named after a schema kind and
    vice versa — the registry cannot drift from the writer surface."""
    import inspect

    from dib_tpu.telemetry.events import EVENT_SCHEMA, EventWriter

    helper_names = {
        name for name, member in inspect.getmembers(
            EventWriter, predicate=inspect.isfunction)
        if not name.startswith("_") and name not in (
            "emit", "close", "metrics")
    } | {"metrics"}
    assert helper_names == set(EVENT_SCHEMA)


# --------------------------------------------------- migrated passes
def test_timing_pass_flags_and_allowlists():
    from dib_tpu.analysis.core import Module, get_pass

    lint = get_pass("timing-hygiene")
    module = Module("x.py", "dib_tpu/x.py",
                    "import time\nt0 = time.time()\n")
    assert [f.line for f in lint.check_module(module)] == [2]
    assert "dib_tpu/utils/profiling.py" in lint.allowlist
    for rel, why in lint.allowlist.items():
        assert why.strip()


def test_exception_pass_scope_is_the_whole_tree():
    from dib_tpu.analysis.core import get_pass

    lint = get_pass("exception-hygiene")
    assert lint.applies_to("dib_tpu/train/loop.py")
    assert lint.applies_to("scripts/fault_drill.py")
