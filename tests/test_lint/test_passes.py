"""Per-pass fixture tests: bad snippet flagged with the right pass id and
line, good snippet clean — the contract docs/static-analysis.md's catalog
describes."""

import os

from tests.test_lint.conftest import REPO, line_of


def _findings(module, pass_id):
    from dib_tpu.analysis.core import get_pass

    lint = get_pass(pass_id)
    return [f for f in lint.check_module(module)
            if not module.suppressed(pass_id, f.line)]


def _project_findings(modules, pass_id, target=None):
    """Run one pass over ``target`` (default: the first module) with an
    interprocedural Project built from ``modules``."""
    from dib_tpu.analysis.core import get_pass
    from dib_tpu.analysis.project import Project

    if not isinstance(modules, (list, tuple)):
        modules = [modules]
    target = target if target is not None else modules[0]
    project = Project(modules)
    lint = get_pass(pass_id)
    return [f for f in lint.check_module_with_project(target, project)
            if not target.suppressed(pass_id, f.line)]


def _load_tree(tmp_path, files: dict):
    from dib_tpu.analysis.core import load_module

    modules = []
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
        modules.append(load_module(str(path), rel))
    return modules


# ------------------------------------------------------ donation-safety
def test_donation_flags_the_pr4_async_save_shape(load_fixture):
    """THE acceptance fixture: run_chunk's donated outputs handed to an
    async checkpoint save inside the chunk loop (docs/robustness.md,
    'Async save vs. donation')."""
    module = load_fixture("donation_async_save_bad.py")
    findings = _findings(module, "donation-safety")
    assert len(findings) == 1
    f = findings[0]
    assert f.pass_id == "donation-safety"
    assert f.line == line_of(module, "manager.save(")
    assert "async checkpoint" in f.message
    assert "run_chunk" in f.message


def test_donation_flags_read_after_donation(load_fixture):
    module = load_fixture("donation_read_after_bad.py")
    findings = _findings(module, "donation-safety")
    assert len(findings) == 1
    assert findings[0].line == line_of(module, 'history["loss"]')
    assert "`history` was donated" in findings[0].message


def test_donation_good_idioms_are_clean(load_fixture):
    module = load_fixture("donation_good.py")
    assert _findings(module, "donation-safety") == []


def test_donation_flags_overlap_alias_shape(load_fixture):
    """The raw-speed-PR bug shape: a bare alias (`snap = state.params`)
    taken BEFORE the donating call, read (by an overlapped measurement)
    after it — the buffers belong to the next chunk by then."""
    module = load_fixture("overlap_alias_bad.py")
    findings = _findings(module, "donation-safety")
    assert len(findings) == 1
    assert findings[0].line == line_of(module, "measure(snap, key)")
    assert "alias" in findings[0].message
    assert "snapshot_params" in findings[0].message


def test_donation_overlap_snapshot_idiom_is_clean(load_fixture):
    """`snapshot_params(state.params)` is a Call, not an alias — clean;
    an alias taken AFTER the rebind points at live buffers — clean."""
    module = load_fixture("overlap_snapshot_good.py")
    assert _findings(module, "donation-safety") == []


def test_donation_alias_orphaned_by_root_rebind_is_clean(tmp_path):
    """Review regression: a NON-donating rebind of the root orphans the
    alias (it views the previous, never-donated tree) — a later donation
    of the NEW binding must not flag reads of it."""
    from dib_tpu.analysis.core import load_module

    src = (
        "from functools import partial\n"
        "import jax\n"
        "@partial(jax.jit, donate_argnames=('state',))\n"
        "def run_chunk(state, key):\n"
        "    return state\n"
        "def g(state):\n"
        "    return state\n"
        "def f(state, key):\n"
        "    snap = state.params\n"
        "    state = g(state)\n"          # non-donating rebind: snap views
        "    state = run_chunk(state, key)\n"   # the OLD tree, not this one
        "    return snap, state\n"
    )
    path = tmp_path / "snippet.py"
    path.write_text(src)
    module = load_module(str(path), "snippet.py")
    assert _findings(module, "donation-safety") == []


def test_donation_pragma_suppresses(tmp_path):
    from dib_tpu.analysis.core import load_module

    src = (
        "from functools import partial\n"
        "import jax\n"
        "@partial(jax.jit, donate_argnames=('state',))\n"
        "def run_chunk(state, key):\n"
        "    return state\n"
        "def f(manager, state, key):\n"
        "    out = run_chunk(state, key)\n"
        "    # lint-ok(donation-safety): CPU-only path, save is synchronous\n"
        "    manager.save(0, args=out)\n"
        "    return out\n"
    )
    path = tmp_path / "snippet.py"
    path.write_text(src)
    module = load_module(str(path), "snippet.py")
    assert _findings(module, "donation-safety") == []


def test_donation_unbound_attribute_call_maps_args_correctly(tmp_path):
    """Review regression: `T.run_chunk(self, state, key)` passes self
    explicitly — positional mapping must not shift by one (which both
    missed the real read-after-donation of `state` and falsely marked
    `self` as donated)."""
    from dib_tpu.analysis.core import load_module

    src = (
        "from functools import partial\n"
        "import jax\n"
        "class T:\n"
        "    @partial(jax.jit, donate_argnames=('state',))\n"
        "    def run_chunk(self, state, key):\n"
        "        return state\n"
        "    def f(self, state, key):\n"
        "        out = T.run_chunk(self, state, key)\n"
        "        leak = state\n"
        "        ok = self.f\n"
        "        return out, leak, ok\n"
    )
    path = tmp_path / "unbound.py"
    path.write_text(src)
    module = load_module(str(path), "unbound.py")
    findings = _findings(module, "donation-safety")
    assert [f.line for f in findings] == [9]          # the `state` read
    assert "`state` was donated" in findings[0].message
    assert not any("`self`" in f.message for f in findings)


# ----------------------------------------------------------- prng-reuse
def test_prng_flags_double_consumption_and_loop_reuse(load_fixture):
    module = load_fixture("prng_bad.py")
    findings = _findings(module, "prng-reuse")
    lines = {f.line for f in findings}
    assert line_of(module, "more = jax.random.normal(key") in lines
    assert line_of(module, "out.append(jax.random.normal(key") in lines


def test_prng_good_is_clean(load_fixture):
    module = load_fixture("prng_good.py")
    assert _findings(module, "prng-reuse") == []


# ------------------------------------------------------------ host-sync
def test_host_sync_flags_implicit_coercions(load_fixture):
    module = load_fixture("host_sync_bad.py")
    findings = _findings(module, "host-sync")
    lines = {f.line for f in findings}
    assert line_of(module, 'float(stats["loss"])') in lines
    assert line_of(module, 'np.asarray(stats["loss"])') in lines
    assert line_of(module, "int(state)") in lines


def test_host_sync_device_get_idiom_is_clean(load_fixture):
    module = load_fixture("host_sync_good.py")
    assert _findings(module, "host-sync") == []


def test_host_sync_targets_only_chunk_loop_modules():
    from dib_tpu.analysis.core import get_pass

    host = get_pass("host-sync")
    # the fit chunk loops, the scheduler's hot modules (the worker pool
    # runs MANY units' chunk loops concurrently — a hidden blocking fetch
    # there serializes the whole pool), and the overlap/prefetch plumbing
    # (an implicit sync there re-serializes the boundary it exists to
    # hide)
    # ...and (ISSUE 10) the async serving hot path, where one implicit
    # device fetch stalls every in-flight request on the event loop
    # ...and (ISSUE 12) the streaming control plane: the online loop is
    # a chunk loop, and the deployer restores/probes while the fleet
    # serves
    # ...and (ISSUE 14) the integrity plane: the anomaly detector runs
    # at every chunk boundary and must live off the row fetch the
    # boundary already pays for; the digest/scrub layer syncs explicitly
    # ...and (ISSUE 15) the study controller, which drives the pool's
    # many concurrent chunk loops from its decision core
    # ...and (ISSUE 16) the fleet aggregator, whose one poll loop
    # follows MANY runs' planes — an implicit fetch there stalls the
    # merge for every source at once
    # ...and (ISSUE 19) the drift autopilot, whose supervise loop sits
    # between the stream's publish tail and the study controller — a
    # blocking fetch there delays every drift→re-anneal apply
    assert set(host.target_modules) == {
        "dib_tpu/train/loop.py",
        "dib_tpu/train/measurement.py",
        "dib_tpu/train/overlap.py",
        "dib_tpu/train/prefetch.py",
        "dib_tpu/parallel/sweep.py",
        "dib_tpu/workloads/boolean.py",
        "dib_tpu/sched/runner.py",
        "dib_tpu/sched/pool.py",
        "dib_tpu/sched/scheduler.py",
        "dib_tpu/serve/engine.py",
        "dib_tpu/serve/batcher.py",
        "dib_tpu/serve/server.py",
        "dib_tpu/serve/pool.py",
        "dib_tpu/serve/zoo.py",
        "dib_tpu/stream/online.py",
        "dib_tpu/stream/deployer.py",
        "dib_tpu/train/anomaly.py",
        "dib_tpu/train/scrub.py",
        "dib_tpu/train/checkpoint.py",
        "dib_tpu/study/controller.py",
        "dib_tpu/telemetry/fleet.py",
        "dib_tpu/autopilot/loop.py",
    }


def test_thread_state_covers_the_async_serving_modules():
    """thread-shared-state is TREE-WIDE (no target_modules), so the new
    async serving modules are covered by construction — this pins that
    they are not allowlisted away and that every serve class mutating
    state from a thread target holds a lock (zero findings on the real
    tree is asserted by the full-tree gate; here we pin the coverage
    contract itself)."""
    from dib_tpu.analysis.core import get_pass

    thread_pass = get_pass("thread-shared-state")
    assert not getattr(thread_pass, "target_modules", None)
    for module in ("dib_tpu/serve/server.py", "dib_tpu/serve/pool.py",
                   "dib_tpu/serve/zoo.py", "dib_tpu/serve/batcher.py",
                   "dib_tpu/stream/online.py",
                   "dib_tpu/stream/deployer.py"):
        assert module not in getattr(thread_pass, "allowlist", {})


def test_tree_wide_passes_cover_the_study_modules():
    """ISSUE 15: the study controller tails streams from a follower
    thread and talks to the scheduler — exactly the bug classes the
    tree-wide passes exist for. Pin that thread-shared-state,
    resource-lifecycle, and async-blocking stay tree-wide (no
    target_modules) and that no study module is allowlisted away; the
    zero-findings full-tree gate does the rest."""
    from dib_tpu.analysis.core import get_pass

    for pass_name in ("thread-shared-state", "resource-lifecycle",
                      "async-blocking"):
        p = get_pass(pass_name)
        assert not getattr(p, "target_modules", None), pass_name
        for module in ("dib_tpu/study/controller.py",
                       "dib_tpu/study/journal.py",
                       "dib_tpu/study/report.py",
                       "dib_tpu/study/cli.py"):
            assert module not in getattr(p, "allowlist", {}), (
                pass_name, module)


# -------------------------------------------------- thread-shared-state
def test_thread_flags_method_and_closure_targets(load_fixture):
    module = load_fixture("thread_bad.py")
    findings = _findings(module, "thread-shared-state")
    lines = {f.line for f in findings}
    assert line_of(module, "self.seq += 1") in lines
    assert line_of(module, 'self.last_beat = "now"') in lines
    assert all("Emitter" in f.message for f in findings)


def test_thread_locked_class_is_trusted(load_fixture):
    module = load_fixture("thread_good.py")
    assert _findings(module, "thread-shared-state") == []


def test_thread_flags_the_fleet_aggregator_shape(load_fixture):
    """ISSUE 16: an aggregator thread mutating the shared timeline (and
    its per-source cursors) without a lock is the exact race the real
    FleetAggregator guards with self._lock — pin that the lockless shape
    is flagged so the guard can never be silently dropped."""
    module = load_fixture("thread_fleet_bad.py")
    findings = _findings(module, "thread-shared-state")
    lines = {f.line for f in findings}
    assert line_of(module, "self.timeline = self.timeline + [record]") in lines
    assert line_of(module, "self.consumed += 1") in lines
    assert all("UnlockedAggregator" in f.message for f in findings)


def test_thread_state_covers_the_fleet_aggregator():
    """ISSUE 16 coverage pin: thread-shared-state stays tree-wide and
    telemetry/fleet.py is not allowlisted away — the real aggregator's
    lock discipline is enforced by the zero-findings full-tree gate."""
    from dib_tpu.analysis.core import get_pass

    thread_pass = get_pass("thread-shared-state")
    assert not getattr(thread_pass, "target_modules", None)
    assert "dib_tpu/telemetry/fleet.py" not in getattr(
        thread_pass, "allowlist", {})


def test_thread_target_resolves_in_the_spawning_class(tmp_path):
    """Review regression: `target=self._run` must resolve to the
    SPAWNING class's method — a later same-named method on a
    lock-holding class must not shadow it and hide the race."""
    from dib_tpu.analysis.core import load_module

    src = (
        "import threading\n"
        "class Unlocked:\n"
        "    def spawn(self):\n"
        "        threading.Thread(target=self._run).start()\n"
        "    def _run(self):\n"
        "        self.count = 0\n"
        "class Locked:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def _run(self):\n"
        "        self.count = 0\n"
    )
    path = tmp_path / "shadow.py"
    path.write_text(src)
    module = load_module(str(path), "shadow.py")
    findings = _findings(module, "thread-shared-state")
    assert len(findings) == 1
    assert findings[0].line == 6
    assert "Unlocked" in findings[0].message


# --------------------------------------------------------- event-schema
def test_event_schema_flags_drift(load_fixture):
    module = load_fixture("event_schema_bad.py")
    findings = _findings(module, "event-schema")
    messages = "\n".join(f.message for f in findings)
    assert "'chnk'" in messages                       # unknown kind
    assert "missing required" in messages             # emit without fields
    assert "mtyp" in messages                         # unknown field
    assert "chunk_elapsed_s" in messages              # documented-but-fake


def test_event_schema_good_is_clean(load_fixture):
    module = load_fixture("event_schema_good.py")
    assert _findings(module, "event-schema") == []


def test_event_schema_docs_in_sync_with_registry():
    """The committed docs/observability.md record-type table matches
    EVENT_SCHEMA exactly (the satellite's docs-cannot-drift guarantee)."""
    from dib_tpu.analysis.core import get_pass

    assert get_pass("event-schema").check_project(REPO) == []


def test_event_schema_docs_drift_detected(tmp_path):
    from dib_tpu.analysis.core import get_pass

    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "Record types and their payloads:\n\n"
        "- **`chunk`** — per-chunk signal.\n"
        "- **`made_up_kind`** — not in the registry.\n"
    )
    findings = get_pass("event-schema").check_project(str(tmp_path))
    messages = "\n".join(f.message for f in findings)
    assert "made_up_kind" in messages          # documented, no schema row
    assert "'mitigation'" in messages          # schema row, undocumented


def test_strict_mode_rejects_unknown_kind(tmp_path, monkeypatch):
    from dib_tpu.telemetry.events import EventWriter

    monkeypatch.setenv("DIB_TELEMETRY_STRICT", "1")
    writer = EventWriter(str(tmp_path))
    try:
        writer.emit("chunk", epoch=0, steps=1, seconds=0.1)  # known: fine
        import pytest

        with pytest.raises(ValueError, match="unknown event kind"):
            writer.emit("chnk", epoch=0)
    finally:
        writer.close()


def test_schema_registry_covers_every_typed_helper():
    """Every typed EventWriter helper is named after a schema kind and
    vice versa — the registry cannot drift from the writer surface."""
    import inspect

    from dib_tpu.telemetry.events import EVENT_SCHEMA, EventWriter

    helper_names = {
        name for name, member in inspect.getmembers(
            EventWriter, predicate=inspect.isfunction)
        if not name.startswith("_") and name not in (
            "emit", "close", "metrics")
    } | {"metrics"}
    assert helper_names == set(EVENT_SCHEMA)


# --------------------------------------------------- migrated passes
def test_timing_pass_flags_and_allowlists():
    from dib_tpu.analysis.core import Module, get_pass

    lint = get_pass("timing-hygiene")
    module = Module("x.py", "dib_tpu/x.py",
                    "import time\nt0 = time.time()\n")
    assert [f.line for f in lint.check_module(module)] == [2]
    assert "dib_tpu/utils/profiling.py" in lint.allowlist
    for rel, why in lint.allowlist.items():
        assert why.strip()


def test_exception_pass_scope_is_the_whole_tree():
    from dib_tpu.analysis.core import get_pass

    lint = get_pass("exception-hygiene")
    assert lint.applies_to("dib_tpu/train/loop.py")
    assert lint.applies_to("scripts/fault_drill.py")


# ----------------------------------------- interprocedural donation/prng
def test_donation_interprocedural_helper_wrapped_donation(tmp_path):
    """The tentpole shape: a helper wraps the donating call; reading the
    argument after the HELPER call is the same use-after-free, and the
    finding names the chain."""
    modules = _load_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/chunks.py": (
            "from functools import partial\n"
            "import jax\n"
            "@partial(jax.jit, donate_argnames=('state',))\n"
            "def run_chunk(state, key):\n"
            "    return state\n"
            "def train_step(state, key):\n"
            "    return run_chunk(state, key)\n"
        ),
        "pkg/driver.py": (
            "from pkg.chunks import train_step\n"
            "def outer(state, key):\n"
            "    out = train_step(state, key)\n"
            "    stale = state['params']\n"
            "    return out, stale\n"
        ),
    })
    driver = modules[2]
    findings = _project_findings(modules, "donation-safety", target=driver)
    assert len(findings) == 1
    assert findings[0].line == line_of(driver, "stale = state")
    assert "train_step" in findings[0].message
    assert "run_chunk" in findings[0].message   # the chain is named


def test_donation_interprocedural_fresh_returner_async_save(tmp_path):
    """Async-save taint through a helper: a function returning the
    un-copied jitted result taints its caller's binding."""
    modules = _load_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/chunks.py": (
            "from functools import partial\n"
            "import jax\n"
            "@partial(jax.jit, donate_argnames=('state',))\n"
            "def run_chunk(state, key):\n"
            "    return state\n"
            "def step(state, key):\n"
            "    return run_chunk(state, key)\n"
        ),
        "pkg/saver.py": (
            "from pkg.chunks import step\n"
            "def save_loop(manager, state, key):\n"
            "    out = step(state, key)\n"
            "    manager.save(0, args=out)\n"
            "    return out\n"
        ),
    })
    saver = modules[2]
    findings = _project_findings(modules, "donation-safety", target=saver)
    assert len(findings) == 1
    assert findings[0].line == line_of(saver, "manager.save(")
    assert "async checkpoint" in findings[0].message


def test_donation_in_return_does_not_poison_unreachable_tail(tmp_path):
    """Review regression (found live on train/measurement.py): a
    donating call riding a `return` cannot poison lexically-later
    statements — control already left the scope."""
    modules = _load_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/m.py": (
            "from functools import partial\n"
            "import jax\n"
            "@partial(jax.jit, donate_argnames=('state',))\n"
            "def run_chunk(state, key):\n"
            "    return state\n"
            "def helper(state, key):\n"
            "    return run_chunk(state, key)\n"
            "def fit(state, key, overlap):\n"
            "    if overlap:\n"
            "        return helper(state, key)\n"
            "    state2 = run_chunk(state, key)\n"
            "    return state2\n"
        ),
    })
    m = modules[1]
    assert _project_findings(modules, "donation-safety", target=m) == []


def test_prng_interprocedural_deriving_helper_not_a_consumption(tmp_path):
    """A helper that only splits its key is no longer a consumption at
    the call site (the refinement that retired the checkpoint.py
    pragma); a helper that samples still is."""
    modules = _load_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/keys.py": (
            "import jax\n"
            "def derive_only(key):\n"
            "    return jax.random.split(key, 2)\n"
            "def sampler(key):\n"
            "    return jax.random.normal(key, (3,))\n"
            "def clean_use(key):\n"
            "    kd = derive_only(key)\n"          # derives: not consumed
            "    out = jax.random.normal(key, (3,))\n"  # the ONE consumption
            "    return kd, out\n"
            "def double_use(key):\n"
            "    a = sampler(key)\n"               # consumption #1 (helper)
            "    b = jax.random.normal(key, (3,))\n"    # consumption #2
            "    return a, b\n"
        ),
    })
    keys_mod = modules[1]
    findings = _project_findings(modules, "prng-reuse", target=keys_mod)
    assert len(findings) == 1
    assert findings[0].line == line_of(
        keys_mod, "b = jax.random.normal(key")


# ------------------------------------------------------ mesh-consistency
def test_mesh_bad_fixture_trips_every_shape(load_fixture):
    module = load_fixture("mesh_bad.py")
    findings = _project_findings(module, "mesh-consistency")
    lines = {f.line for f in findings}
    messages = "\n".join(f.message for f in findings)
    assert line_of(module, '("sweep", "sweep"))') in lines  # dup Mesh axis
    assert line_of(module, 'P("model")') in lines           # unknown axis
    assert line_of(module, 'P("sweep", "sweep")') in lines  # axis-twice spec
    assert line_of(module, "mapped = shard_map(two_arg_kernel") in lines  # arity
    assert "donated" in messages                            # jit sharding
    assert "reshard" in messages.lower()                    # save/restore
    assert line_of(module, "def restore") in lines
    assert len(findings) == 6


def test_mesh_good_fixture_is_clean(load_fixture):
    module = load_fixture("mesh_good.py")
    assert _project_findings(module, "mesh-consistency") == []


def test_mesh_pragma_suppresses(load_fixture):
    module = load_fixture("mesh_pragma.py")
    assert _project_findings(module, "mesh-consistency") == []


def test_mesh_axes_resolve_through_project_constants(load_fixture):
    """The real tree's axis constants (parallel/mesh.py BETA_AXIS etc.)
    are project facts: a fixture spec over 'beta' would be legal when
    the project is the repo tree."""
    from dib_tpu.analysis.passes.mesh import MeshFacts
    from dib_tpu.analysis.core import load_module
    from dib_tpu.analysis.project import Project

    path = os.path.join(REPO, "dib_tpu", "parallel", "mesh.py")
    module = load_module(path, "dib_tpu/parallel/mesh.py")
    project = Project([module])
    facts = MeshFacts([module], project)
    assert {"beta", "data", "seq"} <= facts.axes


# -------------------------------------------------------- async-blocking
def test_async_blocking_bad_fixture_trips_every_shape(load_fixture):
    module = load_fixture("async_blocking_bad.py")
    findings = _project_findings(module, "async-blocking")
    lines = {f.line for f in findings}
    messages = "\n".join(f.message for f in findings)
    assert line_of(module, "time.sleep(0.05)") in lines        # direct
    assert line_of(module, "out = _drain_queue(batch)") in lines  # chain
    assert "_drain_queue" in messages and "via its line" in messages
    assert line_of(module, "subprocess.run(cmd)") in lines
    assert line_of(module, "jax.device_get(outputs)") in lines
    assert line_of(module, "fut.result()") in lines
    # nth=1: the 0th hit is `async def _probe(replica):` itself
    assert line_of(module, "_probe(replica)", nth=1) in lines  # discarded
    assert "never run" in messages
    assert len(findings) == 6


def test_async_blocking_good_fixture_is_clean(load_fixture):
    module = load_fixture("async_blocking_good.py")
    assert _project_findings(module, "async-blocking") == []


def test_async_blocking_pragma_suppresses(load_fixture):
    module = load_fixture("async_blocking_pragma.py")
    assert _project_findings(module, "async-blocking") == []


def test_async_blocking_chain_crosses_modules(tmp_path):
    """Interprocedural: the blocking primitive lives two modules away
    from the coroutine that reaches it."""
    modules = _load_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/low.py": ("import time\n"
                       "def drain(q):\n"
                       "    time.sleep(0.01)\n"
                       "    return q\n"),
        "pkg/mid.py": ("from pkg.low import drain\n"
                       "def handle(q):\n"
                       "    return drain(q)\n"),
        "pkg/server.py": ("from pkg.mid import handle\n"
                          "async def conn(q):\n"
                          "    return handle(q)\n"),
    })
    server = modules[3]
    findings = _project_findings(modules, "async-blocking", target=server)
    assert len(findings) == 1
    assert "handle" in findings[0].message
    assert "blocks the event loop" in findings[0].message


def test_async_blocking_quiet_on_sync_only_modules():
    """No coroutines, no findings — the pass gates on `async def`."""
    from dib_tpu.analysis.core import Module

    module = Module("x.py", "pkg/x.py",
                    "import time\ndef f():\n    time.sleep(1)\n")
    assert _project_findings(module, "async-blocking") == []


# ----------------------------------------------------- resource-lifecycle
def test_resource_bad_fixture_trips_every_shape(load_fixture):
    module = load_fixture("resource_bad.py")
    findings = _project_findings(module, "resource-lifecycle")
    lines = {f.line for f in findings}
    messages = "\n".join(f.message for f in findings)
    assert line_of(module, "proc = subprocess.Popen(cmd)",
                   nth=0) in lines                       # bare local leak
    assert line_of(module, "multiprocessing.Pipe()") in lines
    assert line_of(module, "socket.create_connection") in lines
    assert line_of(module, "threading.Thread(target=target)") in lines
    assert line_of(module, "proc = factory(cmd)") in lines  # via summary
    assert line_of(module, "ctx.Process(target=spec)") in lines
    assert "LeakyOwner" in messages
    # parent AND child sides of the pipe each leak
    assert len(findings) == 7


def test_resource_good_fixture_is_clean(load_fixture):
    module = load_fixture("resource_good.py")
    assert _project_findings(module, "resource-lifecycle") == []


def test_resource_pragma_suppresses(load_fixture):
    module = load_fixture("resource_pragma.py")
    assert _project_findings(module, "resource-lifecycle") == []


def test_resource_prefork_regression_fixture_still_trips(load_fixture):
    """THE committed PR 10 incident fixture: the prefork supervisor's
    respawn loop dropping the replacement worker's Popen handle must
    keep tripping resource-lifecycle — if a refactor stops flagging it,
    the fork-bomb aftermath's leak shape has gone invisible."""
    module = load_fixture("resource_prefork_bad.py")
    findings = _project_findings(module, "resource-lifecycle")
    assert len(findings) == 1
    assert findings[0].line == line_of(module, "proc = subprocess.Popen")
    assert "leak" in findings[0].message


def test_resource_factory_summary_crosses_modules(tmp_path):
    modules = _load_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/spawn.py": ("import subprocess\n"
                         "def spawn(cmd):\n"
                         "    return subprocess.Popen(cmd)\n"),
        "pkg/user.py": ("from pkg.spawn import spawn\n"
                        "def leaky(cmd):\n"
                        "    proc = spawn(cmd)\n"
                        "    return 0\n"
                        "def fine(cmd):\n"
                        "    proc = spawn(cmd)\n"
                        "    try:\n"
                        "        return proc.wait(timeout=5)\n"
                        "    finally:\n"
                        "        proc.kill()\n"),
    })
    user = modules[2]
    findings = _project_findings(modules, "resource-lifecycle",
                                 target=user)
    assert [f.line for f in findings] == [line_of(user, "proc = spawn")]


def test_mesh_heterogeneous_save_specs_do_not_crash(tmp_path):
    """Review regression: save/restore spec signatures mix None/str —
    a bare sorted() raised TypeError and took down the whole run."""
    from dib_tpu.analysis.core import load_module

    src = (
        "import jax\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "class C:\n"
        "    def save(self, m, mgr, x, y):\n"
        "        a = jax.device_put(x, NamedSharding(m, P('data')))\n"
        "        b = jax.device_put(y, NamedSharding(m, P(None, 'data')))\n"
        "        mgr.save(0, (a, b))\n"
        "    def restore(self, m, mgr):\n"
        "        t = mgr.restore(0)\n"
        "        return jax.device_put(t, NamedSharding(m, P('data')))\n"
    )
    path = tmp_path / "hetero.py"
    path.write_text(src)
    module = load_module(str(path), "hetero.py")
    findings = _project_findings(module, "mesh-consistency")
    assert any("restores under" in f.message for f in findings)


def test_prng_aliased_consumption_inside_helper_stays_conservative(tmp_path):
    """Review regression: a helper consuming its key through a local
    alias (`k = key; normal(k)`) must still summarize as consuming —
    otherwise callers reusing the key twice go silently unflagged."""
    modules = _load_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/keys.py": (
            "import jax\n"
            "def helper(key):\n"
            "    k = key\n"
            "    return jax.random.normal(k, (3,))\n"
            "def double(key):\n"
            "    a = helper(key)\n"
            "    b = helper(key)\n"
            "    return a, b\n"
        ),
    })
    keys_mod = modules[1]
    findings = _project_findings(modules, "prng-reuse", target=keys_mod)
    assert [f.line for f in findings] == [
        line_of(keys_mod, "b = helper(key)")]


def test_bind_call_args_stops_mapping_after_starred():
    """Review regression: positions after a *args splat depend on its
    runtime length — they must be left unmapped, not mis-mapped."""
    import ast as ast_mod

    from dib_tpu.analysis.jaxutil import bind_call_args

    call = ast_mod.parse("h(*keys, key)").body[0].value
    assert bind_call_args(call, ("a", "b"), is_method=False) == {}


def test_mesh_3d_spec_on_2d_mesh_is_valid(tmp_path):
    """Review regression: spec length is the ARRAY's rank, not the
    mesh's — P('sweep','data',None) for a 3D array on the 2D mesh must
    not trip; P('sweep','sweep') (one axis, two dims) must."""
    from dib_tpu.analysis.core import load_module

    src = (
        "import jax\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "def make(devices):\n"
        "    return Mesh(devices, ('sweep', 'data'))\n"
        "def ok(m, x):\n"
        "    return jax.device_put(x, NamedSharding(m, P('sweep', 'data', None)))\n"
        "def bad(m, x):\n"
        "    return jax.device_put(x, NamedSharding(m, P('sweep', 'sweep')))\n"
    )
    path = tmp_path / "specs.py"
    path.write_text(src)
    module = load_module(str(path), "specs.py")
    findings = _project_findings(module, "mesh-consistency")
    assert [f.line for f in findings] == [line_of(module, "def bad") + 1]
    assert "two" in findings[0].message


def test_resource_pid_logging_does_not_launder_the_leak(tmp_path):
    """Review regression: `log.info('%s', proc.pid)` passes an int, not
    the handle — the prefork respawn leak must still flag."""
    from dib_tpu.analysis.core import load_module

    src = (
        "import subprocess\n"
        "def respawn(cmd, log):\n"
        "    proc = subprocess.Popen(cmd)\n"
        "    log.info('spawned %s', proc.pid)\n"
        "    return 0\n"
    )
    path = tmp_path / "respawn.py"
    path.write_text(src)
    module = load_module(str(path), "respawn.py")
    findings = _project_findings(module, "resource-lifecycle")
    assert [f.line for f in findings] == [line_of(module, "Popen(cmd)")]


def test_async_blocking_result_with_timeout_still_flags(tmp_path):
    """Review regression: Future.result(5) parks the loop for up to the
    timeout — the positional-timeout form is the same stall."""
    from dib_tpu.analysis.core import load_module

    src = (
        "async def handler(fut):\n"
        "    return fut.result(5)\n"
    )
    path = tmp_path / "fut.py"
    path.write_text(src)
    module = load_module(str(path), "fut.py")
    findings = _project_findings(module, "async-blocking")
    assert len(findings) == 1 and "result" in findings[0].message


def test_event_schema_guard_flags_a_vanished_serving_rollup(tmp_path):
    """Review regression: a tree that HAS telemetry/summary.py but no
    findable serving_rollup is drift, not a silent green pass."""
    from dib_tpu.analysis.core import get_pass

    tel = tmp_path / "dib_tpu" / "telemetry"
    tel.mkdir(parents=True)
    (tel / "summary.py").write_text("def rollup_renamed(events):\n"
                                    "    return {}\n")
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "Record types and their payloads:\n\n"
        "Serving-rollup keys: `requests`.\n")
    findings = get_pass("event-schema").check_project(str(tmp_path))
    assert any("serving_rollup not found" in f.message for f in findings)


def test_event_schema_guard_pins_phase_table_to_request_phases(tmp_path):
    """ISSUE-17 docs drift, both directions: a phase the clock stamps
    but the docs table omits, and a documented phase the vocabulary
    dropped — the code (REQUEST_PHASES) is the source of truth."""
    from dib_tpu.analysis.core import get_pass

    tel = tmp_path / "dib_tpu" / "telemetry"
    tel.mkdir(parents=True)
    (tel / "events.py").write_text(
        'REQUEST_PHASES = ("read", "parse", "write")\n')
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "Record types and their payloads:\n\n"
        "| phase | meaning |\n"
        "|---|---|\n"
        "| `read` | socket read |\n"
        "| `warp` | not a real phase |\n")
    findings = get_pass("event-schema").check_project(str(tmp_path))
    messages = [f.message for f in findings]
    assert any("request phase 'parse'" in m and "missing" in m
               for m in messages), messages
    assert any("request phase 'write'" in m and "missing" in m
               for m in messages), messages
    assert any("documented request phase 'warp'" in m
               for m in messages), messages
    # a tree whose events.py lost the tuple entirely is a lost anchor
    (tel / "events.py").write_text("PHASES_RENAMED = ()\n")
    findings = get_pass("event-schema").check_project(str(tmp_path))
    assert any("REQUEST_PHASES not found" in f.message
               for f in findings)


def test_mesh_donation_sharding_flags_decorator_forms(tmp_path):
    """Review regression: @partial(jax.jit, ...) and @jax.jit(...) are
    the repo's dominant jit spellings — the donation×sharding check
    must fire on them, not only on direct jax.jit(fn, ...) calls (and
    must not double-report the @jax.jit form)."""
    from dib_tpu.analysis.core import load_module

    src = (
        "from functools import partial\n"
        "import jax\n"
        "from jax.sharding import Mesh, PartitionSpec as P\n"
        "def make(devices):\n"
        "    return Mesh(devices, ('sweep', 'data'))\n"
        "@partial(jax.jit, donate_argnames=('states',),\n"
        "         in_shardings=(P('sweep'), P('data')),\n"
        "         out_shardings=(P('data'),))\n"
        "def step(states, batch):\n"
        "    return states\n"
        "@jax.jit(donate_argnums=(0,),\n"
        "         in_shardings=(P('sweep'), P('data')),\n"
        "         out_shardings=(P('data'),))\n"
        "def step2(states, batch):\n"
        "    return states\n"
    )
    path = tmp_path / "deco.py"
    path.write_text(src)
    module = load_module(str(path), "deco.py")
    findings = [f for f in _project_findings(module, "mesh-consistency")
                if "donated" in f.message]
    assert len(findings) == 2
    assert {f.line for f in findings} == {
        line_of(module, "@partial(jax.jit"), line_of(module, "@jax.jit(")}


def test_prng_closure_capture_inside_helper_stays_conservative(tmp_path):
    """Review regression: a helper consuming its key through a nested
    def's closure must still summarize as consuming."""
    modules = _load_tree(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/keys.py": (
            "import jax\n"
            "def helper(key):\n"
            "    def inner():\n"
            "        return jax.random.normal(key, (3,))\n"
            "    return inner()\n"
            "def double(key):\n"
            "    a = helper(key)\n"
            "    b = helper(key)\n"
            "    return a, b\n"
        ),
    })
    keys_mod = modules[1]
    findings = _project_findings(modules, "prng-reuse", target=keys_mod)
    assert [f.line for f in findings] == [
        line_of(keys_mod, "b = helper(key)")]
