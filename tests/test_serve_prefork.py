"""Prefork socket request plane (serve/prefork.py): N full server
processes on one SO_REUSEPORT port, supervised.

The committed BENCH_SERVE_ASYNC_CPU.json headline flows through this
plane, so the fleet smoke here is the CI anchor for it: spawn a 2-worker
fleet through the REAL CLI, prove the kernel spreads connections across
distinct worker pids, SIGKILL one worker and see traffic survive on the
other while the supervisor respawns the dead one.
"""

import importlib.util
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from dib_tpu.serve.prefork import reserve_port, strip_flag

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ units
def test_strip_flag_is_positional():
    argv = ["--workers", "2", "--port", "8100", "--model_name", "port",
            "--prefork=3", "--prefork", "4"]
    # value-equality filtering would eat the "port" MODEL-NAME VALUE; the
    # positional strip removes only flag occurrences + their values, in
    # both "--f v" and "--f=v" spellings
    assert strip_flag(argv, "--prefork", True) == [
        "--workers", "2", "--port", "8100", "--model_name", "port"]
    assert strip_flag(argv, "--port", True) == [
        "--workers", "2", "--model_name", "port", "--prefork=3",
        "--prefork", "4"]
    assert strip_flag(["--reuse_port", "--x"], "--reuse_port", False) \
        == ["--x"]


def test_strip_flag_matches_argparse_prefix_abbreviations():
    """The fork-bomb regression (the PR 8 --watchdog bug class): argparse
    accepts `--prefor 3` as --prefork, so the supervisor must strip the
    ABBREVIATED spellings too — otherwise every worker re-exec parses
    prefork=3 again and spawns its own fleet, recursively."""
    for spelling in ("--prefork", "--prefor", "--pref", "--prefork=3",
                     "--prefor=3"):
        argv = ["--workers", "2", spelling]
        if "=" not in spelling:
            argv.append("3")
        assert strip_flag(argv, "--prefork", True) == ["--workers", "2"], \
            spelling
    # a DIFFERENT flag sharing no prefix relationship is untouched
    assert strip_flag(["--prefork", "3", "--probe_after_s", "5"],
                      "--prefork", True) == ["--probe_after_s", "5"]


def test_reserve_port_does_not_listen():
    sock, port = reserve_port("127.0.0.1")
    try:
        assert port > 0
        # a listening reuseport socket can bind the same port...
        worker = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        worker.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        worker.bind(("127.0.0.1", port))
        worker.listen(8)
        # ...and receives the connections (the reserver never listens, so
        # the kernel routes nothing to it)
        client = socket.create_connection(("127.0.0.1", port), timeout=5)
        conn, _ = worker.accept()
        conn.close()
        client.close()
        worker.close()
    finally:
        sock.close()


def test_supervise_prefork_rejects_zero():
    from dib_tpu.serve.prefork import supervise_prefork

    with pytest.raises(ValueError, match="prefork"):
        supervise_prefork([], prefork=0, host="127.0.0.1", port=0,
                          outdir=".")


# ------------------------------------------------------------ fleet smoke
def _load_loadgen():
    spec = importlib.util.spec_from_file_location(
        "serve_loadgen", os.path.join(REPO, "scripts", "serve_loadgen.py"))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as resp:
        return json.loads(resp.read())


def _post(url, payload):
    request = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(request, timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def test_prefork_fleet_smoke(tmp_path):
    """2 workers on one port through `python -m dib_tpu serve --prefork`:
    distinct pids answer, worker death degrades without an outage, the
    supervisor respawns, SIGTERM shuts the fleet down cleanly."""
    lg = _load_loadgen()
    ckpt_dir, _, _ = lg._train_tiny_checkpoint(6)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "dib_tpu", "serve",
         "--checkpoint_dir", ckpt_dir, *lg._TINY_ARCH_FLAGS,
         "--prefork", "2", "--port", "0",
         "--buckets", "1", "8", "--max_batch", "8",
         "--outdir", str(tmp_path / "fleet")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, env=env)
    try:
        hello = json.loads(proc.stdout.readline())
        assert hello["prefork"] == 2
        assert len(hello["workers"]) == 2
        url = hello["serving"]

        health = _get(url + "/healthz")
        assert health["status"] == "ok"
        width = health["feature_width"]
        row = [0.0] * width

        # the kernel spreads fresh connections across BOTH worker pids
        pids = set()
        for _ in range(24):
            pids.add(_get(url + "/metrics").get("pid"))
            if len(pids) == 2:
                break
        assert len(pids) == 2, "kernel never balanced across the fleet"

        status, payload = _post(url + "/v1/predict", {"x": row})
        assert status == 200 and "prediction" in payload

        # ---- SIGKILL one worker: the survivor carries traffic, the
        # supervisor respawns the dead one (stderr log + healed capacity)
        victim = hello["workers"][0]
        os.kill(victim, signal.SIGKILL)
        ok = 0
        for _ in range(20):
            try:
                status, _ = _post(url + "/v1/predict", {"x": row})
                ok += status == 200
            except OSError:
                pass   # a connection routed at the kill instant may reset
            time.sleep(0.05)
        assert ok >= 15, "fleet lost service during single-worker death"

        deadline = time.monotonic() + 60
        new_pids = set()
        while time.monotonic() < deadline:
            new_pids.add(_get(url + "/metrics").get("pid"))
            if len(new_pids - {victim}) == 2:
                break
            time.sleep(0.25)
        assert len(new_pids - {victim}) == 2, \
            "supervisor never respawned the killed worker"
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
    assert proc.returncode == 0
    assert "respawning" in proc.stderr.read()
