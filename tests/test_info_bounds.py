"""Validation of the log-space float32 MI sandwich bounds.

Three layers of defense, mirroring the reference's characterization notebook
(estimator vs Monte Carlo / analytic ground truth) plus a direct float64 oracle
for the exact reference algorithm (reference utils.py:36-65):
  1. numerical parity: f32 log-space == f64 density-space oracle on shared samples
  2. invariants: lower <= upper; lower <= log(batch)
  3. ground truth: well-separated k-bit discrete X transmits exactly k bits
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dib_tpu.ops import mi_sandwich_from_params, mi_sandwich_bounds, mi_sandwich_probe
from dib_tpu.ops.gaussian import reparameterize


def _f64_reference_bounds(u, mus, logvars):
    """Float64 oracle implementing the reference's density-space algorithm
    (utils.py:48-64): explicit p(u_i|x_j) matrix, mean over rows, diagonal
    zeroed for the LOO bound but still divided by B."""
    u = u.astype(np.float64)
    mus = mus.astype(np.float64)
    logvars = logvars.astype(np.float64)
    B, d = mus.shape
    stddevs = np.exp(logvars / 2.0)
    z = (u[:, None, :] - mus[None, :, :]) / stddevs[None, :, :]
    p = np.exp(-np.sum(z**2, -1) / 2.0 - np.sum(logvars, -1)[None, :] / 2.0)
    p = p / (2.0 * np.pi) ** (d / 2.0)
    p_ii = np.diagonal(p)
    lower = np.mean(np.log(p_ii / np.mean(p, axis=1)))
    p_off = p * (1.0 - np.eye(B))
    upper = np.mean(np.log(p_ii / np.mean(p_off, axis=1)))
    return lower, upper


def test_f32_logspace_matches_f64_density_space(rng):
    """The precision design question from SURVEY.md section 7: log-space f32
    must match the reference's f64 result to well under 0.01 bits."""
    B, d = 256, 32
    mus = rng.normal(scale=2.0, size=(B, d)).astype(np.float32)
    logvars = rng.normal(scale=1.0, size=(B, d)).astype(np.float32) - 1.0
    key = jax.random.key(0)
    u = np.asarray(reparameterize(key, jnp.array(mus), jnp.array(logvars)))

    want_lower, want_upper = _f64_reference_bounds(u, mus, logvars)
    # recompute through the jitted path with the same key => same sample
    got_lower, got_upper = mi_sandwich_from_params(key, jnp.array(mus), jnp.array(logvars))
    assert abs(float(got_lower) - want_lower) / np.log(2) < 1e-3  # < 0.001 bits
    assert abs(float(got_upper) - want_upper) / np.log(2) < 1e-3


def test_f32_logspace_survives_extreme_separation(rng):
    """Densities that underflow f32 (and even f64) in density space: log space
    must stay finite and ordered."""
    B, d = 64, 16
    mus = (rng.integers(0, 2, size=(B, 1)) * 2 - 1) * 50.0  # +-50, huge separation
    mus = np.concatenate([mus, np.zeros((B, d - 1))], -1).astype(np.float32)
    logvars = np.full((B, d), -6.0, dtype=np.float32)
    lower, upper = mi_sandwich_from_params(jax.random.key(1), jnp.array(mus), jnp.array(logvars))
    assert np.isfinite(float(lower)) and np.isfinite(float(upper))
    assert float(lower) <= float(upper) + 1e-5


def test_bound_ordering_and_log_batch_cap(rng):
    B, d = 128, 8
    for seed in range(3):
        mus = rng.normal(scale=1.5, size=(B, d)).astype(np.float32)
        logvars = rng.normal(scale=0.5, size=(B, d)).astype(np.float32)
        lower, upper = mi_sandwich_from_params(jax.random.key(seed), jnp.array(mus), jnp.array(logvars))
        assert float(lower) <= float(upper) + 1e-5
        assert float(lower) <= np.log(B) + 1e-5  # InfoNCE <= log batch size


@pytest.mark.parametrize("bits", [1, 2])
def test_exact_mi_recovery_discrete_channel(bits):
    """Characterization-notebook style ground truth: X uniform over 2^bits
    well-separated centers with tiny variance transmits exactly `bits` bits."""
    B, d = 1024, 8
    rng = np.random.default_rng(42)
    centers = np.array(np.meshgrid(*[[-4.0, 4.0]] * bits)).reshape(bits, -1).T  # [2^bits, bits]
    x_ids = rng.integers(0, centers.shape[0], size=B)
    mus = np.concatenate([centers[x_ids], np.zeros((B, d - bits))], -1).astype(np.float32)
    logvars = np.zeros((B, d), dtype=np.float32)

    lowers, uppers = [], []
    for seed in range(8):
        lo, up = mi_sandwich_from_params(jax.random.key(seed), jnp.array(mus), jnp.array(logvars))
        lowers.append(float(lo))
        uppers.append(float(up))
    lower_bits = np.mean(lowers) / np.log(2)
    upper_bits = np.mean(uppers) / np.log(2)
    assert lower_bits == pytest.approx(bits, abs=0.05)
    assert upper_bits == pytest.approx(bits, abs=0.05)
    # sandwich tightness: the reference claims ~0.01-bit gaps (boolean nb cell 6)
    assert upper_bits - lower_bits < 0.02


def test_zero_information_channel():
    """Identical Gaussians for every x => I = 0; LOO upper also ~0."""
    B, d = 512, 4
    mus = np.zeros((B, d), dtype=np.float32)
    logvars = np.zeros((B, d), dtype=np.float32)
    lower, upper = mi_sandwich_from_params(jax.random.key(3), jnp.array(mus), jnp.array(logvars))
    assert abs(float(lower)) < 0.02
    assert abs(float(upper)) < 0.02


def test_row_block_equals_unblocked(rng):
    B, d = 128, 8
    mus = rng.normal(size=(B, d)).astype(np.float32)
    logvars = rng.normal(scale=0.3, size=(B, d)).astype(np.float32)
    key = jax.random.key(5)
    full = mi_sandwich_from_params(key, jnp.array(mus), jnp.array(logvars))
    blocked = mi_sandwich_from_params(key, jnp.array(mus), jnp.array(logvars), row_block=32)
    np.testing.assert_allclose(float(full[0]), float(blocked[0]), rtol=1e-5)
    np.testing.assert_allclose(float(full[1]), float(blocked[1]), rtol=1e-5)


def test_mi_sandwich_bounds_encoder_contract(rng):
    """End-to-end averaging path with an encode_fn, 1-bit channel."""
    data = np.array([[-1.0], [1.0]] * 256, dtype=np.float32)

    def encode_fn(batch):
        mus = jnp.concatenate([batch * 4.0, jnp.zeros((batch.shape[0], 7))], -1)
        return mus, jnp.zeros_like(mus)

    lower, upper = mi_sandwich_bounds(
        encode_fn, jnp.array(data), jax.random.key(0),
        evaluation_batch_size=256, number_evaluation_batches=4,
    )
    assert float(lower) / np.log(2) == pytest.approx(1.0, abs=0.05)
    assert float(upper) / np.log(2) == pytest.approx(1.0, abs=0.05)


def test_probe_bounds_match_symmetric_case(rng):
    """When probes ARE the data batch (same key => same sample), the probe
    variant's LOO denominator (mean over the N data densities, which then
    include the self term once) is *identical* to the symmetric InfoNCE
    denominator — so probe-upper must equal symmetric-lower exactly. The probe
    InfoNCE counts the self term twice in its N+1-term denominator, so it sits
    slightly below."""
    B, d = 256, 8
    mus = rng.normal(scale=2.0, size=(B, d)).astype(np.float32)
    logvars = np.full((B, d), -1.0, dtype=np.float32)
    key = jax.random.key(9)
    lower_sym, _ = mi_sandwich_from_params(key, jnp.array(mus), jnp.array(logvars))
    lower_p, upper_p = mi_sandwich_probe(
        key, jnp.array(mus), jnp.array(logvars), jnp.array(mus), jnp.array(logvars)
    )
    assert lower_p.shape == (B,)
    np.testing.assert_allclose(float(jnp.mean(upper_p)), float(lower_sym), rtol=1e-5)
    assert float(jnp.mean(lower_p)) <= float(jnp.mean(upper_p)) + 1e-5


def test_probe_bounds_ordering(rng):
    M, N, d = 50, 200, 8
    probe_mus = rng.normal(scale=2.0, size=(M, d)).astype(np.float32)
    probe_logvars = np.full((M, d), -2.0, dtype=np.float32)
    data_mus = rng.normal(scale=2.0, size=(N, d)).astype(np.float32)
    data_logvars = np.full((N, d), -2.0, dtype=np.float32)
    lower, upper = mi_sandwich_probe(
        jax.random.key(2),
        jnp.array(probe_mus), jnp.array(probe_logvars),
        jnp.array(data_mus), jnp.array(data_logvars),
    )
    assert np.all(np.asarray(lower) <= np.asarray(upper) + 1e-5)
