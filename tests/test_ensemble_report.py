"""Unit contract for the north-star ensemble report builder
(scripts/northstar_ensemble.py): stall detection and the bimodal split
behind NORTHSTAR_ENSEMBLE.json's distribution_analysis."""

import importlib.util
import os
import sys

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "northstar_ensemble",
    os.path.join(os.path.dirname(os.path.dirname(__file__)),
                 "scripts", "northstar_ensemble.py"),
)
ens = importlib.util.module_from_spec(_SPEC)
sys.modules.setdefault("northstar_ensemble", ens)
_SPEC.loader.exec_module(ens)


def test_annotate_stalls_flags_only_outliers():
    chunks = [54.8] + [16.4] * 18 + [280.0]
    e = ens.annotate_stalls({"checkpoint_chunk_s": chunks})
    assert e["device_stall_s"] == [280.0]
    assert e["steady_chunk_median_s"] == pytest.approx(16.4)

    clean = ens.annotate_stalls({"checkpoint_chunk_s": [54.8] + [16.4] * 19})
    assert clean["device_stall_s"] == []


def test_annotate_stalls_ignores_first_chunk_and_missing_data():
    # chunk 0 carries init+compile and is excluded from detection
    e = ens.annotate_stalls({"checkpoint_chunk_s": [300.0] + [16.4] * 19})
    assert e["device_stall_s"] == []
    # uninstrumented entries pass through untouched
    assert "device_stall_s" not in ens.annotate_stalls({"value": 7.0})


def test_build_report_median_and_split():
    runs = [
        {"run": 0, "value": 6.9,
         "checkpoint_chunk_s": [54.0] + [16.4] * 19},
        {"run": 1, "value": 11.2,
         "checkpoint_chunk_s": [54.0] + [16.4] * 18 + [280.0]},
        {"run": 2, "value": 11.0},          # uninstrumented slow run
        {"run": 3, "value": 6.8},           # uninstrumented fast run
    ]
    rep = ens.build_report(runs, runs_requested=4)
    assert rep["runs_completed"] == 4
    assert rep["median_minutes"] == pytest.approx((6.9 + 11.0) / 2)
    ana = rep["distribution_analysis"]
    assert ana["stall_free_mode_minutes"] == [6.8, 6.9]
    assert ana["stalled_mode_minutes"] == [11.0, 11.2]
    assert ana["stalls_directly_observed"] == 1
    assert "1 of those have the stall directly observed" in ana["summary"]


def test_build_report_first_chunk_stall_falls_back_to_midpoint():
    # a stall hidden in chunk 0 yields device_stall_s == [] but the VALUE
    # heuristic must still classify the run as stalled (code review round 4)
    runs = [
        {"run": 0, "value": 6.9, "checkpoint_chunk_s": [54.0] + [16.4] * 19},
        {"run": 1, "value": 10.8,
         "checkpoint_chunk_s": [290.0] + [16.4] * 19},
    ]
    ana = ens.build_report(runs, 2)["distribution_analysis"]
    assert ana["stalled_mode_minutes"] == [10.8]
    assert ana["stalls_directly_observed"] == 0


def test_build_report_uniform_runs_are_all_stall_free():
    runs = [{"run": i, "value": 6.8 + 0.05 * i} for i in range(3)]
    ana = ens.build_report(runs, 3)["distribution_analysis"]
    assert ana["stalled_mode_minutes"] == []
    assert len(ana["stall_free_mode_minutes"]) == 3


def test_build_report_empty():
    rep = ens.build_report([{"run": 0, "error": "killed"}], 1)
    assert rep["runs_completed"] == 0
    assert rep["median_minutes"] is None


def test_build_report_watchdog_mitigated_run_counts_as_stalled():
    # a watchdog-mitigated run has CLEAN post-resume chunk clocks; the
    # mitigation record itself is the direct stall observation
    runs = [
        {"run": 0, "value": 6.9, "checkpoint_chunk_s": [54.0] + [16.4] * 19},
        {"run": 1, "value": 8.4,
         "checkpoint_chunk_s": [54.0] + [16.4] * 12,
         "watchdog": {"launches": 2, "mitigations": [
             {"type": "stall_kill", "epoch": 175, "waited_s": 51.0}]}},
    ]
    ana = ens.build_report(runs, 2)["distribution_analysis"]
    assert ana["stalled_mode_minutes"] == [8.4]
    assert ana["stalls_directly_observed"] == 1
    assert ana["stalls_mitigated_by_watchdog"] == 1


def test_build_report_crash_mitigated_run_excluded_from_stall_free_mode():
    runs = [
        {"run": 0, "value": 6.8, "checkpoint_chunk_s": [54.0] + [16.4] * 19},
        {"run": 1, "value": 7.9,
         "checkpoint_chunk_s": [54.0] + [16.4] * 10,
         "watchdog": {"launches": 2, "mitigations": [
             {"type": "crash_restart", "returncode": 1}]}},
    ]
    ana = ens.build_report(runs, 2)["distribution_analysis"]
    assert ana["stall_free_mode_minutes"] == [6.8]
    assert ana["stalled_mode_minutes"] == [7.9]
    assert ana["stalls_directly_observed"] == 0
    assert ana["stalls_mitigated_by_watchdog"] == 1


def test_build_report_member_extras_disqualify_baseline():
    runs = [{"run": 0, "value": 1.2}]
    rep = ens.build_report(runs, 1, ["--replicas", "2"])
    assert rep["vs_baseline_median"] is None
    assert rep["non_default_configuration"] is True
    assert rep["member_extra_flags"] == ["--replicas", "2"]
    assert rep["median_minutes"] == 1.2
