"""Characterization workload: the estimator-validation suite (SURVEY.md section 4).

The MC oracle itself is validated against analytic values first (well-separated
k-bit channels transmit exactly k bits; zero-scale channels transmit 0), then
the production f32 log-space estimator is validated against the oracle.
"""

import numpy as np
import pytest

from dib_tpu.workloads.characterization import (
    CharacterizationResult,
    SyntheticChannel,
    estimate_bounds_bits,
    monte_carlo_mi_bits,
    run_characterization,
    save_characterization_plots,
)


def test_mc_oracle_analytic_limits():
    # Well-separated 2-bit channel: exactly 2 bits.
    ch = SyntheticChannel(input_bits=2, scale=8.0, logvar=-2.0)
    assert monte_carlo_mi_bits(ch, num_samples=4000) == pytest.approx(2.0, abs=0.01)
    # Zero separation: exactly 0 bits.
    ch0 = SyntheticChannel(input_bits=2, scale=0.0)
    assert monte_carlo_mi_bits(ch0, num_samples=4000) == pytest.approx(0.0, abs=1e-9)


def test_mc_oracle_continuous_increases_with_scale():
    lo = monte_carlo_mi_bits(SyntheticChannel(input_bits=0, scale=0.5),
                             num_samples=4000, num_marginal_centers=1024)
    hi = monte_carlo_mi_bits(SyntheticChannel(input_bits=0, scale=5.0),
                             num_samples=4000, num_marginal_centers=1024)
    assert 0.0 <= lo < hi


def test_estimator_brackets_mc_truth_intermediate_regime():
    """In the partial-information regime the sandwich must bracket the truth
    (to within estimator noise) — the core claim of the notebook."""
    ch = SyntheticChannel(input_bits=2, scale=1.0)
    truth = monte_carlo_mi_bits(ch, num_samples=20_000)
    lowers, uppers = estimate_bounds_bits(ch, batch_size=1024, num_repeats=6)
    # Slack 0.05, not the estimator-std 0.02: the InfoNCE lower bound holds
    # in EXPECTATION over batches, and in this regime the single-batch
    # estimate carries a small positive finite-batch bias — measured at
    # +0.026 +- 0.008 bits against a seed-stable MC truth (0.971 at both
    # 20k and 200k samples, lowers.mean() 0.997 +- 0.020/sqrt(6) across
    # repeats). That bias is a property of the estimator at B=1024, not a
    # seed fluke, so the bracket allows bias + noise without masking a real
    # ordering violation (which would overshoot by >> 0.05).
    assert lowers.mean() <= truth + 0.05
    assert uppers.mean() >= truth - 0.02
    # and at B=1024 the sandwich is tight for a <=2-bit channel
    assert uppers.mean() - lowers.mean() < 0.05


def test_lower_bound_saturates_at_log_batch():
    """InfoNCE lower bound <= log2(B): at 6 bits true MI and B=64 (log2=6),
    the lower bound must be visibly capped below the truth while the upper
    bound is not — the batch-size effect the notebook sweeps."""
    ch = SyntheticChannel(input_bits=6, scale=8.0, logvar=-2.0)
    lowers, uppers = estimate_bounds_bits(ch, batch_size=64, num_repeats=4)
    assert lowers.mean() <= np.log2(64) + 0.01
    assert uppers.mean() >= 5.5


@pytest.mark.slow
def test_run_characterization_sweep_and_plots(tmp_path):
    results = run_characterization(
        input_bits_list=(1, 0),
        scales=(0.5, 4.0),
        batch_sizes=(64, 256),
        num_repeats=3,
        mc_samples=4000,
    )
    assert len(results) == 2 * 2 * 2
    for r in results:
        assert isinstance(r, CharacterizationResult)
        assert r.lower_mean <= r.upper_mean + 0.02
        # residual sanity in this easy regime: within a tenth of a bit + noise
        if r.batch_size >= 256 and r.channel.scale >= 4.0 and r.channel.is_discrete:
            assert abs(r.lower_residual) < 0.1
            assert abs(r.upper_residual) < 0.1
    paths = save_characterization_plots(results, str(tmp_path))
    assert len(paths) == 2
    for p in paths:
        assert (tmp_path / p.split("/")[-1]).exists()
