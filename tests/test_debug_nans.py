"""NaN-safety under jax_debug_nans (SURVEY.md section 5, sanitizers row).

With ``jax_debug_nans`` enabled JAX re-runs any primitive that produced a
NaN eagerly and raises — the functional-purity analogue of a sanitizer.
The train step must stay NaN-free even at aggressive beta and learning
rates (log-space bounds and f32-safe schedule math are what make this
hold; the reference's density-space math would NaN here).
"""

import jax
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.models import DistributedIBModel
from dib_tpu.ops import mi_sandwich_from_params
from dib_tpu.train import DIBTrainer, TrainConfig


@pytest.fixture
def debug_nans():
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", False)


@pytest.mark.slow
def test_train_chunk_nan_free_under_debug_nans(debug_nans):
    bundle = get_dataset("boolean_circuit")
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )
    # aggressive corner: large beta from the start, hot learning rate
    config = TrainConfig(
        learning_rate=3e-2, batch_size=64, beta_start=5.0, beta_end=50.0,
        num_pretraining_epochs=1, num_annealing_epochs=5, steps_per_epoch=2,
        max_val_points=128,
    )
    trainer = DIBTrainer(model, bundle, config)
    state, history = trainer.fit(jax.random.key(0))   # raises on any NaN
    rec = history.to_bits()
    assert np.isfinite(rec.loss).all()
    assert np.isfinite(rec.kl_per_feature).all()


def test_mi_bounds_nan_free_under_debug_nans(debug_nans):
    # extreme separations / tiny variances — the regime that NaNs in density
    # space (reference utils.py:54-57) but not in log space
    rng = np.random.default_rng(0)
    mus = jax.numpy.asarray(rng.normal(scale=50.0, size=(128, 8)), jax.numpy.float32)
    logvars = jax.numpy.full((128, 8), -12.0, jax.numpy.float32)
    lower, upper = mi_sandwich_from_params(jax.random.key(0), mus, logvars)
    assert np.isfinite(float(lower)) and np.isfinite(float(upper))
