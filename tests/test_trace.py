"""Span tracer + XLA cost analysis: nesting, parentage, thread safety,
durability under killed writers, cost-model-absent degradation, and the
fit-loop wiring (train/loop.py, workloads/boolean.py emit spans + a
cost-analyzed compile event).
"""

import json
import os
import threading

import numpy as np
import pytest

from dib_tpu.telemetry import (
    EventWriter,
    Tracer,
    read_events,
    span_hotspots,
    span_rollup,
    summarize,
    use_tracer,
)
from dib_tpu.telemetry import trace as trace_mod
from dib_tpu.telemetry import xla_stats
from dib_tpu.telemetry.hooks import FitRecorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ===================================================================== spans
def test_span_nesting_and_parentage(tmp_path):
    with EventWriter(str(tmp_path), run_id="r") as w:
        tracer = Tracer(w)
        with tracer.span("sweep") as outer:
            with tracer.span("chunk", epoch=100):
                pass
            with tracer.span("mi_bounds"):
                pass
            outer.annotate(replicas=8)
    spans = list(read_events(str(tmp_path), types=("span",)))
    # children close (and emit) before their parent
    assert [e["name"] for e in spans] == ["chunk", "mi_bounds", "sweep"]
    by_name = {e["name"]: e for e in spans}
    assert by_name["sweep"]["parent"] is None
    assert by_name["chunk"]["parent"] == by_name["sweep"]["span"]
    assert by_name["mi_bounds"]["parent"] == by_name["sweep"]["span"]
    assert by_name["chunk"]["path"] == "sweep/chunk"
    assert by_name["chunk"]["epoch"] == 100
    assert by_name["sweep"]["replicas"] == 8     # late annotate()
    ids = [e["span"] for e in spans]
    assert len(set(ids)) == 3
    assert all(e["seconds"] >= 0 for e in spans)
    # the timer accumulated under the full path
    assert "sweep/chunk" in tracer.timer.intervals


def test_span_slash_names_extend_path(tmp_path):
    """The issue's spelling — span("sweep/replica3/chunk12/mi_bounds") —
    works with or without enclosing spans."""
    with EventWriter(str(tmp_path), run_id="r") as w:
        tracer = Tracer(w)
        with tracer.span("sweep/replica3/chunk12/mi_bounds"):
            pass
    (e,) = read_events(str(tmp_path), types=("span",))
    assert e["path"] == "sweep/replica3/chunk12/mi_bounds"


def test_span_block_on_registers_outputs(tmp_path):
    import jax.numpy as jnp

    with EventWriter(str(tmp_path), run_id="r") as w:
        tracer = Tracer(w)
        with tracer.span("compute") as handle:
            out = handle.block_on(jnp.ones((4, 4)) @ jnp.ones((4, 4)))
    assert np.asarray(out).shape == (4, 4)
    (e,) = read_events(str(tmp_path), types=("span",))
    assert e["seconds"] > 0


def test_spans_are_thread_safe(tmp_path):
    """Two threads build independent, correctly-parented subtrees with
    globally unique ids on one tracer."""
    with EventWriter(str(tmp_path), run_id="r") as w:
        tracer = Tracer(w)
        barrier = threading.Barrier(2)

        def work(name):
            barrier.wait()
            for _ in range(20):
                with tracer.span(name):
                    with tracer.span("inner"):
                        pass

        threads = [threading.Thread(target=work, args=(f"t{i}",))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    spans = list(read_events(str(tmp_path), types=("span",)))
    assert len(spans) == 80
    assert len({e["span"] for e in spans}) == 80      # globally unique ids
    by_id = {e["span"]: e for e in spans}
    for e in spans:
        if e["name"] == "inner":
            parent = by_id[e["parent"]]
            # an inner span is parented to ITS thread's outer span
            assert e["path"] == parent["path"] + "/inner"


def test_span_stack_survives_block_failure(tmp_path):
    """A device error surfacing at block time (async dispatch defers it)
    must still pop and record the span — later spans in the thread must
    not inherit a dead parent or a bogus path prefix."""
    class Exploding:
        def block_until_ready(self):   # what a failed async chunk does
            raise RuntimeError("device OOM surfaced at block time")

    with EventWriter(str(tmp_path), run_id="r") as w:
        tracer = Tracer(w)
        with pytest.raises(RuntimeError, match="device OOM"):
            with tracer.span("doomed") as h:
                h.block_on(Exploding())
        with tracer.span("after"):
            pass
    spans = list(read_events(str(tmp_path), types=("span",)))
    assert [e["name"] for e in spans] == ["doomed", "after"]
    assert spans[1]["parent"] is None
    assert spans[1]["path"] == "after"       # no 'doomed/' prefix


def test_begin_end_open_span_parents_between(tmp_path):
    """The hook-pair span API: spans opened between begin() and end()
    nest under it (the northstar instrumentation-phase attribution)."""
    with EventWriter(str(tmp_path), run_id="r") as w:
        tracer = Tracer(w)
        token = tracer.begin("instrumentation", epoch=25)
        with tracer.span("mi_bounds"):
            pass
        tracer.end(token)
    spans = {e["name"]: e for e in read_events(str(tmp_path),
                                               types=("span",))}
    assert spans["mi_bounds"]["path"] == "instrumentation/mi_bounds"
    assert spans["mi_bounds"]["parent"] == spans["instrumentation"]["span"]
    assert spans["instrumentation"]["epoch"] == 25


def test_chunk_phase_hooks_nest_hook_spans(tmp_path):
    """End-to-end northstar shape: SpannedHook work between pre and post
    parents under 'instrumentation' — no sibling double-count."""
    from dib_tpu.telemetry import ChunkPhaseHooks, SpannedHook

    with EventWriter(str(tmp_path), run_id="ns") as w:
        tracer = Tracer(w)
        phases = ChunkPhaseHooks(telemetry=w, tracer=tracer,
                                 steps_per_epoch=50)
        hook = SpannedHook("mi_bounds", lambda t, s, e: None)
        phases.start()
        states = np.zeros(2)
        with use_tracer(tracer):
            phases.pre(None, states, 25)
            hook(None, states, 25)
            phases.post(None, states, 25)
    spans = {e["name"]: e for e in read_events(str(tmp_path),
                                               types=("span",))}
    assert spans["mi_bounds"]["path"] == "instrumentation/mi_bounds"
    assert spans["mi_bounds"]["parent"] == spans["instrumentation"]["span"]
    assert spans["chunk"]["parent"] is None
    # the instrumentation interval covers its nested hook
    assert spans["instrumentation"]["seconds"] >= spans["mi_bounds"]["seconds"]


def test_span_hotspots_nearest_ancestor_children():
    """Slash-named spans may skip levels: a grandchild with no recorded
    intermediate still reduces its nearest present ancestor's self time."""
    rollup = {
        "a": {"total_s": 10.0, "count": 1, "mean_s": 10.0},
        "a/b/c": {"total_s": 8.0, "count": 1, "mean_s": 8.0},
    }
    hot = {h["path"]: h["self_s"] for h in span_hotspots(rollup)}
    assert hot["a"] == pytest.approx(2.0)
    assert hot["a/b/c"] == pytest.approx(8.0)


def test_tracer_add_external_interval(tmp_path):
    """Hook-boundary timers (ChunkPhaseHooks) record via add() — spans
    without a with-block, still parented and on the timer."""
    with EventWriter(str(tmp_path), run_id="r") as w:
        tracer = Tracer(w)
        tracer.add("chunk", 1.25, epoch=50)
    (e,) = read_events(str(tmp_path), types=("span",))
    assert e["name"] == "chunk" and e["seconds"] == 1.25 and e["epoch"] == 50
    assert tracer.timer.totals["chunk"] == 1.25


def test_use_tracer_binds_module_level_span(tmp_path):
    with EventWriter(str(tmp_path), run_id="r") as w:
        tracer = Tracer(w)
        with use_tracer(tracer):
            assert trace_mod.current_tracer() is tracer
            with trace_mod.span("bound"):
                pass
        # unbound: module-level spans still work, but emit nothing
        with trace_mod.span("unbound"):
            pass
    assert [e["name"] for e in read_events(str(tmp_path), types=("span",))] \
        == ["bound"]


def test_spanned_hook_cadence_and_passthrough(tmp_path):
    from dib_tpu.telemetry import SpannedHook
    from dib_tpu.train.hooks import Every

    calls = []

    class Inner:
        records = ["sentinel"]

        def __call__(self, trainer, state, epoch):
            calls.append(epoch)

    with EventWriter(str(tmp_path), run_id="r") as w:
        hook = SpannedHook("mi_bounds", Every(100, Inner()))
        with use_tracer(Tracer(w)):
            hook(None, None, 50)     # cadence miss: no phantom span
            hook(None, None, 100)
    assert calls == [100]
    # attribute passthrough reaches the directly wrapped hook
    assert SpannedHook("x", Inner()).records == ["sentinel"]
    spans = list(read_events(str(tmp_path), types=("span",)))
    assert [e["epoch"] for e in spans] == [100]
    assert spans[0]["name"] == "mi_bounds"


def test_torn_span_line_tolerated(tmp_path):
    """A writer killed mid-span-append leaves one torn line; the rest of
    the span stream (and its rollups) stays readable."""
    with EventWriter(str(tmp_path), run_id="r") as w:
        tracer = Tracer(w)
        for i in range(3):
            with tracer.span("chunk", epoch=i):
                pass
        w.chunk(epoch=3, steps=10, seconds=1.0)
    path = os.path.join(str(tmp_path), "events.jsonl")
    raw = open(path, "rb").read().split(b"\n")
    raw[1] = b'{"v": 1, "run": "r", "type": "span", "name": "chu'  # SIGKILL
    with open(path, "wb") as f:
        f.write(b"\n".join(raw))
    with pytest.warns(UserWarning, match="torn event line"):
        spans = list(read_events(path, types=("span",)))
    assert len(spans) == 2
    with pytest.warns(UserWarning):
        s = summarize(path)
    assert s["spans"]["chunk"]["count"] == 2


# ================================================================== rollups
def test_span_rollup_normalizes_dynamic_indices():
    events = [
        {"path": "sweep/replica3/chunk12/mi_bounds", "seconds": 1.0},
        {"path": "sweep/replica7/chunk9/mi_bounds", "seconds": 2.0},
        {"path": "sweep/replica3", "seconds": 4.0},
    ]
    rollup = span_rollup(events)
    assert rollup["sweep/replica*/chunk*/mi_bounds"]["count"] == 2
    assert rollup["sweep/replica*/chunk*/mi_bounds"]["total_s"] == 3.0
    assert rollup["sweep/replica*"]["total_s"] == 4.0


def test_span_hotspots_rank_by_self_time():
    rollup = {
        "fit": {"total_s": 10.0, "count": 1, "mean_s": 10.0},
        "fit/chunk": {"total_s": 7.0, "count": 5, "mean_s": 1.4},
        "fit/mi": {"total_s": 2.0, "count": 5, "mean_s": 0.4},
    }
    hot = span_hotspots(rollup)
    assert hot[0]["path"] == "fit/chunk" and hot[0]["self_s"] == 7.0
    # fit's SELF time is 10 - 9 = 1, ranked below mi's 2
    assert [h["path"] for h in hot] == ["fit/chunk", "fit/mi", "fit"]
    assert hot[2]["self_s"] == pytest.approx(1.0)


# ================================================================ xla stats
def test_backend_peaks_ordered_match():
    assert xla_stats.backend_peaks("TPU v5p chip")["bf16_tflops"] == 459.0
    assert xla_stats.backend_peaks("TPU v5 lite")["bf16_tflops"] == 197.0
    assert xla_stats.backend_peaks("cpu") is None
    assert xla_stats.backend_peaks(None) is None


def test_achieved_roofline_arithmetic():
    out = xla_stats.achieved(2.0, flops=4e12, bytes_accessed=2e10,
                             peaks={"bf16_tflops": 200.0, "hbm_gbps": 800.0})
    assert out["achieved_gflops"] == pytest.approx(2000.0)
    assert out["flops_frac_of_peak"] == pytest.approx(0.01)
    assert out["achieved_gbps"] == pytest.approx(10.0)
    assert out["bandwidth_frac_of_peak"] == pytest.approx(0.0125)
    assert out["arithmetic_intensity"] == pytest.approx(200.0)
    assert xla_stats.achieved(0.0, flops=1.0) == {}


def test_compiled_cost_stats_on_cpu():
    """The CPU backend exposes a cost model: flops/bytes of a real jitted
    program come back as finite floats."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return (x @ x).sum()

    cost = xla_stats.compiled_cost_stats(f, jnp.ones((32, 32)))
    assert cost is not None
    assert cost["flops"] > 0 and cost["bytes_accessed"] > 0


def test_compiled_cost_stats_degrades_to_none():
    class Broken:
        def lower(self, *a, **k):
            raise RuntimeError("no cost model on this backend")

    assert xla_stats.compiled_cost_stats(Broken()) is None


def test_record_compile_event_duration_only(tmp_path):
    """cost_analysis()-absent backends: the compile event is still emitted
    (duration-only) and nothing downstream crashes — summarize reports
    spans with no utilization section."""

    class Broken:
        def lower(self, *a, **k):
            raise RuntimeError("unsupported")

    with EventWriter(str(tmp_path), run_id="r") as w:
        tracer = Tracer(w)
        with tracer.span("chunk"):
            pass
        cost = xla_stats.record_compile_event(w, "run_chunk", Broken(),
                                              cache="off")
        assert cost is None
        w.chunk(epoch=1, steps=10, seconds=1.0)
    (compile_event,) = read_events(str(tmp_path), types=("compile",))
    assert "flops" not in compile_event
    s = summarize(str(tmp_path))
    assert "chunk" in s["spans"]
    assert "utilization" not in s


def test_fit_recorder_record_compile_counts_cache(tmp_path, monkeypatch):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return x * 2

    monkeypatch.setattr("dib_tpu.utils.compile_cache._STATUS", "warm")
    with EventWriter(str(tmp_path), run_id="r") as w:
        rec = FitRecorder(w, steps_per_epoch=10)
        cost = rec.record_compile("run_chunk", f, jnp.ones(4), epochs=2)
        assert cost is not None and cost["flops"] >= 0
        # second call with the same name is a no-op (once per fit)
        assert rec.record_compile("run_chunk", f, jnp.ones(4)) is None
    snap = rec.registry.snapshot()
    assert snap["counters"]["compile_cache.hits"] == 1.0
    (compile_event,) = read_events(str(tmp_path), types=("compile",))
    assert compile_event["cache"] == "warm"


def test_cost_analysis_env_opt_out(tmp_path, monkeypatch):
    monkeypatch.setenv("DIB_XLA_COST_ANALYSIS", "0")
    assert not xla_stats.cost_analysis_enabled()

    class Exploding:
        def lower(self, *a, **k):  # must never be reached when opted out
            raise AssertionError("lowered despite opt-out")

    with EventWriter(str(tmp_path), run_id="r") as w:
        assert xla_stats.record_compile_event(w, "x", Exploding(),
                                              cache="off") is None
    (e,) = read_events(str(tmp_path), types=("compile",))
    assert e["name"] == "x"


# ============================================================== memory stats
def test_host_memory_stats_linux():
    from dib_tpu.telemetry import host_memory_stats

    stats = host_memory_stats()
    assert stats is not None            # CI runs on Linux
    assert stats["rss_bytes"] > 0
    # VmHWM can be hidden by sandboxed kernels; when present it bounds RSS
    if "peak_rss_bytes" in stats:
        assert stats["peak_rss_bytes"] >= stats["rss_bytes"]


# ============================================================== fit wiring
@pytest.fixture(scope="module")
def boolean_run(tmp_path_factory):
    """One tiny boolean fit with telemetry: spans + cost-analyzed compiles."""
    import jax

    from dib_tpu.workloads.boolean import (
        BooleanTrainer,
        BooleanWorkloadConfig,
        fetch_boolean_circuit,
    )

    tmp = tmp_path_factory.mktemp("boolean_run")
    config = BooleanWorkloadConfig(num_steps=40, mi_every=20,
                                   integration_hidden=(32,), batch_size=64)
    trainer = BooleanTrainer(fetch_boolean_circuit(), config)
    with EventWriter(str(tmp), run_id="fit") as w:
        trainer.fit(jax.random.key(0), telemetry=w)
    return str(tmp)


def test_boolean_fit_emits_spans_and_cost(boolean_run):
    events = list(read_events(boolean_run))
    spans = [e for e in events if e["type"] == "span"]
    assert {e["name"] for e in spans} == {"chunk", "mi_bounds"}
    assert len([e for e in spans if e["name"] == "chunk"]) == 2
    compiles = {e["name"]: e for e in events if e["type"] == "compile"}
    assert set(compiles) == {"run_chunk", "channel_mi_bounds"}
    # the CPU backend has a cost model: flops recorded
    assert compiles["channel_mi_bounds"]["flops"] > 0
    # chunk events carry the host-RSS fallback even though device memory
    # stats are None on CPU
    chunk = next(e for e in events if e["type"] == "chunk")
    assert chunk["memory"] is None
    assert chunk["host_memory"]["rss_bytes"] > 0


def test_boolean_fit_summary_rollups(boolean_run):
    s = summarize(boolean_run)
    assert s["spans"]["chunk"]["count"] == 2
    assert s["spans"]["mi_bounds"]["count"] == 2
    assert len(s["span_hotspots"]) >= 2
    assert "channel_mi_bounds" in s["utilization"]
    assert s["utilization"]["channel_mi_bounds"]["achieved_gflops"] > 0
    assert s["memory"]["host_peak_rss_bytes"] > 0
    # live gauges from the metrics rollup: achieved rates for the chunk
    gauges = {k: v for k, v in s["metrics"].items() if "achieved" in k}
    assert any(k.startswith("gauges.achieved_gflops.run_chunk")
               for k in gauges)


def test_serial_trainer_fit_emits_chunk_spans(tmp_path):
    import jax

    from dib_tpu.data import get_dataset
    from dib_tpu.models import DistributedIBModel
    from dib_tpu.train import DIBTrainer, TrainConfig

    bundle = get_dataset("boolean_circuit")
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(8,),
        output_dim=bundle.output_dimensionality, embedding_dim=2,
    )
    config = TrainConfig(num_pretraining_epochs=0, num_annealing_epochs=4,
                         batch_size=32, steps_per_epoch=2)
    trainer = DIBTrainer(model, bundle, config)
    with EventWriter(str(tmp_path), run_id="serial") as w:
        trainer.fit(jax.random.key(0), hook_every=2, telemetry=w)
    events = list(read_events(str(tmp_path)))
    spans = [e for e in events if e["type"] == "span"]
    assert [e["name"] for e in spans] == ["chunk", "chunk"]
    (compile_event,) = [e for e in events if e["type"] == "compile"]
    assert compile_event["name"] == "run_chunk"


def test_chunk_phase_hooks_mirror_spans(tmp_path):
    """The northstar driver's checkpoint cycle: with a tracer attached,
    every chunk/instrumentation interval also lands as a span event, and
    the PhaseTimer intervals keep their historical keys."""
    from dib_tpu.telemetry import ChunkPhaseHooks

    with EventWriter(str(tmp_path), run_id="ns") as w:
        tracer = Tracer(w)
        phases = ChunkPhaseHooks(telemetry=w, tracer=tracer,
                                 steps_per_epoch=50)
        phases.start()
        states = np.zeros(2)
        phases.pre(None, states, 25)
        phases.post(None, states, 25)
        phases.pre(None, states, 50)
        phases.post(None, states, 50)
    assert len(phases.timer.intervals["chunk"]) == 2
    assert len(phases.timer.intervals["instrumentation"]) == 2
    spans = list(read_events(str(tmp_path), types=("span",)))
    assert [e["name"] for e in spans] == ["chunk", "instrumentation"] * 2
    assert [e["epoch"] for e in spans] == [25, 25, 50, 50]
    # chunk events still emitted alongside (back-compat with summarize)
    assert len(list(read_events(str(tmp_path), types=("chunk",)))) == 2


# ======================================================== timing hygiene gate
def test_package_timing_hygiene():
    import sys

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from check_timing_hygiene import scan_package

    violations = scan_package()
    assert not violations, "\n".join(violations)
