"""Model zoo: executable LRU, response cache, reload invalidation
(docs/serving.md "The model zoo and its two caches").

The load-bearing contracts:

  - lazy engines produce BIT-IDENTICAL results to eager ones, through
    compile-on-miss, cache hits, and recompile-after-eviction;
  - a reloaded checkpoint NEVER serves stale cached responses (the
    invalidation test the ISSUE names);
  - model selection over one endpoint routes to the named checkpoint.
"""

import jax
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.models import DistributedIBModel
from dib_tpu.serve import (
    DIBServer,
    ExecutableLRU,
    InferenceEngine,
    MicroBatcher,
    ModelZoo,
    ReplicaEntry,
    ReplicaRouter,
    ResponseCache,
)
from dib_tpu.telemetry import MetricsRegistry


@pytest.fixture(scope="module")
def bundle():
    return get_dataset("boolean_circuit")


@pytest.fixture(scope="module")
def model(bundle):
    return DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )


@pytest.fixture(scope="module")
def params(bundle, model):
    x0 = np.asarray(bundle.x_train[:4], np.float32)
    return model.init(jax.random.key(0), x0, jax.random.key(1))


@pytest.fixture(scope="module")
def params_b(bundle, model):
    x0 = np.asarray(bundle.x_train[:4], np.float32)
    return model.init(jax.random.key(7), x0, jax.random.key(8))


# --------------------------------------------------------------- exec LRU
def test_lazy_engine_matches_eager_through_hits_and_evictions(
        model, params, bundle):
    """Lazy compile-on-miss, hit, evict, recompile — every path returns
    exactly what the eager engine returns, and the counters tell the
    story."""
    registry = MetricsRegistry()
    lru = ExecutableLRU(2, registry=registry)
    lazy = InferenceEngine(model, params, batch_buckets=(1, 4),
                           exec_cache=lru, cache_key="m/r0")
    eager = InferenceEngine(model, params, batch_buckets=(1, 4))
    rows = np.asarray(bundle.x_valid[:3], np.float32)

    def counters():
        c = registry.snapshot()["counters"]
        return (c.get("serve.cache.exec.hits", 0),
                c.get("serve.cache.exec.misses", 0),
                c.get("serve.cache.exec.evictions", 0))

    assert counters() == (0, 0, 0)   # nothing compiled at init (lazy)
    one = lazy.predict(rows[0])                      # miss: (predict, 1)
    np.testing.assert_array_equal(one["prediction"],
                                  eager.predict(rows[0])["prediction"])
    assert counters() == (0, 1, 0)
    lazy.predict(rows[0])                            # hit
    assert counters() == (1, 1, 0)
    batch = lazy.predict(rows)                       # miss: (predict, 4)
    np.testing.assert_array_equal(batch["prediction"],
                                  eager.predict(rows)["prediction"])
    assert counters() == (1, 2, 0)
    enc = lazy.encode(rows[0])                       # miss -> EVICTS (predict,1)
    np.testing.assert_array_equal(enc["mus"],
                                  eager.encode(rows[0])["mus"])
    assert counters() == (1, 3, 1)
    assert lru.stats() == {"entries": 2, "capacity": 2}
    # the evicted executable recompiles transparently, bit-identical
    again = lazy.predict(rows[0])
    np.testing.assert_array_equal(again["prediction"], one["prediction"])
    assert counters()[1] == 4


def test_exec_lru_invalidate_by_prefix(model, params):
    registry = MetricsRegistry()
    lru = ExecutableLRU(8, registry=registry)
    a = InferenceEngine(model, params, batch_buckets=(1,),
                        exec_cache=lru, cache_key="a/r0")
    b = InferenceEngine(model, params, batch_buckets=(1,),
                        exec_cache=lru, cache_key="b/r0")
    x = np.zeros(a.feature_width, np.float32)
    a.predict(x), b.predict(x)
    assert lru.stats()["entries"] == 2
    assert lru.invalidate("a/") == 1
    assert lru.stats()["entries"] == 1
    b.predict(x)   # b's executable survived
    assert registry.snapshot()["counters"]["serve.cache.exec.hits"] == 1


# ---------------------------------------------------------- response cache
def test_response_cache_lru_and_stats():
    cache = ResponseCache(2, registry=MetricsRegistry())
    k1, k2, k3 = ("m", "predict", None, "d1"), ("m", "predict", None, "d2"), \
        ("m", "predict", None, "d3")
    assert cache.get(k1) is None
    cache.put(k1, {"v": 1})
    cache.put(k2, {"v": 2})
    assert cache.get(k1) == {"v": 1}
    cache.put(k3, {"v": 3})          # evicts k2 (k1 was touched)
    assert cache.get(k2) is None
    assert cache.get(k1) == {"v": 1}
    assert cache.stats() == {"entries": 2, "capacity": 2}


def _zoo_server(zoo):
    return DIBServer(zoo, port=0)   # handle_post facade; no socket needed


def _router(model, params, zoo=None, name=None, registry=None):
    engine = InferenceEngine(
        model, params, batch_buckets=(1, 4), registry=registry,
        exec_cache=zoo.exec_cache if zoo is not None else None,
        cache_key=f"{name}/r0" if name is not None else None)
    return ReplicaRouter(
        [ReplicaEntry(engine, MicroBatcher(engine, max_wait_ms=0.0), 0)])


def test_response_cache_invalidated_on_checkpoint_reload(
        model, params, params_b, bundle):
    """THE invalidation contract: after ``ModelZoo.reload``, a repeated
    query re-dispatches against the NEW params — yesterday's cached
    answer never survives the swap (and the old executables are
    evicted)."""
    registry = MetricsRegistry()
    zoo = ModelZoo(exec_capacity=8, response_capacity=32,
                   registry=registry)
    zoo.register("m", _router(model, params, zoo=zoo, name="m"))
    server = _zoo_server(zoo)
    try:
        row = np.asarray(bundle.x_valid[0], np.float32).tolist()
        status, first = server.handle_post("/v1/predict", {"x": row})
        assert status == 200 and "cached" not in first
        status, second = server.handle_post("/v1/predict", {"x": row})
        assert status == 200 and second.get("cached") is True
        assert second["prediction"] == first["prediction"]

        zoo.reload("m", _router(model, params_b, zoo=zoo, name="m"))

        status, third = server.handle_post("/v1/predict", {"x": row})
        assert status == 200
        # NOT served from the stale cache...
        assert "cached" not in third
        # ...and numerically the NEW checkpoint's answer
        want = InferenceEngine(model, params_b,
                               batch_buckets=(4,)).predict(
            np.asarray([row], np.float32))
        np.testing.assert_allclose(third["prediction"],
                                   want["prediction"], rtol=1e-6)
        assert third["prediction"] != first["prediction"]
        # a repeat is cached again, against the new params
        status, fourth = server.handle_post("/v1/predict", {"x": row})
        assert fourth.get("cached") is True
        assert fourth["prediction"] == third["prediction"]
        counters = registry.snapshot()["counters"]
        assert counters["serve.cache.response.invalidations"] == 1
        assert counters["serve.zoo.reloads"] == 1
    finally:
        server.close()


def test_reload_unknown_model_raises(model, params):
    zoo = ModelZoo()
    zoo.register("m", _router(model, params))
    with pytest.raises(KeyError, match="not registered"):
        zoo.reload("nope", _router(model, params))
    zoo.close()


# ------------------------------------------------------------ zoo routing
def test_model_selection_routes_to_named_checkpoint(
        model, params, params_b, bundle):
    """Two checkpoints behind one endpoint: {"model": name} selects, the
    default resolves to the first registered, unknown names 404."""
    zoo = ModelZoo(response_capacity=8)
    zoo.register("alpha", _router(model, params))
    zoo.register("bravo", _router(model, params_b))
    server = _zoo_server(zoo)
    try:
        row = np.asarray(bundle.x_valid[1], np.float32).tolist()
        status, default = server.handle_post("/v1/predict", {"x": row})
        assert status == 200 and default["model"] == "alpha"
        status, named = server.handle_post("/v1/predict",
                                           {"x": row, "model": "bravo"})
        assert status == 200 and named["model"] == "bravo"
        assert named["prediction"] != default["prediction"]
        # per-(model, input) cache keys never cross checkpoints
        status, named2 = server.handle_post("/v1/predict",
                                            {"x": row, "model": "bravo"})
        assert named2.get("cached") is True
        assert named2["prediction"] == named["prediction"]
        status, missing = server.handle_post("/v1/predict",
                                             {"x": row, "model": "zulu"})
        assert status == 404 and "zulu" in missing["error"]
        # the registry surface
        status, listing = server.handle_get("/v1/models")
        assert status == 200
        assert [m["model"] for m in listing["models"]] == ["alpha", "bravo"]
    finally:
        server.close()


def test_zoo_add_params_and_describe(model, params, bundle):
    zoo = ModelZoo(exec_capacity=4, response_capacity=4)
    zoo.add_params("m", model, params, batch_buckets=(1, 4),
                   max_wait_ms=0.0, checkpoint_dir="/tmp/ckpt-m")
    name, router = zoo.resolve(None)
    assert name == "m" and len(router.entries) >= 1
    x = np.asarray(bundle.x_valid[:2], np.float32)
    got = router.entries[0].batcher(x, timeout_s=30.0)
    want = InferenceEngine(model, params, batch_buckets=(4,)).predict(x)
    np.testing.assert_array_equal(got["prediction"], want["prediction"])
    desc = zoo.describe()
    assert desc[0]["model"] == "m"
    assert desc[0]["checkpoint_dir"] == "/tmp/ckpt-m"
    assert zoo.cache_stats()["exec"]["capacity"] == 4
    zoo.close()
