"""The drill matrix artifact contract + the full end-to-end matrix (slow).

Fast tier: the committed ``FAULT_DRILL.json`` must exist, validate against
the shared artifact schema, cover every drill in the matrix, and show
every drill passing — the drilled recovery guarantees docs/robustness.md
cites are only as good as the committed evidence. Slow tier: actually
re-run the whole matrix (subprocess CLI workers under the watchdog
included) and require a clean sweep.
"""

import importlib.util
import json
import os
import sys

import pytest

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "FAULT_DRILL.json")

EXPECTED_DRILLS = {
    "train_stall", "train_kill", "train_nan", "preempt",
    "sweep_replica_nan", "sweep_replica_ejected", "sweep_member_backfill",
    "desync",
    "ckpt_truncate", "ckpt_bitflip_manifest",
    "serve_replica_error", "serve_replica_slow", "serve_batcher_crash",
    "http_malformed",
}


def _load_drill_module():
    spec = importlib.util.spec_from_file_location(
        "fault_drill", os.path.join(REPO, "scripts", "fault_drill.py"))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_committed_drill_artifact_validates():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from check_run_artifacts import check_file

    assert os.path.exists(ARTIFACT), (
        "FAULT_DRILL.json missing — run `python scripts/fault_drill.py "
        "--out FAULT_DRILL.json` and commit the record")
    assert check_file(ARTIFACT) == []


def test_committed_drill_matrix_is_complete_and_green():
    with open(ARTIFACT) as f:
        record = json.load(f)
    assert record["metric"] == "fault_drill_matrix"
    assert record["unit"] == "drills_passed"
    drills = {d["drill"]: d for d in record["matrix"]}
    assert set(drills) == EXPECTED_DRILLS
    failed = [name for name, d in drills.items() if not d["ok"]]
    assert not failed, f"committed drill record shows failures: {failed}"
    assert record["all_passed"] is True
    assert record["value"] == record["total"] == len(EXPECTED_DRILLS)
    # the committed record must be the FULL matrix, not a --quick run
    assert record["quick"] is False


def test_committed_drill_evidence_has_detection_and_recovery():
    """The stream-side join (telemetry summarize) must agree with the
    script's own bookkeeping: every injected train/serve fault detected
    AND recovered, with measured times."""
    with open(ARTIFACT) as f:
        record = json.load(f)
    for d in record["matrix"]:
        evidence = d.get("evidence") or {}
        faults = evidence.get("faults")
        if faults is None:
            continue   # http_malformed evidence is status-code-only
        assert faults["undetected"] == [], d["drill"]
        assert faults["detected"] == faults["injected"], d["drill"]
        assert faults["recovered"] == faults["injected"], d["drill"]
        assert faults["time_to_detect_s"]["mean"] >= 0, d["drill"]
    # the watchdog + sweep-heal drills carry the bit-identity verdict
    # explicitly (a healed replica must be indistinguishable from a run
    # the fault never touched)
    for name in ("train_stall", "train_kill", "train_nan", "preempt",
                 "sweep_replica_nan"):
        (d,) = [x for x in record["matrix"] if x["drill"] == name]
        assert d["bit_identical_history"] is True, name
    # the ejection drill proves degradation, not healing: the member is
    # marked, the neighbor untouched
    (d,) = [x for x in record["matrix"]
            if x["drill"] == "sweep_replica_ejected"]
    assert d["ejected_replica"] == 1 and d["neighbor_bit_identical"] is True
    # the desync drill proves naming + bounded detection
    (d,) = [x for x in record["matrix"] if x["drill"] == "desync"]
    assert d["lagging_host_named"] is True
    assert d["straggler_bounded"] is True


@pytest.mark.slow
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_full_drill_matrix_end_to_end(tmp_path):
    """Re-run the ENTIRE matrix (watchdog subprocess drills included) on
    this machine; every drill must pass."""
    module = _load_drill_module()
    record = module.run_drills(workdir=str(tmp_path), quick=False,
                               log=lambda m: None)
    failed = [d for d in record["matrix"] if not d["ok"]]
    assert not failed, json.dumps(failed, indent=1, default=str)[:4000]
    assert record["all_passed"]


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_quick_serve_and_ckpt_drills(tmp_path):
    """The in-process half of the matrix runs green in the fast tier (the
    subprocess watchdog drills stay behind @slow)."""
    module = _load_drill_module()
    record = module.run_drills(workdir=str(tmp_path), quick=True,
                               log=lambda m: None)
    failed = [d for d in record["matrix"] if not d["ok"]]
    assert not failed, json.dumps(failed, indent=1, default=str)[:4000]
    assert {d["drill"] for d in record["matrix"]} == {
        "sweep_replica_nan", "sweep_replica_ejected",
        "sweep_member_backfill", "desync",
        "ckpt_truncate", "ckpt_bitflip_manifest", "serve_replica_error",
        "serve_replica_slow", "serve_batcher_crash", "http_malformed",
    }