"""Content-integrity checkpoints (ISSUE 14): manifest v3 per-leaf
digests, v1/v2/v3 interop, the quarantine lifecycle, and the
``python -m dib_tpu ckpt scrub`` CLI.

The load-bearing contracts:

  - v3 save → v3 restore verifies digests on EVERY restore path (they
    all funnel through ``DIBCheckpointer.restore``);
  - a digest mismatch raises ``CheckpointCorruptionError`` NAMING the
    offending leaf — not a deep Orbax error (and for a bit flip in the
    tensorstore data plane, Orbax raises NOTHING: the digest is the only
    detector — pinned here);
  - a v2/v1 manifest restores vacuously under the v3 reader (rolling
    upgrade);
  - corrupt steps are QUARANTINED (moved, never deleted) and no restore
    or rollback path can ever re-select them;
  - ``ckpt scrub`` exits 0 clean / 1 mismatch / 2 bad operand, in
    process and through the subprocess CLI.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.models import DistributedIBModel
from dib_tpu.train import (
    CheckpointCorruptionError,
    CheckpointHook,
    DIBCheckpointer,
    DIBTrainer,
    TrainConfig,
)
from dib_tpu.train.checkpoint import MANIFEST_FILENAME, read_manifest
from dib_tpu.train.scrub import scrub_main

pytestmark = pytest.mark.fault


@pytest.fixture(scope="module")
def bundle():
    return get_dataset("boolean_circuit")


def make_trainer(bundle):
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=bundle.output_dimensionality, embedding_dim=2,
    )
    return DIBTrainer(model, bundle, TrainConfig(
        batch_size=64, num_pretraining_epochs=2, num_annealing_epochs=4,
        steps_per_epoch=2, max_val_points=128,
    ))


@pytest.fixture()
def two_steps(bundle, tmp_path):
    """A checkpoint dir holding intact steps 3 and 6 (v3 manifest)."""
    trainer = make_trainer(bundle)
    ckpt = DIBCheckpointer(str(tmp_path / "ck"))
    trainer.fit(jax.random.key(0), hooks=[CheckpointHook(ckpt)],
                hook_every=3)
    yield ckpt, trainer
    ckpt.close()


def _flip_data_bit(ckpt_dir: str, step: int) -> str:
    from dib_tpu.faults import corrupt_checkpoint

    return corrupt_checkpoint(ckpt_dir, "ckpt_bitflip_payload",
                              step=step)["path"]


# ------------------------------------------------------------ v3 digests
def test_v3_restore_verifies_digests_and_bitflip_is_orbax_silent(
        bundle, two_steps):
    """THE SDC shape: one flipped bit in the tensorstore data plane
    restores silently through Orbax — only the v3 digest catches it,
    and the error names the offending leaf path."""
    ckpt, trainer = two_steps
    manifest = read_manifest(ckpt.directory)
    assert manifest["checkpoint_schema"] == 3
    assert set(manifest["content"]) == {"3", "6"}

    # clean restore verifies silently
    state, _, _ = ckpt.restore(make_trainer(bundle), step=6, chunk_size=3)
    assert int(state.epoch) == 6

    _flip_data_bit(ckpt.directory, 6)
    # Orbax itself reads the flipped step without complaint — prove it,
    # because this is the reason the digest layer exists
    ckpt._restore_raw(6)
    with pytest.raises(CheckpointCorruptionError) as excinfo:
        ckpt.restore(make_trainer(bundle), step=6, chunk_size=3)
    msg = str(excinfo.value)
    assert "content-digest" in msg
    # the offending leaf is NAMED with the normalized slash path
    assert "state/" in msg or "history/" in msg
    assert "scrub" in msg


def test_digest_tamper_in_manifest_raises_naming_leaf(bundle, two_steps):
    """Flipping the RECORDED digest (not the bytes) must also fail the
    restore — the manifest and the payload vouch for each other."""
    ckpt, _ = two_steps
    path = os.path.join(ckpt.directory, MANIFEST_FILENAME)
    manifest = json.load(open(path))
    leaf = sorted(manifest["content"]["6"]["leaves"])[0]
    manifest["content"]["6"]["leaves"][leaf] = "0" * 64
    with open(path, "w") as f:
        json.dump(manifest, f)
    with pytest.raises(CheckpointCorruptionError, match="content-digest"):
        ckpt.restore(make_trainer(bundle), step=6, chunk_size=3)
    # the older step still restores (its rows untouched)
    state, _, _ = ckpt.restore(make_trainer(bundle), step=3, chunk_size=3)
    assert int(state.epoch) == 3


def test_v2_and_v1_manifests_restore_vacuously(bundle, two_steps):
    """Rolling upgrade: stripping the content block (v2) or everything
    versioned (v1) must restore cleanly under the v3 reader — and a
    flipped bit is then INVISIBLE, which is exactly why v3 exists."""
    ckpt, _ = two_steps
    path = os.path.join(ckpt.directory, MANIFEST_FILENAME)
    manifest = json.load(open(path))
    manifest.pop("content")
    manifest["checkpoint_schema"] = 2
    manifest["mesh"] = None
    manifest.pop("mesh")
    with open(path, "w") as f:
        json.dump(manifest, f)
    state, _, _ = ckpt.restore(make_trainer(bundle), step=6, chunk_size=3)
    assert int(state.epoch) == 6

    manifest["checkpoint_schema"] = 1
    with open(path, "w") as f:
        json.dump(manifest, f)
    _flip_data_bit(ckpt.directory, 6)
    # vacuous: the v1 manifest has no digests to disagree with
    state, _, _ = ckpt.restore(make_trainer(bundle), step=6, chunk_size=3)
    assert int(state.epoch) == 6


# ------------------------------------------------------------ quarantine
def test_fallback_quarantines_and_rollback_never_reselects(
        bundle, two_steps):
    """The poisoned-target fix: the corrupt step moves to quarantine/
    (bytes kept), vanishes from every step listing, and a re-save over
    its step number works — so neither the divergence rollback nor a
    later resume can ever pick it again."""
    ckpt, trainer = two_steps
    _flip_data_bit(ckpt.directory, 6)
    skipped = []
    state, history, key = ckpt.restore_latest_intact(
        make_trainer(bundle), chunk_size=3, on_fallback=skipped.append)
    assert int(state.epoch) == 3
    assert [s["step"] for s in skipped] == [6]
    qpath = skipped[0]["quarantined"]
    assert os.path.isdir(qpath)
    meta = json.load(open(os.path.join(qpath, "QUARANTINE.json")))
    assert meta["step"] == 6 and "corrupt at restore" in meta["reason"]
    assert 6 not in ckpt.manager.all_steps()
    # the gap re-checkpoints over the freed step number
    trainer2 = make_trainer(bundle)
    trainer2.fit(key, num_epochs=3, state=state, history=history,
                 hooks=[CheckpointHook(ckpt)], hook_every=3)
    assert ckpt.latest_step == 6
    state6, _, _ = ckpt.restore(make_trainer(bundle), step=6, chunk_size=3)
    assert int(state6.epoch) == 6
    # the quarantined bytes are still there for the operator
    assert os.path.isdir(qpath)


def test_quarantine_without_manifest_keeps_steps_in_place(
        bundle, two_steps):
    """No manifest -> a deep restore error could be a template mismatch;
    the walk must skip WITHOUT moving anything."""
    ckpt, _ = two_steps
    os.remove(os.path.join(ckpt.directory, MANIFEST_FILENAME))
    from dib_tpu.faults import corrupt_checkpoint

    corrupt_checkpoint(ckpt.directory, "ckpt_truncate")
    skipped = []
    state, _, _ = ckpt.restore_latest_intact(
        make_trainer(bundle), chunk_size=3, on_fallback=skipped.append)
    assert int(state.epoch) == 3
    assert skipped[0]["quarantined"] is False
    assert "no integrity manifest" in skipped[0]["reason"]
    assert sorted(ckpt.manager.all_steps()) == [3, 6]


def test_fallback_reporter_emits_mitigation_and_quarantine_events(
        bundle, two_steps, tmp_path):
    from dib_tpu.telemetry import EventWriter, read_events
    from dib_tpu.train import fallback_reporter

    ckpt, _ = two_steps
    _flip_data_bit(ckpt.directory, 6)
    outdir = tmp_path / "events"
    with EventWriter(str(outdir), run_id="integrity-test") as writer:
        ckpt.restore_latest_intact(
            make_trainer(bundle), chunk_size=3,
            on_fallback=fallback_reporter(writer, source="test",
                                          log=lambda m: None))
    events = list(read_events(str(outdir)))
    mits = [e for e in events if e.get("type") == "mitigation"]
    assert [m["mtype"] for m in mits] == ["checkpoint_fallback"]
    assert mits[0]["step"] == 6 and mits[0]["quarantined"]
    quars = [e for e in events if e.get("type") == "quarantine"]
    assert len(quars) == 1 and quars[0]["step"] == 6
    assert quars[0]["path"] == mits[0]["quarantined"]


# ----------------------------------------------------------------- scrub
def test_scrub_exit_codes_in_process(bundle, two_steps, tmp_path):
    ckpt, _ = two_steps
    # 0: clean
    assert scrub_main([ckpt.directory]) == 0
    # 1: mismatch — report-only leaves the step in place
    _flip_data_bit(ckpt.directory, 6)
    assert scrub_main([ckpt.directory]) == 1
    assert 6 in ckpt.manager.all_steps()
    # 1 + --quarantine: the damaged step moves aside
    assert scrub_main([ckpt.directory, "--quarantine"]) == 1
    ckpt.manager.reload()
    assert 6 not in ckpt.manager.all_steps()
    assert os.path.isdir(os.path.join(ckpt.directory, "quarantine", "6"))
    # 0 again: what remains is clean
    assert scrub_main([ckpt.directory]) == 0
    # 2: bad operands
    assert scrub_main([str(tmp_path / "nope")]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert scrub_main([str(empty)]) == 2


def test_scrub_report_names_steps_and_statuses(bundle, two_steps, capsys):
    ckpt, _ = two_steps
    _flip_data_bit(ckpt.directory, 6)
    rc = scrub_main([ckpt.directory, "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    by_step = {r["step"]: r for r in report["steps"]}
    assert by_step[3]["status"] == "ok"
    assert by_step[6]["status"] in ("mismatch", "unreadable")
    if by_step[6]["status"] == "mismatch":
        assert by_step[6]["leaves"]
    assert report["corrupt"] == [6]
    assert report["clean"] is False


def test_scrub_subprocess_cli(bundle, two_steps):
    """The committed acceptance: `python -m dib_tpu ckpt scrub` detects
    a single flipped bit in a retained step's payload, via the real
    CLI."""
    ckpt, _ = two_steps
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    clean = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "ckpt", "scrub",
         ckpt.directory],
        env=env, capture_output=True, text=True, timeout=600)
    assert clean.returncode == 0, clean.stderr[-800:]
    _flip_data_bit(ckpt.directory, 6)
    dirty = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "ckpt", "scrub",
         ckpt.directory, "--json"],
        env=env, capture_output=True, text=True, timeout=600)
    assert dirty.returncode == 1, dirty.stderr[-800:]
    assert 6 in json.loads(dirty.stdout)["corrupt"]
    bad = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "ckpt", "scrub",
         "/definitely/not/a/dir"],
        env=env, capture_output=True, text=True, timeout=600)
    assert bad.returncode == 2
    unknown = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "ckpt", "frobnicate"],
        env=env, capture_output=True, text=True, timeout=600)
    assert unknown.returncode == 2


def test_digests_disabled_env_restores_without_verification(
        bundle, tmp_path, monkeypatch):
    """DIB_CKPT_CONTENT_DIGESTS=0: the rolling-upgrade escape writes
    pre-v3 manifests and scrub reports no_digests without failing."""
    monkeypatch.setenv("DIB_CKPT_CONTENT_DIGESTS", "0")
    trainer = make_trainer(bundle)
    ckpt = DIBCheckpointer(str(tmp_path / "ck"))
    try:
        trainer.fit(jax.random.key(0), hooks=[CheckpointHook(ckpt)],
                    hook_every=3)
        manifest = read_manifest(ckpt.directory)
        assert manifest["checkpoint_schema"] == 1
        assert "content" not in manifest
        report = ckpt.scrub()
        assert report["clean"] is True
        assert all(r["status"] == "no_digests" for r in report["steps"])
    finally:
        ckpt.close()
