"""The streaming chaos-suite artifact contract + the always-on loop
under faults (``scripts/chaos_stream.py``, docs/streaming.md "Chaos
invariants").

Fast tier (``-m fault``): the committed ``CHAOS_STREAM.json`` must
exist, validate against the artifact schema (per-row streaming
invariants included), cover every drill, and show all of them passing —
"zero lost publishes / no double promotion / single-checkpoint
responses" are only as good as the committed evidence. The in-process
drill half (reload storm, canary rollback) re-runs in tier 1, as does
the end-to-end ``clean_loop`` drill: real ``stream run`` / ``stream
deploy`` CLI processes sharing only the publish journal, with live HTTP
traffic riding a hot swap. The full matrix with the subprocess kill
drills is ``@slow``.
"""

import importlib.util
import json
import os
import sys

import pytest

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "CHAOS_STREAM.json")

EXPECTED_DRILLS = {
    "clean_loop", "mid_publish_kill", "deployer_kill", "reload_storm",
    "canary_rollback",
}
QUICK_DRILLS = {"reload_storm", "canary_rollback"}
INVARIANTS = ("zero_lost_publishes", "no_double_promotion",
              "single_checkpoint_responses")


def _load_chaos_module():
    spec = importlib.util.spec_from_file_location(
        "chaos_stream", os.path.join(REPO, "scripts", "chaos_stream.py"))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_committed_chaos_stream_artifact_validates():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from check_run_artifacts import check_file

    assert os.path.exists(ARTIFACT), (
        "CHAOS_STREAM.json missing — run `python scripts/chaos_stream.py "
        "--out CHAOS_STREAM.json` and commit the record")
    assert check_file(ARTIFACT) == []


def test_committed_chaos_stream_matrix_is_complete_and_green():
    with open(ARTIFACT) as f:
        record = json.load(f)
    assert record["metric"] == "chaos_stream_matrix"
    assert record["unit"] == "drills_passed"
    drills = {d["drill"]: d for d in record["matrix"]}
    assert set(drills) == EXPECTED_DRILLS
    failed = [name for name, d in drills.items() if not d["ok"]]
    assert not failed, f"committed chaos record shows failures: {failed}"
    assert record["all_passed"] is True
    assert record["value"] == record["total"] == len(EXPECTED_DRILLS)
    # the committed record must be the FULL matrix, not a --quick run
    assert record["quick"] is False
    # every drill holds all three streaming invariants
    for name, d in drills.items():
        for invariant in INVARIANTS:
            assert d[invariant] is True, (name, invariant)


def test_committed_chaos_stream_evidence_detection_and_recovery():
    """The stream-side join (telemetry summarize, embedded as evidence)
    must agree with the suite's bookkeeping: every injected fault
    detected AND recovered, the journal invariants zero on every
    deployer stream, and the end-to-end drill green against the
    committed SLO.json with traffic on BOTH sides of the swap."""
    with open(ARTIFACT) as f:
        record = json.load(f)
    by_name = {d["drill"]: d for d in record["matrix"]}
    for d in record["matrix"]:
        for side in ("trainer", "deployer"):
            evidence = (d.get("evidence") or {}).get(side) or {}
            faults = evidence.get("faults")
            if faults is not None:
                assert faults["undetected"] == [], (d["drill"], side)
                assert faults["detected"] == faults["injected"]
                assert faults["recovered"] == faults["injected"]
            streaming = evidence.get("streaming")
            if streaming is not None and "deploys" in streaming:
                assert streaming["lost_publishes"] == 0, d["drill"]
                assert streaming["double_promotions"] == 0, d["drill"]
    # the kill drills actually killed (rc 137 = SIGKILL-shaped os._exit)
    assert by_name["mid_publish_kill"]["kill_rc"] == 137
    assert by_name["mid_publish_kill"]["torn_staging"] is True
    assert by_name["deployer_kill"]["kill_rc"] == 137
    # the poisoned publish was rolled back, the rest promoted
    assert by_name["canary_rollback"]["rollbacks"] == 1
    # the storm rode the response cache through real invalidations
    assert by_name["reload_storm"]["cache_hits"] > 0
    assert by_name["reload_storm"]["cache_invalidations"] >= 2
    # the end-to-end loop: SLO-green, traffic on both sides of the swap
    clean = by_name["clean_loop"]
    assert clean["slo_check_rc"] == 0
    assert clean["rode_the_swap"] is True
    served_per_checkpoint = clean["traffic"]["per_candidate"]
    assert sum(1 for n in served_per_checkpoint.values() if n > 0) >= 2


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_quick_chaos_stream_matrix_end_to_end(tmp_path):
    """Run the in-process streaming drills for real in tier 1: hot swaps
    racing a cache-hot tenant storm, and a poisoned checkpoint rolled
    back by the canary gate — all three invariants must hold."""
    module = _load_chaos_module()
    record = module.run_chaos(workdir=str(tmp_path), quick=True,
                              log=lambda m: None)
    failed = [d for d in record["matrix"] if not d["ok"]]
    assert not failed, json.dumps(failed, indent=1, default=str)[:4000]
    assert {d["drill"] for d in record["matrix"]} == QUICK_DRILLS
    assert record["all_passed"]


def test_clean_loop_cli_end_to_end(tmp_path):
    """The acceptance drill in tier 1: `stream run` trains and publishes
    through the real CLI, `stream deploy` serves and hot-swaps through
    the real CLI (separate processes sharing only the publish journal),
    live HTTP traffic rides the swap, and every response is numerically
    from exactly one published checkpoint."""
    module = _load_chaos_module()
    drill = module.run_clean_loop_drill(str(tmp_path), log=lambda m: None)
    assert drill["ok"], json.dumps(
        {k: v for k, v in drill.items() if k != "evidence"}, indent=1,
        default=str)[:4000]
    assert drill["rode_the_swap"] is True
    assert drill["single_checkpoint_responses"] is True
    assert drill["slo_check_rc"] == 0


@pytest.mark.slow
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_full_chaos_stream_matrix_end_to_end(tmp_path):
    """The full matrix including the subprocess kill drills."""
    module = _load_chaos_module()
    record = module.run_chaos(workdir=str(tmp_path), quick=False,
                              log=lambda m: None)
    failed = [d for d in record["matrix"] if not d["ok"]]
    assert not failed, json.dumps(failed, indent=1, default=str)[:4000]
    assert record["all_passed"]


def test_chaos_stream_registers_in_fleet_registry(tmp_path):
    """Satellite: drill records land in the fleet registry under an
    explicit runs root, so `telemetry runs trajectory` carries the
    always-on robustness history."""
    module = _load_chaos_module()
    with open(ARTIFACT) as f:
        record = json.load(f)
    root = str(tmp_path / "runs")
    module._register(record, root, log=lambda m: None)
    from dib_tpu.telemetry.registry import RunRegistry, validate_index_entry

    entries = RunRegistry(root).bench_history()
    assert len(entries) == 1
    assert entries[0]["metric"] == "chaos_stream_matrix"
    assert entries[0]["all_passed"] is True
    assert validate_index_entry(entries[0]) == []
    # ... and NOT without one (the committed index must not grow from
    # ad-hoc local runs)
    os.environ.pop("DIB_RUNS_ROOT", None)
    module._register(record, None, log=lambda m: None)
    assert len(RunRegistry(root).bench_history()) == 1


def test_committed_registry_carries_streaming_history():
    """The committed runs/index.jsonl is seeded with the streaming drill
    evidence, next to the scheduler chaos history."""
    from dib_tpu.telemetry.registry import RunRegistry

    entries = RunRegistry(os.path.join(REPO, "runs")).bench_history()
    stream = [e for e in entries
              if e.get("metric") == "chaos_stream_matrix"]
    assert len(stream) == 1
    assert stream[0]["all_passed"] is True
    assert stream[0]["value"] == stream[0]["total"] == 5
