"""`telemetry report`: self-contained HTML from an events.jsonl.

Covers HTML well-formedness (a strict tag-balance parse), the required
sections (span breakdown, training trajectory, MI-bound sandwich, memory,
roofline utilization), cost-model-absent degradation, CLI exit codes, and
the committed fixture run (``tests/fixtures/telemetry_run``) staying
renderable forever.
"""

import os
from html.parser import HTMLParser

import pytest

from dib_tpu.telemetry import EventWriter, Tracer, telemetry_main
from dib_tpu.telemetry.report import render_report, write_report

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_RUN = os.path.join(REPO, "tests", "fixtures", "telemetry_run")


class _BalanceParser(HTMLParser):
    """Fails on mismatched/unclosed tags — 'valid HTML' for a generator."""

    VOID = {"meta", "br", "hr", "img", "link", "input", "circle", "line",
            "polyline", "polygon", "path", "rect"}

    def __init__(self):
        super().__init__(convert_charrefs=True)
        self.stack: list[str] = []
        self.errors: list[str] = []

    def handle_starttag(self, tag, attrs):
        if tag not in self.VOID:
            self.stack.append(tag)

    def handle_endtag(self, tag):
        if tag in self.VOID:
            return
        if not self.stack or self.stack[-1] != tag:
            self.errors.append(f"mismatched </{tag}> (open: {self.stack[-3:]})")
        else:
            self.stack.pop()


def assert_valid_html(text: str) -> None:
    parser = _BalanceParser()
    parser.feed(text)
    parser.close()
    assert not parser.errors, parser.errors[:5]
    assert not parser.stack, f"unclosed tags: {parser.stack}"
    assert text.startswith("<!DOCTYPE html>")


def write_traced_run(directory, *, with_cost=True):
    """A run with spans, chunks, MI bounds, memory, and (optionally) a
    cost-analyzed compile event."""
    with EventWriter(directory, run_id="traced") as w:
        w.run_start({"git_sha": "a" * 40, "device_kind": "TPU v5 lite",
                     "device_count": 1, "config_hash": "cafe"})
        if with_cost:
            w.compile(name="run_chunk", seconds=1.0, cache="warm",
                      flops=1e12, bytes_accessed=1e10)
        else:
            w.compile(name="run_chunk", seconds=1.0, cache="warm")
        tracer = Tracer(w)
        for i in range(3):
            tracer.add("chunk", 1.0 + 0.1 * i, epoch=(i + 1) * 10)
            tracer.add("mi_bounds", 0.2, epoch=(i + 1) * 10)
            w.chunk(epoch=(i + 1) * 10, steps=100, seconds=1.0 + 0.1 * i,
                    loss=1.0 - 0.1 * i, val_loss=1.1 - 0.1 * i,
                    kl_per_feature=[0.5, 0.25],
                    memory={"peak_bytes_in_use": (2 + i) * 2**30},
                    host_memory={"rss_bytes": 2**30,
                                 "peak_rss_bytes": (1 + i) * 2**30})
            w.mi_bounds(epoch=(i + 1) * 10,
                        lower_bits=[0.4 + 0.1 * i], upper_bits=[0.6 + 0.1 * i])
        w.run_end(status="ok")
    return directory


def test_report_valid_html_with_all_sections(tmp_path):
    run = write_traced_run(str(tmp_path))
    html = render_report(run)
    assert_valid_html(html)
    for section in ("Span breakdown", "Training trajectory",
                    "MI-bound trajectory", "Memory", "Roofline utilization"):
        assert section in html
    # span bars, the sandwich band, utilization numbers, memory tiles
    assert "span-bar" in html
    assert "polygon" in html                  # MI band fill
    assert "run_chunk" in html
    assert "% FLOP peak" in html
    assert "GiB" in html
    # self-contained: no external fetches of any kind
    assert "http://" not in html and "https://" not in html
    assert "<script" not in html


def test_report_renders_orphan_slash_paths(tmp_path):
    """Spans recorded with slash names and no enclosing spans (the
    documented span('sweep/replica3/mi_bounds') form) must appear in the
    breakdown, rooted at their nearest present ancestor."""
    with EventWriter(str(tmp_path), run_id="orphan") as w:
        w.run_start({"device_kind": "cpu", "config_hash": "x"})
        tracer = Tracer(w)
        with tracer.span("sweep/replica1/mi_bounds"):
            pass
        with tracer.span("sweep/replica2/mi_bounds"):
            pass
        w.run_end(status="ok")
    html = render_report(str(tmp_path))
    assert_valid_html(html)
    assert "sweep/replica*/mi_bounds" in html
    assert "span-bar" in html


def test_report_degrades_without_cost_analysis(tmp_path):
    """cost_analysis()-absent backends produce duration-only spans; the
    utilization section must say so instead of crashing or vanishing."""
    run = write_traced_run(str(tmp_path), with_cost=False)
    html = render_report(run)
    assert_valid_html(html)
    assert "Span breakdown" in html and "span-bar" in html
    assert "No XLA cost-analysis numbers" in html


def test_report_empty_ish_stream_still_renders(tmp_path):
    """A minimal stream (no spans, no MI, no memory) renders with the
    explanatory notes, not an exception."""
    with EventWriter(str(tmp_path), run_id="min") as w:
        w.run_start({"device_kind": "cpu", "config_hash": "x"})
        w.chunk(epoch=1, steps=10, seconds=1.0)
        w.run_end(status="ok")
    html = render_report(str(tmp_path))
    assert_valid_html(html)
    assert "No span events" in html
    assert "No mi_bounds events" in html


def test_write_report_default_path_and_cli(tmp_path, capsys):
    run = write_traced_run(str(tmp_path))
    out = write_report(run)
    assert out == os.path.join(run, "report.html")
    assert os.path.getsize(out) > 1000

    rc = telemetry_main(["report", run, "--out",
                         str(tmp_path / "custom.html")])
    assert rc == 0
    assert capsys.readouterr().out.strip() == str(tmp_path / "custom.html")
    assert os.path.exists(tmp_path / "custom.html")

    # bad operand: exit 2 (distinct from a regression verdict's 1)
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert telemetry_main(["report", str(empty)]) == 2
    assert "telemetry report" in capsys.readouterr().err


def test_committed_fixture_run_renders(tmp_path):
    """The committed fixture stream (with its torn span line) must stay
    summarizable and renderable — the report contract's regression anchor."""
    from dib_tpu.telemetry import summarize

    with pytest.warns(UserWarning, match="torn event line"):
        s = summarize(FIXTURE_RUN)
    assert s["spans"]["checkpoint/replica*"]["count"] == 3
    assert s["utilization"]["run_chunk"]["flops_frac_of_peak"] > 0
    assert s["compile"]["cache_hits"] == 1
    assert s["compile"]["cache_misses"] == 1
    assert s["memory"] == {"device_peak_bytes": 6 * 2**30,
                           "host_peak_rss_bytes": 4 * 2**30}

    with pytest.warns(UserWarning, match="torn event line"):
        out = write_report(FIXTURE_RUN, out=str(tmp_path / "fixture.html"))
    html = open(out).read()
    assert_valid_html(html)
    assert "replica*" in html             # per-replica spans rolled up
    assert "mi_bounds" in html
    assert "197" in html                  # v5e bf16 peak from the table


def test_run_report_acceptance_cpu(tmp_path):
    """The acceptance criterion end-to-end on a FRESH CPU run: workload ->
    events.jsonl -> `telemetry report` emits self-contained HTML with span
    breakdown, MI-bound trajectory, and a utilization section."""
    from dib_tpu.cli import workload_main

    run_dir = str(tmp_path / "fresh")
    rc = workload_main([
        "boolean", "--telemetry-dir", run_dir,
        "--set", "num_steps=40", "--set", "mi_every=20",
        "--set", "integration_hidden=(32,)", "--set", "batch_size=64",
    ])
    assert rc == 0
    assert telemetry_main(["report", run_dir]) == 0
    html = open(os.path.join(run_dir, "report.html")).read()
    assert_valid_html(html)
    assert "Span breakdown" in html and "span-bar" in html
    assert "MI-bound trajectory" in html and "polygon" in html
    assert "Roofline utilization" in html
    # CPU has a cost model, so the fresh run carries real numbers
    assert "channel_mi_bounds" in html
