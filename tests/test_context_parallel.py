"""Sequence/context parallelism: ring + Ulysses attention vs the dense oracle.

All tests run on the virtual 8-device CPU mesh (conftest). The correctness
contract: sharding the SET/sequence axis over the mesh must be numerically
invisible — collective attention, pooling, deterministic forwards, and
gradients all match the single-device dense computation to float tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

pytestmark = pytest.mark.slow
from jax.sharding import PartitionSpec as P

from dib_tpu.models.per_particle import PerParticleDIBModel
from dib_tpu.models.set_transformer import SetTransformer
from dib_tpu.parallel.context import (
    context_parallel_apply,
    context_parallel_step_fn,
    dense_self_attention,
    ring_self_attention,
    ulysses_self_attention,
)
from dib_tpu.parallel.mesh import SEQ_AXIS, make_context_mesh


def _qkv(rng, batch=2, seq=16, heads=8, dim=4):
    return tuple(
        jnp.asarray(rng.standard_normal((batch, seq, heads, dim)), jnp.float32)
        for _ in range(3)
    )


def _shard_attention(kernel, mesh, q, k, v):
    fn = jax.shard_map(
        lambda q, k, v: kernel(q, k, v, SEQ_AXIS),
        mesh=mesh,
        in_specs=(P(None, SEQ_AXIS), P(None, SEQ_AXIS), P(None, SEQ_AXIS)),
        out_specs=P(None, SEQ_AXIS),
    )
    return fn(q, k, v)


@pytest.mark.parametrize("kernel", [ring_self_attention, ulysses_self_attention])
def test_collective_attention_matches_dense(rng, kernel):
    q, k, v = _qkv(rng)
    mesh = make_context_mesh()  # all 8 devices on 'seq'
    out = _shard_attention(kernel, mesh, q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_self_attention(q, k, v)),
        rtol=1e-5, atol=1e-5,
    )


def test_ring_attention_odd_head_count(rng):
    # ring has no divisibility constraint: 6 heads on 4 seq shards
    q, k, v = _qkv(rng, heads=6)
    mesh = make_context_mesh(num_seq=4)
    out = _shard_attention(ring_self_attention, mesh, q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_self_attention(q, k, v)),
        rtol=1e-5, atol=1e-5,
    )


def test_ulysses_rejects_indivisible_heads(rng):
    q, k, v = _qkv(rng, heads=6)
    mesh = make_context_mesh()  # 8 shards, 6 heads
    with pytest.raises(ValueError, match="divisible"):
        _shard_attention(ulysses_self_attention, mesh, q, k, v)


def _tiny_set_transformer(**kwargs):
    return SetTransformer(
        num_blocks=2, num_heads=4, key_dim=8, model_dim=8,
        ff_hidden=(16,), head_hidden=(16,), output_dim=1, **kwargs
    )


@pytest.mark.parametrize("seq_impl", ["ring", "ulysses"])
def test_set_transformer_seq_sharded_matches_dense(rng, seq_impl):
    x = jnp.asarray(rng.standard_normal((3, 16, 8)), jnp.float32)
    dense = _tiny_set_transformer()
    params = dense.init(jax.random.key(0), x)
    want = dense.apply(params, x)

    # ulysses needs num_heads (4) % axis_size == 0
    mesh = make_context_mesh(num_seq=4 if seq_impl == "ulysses" else None)
    local = dense.clone(seq_axis=SEQ_AXIS, seq_impl=seq_impl)
    got = jax.shard_map(
        lambda p, x: local.apply(p, x),
        mesh=mesh,
        in_specs=(P(), P(None, SEQ_AXIS)),
        out_specs=P(),
    )(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def _tiny_model(**kwargs):
    return PerParticleDIBModel(
        num_particles=16, particle_feature_dim=3, encoder_hidden=(16,),
        embedding_dim=8, num_blocks=2, num_heads=4, key_dim=8,
        ff_hidden=(16,), head_hidden=(16,), **kwargs
    )


def test_context_parallel_apply_matches_unsharded(rng):
    """Deterministic forward (sample=False): sharding the particle axis must
    reproduce the single-device model exactly — prediction, per-particle KL,
    and channel parameters."""
    model = _tiny_model()
    x = jnp.asarray(rng.standard_normal((4, 16 * 3)), jnp.float32)
    key = jax.random.key(1)
    params = model.init(jax.random.key(0), x, key)
    want_pred, want_aux = model.apply(params, x, key, sample=False)

    mesh = make_context_mesh()
    got_pred, got_aux = context_parallel_apply(
        model, params, x, key, mesh, sample=False
    )
    np.testing.assert_allclose(
        np.asarray(got_pred), np.asarray(want_pred), rtol=1e-5, atol=1e-5
    )
    for name in ("kl_per_feature", "mus", "logvars"):
        np.testing.assert_allclose(
            np.asarray(got_aux[name]), np.asarray(want_aux[name]),
            rtol=1e-5, atol=1e-5, err_msg=name,
        )


def test_context_parallel_data_times_seq_mesh(rng):
    """Combined dp x sp: batch rows over 'data' AND particles over 'seq'
    reproduce the single-device deterministic forward."""
    model = _tiny_model()
    x = jnp.asarray(rng.standard_normal((4, 16 * 3)), jnp.float32)
    key = jax.random.key(1)
    params = model.init(jax.random.key(0), x, key)
    want_pred, want_aux = model.apply(params, x, key, sample=False)

    mesh = make_context_mesh(num_seq=4, num_data=2)
    got_pred, got_aux = context_parallel_apply(
        model, params, x, key, mesh, sample=False
    )
    np.testing.assert_allclose(
        np.asarray(got_pred), np.asarray(want_pred), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(got_aux["kl_per_feature"]),
        np.asarray(want_aux["kl_per_feature"]), rtol=1e-5, atol=1e-5,
    )


def test_make_context_mesh_rejects_unsatisfiable():
    with pytest.raises(ValueError, match="not satisfiable"):
        make_context_mesh(num_data=16)  # 8 devices -> num_seq would be 0


def test_context_parallel_grads_match_unsharded(rng):
    """jax.grad through shard_map + ring collectives == single-device grads."""
    model = _tiny_model()
    x = jnp.asarray(rng.standard_normal((4, 16 * 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, 4), jnp.float32)
    key = jax.random.key(1)
    params = model.init(jax.random.key(0), x, key)
    mesh = make_context_mesh()

    def loss_dense(p):
        pred, aux = model.apply(p, x, key, sample=False)
        return (
            jnp.mean(optax.sigmoid_binary_cross_entropy(pred.squeeze(-1), y))
            + 1e-3 * jnp.sum(aux["kl_per_feature"])
        )

    def loss_sharded(p):
        pred, aux = context_parallel_apply(model, p, x, key, mesh, sample=False)
        return (
            jnp.mean(optax.sigmoid_binary_cross_entropy(pred.squeeze(-1), y))
            + 1e-3 * jnp.sum(aux["kl_per_feature"])
        )

    g_dense = jax.grad(loss_dense)(params)
    g_shard = jax.grad(loss_sharded)(params)
    flat_d, _ = jax.flatten_util.ravel_pytree(g_dense)
    flat_s, _ = jax.flatten_util.ravel_pytree(g_shard)
    np.testing.assert_allclose(
        np.asarray(flat_s), np.asarray(flat_d), rtol=1e-4, atol=1e-5
    )


def test_context_parallel_training_learns(rng):
    """End-to-end: the jitted context-parallel step trains a separable task."""
    model = _tiny_model()
    # label = sign of the mean of the first feature over particles
    x = jnp.asarray(rng.standard_normal((32, 16 * 3)), jnp.float32)
    y = (x.reshape(32, 16, 3)[..., 0].mean(-1) > 0).astype(jnp.float32)
    params = model.init(jax.random.key(0), x, jax.random.key(1))
    optimizer = optax.adam(3e-3)
    opt_state = optimizer.init(params)

    mesh = make_context_mesh()
    step = context_parallel_step_fn(model, optimizer, mesh)
    key = jax.random.key(2)
    first = None
    for i in range(40):
        key, sub = jax.random.split(key)
        params, opt_state, metrics = step(
            params, opt_state, x, y, sub, jnp.float32(1e-4)
        )
        if first is None:
            first = float(metrics["task"])
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["task"]) < first * 0.8


def test_sharded_probe_bounds_matches_dense(rng):
    """Sharding the probe axis is numerically invisible: the dense evaluator
    fed the same per-shard noise draws gives identical bounds."""
    from dib_tpu.ops.gaussian import reparameterize
    from dib_tpu.ops.info_bounds import mi_sandwich_probe
    from dib_tpu.parallel.context import sharded_probe_bounds

    m, n, d = 44, 16, 4   # m=44 pads to 48 over 8 shards
    probe_mus = jnp.asarray(rng.standard_normal((m, d)), jnp.float32)
    probe_lvs = jnp.asarray(rng.standard_normal((m, d)) * 0.1, jnp.float32)
    data_mus = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    data_lvs = jnp.asarray(rng.standard_normal((n, d)) * 0.1, jnp.float32)

    mesh = make_context_mesh()
    key = jax.random.key(3)
    lower_s, upper_s = sharded_probe_bounds(
        key, probe_mus, probe_lvs, data_mus, data_lvs, mesh
    )
    assert lower_s.shape == (m,)

    # reconstruct the per-shard draws on the dense path
    padded_m = (m + 8 - 1) // 8 * 8      # 44 -> 48
    shard = padded_m // 8                # 6 probes per shard
    pm = jnp.pad(probe_mus, ((0, padded_m - m), (0, 0)))
    pl = jnp.pad(probe_lvs, ((0, padded_m - m), (0, 0)))
    u = jnp.concatenate([
        reparameterize(jax.random.fold_in(key, i),
                       pm[i * shard:(i + 1) * shard],
                       pl[i * shard:(i + 1) * shard])
        for i in range(8)
    ])
    lower_d, upper_d = mi_sandwich_probe(
        key, pm, pl, data_mus, data_lvs, u=u
    )
    np.testing.assert_allclose(np.asarray(lower_s), np.asarray(lower_d[:m]),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(upper_s), np.asarray(upper_d[:m]),
                               rtol=1e-5, atol=1e-5)
    # (no pointwise lower<=upper assertion: the sandwich ordering holds in
    # expectation, not per single-sample probe estimate)


def test_dense_attention_f32_scores_fallback(monkeypatch):
    """DIB_ATTN_SCORE_DTYPE=float32 restores the conservative path: every
    dot_general outputs float32 (no bf16 score round-trip anywhere)."""
    monkeypatch.setenv("DIB_ATTN_SCORE_DTYPE", "float32")
    jax.clear_caches()    # the env is read at TRACE time; drop cached traces
    q = jnp.ones((2, 8, 2, 4), jnp.bfloat16)
    jaxpr = jax.make_jaxpr(dense_self_attention)(q, q, q)
    dots = [e for e in jaxpr.jaxpr.eqns if e.primitive.name == "dot_general"]
    assert dots, "expected dot_general ops in dense attention"
    for eqn in dots:
        assert eqn.outvars[0].aval.dtype == jnp.float32, (
            f"dot_general emits {eqn.outvars[0].aval.dtype}; the f32-scores "
            "fallback has been regressed"
        )


def test_dense_attention_default_bf16_scores_recipe(monkeypatch):
    """The DEFAULT is the adopted bf16-scores variant (round 3: +12% on the
    v5e bench, 25k-step sweep all-finite — NORTHSTAR_BF16.json): bf16 score
    emission from the MXU, q scaled BEFORE the matmul, float32 softmax —
    pin all three stability-recipe properties, and numerical agreement with
    the f32-scores fallback."""
    monkeypatch.delenv("DIB_ATTN_SCORE_DTYPE", raising=False)
    jax.clear_caches()    # the env is read at TRACE time; drop cached traces
    q = jnp.ones((2, 8, 2, 4), jnp.bfloat16)
    jaxpr = jax.make_jaxpr(dense_self_attention)(q, q, q)
    dots = [e for e in jaxpr.jaxpr.eqns if e.primitive.name == "dot_general"]
    assert dots[0].outvars[0].aval.dtype == jnp.bfloat16   # scores from MXU
    assert dots[-1].outvars[0].aval.dtype == jnp.float32   # value matmul acc
    # q scaled BEFORE the matmul: the scores dot consumes a scaled operand,
    # i.e. some multiply feeds the first dot_general
    first_dot_inputs = {v for v in dots[0].invars}
    muls = [e for e in jaxpr.jaxpr.eqns if e.primitive.name == "mul"
            and e.outvars[0] in first_dot_inputs]
    assert muls, "q must be scaled before the scores matmul (scale-first)"
    # softmax runs in f32: its exp's operand must be f32
    exps = [e for e in jaxpr.jaxpr.eqns if e.primitive.name == "exp"]
    assert exps and all(
        e.invars[0].aval.dtype == jnp.float32 for e in exps
    ), "softmax must stay float32 under the bf16-scores default"

    k = jax.random.key(0)
    q32 = jax.random.normal(k, (2, 16, 2, 8), jnp.float32)
    qb = q32.astype(jnp.bfloat16)
    got = dense_self_attention(qb, qb, qb)
    monkeypatch.setenv("DIB_ATTN_SCORE_DTYPE", "float32")
    jax.clear_caches()
    want = dense_self_attention(qb, qb, qb)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=0.02, rtol=0.02
    )


def test_score_dtype_env_rejects_unknown(monkeypatch):
    monkeypatch.setenv("DIB_ATTN_SCORE_DTYPE", "fp16")
    jax.clear_caches()
    with pytest.raises(ValueError, match="DIB_ATTN_SCORE_DTYPE"):
        q = jnp.ones((1, 4, 1, 4), jnp.bfloat16)
        dense_self_attention(q, q, q)
