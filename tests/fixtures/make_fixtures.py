"""Deterministic generator for the committed golden data fixtures.

The fixtures replicate the REFERENCE'S real export schemas (VERDICT round 1,
item 6) so the real-file ingestion paths are tested without egress:

  - ``glass_csv/``: the manuscript's ``glass_data.tar.gz`` csv layout
    (amorphous notebook cell 3): padded rows with the neighborhood length as
    the last entry, per-protocol/per-split files, plus g(r) curves and bins.
  - ``tabular/``: one file per UCI/nodegam loader in its authentic column
    layout (winequality-red.csv ';'-separated with the UCI header; bikeshare
    hour.csv; mice Data_Cortex_Nuclear with MouseID + 77 protein columns +
    Genotype/Treatment/Behavior; credit-card fraud V1..V28; Vanderbilt
    SUPPORT2 columns; MSLR-style numeric train.csv).

Values are synthetic (tiny, seeded) — the SCHEMAS are the fixtures' point.
Regenerate with: python tests/fixtures/make_fixtures.py
"""

from __future__ import annotations

import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))

MICE_PROTEINS = [
    "DYRK1A_N", "ITSN1_N", "BDNF_N", "NR1_N", "NR2A_N", "pAKT_N", "pBRAF_N",
    "pCAMKII_N", "pCREB_N", "pELK_N", "pERK_N", "pJNK_N", "PKCA_N", "pMEK_N",
    "pNR1_N", "pNR2A_N", "pNR2B_N", "pPKCAB_N", "pRSK_N", "AKT_N", "BRAF_N",
    "CAMKII_N", "CREB_N", "ELK_N", "ERK_N", "GSK3B_N", "JNK_N", "MEK_N",
    "TRKA_N", "RSK_N", "APP_N", "Bcatenin_N", "SOD1_N", "MTOR_N", "P38_N",
    "pMTOR_N", "DSCR1_N", "AMPKA_N", "NR2B_N", "pNUMB_N", "RAPTOR_N",
    "TIAM1_N", "pP70S6_N", "NUMB_N", "P70S6_N", "pGSK3B_N", "pPKCG_N",
    "CDK5_N", "S6_N", "ADARB1_N", "AcetylH3K9_N", "RRP1_N", "BAX_N", "ARC_N",
    "ERBB4_N", "nNOS_N", "Tau_N", "GFAP_N", "GluR3_N", "GluR4_N", "IL1B_N",
    "P3525_N", "pCASP9_N", "PSD95_N", "SNCA_N", "Ubiquitin_N",
    "pGSK3B_Tyr216_N", "SHH_N", "BAD_N", "BCL2_N", "pS6_N", "pCFOS_N",
    "SYP_N", "H3AcK18_N", "EGR1_N", "H3MeK4_N", "CaNA_N",
]
assert len(MICE_PROTEINS) == 77


def write_glass_csv(out_dir: str) -> None:
    """glass_data.tar.gz layout: padded csv rows, length in the last entry."""
    rng = np.random.default_rng(42)
    os.makedirs(out_dir, exist_ok=True)
    for protocol in ("RapidQuench", "GradualQuench"):
        for split, sizes in (("train", [4, 3, 5]), ("val", [3, 4])):
            max_len = 6  # > max neighborhood size; last slot holds the size
            labels = rng.integers(0, 2, size=len(sizes)).astype(float)
            np.savetxt(
                os.path.join(out_dir, f"{protocol}_{split}_is_loci.csv"),
                labels, delimiter=",", fmt="%.1f",
            )
            pos_rows, type_rows = [], []
            for size in sizes:
                pos = np.zeros((max_len, 2))
                pos[:size] = np.round(rng.normal(0, 3.0, size=(size, 2)), 3)
                pos[-1, 0] = size
                pos_rows.append(pos.reshape(-1))
                typ = np.zeros((max_len, 1))
                typ[:size, 0] = rng.integers(1, 3, size=size)
                typ[-1, 0] = size
                type_rows.append(typ.reshape(-1))
            np.savetxt(
                os.path.join(
                    out_dir, f"{protocol}_{split}_particle_positions.csv"
                ),
                np.stack(pos_rows), delimiter=",", fmt="%.3f",
            )
            np.savetxt(
                os.path.join(out_dir, f"{protocol}_{split}_types.csv"),
                np.stack(type_rows), delimiter=",", fmt="%.1f",
            )
        for particle_type in "AB":
            np.savetxt(
                os.path.join(out_dir, f"g_r_A{particle_type}_{protocol}.csv"),
                np.round(rng.uniform(0, 2.5, size=8), 4)[None],
                delimiter=",", fmt="%.4f",
            )
    np.savetxt(
        os.path.join(out_dir, "g_r_bins.csv"),
        np.linspace(0.25, 4.0, 8)[None], delimiter=",", fmt="%.4f",
    )


def write_tabular(out_dir: str) -> None:
    import pandas as pd

    rng = np.random.default_rng(7)
    n = 64

    # wine: UCI winequality-red.csv, ';' separated
    wine_cols = [
        "fixed acidity", "volatile acidity", "citric acid", "residual sugar",
        "chlorides", "free sulfur dioxide", "total sulfur dioxide",
        "density", "pH", "sulphates", "alcohol",
    ]
    wine = pd.DataFrame(
        {c: np.round(rng.uniform(0.1, 10.0, n), 3) for c in wine_cols}
    )
    wine["quality"] = rng.integers(3, 9, size=n)
    wine.to_csv(os.path.join(out_dir, "winequality-red.csv"),
                sep=";", index=False)

    # bikeshare: UCI hour.csv layout
    bike = pd.DataFrame({
        "instant": np.arange(1, n + 1),
        "dteday": "2011-01-01",
        "season": rng.integers(1, 5, n),
        "yr": rng.integers(0, 2, n),
        "mnth": rng.integers(1, 13, n),
        "hr": rng.integers(0, 24, n),
        "holiday": rng.integers(0, 2, n),
        "weekday": rng.integers(0, 7, n),
        "workingday": rng.integers(0, 2, n),
        "weathersit": rng.integers(1, 5, n),
        "temp": np.round(rng.uniform(0, 1, n), 2),
        "atemp": np.round(rng.uniform(0, 1, n), 4),
        "hum": np.round(rng.uniform(0, 1, n), 2),
        "windspeed": np.round(rng.uniform(0, 0.9, n), 4),
        "casual": rng.integers(0, 50, n),
        "registered": rng.integers(0, 200, n),
    })
    bike["cnt"] = bike["casual"] + bike["registered"]
    bike.to_csv(os.path.join(out_dir, "hour.csv"), index=False)

    # mice protein: MouseID + 77 proteins + Genotype/Treatment/Behavior/class
    os.makedirs(os.path.join(out_dir, "mice_protein"), exist_ok=True)
    rows = 8 * 8  # all 8 (Genotype, Treatment, Behavior) classes
    mice = {"MouseID": [f"M{i}_{i % 15 + 1}" for i in range(rows)]}
    for p in MICE_PROTEINS:
        col = np.round(rng.lognormal(-1.0, 0.5, rows), 6)
        # sprinkle NaNs like the real sheet (exercises the groupby fill)
        col[rng.random(rows) < 0.05] = np.nan
        mice[p] = col
    geno = np.where(np.arange(rows) % 2 == 0, "Control", "Ts65Dn")
    treat = np.where((np.arange(rows) // 2) % 2 == 0, "Memantine", "Saline")
    behav = np.where((np.arange(rows) // 4) % 2 == 0, "C/S", "S/C")
    mice["Genotype"], mice["Treatment"], mice["Behavior"] = geno, treat, behav
    mice["class"] = [
        f"{'c' if g == 'Control' else 't'}-"
        f"{'CS' if b == 'C/S' else 'SC'}-"
        f"{'m' if t == 'Memantine' else 's'}"
        for g, t, b in zip(geno, treat, behav)
    ]
    pd.DataFrame(mice).to_csv(
        os.path.join(out_dir, "mice_protein", "Data_Cortex_Nuclear.csv"),
        index=False,
    )

    # credit: card-fraud layout Time, V1..V28, Amount, Class
    os.makedirs(os.path.join(out_dir, "credit"), exist_ok=True)
    credit = {"Time": np.sort(rng.uniform(0, 172_000, n))}
    for i in range(1, 29):
        credit[f"V{i}"] = np.round(rng.normal(0, 1, n), 6)
    credit["Amount"] = np.round(rng.lognormal(3, 1, n), 2)
    credit["Class"] = (rng.random(n) < 0.1).astype(int)
    pd.DataFrame(credit).to_csv(
        os.path.join(out_dir, "credit", "data.csv"), index=False
    )

    # support2: Vanderbilt column set (subset incl. all loader-selected ones)
    os.makedirs(os.path.join(out_dir, "support2"), exist_ok=True)
    s2 = {
        "age": np.round(rng.uniform(20, 95, n), 1),
        "death": rng.integers(0, 2, n),
        "sex": rng.choice(["male", "female"], n),
        "hospdead": rng.integers(0, 2, n),
        "slos": rng.integers(3, 60, n),
        "d.time": rng.integers(5, 2000, n),
        "dzgroup": rng.choice(
            ["ARF/MOSF w/Sepsis", "CHF", "COPD", "Cirrhosis", "Colon Cancer",
             "Coma", "Lung Cancer", "MOSF w/Malig"], n),
        "dzclass": rng.choice(
            ["ARF/MOSF", "COPD/CHF/Cirrhosis", "Cancer", "Coma"], n),
        "num.co": rng.integers(0, 7, n),
        "edu": rng.integers(8, 22, n).astype(float),
        "income": rng.choice(
            ["under $11k", "$11-$25k", "$25-$50k", ">$50k"], n),
        "scoma": rng.integers(0, 100, n).astype(float),
        "charges": np.round(rng.lognormal(10, 1, n), 1),
        "avtisst": np.round(rng.uniform(5, 60, n), 2),
        "race": rng.choice(["white", "black", "hispanic", "other"], n),
        "sps": np.round(rng.uniform(10, 70, n), 2),
        "aps": rng.integers(5, 120, n).astype(float),
        "surv2m": np.round(rng.uniform(0, 1, n), 3),
        "surv6m": np.round(rng.uniform(0, 1, n), 3),
        "hday": rng.integers(1, 20, n),
        "diabetes": rng.integers(0, 2, n),
        "dementia": rng.integers(0, 2, n),
        "ca": rng.choice(["no", "yes", "metastatic"], n),
        "meanbp": rng.integers(40, 140, n).astype(float),
        "wblc": np.round(rng.uniform(1, 40, n), 2),
        "hrt": rng.integers(40, 160, n).astype(float),
        "resp": rng.integers(8, 50, n).astype(float),
        "temp": np.round(rng.uniform(35, 40.5, n), 1),
        "pafi": np.round(rng.uniform(60, 500, n), 1),
        "alb": np.round(rng.uniform(1, 5, n), 2),
        "bili": np.round(rng.uniform(0.2, 20, n), 2),
        "crea": np.round(rng.uniform(0.4, 8, n), 2),
        "sod": rng.integers(120, 160, n).astype(float),
        "ph": np.round(rng.uniform(7.0, 7.7, n), 3),
        "glucose": rng.integers(40, 400, n).astype(float),
        "bun": rng.integers(5, 120, n).astype(float),
        "urine": rng.integers(0, 4000, n).astype(float),
        "adlsc": np.round(rng.uniform(0, 7, n), 2),
    }
    df2 = pd.DataFrame(s2)
    # sprinkle NaNs in numeric + categorical (exercises the fill paths)
    for col in ("edu", "urine", "alb"):
        df2.loc[df2.sample(frac=0.15, random_state=1).index, col] = np.nan
    df2.loc[df2.sample(frac=0.1, random_state=2).index, "income"] = np.nan
    df2.to_csv(os.path.join(out_dir, "support2", "support2.csv"), index=False)

    # microsoft: numeric train.csv, first column = relevance target
    os.makedirs(os.path.join(out_dir, "microsoft"), exist_ok=True)
    ms = {"0": rng.integers(0, 5, n)}
    for i in range(1, 17):
        ms[str(i)] = np.round(rng.normal(0, 1, n), 5)
    pd.DataFrame(ms).to_csv(
        os.path.join(out_dir, "microsoft", "train.csv"), index=False
    )


def main() -> None:
    write_glass_csv(os.path.join(HERE, "glass_csv"))
    tab = os.path.join(HERE, "tabular")
    os.makedirs(tab, exist_ok=True)
    write_tabular(tab)
    print("fixtures written under", HERE)


if __name__ == "__main__":
    main()
