"""Sweep-level self-healing, preemption tolerance, and the desync guard.

The fast tier of the ISSUE-5 drill matrix (docs/robustness.md, "Sweep and
pod failures"):

  - per-replica divergence quarantine: a poisoned sweep member is healed
    by an original-width replay spliced back bit-identically; a member
    whose replay re-diverges is EJECTED and the rest of the sweep is
    unharmed;
  - preemption: a SIGTERM-shaped request at a chunk boundary writes a
    final chunk-aligned checkpoint, unwinds with ``TrainingPreempted``,
    and the watchdog treats the distinct exit code as "relaunch
    immediately, no backoff";
  - multihost desync guard: ``assert_same_chunk`` raises naming the
    divergent host (and bounds a straggler's hang with a timeout) instead
    of wedging in a collective.

The full subprocess preemption matrix lives in ``scripts/fault_drill.py``
(re-run end-to-end behind ``@pytest.mark.slow`` in test_fault_drill.py).
"""

import os
import sys
import textwrap
import time
import warnings

import jax
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.faults import FaultPlan, PoisonedReplicaRestore
from dib_tpu.models import DistributedIBModel
from dib_tpu.parallel import BetaSweepTrainer
from dib_tpu.parallel.multihost import HostDesyncError, assert_same_chunk
from dib_tpu.telemetry import (
    EventWriter,
    read_events,
    runtime_manifest,
    summarize,
)
from dib_tpu.train import (
    CheckpointHook,
    DIBCheckpointer,
    DIBTrainer,
    PreemptionGuard,
    TrainConfig,
    TrainingPreempted,
)
from dib_tpu.train.preempt import PREEMPT_EXIT_CODE
from dib_tpu.train.watchdog import WatchdogConfig, supervise

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CFG = TrainConfig(batch_size=64, num_pretraining_epochs=2,
                   num_annealing_epochs=6, steps_per_epoch=2,
                   max_val_points=128)


def _tiny_model(bundle):
    return DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )


@pytest.fixture(scope="module")
def sweep_parts():
    bundle = get_dataset("boolean_circuit")
    model = _tiny_model(bundle)
    return model, bundle


@pytest.fixture(scope="module")
def sweep_keys():
    return jax.random.split(jax.random.key(3), 2)


@pytest.fixture(scope="module")
def baseline(sweep_parts, sweep_keys):
    """Uninterrupted 2-member sweep: 8 epochs in chunks of 2."""
    model, bundle = sweep_parts
    sweep = BetaSweepTrainer(model, bundle, _CFG, 1e-4, [0.1, 1.0])
    states, records = sweep.fit(sweep_keys, hooks=[lambda *a: None],
                                hook_every=2)
    return states, records


def _mk_sweep(sweep_parts):
    model, bundle = sweep_parts
    return BetaSweepTrainer(model, bundle, _CFG, 1e-4, [0.1, 1.0])


# ------------------------------------------------- per-replica quarantine
def test_replica_nan_quarantine_heals_bit_identically(
        tmp_path, sweep_parts, sweep_keys, baseline):
    """Poison ONE member mid-sweep; the quarantine must roll back only
    that member, replay at the original width, splice it back, and finish
    with EVERY member's history and params bit-identical to the
    uninterrupted baseline — the replica_nan drill's acceptance
    criterion, in-process and fast."""
    states_a, recs_a = baseline
    run_dir = str(tmp_path / "run")
    writer = EventWriter(run_dir)
    writer.run_start(runtime_manifest())
    ckpt = DIBCheckpointer(str(tmp_path / "ck"))
    plan = FaultPlan.parse("replica_nan@chunk2:1", state_dir=str(tmp_path))
    sweep = _mk_sweep(sweep_parts)
    with pytest.warns(UserWarning, match="member 1.*rolled back"):
        states_b, recs_b = sweep.fit(
            sweep_keys, hooks=[CheckpointHook(ckpt)], hook_every=2,
            telemetry=writer, fault_plan=plan,
        )
    writer.run_end(status="ok")
    writer.close()
    ckpt.close()

    for r in range(2):
        assert not recs_b[r].ejected
        np.testing.assert_array_equal(recs_a[r].loss, recs_b[r].loss)
        np.testing.assert_array_equal(recs_a[r].kl_per_feature,
                                      recs_b[r].kl_per_feature)
        np.testing.assert_array_equal(recs_a[r].beta, recs_b[r].beta)
    for a, b in zip(jax.tree.leaves(states_a.params),
                    jax.tree.leaves(states_b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    events = list(read_events(run_dir))
    faults = [e for e in events if e["type"] == "fault"]
    assert [(e["kind"], e.get("replica")) for e in faults] == [
        ("replica_nan", 1)]
    mits = [e for e in events if e["type"] == "mitigation"]
    assert [(e["mtype"], e.get("replica")) for e in mits] == [
        ("divergence_rollback", 1)]
    # the mitigation is β-attributable, as the event schema promises
    assert mits[0]["beta_end"] == pytest.approx(1.0)

    rollup = summarize(run_dir)["faults"]
    assert rollup["injected"] == rollup["detected"] == rollup["recovered"] == 1
    assert rollup["undetected"] == []


def test_twice_diverging_replica_is_ejected(
        tmp_path, sweep_parts, sweep_keys, baseline):
    """A member whose quarantine replay re-diverges in the same chunk is
    deterministic: it must be EJECTED (replica_ejected mitigation, record
    marked) while the rest of the sweep finishes unharmed — never healed
    in a loop, never poisoning the run."""
    _, recs_a = baseline
    run_dir = str(tmp_path / "run")
    writer = EventWriter(run_dir)
    writer.run_start(runtime_manifest())
    # FlakyEngine-style injector: every restore hands back a stack whose
    # member 1 is poisoned, so each heal replay re-diverges
    ckpt = DIBCheckpointer(str(tmp_path / "ck"))
    sick = PoisonedReplicaRestore(ckpt, replica=1)
    plan = FaultPlan.parse("replica_nan@chunk2:1", state_dir=str(tmp_path))
    sweep = _mk_sweep(sweep_parts)
    with pytest.warns(UserWarning, match="EJECTED"):
        _, recs_b = sweep.fit(
            sweep_keys, hooks=[CheckpointHook(sick)], hook_every=2,
            telemetry=writer, fault_plan=plan,
        )
    writer.run_end(status="ok")
    writer.close()
    ckpt.close()

    assert [r.ejected for r in recs_b] == [False, True]
    assert list(sweep.ejected_replicas) == [1]
    info = sweep.ejected_replicas[1]
    assert info["beta_end"] == pytest.approx(1.0)
    # the ejected flag survives the reporting-units conversion
    assert recs_b[1].to_bits().ejected is True
    # the healthy member's trajectory is untouched by its neighbor's death
    np.testing.assert_array_equal(recs_a[0].loss, recs_b[0].loss)
    np.testing.assert_array_equal(recs_a[0].kl_per_feature,
                                  recs_b[0].kl_per_feature)
    # the ejected member's tail is honestly non-finite, not spliced over
    assert not np.isfinite(recs_b[1].loss[-1])

    mits = [(e["mtype"], e.get("replica"))
            for e in read_events(run_dir) if e["type"] == "mitigation"]
    assert ("replica_ejected", 1) in mits
    rollup = summarize(run_dir)["faults"]
    assert rollup["detected"] == rollup["injected"]
    assert rollup["undetected"] == []


def test_sweep_divergence_without_checkpoint_warns_once(
        tmp_path, sweep_parts, sweep_keys):
    """No checkpoint hook in a sweep fit: the guard must warn loudly once
    (mitigation divergence_detected naming the members) and keep going —
    parity with the serial trainer's degraded path."""
    run_dir = str(tmp_path / "run")
    writer = EventWriter(run_dir)
    writer.run_start(runtime_manifest())
    plan = FaultPlan.parse("replica_nan@chunk1:0", state_dir=str(tmp_path))
    sweep = _mk_sweep(sweep_parts)
    with pytest.warns(UserWarning, match="no checkpoint"):
        _, recs = sweep.fit(sweep_keys, hooks=[lambda *a: None],
                            hook_every=2, telemetry=writer, fault_plan=plan)
    writer.run_end(status="ok")
    writer.close()
    assert not np.isfinite(recs[0].loss[-1])     # honestly diverged
    assert np.isfinite(recs[1].loss).all()       # neighbor untouched
    mits = [e for e in read_events(run_dir) if e["type"] == "mitigation"]
    assert [m["mtype"] for m in mits] == ["divergence_detected"]
    assert mits[0]["replicas"] == [0]


# ------------------------------------------------------------- key checks
def test_check_keys_rejects_non_key_arrays(sweep_parts):
    sweep = _mk_sweep(sweep_parts)
    with pytest.raises(ValueError, match=r"jax\.random\.split"):
        sweep.fit(np.zeros(2, np.float32), num_epochs=2)
    with pytest.raises(ValueError, match=r"jax\.random\.split"):
        sweep._check_keys(np.zeros((2, 3), np.uint32))
    # typed [R] keys and raw uint32 [R, 2] key data both pass
    typed = jax.random.split(jax.random.key(0), 2)
    sweep._check_keys(typed)
    sweep._check_keys(np.asarray(jax.random.key_data(typed)))
    with pytest.raises(ValueError, match="replica keys"):
        sweep._check_keys(jax.random.split(jax.random.key(0), 3))


def test_host_beta_endpoints_back_replica_views(sweep_parts):
    """replica_trainer/PerReplicaHook read host numpy endpoints fetched
    once in __init__ — no per-call device round-trip, multihost-safe."""
    sweep = _mk_sweep(sweep_parts)
    assert isinstance(sweep.beta_ends_host, np.ndarray)
    assert sweep.replica_trainer(1).config.beta_end == pytest.approx(1.0)
    assert sweep.replica_trainer(0).config.beta_end == pytest.approx(0.1)
    from dib_tpu.parallel.sweep import PerReplicaHook

    seen = {}
    hook = PerReplicaHook(lambda r: (lambda tr, st, ep:
                                     seen.setdefault(r, tr.config.beta_end)))
    states, _ = sweep.init(jax.random.split(jax.random.key(0), 2))
    hook(sweep, states, 0)
    assert seen == {0: pytest.approx(0.1), 1: pytest.approx(1.0)}


# ------------------------------------------------------------- preemption
def _serial_trainer():
    bundle = get_dataset("boolean_circuit")
    return DIBTrainer(_tiny_model(bundle), bundle, _CFG)


def test_preempt_checkpoints_at_boundary_and_resumes_bit_identically(
        tmp_path):
    """A preemption request mid-fit must finish the in-flight chunk, write
    a chunk-aligned checkpoint, emit preempt_checkpoint, and unwind with
    TrainingPreempted; the relaunch must resume bit-identically."""
    key = jax.random.key(0)
    trainer_a = _serial_trainer()
    state_a, hist_a = trainer_a.fit(key, hooks=[lambda *a: None],
                                    hook_every=2)

    run_dir = str(tmp_path / "run")
    writer = EventWriter(run_dir)
    writer.run_start(runtime_manifest())
    ckpt = DIBCheckpointer(str(tmp_path / "ck"))
    guard = PreemptionGuard(grace_s=120.0)

    def request_at_4(trainer, state, epoch):
        if epoch == 4:
            guard.request()          # the SIGTERM handler body, sans signal

    trainer_b = _serial_trainer()
    with pytest.raises(TrainingPreempted) as excinfo:
        trainer_b.fit(key, hooks=[request_at_4, CheckpointHook(ckpt)],
                      hook_every=2, telemetry=writer, preempt=guard)
    writer.run_end(status="preempted", epoch=excinfo.value.epoch)
    writer.close()
    assert excinfo.value.epoch == 4
    assert excinfo.value.checkpoint_saved
    assert ckpt.latest_step == 4
    mits = [e["mtype"] for e in read_events(run_dir)
            if e["type"] == "mitigation"]
    assert mits == ["preempt_checkpoint"]
    assert summarize(run_dir)["status"] == "preempted"

    # the relaunch: restore and finish — bit-identical to uninterrupted
    trainer_c = _serial_trainer()
    state_4, hist_4, key_4 = ckpt.restore(trainer_c, chunk_size=2)
    state_c, hist_c = trainer_c.fit(key_4, num_epochs=4, state=state_4,
                                    history=hist_4,
                                    hooks=[lambda *a: None], hook_every=2)
    np.testing.assert_array_equal(hist_a.loss, hist_c.loss)
    for a, c in zip(jax.tree.leaves(state_a.params),
                    jax.tree.leaves(state_c.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
    ckpt.close()


def test_sweep_preempt_uses_the_same_contract(tmp_path, sweep_parts,
                                              sweep_keys):
    ckpt = DIBCheckpointer(str(tmp_path / "ck"))
    guard = PreemptionGuard(grace_s=120.0)

    def request_at_4(sweep, states, epoch):
        if epoch == 4:
            guard.request()

    sweep = _mk_sweep(sweep_parts)
    with pytest.raises(TrainingPreempted) as excinfo:
        sweep.fit(sweep_keys, hooks=[request_at_4, CheckpointHook(ckpt)],
                  hook_every=2, preempt=guard)
    assert excinfo.value.epoch == 4
    assert ckpt.latest_step == 4
    ckpt.close()


def test_preempt_guard_arms_and_restores_handlers():
    import signal as signal_mod

    before = signal_mod.getsignal(signal_mod.SIGTERM)
    with PreemptionGuard(grace_s=60.0) as guard:
        assert signal_mod.getsignal(signal_mod.SIGTERM) == guard._handle
        assert not guard.requested
        assert guard.remaining_s() is None
    assert signal_mod.getsignal(signal_mod.SIGTERM) == before


# ----------------------------------------------------- watchdog exit code
def _scripted_worker(tmp_path, body: str) -> list:
    path = tmp_path / "worker.py"
    path.write_text(textwrap.dedent(body))
    return [sys.executable, str(path)]


def test_watchdog_relaunches_preempted_worker_without_backoff(tmp_path):
    """rc=75 with heartbeat progress: immediate relaunch, a
    preempt_restart mitigation (never crash_restart), no backoff sleep,
    and no restart-budget burn."""
    hb = str(tmp_path / "hb.json")
    marker = str(tmp_path / "preempted_once")
    cmd = _scripted_worker(tmp_path, f"""
        import json, os, sys, time
        hb, marker = {hb!r}, {marker!r}
        def beat(n):
            payload = {{"pid": os.getpid(), "epoch": n, "beat": n,
                        "time": time.time(), "intervals_s": [0.1] * n}}
            with open(hb + ".tmp", "w") as f:
                json.dump(payload, f)
            os.replace(hb + ".tmp", hb)
        beat(1); time.sleep(0.2); beat(2)
        if not os.path.exists(marker):
            open(marker, "w").close()
            sys.exit({PREEMPT_EXIT_CODE})   # cooperative preemption
        sys.exit(0)
    """)
    t0 = time.time()
    result = supervise(
        cmd, hb,
        # max_restarts=0: ANY crash-budget burn would give up — proving
        # the preempt relaunch is budget-free; backoff would show in wall
        WatchdogConfig(poll_s=0.05, max_restarts=0,
                       restart_backoff_s=30.0, min_uptime_s=0.0),
    )
    assert result["returncode"] == 0
    assert result["launches"] == 2
    assert [m["type"] for m in result["mitigations"]] == ["preempt_restart"]
    assert result["mitigations"][0]["beats"] == 2
    assert time.time() - t0 < 25     # no 30 s backoff was taken


def test_watchdog_preempts_pinned_at_one_epoch_are_budgeted(tmp_path):
    """Repeated rc-75 exits that never ADVANCE past the previous
    preemption's epoch (every chunk outliving the grace budget, or a
    worker wedged at one checkpoint) are a preemption-shaped stall: they
    must burn the restart budget, not relaunch forever."""
    hb = str(tmp_path / "hb.json")
    cmd = _scripted_worker(tmp_path, f"""
        import json, os, sys, time
        hb = {hb!r}
        payload = {{"pid": os.getpid(), "epoch": 2, "beat": 1,
                    "time": time.time(), "intervals_s": [0.1]}}
        with open(hb + ".tmp", "w") as f:
            json.dump(payload, f)
        os.replace(hb + ".tmp", hb)
        sys.exit({PREEMPT_EXIT_CODE})    # same epoch, every launch
    """)
    result = supervise(cmd, hb, WatchdogConfig(poll_s=0.05, max_restarts=1))
    assert result["returncode"] == PREEMPT_EXIT_CODE
    assert "error" in result
    # first preempt (epoch advanced from nothing) is free; the repeats at
    # the same epoch burn the budget of 1
    assert result["launches"] == 3
    assert all(m["type"] == "preempt_restart" for m in result["mitigations"])


def test_watchdog_zero_progress_preempt_exit_is_budgeted(tmp_path):
    """A worker spinning on rc=75 without EVER heartbeating is a crash
    loop wearing the preemption code — it must burn the restart budget,
    not relaunch forever."""
    hb = str(tmp_path / "hb.json")
    cmd = _scripted_worker(
        tmp_path, f"import sys; sys.exit({PREEMPT_EXIT_CODE})")
    result = supervise(cmd, hb, WatchdogConfig(poll_s=0.05, max_restarts=1))
    assert result["returncode"] == PREEMPT_EXIT_CODE
    assert "error" in result
    assert result["launches"] == 2


# ------------------------------------------------------------ desync guard
def test_desync_barrier_single_process_is_noop():
    assert_same_chunk("run", 3, timeout_s=0.5) is None


def test_desync_barrier_names_the_lagging_host(tmp_path):
    """One host arrives with a stale chunk: the barrier must raise naming
    THAT host and its (run_id, chunk), and record a desync_detected
    mitigation on the stream."""
    run_dir = str(tmp_path / "run")
    writer = EventWriter(run_dir)

    def gather(mine):
        return [mine, "run-a|2|sha0", mine]   # host 1 is a chunk behind

    with pytest.raises(HostDesyncError, match="host 1") as excinfo:
        assert_same_chunk("run-a", 3, timeout_s=5.0, git_sha="sha0",
                          telemetry=writer, _gather=gather)
    writer.close()
    assert "run-a|2" in str(excinfo.value)     # the stale value is named
    mits = [e for e in read_events(run_dir) if e["type"] == "mitigation"]
    assert [m["mtype"] for m in mits] == ["desync_detected"]
    assert mits[0]["divergent_hosts"] == [1]


def test_desync_barrier_two_host_tie_names_both_sides():
    """A 2-host pod split 1-1 has no majority: claiming one would point
    the operator at an arbitrary (possibly healthy) host — the error must
    list every host's row instead."""
    def gather(mine):
        return [mine, "drill|1|other"]

    with pytest.raises(HostDesyncError, match="no majority") as excinfo:
        assert_same_chunk("run-a", 3, timeout_s=5.0, git_sha="sha0",
                          _gather=gather)
    msg = str(excinfo.value)
    assert "host 0" in msg and "host 1" in msg
    assert "drill|1|other" in msg


def test_desync_barrier_names_code_drift():
    def gather(mine):
        other = mine.rsplit("|", 1)[0] + "|othersha"
        return [mine, mine, other]

    with pytest.raises(HostDesyncError, match="host 2"):
        assert_same_chunk("run-a", 3, timeout_s=5.0, git_sha="mysha",
                          _gather=gather)


def test_desync_barrier_timeout_bounds_a_straggler(tmp_path):
    """A host that never arrives must turn into an actionable error within
    the timeout — not a forever-hang in the collective."""
    def hang(mine):
        time.sleep(60.0)

    t0 = time.time()
    with pytest.raises(HostDesyncError, match="never arrived"):
        assert_same_chunk("run-a", 3, timeout_s=0.5, git_sha="sha0",
                          _gather=hang)
    assert time.time() - t0 < 5.0


def test_desync_barrier_agreement_passes():
    def gather(mine):
        return [mine] * 4

    assert_same_chunk("run-a", 3, timeout_s=5.0, git_sha="sha0",
                      _gather=gather)


def test_desync_barrier_oversize_run_id_still_compares_chunk():
    """A run_id longer than the fixed payload must not silently truncate
    the chunk/sha out of the compared row (desynced hosts would then
    compare equal) — the oversize id is hashed instead, and a stale chunk
    still raises."""
    from dib_tpu.parallel.multihost import _BARRIER_PAYLOAD_BYTES, _barrier_row

    long_id = "r" * (_BARRIER_PAYLOAD_BYTES + 40)
    row = _barrier_row(long_id, 3, "sha0")
    assert len(row.encode()) <= _BARRIER_PAYLOAD_BYTES
    assert row.endswith("|3|sha0")

    def stale_gather(mine):
        return [mine, _barrier_row(long_id, 2, "sha0")]

    with pytest.raises(HostDesyncError, match="host 1"):
        assert_same_chunk(long_id, 3, timeout_s=5.0, git_sha="sha0",
                          _gather=stale_gather)

    def agree_gather(mine):
        return [mine, mine]

    assert_same_chunk(long_id, 3, timeout_s=5.0, git_sha="sha0",
                      _gather=agree_gather)
