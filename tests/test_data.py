"""Data-layer tests: registry, boolean circuits, pendulum physics oracles,
chaotic maps, amorphous feature engineering, tabular preprocessing."""

import numpy as np
import pytest

from dib_tpu.data import (
    available_datasets,
    get_dataset,
    PAPER_CIRCUIT,
    FIG_S1_CIRCUITS,
    full_truth_table,
    random_circuit,
    exact_subset_informations,
    total_energy,
    unroll_angles,
    generate_data,
    ENTROPY_RATE_BITS,
    per_particle_features,
    synthetic_glass_neighborhoods,
    build_neighborhood_arrays,
    TabularPreprocessor,
)
from dib_tpu.data.pendulum import simulate_double_pendulum
from dib_tpu.ops.entropy import sequence_entropy_bits


def test_registry_has_reference_parity_names():
    names = available_datasets()
    # reference data.py:397-406 registry
    for name in ["boolean_circuit", "double_pendulum", "mice_protein", "microsoft",
                 "credit", "support2", "wine", "bikeshare"]:
        assert name in names
    # notebook workloads promoted to first-class datasets
    assert "amorphous_particles" in names
    assert "amorphous_radial_shells" in names


# ------------------------------------------------------------------- boolean
def test_paper_circuit_truth_table():
    table = full_truth_table(PAPER_CIRCUIT)
    assert table.shape == (1024, 19)
    y = table[:, -1]
    assert set(np.unique(y)) <= {0, 1}
    assert 0.4 < sequence_entropy_bits(y) <= 1.0


def test_random_circuit_structure(rng):
    spec = random_circuit(6, rng)
    assert sum(1 for v in spec if isinstance(v, (int, np.integer))) == 6
    table = full_truth_table(spec)
    assert table.shape[0] == 64


def test_exact_subset_informations_monotone():
    infos = exact_subset_informations(full_truth_table(FIG_S1_CIRCUITS[1]), 3)
    # MI is monotone under superset inclusion
    assert infos[(0,)] <= infos[(0, 1)] + 1e-12
    assert infos[(0, 1)] <= infos[(0, 1, 2)] + 1e-12


# ------------------------------------------------------------------ pendulum
@pytest.mark.slow
def test_pendulum_energy_conservation():
    data = simulate_double_pendulum(
        num_trajectories=4, initial_time=2.0, simulation_time=3.0, seed=0
    )
    assert data.shape[0] == 4 and data.shape[-1] == 4
    e = np.asarray(total_energy(data))
    drift = np.abs(e - e[:, :1]) / np.abs(e[:, :1])
    assert drift.max() < 1e-3  # the reference's rejection tolerance


def test_unroll_angles_geometry(rng):
    arr = rng.normal(size=(2, 5, 4))
    out = unroll_angles(arr)
    assert out.shape == (2, 5, 6)
    np.testing.assert_allclose(out[..., 0] ** 2 + out[..., 1] ** 2, 1.0, rtol=1e-6)
    np.testing.assert_allclose(out[..., 2], arr[..., 1])


def test_fetch_double_pendulum_bundle(tmp_path):
    bundle = get_dataset(
        "double_pendulum",
        data_path=str(tmp_path),
        num_trajectories=12,
        pendulum_time_delta=1.0,
        regenerate=True,
    )
    assert bundle.feature_dimensionalities == [2, 1, 2, 1]
    assert bundle.x_train.shape[-1] == 6
    assert bundle.loss == "infonce"
    # y is the state time_delta later: same manifold (unit arm vectors)
    np.testing.assert_allclose(
        bundle.y_train[:, 0] ** 2 + bundle.y_train[:, 1] ** 2, 1.0, rtol=1e-5
    )


# --------------------------------------------------------------------- chaos
@pytest.mark.parametrize("system", ["logistic", "henon", "ikeda"])
def test_chaos_maps_stay_on_attractor(system):
    data = generate_data(system, number_iterations=5000, number_skip_iterations=500, seed=1)
    assert data.shape == (5000, 1 if system == "logistic" else 2)
    assert np.all(np.isfinite(data))
    # bounded attractors
    assert np.abs(data).max() < 10.0
    # chaotic: not collapsed to a fixed point
    assert np.std(data[-100:], axis=0).max() > 1e-2


def test_logistic_map_recurrence_exact():
    data = generate_data("logistic", number_iterations=100, number_skip_iterations=0, seed=3)
    x = data[:, 0]
    np.testing.assert_allclose(x[1:], 3.7115 * x[:-1] * (1 - x[:-1]), rtol=1e-10)


def test_known_entropy_rates_table():
    assert ENTROPY_RATE_BITS == {"logistic": 0.5203, "henon": 0.6048, "ikeda": 0.726}


# ----------------------------------------------------------------- amorphous
def test_per_particle_features_layout(rng):
    pos = rng.normal(size=(30, 2)).astype(np.float32)
    typ = rng.integers(1, 3, size=30)
    feats = per_particle_features(pos, typ, number_particles_to_use=20)
    assert feats.shape == (20, 12)
    # radius column (index 4) must be sorted ascending after clipping
    assert np.all(np.diff(feats[:, 4]) >= 0)
    # one-hot columns sum to 1
    np.testing.assert_allclose(feats[:, 10] + feats[:, 11], 1.0)


def test_amorphous_particles_bundle():
    bundle = get_dataset("amorphous_particles", num_synthetic_neighborhoods=64,
                         number_particles_to_use=16)
    sets = bundle.extras["sets_train"]
    assert sets.ndim == 3 and sets.shape[1:] == (16, 12)
    assert bundle.x_train.shape == (sets.shape[0], 16 * 12)
    assert set(np.unique(bundle.y_train)) <= {0.0, 1.0}
    # planted signal: labels not all identical
    assert 0.05 < bundle.y_train.mean() < 0.95


def test_amorphous_radial_shells_bundle():
    bundle = get_dataset("amorphous_radial_shells", num_synthetic_neighborhoods=64,
                         num_shells=6)
    assert bundle.feature_dimensionalities == [1] * 12
    assert bundle.x_train.shape[-1] == 12
    assert np.all(bundle.x_train >= 0)  # densities


# ------------------------------------------------------------------- tabular
def test_tabular_preprocessor_quantile_and_onehot(rng):
    import pandas as pd

    df = pd.DataFrame({
        "a": rng.normal(size=200),
        "b": rng.exponential(size=200),
        "c": rng.choice(["x", "y", "z"], size=200),
    })
    y = rng.normal(size=200)
    prep = TabularPreprocessor(cat_features=("c",), y_normalize=True).fit(df, y)
    x_t, y_t = prep.transform(df, y)
    assert x_t.shape == (200, 5)  # a, b, 3x onehot
    assert prep.feature_dimensionalities_ == [1, 1, 3]
    assert abs(float(np.mean(y_t))) < 1e-6
    # quantile-normal output: roughly standard normal for continuous cols
    assert abs(float(np.std(x_t[:, 0])) - 1.0) < 0.2


@pytest.mark.parametrize("name", ["wine", "bikeshare", "mice_protein", "credit",
                                  "support2", "microsoft"])
def test_tabular_bundles_synthesize_without_files(name, tmp_path):
    bundle = get_dataset(name, data_path=str(tmp_path))
    assert bundle.extras["source"] == "synthetic"
    assert bundle.x_train.shape[0] > 100
    assert bundle.x_train.dtype == np.float32
    assert bundle.number_features == len(bundle.feature_dimensionalities)
    if bundle.loss == "sparse_ce":
        assert bundle.output_dimensionality >= 2
