"""TRUE multi-process exercise of dib_tpu.parallel.multihost (VERDICT r4
item 7): two OS processes, each owning 2 virtual CPU devices, wired into one
4-device JAX cluster via ``jax.distributed.initialize`` — `initialize()`,
`process_local_batch()` and `fetch_to_host()` all cross real process
boundaries here, not the single-process degenerate paths.

The cluster uses JAX's multi-controller runtime exactly as a TPU pod would
(SURVEY.md section 2.3): same program on every process, a gRPC coordinator,
and cross-process collectives (gloo on CPU standing in for ICI/DCN).
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = """
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, {repo!r})
    port, proc_id, out_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]

    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as e:                       # gloo not in this jaxlib
        json.dump({{"skip": str(e)}}, open(out_path, "w")); sys.exit(0)

    from dib_tpu.parallel.multihost import (
        fetch_to_host, initialize, process_local_batch,
    )

    # the helper's explicit-spec path — the pod-launcher contract
    active = initialize(f"127.0.0.1:{{port}}", num_processes=2,
                        process_id=proc_id)
    assert active, "two-process cluster must report active"
    assert jax.process_count() == 2
    assert len(jax.devices()) == 4               # global across processes
    assert len(jax.local_devices()) == 2

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("data",))
    sharding = NamedSharding(mesh, P("data"))

    # each process feeds ONLY its own rows; the global array is their
    # concatenation in process order
    local_rows = np.arange(proc_id * 2, proc_id * 2 + 2,
                           dtype=np.float32)[:, None] * np.ones((2, 3),
                                                                np.float32)
    garr = process_local_batch(local_rows, sharding)
    assert garr.shape == (4, 3)
    assert not garr.is_fully_addressable         # genuinely cross-process

    # a jitted reduction over the cross-process array: XLA inserts the
    # cross-process all-reduce (gloo here; ICI/DCN on a pod)
    total = float(jax.jit(jnp.sum)(garr))

    # gather the cross-host-sharded array back to EVERY host
    fetched = fetch_to_host({{"batch": garr, "scalar": 7}})
    json.dump({{
        "process_id": proc_id,
        "process_count": jax.process_count(),
        "total": total,
        "fetched_shape": list(np.asarray(fetched["batch"]).shape),
        "fetched_rows": np.asarray(fetched["batch"])[:, 0].tolist(),
        "scalar": int(fetched["scalar"]),
    }}, open(out_path, "w"))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_cluster_end_to_end(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent(_WORKER.format(repo=REPO)))
    port = _free_port()
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                "DIB_COMPILE_CACHE": "",
                "JAX_COMPILATION_CACHE_DIR": "/root/.cache/jax_comp_cache_cpu"})
    outs = [str(tmp_path / f"out{i}.json") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(port), str(i), outs[i]],
            env=env,
        )
        for i in range(2)
    ]
    for p in procs:
        assert p.wait(timeout=300) == 0
    results = [json.load(open(o)) for o in outs]
    if any("skip" in r for r in results):
        pytest.skip(f"CPU cross-process collectives unavailable: {results}")

    # global array rows are 0,1 (proc 0) and 2,3 (proc 1) => sum = 6*3 = 18
    for r in results:
        assert r["process_count"] == 2
        assert r["total"] == pytest.approx(18.0)
        # fetch_to_host delivered the FULL global array to this host
        assert r["fetched_shape"] == [4, 3]
        assert r["fetched_rows"] == [0.0, 1.0, 2.0, 3.0]
        assert r["scalar"] == 7
