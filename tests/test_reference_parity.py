"""Differential parity against the ACTUAL reference implementation.

These tests import and execute the reference codebase (read-only at
/root/reference, TF/Keras) as a behavioral oracle — no code is copied; the
reference runs as-is and its outputs are compared with dib-tpu's:

  1. the beta annealing schedule (reference ``models.py:125-149``) vs
     ``dib_tpu.ops.schedules.log_annealed_beta`` — exact math parity,
  2. the float64 MI sandwich-bound estimator (reference ``utils.py:10-73``)
     vs the f32 log-space ``mi_sandwich_bounds`` — statistical parity on a
     known channel,
  3. an end-to-end boolean-circuit training run (reference ``DistributedIBNet``
     + Keras fit + annealing callback, the ``train.py:133-178`` path) vs
     ``DIBTrainer`` — info-plane trajectory parity (the BASELINE.json
     criterion) at a shrunk configuration.

Skipped wherever TensorFlow or the reference checkout is unavailable.
"""

import os
import sys
from types import SimpleNamespace

import numpy as np
import pytest

REFERENCE_PATH = "/root/reference"


@pytest.fixture(scope="module")
def reference():
    if not os.path.isdir(REFERENCE_PATH):
        pytest.skip("reference checkout not available")
    # The reference is Keras-2 code (add_metric/add_loss in call()); route
    # tf.keras to the legacy tf_keras package. Must happen before TF imports.
    if "tensorflow" in sys.modules and os.environ.get("TF_USE_LEGACY_KERAS") != "1":
        pytest.skip("tensorflow already imported without TF_USE_LEGACY_KERAS")
    pytest.importorskip("tf_keras")
    prev_env = os.environ.get("TF_USE_LEGACY_KERAS")
    prev_bytecode = sys.dont_write_bytecode
    os.environ["TF_USE_LEGACY_KERAS"] = "1"
    sys.dont_write_bytecode = True          # /root/reference is read-only
    sys.path.insert(0, REFERENCE_PATH)
    try:
        tf = pytest.importorskip("tensorflow")
        tf.config.set_visible_devices([], "GPU")
        import models as ref_models
        import utils as ref_utils

        yield SimpleNamespace(models=ref_models, utils=ref_utils, tf=tf)
    finally:
        sys.path.remove(REFERENCE_PATH)
        sys.dont_write_bytecode = prev_bytecode
        if prev_env is None:
            os.environ.pop("TF_USE_LEGACY_KERAS", None)
        else:
            os.environ["TF_USE_LEGACY_KERAS"] = prev_env
        for name in ("models", "utils", "visualization"):
            sys.modules.pop(name, None)


@pytest.mark.slow
def test_beta_schedule_matches_reference_exactly(reference):
    """Our schedule function reproduces the reference callback's beta at every
    epoch — including its quirks: clamped below (pretraining), NOT clamped
    above (it extrapolates past beta_end if trained longer)."""
    from dib_tpu.ops.schedules import log_annealed_beta

    tf = reference.tf
    cb = reference.models.InfoBottleneckAnnealingCallback(
        beta_start=1e-3, beta_end=5.0,
        number_pretraining_epochs=10, number_annealing_epochs=100,
    )
    holder = SimpleNamespace(beta=tf.Variable(1.0, dtype=tf.float32))
    cb.set_model(holder)
    for epoch in [0, 3, 10, 11, 37, 60, 109, 110, 150]:
        cb.on_epoch_begin(epoch)
        ref_beta = float(holder.beta.numpy())
        ours = float(log_annealed_beta(
            epoch, 1e-3, 5.0, 100, 10, clip_progress=False
        ))
        assert ours == pytest.approx(ref_beta, rel=2e-5), f"epoch {epoch}"


@pytest.mark.slow
def test_mi_bounds_match_reference_estimator(reference):
    """The reference's f64 density-space estimator and our f32 log-space one
    agree on a known 2-bit channel (independent u-draws -> statistical
    tolerance; the channel is tight so bounds concentrate)."""
    import jax

    from dib_tpu.ops.info_bounds import mi_sandwich_bounds

    tf = reference.tf
    rng = np.random.default_rng(0)
    n, d, bits = 2048, 8, 2
    corners = np.array(np.meshgrid(*[[-4.0, 4.0]] * bits)).reshape(bits, -1).T
    mus = np.concatenate(
        [corners[rng.integers(0, 4, n)], np.zeros((n, d - bits))], -1
    )
    logvars = np.full((n, d), -2.0)
    concat = np.concatenate([mus, logvars], -1).astype(np.float64)

    tf.random.set_seed(0)
    dataset = tf.data.Dataset.from_tensor_slices(concat)
    ref_lower, ref_upper = reference.utils.estimate_mi_sandwich_bounds(
        lambda batch: batch, dataset,
        evaluation_batch_size=256, number_evaluation_batches=4,
    )

    import jax.numpy as jnp

    data = jnp.asarray(concat, jnp.float32)
    ours_lower, ours_upper = mi_sandwich_bounds(
        lambda batch: (batch[:, :d], batch[:, d:]),
        data, jax.random.key(0),
        evaluation_batch_size=256, number_evaluation_batches=4,
    )
    ln2 = np.log(2.0)
    assert float(ours_lower) / ln2 == pytest.approx(float(ref_lower) / ln2, abs=0.05)
    assert float(ours_upper) / ln2 == pytest.approx(float(ref_upper) / ln2, abs=0.05)
    # both sandwiches contain the true 2 bits
    assert float(ref_lower) / ln2 <= 2.0 + 0.05
    assert float(ours_lower) / ln2 <= 2.0 + 0.05


@pytest.mark.slow
def test_flagship_amorphous_trajectory_parity(reference, tmp_path):
    """FLAGSHIP parity (VERDICT round-4 item 2): the amorphous notebook
    cell-8 loop — per-particle KL, set-transformer aggregator, per-step beta
    ramp, I(U;X) sandwich from eval_start — EXECUTED in TF at a reduced
    budget on the same synthetic neighborhoods as dib-tpu's shipping
    ``run_amorphous_workload``. Bands calibrated from the committed
    ``FLAGSHIP_PARITY.json`` (2500 steps: task-loss max gap 0.193 bits,
    KL spearman 0.90, final KL 8.56 vs 8.41 bits, MI spearman 0.93)."""
    scripts_dir = os.path.join(os.path.dirname(__file__), "..", "scripts")
    sys.path.insert(0, scripts_dir)
    try:
        import flagship_parity as fp
    finally:
        # remove by value: importing flagship_parity prepends REPO itself,
        # so pop(0) would evict the wrong entry
        sys.path.remove(scripts_dir)

    from dib_tpu.data import get_dataset

    cfg = fp.FlagshipConfig(steps=2500)
    bundle = get_dataset(
        "amorphous_particles",
        number_particles_to_use=cfg.particles,
        num_synthetic_neighborhoods=cfg.num_neighborhoods,
        seed=cfg.data_seed,
    )
    ref_ns = fp.load_reference_cells(reference.tf)
    ref = fp.run_reference_flagship(
        reference.tf, ref_ns,
        np.asarray(bundle.extras["sets_train"], np.float32),
        np.asarray(bundle.y_train, np.float32),
        np.asarray(bundle.extras["sets_valid"], np.float32),
        np.asarray(bundle.y_valid, np.float32),
        cfg,
    )
    ours = fp.run_dib_flagship(bundle, cfg, str(tmp_path))
    cmp = fp.compare(ref, ours, cfg)

    # 1. both frameworks keep the task loss in the same regime at EVERY
    #    matched checkpoint (measured max gap 0.19 bits; margin for TF
    #    thread nondeterminism)
    assert cmp["task_loss_max_abs_gap_bits"] < 0.3, cmp
    # 2. the per-step anneal crushes the per-particle channel identically
    #    (measured final 8.56 vs 8.41 bits)
    fin = cmp["final_kl_bits"]
    assert fin["reference"] < 15 and fin["dib_tpu"] < 15, cmp
    ratio = max(fin["reference"], fin["dib_tpu"]) / max(
        min(fin["reference"], fin["dib_tpu"]), 1e-9)
    assert ratio < 1.35, cmp
    # 3. info-plane x-axis parity: KL trajectories strongly rank-correlated
    #    over the anneal (the wide-open first half is init noise — seed-1
    #    check measured full-series rho 0.66 but anneal-phase 1.0);
    #    constrained-regime checkpoints inside the boolean-test envelope
    assert cmp["kl_spearman_anneal"] > 0.9, cmp
    if cmp["kl_constrained_max_ratio"] is not None:
        assert cmp["kl_constrained_max_ratio"] < 1.75 or \
            cmp["kl_constrained_max_abs_gap_bits"] < 0.75, cmp
    # 4. the measured I(U;X) sandwich (executed cell-5 estimator vs the
    #    vmapped log-space hook) tracks across the anneal and lands on the
    #    same final total information
    assert cmp["mi_checkpoints_compared"] >= 10, cmp
    assert cmp["mi_spearman"] > 0.85, cmp
    ref_mi = np.mean(cmp["final_total_info_bits"]["reference_sandwich"])
    our_mi = np.mean(cmp["final_total_info_bits"]["dib_tpu_sandwich"])
    assert abs(ref_mi - our_mi) < max(0.25 * ref_mi, 1.0), cmp


@pytest.mark.slow
def test_info_plane_trajectory_parity_boolean(reference):
    """End-to-end: the reference Keras path and dib-tpu trained on the same
    circuit with the same schedule produce matching info-plane trajectories
    (statistical: different RNG/init/optimizer internals)."""
    import jax

    from dib_tpu.data import get_dataset
    from dib_tpu.models import DistributedIBModel
    from dib_tpu.train import DIBTrainer, TrainConfig

    tf = reference.tf
    tf.keras.utils.set_random_seed(0)

    bundle = get_dataset("boolean_circuit")        # the paper circuit
    x, y = bundle.x_train, bundle.y_train
    pre, anneal, batch = 100, 200, 256
    beta_start, beta_end = 1e-4, 3.0
    arch, integ, emb = [32], [64], 4
    lr = 1e-3

    ref_model = reference.models.DistributedIBNet(
        feature_dimensionalities=[1] * 10,
        feature_encoder_architecture=arch,
        integration_network_architecture=integ,
        output_dimensionality=1,
        feature_embedding_dimension=emb,
    )
    # The reference's DistributedIBNet.build calls
    # self.integration_network.build() with no input shape — a documented
    # breakage (SURVEY.md section 0; reference models.py:93) that modern
    # Keras rejects. The sub-Sequentials are already built (they start with
    # Input layers), so a no-op build is the working behavior.
    ref_model.build = lambda *a, **k: setattr(ref_model, "built", True)
    ref_model.compile(
        optimizer=tf.keras.optimizers.Adam(lr),
        loss=tf.keras.losses.BinaryCrossentropy(from_logits=True),
    )
    cb = reference.models.InfoBottleneckAnnealingCallback(
        beta_start, beta_end, pre, anneal)
    hist = ref_model.fit(
        x, y, batch_size=batch, epochs=pre + anneal, callbacks=[cb], verbose=0
    ).history
    betas = np.array([
        np.exp(np.log(beta_start)
               + max(e - pre, 0) / anneal * (np.log(beta_end) - np.log(beta_start)))
        for e in range(pre + anneal)
    ])
    ref_kl = np.stack([hist[f"KL{i}"] for i in range(10)], -1)      # nats
    ref_total_kl_bits = ref_kl.sum(-1) / np.log(2.0)
    # Keras 'loss' is the epoch-averaged combined objective; un-mix it the
    # way the reference does on host (train.py:169-174)
    ref_task_bits = (np.array(hist["loss"]) - betas * ref_kl.sum(-1)) / np.log(2.0)

    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=tuple(arch), integration_hidden=tuple(integ),
        output_dim=1, embedding_dim=emb,
    )
    config = TrainConfig(
        learning_rate=lr, batch_size=batch,
        beta_start=beta_start, beta_end=beta_end,
        num_pretraining_epochs=pre, num_annealing_epochs=anneal,
        max_val_points=1024,
    )
    trainer = DIBTrainer(model, bundle, config)
    _, history = trainer.fit(jax.random.key(0))
    ours = history.to_bits()

    # 1. pretraining learns the task in both frameworks (H(Y) = 0.758 bits)
    assert ref_task_bits[pre - 1] < 0.65
    assert ours.loss[pre - 1] < 0.65
    # 2. the anneal crushes the channel in both (same final beta)
    assert ref_total_kl_bits[-1] < 1.5
    assert float(ours.total_kl[-1]) < 1.5
    # 3. trajectory shape parity: total-KL series strongly rank-correlated
    #    across the anneal (the info-plane x-axis)
    from scipy.stats import spearmanr

    rho = spearmanr(ref_total_kl_bits[pre:], np.asarray(ours.total_kl)[pre:]).statistic
    assert rho > 0.9, f"info-plane KL trajectories diverge (spearman {rho:.3f})"
    # 4. quantitative beta-matched parity (VERDICT round 1, item 5). Two
    #    regimes at each matched beta checkpoint:
    #    - CONSTRAINED (the anneal has started compressing, KL <= 50 bits):
    #      total KL within a factor of 1.75 (0.75-bit absolute floor where
    #      the channel is nearly crushed). Measured agreement is 1.0-1.6x;
    #      the bound is ratcheted to that envelope (VERDICT round 2, item 8)
    #      with a small margin for independent inits/RNG.
    #    - WIDE-OPEN (early anneal, both > 50 bits): KL is initialization
    #      noise — the reference itself varies ~1.7x run to run there — so
    #      only a both-channels-wide-open sanity check applies.
    #    The RECOVERED TASK LOSS (info-plane y-axis, loss minus beta*KL,
    #    un-mixed the reference's way) must match within 0.2 bits at EVERY
    #    checkpoint (measured: <= 0.16; ratcheted from 0.25, VERDICT round
    #    2, item 8).
    ours_task_bits = np.asarray(ours.loss)
    for frac in (0.1, 0.25, 0.4, 0.5, 0.6, 0.75, 0.9, 1.0):
        e = min(pre + int(frac * anneal), pre + anneal - 1)
        a, b = ref_total_kl_bits[e], float(ours.total_kl[e])
        lo, hi = min(a, b), max(a, b)
        if lo > 50.0:      # wide open: init noise dominates
            pass
        else:
            assert hi - lo < 0.75 or hi < 1.75 * lo, (
                f"KL at anneal {frac:.0%} (beta {betas[e]:.2e}): reference "
                f"{a:.2f} vs ours {b:.2f} bits (> 1.75x apart)"
            )
        ta, tb = ref_task_bits[e], ours_task_bits[e]
        assert abs(ta - tb) < 0.2, (
            f"recovered task loss at anneal {frac:.0%}: reference {ta:.3f} "
            f"vs ours {tb:.3f} bits"
        )
