"""Async serving engine: continuous batching, event-loop front end,
tenancy/quotas, admission control (docs/serving.md "The async front end").

The sharp edges the ISSUE-10 rebuild must prove:

  - **continuous-batch join**: a request arriving MID-DISPATCH lands in
    the very next batch the moment the executable returns — no fresh
    ``max_wait_ms`` window is waited out over a non-empty queue;
  - **quota 429**: a tenant over its token bucket is refused with 429 +
    ``Retry-After`` while other tenants keep serving;
  - **admission shed**: the global in-flight bound refuses with 503
    before any queueing happens;
  - **the tier-1 smoke**: asyncio server → concurrent mixed-tenant
    requests → ``summarize`` accepts the stream (tenant/cache/quota keys
    in the ``serving`` rollup) and ``telemetry check`` passes under the
    committed SLO.json.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.models import DistributedIBModel
from dib_tpu.serve import (
    DIBServer,
    InferenceEngine,
    MicroBatcher,
    ModelZoo,
    ReplicaEntry,
    ReplicaRouter,
    TenantQuotas,
)
from dib_tpu.telemetry import (
    EventWriter,
    MetricsRegistry,
    Tracer,
    read_events,
    runtime_manifest,
    summarize,
)


@pytest.fixture(scope="module")
def bundle():
    return get_dataset("boolean_circuit")


@pytest.fixture(scope="module")
def model(bundle):
    return DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )


@pytest.fixture(scope="module")
def params(bundle, model):
    x0 = np.asarray(bundle.x_train[:4], np.float32)
    return model.init(jax.random.key(0), x0, jax.random.key(1))


def _post(url: str, payload: dict, headers: dict | None = None):
    request = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(request, timeout=30) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read()), dict(exc.headers)


# -------------------------------------------------------- continuous batching
class _GatedEngine:
    """First dispatch blocks until released — the window in which a
    mid-dispatch request must queue and then ride the NEXT dispatch."""

    feature_width = 4
    max_bucket = 8

    def __init__(self):
        self.release = threading.Event()
        self.calls: list[int] = []

    def bucket_for(self, n: int) -> int:
        return 8

    def predict(self, x):
        first = not self.calls
        self.calls.append(int(np.asarray(x).shape[0]))
        if first:
            assert self.release.wait(10.0), "test never released the gate"
        return {"prediction": np.asarray(x)[:, :1]}

    encode = predict


def test_request_arriving_mid_dispatch_joins_the_very_next_batch():
    """THE continuous-batching contract: with a deliberately huge
    max_wait_ms, a request that arrived while a dispatch was in flight
    completes promptly after the dispatch returns — a collect-then-wait
    batcher would hold it for the full window over an idle engine."""
    engine = _GatedEngine()
    batcher = MicroBatcher(engine, max_batch=2, max_wait_ms=5000.0)
    # fill max_batch so the first dispatch starts without a window
    a = batcher.submit(np.zeros(4, np.float32), timeout_s=30.0)
    b = batcher.submit(np.zeros(4, np.float32), timeout_s=30.0)
    deadline = time.monotonic() + 5.0
    while not engine.calls and time.monotonic() < deadline:
        time.sleep(0.005)
    assert engine.calls, "first dispatch never started"
    # c arrives MID-DISPATCH
    c = batcher.submit(np.ones(4, np.float32), timeout_s=30.0)
    engine.release.set()
    t0 = time.monotonic()
    assert c.result(10.0)["prediction"][0][0] == 1.0
    elapsed = time.monotonic() - t0
    a.result(10.0), b.result(10.0)
    batcher.close()
    # far below the 5 s window a non-continuous batcher would have waited
    assert elapsed < 2.0, f"mid-dispatch join took {elapsed:.2f}s"
    assert engine.calls == [2, 1]


def test_idle_lone_request_still_pays_only_max_wait():
    """The depth-1 latency floor is unchanged: an idle engine holds a
    lone request only max_wait_ms for batch-mates."""
    engine = _GatedEngine()
    engine.release.set()   # no gating
    batcher = MicroBatcher(engine, max_batch=8, max_wait_ms=30.0)
    t0 = time.monotonic()
    batcher(np.zeros(4, np.float32), timeout_s=10.0)
    assert time.monotonic() - t0 < 2.0
    batcher.close()


# ------------------------------------------------------------------- quotas
def test_tenant_quota_bucket_math():
    quotas = TenantQuotas(rate=10.0, burst=2.0,
                          overrides={"gold": (100.0, 100.0)})
    assert quotas.admit("a") == 0.0
    assert quotas.admit("a") == 0.0
    retry = quotas.admit("a")            # burst exhausted
    assert 0.0 < retry <= 0.1 + 1e-6
    assert quotas.admit("b") == 0.0      # buckets are per-tenant
    for _ in range(50):
        assert quotas.admit("gold") == 0.0   # override tier
    assert TenantQuotas(rate=0.0).admit("anyone") == 0.0   # disabled


def test_tenant_quota_bucket_map_is_bounded():
    """Tenant ids are client-controlled, so the bucket map must not grow
    without bound — and pruning must never refund a genuinely throttled
    tenant (eviction resets a bucket to FULL, so only near-full buckets
    may go)."""
    quotas = TenantQuotas(rate=100.0, burst=2.0, max_tenants=50)
    for i in range(500):
        quotas.admit(f"throwaway-{i}")
    assert len(quotas._buckets) <= 50
    # a tenant mid-throttle survives a unique-id flood un-reset
    slow = TenantQuotas(rate=0.5, burst=2.0, max_tenants=4)
    assert slow.admit("a") == 0.0 and slow.admit("a") == 0.0
    assert slow.admit("a") > 0            # burst spent, now draining
    for i in range(10):
        slow.admit(f"x{i}")               # flood forces pruning
    assert slow.admit("a") > 0, \
        "pruning refunded a throttled tenant's burst"


def _stack(model, params, run_dir=None, quotas=None, admission_limit=None,
           response_capacity=None, max_wait_ms=1.0):
    writer = registry = tracer = None
    registry = MetricsRegistry()
    if run_dir is not None:
        writer = EventWriter(run_dir)
        writer.run_start(runtime_manifest(extra={"mode": "serve"}))
        tracer = Tracer(writer)
    engine = InferenceEngine(model, params, batch_buckets=(1, 4),
                             telemetry=writer, registry=registry)
    batcher = MicroBatcher(engine, max_batch=4, max_wait_ms=max_wait_ms,
                           tracer=tracer, registry=registry)
    router = ReplicaRouter([ReplicaEntry(engine, batcher, 0)])
    zoo = ModelZoo.single(router, response_capacity=response_capacity,
                          telemetry=writer, registry=registry)
    server = DIBServer(zoo, port=0, telemetry=writer, registry=registry,
                       tracer=tracer, quotas=quotas,
                       admission_limit=admission_limit).start()
    return server, registry


def test_quota_exhausted_tenant_gets_429_with_retry_after(model, params):
    """The new 429 arm: a tenant past its burst is refused with
    Retry-After; a different tenant is admitted concurrently; the
    rejection is visible in /metrics."""
    server, registry = _stack(
        model, params, quotas=TenantQuotas(rate=0.5, burst=2.0))
    try:
        width = server.router.entries[0].engine.feature_width
        row = [0.0] * width
        seen = []
        for _ in range(4):
            status, payload, headers = _post(
                server.url + "/v1/predict", {"x": row},
                headers={"X-DIB-Tenant": "greedy"})
            seen.append(status)
        assert seen[:2] == [200, 200]
        assert 429 in seen[2:]
        idx = seen.index(429)
        status, payload, headers = 429, None, None
        # re-fetch one more 429 deterministically (bucket refills at 0.5/s)
        status, payload, headers = _post(
            server.url + "/v1/predict", {"x": row},
            headers={"X-DIB-Tenant": "greedy"})
        assert status == 429
        assert "quota" in payload["error"]
        assert payload["tenant"] == "greedy"
        assert float(headers["Retry-After"]) >= 1
        assert payload["retry_after_s"] > 0
        # a WELL-BEHAVED tenant is untouched by the greedy one's bucket
        status, _, _ = _post(server.url + "/v1/predict", {"x": row},
                             headers={"X-DIB-Tenant": "polite"})
        assert status == 200
        # tenant field in the body works too
        status, _, _ = _post(server.url + "/v1/predict",
                             {"x": row, "tenant": "greedy"})
        assert status == 429
        assert registry.snapshot()["counters"]["serve.requests.quota"] >= 2
    finally:
        server.close()


def test_admission_limit_sheds_with_503(model, params):
    """Global admission control: beyond the in-flight bound requests shed
    BEFORE queueing, with 503 + Retry-After."""

    class _SlowBatcher:
        def __init__(self, inner):
            self.inner = inner

        def is_alive(self):
            return True

        def close(self):
            self.inner.close()

        def submit(self, x, op, timeout_s=None, tenant=None):
            time.sleep(0.4)
            return self.inner.submit(x, op, timeout_s=timeout_s,
                                     tenant=tenant)

    engine = InferenceEngine(model, params, batch_buckets=(1,))
    batcher = _SlowBatcher(MicroBatcher(engine, max_wait_ms=0.0))
    router = ReplicaRouter([ReplicaEntry(engine, batcher, 0)])
    server = DIBServer(router, port=0, admission_limit=1,
                       registry=MetricsRegistry()).start()
    try:
        width = engine.feature_width
        row = [0.0] * width
        results = []

        def client():
            results.append(_post(server.url + "/v1/predict", {"x": row}))

        threads = [threading.Thread(target=client) for _ in range(3)]
        threads[0].start()
        time.sleep(0.15)   # first request is now in flight
        for t in threads[1:]:
            t.start()
        for t in threads:
            t.join(timeout=30)
        codes = sorted(status for status, _, _ in results)
        assert codes[0] == 200 and codes[-1] == 503
        shed = [payload for status, payload, _ in results if status == 503]
        assert any("admission limit" in p["error"] for p in shed)
    finally:
        server.close()


# ------------------------------------------------------------ tier-1 smoke
def test_async_server_mixed_tenant_smoke(model, params, bundle, tmp_path):
    """THE ISSUE-10 serving CI gate: asyncio server, concurrent clients
    across tenants, repeated queries through the response cache; the
    stream summarizes with the new serving-rollup keys and passes
    `telemetry check` under the committed SLO.json."""
    run_dir = str(tmp_path / "serve_async_run")
    server, registry = _stack(
        model, params, run_dir=run_dir,
        quotas=TenantQuotas(rate=1000.0, burst=1000.0),
        response_capacity=64)
    rows = np.asarray(bundle.x_valid[:8], np.float32)
    statuses: list[tuple[int, dict]] = []

    def client(tid):
        tenant = ("alpha", "beta", "gamma")[tid % 3]
        for j in range(4):
            i = tid * 4 + j
            # i % 4 repeats inputs across clients -> cache traffic
            status, payload, _ = _post(
                server.url + "/v1/predict", {"x": rows[i % 4].tolist()},
                headers={"X-DIB-Tenant": tenant})
            statuses.append((status, payload))

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert [s for s, _ in statuses] == [200] * 24
    # a sequential repeat is a DETERMINISTIC response-cache hit (the
    # concurrent wave above may race its own first fills)
    status, repeat, _ = _post(server.url + "/v1/predict",
                              {"x": rows[0].tolist()},
                              headers={"X-DIB-Tenant": "alpha"})
    assert status == 200 and repeat.get("cached") is True
    # an encode rides the same stream
    status, enc, _ = _post(server.url + "/v1/encode",
                           {"x": rows[0].tolist()})
    assert status == 200 and "mus" in enc
    server.close()

    events = list(read_events(run_dir))
    assert events[0]["type"] == "run_start"
    assert events[-1]["type"] == "run_end"
    request_spans = [e for e in events
                     if e["type"] == "span" and e["name"] == "request"]
    assert len(request_spans) == 26
    assert {e.get("tenant") for e in request_spans if e.get("tenant")} \
        >= {"alpha", "beta", "gamma"}   # encode rides as "anonymous"
    assert any(e.get("cached") for e in request_spans)

    summary = summarize(run_dir)
    serving = summary["serving"]
    assert serving["requests"] == 26
    assert serving["statuses"]["ok"] == 26
    assert serving["tenants"].keys() >= {"alpha", "beta", "gamma"}
    assert serving["cached_requests"] >= 1
    assert 0 < serving["cache_hit_frac"] < 1
    assert serving["quota_rejected_frac"] == 0.0
    assert serving["response_cache"]["hits"] >= 1
    assert serving["response_cache"]["misses"] >= 1
    assert "hit_frac" in serving["response_cache"]
    assert serving["uncached_request_p99_ms"] >= 0

    # the committed SLO budget accepts the stream (rc 0, nothing written)
    from dib_tpu.telemetry.slo import check_run

    report = check_run(run_dir, "SLO.json", write=False)
    assert report["violations"] == 0, report


def test_http_keepalive_and_model_listing(model, params):
    """The asyncio front end keeps HTTP/1.1 connections alive across
    requests on one socket, and /v1/models lists the zoo."""
    import http.client

    server, _ = _stack(model, params)
    try:
        width = server.router.entries[0].engine.feature_width
        conn = http.client.HTTPConnection(server.host, server.port,
                                          timeout=30)
        for _ in range(3):
            conn.request("POST", "/v1/predict",
                         body=json.dumps({"x": [0.0] * width}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            json.loads(resp.read())
        conn.request("GET", "/v1/models")
        resp = conn.getresponse()
        assert resp.status == 200
        listing = json.loads(resp.read())
        assert listing["models"][0]["model"] == "default"
        conn.close()
    finally:
        server.close()
