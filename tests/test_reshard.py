"""Mesh-shape-portable checkpoints + the shard_map sweep engine.

The PR 13 acceptance contract (docs/parallelism.md):

  - the explicit ``('sweep', 'data')`` shard_map engine matches the serial
    ``DIBTrainer`` BIT-identically on the same keys (one replica per shard
    traces exactly the serial epoch body);
  - a checkpoint saved at sweep width R restores and CONTINUES training at
    width R' != R — shrink, grow, width-1 carve-out — with the matched
    members' histories, resume keys, and continued trajectories
    bit-identical to the uninterrupted width-R run
    (``parallel/elastic.py:restore_sweep_resharded``);
  - pre-mesh (manifest v1) checkpoints still restore: the reshard is
    vacuous, widths must match, nothing breaks.

Fit-driving tests share the module-scoped width-4 baseline + checkpoint
fixtures; the grow case (2R) rides the slow tier with the rest of the
heavy sweep matrix (tests/test_parallel.py convention).
"""

import json
import os
import shutil

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.models import DistributedIBModel
from dib_tpu.parallel import (
    BetaSweepTrainer,
    backfill_member,
    factor_devices,
    make_sweep_engine_mesh,
    make_sweep_mesh,
    restore_sweep_resharded,
    validate_sweep_shapes,
)
from dib_tpu.train import CheckpointHook, DIBCheckpointer, DIBTrainer, TrainConfig
from dib_tpu.train.checkpoint import (
    MANIFEST_FILENAME,
    read_manifest,
    write_manifest,
)

CHUNK = 4
ENDS = (0.03, 0.1, 0.3, 1.0)

CFG = TrainConfig(
    batch_size=64,
    beta_start=1e-3,
    beta_end=1.0,
    num_pretraining_epochs=2,
    num_annealing_epochs=6,
    steps_per_epoch=2,
    max_val_points=128,
)


@pytest.fixture(scope="module")
def bundle():
    return get_dataset("boolean_circuit")


@pytest.fixture(scope="module")
def model(bundle):
    return DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,),
        integration_hidden=(16,),
        output_dim=1,
        embedding_dim=2,
    )


def _keys():
    return jax.random.split(jax.random.key(0), len(ENDS))


def _history_identical(a, b):
    return (np.array_equal(a.loss, b.loss)
            and np.array_equal(a.kl_per_feature, b.kl_per_feature)
            and np.array_equal(a.beta, b.beta))


@pytest.fixture(scope="module")
def full_run(model, bundle):
    """The uninterrupted width-4 shard_map run every reshard compares to.

    ``hook_every=CHUNK`` pins the chunk boundaries — the PRNG chain is
    keyed to them, so bit-identical continuation (like bit-identical
    resume everywhere else in the tree) is defined at matching chunk
    size."""
    mesh = make_sweep_engine_mesh(len(ENDS), 1)
    sweep = BetaSweepTrainer(model, bundle, CFG, 1e-3, jnp.asarray(ENDS),
                             mesh=mesh)
    assert sweep.engine == "shard_map"
    states, records = sweep.fit(_keys(), hook_every=CHUNK)
    return {
        "states": states,
        "records": records,
        "resume_key": sweep.resume_key,
    }


@pytest.fixture(scope="module")
def ckpt_dir(model, bundle, tmp_path_factory):
    """A width-4 checkpoint saved mid-run (epoch 4 of 8) on the shard_map
    mesh — the artifact every reshard test restores from."""
    path = tmp_path_factory.mktemp("reshard") / "ckpt"
    mesh = make_sweep_engine_mesh(len(ENDS), 1)
    sweep = BetaSweepTrainer(model, bundle, CFG, 1e-3, jnp.asarray(ENDS),
                             mesh=mesh)
    ckpt = DIBCheckpointer(str(path))
    sweep.fit(_keys(), num_epochs=CHUNK, hooks=[CheckpointHook(ckpt)],
              hook_every=CHUNK)
    ckpt.close()
    return str(path)


# ------------------------------------------------------- engine contract
def test_shard_map_engine_matches_serial_bit_identical(model, bundle):
    """THE numerical contract: a shard_map sweep replica with the serial
    trainer's key reproduces it bit for bit (not tolerance — equality)."""
    key = jax.random.key(7)
    serial = DIBTrainer(model, bundle, CFG)
    _, hist = serial.fit(key)

    mesh = make_sweep_engine_mesh(1, 1)
    sweep = BetaSweepTrainer(model, bundle, CFG, CFG.beta_start,
                             jnp.asarray([CFG.beta_end]), mesh=mesh)
    assert sweep.engine == "shard_map"
    _, records = sweep.fit(jnp.stack([key]))

    assert np.array_equal(np.asarray(records[0].loss), np.asarray(hist.loss))
    assert np.array_equal(np.asarray(records[0].kl_per_feature),
                          np.asarray(hist.kl_per_feature))
    assert np.array_equal(np.asarray(records[0].beta), np.asarray(hist.beta))


def test_data_sharded_engine_trains_deterministically(model, bundle):
    """The nd>1 arm: each data shard gathers only ITS permutation row
    block (`_epoch_batches` pre-slices the index array) and draws
    shard-folded noise, so the run is a different — equally valid —
    stochastic realization than nd=1 (docs/parallelism.md, "Numerical
    contract"). Pin what the contract does promise: the run trains,
    and it is bit-reproducible."""
    mesh = make_sweep_engine_mesh(2, 2)

    def run():
        sweep = BetaSweepTrainer(model, bundle, CFG, 1e-3,
                                 jnp.asarray(ENDS[:2]), mesh=mesh)
        assert sweep.engine == "shard_map"
        _, records = sweep.fit(_keys()[:2], hook_every=CHUNK)
        return records

    first, second = run(), run()
    for ra, rb in zip(first, second):
        assert np.isfinite(np.asarray(ra.loss)).all()
        assert _history_identical(ra, rb)


def test_engine_selection_and_validation(model, bundle):
    ends = jnp.asarray([0.1, 1.0])
    # no mesh: vmap fallback; forcing shard_map without a mesh is an error
    plain = BetaSweepTrainer(model, bundle, CFG, 1e-3, ends)
    assert plain.engine == "vmap"
    with pytest.raises(ValueError, match="make_sweep_engine_mesh"):
        BetaSweepTrainer(model, bundle, CFG, 1e-3, ends, engine="shard_map")
    # 'sweep' mesh: auto resolves to shard_map, forcing vmap stays allowed
    # (the A/B parity configuration)
    mesh = make_sweep_engine_mesh(2, 1)
    assert BetaSweepTrainer(model, bundle, CFG, 1e-3, ends,
                            mesh=mesh).engine == "shard_map"
    assert BetaSweepTrainer(model, bundle, CFG, 1e-3, ends, mesh=mesh,
                            engine="vmap").engine == "vmap"
    # legacy 'beta' mesh cannot drive the shard_map engine
    legacy = make_sweep_mesh(2, 1)
    assert BetaSweepTrainer(model, bundle, CFG, 1e-3, ends,
                            mesh=legacy).engine == "vmap"
    with pytest.raises(ValueError, match="'beta' mesh drives the vmap"):
        BetaSweepTrainer(model, bundle, CFG, 1e-3, ends, mesh=legacy,
                         engine="shard_map")
    with pytest.raises(ValueError, match="engine must be"):
        BetaSweepTrainer(model, bundle, CFG, 1e-3, ends, engine="pjit")


# --------------------------------------------------- reshard-on-restore
def test_reshard_shrink_continues_bit_identically(model, bundle, full_run,
                                                  ckpt_dir):
    """Width 4 -> 2: the surviving members' continued trajectories AND
    final resume keys match the uninterrupted width-4 run exactly."""
    mesh = make_sweep_engine_mesh(2, 1)
    sweep = BetaSweepTrainer(model, bundle, CFG, 1e-3,
                             jnp.asarray([ENDS[1], ENDS[3]]), mesh=mesh)
    ckpt = DIBCheckpointer(ckpt_dir)
    try:
        states, histories, keys, info = restore_sweep_resharded(
            ckpt, sweep, chunk_size=CHUNK)
    finally:
        ckpt.close()
    assert info["saved_width"] == 4 and info["restored_width"] == 2
    assert info["matched"] == [0, 1] and info["new"] == []

    done = int(np.max(np.asarray(jax.device_get(states.epoch))))
    _, records = sweep.fit(keys, num_epochs=CFG.num_epochs - done,
                           states=states, histories=histories,
                           hook_every=CHUNK)
    for lane, rec in zip((1, 3), records):
        assert _history_identical(full_run["records"][lane], rec)
    # the resume-key chain is the SAME bitstream the width-4 run ended on
    want = np.asarray(jax.random.key_data(full_run["resume_key"]))[[1, 3]]
    got = np.asarray(jax.random.key_data(sweep.resume_key))
    assert np.array_equal(got, want)


def test_reshard_carveout_width_one_no_mesh(model, bundle, full_run,
                                            ckpt_dir):
    """Width 4 -> 1, meshless: carve one member out of a pod-trained
    checkpoint and continue it on a single device."""
    sweep = BetaSweepTrainer(model, bundle, CFG, 1e-3,
                             jnp.asarray([ENDS[2]]))
    ckpt = DIBCheckpointer(ckpt_dir)
    try:
        states, histories, keys, info = restore_sweep_resharded(
            ckpt, sweep, chunk_size=CHUNK)
    finally:
        ckpt.close()
    assert info["saved_width"] == 4 and info["restored_width"] == 1
    assert info["matched"] == [0]

    done = int(np.max(np.asarray(jax.device_get(states.epoch))))
    _, records = sweep.fit(keys, num_epochs=CFG.num_epochs - done,
                           states=states, histories=histories,
                           hook_every=CHUNK)
    assert _history_identical(full_run["records"][2], records[0])


@pytest.mark.slow
def test_reshard_grow_matches_and_inits_new(model, bundle, full_run,
                                            ckpt_dir):
    """Width 4 -> 8: matched members continue bit-identically; the four
    new endpoints start fresh from their own keys at epoch 0."""
    ends8 = jnp.asarray(list(ENDS) + [3.0, 10.0, 0.01, 0.05])
    mesh = make_sweep_engine_mesh(8, 1)
    sweep = BetaSweepTrainer(model, bundle, CFG, 1e-3, ends8, mesh=mesh)
    ckpt = DIBCheckpointer(ckpt_dir)
    try:
        new_keys = jax.random.split(jax.random.key(99), 4)
        states, histories, keys, info = restore_sweep_resharded(
            ckpt, sweep, chunk_size=CHUNK, new_member_keys=new_keys)
    finally:
        ckpt.close()
    assert info["matched"] == [0, 1, 2, 3] and info["new"] == [4, 5, 6, 7]
    epochs = np.asarray(jax.device_get(states.epoch))
    assert list(epochs) == [CHUNK] * 4 + [0] * 4

    done = int(np.max(epochs))
    _, records = sweep.fit(keys, num_epochs=CFG.num_epochs - done,
                           states=states, histories=histories,
                           hook_every=CHUNK)
    for lane in range(4):
        assert _history_identical(full_run["records"][lane], records[lane])
    # new members actually trained (their own beta ramps, finite losses)
    for lane in range(4, 8):
        tail = np.asarray(records[lane].loss)[-(CFG.num_epochs - done):]
        assert np.isfinite(tail).all()


def test_reshard_grow_requires_new_member_keys(model, bundle, ckpt_dir):
    ends = jnp.asarray(list(ENDS) + [42.0])
    sweep = BetaSweepTrainer(model, bundle, CFG, 1e-3, ends)
    ckpt = DIBCheckpointer(ckpt_dir)
    try:
        with pytest.raises(ValueError, match="new_member_keys"):
            restore_sweep_resharded(ckpt, sweep, chunk_size=CHUNK)
    finally:
        ckpt.close()


def test_premesh_checkpoint_restores_vacuously(model, bundle, ckpt_dir,
                                               tmp_path):
    """Backward compat: a manifest-v1 checkpoint (no mesh block) restores
    through the plain path — same width, vacuous reshard, no error."""
    legacy = tmp_path / "legacy_ckpt"
    shutil.copytree(ckpt_dir, legacy)
    path = legacy / MANIFEST_FILENAME
    manifest = json.loads(path.read_text())
    manifest.pop("mesh", None)
    manifest.pop("sharding_rows", None)
    manifest["checkpoint_schema"] = 1
    path.write_text(json.dumps(manifest))

    mesh = make_sweep_engine_mesh(len(ENDS), 1)
    sweep = BetaSweepTrainer(model, bundle, CFG, 1e-3, jnp.asarray(ENDS),
                             mesh=mesh)
    ckpt = DIBCheckpointer(str(legacy))
    try:
        states, histories, keys, info = restore_sweep_resharded(
            ckpt, sweep, chunk_size=CHUNK)
    finally:
        ckpt.close()
    assert info["saved_width"] == info["restored_width"] == len(ENDS)
    assert info["saved_mesh_axes"] is None
    assert int(np.max(np.asarray(jax.device_get(states.epoch)))) == CHUNK


# ----------------------------------------------------- manifest contract
def test_manifest_v2_mesh_rows(ckpt_dir):
    manifest = read_manifest(ckpt_dir)
    # mesh rows unchanged since v2; the schema reads 3 because sweep
    # saves also carry the ISSUE 14 content-digest block
    assert manifest["checkpoint_schema"] == 3
    assert manifest["content"]
    block = manifest["mesh"]
    assert block["logical_grid"] == [len(ENDS)]
    assert block["beta_ends"] == [pytest.approx(b) for b in ENDS]
    assert block["engine"] == "shard_map"
    assert block["mesh_axes"] == {"sweep": len(ENDS), "data": 1}
    assert block["replica_axis"] == "sweep"
    rows = manifest["sharding_rows"]
    assert rows == sorted(rows) and rows
    # every row is "leaf-path partition-spec"
    assert all(len(r.split(" ", 1)) == 2 for r in rows)
    assert any(r.startswith("state") for r in rows)
    assert any(r.startswith("history") for r in rows)


def test_serial_manifest_carries_no_mesh_block(tmp_path):
    params = {"w": jnp.zeros((2,))}
    write_manifest(str(tmp_path), params)
    manifest = read_manifest(str(tmp_path))
    # mesh-free manifests stay on schema 1: the payload is unchanged, so
    # a v1-only reader (a not-yet-upgraded worker stealing a serial unit
    # mid-rolling-upgrade) must keep restoring them
    assert manifest["checkpoint_schema"] == 1
    assert "mesh" not in manifest and "sharding_rows" not in manifest


# ------------------------------------------------------------ mesh utils
def test_factor_devices_num_replicas_mode():
    # never factors the sweep axis wider than R — and it always DIVIDES R
    assert factor_devices(8, num_replicas=6) == (2, 4)
    assert factor_devices(8, num_replicas=8) == (8, 1)
    assert factor_devices(8, num_replicas=3) == (1, 8)
    assert factor_devices(8, num_replicas=16) == (8, 1)
    assert factor_devices(6, num_replicas=4) == (2, 3)
    assert factor_devices(8, num_replicas=1) == (1, 8)
    with pytest.raises(ValueError, match="num_replicas"):
        factor_devices(8, num_replicas=0)
    # legacy mode unchanged
    assert factor_devices(8) == (4, 2)


def test_validate_sweep_shapes_errors_name_the_fix():
    mesh = make_sweep_engine_mesh(4, 2)
    with pytest.raises(ValueError, match=r"num_replicas=8"):
        validate_sweep_shapes(mesh, 6, 64)
    with pytest.raises(ValueError, match=r"factor_devices"):
        validate_sweep_shapes(mesh, 6, 64)
    with pytest.raises(ValueError, match=r"pad batch_size to 64"):
        validate_sweep_shapes(mesh, 4, 63)
    # clean shapes pass for both mesh flavors
    validate_sweep_shapes(mesh, 4, 64)
    validate_sweep_shapes(make_sweep_mesh(4, 2), 4, 64)


def test_sweep_engine_mesh_axes():
    mesh = make_sweep_engine_mesh(4, 2)
    assert mesh.shape == {"sweep": 4, "data": 2}
    from dib_tpu.parallel import sweep_axis_name

    assert sweep_axis_name(mesh) == "sweep"
    assert sweep_axis_name(make_sweep_mesh(4, 2)) == "beta"


# ---------------------------------------------------- scheduler mesh unit
def test_sched_runner_sweep_unit_and_reshard_resume(tmp_path):
    """The scheduler hands a job a whole mesh: a unit whose spec carries
    ``betas`` trains the full grid as ONE sweep, and a re-submission at a
    different grid width reshards the unit's checkpoint instead of
    wedging (the stolen-by-a-differently-shaped-holder path)."""
    from dib_tpu.sched import TrainingUnitRunner
    from dib_tpu.sched.scheduler import WorkUnit

    spec = {"betas": [0.1, 1.0], "chunk_epochs": 2}
    unit = WorkUnit(unit_id="u1", job_id="j1", beta=1.0, seed=3,
                    train=spec)
    mesh = make_sweep_engine_mesh(2, 1)
    runner = TrainingUnitRunner(str(tmp_path), mesh=mesh)
    result = runner(unit)
    assert result["betas"] == [0.1, 1.0]
    assert result["replicas"] == 2
    assert result["engine"] == "shard_map"
    assert result["epochs"] == 8
    saved = np.load(runner.history_path(unit))
    assert saved["loss"].shape[0] == 2
    assert np.isfinite(saved["loss"]).all()

    # re-submit the SAME unit dir at a wider grid on a different mesh:
    # matched members restore, new ones initialize from the unit seed
    wide = WorkUnit(unit_id="u1", job_id="j1", beta=1.0, seed=3,
                    train={"betas": [0.1, 1.0, 3.0], "chunk_epochs": 2})
    meshless_runner = TrainingUnitRunner(str(tmp_path))
    result2 = meshless_runner(wide)
    assert result2["replicas"] == 3
    wide_hist = np.load(meshless_runner.history_path(wide))
    assert wide_hist["loss"].shape[0] == 3
    # matched members carried their exact trajectories through the reshard
    assert np.array_equal(wide_hist["loss"][0], saved["loss"][0])
    assert np.array_equal(wide_hist["loss"][1], saved["loss"][1])
    # the grown member trained to COMPLETION: the re-submitted unit was
    # already finished, so the lockstep fit alone would have given it
    # zero epochs — the runner's leveling carve-out owes it the full
    # schedule, and the unit must not report an untrained lane as done
    assert np.isfinite(wide_hist["loss"][2]).all()
    assert wide_hist["loss"].shape[1] == 8
    assert all(loss is not None for loss in result2["final_loss"])


# ------------------------------------------------- consolidation serving
def test_consolidated_sweep_checkpoint_serves_from_zoo(model, bundle,
                                                       ckpt_dir):
    """The consolidation-for-serving recipe (docs/parallelism.md): a
    mesh-trained sweep checkpoint registers on a zoo directly — the
    restore IS the reshard onto the serving host — and every member
    serves as a β-labeled replica."""
    from dib_tpu.serve.zoo import ModelZoo

    zoo = ModelZoo(response_capacity=8)
    router = zoo.add_sweep_checkpoint("sweep", ckpt_dir, model, bundle,
                                      CFG, max_wait_ms=0.0)
    try:
        assert len(router.entries) == len(ENDS)
        assert sorted(e.beta_end for e in router.entries) == pytest.approx(
            sorted(ENDS))
        # log-nearest β routing picks the right member
        assert router.route(beta=0.09).beta_end == pytest.approx(0.1)
        x = np.asarray(bundle.x_valid[:1], np.float32)
        out = router.route(beta=1.0).engine.predict(x)
        assert np.isfinite(np.asarray(out["prediction"])).all()
        name, resolved = zoo.resolve("sweep")
        assert name == "sweep" and resolved is router
    finally:
        zoo.close()

    # a serial (mesh-block-free) checkpoint is rejected with a named error
    from dib_tpu.parallel.elastic import consolidate_sweep_checkpoint
    from dib_tpu.train.checkpoint import DIBCheckpointer

    with pytest.raises(ValueError, match="no mesh manifest block"):
        import tempfile

        empty = tempfile.mkdtemp()
        write_manifest(empty, {"w": jnp.zeros((2,))})
        ck = DIBCheckpointer(empty)
        try:
            consolidate_sweep_checkpoint(ck, model, bundle, CFG)
        finally:
            ck.close()


# -------------------------------------------------------- telemetry view
def test_mesh_rollup():
    from dib_tpu.telemetry.summary import mesh_rollup

    events = [
        {"type": "run_start",
         "manifest": {"mesh_shape": {"sweep": 4, "data": 2},
                      "sweep_engine": "shard_map"}},
        {"type": "mitigation", "mtype": "sweep_reshard",
         "saved_width": 4, "restored_width": 2,
         "saved_mesh_axes": {"sweep": 4, "data": 2},
         "mesh_axes": {"sweep": 2, "data": 1}},
        {"type": "mitigation", "mtype": "member_backfill", "replica": 1},
        {"type": "mitigation", "mtype": "watchdog"},  # unrelated
    ]
    rollup = mesh_rollup(events)
    assert rollup["axes"] == {"sweep": 4, "data": 2}
    assert rollup["engine"] == "shard_map"
    assert rollup["reshards"] == 1
    assert rollup["reshard_events"][0]["restored_width"] == 2
    assert rollup["backfills"] == 1
    assert rollup["backfilled_replicas"] == [1]
    # serial runs carry no mesh plane at all
    assert mesh_rollup([{"type": "run_start", "manifest": {}}]) is None
