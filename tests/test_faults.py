"""Fault-injection subsystem (dib_tpu/faults) + the divergence guard.

The fast tier of the drill matrix: plan grammar, once-only fired state,
param poisoning, the in-fit NaN drill (inject → detect → rollback →
bit-identical finish), the faults telemetry rollup and its compare gate,
and the exception-hygiene static check. The subprocess watchdog drills
(stall/kill) live in ``tests/test_fault_drill.py`` behind
``@pytest.mark.slow``.
"""

import json
import os

import jax
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.faults import FAULT_KINDS, FaultPlan, poison_params
from dib_tpu.models import DistributedIBModel
from dib_tpu.telemetry import (
    EventWriter,
    faults_rollup,
    read_events,
    runtime_manifest,
    summarize,
)
from dib_tpu.telemetry.summary import compare
from dib_tpu.train import (
    CheckpointHook,
    DIBCheckpointer,
    DIBTrainer,
    TrainConfig,
)

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ plan grammar
def test_plan_parses_the_readme_example():
    plan = FaultPlan.parse("stall@chunk3:45s,kill@chunk5,nan@chunk7")
    assert [(s.kind, s.chunk, s.arg) for s in plan.specs] == [
        ("stall", 3, 45.0), ("kill", 5, None), ("nan", 7, None)]
    assert [s.raw for s in plan.due(5)] == ["kill@chunk5"]
    assert plan.due(4) == []


def test_plan_rejects_unknown_kind_naming_the_registry():
    with pytest.raises(ValueError, match="Unknown fault kind"):
        FaultPlan.parse("gremlin@chunk1")
    with pytest.raises(ValueError, match="kind@chunkN"):
        FaultPlan.parse("stall at chunk 3")
    # serve/checkpoint kinds are drill-injected, not plan-grammar kinds
    with pytest.raises(ValueError, match="scope"):
        FaultPlan.parse("replica_error@chunk1")


def test_plan_stall_requires_seconds():
    with pytest.raises(ValueError, match="argument"):
        FaultPlan.parse("stall@chunk3")
    assert FaultPlan.parse("stall@chunk3:45").specs[0].arg == 45.0


def test_fired_markers_survive_across_plan_instances(tmp_path):
    """The kill fault's contract: a relaunched worker re-parses the same
    env plan but must find the fired marker and NOT re-fire."""
    plan = FaultPlan.parse("kill@chunk2", state_dir=str(tmp_path))
    (spec,) = plan.due(2)
    plan.mark_fired(spec)
    assert plan.due(2) == []
    relaunched = FaultPlan.parse("kill@chunk2", state_dir=str(tmp_path))
    assert relaunched.due(2) == []


def test_plan_from_env(monkeypatch, tmp_path):
    monkeypatch.delenv("DIB_FAULT_PLAN", raising=False)
    assert FaultPlan.from_env() is None
    monkeypatch.setenv("DIB_FAULT_PLAN", "nan@chunk1")
    monkeypatch.setenv("DIB_FAULT_STATE_DIR", str(tmp_path))
    plan = FaultPlan.from_env(state_dir="/ignored")
    assert plan.state_dir == str(tmp_path)
    assert plan.specs[0].kind == "nan"


def test_registry_covers_the_drill_matrix():
    scopes = {scope for scope, _, _ in FAULT_KINDS.values()}
    assert scopes == {"train", "checkpoint", "serve", "http", "multihost",
                      "sched"}
    for kind in ("stall", "kill", "nan", "ckpt_truncate",
                 "ckpt_bitflip_manifest", "ckpt_bitflip_payload",
                 "replica_error", "replica_slow",
                 "batcher_crash", "http_malformed",
                 "replica_nan", "preempt", "desync", "sdc", "replica_sdc",
                 "sched_worker_kill", "lease_expire", "journal_torn"):
        assert kind in FAULT_KINDS


def test_plan_replica_nan_requires_replica_and_parses():
    with pytest.raises(ValueError, match="argument"):
        FaultPlan.parse("replica_nan@chunk2")
    (spec,) = FaultPlan.parse("replica_nan@chunk2:1").specs
    assert (spec.kind, spec.chunk, spec.arg) == ("replica_nan", 2, 1.0)
    # two same-kind specs at one boundary with different targets fire
    # independently (the marker embeds the arg)
    plan = FaultPlan.parse("replica_nan@chunk2:0,replica_nan@chunk2:1")
    a, b = plan.specs
    assert a.marker != b.marker
    # desync is drill-injected, never plan-grammar injectable
    with pytest.raises(ValueError, match="scope"):
        FaultPlan.parse("desync@chunk1")
    assert FaultPlan.parse("preempt@chunk3").specs[0].kind == "preempt"


# -------------------------------------------------------- fault executors
def _tiny_trainer():
    bundle = get_dataset("boolean_circuit")
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )
    config = TrainConfig(batch_size=64, num_pretraining_epochs=2,
                         num_annealing_epochs=6, steps_per_epoch=2,
                         max_val_points=128)
    return DIBTrainer(model, bundle, config)


def test_poison_params_makes_forward_pass_nonfinite():
    trainer = _tiny_trainer()
    state, _ = trainer.init(jax.random.key(0))
    poisoned = poison_params(state.params, float("nan"))
    x = jax.numpy.asarray(trainer.bundle.x_valid[:4])
    loss, _ = trainer._forward_loss(poisoned, x,
                                    jax.numpy.asarray(trainer.bundle.y_valid[:4]),
                                    0.1, jax.random.key(1))
    assert not np.isfinite(float(loss))
    # structure untouched: only values were poisoned
    assert jax.tree.structure(poisoned) == jax.tree.structure(state.params)


# -------------------------------------------- THE fast NaN drill (tier 1)
def test_nan_injection_rolls_back_bit_identically(tmp_path):
    """Inject NaN at a chunk boundary; the divergence guard must emit a
    mitigation, roll back to the chunk-aligned checkpoint, and finish with
    a history BIT-IDENTICAL to an uninterrupted run — the acceptance
    criterion for the nan drill, in-process and fast."""
    trainer_a = _tiny_trainer()
    state_a, hist_a = trainer_a.fit(jax.random.key(0),
                                    hooks=[lambda *a: None], hook_every=2)

    run_dir = str(tmp_path / "run")
    writer = EventWriter(run_dir)
    writer.run_start(runtime_manifest())
    ckpt = DIBCheckpointer(str(tmp_path / "ck"))
    plan = FaultPlan.parse("nan@chunk2", state_dir=str(tmp_path))
    trainer_b = _tiny_trainer()
    with pytest.warns(UserWarning, match="rolled back"):
        state_b, hist_b = trainer_b.fit(
            jax.random.key(0), hooks=[CheckpointHook(ckpt)], hook_every=2,
            telemetry=writer, fault_plan=plan,
        )
    writer.run_end(status="ok")
    writer.close()
    ckpt.close()

    events = list(read_events(run_dir))
    assert [e["kind"] for e in events if e["type"] == "fault"] == ["nan"]
    mits = [e["mtype"] for e in events if e["type"] == "mitigation"]
    assert mits == ["divergence_rollback"]

    # bit-identical continuation: the trajectory never saw the fault
    np.testing.assert_array_equal(hist_a.loss, hist_b.loss)
    np.testing.assert_array_equal(hist_a.beta, hist_b.beta)
    np.testing.assert_array_equal(hist_a.kl_per_feature, hist_b.kl_per_feature)
    for a, b in zip(jax.tree.leaves(state_a.params),
                    jax.tree.leaves(state_b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # the stream's own verdict agrees: injected == detected == recovered
    summary = summarize(run_dir)
    faults = summary["faults"]
    assert faults["injected"] == faults["detected"] == faults["recovered"] == 1
    assert faults["undetected"] == []
    assert faults["time_to_detect_s"]["mean"] >= 0


def test_divergence_without_checkpoint_warns_and_continues(tmp_path):
    """No checkpoint hook → nothing to roll back to: the guard must emit a
    mitigation + warning and keep going (not crash a science run), once."""
    run_dir = str(tmp_path / "run")
    writer = EventWriter(run_dir)
    writer.run_start(runtime_manifest())
    plan = FaultPlan.parse("nan@chunk1")
    trainer = _tiny_trainer()
    with pytest.warns(UserWarning, match="no checkpoint"):
        _, hist = trainer.fit(jax.random.key(0), hooks=[lambda *a: None],
                              hook_every=2, telemetry=writer,
                              fault_plan=plan)
    writer.run_end(status="ok")
    writer.close()
    assert not np.isfinite(hist.loss[-1])   # honestly diverged
    events = list(read_events(run_dir))
    mits = [e["mtype"] for e in events if e["type"] == "mitigation"]
    assert mits == ["divergence_detected"]   # once, not per boundary


def test_recurring_divergence_raises_instead_of_looping(tmp_path):
    """A rollback whose replay diverges AGAIN at the same epoch is
    deterministic divergence — the guard must raise actionably, not
    restore-replay forever."""
    trainer = _tiny_trainer()

    class PoisonedCheckpointer:
        """Restores a state that diverges immediately on the next chunk."""

        latest_step = 2

        def __init__(self):
            state, history = trainer.init(jax.random.key(3))
            self.payload = (
                state._replace(params=poison_params(state.params,
                                                    float("nan"))),
                history, jax.random.key(4),
            )

        def restore(self, t, chunk_size=None):
            return self.payload

    class Hook:
        checkpointer = PoisonedCheckpointer()

        def __call__(self, *a):
            pass

    plan = FaultPlan.parse("nan@chunk1")
    with pytest.raises(RuntimeError, match="deterministically"):
        with pytest.warns(UserWarning):
            trainer.fit(jax.random.key(0), hooks=[Hook()], hook_every=2,
                        fault_plan=plan)


# ------------------------------------------------------ telemetry rollup
def _stream(tmp_path, events):
    """Write a synthetic event stream; events = [(type, fields), ...]."""
    run_dir = str(tmp_path / "synthetic")
    writer = EventWriter(run_dir)
    for etype, fields in events:
        writer.emit(etype, **fields)
    writer.close()
    return run_dir


def test_faults_rollup_joins_detection_and_recovery(tmp_path):
    run_dir = _stream(tmp_path, [
        ("run_start", {"manifest": {}}),
        ("fault", {"kind": "stall", "spec": "stall@chunk2:45s"}),
        ("mitigation", {"mtype": "stall_kill"}),
        ("run_start", {"manifest": {}}),      # the relaunch
        ("chunk", {"epoch": 6, "steps": 10, "seconds": 1.0, "loss": 0.5}),
        ("fault", {"kind": "nan"}),
        ("chunk", {"epoch": 8, "steps": 10, "seconds": 1.0,
                   "loss": "NaN"}),           # diverged boundary
        ("run_end", {"status": "ok"}),
    ])
    rollup = faults_rollup(list(read_events(run_dir)))
    assert rollup["injected"] == 2
    assert rollup["detected"] == 1            # the nan had no mitigation
    assert rollup["undetected"] == ["nan"]
    stall = rollup["by_kind"]["stall"]
    assert stall == {"injected": 1, "detected": 1, "recovered": 1}
    # a NaN-loss chunk must NOT count as the stall's recovery marker
    (stall_row,) = [f for f in rollup["faults"] if f["kind"] == "stall"]
    assert stall_row["detected_by"] == "stall_kill"
    assert stall_row["recovered"] is True


def test_detection_join_respects_replica_identity(tmp_path):
    """Replica 0's ejection must not mark replica 1's injected fault
    detected (code review finding) — when both events name a replica,
    the join requires them to match."""
    run_dir = _stream(tmp_path, [
        ("run_start", {"manifest": {}}),
        ("fault", {"kind": "replica_error", "replica": 0}),
        ("fault", {"kind": "replica_error", "replica": 1}),
        ("mitigation", {"mtype": "replica_ejected", "replica": 0}),
        ("mitigation", {"mtype": "replica_readmitted", "replica": 0}),
        ("run_end", {"status": "ok"}),
    ])
    rollup = faults_rollup(list(read_events(run_dir)))
    assert rollup["injected"] == 2
    assert rollup["detected"] == 1
    assert rollup["undetected"] == ["replica_error"]


def test_recovery_join_respects_replica_identity(tmp_path):
    """Replica 0's readmission must not mark replica 1's fault recovered
    — a broken re-admission path has to show in the rollup."""
    run_dir = _stream(tmp_path, [
        ("run_start", {"manifest": {}}),
        ("fault", {"kind": "replica_error", "replica": 0}),
        ("fault", {"kind": "replica_error", "replica": 1}),
        ("mitigation", {"mtype": "replica_ejected", "replica": 0}),
        ("mitigation", {"mtype": "replica_ejected", "replica": 1}),
        ("mitigation", {"mtype": "replica_readmitted", "replica": 0}),
        ("run_end", {"status": "ok"}),
    ])
    rollup = faults_rollup(list(read_events(run_dir)))
    assert rollup["detected"] == 2
    assert rollup["recovered"] == 1


def test_rollback_refuses_checkpoint_predating_the_fit():
    """A checkpoint directory holding an OLDER run's steps must not be
    'rolled back' into mid-fit (code review finding): done would go
    negative and training would silently continue a different
    trajectory."""
    trainer = _tiny_trainer()
    state, _ = trainer.fit(jax.random.key(0), num_epochs=4,
                           hooks=[lambda *a: None], hook_every=2)
    history = trainer.latest_history
    resume_key = trainer.resume_key

    class StaleCheckpointer:
        """Pretends to hold a checkpoint from before this fit started."""

        latest_step = 2

        def __init__(self):
            s, h = trainer.init(jax.random.key(9))
            self.payload = (s, h, jax.random.key(1))   # epoch 0 state

        def restore(self, t, chunk_size=None):
            return self.payload

    class Hook:
        checkpointer = StaleCheckpointer()

        def __call__(self, *a):
            pass

    plan = FaultPlan.parse("nan@chunk1")
    with pytest.raises(RuntimeError, match="predates"):
        trainer.fit(resume_key, num_epochs=4, state=state, history=history,
                    hooks=[Hook()], hook_every=2, fault_plan=plan)


def test_unregistered_fault_kind_scores_undetected(tmp_path):
    """A fault kind with no detector mapping must NOT be waved through by
    an unrelated later mitigation (code review finding) — the compare
    gate exists precisely for faults nothing detected."""
    run_dir = _stream(tmp_path, [
        ("run_start", {"manifest": {}}),
        ("fault", {"kind": "mystery_future_kind"}),
        ("mitigation", {"mtype": "replica_ejected", "replica": 0}),
        ("run_end", {"status": "ok"}),
    ])
    rollup = faults_rollup(list(read_events(run_dir)))
    assert rollup["detected"] == 0
    assert rollup["undetected"] == ["mystery_future_kind"]


def test_every_registered_injectable_kind_has_a_detector():
    """FAULT_KINDS and the summary's detector map must not drift: every
    kind whose injection emits fault events needs a detection mapping
    (http_malformed is containment-only by design — its drills record
    status codes, not fault events)."""
    from dib_tpu.telemetry.summary import _FAULT_DETECTORS

    emitting = set(FAULT_KINDS) - {"http_malformed"}
    missing = emitting - set(_FAULT_DETECTORS)
    assert not missing, (
        f"fault kinds without a detector mapping: {sorted(missing)} — "
        "their drills would always gate as undetected regressions"
    )


def test_faults_rollup_none_for_uninjected_runs(tmp_path):
    run_dir = _stream(tmp_path, [
        ("run_start", {"manifest": {}}),
        ("chunk", {"epoch": 2, "steps": 4, "seconds": 1.0, "loss": 0.5}),
        ("run_end", {"status": "ok"}),
    ])
    assert faults_rollup(list(read_events(run_dir))) is None
    assert "faults" not in summarize(run_dir)


def test_compare_gates_on_undetected_injected_fault(tmp_path):
    """ISSUE 4 satellite: an injected fault nobody detected is a
    regression (nonzero verdict), regardless of the baseline."""
    base = {"metric": "run_telemetry_summary", "steps_per_s": 10.0}
    good = {"metric": "run_telemetry_summary", "steps_per_s": 10.0,
            "faults": {"injected": 2, "detected": 2, "recovered": 2}}
    bad = {"metric": "run_telemetry_summary", "steps_per_s": 10.0,
           "faults": {"injected": 2, "detected": 1, "recovered": 1}}
    report, regressed = compare(base, good)
    assert not report["fields"]["faults_undetected"]["regressed"]
    report, regressed = compare(base, bad)
    assert report["fields"]["faults_undetected"]["regressed"]
    assert regressed


def test_compare_cli_exits_nonzero_on_undetected_fault(tmp_path):
    from dib_tpu.telemetry import telemetry_main

    base_dir = _stream(tmp_path, [
        ("run_start", {"manifest": {}}),
        ("chunk", {"epoch": 2, "steps": 4, "seconds": 1.0, "loss": 0.5}),
        ("run_end", {"status": "ok"}),
    ])
    cand_dir = str(tmp_path / "cand")
    writer = EventWriter(cand_dir)
    writer.emit("run_start", manifest={})
    writer.emit("chunk", epoch=2, steps=4, seconds=1.0, loss=0.5)
    writer.fault(kind="kill", spec="kill@chunk1")
    writer.emit("run_end", status="ok")
    writer.close()
    rc = telemetry_main(["compare", base_dir, cand_dir])
    assert rc == 1


# ----------------------------------------------------- exception hygiene
def test_exception_hygiene_gate():
    """The static check passes on the package and its scanner actually
    catches a violation (and honors the fault-ok pragma)."""
    import sys

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from check_exception_hygiene import scan_file, scan_package

    assert scan_package() == []


def test_exception_hygiene_scanner_flags_and_pragmas(tmp_path):
    import sys

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from check_exception_hygiene import scan_file

    bad = tmp_path / "bad.py"
    bad.write_text(
        "try:\n    x = 1\nexcept Exception:\n    pass\n"
        "try:\n    y = 2\nexcept (ValueError, BaseException):\n    ...\n"
        "try:\n    z = 3\nexcept ValueError:\n    pass\n"   # narrow: fine
        "try:\n    w = 4\nexcept Exception:  # fault-ok: test pragma\n"
        "    pass\n"
    )
    violations = scan_file(str(bad), "bad.py")
    assert len(violations) == 2
    assert violations[0].startswith("bad.py:3")
    # handlers that DO something are fine even when broad
    good = tmp_path / "good.py"
    good.write_text(
        "try:\n    x = 1\nexcept Exception as exc:\n    raise\n"
        "try:\n    y = 2\nexcept:\n    y = None\n"
    )
    assert scan_file(str(good), "good.py") == []
