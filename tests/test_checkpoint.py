"""Checkpoint/resume: bit-identical continuation and sweep recovery."""

import os

import jax
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.models import DistributedIBModel
from dib_tpu.parallel import BetaSweepTrainer
from dib_tpu.train import (
    CheckpointHook,
    DIBCheckpointer,
    DIBTrainer,
    TrainConfig,
)


def make_trainer():
    bundle = get_dataset("boolean_circuit")
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )
    config = TrainConfig(
        batch_size=64, num_pretraining_epochs=4, num_annealing_epochs=6,
        steps_per_epoch=2, max_val_points=128,
    )
    return DIBTrainer(model, bundle, config)


def tree_equal(a, b) -> bool:
    leaves_a, leaves_b = jax.tree.leaves(a), jax.tree.leaves(b)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(leaves_a, leaves_b))


@pytest.mark.slow
def test_resume_is_bit_identical(tmp_path):
    key = jax.random.key(7)

    # Uninterrupted run: 10 epochs in chunks of 5 (a no-op hook fixes chunking).
    trainer_a = make_trainer()
    noop = lambda trainer, state, epoch: None
    state_a, hist_a = trainer_a.fit(key, hooks=[noop], hook_every=5)

    # Interrupted run: checkpoint at epoch 5, then restore and continue.
    ckpt = DIBCheckpointer(str(tmp_path / "ckpt"))
    trainer_b = make_trainer()
    saves = []

    def save_once(trainer, state, epoch):
        if epoch == 5:
            CheckpointHook(ckpt)(trainer, state, epoch)
            saves.append(epoch)

    trainer_b.fit(key, hooks=[save_once], hook_every=5)
    assert saves == [5]
    assert ckpt.latest_step == 5

    trainer_c = make_trainer()
    state_5, hist_5, key_5 = ckpt.restore(trainer_c)
    assert int(state_5.epoch) == 5
    state_c, hist_c = trainer_c.fit(
        key_5, num_epochs=5, state=state_5, history=hist_5,
        hooks=[noop], hook_every=5,
    )

    # The resumed run reproduces the uninterrupted run exactly.
    assert tree_equal(state_a.params, state_c.params)
    np.testing.assert_array_equal(hist_a.beta, hist_c.beta)
    np.testing.assert_array_equal(hist_a.loss, hist_c.loss)
    np.testing.assert_array_equal(hist_a.kl_per_feature, hist_c.kl_per_feature)
    ckpt.close()


@pytest.mark.slow
def test_sweep_checkpoint_roundtrip(tmp_path):
    bundle = get_dataset("boolean_circuit")
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )
    config = TrainConfig(
        batch_size=64, num_pretraining_epochs=2, num_annealing_epochs=4,
        steps_per_epoch=2, max_val_points=128,
    )
    sweep = BetaSweepTrainer(model, bundle, config, 1e-4, [0.1, 1.0])
    keys = jax.random.split(jax.random.key(0), 2)

    ckpt = DIBCheckpointer(str(tmp_path / "sweep_ckpt"))
    hook = CheckpointHook(ckpt)
    states, records = sweep.fit(keys, hooks=[hook], hook_every=3)
    assert ckpt.latest_step == 6

    sweep2 = BetaSweepTrainer(model, bundle, config, 1e-4, [0.1, 1.0])
    states_r, hists_r, keys_r = ckpt.restore(sweep2)
    assert keys_r.shape[0] == 2
    assert tree_equal(states.params, states_r.params)
    np.testing.assert_array_equal(
        np.asarray(hists_r["cursor"]), np.array([6, 6], dtype=np.int32)
    )
    ckpt.close()


@pytest.mark.slow
def test_lost_sweep_member_recovery(tmp_path):
    """Elastic recovery (SURVEY.md section 5): a lost sweep member re-run from
    the stacked checkpoint as a 1-replica sweep reproduces the full sweep's
    result for that member — same key chain and schedule; agreement to float
    tolerance (XLA reduction order differs across sweep widths, and ulp-level
    differences amplify through training)."""
    bundle = get_dataset("boolean_circuit")
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )
    config = TrainConfig(
        batch_size=64, num_pretraining_epochs=2, num_annealing_epochs=4,
        steps_per_epoch=2, max_val_points=128,
    )
    keys = jax.random.split(jax.random.key(3), 2)

    # Full run with a checkpoint halfway.
    sweep = BetaSweepTrainer(model, bundle, config, 1e-4, [0.1, 1.0])
    ckpt = DIBCheckpointer(str(tmp_path / "ck"))

    def save_at_3(trainer, states, epoch):
        if epoch == 3:
            CheckpointHook(ckpt)(trainer, states, epoch)

    states_full, records_full = sweep.fit(keys, hooks=[save_at_3], hook_every=3)

    # "Member 1 was lost": restore the stacked checkpoint, carve it out,
    # continue the remaining 3 epochs independently.
    sweep2 = BetaSweepTrainer(model, bundle, config, 1e-4, [0.1, 1.0])
    states_3, hists_3, keys_3 = ckpt.restore(sweep2)
    sub, state_r, hist_r, key_r = sweep2.recover_replica(states_3, hists_3, keys_3, 1)
    states_rec, records_rec = sub.fit(
        key_r, num_epochs=3, states=state_r, histories=hist_r, hook_every=3,
        hooks=[lambda *a: None],
    )

    # beta schedule: deterministic scalar math, exact at any width
    np.testing.assert_array_equal(records_full[1].beta, records_rec[0].beta)
    # loss trajectory and params: float-tolerance agreement (ulp differences
    # from the width change, amplified over the 3 continued epochs)
    np.testing.assert_allclose(
        records_full[1].loss, records_rec[0].loss, rtol=0.05, atol=5e-3
    )
    want = jax.tree.map(lambda a: np.asarray(a)[1], states_full.params)
    got = jax.tree.map(lambda a: np.asarray(a)[0], states_rec.params)
    for w, g in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
        np.testing.assert_allclose(w, g, atol=5e-3)
    ckpt.close()


@pytest.mark.fault
def test_recover_replica_continuation_enforces_chunk_contract(tmp_path):
    """The recover_replica docstring promises the chunk-size contract is
    ENFORCED: a carved-out member continued at a different chunk size
    would draw a different epoch-key chain (a valid-looking but
    incomparable trajectory), so restore(chunk_size=...) must refuse the
    mismatch — and the same-chunk-size continuation must match the
    uninterrupted sweep member to the documented float tolerance."""
    bundle = get_dataset("boolean_circuit")
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )
    config = TrainConfig(
        batch_size=64, num_pretraining_epochs=2, num_annealing_epochs=4,
        steps_per_epoch=2, max_val_points=128,
    )
    keys = jax.random.split(jax.random.key(3), 2)

    sweep = BetaSweepTrainer(model, bundle, config, 1e-4, [0.1, 1.0])
    ckpt = DIBCheckpointer(str(tmp_path / "ck"))

    def save_at_3(trainer, states, epoch):
        if epoch == 3:
            CheckpointHook(ckpt)(trainer, states, epoch)

    states_full, records_full = sweep.fit(keys, hooks=[save_at_3],
                                          hook_every=3)

    sweep2 = BetaSweepTrainer(model, bundle, config, 1e-4, [0.1, 1.0])
    # a mismatched continuation chunk size actually raises, as documented
    with pytest.raises(ValueError, match="chunk size"):
        ckpt.restore(sweep2, chunk_size=2)

    # same chunk size: the carved-out member's continuation matches the
    # uninterrupted sweep member to the documented tolerance (bitwise
    # identity holds only at the original width — which is why the
    # automated quarantine replays full-width; see sweep.py)
    states_3, hists_3, keys_3 = ckpt.restore(sweep2, chunk_size=3)
    sub, state_r, hist_r, key_r = sweep2.recover_replica(
        states_3, hists_3, keys_3, 1)
    states_rec, records_rec = sub.fit(
        key_r, num_epochs=3, states=state_r, histories=hist_r,
        hook_every=3,
    )
    np.testing.assert_array_equal(records_full[1].beta, records_rec[0].beta)
    np.testing.assert_allclose(records_full[1].loss, records_rec[0].loss,
                               rtol=0.05, atol=5e-3)
    want = jax.tree.map(lambda a: np.asarray(a)[1], states_full.params)
    got = jax.tree.map(lambda a: np.asarray(a)[0], states_rec.params)
    for w, g in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
        np.testing.assert_allclose(w, g, atol=5e-3)
    ckpt.close()


def test_restore_without_checkpoint_raises(tmp_path):
    ckpt = DIBCheckpointer(str(tmp_path / "empty"))
    with pytest.raises(FileNotFoundError):
        ckpt.restore(make_trainer())
    ckpt.close()


@pytest.mark.slow
def test_restore_rejects_mismatched_chunk_size(tmp_path):
    """The epoch-key chain is keyed to chunk boundaries: continuing a
    checkpoint at a different hook_every would silently sample a different
    (valid-looking) trajectory, so restore() must refuse."""
    key = jax.random.key(11)
    trainer = make_trainer()
    ckpt = DIBCheckpointer(str(tmp_path / "ck"))
    trainer.fit(key, num_epochs=4, hooks=[CheckpointHook(ckpt)], hook_every=2)

    trainer2 = make_trainer()
    with pytest.raises(ValueError, match="chunk size"):
        ckpt.restore(trainer2, chunk_size=3)
    # matching chunk size restores fine and records what was saved
    state, hist, k = ckpt.restore(trainer2, chunk_size=2)
    assert ckpt.restored_chunk_size == 2
    assert int(state.epoch) == 4
    ckpt.close()


@pytest.mark.slow
def test_history_extend_past_capacity():
    """history_extend grows the record buffers so a resumed run can train
    past the preallocated horizon; recorded rows and cursor are untouched."""
    from dib_tpu.train import history_extend

    trainer = make_trainer()           # capacity = 10 epochs
    key = jax.random.key(5)
    noop = lambda *a: None
    state, _ = trainer.fit(key, num_epochs=10, hooks=[noop], hook_every=5)
    history = trainer.latest_history
    resume_key = trainer.resume_key

    with pytest.raises(ValueError, match="history_extend"):
        trainer.fit(resume_key, num_epochs=2, state=state, history=history)

    bigger = history_extend(history, 4)
    assert bigger["beta"].shape[0] == 14
    before = np.asarray(history["beta"]).copy()
    state2, record = trainer.fit(
        resume_key, num_epochs=4, state=state, history=bigger
    )
    assert int(state2.epoch) == 14
    assert record.beta.shape[0] == 14
    np.testing.assert_array_equal(record.beta[:10], before)


@pytest.mark.slow
def test_restore_old_format_checkpoint_without_chunk_size(tmp_path):
    """Checkpoints written before chunk-size tracking (no 'chunk_size' key)
    must still restore — the resume path exists precisely for runs started
    earlier."""
    import orbax.checkpoint as ocp

    from dib_tpu.train.checkpoint import _pack_key

    trainer = make_trainer()
    key = jax.random.key(2)
    state, _ = trainer.fit(key, num_epochs=2)
    history = trainer.latest_history

    mgr = ocp.CheckpointManager(
        str(tmp_path / "old"), options=ocp.CheckpointManagerOptions(create=True)
    )
    mgr.save(2, args=ocp.args.StandardSave(
        {"state": state, "history": history, "key": _pack_key(trainer.resume_key)}
    ))
    mgr.wait_until_finished()
    mgr.close()

    ckpt = DIBCheckpointer(str(tmp_path / "old"))
    state_r, hist_r, key_r = ckpt.restore(make_trainer(), chunk_size=7)
    assert int(state_r.epoch) == 2
    assert ckpt.restored_chunk_size is None   # nothing recorded, nothing enforced
    ckpt.close()


@pytest.mark.slow
def test_restore_extended_history_checkpoint(tmp_path):
    """A checkpoint saved AFTER history_extend has larger record buffers than
    trainer.init allocates; restore must follow the stored shapes."""
    from dib_tpu.train import history_extend

    trainer = make_trainer()           # capacity = 10
    key = jax.random.key(9)
    noop = lambda *a: None
    state, _ = trainer.fit(key, num_epochs=10, hooks=[noop], hook_every=5)
    bigger = history_extend(trainer.latest_history, 6)

    ckpt = DIBCheckpointer(str(tmp_path / "ext"))
    hook = CheckpointHook(ckpt)
    state2, _ = trainer.fit(
        trainer.resume_key, num_epochs=6, state=state, history=bigger,
        hooks=[hook], hook_every=5,
    )
    # epoch 16 sits OFF the 5-chunk grid (the final chunk was partial), so
    # a chunk_size-enforced restore refuses it...
    with pytest.raises(ValueError, match="chunk grid"):
        ckpt.restore(make_trainer(), chunk_size=5)
    # ...while the extension path (no continuation contract) restores fine
    state_r, hist_r, key_r = ckpt.restore(make_trainer())
    assert hist_r["beta"].shape[0] == 16
    assert int(np.asarray(hist_r["cursor"])) == 16
    assert int(state_r.epoch) == 16
    # and an aligned earlier step restores under the contract
    state_15, _, _ = ckpt.restore(make_trainer(), step=15, chunk_size=5)
    assert int(state_15.epoch) == 15
    ckpt.close()


def test_history_extend_stacked_sweep_axis():
    """Stacked [R, T, ...] sweep histories extend along the record axis."""
    import jax.numpy as jnp

    from dib_tpu.train.history import history_extend, history_init

    stacked = jax.vmap(lambda _: history_init(3, 2))(jnp.arange(2))
    grown = history_extend(stacked, 5)
    assert grown["beta"].shape == (2, 8)
    assert grown["kl_per_feature"].shape == (2, 8, 2)
    assert grown["cursor"].shape == (2,)


# ------------------------------------------------------- integrity manifest
def test_manifest_written_and_verified(tmp_path, monkeypatch):
    """ISSUE 3 satellite: every save records a schema version + param-tree
    structure hash; restore verifies the template against it. ISSUE 14:
    digest-bearing manifests are v3; with digests disabled
    (DIB_CKPT_CONTENT_DIGESTS=0) a serial save stays on the v1 schema —
    the schema names the manifest CONTENT, so v1-only readers keep
    restoring it through a rolling fleet upgrade."""
    from dib_tpu.train.checkpoint import (
        CHECKPOINT_SCHEMA_VERSION,
        MESH_FREE_CHECKPOINT_SCHEMA,
        param_structure_hash,
        read_manifest,
        verify_manifest,
    )

    trainer = make_trainer()
    key = jax.random.key(1)
    state, history = trainer.init(key)
    ckpt = DIBCheckpointer(str(tmp_path / "ck"))
    ckpt.save(0, state, history, key)
    ckpt.manager.wait_until_finished()

    manifest = read_manifest(ckpt.directory)
    # content digests on (the default): the manifest is v3 and carries a
    # per-leaf digest row for the saved step
    assert manifest["checkpoint_schema"] == CHECKPOINT_SCHEMA_VERSION
    assert "0" in manifest["content"]
    assert all(len(d) == 64
               for d in manifest["content"]["0"]["leaves"].values())
    assert manifest["param_structure_hash"] == param_structure_hash(state.params)
    assert any("encoders" in row for row in manifest["param_structure_rows"])

    # the matching template verifies silently
    verify_manifest(ckpt.directory, state.params)

    # digests disabled: the rolling-upgrade escape keeps serial saves v1
    monkeypatch.setenv("DIB_CKPT_CONTENT_DIGESTS", "0")
    ckpt.save(3, state, history, key)
    ckpt.manager.wait_until_finished()
    manifest = read_manifest(ckpt.directory)
    assert manifest["checkpoint_schema"] == MESH_FREE_CHECKPOINT_SCHEMA
    assert "content" not in manifest
    ckpt.close()


def test_manifest_mismatch_is_actionable(tmp_path):
    """A wrong-architecture template fails restore with the differing
    leaves NAMED — not a deep Orbax pytree error."""
    trainer = make_trainer()
    key = jax.random.key(2)
    state, history = trainer.init(key)
    ckpt = DIBCheckpointer(str(tmp_path / "ck"))
    ckpt.save(0, state, history, key)
    ckpt.manager.wait_until_finished()
    ckpt.close()

    bundle = get_dataset("boolean_circuit")
    wrong_model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(12,), integration_hidden=(16,),   # wrong width
        output_dim=1, embedding_dim=2,
    )
    wrong_trainer = DIBTrainer(wrong_model, bundle, trainer.config)
    ckpt2 = DIBCheckpointer(str(tmp_path / "ck"))
    with pytest.raises(ValueError) as excinfo:
        ckpt2.restore(wrong_trainer)
    msg = str(excinfo.value)
    assert "param structure" in msg
    assert "architecture flags" in msg
    assert "encoders" in msg          # the differing leaf is named
    ckpt2.close()


def test_manifest_schema_version_gate(tmp_path):
    from dib_tpu.train.checkpoint import verify_manifest, write_manifest

    trainer = make_trainer()
    state, _ = trainer.init(jax.random.key(0))
    directory = str(tmp_path)
    manifest = write_manifest(directory, state.params)
    # tamper the schema version: verification must refuse with the eras named
    import json as _json
    path = os.path.join(directory, "dib_manifest.json")
    manifest["checkpoint_schema"] = 99
    with open(path, "w") as f:
        _json.dump(manifest, f)
    with pytest.raises(ValueError, match="schema"):
        verify_manifest(directory, state.params)


def test_manifest_absent_verifies_vacuously(tmp_path):
    """Pre-manifest checkpoints (older runs) must keep restoring."""
    from dib_tpu.train.checkpoint import verify_manifest

    trainer = make_trainer()
    state, _ = trainer.init(jax.random.key(0))
    verify_manifest(str(tmp_path / "nothing_here"), state.params)


def _fit_two_checkpoints(tmp_path, name="ck"):
    """A trainer run leaving steps [3, 6] behind."""
    trainer = make_trainer()
    ckpt = DIBCheckpointer(str(tmp_path / name))
    trainer.fit(jax.random.key(0), num_epochs=6,
                hooks=[CheckpointHook(ckpt)], hook_every=3)
    ckpt.manager.wait_until_finished()
    return ckpt


def _truncate_largest(step_dir: str) -> None:
    largest = max(
        (os.path.join(root, name) for root, _, files in os.walk(step_dir)
         for name in files),
        key=os.path.getsize,
    )
    with open(largest, "rb+") as f:
        f.truncate(os.path.getsize(largest) // 2)


@pytest.mark.fault
def test_truncated_step_raises_actionable_corruption_error(tmp_path):
    """ISSUE 4 satellite: a truncated Orbax step dir must surface as ONE
    actionable CheckpointCorruptionError naming the step — not a deep
    pytree/msgpack traceback."""
    from dib_tpu.faults import corrupt_checkpoint
    from dib_tpu.train import CheckpointCorruptionError

    ckpt = _fit_two_checkpoints(tmp_path)
    corrupt_checkpoint(ckpt.directory, "ckpt_truncate")
    with pytest.raises(CheckpointCorruptionError) as excinfo:
        ckpt.restore(make_trainer(), step=6)
    msg = str(excinfo.value)
    assert "step 6" in msg and "restore_latest_intact" in msg
    ckpt.close()


@pytest.mark.fault
def test_restore_latest_intact_falls_back_past_corruption(tmp_path):
    """The watchdog-relaunch contract: a step truncated by the very kill
    being recovered from must not crash-loop — fall back to the previous
    intact step and report the skip."""
    from dib_tpu.faults import corrupt_checkpoint

    ckpt = _fit_two_checkpoints(tmp_path)
    corrupt_checkpoint(ckpt.directory, "ckpt_truncate")
    skipped = []
    state, history, key = ckpt.restore_latest_intact(
        make_trainer(), chunk_size=3, on_fallback=skipped.append)
    assert int(state.epoch) == 3
    assert [s["step"] for s in skipped] == [6]
    assert ckpt.fallback_skipped_steps == [6]
    # the restored state actually continues: finite params, right cursor
    assert int(np.asarray(history["cursor"])) == 3
    # the corrupt step was QUARANTINED, not left as latest: orbax refuses
    # to re-save step <= latest_step, so keeping it would silently block
    # the re-trained gap from checkpointing and leave a poisoned rollback
    # target — and ISSUE 14 moves (never deletes) so the operator keeps
    # the evidence under quarantine/
    qpath = skipped[0]["quarantined"]
    assert qpath and os.path.isdir(qpath)
    assert os.path.basename(os.path.dirname(qpath)) == "quarantine"
    assert os.path.exists(os.path.join(qpath, "QUARANTINE.json"))
    assert 6 not in ckpt.manager.all_steps()
    trainer = make_trainer()
    state, hist2 = trainer.fit(key, num_epochs=3, state=state,
                               history=history,
                               hooks=[CheckpointHook(ckpt)], hook_every=3)
    assert ckpt.latest_step == 6              # the gap re-saved cleanly
    state6, _, _ = ckpt.restore(make_trainer(), step=6, chunk_size=3)
    assert int(state6.epoch) == 6
    ckpt.close()


@pytest.mark.fault
def test_restore_latest_intact_raises_when_everything_is_corrupt(tmp_path):
    from dib_tpu.train import CheckpointCorruptionError

    ckpt = _fit_two_checkpoints(tmp_path)
    for step in ("3", "6"):
        _truncate_largest(os.path.join(ckpt.directory, step))
    with pytest.raises(CheckpointCorruptionError, match="corrupt"):
        ckpt.restore_latest_intact(make_trainer(), chunk_size=3)
    assert ckpt.fallback_skipped_steps == [6, 3]
    ckpt.close()


@pytest.mark.fault
def test_corrupt_manifest_does_not_delete_intact_steps(tmp_path):
    """The manifest is DIRECTORY-level: one torn JSON file must not make
    the fallback walk delete every intact step (code review finding) —
    restore_latest_intact raises the manifest error up front, steps
    untouched."""
    from dib_tpu.faults import corrupt_checkpoint
    from dib_tpu.train import CheckpointCorruptionError

    ckpt = _fit_two_checkpoints(tmp_path)
    corrupt_checkpoint(ckpt.directory, "ckpt_bitflip_manifest")
    with pytest.raises(CheckpointCorruptionError, match="manifest"):
        ckpt.restore_latest_intact(make_trainer(), chunk_size=3)
    assert sorted(ckpt.manager.all_steps()) == [3, 6]   # nothing deleted
    # the operator action the error names actually works: delete the
    # manifest, restore verifies vacuously, the data is intact
    os.remove(os.path.join(ckpt.directory, "dib_manifest.json"))
    state, _, _ = ckpt.restore_latest_intact(make_trainer(), chunk_size=3)
    assert int(state.epoch) == 6
    ckpt.close()


@pytest.mark.fault
def test_bitflipped_manifest_raises_actionable_error(tmp_path):
    """A manifest that EXISTS but is unreadable is corruption evidence —
    it must not silently verify vacuously like an absent one."""
    from dib_tpu.faults import corrupt_checkpoint
    from dib_tpu.train import CheckpointCorruptionError

    ckpt = _fit_two_checkpoints(tmp_path)
    corrupt_checkpoint(ckpt.directory, "ckpt_bitflip_manifest")
    with pytest.raises(CheckpointCorruptionError) as excinfo:
        ckpt.restore(make_trainer())
    msg = str(excinfo.value)
    assert "dib_manifest.json" in msg
    assert "delete the manifest" in msg      # the operator action is named
    ckpt.close()


@pytest.mark.fault
def test_donating_restored_buffers_cannot_poison_a_later_restore(tmp_path):
    """The fault drills caught orbax handing back zero-copy host views
    whose donation to run_chunk corrupted the heap and later checkpoints;
    restore now copies every leaf onto XLA-owned buffers. Donating (and
    overwriting) a restored tree must leave a subsequent restore of the
    same step byte-identical."""
    trainer = make_trainer()
    ckpt = DIBCheckpointer(str(tmp_path / "ck"))
    trainer.fit(jax.random.key(4), num_epochs=5,
                hooks=[CheckpointHook(ckpt)], hook_every=5)
    state, _, _ = ckpt.restore(make_trainer(), chunk_size=5)
    baseline = [np.asarray(leaf).copy()
                for leaf in jax.tree.leaves(state.params)]
    consume = jax.jit(
        lambda t: jax.tree.map(lambda a: a * 2.0 + 1.0, t), donate_argnums=0)
    jax.block_until_ready(consume(state.params))   # overwrite the buffers
    state2, _, _ = ckpt.restore(make_trainer(), chunk_size=5)
    for want, got in zip(baseline, jax.tree.leaves(state2.params)):
        np.testing.assert_array_equal(want, np.asarray(got))
    ckpt.close()


def test_param_structure_hash_properties():
    from dib_tpu.train.checkpoint import (
        param_structure_hash,
        param_structure_rows,
    )

    trainer = make_trainer()
    state, _ = trainer.init(jax.random.key(0))
    state2, _ = make_trainer().init(jax.random.key(9))
    # hash depends on STRUCTURE only, not values/seed
    assert param_structure_hash(state.params) == param_structure_hash(state2.params)
    rows = param_structure_rows(state.params)
    assert rows == sorted(rows)
    assert all(" [" in r for r in rows)
