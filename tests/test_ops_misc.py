"""Tests for positional encoding, schedules, similarities, and entropy helpers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dib_tpu.ops import (
    positional_encoding,
    positional_encoding_frequencies,
    posenc_output_dim,
    log_annealed_beta,
    beta_grid,
    linear_warmup,
    pairwise_sqeuclidean,
    pairwise_l1,
    pairwise_linf,
    scaled_similarity,
    symmetric_infonce,
    entropy_bits,
    sequence_entropy_bits,
    mutual_information_bits,
    entropy_rate_scaling_ansatz,
    LN2,
)


# ---------------------------------------------------------------- posenc
def test_posenc_frequencies_reference_convention():
    # reference models.py:70 -> 2**np.arange(1, 5) == [2, 4, 8, 16]
    freqs = positional_encoding_frequencies(4, start_power=1)
    np.testing.assert_array_equal(freqs, [2.0, 4.0, 8.0, 16.0])
    # chaos notebook cell 3 -> 2**np.arange(10) starts at 1
    freqs = positional_encoding_frequencies(3, start_power=0)
    np.testing.assert_array_equal(freqs, [1.0, 2.0, 4.0])


def test_posenc_shape_and_values(rng):
    x = rng.normal(size=(7, 3)).astype(np.float32)
    freqs = [2.0, 4.0]
    out = np.asarray(positional_encoding(jnp.array(x), freqs))
    assert out.shape == (7, posenc_output_dim(3, 2))
    np.testing.assert_allclose(out[:, :3], x, rtol=1e-6)
    # frequency-major grouping: [x, sin(2x), sin(4x)]
    np.testing.assert_allclose(out[:, 3:6], np.sin(2.0 * x), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(out[:, 6:9], np.sin(4.0 * x), rtol=1e-5, atol=1e-6)


def test_posenc_zero_padding_stays_zero():
    x = jnp.zeros((4, 2))
    out = np.asarray(positional_encoding(x, [2.0, 4.0, 8.0]))
    np.testing.assert_array_equal(out, 0.0)


def test_posenc_no_frequencies_identity(rng):
    x = rng.normal(size=(4, 2)).astype(np.float32)
    out = np.asarray(positional_encoding(jnp.array(x), []))
    np.testing.assert_array_equal(out, x)


# ---------------------------------------------------------------- schedules
def test_beta_schedule_endpoints_and_pretraining():
    b0, b1, pre, ann = 1e-4, 3.0, 10, 100
    assert np.isclose(float(log_annealed_beta(0, b0, b1, ann, pre)), b0)
    assert np.isclose(float(log_annealed_beta(pre, b0, b1, ann, pre)), b0)
    assert np.isclose(float(log_annealed_beta(pre + ann, b0, b1, ann, pre)), b1, rtol=1e-5)
    # log-linear midpoint
    mid = float(log_annealed_beta(pre + ann // 2, b0, b1, ann, pre))
    assert np.isclose(np.log(mid), 0.5 * (np.log(b0) + np.log(b1)), rtol=1e-5)


def test_beta_schedule_matches_reference_formula():
    # reference models.py:147-149: exp(log b0 + max(e-pre,0)/N * (log b1 - log b0))
    b0, b1, pre, ann = 2e-6, 2e-1, 3, 50
    for epoch in [0, 2, 3, 10, 37, 53]:
        want = np.exp(
            np.log(b0) + max(epoch - pre, 0) / ann * (np.log(b1) - np.log(b0))
        )
        got = float(log_annealed_beta(epoch, b0, b1, ann, pre))
        np.testing.assert_allclose(got, want, rtol=1e-5)


def test_beta_schedule_downward():
    # chaos notebook cell 10: beta ramps DOWN 10 -> 1e-4
    assert float(log_annealed_beta(0, 10.0, 1e-4, 100)) == pytest.approx(10.0)
    assert float(log_annealed_beta(100, 10.0, 1e-4, 100)) == pytest.approx(1e-4, rel=1e-4)
    assert float(log_annealed_beta(200, 10.0, 1e-4, 100)) == pytest.approx(1e-4, rel=1e-4)


def test_beta_grid_log_spacing():
    grid = np.asarray(beta_grid(1e-4, 1.0, 5))
    np.testing.assert_allclose(np.diff(np.log(grid)), np.log(10.0), rtol=1e-5)


def test_beta_schedule_vmaps_over_phase_grid():
    steps = jnp.arange(5) * 25
    betas = jax.vmap(lambda s: log_annealed_beta(s, 1e-3, 1.0, 100))(steps)
    assert betas.shape == (5,)
    assert float(betas[0]) < float(betas[-1])


def test_linear_warmup():
    assert float(linear_warmup(0, 1e-4, 100)) == 0.0
    assert float(linear_warmup(50, 1e-4, 100)) == pytest.approx(5e-5)
    assert float(linear_warmup(1000, 1e-4, 100)) == pytest.approx(1e-4)


# ---------------------------------------------------------------- similarity
def test_pairwise_distances_match_numpy(rng):
    a = rng.normal(size=(6, 4)).astype(np.float32)
    b = rng.normal(size=(9, 4)).astype(np.float32)
    diff = a[:, None, :] - b[None, :, :]
    np.testing.assert_allclose(
        np.asarray(pairwise_sqeuclidean(jnp.array(a), jnp.array(b))),
        np.sum(diff**2, -1), rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(pairwise_l1(jnp.array(a), jnp.array(b))),
        np.sum(np.abs(diff), -1), rtol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(pairwise_linf(jnp.array(a), jnp.array(b))),
        np.max(np.abs(diff), -1), rtol=1e-5,
    )


@pytest.mark.parametrize("sim_type", ["l2sq", "l2", "l1", "linf", "cosine"])
def test_scaled_similarity_types(rng, sim_type):
    a = rng.normal(size=(5, 3)).astype(np.float32)
    b = rng.normal(size=(5, 3)).astype(np.float32)
    sim = np.asarray(scaled_similarity(jnp.array(a), jnp.array(b), sim_type, temperature=2.0))
    assert sim.shape == (5, 5)
    if sim_type != "cosine":
        assert np.all(sim <= 1e-5)  # negated distances


def test_scaled_similarity_unknown_type_raises(rng):
    with pytest.raises(ValueError):
        scaled_similarity(jnp.ones((2, 2)), jnp.ones((2, 2)), "hamming", 1.0)


def test_symmetric_infonce_perfect_alignment_lower_than_random(rng):
    e = jnp.array(rng.normal(size=(16, 8)).astype(np.float32))
    shuffled = e[jnp.array(rng.permutation(16))]
    aligned = float(symmetric_infonce(e * 10, e * 10, "l2sq"))
    misaligned = float(symmetric_infonce(e * 10, shuffled * 10, "l2sq"))
    assert aligned < misaligned
    # with perfectly separable embeddings, loss -> 0
    assert aligned < 0.01


def test_symmetric_infonce_bounded_by_log_batch(rng):
    e1 = jnp.array(rng.normal(size=(32, 4)).astype(np.float32))
    e2 = jnp.array(rng.normal(size=(32, 4)).astype(np.float32))
    loss = float(symmetric_infonce(e1, e2, "l2", halved=True))
    # InfoNCE cross entropy can't exceed ~log B by much for random inputs
    assert loss < 2 * np.log(32)


# ---------------------------------------------------------------- entropy
def test_entropy_bits_uniform():
    assert entropy_bits([0.25] * 4) == pytest.approx(2.0)
    assert entropy_bits([0.5, 0.5, 0.0]) == pytest.approx(1.0)


def test_sequence_entropy_and_mi_on_xor():
    # XOR truth table: Y = A xor B. I(A;Y)=0, I(B;Y)=0, I((A,B);Y)=1
    a = np.array([0, 0, 1, 1])
    b = np.array([0, 1, 0, 1])
    y = a ^ b
    assert sequence_entropy_bits(y) == pytest.approx(1.0)
    assert mutual_information_bits(a, y) == pytest.approx(0.0, abs=1e-12)
    assert mutual_information_bits(np.stack([a, b], -1), y) == pytest.approx(1.0)


def test_entropy_rate_ansatz_limits():
    # as N -> inf the correction vanishes
    assert entropy_rate_scaling_ansatz(1e12, 0.52, 0.5, 1.0) == pytest.approx(0.52, abs=1e-3)
    assert entropy_rate_scaling_ansatz(100, 0.5, 0.5, 2.0) == pytest.approx(
        0.5 + np.log2(100) / 10.0 / 2.0
    )


def test_ln2_constant():
    assert LN2 == pytest.approx(np.log(2))
