"""Fleet run registry (telemetry/registry.py): append-only index
semantics, entry schemas, CLI (`telemetry runs list|show|trajectory`),
the committed seed index, bench registration, end-of-run registration
through the workload CLI, and the multi-run index report page.
"""

import json
import os
import sys

import pytest

from dib_tpu.telemetry.events import EventWriter
from dib_tpu.telemetry.registry import (
    RunRegistry,
    bench_entry,
    register_run,
    resolve_runs_root,
    run_entry,
    validate_index_entry,
)
from dib_tpu.telemetry.summary import telemetry_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_stream(directory, run_id="reg-run", status="ok"):
    with EventWriter(str(directory), run_id=run_id) as w:
        w.run_start({"device_kind": "cpu", "device_platform": "cpu",
                     "config_hash": "cafe"})
        w.chunk(epoch=10, steps=100, seconds=1.0, loss=2.0, val_loss=2.5,
                kl_per_feature=[0.1, 0.2], beta=0.1)
        w.run_end(status=status)


# ================================================================= registry
def test_append_latest_supersede(tmp_path):
    registry = RunRegistry(str(tmp_path / "root"))
    registry.append({"kind": "run", "run_id": "a", "status": "incomplete",
                     "metrics": {}})
    registry.append({"kind": "run", "run_id": "b", "status": "ok",
                     "metrics": {}})
    registry.append({"kind": "run", "run_id": "a", "status": "ok",
                     "metrics": {"steps_per_s": 5.0}})
    latest = registry.latest()
    assert set(latest) == {"a", "b"}
    # append-only supersede: the LATEST line wins, history is retained
    assert latest["a"]["status"] == "ok"
    assert len(registry.history("a")) == 2
    assert registry.history("a")[0]["status"] == "incomplete"
    # every appended line is stamped with schema version + time
    for entry in registry.entries():
        assert validate_index_entry(entry) == []


def test_registry_tolerates_torn_final_line(tmp_path):
    registry = RunRegistry(str(tmp_path))
    registry.append({"kind": "bench", "metric": "m", "value": 1.0})
    with open(registry.path, "a") as f:
        f.write('{"kind": "bench", "met')   # writer killed mid-append
    assert len(registry.entries()) == 1
    assert len(registry.bench_history()) == 1


def test_run_entry_headline_metrics(tmp_path):
    _write_stream(tmp_path, status="preempted")
    entry = run_entry(str(tmp_path))
    assert entry["kind"] == "run"
    assert entry["run_id"] == "reg-run"
    assert entry["status"] == "preempted"       # incl. preempted/incomplete
    assert entry["metrics"]["steps_per_s"] == pytest.approx(100.0)
    assert entry["metrics"]["final_val_loss"] == 2.5
    assert entry["provenance"]["config_hash"] == "cafe"
    assert validate_index_entry({"v": 1, "t": 0.0, **entry}) == []


def test_register_run_disabled_and_degraded(tmp_path, monkeypatch):
    monkeypatch.delenv("DIB_RUNS_ROOT", raising=False)
    # empty root disables; a missing stream degrades to a warning
    assert register_run(str(tmp_path / "nope"), root="") is None
    with pytest.warns(UserWarning, match="could not register"):
        assert register_run(str(tmp_path / "nope"),
                            root=str(tmp_path / "r")) is None


def test_resolve_runs_root_precedence(monkeypatch):
    monkeypatch.setenv("DIB_RUNS_ROOT", "/env/root")
    assert resolve_runs_root(None) == "/env/root"
    assert resolve_runs_root("/flag/root") == "/flag/root"
    assert resolve_runs_root("") is None
    monkeypatch.delenv("DIB_RUNS_ROOT")
    assert resolve_runs_root(None) == "runs"    # the committed default


def test_validate_index_entry_rejects_shapes():
    assert validate_index_entry([]) == ["entry must be an object"]
    assert any("kind" in p for p in validate_index_entry(
        {"v": 1, "t": 0.0, "kind": "mystery"}))
    assert any("run_id" in p for p in validate_index_entry(
        {"v": 1, "t": 0.0, "kind": "run", "status": "ok", "metrics": {}}))
    assert any("value" in p for p in validate_index_entry(
        {"v": 1, "t": 0.0, "kind": "bench", "metric": "m"}))
    # degraded bench entries may carry a null value — explained
    assert validate_index_entry(
        {"v": 1, "t": 0.0, "kind": "bench", "metric": "m",
         "degraded": "no_device"}) == []


def test_bench_entry_from_bench_line():
    entry = bench_entry({
        "metric": "amorphous_set_transformer_beta_sweep_projected",
        "value": 6.0, "unit": "minutes", "vs_baseline": 0.6,
        "steps_per_s": 617.0, "mfu": 0.0654, "device_kind": "TPU v5 lite",
        "telemetry": {"run_id": "bench-run"},
    })
    assert entry["kind"] == "bench"
    assert entry["run_id"] == "bench-run"
    assert entry["mfu"] == 0.0654
    assert validate_index_entry({"v": 1, "t": 0.0, **entry}) == []


def test_committed_seed_index_validates_and_carries_history():
    """The committed runs/index.jsonl seeds the perf trajectory from the
    committed BENCH_CACHE/BENCH_SERVE_CPU measurements."""
    registry = RunRegistry(os.path.join(REPO, "runs"))
    entries = registry.entries()
    assert entries, "committed runs/index.jsonl missing or empty"
    for entry in entries:
        assert validate_index_entry(entry) == [], entry
    bench = registry.bench_history()
    metrics = {e["metric"] for e in bench}
    assert "amorphous_set_transformer_beta_sweep_projected" in metrics
    assert "serve_cpu_loadgen" in metrics


# ====================================================================== CLI
def test_runs_cli_list_show_trajectory(tmp_path, capsys):
    root = str(tmp_path / "root")
    _write_stream(tmp_path / "run_a")
    register_run(str(tmp_path / "run_a"), root=root)
    RunRegistry(root).append(bench_entry({
        "metric": "m", "value": 2.5, "unit": "minutes",
        "steps_per_s": 700.0, "mfu": 0.08, "device_kind": "TPU v5 lite"}))

    assert telemetry_main(["runs", "list", "--runs-root", root]) == 0
    out = capsys.readouterr().out
    assert "reg-run" in out and "ok" in out

    assert telemetry_main(["runs", "show", "reg-run",
                           "--runs-root", root]) == 0
    shown = json.loads(capsys.readouterr().out)
    assert shown["run_id"] == "reg-run"
    assert shown["metrics"]["steps_per_s"] == pytest.approx(100.0)

    assert telemetry_main(["runs", "show", "ghost",
                           "--runs-root", root]) == 2
    capsys.readouterr()

    assert telemetry_main(["runs", "trajectory", "--runs-root", root]) == 0
    out = capsys.readouterr().out
    assert "700" in out and "0.08" in out

    # empty/missing registries answer instead of crashing
    assert telemetry_main(["runs", "list", "--runs-root",
                           str(tmp_path / "empty")]) == 0
    assert "no runs registered" in capsys.readouterr().out


def test_runs_cli_lineage_column_and_origin_chain(tmp_path, capsys):
    """Traced streams surface their causal lineage on the registry CLI:
    `runs list` shows the cross-plane join key (parent ref, falling back
    to the trace id) and `runs show` prints the origin chain line; a
    pre-tracing stream stays blank instead of inventing lineage."""
    from dib_tpu.telemetry.context import mint

    root = str(tmp_path / "root")
    ctx = mint("study", trace_id="trace-lin").child("sched:unit:u7",
                                                    origin="sched")
    with EventWriter(str(tmp_path / "traced"), run_id="traced-run",
                     ctx=ctx) as w:
        w.run_start({"device_kind": "cpu", "config_hash": "cafe"})
        w.run_end(status="ok")
    register_run(str(tmp_path / "traced"), root=root)
    _write_stream(tmp_path / "plain", run_id="plain-run")
    register_run(str(tmp_path / "plain"), root=root)

    assert telemetry_main(["runs", "list", "--runs-root", root]) == 0
    out = capsys.readouterr().out
    assert "lineage" in out                      # the column header
    traced_line = [l for l in out.splitlines() if "traced-run" in l][0]
    assert "sched:unit:u7" in traced_line
    plain_line = [l for l in out.splitlines() if "plain-run" in l][0]
    assert "trace" not in plain_line

    assert telemetry_main(["runs", "show", "traced-run",
                           "--runs-root", root]) == 0
    captured = capsys.readouterr()
    # the origin chain rides stderr; stdout stays pure JSON for piping
    assert "lineage: trace trace-lin" in captured.err
    assert "parent sched:unit:u7" in captured.err
    assert "study → sched" in captured.err
    assert json.loads(captured.out)["lineage"]["trace_id"] == "trace-lin"

    assert telemetry_main(["runs", "show", "plain-run",
                           "--runs-root", root]) == 0
    captured = capsys.readouterr()
    assert "lineage:" not in captured.err
    assert "lineage" not in json.loads(captured.out)


def test_workload_cli_registers_run_at_end(tmp_path, capsys):
    """End-of-run registration through the real CLI surface: a boolean
    workload run with --runs-root lands in the index with its headline
    metrics, and `runs list` shows it."""
    from dib_tpu.cli import workload_main

    root = str(tmp_path / "fleet")
    rc = workload_main([
        "boolean", "--telemetry-dir", str(tmp_path / "run"),
        "--runs-root", root,
        "--set", "num_steps=20", "--set", "mi_every=10",
        "--set", "integration_hidden=(32,)", "--set", "batch_size=64",
    ])
    capsys.readouterr()
    assert rc == 0
    latest = RunRegistry(root).latest()
    assert len(latest) == 1
    (entry,) = latest.values()
    assert entry["status"] == "ok"
    assert entry["metrics"]["total_steps"] == 20
    assert entry["metrics"]["heartbeat_max_gap_s"] >= 0
    assert entry["run_dir"] == str(tmp_path / "run")


# ============================================================== index page
def test_index_report_links_runs_and_charts_trajectory(tmp_path, capsys):
    from dib_tpu.telemetry.report import write_report

    root = str(tmp_path / "root")
    run_dir = tmp_path / "run_a"
    _write_stream(run_dir)
    register_run(str(run_dir), root=root)
    write_report(str(run_dir))                 # per-run report to link
    registry = RunRegistry(root)
    for value, steps in ((6.0, 617.0), (4.0, 900.0)):
        registry.append(bench_entry({
            "metric": "m", "value": value, "unit": "minutes",
            "steps_per_s": steps, "mfu": 0.07,
            "device_kind": "TPU v5 lite",
            "measured_at": "2026-08-01T00:00:00Z"}))

    assert telemetry_main(["report", "--index", "--runs-root", root]) == 0
    out_path = capsys.readouterr().out.strip()
    assert out_path == os.path.join(root, "index.html")
    html = open(out_path).read()
    assert html.count("<svg") >= 2              # trajectory charts
    assert "reg-run" in html
    assert 'href="../run_a/report.html"' in html
    assert "617" in html and "900" in html
    assert "Performance trajectory" in html
    # balanced-ish document contract like the per-run report
    assert html.startswith("<!DOCTYPE html>") and html.rstrip().endswith(
        "</html>")


def test_index_report_empty_root_renders_placeholders(tmp_path):
    from dib_tpu.telemetry.report import write_index

    out = write_index(str(tmp_path))
    html = open(out).read()
    assert "No runs registered yet" in html
    assert "No bench entries yet" in html


def test_report_index_cli_requires_some_operand(capsys):
    assert telemetry_main(["report"]) == 2
    assert "required" in capsys.readouterr().err


def test_bench_register_helper(tmp_path, monkeypatch):
    """bench.py's registration hook: fresh records register under the
    default root; degraded ones only under an explicit DIB_RUNS_ROOT."""
    sys.path.insert(0, REPO)
    import bench

    root = str(tmp_path / "r")
    monkeypatch.setenv("DIB_RUNS_ROOT", root)
    bench.register_bench({"metric": "m", "value": 1.0, "unit": "minutes",
                          "steps_per_s": 10.0})
    bench.register_bench({"metric": "m", "value": None, "unit": "minutes",
                          "degraded": "no_device"})
    assert len(RunRegistry(root).bench_history()) == 2
    # unset env + degraded: never grows the committed index
    monkeypatch.delenv("DIB_RUNS_ROOT")
    committed = RunRegistry(os.path.join(REPO, "runs"))
    before = len(committed.entries())
    bench.register_bench({"metric": "m", "value": None,
                          "degraded": "no_device"})
    assert len(committed.entries()) == before
    # empty env root disables entirely
    monkeypatch.setenv("DIB_RUNS_ROOT", "")
    bench.register_bench({"metric": "m", "value": 1.0})
    assert len(RunRegistry(root).bench_history()) == 2
