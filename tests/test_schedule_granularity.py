"""Coarse-vs-fine beta-ramp parity (VERDICT round 1, weak item 4).

The reference's set-transformer workload advances beta every STEP (amorphous
notebook cell 8); the sweep/bench drivers hold beta for ``steps_per_epoch``
steps to amortize host re-entry. These tests quantify that approximation:

  1. schedule math: over the north-star config the held beta never deviates
     from the per-step ramp by more than ~2.5% (50/25000 of the 5-decade log
     range) — a bound, not a vibe;
  2. end-to-end: a shrunk per-particle run trained with the coarse ramp
     reproduces the fine ramp's endpoint (final KL / val loss) within seed
     noise, measured against the seed-to-seed spread of the fine ramp.
"""

import jax
import numpy as np
import pytest

from dib_tpu.ops.schedules import log_annealed_beta


def test_held_beta_bound_north_star_config():
    steps, hold = 25_000, 50
    b0, b1 = 2e-6, 2e-1
    step_grid = np.arange(steps)
    fine = np.array([
        float(log_annealed_beta(s, b0, b1, steps, 0)) for s in step_grid[::250]
    ])
    held = np.array([
        float(log_annealed_beta((s // hold) * hold, b0, b1, steps, 0))
        for s in step_grid[::250]
    ])
    rel = np.abs(np.log(held) - np.log(fine))
    # the held ramp lags by at most hold/steps of the full log range
    bound = (np.log(b1) - np.log(b0)) * hold / steps
    assert rel.max() <= bound + 1e-12
    assert bound < 0.025  # < 2.5% multiplicative deviation


@pytest.mark.slow
def test_coarse_ramp_endpoint_matches_fine(tmp_path):
    from dib_tpu.workloads.amorphous import (
        AmorphousWorkloadConfig,
        run_amorphous_workload,
    )

    def endpoint(steps_per_epoch, seed):
        config = AmorphousWorkloadConfig(
            num_steps=400, number_particles=8, batch_size=16,
            warmup_steps=50, eval_every=400, probe_every=0,
            mi_eval_batch_size=64, mi_eval_batches=1,
            beta_start=1e-5, beta_end=0.5,
        )
        result = run_amorphous_workload(
            key=seed, config=config, outdir=str(tmp_path / f"r{steps_per_epoch}_{seed}"),
            steps_per_epoch=steps_per_epoch, probe_maps=False,
            model_overrides={
                "encoder_hidden": (32,), "embedding_dim": 8, "num_blocks": 2,
                "num_heads": 2, "key_dim": 16, "ff_hidden": (32,),
                "head_hidden": (32,),
            },
            num_synthetic_neighborhoods=256,
        )
        h = result["history"]
        return float(h.total_kl[-1]), float(h.val_loss[-1])

    fine = [endpoint(1, s) for s in (0, 1)]
    coarse = endpoint(50, 0)
    fine_kl = np.array([f[0] for f in fine])
    fine_loss = np.array([f[1] for f in fine])
    # seed-to-seed spread of the fine ramp sets the comparison scale
    kl_scale = max(abs(fine_kl[0] - fine_kl[1]), 0.25 * abs(fine_kl.mean()), 0.05)
    loss_scale = max(abs(fine_loss[0] - fine_loss[1]), 0.1)
    assert abs(coarse[0] - fine_kl.mean()) < 3 * kl_scale, (
        f"coarse-ramp final KL {coarse[0]:.3f} outside fine-ramp range "
        f"{fine_kl} +- {3 * kl_scale:.3f}"
    )
    assert abs(coarse[1] - fine_loss.mean()) < 3 * loss_scale
