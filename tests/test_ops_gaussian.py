"""Unit tests for dib_tpu.ops.gaussian against independent float64 NumPy/SciPy oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dib_tpu.ops import (
    kl_diagonal_gaussian,
    reparameterize,
    bhattacharyya_dist_mat,
    kl_divergence_mat,
    gaussian_log_density_mat,
)


def _np_kl_to_unit(mu, logvar):
    return 0.5 * np.sum(mu**2 + np.exp(logvar) - logvar - 1.0, axis=-1)


def test_kl_diagonal_gaussian_matches_f64_closed_form(rng):
    mu = rng.normal(size=(16, 8)).astype(np.float32)
    logvar = rng.normal(scale=0.5, size=(16, 8)).astype(np.float32)
    got = np.asarray(kl_diagonal_gaussian(jnp.array(mu), jnp.array(logvar)))
    want = _np_kl_to_unit(mu.astype(np.float64), logvar.astype(np.float64))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_kl_zero_at_prior():
    mu = jnp.zeros((4, 8))
    logvar = jnp.zeros((4, 8))
    np.testing.assert_allclose(np.asarray(kl_diagonal_gaussian(mu, logvar)), 0.0, atol=1e-7)


def test_reparameterize_statistics():
    key = jax.random.key(0)
    mu = jnp.full((20000, 2), 1.5)
    logvar = jnp.full((20000, 2), np.log(0.25))
    samples = np.asarray(reparameterize(key, mu, logvar))
    np.testing.assert_allclose(samples.mean(axis=0), 1.5, atol=0.02)
    np.testing.assert_allclose(samples.std(axis=0), 0.5, atol=0.02)


def test_reparameterize_deterministic_per_key():
    key = jax.random.key(7)
    mu = jnp.ones((4, 3))
    logvar = jnp.zeros((4, 3))
    a = reparameterize(key, mu, logvar)
    b = reparameterize(key, mu, logvar)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _np_bhattacharyya(mus1, logvars1, mus2, logvars2):
    """Independent float64 oracle, elementwise loops (no broadcasting tricks)."""
    n, m = mus1.shape[0], mus2.shape[0]
    out = np.zeros((n, m))
    for i in range(n):
        for j in range(m):
            v1, v2 = np.exp(logvars1[i]), np.exp(logvars2[j])
            vbar = 0.5 * (v1 + v2)
            diff = mus1[i] - mus2[j]
            t1 = 0.125 * np.sum(diff**2 / vbar)
            t2 = 0.5 * np.log(np.prod(vbar) / np.sqrt(np.prod(v1) * np.prod(v2)))
            out[i, j] = t1 + t2
    return out


def _np_kl_mat(mus1, logvars1, mus2, logvars2):
    n, m, d = mus1.shape[0], mus2.shape[0], mus1.shape[1]
    out = np.zeros((n, m))
    for i in range(n):
        for j in range(m):
            v1, v2 = np.exp(logvars1[i]), np.exp(logvars2[j])
            diff = mus2[j] - mus1[i]
            out[i, j] = 0.5 * (
                np.sum(v1 / v2) + np.sum(diff**2 / v2) - d + np.sum(logvars2[j]) - np.sum(logvars1[i])
            )
    return out


@pytest.mark.parametrize("n,m,d", [(5, 7, 3), (1, 4, 2), (6, 1, 5)])
def test_bhattacharyya_matches_oracle(rng, n, m, d):
    mus1 = rng.normal(size=(n, d))
    logvars1 = rng.normal(scale=0.7, size=(n, d))
    mus2 = rng.normal(size=(m, d))
    logvars2 = rng.normal(scale=0.7, size=(m, d))
    got = np.asarray(
        bhattacharyya_dist_mat(*(jnp.array(a, dtype=jnp.float32) for a in (mus1, logvars1, mus2, logvars2)))
    )
    want = _np_bhattacharyya(mus1, logvars1, mus2, logvars2)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=2e-4)


def test_bhattacharyya_zero_on_identical_gaussians(rng):
    mus = rng.normal(size=(4, 3))
    logvars = rng.normal(size=(4, 3))
    mat = np.asarray(
        bhattacharyya_dist_mat(*(jnp.array(a, dtype=jnp.float32) for a in (mus, logvars, mus, logvars)))
    )
    np.testing.assert_allclose(np.diagonal(mat), 0.0, atol=1e-4)


@pytest.mark.parametrize("n,m,d", [(5, 7, 3), (3, 3, 4)])
def test_kl_divergence_mat_matches_oracle(rng, n, m, d):
    mus1 = rng.normal(size=(n, d))
    logvars1 = rng.normal(scale=0.7, size=(n, d))
    mus2 = rng.normal(size=(m, d))
    logvars2 = rng.normal(scale=0.7, size=(m, d))
    got = np.asarray(
        kl_divergence_mat(*(jnp.array(a, dtype=jnp.float32) for a in (mus1, logvars1, mus2, logvars2)))
    )
    want = _np_kl_mat(mus1, logvars1, mus2, logvars2)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=2e-4)


def test_kl_divergence_mat_diag_vs_prior_formula(rng):
    """KL matrix against the unit normal must reduce to the bottleneck KL."""
    mus = rng.normal(size=(6, 4)).astype(np.float32)
    logvars = rng.normal(scale=0.5, size=(6, 4)).astype(np.float32)
    mat = kl_divergence_mat(
        jnp.array(mus), jnp.array(logvars), jnp.zeros((1, 4)), jnp.zeros((1, 4))
    )
    direct = kl_diagonal_gaussian(jnp.array(mus), jnp.array(logvars))
    np.testing.assert_allclose(np.asarray(mat[:, 0]), np.asarray(direct), rtol=1e-5)


def test_gaussian_log_density_matches_scipy(rng):
    from scipy.stats import multivariate_normal

    u = rng.normal(size=(4, 3))
    mus = rng.normal(size=(5, 3))
    logvars = rng.normal(scale=0.5, size=(5, 3))
    got = np.asarray(
        gaussian_log_density_mat(
            jnp.array(u, dtype=jnp.float32),
            jnp.array(mus, dtype=jnp.float32),
            jnp.array(logvars, dtype=jnp.float32),
        )
    )
    for i in range(4):
        for j in range(5):
            want = multivariate_normal.logpdf(u[i], mean=mus[j], cov=np.diag(np.exp(logvars[j])))
            np.testing.assert_allclose(got[i, j], want, rtol=1e-4)
