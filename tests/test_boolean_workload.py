"""Boolean-circuit workload: exact-oracle tests.

The truth table gives EXACT ground truth (boolean notebook cells 5/7/10), so
these tests check hard equalities, not tolerances-of-convenience:
  - Shapley efficiency: sum_i phi_i == I(all inputs; Y) == H(Y) for a
    deterministic circuit.
  - Null player: an input the circuit ignores gets phi == 0 exactly.
  - Symmetry: interchangeable inputs (e.g. x0, x1 of XOR) get equal phi.
  - The trained DIB recovers the important channels of a small circuit.
"""

import jax
import numpy as np
import pytest

from dib_tpu.data.boolean_circuit import (
    FIG_S1_CIRCUITS,
    exact_subset_informations,
    fetch_boolean_circuit,
    full_truth_table,
    num_circuit_inputs,
)
from dib_tpu.workloads.boolean import (
    BooleanTrainer,
    BooleanWorkloadConfig,
    best_subsets_by_size,
    logistic_regression_importances,
    run_boolean_workload,
    shapley_values_bits,
)

# x2 feeds only a dead gate (g3 = x2 XOR x2), so it is an exact null player.
AND_WITH_SPECTATOR = [0, 1, 2, [2, 2, 2], [0, 0, 1]]  # y = x0 AND x1
XOR3 = [0, 1, 2, [2, 0, 1], [2, 3, 2]]                # y = x0 XOR x1 XOR x2


def test_shapley_efficiency_and_null_player():
    table = full_truth_table(AND_WITH_SPECTATOR)
    n = 3
    infos = exact_subset_informations(table, n)
    phi = shapley_values_bits(table, n, infos)
    # efficiency: sum of Shapley values == v(grand coalition) == H(Y)
    assert np.isclose(phi.sum(), infos[(0, 1, 2)], atol=1e-12)
    # null player: x2 cannot affect y
    assert np.isclose(phi[2], 0.0, atol=1e-12)
    # symmetry: x0 and x1 are interchangeable in AND
    assert np.isclose(phi[0], phi[1], atol=1e-12)


def test_shapley_xor_symmetry():
    table = full_truth_table(XOR3)
    phi = shapley_values_bits(table, 3)
    # XOR of 3 fair bits: every input has identical Shapley value, and they
    # sum to H(Y) = 1 bit, so each phi == 1/3 bit.
    assert np.allclose(phi, 1.0 / 3.0, atol=1e-12)


def test_best_subsets_oracle():
    table = full_truth_table(AND_WITH_SPECTATOR)
    infos = exact_subset_informations(table, 3)
    best = best_subsets_by_size(infos)
    # the best pair must be (x0, x1) with full H(Y); H(Y) for AND = h(1/4)
    h_y = -(0.25 * np.log2(0.25) + 0.75 * np.log2(0.75))
    assert best[2][0] == (0, 1)
    assert np.isclose(best[2][1], h_y, atol=1e-12)
    # singletons of AND carry identical information
    assert np.isclose(infos[(0,)], infos[(1,)], atol=1e-12)


def test_logreg_importances_spectator_small():
    table = full_truth_table(AND_WITH_SPECTATOR)
    x = (2 * table[:, :3] - 1).astype(np.float64)
    y = table[:, -1]
    imp = logistic_regression_importances(x, y)
    assert imp.shape == (3,)
    # the dead input gets (near-)zero weight; live inputs clearly positive
    assert imp[2] < 0.1 * min(imp[0], imp[1])


@pytest.mark.slow
def test_run_boolean_workload_small_circuit():
    config = BooleanWorkloadConfig(
        num_steps=600, batch_size=64, mi_every=200, integration_hidden=(32, 32)
    )
    result = run_boolean_workload(
        key=0, config=config, circuit_specification=FIG_S1_CIRCUITS[0]
    )
    n = num_circuit_inputs(FIG_S1_CIRCUITS[0])
    hist = result["history"]
    assert hist["task"].shape == (600,)
    assert hist["mi_lower_bits"].shape[1] == n
    # sandwich ordering: lower <= upper at every check, every channel
    assert np.all(hist["mi_lower_bits"] <= hist["mi_upper_bits"] + 1e-6)
    # beta ramps upward
    assert hist["beta"][0] < hist["beta"][-1]
    # channel information never exceeds 1 bit (binary input) by more than slack
    assert np.all(hist["mi_lower_bits"] <= 1.0 + 0.05)
    # exact oracles present and consistent
    assert result["entropy_y_bits"] <= 1.0 + 1e-12
    phi_sum = result["shapley_bits"].sum()
    grand = result["subset_informations"][tuple(range(n))]
    assert np.isclose(phi_sum, grand, atol=1e-9)


@pytest.mark.slow
def test_sandwich_gap_at_reference_tightness():
    """The reference claims the sandwich bounds stay within ~0.01 bits of
    each other during boolean training (boolean notebook cell 6 comment;
    SURVEY.md section 6). Pin that regime quantitatively: converged binary
    channels on the full truth table must show gap <= 0.01 bits with the
    sandwich containing the true 1 bit per +-1 input."""
    import jax

    bundle = fetch_boolean_circuit()
    cfg = BooleanWorkloadConfig(
        num_steps=3000, beta_start=1e-3, beta_end=1e-3,   # converged, low beta
        batch_size=512, mi_every=3000,
    )
    trainer = BooleanTrainer(bundle, cfg)
    state, _ = trainer.fit(jax.random.key(0))
    lower, upper = trainer.channel_mi_bounds(state, jax.random.key(1))
    lower_bits = np.asarray(lower) / np.log(2.0)
    upper_bits = np.asarray(upper) / np.log(2.0)
    gap = upper_bits - lower_bits
    assert (gap >= -1e-6).all(), "LOO upper fell below InfoNCE lower"
    assert gap.max() <= 0.01, f"sandwich gap {gap.max():.4f} bits > 0.01"
    # each +-1 input carries exactly 1 bit; the sandwich must contain it
    assert (lower_bits <= 1.0 + 1e-3).all()
    assert (upper_bits >= 1.0 - 5e-3).all()


@pytest.mark.slow
def test_boolean_trainer_learns_at_low_beta():
    # With beta held tiny, the model must learn the circuit (acc ~ 1 on the
    # full table) — the pretraining-phase behavior of the notebook.
    bundle = fetch_boolean_circuit(circuit_specification=XOR3)
    config = BooleanWorkloadConfig(
        num_steps=1500,
        batch_size=8,
        beta_start=1e-6,
        beta_end=1e-6,
        mi_every=1500,
        integration_hidden=(64, 64),
        learning_rate=3e-3,
    )
    trainer = BooleanTrainer(bundle, config)
    state, _ = trainer.fit(jax.random.key(1))
    _, acc = trainer.full_table_eval(state, jax.random.key(2))
    assert float(acc) == 1.0
