"""Radial-density-shell workload: end-to-end run + history parity identity."""

import numpy as np
import pytest

from dib_tpu.train.history import HistoryRecord
from dib_tpu.workloads.radial_shells import RadialShellsConfig, run_radial_shells_workload


def test_combined_loss_commutes_with_to_bits():
    """Reference-parity property (train.py:169-174): for info-based losses,
    the reported combined series converts nats->bits the same whether the
    conversion happens before or after recombining task + beta*KL."""
    rng = np.random.default_rng(0)
    rec = HistoryRecord(
        beta=rng.uniform(0.1, 1.0, 5).astype(np.float32),
        kl_per_feature=rng.uniform(size=(5, 3)).astype(np.float32),
        loss=rng.uniform(size=5).astype(np.float32),
        val_loss=np.zeros(5, np.float32),
        metric=np.zeros(5, np.float32),
        val_metric=np.zeros(5, np.float32),
    )
    np.testing.assert_allclose(
        rec.to_bits().combined_loss, rec.combined_loss / np.log(2.0), rtol=1e-6
    )


@pytest.mark.slow
def test_radial_shells_end_to_end(tmp_path):
    config = RadialShellsConfig(
        batch_size=32, num_pretraining_epochs=10, num_annealing_epochs=30,
        num_shells=4, encoder_hidden=(8,), integration_hidden=(16,),
        embedding_dim=2, eval_every=20, mi_eval_batch_size=128, mi_eval_batches=1,
    )
    result = run_radial_shells_workload(
        key=0, config=config, outdir=str(tmp_path),
        num_synthetic_neighborhoods=128,
    )
    hist = result["history"]
    assert hist.kl_per_feature.shape == (40, 8)       # 2 types x 4 shells
    assert np.isfinite(hist.loss).all()
    assert result["final_shell_profile_bits"].shape == (8,)
    # peak profile: per-shell (not per-epoch) reduction that dominates
    # every recorded check — catches wrong-axis reductions
    peak = result["peak_shell_profile_bits"]
    assert peak.shape == (8,)
    assert (peak[None, :] >= result["mi_bounds_bits"][:, :, 0] - 1e-9).all()
    assert (tmp_path / "distributed_info_plane.png").exists()
    assert (tmp_path / "information_vs_radius.png").exists()
