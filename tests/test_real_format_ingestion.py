"""Real-export-format ingestion against committed golden fixtures.

VERDICT round 1, item 6: every workload trains on synthetic surrogates in
this egress-free environment, so these tests prove the real-file branches
work against the reference's ACTUAL export schemas — switching surrogate ->
real data is a drop-in. Fixtures live in ``tests/fixtures`` (see
``make_fixtures.py``; schemas per amorphous notebook cell 3 and the UCI /
nodegam layouts the reference's ``data.py:299-395`` loaders point at).
"""

import os
import shutil

import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.data.amorphous import (
    convert_glass_csv_exports,
    load_glass_splits,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
GLASS = os.path.join(FIXTURES, "glass_csv")
TABULAR = os.path.join(FIXTURES, "tabular")


# ---------------------------------------------------------------- glass csv

def test_glass_csv_to_npz_conversion(tmp_path):
    """The notebook's padded-csv parsing: length marker honored, padding
    dropped, types flattened to 1-D, labels [N, 1]."""
    written = convert_glass_csv_exports(GLASS, out_dir=str(tmp_path))
    names = {os.path.basename(p) for p in written}
    assert {"RapidQuench.npz", "GradualQuench.npz", "g_r_bins.npy",
            "g_r_AA_RapidQuench.npy", "g_r_AB_GradualQuench.npy"} <= names

    splits = load_glass_splits(str(tmp_path), "GradualQuench")
    pos_train, typ_train, y_train = splits["train"]
    # fixture sizes: train [4, 3, 5], val [3, 4] (make_fixtures.py)
    assert [p.shape for p in pos_train] == [(4, 2), (3, 2), (5, 2)]
    assert [t.shape for t in typ_train] == [(4,), (3,), (5,)]
    assert y_train.shape == (3, 1)
    pos_val, typ_val, y_val = splits["val"]
    assert [p.shape for p in pos_val] == [(3, 2), (4, 2)]
    assert y_val.shape == (2, 1)
    assert set(np.unique(np.concatenate(typ_train))) <= {1.0, 2.0}
    # csv row layout is round-trippable: re-read one row by hand
    raw = np.loadtxt(
        os.path.join(GLASS, "GradualQuench_train_particle_positions.csv"),
        delimiter=",",
    )
    first = raw[0].reshape(-1, 2)
    assert int(first[-1, 0]) == 4
    np.testing.assert_allclose(first[:4], pos_train[0], atol=1e-6)


def test_amorphous_particles_real_branch(tmp_path):
    convert_glass_csv_exports(GLASS, out_dir=str(tmp_path))
    bundle = get_dataset(
        "amorphous_particles", data_path=str(tmp_path),
        protocol="RapidQuench", number_particles_to_use=4,
    )
    assert bundle.extras["source"] == "real"
    assert bundle.extras["sets_train"].shape == (3, 4, 12)
    assert bundle.extras["sets_valid"].shape == (2, 4, 12)
    assert bundle.x_train.shape == (3, 4 * 12)
    assert bundle.y_train.shape == (3, 1)


def test_amorphous_radial_shells_real_branch(tmp_path):
    convert_glass_csv_exports(GLASS, out_dir=str(tmp_path))
    bundle = get_dataset(
        "amorphous_radial_shells", data_path=str(tmp_path),
        protocol="GradualQuench", num_shells=4,
    )
    assert bundle.x_train.shape == (3, 8)
    assert bundle.feature_dimensionalities == [1] * 8
    # density features: every particle lands in some shell
    assert (bundle.x_train.sum(axis=1) > 0).all()


# ------------------------------------------------------------- UCI tabular

def _real_bundle(name, **kwargs):
    bundle = get_dataset(name, data_path=TABULAR, seed=3, **kwargs)
    assert bundle.extras["source"] == "real", f"{name} fell back to synthetic"
    assert np.isfinite(bundle.x_train).all()
    assert np.isfinite(bundle.x_valid).all()
    assert bundle.x_train.shape[1] == sum(bundle.feature_dimensionalities)
    return bundle


def test_wine_real_file():
    bundle = _real_bundle("wine")
    assert len(bundle.feature_dimensionalities) == 11
    assert bundle.loss == "mse"
    assert "alcohol" in bundle.feature_labels


def test_bikeshare_real_file():
    bundle = _real_bundle("bikeshare")
    # instant/dteday/casual/registered dropped -> 12 features
    assert len(bundle.feature_dimensionalities) == 12
    assert "hr" in bundle.feature_labels
    assert bundle.loss == "mse"


def test_mice_protein_real_file():
    bundle = _real_bundle("mice_protein")
    assert len(bundle.feature_dimensionalities) == 77
    assert bundle.output_dimensionality == 8
    assert bundle.loss == "sparse_ce"
    assert "DYRK1A_N" in bundle.feature_labels
    # the fixture plants NaNs; the class-mean fill must clear them all
    assert np.isfinite(bundle.x_train).all()


def test_credit_real_file():
    bundle = _real_bundle("credit")
    assert len(bundle.feature_dimensionalities) == 30  # Time + V1..V28 + Amount
    assert bundle.loss == "bce"
    assert set(np.unique(bundle.y_train)) <= {0.0, 1.0}


def test_support2_real_file():
    bundle = _real_bundle("support2")
    assert bundle.loss == "bce"
    # categorical columns one-hot to >1-dim features; numerics stay 1-dim
    by_label = dict(zip(bundle.feature_labels, bundle.feature_dimensionalities))
    assert by_label["age"] == 1
    assert by_label["dzgroup"] > 1
    assert by_label["sex"] > 1


def test_microsoft_real_file():
    bundle = _real_bundle("microsoft")
    assert len(bundle.feature_dimensionalities) == 16
    assert bundle.loss == "mse"


def test_missing_real_files_fall_back_with_warning(tmp_path):
    with pytest.warns(UserWarning, match="synthetic"):
        bundle = get_dataset("wine", data_path=str(tmp_path / "nope"))
    assert bundle.extras["source"] == "synthetic"


def test_malformed_real_file_raises(tmp_path):
    # A present-but-broken real file must raise, never silently fall back
    # to the surrogate (tabular._local_or_synthetic contract).
    target = tmp_path / "winequality-red.csv"
    target.write_text("this;is;not\na;wine;file\n")
    with pytest.raises(Exception) as err:
        get_dataset("wine", data_path=str(tmp_path))
    assert not isinstance(err.value, FileNotFoundError)


def test_diabetes_committed_real_file():
    """diabetes is the one registry entry whose REAL file is committed
    (data/diabetes.csv, public LARS study data shipped with scikit-learn):
    the real-file ingestion branch is covered by actual data in-tree, not
    just fixtures — VERDICT round 2, item 6."""
    repo_data = os.path.join(os.path.dirname(os.path.dirname(__file__)), "data")
    bundle = get_dataset("diabetes", data_path=repo_data, seed=3)
    assert bundle.extras["source"] == "real"
    assert bundle.x_train.shape[0] + bundle.x_valid.shape[0] == 442
    assert bundle.feature_labels[:4] == ["age", "sex", "bmi", "bp"]
    assert bundle.loss == "mse"
    assert np.isfinite(bundle.x_train).all()


def test_breast_cancer_committed_real_file():
    """Second committed-real registry entry (VERDICT round 3 item 5):
    data/breast_cancer.csv via scripts/export_sklearn_datasets.py — covers
    the BINARY (info-based BCE) loss on real data."""
    repo_data = os.path.join(os.path.dirname(os.path.dirname(__file__)), "data")
    bundle = get_dataset("breast_cancer", data_path=repo_data, seed=3)
    assert bundle.extras["source"] == "real"
    assert bundle.x_train.shape[0] + bundle.x_valid.shape[0] == 569
    assert bundle.number_features == 30
    assert bundle.loss == "bce" and bundle.loss_is_info_based
    assert set(np.unique(bundle.y_train)) <= {0.0, 1.0}
    assert np.isfinite(bundle.x_train).all()


def test_wine_recognition_committed_real_file():
    """Third committed-real registry entry (VERDICT round 3 item 5):
    data/wine_recognition.csv — covers the MULTICLASS sparse-CE loss on
    real data (distinct from 'wine', the UCI wine-quality file)."""
    repo_data = os.path.join(os.path.dirname(os.path.dirname(__file__)), "data")
    bundle = get_dataset("wine_recognition", data_path=repo_data, seed=3)
    assert bundle.extras["source"] == "real"
    assert bundle.x_train.shape[0] + bundle.x_valid.shape[0] == 178
    assert bundle.number_features == 13
    assert bundle.loss == "sparse_ce"
    assert bundle.output_dimensionality == 3
    assert set(np.unique(bundle.y_train)) <= {0, 1, 2}
    assert np.isfinite(bundle.x_train).all()
