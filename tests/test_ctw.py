"""Tests for the native CTW entropy-rate estimator (dib_tpu.ctw).

Oracles (SURVEY.md section 4): hand-computed KT/CTW code lengths on tiny
sequences, plug-in entropy agreement on i.i.d. sequences, and a differential
check against an independent naive full-expansion CTW implemented here in
pure Python (no path compression — mathematically equivalent because any
context node with a single count has weighted code length log2(K)
independent of its subtree).
"""

from __future__ import annotations

import math

import numpy as np
import pytest

from dib_tpu.ctw import CTWEstimator, estimate_entropy


def naive_ctw_code_length(seq, alphabet_size: int, max_depth: int = 10**9) -> float:
    """Reference-free naive CTW: full context expansion, recursive mixing."""
    K = alphabet_size
    b = 1.0 / K

    class Node:
        __slots__ = ("counts", "children")

        def __init__(self):
            self.counts = [0] * K
            self.children = {}

    root = Node()
    for i, s in enumerate(seq):
        root.counts[s] += 1
        node = root
        for j in range(i - 1, -1, -1):
            if i - j > max_depth:
                break
            ctx = seq[j]
            if ctx not in node.children:
                node.children[ctx] = Node()
            node = node.children[ctx]
            node.counts[s] += 1

    def weighted(node: Node) -> float:
        total = sum(node.counts)
        le = (
            math.lgamma(total + K * b)
            - math.lgamma(K * b)
            - sum(math.lgamma(c + b) - math.lgamma(b) for c in node.counts)
        ) / math.log(2)
        if node.children and total > 1:
            lc = sum(weighted(ch) for ch in node.children.values())
            return 1 + min(le, lc) - math.log2(1 + 2 ** (-abs(le - lc)))
        return le

    return weighted(root)


class TestHandComputed:
    def test_two_symbol_sequence_exact(self):
        # Sequence [0, 1], K=2: root KT code of counts (1,1) is 3 bits
        # (1/2 * 1/4); the depth-1 node codes one symbol at 1 bit; mixing
        # gives -log2((2^-3 + 2^-1)/2) = 1.678072 bits; /2 symbols.
        expected = (1 + 1 - math.log2(1 + 2 ** (-2.0))) / 2
        assert estimate_entropy([0, 1], 2) == pytest.approx(expected, abs=1e-12)

    def test_single_symbol(self):
        # One symbol, K=2: KT gives p=1/2 -> 1 bit -> rate 1 bit/symbol.
        assert estimate_entropy([1], 2) == pytest.approx(1.0, abs=1e-12)

    def test_smoke_sequence(self):
        # The reference's build smoke test input (chaos/setup.py:26-28);
        # value checked against the independent naive implementation.
        seq = [1, 0, 0, 1]
        expected = naive_ctw_code_length(seq, 2) / len(seq)
        assert estimate_entropy(seq, 2) == pytest.approx(expected, abs=1e-10)


class TestDifferentialVsNaive:
    @pytest.mark.parametrize("alphabet_size", [2, 3, 5])
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_sequences(self, alphabet_size, seed):
        rng = np.random.default_rng(seed)
        seq = rng.integers(0, alphabet_size, size=200).tolist()
        expected = naive_ctw_code_length(seq, alphabet_size) / len(seq)
        got = estimate_entropy(seq, alphabet_size)
        assert got == pytest.approx(expected, rel=1e-9)

    @pytest.mark.parametrize("max_depth", [1, 2, 4, 16])
    def test_depth_capped_matches_naive(self, max_depth):
        rng = np.random.default_rng(11)
        seq = rng.integers(0, 2, size=250).tolist()
        expected = naive_ctw_code_length(seq, 2, max_depth=max_depth) / len(seq)
        got = estimate_entropy(seq, 2, max_depth=max_depth)
        assert got == pytest.approx(expected, rel=1e-9)

    def test_structured_sequence(self):
        # Markov-ish structure exercises tail expansion heavily.
        rng = np.random.default_rng(7)
        seq = []
        s = 0
        for _ in range(300):
            s = (s + (1 if rng.random() < 0.9 else 2)) % 3
            seq.append(s)
        expected = naive_ctw_code_length(seq, 3) / len(seq)
        assert estimate_entropy(seq, 3) == pytest.approx(expected, rel=1e-9)


class TestAsymptotics:
    def test_iid_uniform_bits(self):
        rng = np.random.default_rng(0)
        seq = rng.integers(0, 2, size=20000)
        h = estimate_entropy(seq, 2)
        assert h == pytest.approx(1.0, abs=0.02)

    def test_iid_biased_bits(self):
        rng = np.random.default_rng(1)
        p = 0.8
        seq = (rng.random(20000) < p).astype(np.int32)
        h_true = -(p * math.log2(p) + (1 - p) * math.log2(1 - p))
        assert estimate_entropy(seq, 2) == pytest.approx(h_true, abs=0.02)

    def test_constant_sequence_near_zero(self):
        h = estimate_entropy(np.zeros(5000, np.int32), 2)
        assert h < 0.01

    def test_periodic_sequence_near_zero(self):
        seq = np.tile([0, 1, 2, 1], 2000)
        h = estimate_entropy(seq, 3)
        assert h < 0.02

    def test_depth_cap_still_sane(self):
        rng = np.random.default_rng(2)
        seq = rng.integers(0, 2, size=5000)
        h = estimate_entropy(seq, 2, max_depth=4)
        assert h == pytest.approx(1.0, abs=0.05)


class TestIncremental:
    def test_incremental_matches_one_shot(self):
        rng = np.random.default_rng(3)
        seq = rng.integers(0, 3, size=500)
        with CTWEstimator(3) as est:
            est.append(seq[:100]).append(seq[100:350]).append(seq[350:])
            assert est.length == 500
            assert est.entropy_rate() == pytest.approx(
                estimate_entropy(seq, 3), rel=1e-12
            )

    def test_prefix_queries_match_rebuilds(self):
        rng = np.random.default_rng(4)
        seq = rng.integers(0, 2, size=600)
        with CTWEstimator(2) as est:
            for cut in (150, 300, 600):
                prev = est.length
                est.append(seq[prev:cut])
                assert est.entropy_rate() == pytest.approx(
                    estimate_entropy(seq[:cut], 2), rel=1e-12
                )

    def test_validation_errors(self):
        with pytest.raises(ValueError):
            estimate_entropy([0, 1, 2], 2)  # symbol out of range
        with pytest.raises(ValueError):
            estimate_entropy([[0, 1]], 2)  # not 1-D
        with pytest.raises(ValueError):
            CTWEstimator(1)
