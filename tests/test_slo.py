"""SLO engine (telemetry/slo.py): rule grammar validation, metric
resolution, transition detection, durable+idempotent alert writes,
`telemetry check` exit codes (in-process and subprocess), live
evaluation through the tail engine, and the committed SLO.json contract
(valid grammar; the committed fixture stream passes it clean).
"""

import io
import json
import os
import subprocess
import sys
import threading

import pytest

from dib_tpu.telemetry.events import EventWriter, read_events
from dib_tpu.telemetry.slo import (
    SLOEngine,
    check_run,
    detect_transitions,
    evaluate_rules,
    load_slo,
    resolve_metric,
    validate_slo,
)
from dib_tpu.telemetry.summary import telemetry_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_RUN = os.path.join(REPO, "tests", "fixtures", "telemetry_run")


# ================================================================== grammar
def test_validate_slo_accepts_minimal_and_rejects_shapes():
    ok = {"rules": [{"name": "a", "metric": "m", "min": 1.0}]}
    assert validate_slo(ok) == []
    bad = {
        "rules": [
            {"metric": "m", "min": 1.0},                 # no name
            {"name": "b", "min": 1.0},                   # no metric
            {"name": "c", "metric": "m"},                # no bound
            {"name": "d", "metric": "m", "min": 1, "max": 2},  # two bounds
            {"name": "d", "metric": "m", "min": 1.0},    # dup name
            {"name": "e", "metric": "m", "max": float("nan")},
            {"name": "f", "metric": "m", "min": 0, "when": "tpu"},
        ],
        "transitions": {"kl_threshold_nats": -1},
    }
    problems = validate_slo(bad)
    assert len(problems) >= 7
    assert any("duplicate" in p for p in problems)
    assert any("kl_threshold_nats" in p for p in problems)


def test_validate_burn_rates_grammar():
    base = {"rules": [{"name": "a", "metric": "m", "min": 1.0}]}
    good = dict(base, burn_rates=[
        {"name": "br", "bad": {"type": "alert"}, "total": {},
         "budget": 0.1, "fast_window_s": 60, "slow_window_s": 3600,
         "threshold": 2.0, "severity": "page"}])
    assert validate_slo(good) == []

    bad = dict(base, burn_rates=[
        {"bad": {"type": "alert"}, "budget": 0.1,          # no name
         "fast_window_s": 60, "slow_window_s": 3600, "threshold": 2},
        {"name": "a", "bad": {"type": "alert"},            # dup vs rules
         "budget": 0.1, "fast_window_s": 60, "slow_window_s": 3600,
         "threshold": 2},
        {"name": "b", "bad": {},                           # empty matcher
         "budget": 0.1, "fast_window_s": 60, "slow_window_s": 3600,
         "threshold": 2},
        {"name": "c", "bad": {"type": "alert"},            # budget > 1
         "budget": 2.0, "fast_window_s": 60, "slow_window_s": 3600,
         "threshold": 2},
        {"name": "d", "bad": {"type": "alert"},            # slow <= fast
         "budget": 0.1, "fast_window_s": 60, "slow_window_s": 60,
         "threshold": 2},
        {"name": "e", "bad": {"type": "alert"},            # bad threshold
         "budget": 0.1, "fast_window_s": 60, "slow_window_s": 3600,
         "threshold": 0},
        "not-an-object",
    ])
    problems = validate_slo(bad)
    assert any("'name' must be" in p for p in problems)
    assert any("duplicate rule name 'a'" in p for p in problems)
    assert any("'bad' must be" in p for p in problems)
    assert any("'budget' must be" in p for p in problems)
    assert any("greater than 'fast_window_s'" in p for p in problems)
    assert any("'threshold' must be" in p for p in problems)
    assert any("must be an object" in p for p in problems)
    assert validate_slo(dict(base, burn_rates="x")) \
        == ["'burn_rates' must be a list"]


def test_load_slo_raises_on_invalid(tmp_path):
    path = tmp_path / "slo.json"
    path.write_text(json.dumps({"rules": []}))
    with pytest.raises(ValueError, match="non-empty list"):
        load_slo(str(path))


def test_committed_slo_json_is_valid():
    spec = load_slo(os.path.join(REPO, "SLO.json"))
    names = [r["name"] for r in spec["rules"]]
    # the budgets the ISSUE grounds in BENCH_r05/BENCH_SERVE_CPU history
    assert "north_star_mfu_floor" in names
    assert "serve_p99_ceiling" in names
    assert "no_undetected_faults" in names
    assert "fleet_orphan_ceiling" in names
    burn_names = [r["name"] for r in spec.get("burn_rates") or []]
    assert "fleet_alert_burn" in burn_names
    assert "fleet_mitigation_burn" in burn_names
    assert spec["transitions"]["kl_threshold_nats"] > 0


# ======================================================== metric resolution
def test_resolve_metric_semantics():
    summary = {
        "steps_per_s": 100.0,
        "final_loss": ["1.0", 3.0],                  # numeric-ish list
        "faults": {"undetected": ["nan", "stall"]},  # non-numeric list
        "serving": {"request_p99_ms": 12.5},
        "diverged": "NaN",
        "flag": True,
    }
    assert resolve_metric(summary, "steps_per_s") == 100.0
    assert resolve_metric(summary, "final_loss") == pytest.approx(2.0)
    assert resolve_metric(summary, "faults.undetected") == 2.0
    assert resolve_metric(summary, "serving.request_p99_ms") == 12.5
    assert resolve_metric(summary, "missing.path") is None
    assert resolve_metric(summary, "flag") is None    # bools never gate
    nan = resolve_metric(summary, "diverged")
    assert nan != nan                                  # parses to real NaN


def test_evaluate_rules_statuses():
    rules = [
        {"name": "floor_ok", "metric": "steps_per_s", "min": 50.0},
        {"name": "floor_bad", "metric": "steps_per_s", "min": 200.0},
        {"name": "guarded_off", "metric": "steps_per_s", "min": 1e9,
         "when": {"device_platform": "tpu"}},
        {"name": "absent", "metric": "serving.request_p99_ms", "max": 1.0},
        {"name": "required_absent", "metric": "nope", "max": 1.0,
         "required": True},
        {"name": "nonfinite_skips", "metric": "diverged", "max": 1.0},
    ]
    summary = {"steps_per_s": 100.0, "device_platform": "cpu",
               "diverged": "NaN"}
    by_name = {r["rule"]: r for r in evaluate_rules(rules, summary)}
    assert by_name["floor_ok"]["status"] == "ok"
    assert by_name["floor_bad"]["status"] == "violated"
    assert by_name["guarded_off"]["status"] == "skipped"
    assert by_name["guarded_off"]["reason"] == "when-guard unmatched"
    assert by_name["absent"]["status"] == "skipped"
    assert by_name["required_absent"]["status"] == "violated"
    assert by_name["nonfinite_skips"]["status"] == "skipped"


def test_when_guard_membership_list():
    rules = [{"name": "r", "metric": "x", "min": 0.0,
              "when": {"device_platform": ["tpu", "gpu"]}}]
    (tpu,) = evaluate_rules(rules, {"x": 1.0, "device_platform": "tpu"})
    (cpu,) = evaluate_rules(rules, {"x": 1.0, "device_platform": "cpu"})
    assert tpu["status"] == "ok"
    assert cpu["status"] == "skipped"


def test_when_not_guard_excludes_and_fails_closed_on_absent_key():
    """`when_not` skips on a MATCH, but an absent key excludes nothing —
    a stream that never tagged its mode stays gated (the inclusion-guard
    regression: `when` would silently un-gate it)."""
    rules = [{"name": "r", "metric": "x", "max": 0.5,
              "when_not": {"mode": ["stream_deploy", "fault_drill"]}}]
    (excluded,) = evaluate_rules(rules, {"x": 1.0, "mode": "stream_deploy"})
    (gated,) = evaluate_rules(rules, {"x": 1.0, "mode": "serve"})
    (untagged,) = evaluate_rules(rules, {"x": 1.0})
    assert excluded["status"] == "skipped"
    assert gated["status"] == "violated"
    assert untagged["status"] == "violated"
    # grammar: when_not must be an object, like when
    assert validate_slo({"rules": [
        {"name": "r", "metric": "m", "min": 0, "when_not": "x"}]})


# ============================================================== transitions
def test_detect_transitions_crossings():
    chunks = [
        {"epoch": 10, "kl_per_feature": [0.5, 0.01], "beta": 0.1},
        {"epoch": 20, "kl_per_feature": [0.5, 0.20], "beta": 0.2},  # ch1 up
        {"epoch": 30, "kl_per_feature": [0.02, 0.20], "beta": 0.3},  # ch0 dn
        {"epoch": 40, "kl_per_feature": [0.01, 0.20], "beta": 0.4},  # none
    ]
    out = detect_transitions(chunks, 0.05)
    assert [(t["channel"], t["epoch"], t["direction"]) for t in out] == [
        (1, 20, "up"), (0, 30, "down")]
    assert out[1]["kl_before"] == 0.5 and out[1]["kl_after"] == 0.02
    assert out[1]["beta"] == pytest.approx(0.3)


def test_transitions_ignore_sweep_streams():
    # sweep chunk events carry per-replica totals, no per-channel signal
    assert detect_transitions(
        [{"epoch": 1, "kl_total": [1.0, 2.0]},
         {"epoch": 2, "kl_total": [0.0, 0.0]}], 0.05) == []


# ================================================== durable alerts / check
def _write_run(directory, *, steps_per_s=100.0, kl_rows=None,
               status="ok", run_id="slo-run"):
    with EventWriter(str(directory), run_id=run_id) as w:
        w.run_start({"device_kind": "cpu", "device_platform": "cpu"})
        rows = kl_rows or [[0.5, 0.5]] * 2
        for i, row in enumerate(rows):
            w.chunk(epoch=(i + 1) * 10, steps=int(steps_per_s),
                    seconds=1.0, loss=1.0, val_loss=1.1,
                    kl_per_feature=row, beta=0.1 * (i + 1))
        w.run_end(status=status)


def test_check_run_clean_writes_nothing(tmp_path):
    _write_run(tmp_path)
    slo = tmp_path / "slo.json"
    slo.write_text(json.dumps({"rules": [
        {"name": "floor", "metric": "steps_per_s", "min": 1.0}]}))
    before = open(tmp_path / "events.jsonl", "rb").read()
    report = check_run(str(tmp_path), str(slo))
    assert report["violations"] == 0
    # a clean run's stream stays BIT-IDENTICAL (fixture safety)
    assert open(tmp_path / "events.jsonl", "rb").read() == before


def test_check_run_violation_durable_and_idempotent(tmp_path):
    _write_run(tmp_path, kl_rows=[[0.5, 0.5], [0.5, 0.01]])
    slo = tmp_path / "slo.json"
    slo.write_text(json.dumps({
        "rules": [{"name": "floor", "metric": "steps_per_s", "min": 1e9}],
        "transitions": {"kl_threshold_nats": 0.05},
    }))
    report = check_run(str(tmp_path), str(slo))
    assert report["violations"] == 1
    assert report["written"] == {"alerts": 1, "transitions": 1}
    # durable: the events are ON the stream, tagged with their source
    alerts = list(read_events(str(tmp_path), types=("alert",)))
    transitions = list(read_events(str(tmp_path), types=("transition",)))
    assert alerts[0]["rule"] == "floor" and alerts[0]["source"] == "check"
    assert alerts[0]["budget"] == 1e9 and alerts[0]["tags"] == {"src": "slo"}
    assert transitions[0]["channel"] == 1
    assert transitions[0]["direction"] == "down"
    assert transitions[0]["threshold_nats"] == 0.05
    # idempotent: re-checking writes nothing new
    again = check_run(str(tmp_path), str(slo))
    assert again["written"] == {"alerts": 0, "transitions": 0}
    assert len(list(read_events(str(tmp_path), types=("alert",)))) == 1
    # and the durable residue shows up in summarize + compare's view
    from dib_tpu.telemetry.summary import summarize

    s = summarize(str(tmp_path))
    assert s["alerts"] == {"count": 1, "by_rule": {"floor": 1}}
    assert s["transitions"]["count"] == 1
    assert s["transitions"]["down"] == 1


def test_check_cli_exit_codes_in_process(tmp_path, capsys):
    _write_run(tmp_path)
    clean = tmp_path / "clean.json"
    clean.write_text(json.dumps({"rules": [
        {"name": "floor", "metric": "steps_per_s", "min": 1.0}]}))
    violated = tmp_path / "violated.json"
    violated.write_text(json.dumps({"rules": [
        {"name": "floor", "metric": "steps_per_s", "min": 1e9}]}))
    assert telemetry_main(["check", str(tmp_path), "--slo",
                           str(clean)]) == 0
    assert telemetry_main(["check", str(tmp_path), "--slo",
                           str(violated)]) == 1
    err = capsys.readouterr().err
    assert "SLO violation" in err
    # unusable operands: exit 2, distinct from the violation verdict
    assert telemetry_main(["check", str(tmp_path / "nope"), "--slo",
                           str(clean)]) == 2
    bad_slo = tmp_path / "bad.json"
    bad_slo.write_text(json.dumps({"rules": []}))
    assert telemetry_main(["check", str(tmp_path), "--slo",
                           str(bad_slo)]) == 2


def test_check_cli_subprocess(tmp_path):
    """Each seeded violation kind exits nonzero through the real CLI."""
    _write_run(tmp_path / "run")
    cases = {
        "steps_floor": {"name": "f", "metric": "steps_per_s", "min": 1e9},
        "loss_ceiling": {"name": "f", "metric": "final_loss", "max": 0.0},
        "gap_required": {"name": "f", "metric": "heartbeats.max_gap_s",
                         "max": 1.0, "required": True},
    }
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    for label, rule in cases.items():
        slo = tmp_path / f"{label}.json"
        slo.write_text(json.dumps({"rules": [rule]}))
        proc = subprocess.run(
            [sys.executable, "-m", "dib_tpu", "telemetry", "check",
             str(tmp_path / "run"), "--slo", str(slo), "--no-write"],
            capture_output=True, text=True, timeout=120, env=env)
        assert proc.returncode == 1, (label, proc.stderr)
        assert json.loads(proc.stdout)["violations"] == 1
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps({"rules": [
        {"name": "f", "metric": "steps_per_s", "min": 1.0}]}))
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "check",
         str(tmp_path / "run"), "--slo", str(ok)],
        capture_output=True, text=True, timeout=120, env=env)
    assert proc.returncode == 0, proc.stderr


def test_no_write_flag_leaves_stream_untouched(tmp_path, capsys):
    _write_run(tmp_path)
    slo = tmp_path / "slo.json"
    slo.write_text(json.dumps({"rules": [
        {"name": "floor", "metric": "steps_per_s", "min": 1e9}]}))
    before = open(tmp_path / "events.jsonl", "rb").read()
    assert telemetry_main(["check", str(tmp_path), "--slo", str(slo),
                           "--no-write"]) == 1
    capsys.readouterr()
    assert open(tmp_path / "events.jsonl", "rb").read() == before


# ===================================================== committed fixture
def test_committed_fixture_passes_committed_slo():
    """THE tier-1 wiring the ISSUE asks for: `telemetry check` against
    the committed fixture stream under the committed SLO.json exits 0 —
    and, being clean, writes nothing into the committed fixture."""
    before = open(os.path.join(FIXTURE_RUN, "events.jsonl"), "rb").read()
    with pytest.warns(UserWarning, match="torn event line"):
        report = check_run(FIXTURE_RUN, os.path.join(REPO, "SLO.json"))
    assert report["violations"] == 0
    assert report["written"] == {"alerts": 0, "transitions": 0}
    after = open(os.path.join(FIXTURE_RUN, "events.jsonl"), "rb").read()
    assert after == before
    # the TPU-guarded rules actually APPLIED to this tpu-labeled fixture
    by_name = {r["rule"]: r for r in report["rules"]}
    assert by_name["north_star_mfu_floor"]["status"] == "ok"
    assert by_name["north_star_steps_per_s_floor"]["status"] == "ok"


# ================================================================ live SLO
def test_live_engine_alerts_through_tail(tmp_path):
    """tail --slo: the live engine writes the same durable events the
    terminal check does, while the run is still in flight."""
    from dib_tpu.telemetry.live import tail

    def write():
        with EventWriter(str(tmp_path), run_id="live") as w:
            w.run_start({"device_platform": "cpu"})
            w.chunk(epoch=10, steps=10, seconds=1.0, loss=1.0,
                    kl_per_feature=[0.5, 0.5], beta=0.1)
            w.chunk(epoch=20, steps=10, seconds=1.0, loss=1.0,
                    kl_per_feature=[0.5, 0.01], beta=0.2)
            w.run_end(status="ok")

    engine = SLOEngine({
        "rules": [{"name": "floor", "metric": "steps_per_s", "min": 1e9}],
        "transitions": {"kl_threshold_nats": 0.05},
    }, str(tmp_path))
    thread = threading.Thread(target=write)
    thread.start()
    tail(str(tmp_path), slo=engine, refresh_s=0.02, duration_s=30,
         out=io.StringIO(), ansi=False)
    thread.join()
    engine.close()
    assert [a["rule"] for a in engine.alerts] == ["floor"]
    assert len(engine.transitions) == 1
    alerts = list(read_events(str(tmp_path), types=("alert",)))
    assert alerts and alerts[0]["source"] == "tail"
    transitions = list(read_events(str(tmp_path), types=("transition",)))
    assert transitions[0]["channel"] == 1
    # a terminal re-check sees the live engine's residue: idempotent
    slo = tmp_path / "slo.json"
    slo.write_text(json.dumps({
        "rules": [{"name": "floor", "metric": "steps_per_s", "min": 1e9}],
        "transitions": {"kl_threshold_nats": 0.05}}))
    report = check_run(str(tmp_path), str(slo))
    assert report["written"] == {"alerts": 0, "transitions": 0}


def test_live_engine_steady_floor_skips_compile_chunk(tmp_path):
    """Review hardening: a steady_steps_per_s floor must not write a
    durable false alert off the compile-laden FIRST chunk — live
    evaluation mirrors summarize's steady-state exclusion (skip until a
    steady chunk lands), then fires on real steady data."""
    engine = SLOEngine({
        "rules": [{"name": "floor", "metric": "steady_steps_per_s",
                   "min": 100.0}],
    }, str(tmp_path))
    engine.observe({"type": "run_start", "run": "r", "t": 0.0,
                    "manifest": {}})
    # first chunk: compile-laden, 1 step/s — would false-fire naively
    engine.observe({"type": "chunk", "proc": 0, "epoch": 1, "steps": 10,
                    "seconds": 10.0, "t": 10.0})
    engine.flush()
    assert engine.alerts == []
    # steady chunk at 10 steps/s: now the floor legitimately fires
    engine.observe({"type": "chunk", "proc": 0, "epoch": 2, "steps": 10,
                    "seconds": 1.0, "t": 11.0})
    engine.flush()
    engine.close()
    assert [a["rule"] for a in engine.alerts] == ["floor"]
    (alert,) = read_events(str(tmp_path), types=("alert",))
    assert alert["value"] == pytest.approx(10.0)   # steady, not blended


def test_check_run_bare_filename_operand(tmp_path, monkeypatch):
    """Review hardening: `cd <run-dir> && telemetry check events.jsonl`
    must write the durable alert and exit 1, not crash on dirname('')."""
    _write_run(tmp_path)
    slo = tmp_path / "slo.json"
    slo.write_text(json.dumps({"rules": [
        {"name": "floor", "metric": "steps_per_s", "min": 1e9}]}))
    monkeypatch.chdir(tmp_path)
    assert telemetry_main(["check", "events.jsonl", "--slo",
                           "slo.json"]) == 1
    (alert,) = read_events(str(tmp_path), types=("alert",))
    assert alert["rule"] == "floor"


# ===================================================== serving SLO (ISSUE 10)
COMMITTED_SLO = os.path.join(REPO, "SLO.json")


def _sweep_record(**overrides) -> dict:
    """A minimal serve_async_loadgen_sweep bench one-liner the serve
    rules evaluate (the committed-record shape, small)."""
    record = {
        "metric": "serve_async_loadgen_sweep",
        "unit": "req_per_s",
        "value": 1597.7,
        "response_cache_hit_frac": 0.999,
        "quota_rejected_frac": 0.0,
        "baseline_req_per_s": 370.0,
    }
    record.update(overrides)
    return record


def test_serve_sweep_rules_exit_codes(tmp_path):
    """`telemetry check` exit codes for each new serving rule, against
    the COMMITTED SLO.json: clean record -> 0; a throughput regression,
    a cold cached-path, and an over-quota tenant mix each -> 1 with the
    matching rule violated."""
    cases = {
        "clean": (_sweep_record(), 0, None),
        "req_floor": (_sweep_record(value=900.0), 1,
                      "serve_req_per_s_floor"),
        "cache_hit": (_sweep_record(response_cache_hit_frac=0.5), 1,
                      "serve_cache_hit_floor"),
        "quota": (_sweep_record(quota_rejected_frac=0.05), 1,
                  "serve_quota_rejection_ceiling"),
    }
    for label, (record, want_rc, rule) in cases.items():
        path = tmp_path / f"{label}.json"
        path.write_text(json.dumps(record))
        report = check_run(str(path), COMMITTED_SLO)
        assert (1 if report["violations"] else 0) == want_rc, (label, report)
        if rule is not None:
            violated = [r["rule"] for r in report["rules"]
                        if r["status"] == "violated"]
            assert violated == [rule], (label, violated)
        assert telemetry_main(["check", str(path), "--slo",
                               COMMITTED_SLO]) == want_rc


def test_serve_rules_skip_non_serving_operands():
    """The when-guard keeps the serving rules off every other record
    kind: the committed training fixture and the north-star bench lines
    must not trip them."""
    report = check_run(FIXTURE_RUN, COMMITTED_SLO, write=False)
    serving_rows = {r["rule"]: r for r in report["rules"]
                    if r["rule"].startswith("serve_")}
    for name in ("serve_req_per_s_floor", "serve_cache_hit_floor",
                 "serve_quota_rejection_ceiling"):
        assert serving_rows[name]["status"] == "skipped"


def test_committed_serve_async_bench_passes_committed_slo():
    """The committed BENCH_SERVE_ASYNC_CPU.json + SLO.json pair stays
    green: the acceptance evidence is re-validated on every run."""
    record_path = os.path.join(REPO, "BENCH_SERVE_ASYNC_CPU.json")
    report = check_run(record_path, COMMITTED_SLO)
    assert report["violations"] == 0, report
    by_rule = {r["rule"]: r for r in report["rules"]}
    assert by_rule["serve_req_per_s_floor"]["status"] == "ok"
    assert by_rule["serve_cache_hit_floor"]["status"] == "ok"
    assert by_rule["serve_quota_rejection_ceiling"]["status"] == "ok"
    # the headline actually clears 3x the PR 3 baseline
    with open(record_path) as f:
        record = json.load(f)
    assert record["value"] >= 3 * record["baseline_req_per_s"]


def test_serve_stream_rejection_rule(tmp_path):
    """The stream-level rejection guard: a serving stream whose request
    spans are >5% quota rejections violates; a clean mix passes; streams
    without request spans skip."""
    from dib_tpu.telemetry import Tracer, runtime_manifest

    def write_stream(directory, quota, ok):
        writer = EventWriter(str(directory))
        writer.run_start(runtime_manifest(extra={"mode": "serve"}))
        tracer = Tracer(writer)
        for _ in range(ok):
            tracer.add("request", 0.002, op="predict", status="ok", rows=1,
                       tenant="polite")
        for _ in range(quota):
            tracer.add("request", 0.0001, op="predict", status="quota",
                       rows=0, tenant="greedy")
        writer.run_end(status="ok")
        writer.close()

    write_stream(tmp_path / "noisy", quota=10, ok=10)
    report = check_run(str(tmp_path / "noisy"), COMMITTED_SLO,
                       write=False)
    violated = [r["rule"] for r in report["rules"]
                if r["status"] == "violated"]
    assert "serve_stream_rejection_ceiling" in violated

    write_stream(tmp_path / "clean", quota=0, ok=20)
    report = check_run(str(tmp_path / "clean"), COMMITTED_SLO,
                       write=False)
    by_rule = {r["rule"]: r for r in report["rules"]}
    assert by_rule["serve_stream_rejection_ceiling"]["status"] == "ok"


# =========================================================== streaming rules
def _write_deployer_stream(directory, *, indices=(0, 1, 2), rollbacks=0,
                           latency_s=0.5, request_ms=None):
    """A stream_deploy-shaped stream: deploy decisions per publish index
    (the streaming SLO surface), optionally with serving request spans."""
    from dib_tpu.telemetry import Tracer, runtime_manifest

    writer = EventWriter(str(directory))
    writer.run_start(runtime_manifest(extra={"mode": "stream_deploy"}))
    for n, index in enumerate(indices):
        writer.deploy(publish_id=f"pub-{index:08d}", action="promoted",
                      index=index, latency_s=latency_s)
    for n in range(rollbacks):
        writer.deploy(publish_id=f"pub-bad-{n}", action="rolled_back",
                      index=max(indices, default=-1) + 1 + n,
                      latency_s=latency_s, error="canary: non-finite")
    if request_ms is not None:
        tracer = Tracer(writer)
        for _ in range(10):
            tracer.add("request", request_ms / 1e3, op="predict",
                       status="ok", rows=1, tenant="t0")
    writer.run_end(status="ok")
    writer.close()


def test_streaming_rules_clean_deployer_stream_exits_zero(tmp_path):
    """A healthy deployer stream (every publish decided once, fast,
    one rollback allowed for the deliberate canary drill) passes the
    committed SLO.json in-process."""
    _write_deployer_stream(tmp_path, indices=(0, 1, 2), rollbacks=1,
                           latency_s=2.5, request_ms=150.0)
    assert telemetry_main(["check", str(tmp_path), "--slo",
                           COMMITTED_SLO, "--no-write"]) == 0
    report = check_run(str(tmp_path), COMMITTED_SLO, write=False)
    by_rule = {r["rule"]: r for r in report["rules"]}
    assert by_rule["stream_lost_publish_max"]["status"] == "ok"
    assert by_rule["stream_rollback_ceiling"]["status"] == "ok"
    assert by_rule["stream_publish_to_serve_p99_ceiling"]["status"] == "ok"
    # the mode guard routes the fleet's latency to the streaming ceiling,
    # not the dedicated-host 20 ms rules (a hot swap co-hosts compiles
    # with traffic by design)
    assert by_rule["stream_serve_p99_ceiling"]["status"] == "ok"
    assert by_rule["serve_p99_ceiling"]["status"] == "skipped"
    assert by_rule["serve_uncached_p99_ceiling"]["status"] == "skipped"


def test_untagged_serving_stream_stays_gated_by_dedicated_p99(tmp_path):
    """A serving stream whose run_start manifest never tagged a `mode`
    (e.g. a DIBServer driven via the Python API) must STILL trip the
    page-severity p99 ceiling — the stream_deploy carve-out is an
    exclusion, not an inclusion list."""
    from dib_tpu.telemetry import Tracer, runtime_manifest

    writer = EventWriter(str(tmp_path))
    writer.run_start(runtime_manifest())          # no mode tag
    tracer = Tracer(writer)
    for _ in range(10):
        tracer.add("request", 0.5, op="predict", status="ok", rows=1,
                   tenant="t0")
    writer.run_end(status="ok")
    writer.close()
    report = check_run(str(tmp_path), COMMITTED_SLO, write=False)
    by_rule = {r["rule"]: r for r in report["rules"]}
    assert by_rule["serve_p99_ceiling"]["status"] == "violated"
    # the streaming ceiling stays scoped to tagged stream_deploy fleets
    assert by_rule["stream_serve_p99_ceiling"]["status"] == "skipped"


def test_streaming_rules_each_violation_kind(tmp_path):
    """Every streaming SLO rule fires on its own seeded breach."""
    cases = {
        "lost": (dict(indices=(0, 2)), "stream_lost_publish_max"),
        "rollbacks": (dict(rollbacks=2), "stream_rollback_ceiling"),
        "lag": (dict(latency_s=120.0),
                "stream_publish_to_serve_p99_ceiling"),
        "wedged": (dict(request_ms=5000.0), "stream_serve_p99_ceiling"),
    }
    for label, (spec, rule) in cases.items():
        directory = tmp_path / label
        _write_deployer_stream(directory, **spec)
        report = check_run(str(directory), COMMITTED_SLO, write=False)
        violated = [r["rule"] for r in report["rules"]
                    if r["status"] == "violated"]
        assert violated == [rule], (label, violated)
        assert telemetry_main(["check", str(directory), "--slo",
                               COMMITTED_SLO, "--no-write"]) == 1


def test_streaming_rules_skip_non_streaming_streams():
    """Streams without publish/deploy events skip every streaming rule —
    the committed fixture stays exit 0 (pinned above) and reports the
    streaming rules as skipped."""
    report = check_run(FIXTURE_RUN, COMMITTED_SLO, write=False)
    by_rule = {r["rule"]: r for r in report["rules"]}
    for rule in ("stream_publish_to_serve_p99_ceiling",
                 "stream_rollback_ceiling", "stream_lost_publish_max",
                 "stream_serve_p99_ceiling"):
        assert by_rule[rule]["status"] == "skipped", rule


def test_streaming_lost_publish_pages_via_subprocess(tmp_path):
    """The page-severity invariant breach exits 1 through the real CLI
    against the committed SLO.json."""
    _write_deployer_stream(tmp_path / "run", indices=(0, 2))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "check",
         str(tmp_path / "run"), "--slo", COMMITTED_SLO, "--no-write"],
        capture_output=True, text=True, timeout=120, env=env)
    assert proc.returncode == 1, proc.stderr
    report = json.loads(proc.stdout)
    violated = [r["rule"] for r in report["rules"]
                if r["status"] == "violated"]
    assert violated == ["stream_lost_publish_max"]
    assert report["violations"] == 1


# ================================================== study rules (ISSUE 15)
def _write_study_stream(directory, *, rounds=2, max_rounds=4,
                        verdict="converged"):
    """A synthetic study-controller stream (dib_tpu/study events) with
    the violation knobs the two study SLO rules gate."""
    with EventWriter(str(directory), run_id="study-slo") as writer:
        writer.run_start({"mode": "study"})
        for r in range(rounds):
            writer.study(study_id="s", action="submit", round=r,
                         job_id=f"job-{r}", units=4,
                         budget_spent=4 * (r + 1), budget_max=40)
            writer.study(study_id="s", action="round", round=r,
                         estimates={"0": 0.3},
                         deltas_decades={"0": 0.01}, units=4,
                         budget_spent=4 * (r + 1), budget_max=40,
                         max_rounds=max_rounds)
        writer.study(study_id="s", action=verdict, verdict=verdict,
                     reason="synthetic", budget_spent=4 * rounds,
                     budget_max=40, max_rounds=max_rounds)
        writer.run_end(status="ok")


def test_study_rules_clean_converged_stream_exits_zero(tmp_path):
    _write_study_stream(tmp_path / "run")
    report = check_run(str(tmp_path / "run"), COMMITTED_SLO, write=False)
    by_rule = {r["rule"]: r for r in report["rules"]}
    assert by_rule["study_rounds_ceiling"]["status"] == "ok"
    assert by_rule["study_unconverged_max"]["status"] == "ok"
    assert telemetry_main(["check", str(tmp_path / "run"), "--slo",
                           COMMITTED_SLO, "--no-write"]) == 0


def test_study_rules_each_violation_kind(tmp_path):
    cases = {
        "runaway": (dict(rounds=5, max_rounds=3),
                    "study_rounds_ceiling"),
        "unconverged": (dict(verdict="unconverged"),
                        "study_unconverged_max"),
    }
    for label, (spec, rule) in cases.items():
        directory = tmp_path / label
        _write_study_stream(directory, **spec)
        report = check_run(str(directory), COMMITTED_SLO, write=False)
        violated = [r["rule"] for r in report["rules"]
                    if r["status"] == "violated"]
        assert violated == [rule], (label, violated)
        assert telemetry_main(["check", str(directory), "--slo",
                               COMMITTED_SLO, "--no-write"]) == 1


def test_study_rules_skip_non_study_streams():
    report = check_run(FIXTURE_RUN, COMMITTED_SLO, write=False)
    by_rule = {r["rule"]: r for r in report["rules"]}
    for rule in ("study_rounds_ceiling", "study_unconverged_max"):
        assert by_rule[rule]["status"] == "skipped", rule


def test_study_runaway_pages_via_subprocess(tmp_path):
    """The page-severity runaway-rounds breach exits 1 through the real
    CLI against the committed SLO.json."""
    _write_study_stream(tmp_path / "run", rounds=5, max_rounds=3)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "check",
         str(tmp_path / "run"), "--slo", COMMITTED_SLO, "--no-write"],
        capture_output=True, text=True, timeout=120, env=env)
    assert proc.returncode == 1, proc.stderr
    report = json.loads(proc.stdout)
    violated = [r["rule"] for r in report["rules"]
                if r["status"] == "violated"]
    assert violated == ["study_rounds_ceiling"]


# ============================================== autopilot rules (ISSUE 19)
def _write_autopilot_stream(directory, *, breaker_trips=0,
                            drift_to_apply_s=30.0, applied=True):
    """A synthetic drift-autopilot stream (dib_tpu/autopilot events)
    with the violation knobs the autopilot SLO rules gate."""
    with EventWriter(str(directory), run_id="autopilot-slo") as writer:
        writer.run_start({"mode": "autopilot"})
        writer.autopilot(action="intent", round=2, study_id="drift-r0002")
        writer.autopilot(action="submitted", round=2,
                         study_id="drift-r0002")
        if applied:
            writer.autopilot(action="verdict", round=2,
                             verdict="converged")
            writer.autopilot(action="applied", round=2,
                             drift_to_apply_s=drift_to_apply_s)
        else:
            writer.autopilot(action="verdict", round=2, verdict="error")
            writer.autopilot(action="apply_skip", round=2)
        for _ in range(breaker_trips):
            writer.breaker(action="trip", consecutive=2, threshold=2)
        writer.run_end(status="ok")


def test_autopilot_rules_clean_stream_exits_zero(tmp_path):
    _write_autopilot_stream(tmp_path / "run")
    report = check_run(str(tmp_path / "run"), COMMITTED_SLO, write=False)
    by_rule = {r["rule"]: r for r in report["rules"]}
    assert by_rule["autopilot_breaker_trip_ceiling"]["status"] == "ok"
    assert by_rule["drift_to_apply_p99_ceiling"]["status"] == "ok"
    # the exactly-once gate is `when`-scoped to the committed chaos
    # record — live streams never trip it by accident
    assert by_rule["autopilot_duplicate_study_max"]["status"] == "skipped"
    assert telemetry_main(["check", str(tmp_path / "run"), "--slo",
                           COMMITTED_SLO, "--no-write"]) == 0


def test_autopilot_rules_each_violation_kind(tmp_path):
    cases = {
        "trips": (dict(breaker_trips=2),
                  "autopilot_breaker_trip_ceiling"),
        "latency": (dict(drift_to_apply_s=400.0),
                    "drift_to_apply_p99_ceiling"),
    }
    for label, (spec, rule) in cases.items():
        directory = tmp_path / label
        _write_autopilot_stream(directory, **spec)
        report = check_run(str(directory), COMMITTED_SLO, write=False)
        violated = [r["rule"] for r in report["rules"]
                    if r["status"] == "violated"]
        assert violated == [rule], (label, violated)
        assert telemetry_main(["check", str(directory), "--slo",
                               COMMITTED_SLO, "--no-write"]) == 1


def test_autopilot_latency_rule_skips_when_nothing_applied(tmp_path):
    """A breaker-open stream (drift detected, every study skipped or
    failed) carries no drift→apply percentile: the latency rule skips
    instead of inventing a number."""
    _write_autopilot_stream(tmp_path / "run", applied=False,
                            breaker_trips=1)
    report = check_run(str(tmp_path / "run"), COMMITTED_SLO, write=False)
    by_rule = {r["rule"]: r for r in report["rules"]}
    assert by_rule["drift_to_apply_p99_ceiling"]["status"] == "skipped"
    assert by_rule["autopilot_breaker_trip_ceiling"]["status"] == "ok"


def test_autopilot_rules_skip_non_autopilot_streams():
    report = check_run(FIXTURE_RUN, COMMITTED_SLO, write=False)
    by_rule = {r["rule"]: r for r in report["rules"]}
    for rule in ("autopilot_duplicate_study_max",
                 "autopilot_breaker_trip_ceiling",
                 "drift_to_apply_p99_ceiling"):
        assert by_rule[rule]["status"] == "skipped", rule


def test_autopilot_breaker_trips_fail_via_subprocess(tmp_path):
    """Back-to-back breaker trips exit 1 through the real CLI against
    the committed SLO.json."""
    _write_autopilot_stream(tmp_path / "run", breaker_trips=2)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "check",
         str(tmp_path / "run"), "--slo", COMMITTED_SLO, "--no-write"],
        capture_output=True, text=True, timeout=120, env=env)
    assert proc.returncode == 1, proc.stderr
    report = json.loads(proc.stdout)
    violated = [r["rule"] for r in report["rules"]
                if r["status"] == "violated"]
    assert violated == ["autopilot_breaker_trip_ceiling"]


def test_committed_study_record_passes_committed_slo():
    """STUDY_CPU.json is a valid `telemetry check` operand (the bench
    one-liner path) and holds the study budgets — in-process and via
    the real CLI."""
    record_path = os.path.join(REPO, "STUDY_CPU.json")
    report = check_run(record_path, COMMITTED_SLO, write=False)
    assert report["violations"] == 0
    by_rule = {r["rule"]: r for r in report["rules"]}
    assert by_rule["study_rounds_ceiling"]["status"] == "ok"
    assert by_rule["study_unconverged_max"]["status"] == "ok"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "check",
         record_path, "--slo", COMMITTED_SLO],
        capture_output=True, text=True, timeout=120, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
