"""Serving engine + micro-batcher + replica routing (docs/serving.md).

The load-bearing contract is SEMANTIC INVISIBILITY of batching: for the
same checkpoint and input, a padded micro-batch must produce bit-identical
(CPU, f32) results to the single-request path. Everything else — timeouts,
backpressure, error isolation, β routing — is the operational surface the
batcher promises around that.
"""

import threading
import time

import jax
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.models import DistributedIBModel
from dib_tpu.serve import (
    BatcherClosed,
    InferenceEngine,
    MicroBatcher,
    QueueFullError,
    ReplicaEntry,
    ReplicaRouter,
    RequestTimeout,
)


@pytest.fixture(scope="module")
def bundle():
    return get_dataset("boolean_circuit")


@pytest.fixture(scope="module")
def model(bundle):
    return DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )


@pytest.fixture(scope="module")
def params(bundle, model):
    x0 = np.asarray(bundle.x_train[:4], np.float32)
    return model.init(jax.random.key(0), x0, jax.random.key(1))


@pytest.fixture(scope="module")
def engine(model, params):
    return InferenceEngine(model, params, batch_buckets=(1, 4, 8))


@pytest.fixture(scope="module")
def rows(bundle):
    return np.asarray(bundle.x_valid[:8], np.float32)


# ------------------------------------------------------------------ engine
def test_padded_batch_bit_identical_to_single(engine, rows):
    """The acceptance contract: padding/bucketing is semantically
    invisible — full-batch results equal per-row results EXACTLY."""
    batch = engine.predict(rows[:6])          # pads 6 -> bucket 8
    for i in range(6):
        single = engine.predict(rows[i])      # bucket 1, no padding
        np.testing.assert_array_equal(single["prediction"][0],
                                      batch["prediction"][i])
        np.testing.assert_array_equal(single["kl_per_feature"][0],
                                      batch["kl_per_feature"][i])
    enc_batch = engine.encode(rows[:6])
    for i in range(6):
        enc_single = engine.encode(rows[i])
        np.testing.assert_array_equal(enc_single["mus"][0],
                                      enc_batch["mus"][i])
        np.testing.assert_array_equal(enc_single["logvars"][0],
                                      enc_batch["logvars"][i])


def test_engine_determinism_and_shapes(engine, rows):
    a = engine.predict(rows[:3])
    b = engine.predict(rows[:3])
    np.testing.assert_array_equal(a["prediction"], b["prediction"])
    assert a["prediction"].shape == (3, 1)
    assert a["kl_per_feature"].shape == (3, engine.num_features)
    enc = engine.encode(rows[:2])
    assert enc["mus"].shape == (2, engine.num_features, 2)
    # KL is a non-negative information quantity
    assert np.all(a["kl_per_feature"] >= 0)


def test_engine_bucket_selection(engine):
    assert engine.bucket_for(1) == 1
    assert engine.bucket_for(2) == 4
    assert engine.bucket_for(5) == 8
    assert engine.bucket_for(999) == 8   # top bucket; dispatch chunks


def test_engine_chunks_oversize_batches(engine, bundle):
    """Requests beyond the top bucket run in top-bucket chunks with
    results concatenated — and stay bit-identical to per-row dispatch."""
    big = np.asarray(bundle.x_valid[:19], np.float32)
    out = engine.predict(big)
    assert out["prediction"].shape[0] == 19
    single = engine.predict(big[17])
    np.testing.assert_array_equal(out["prediction"][17],
                                  single["prediction"][0])


def test_engine_rejects_bad_width(engine):
    with pytest.raises(ValueError, match="width"):
        engine.predict(np.zeros((2, 3), np.float32))


# ----------------------------------------------------------------- batcher
def test_batcher_results_match_engine_under_concurrency(engine, rows):
    """Thread-pool clients racing through the batcher get EXACTLY what a
    direct engine call would return — coalescing and padding never leak."""
    batcher = MicroBatcher(engine, max_batch=8, max_wait_ms=5.0)
    want = engine.predict(rows)
    results: dict[int, dict] = {}
    errors: list = []

    def client(i: int):
        try:
            results[i] = batcher(rows[i], timeout_s=30.0)
        except Exception as exc:   # pragma: no cover - fails the test below
            errors.append(exc)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    batcher.close()
    assert not errors
    assert sorted(results) == list(range(8))
    for i in range(8):
        np.testing.assert_array_equal(results[i]["prediction"][0],
                                      want["prediction"][i])
        np.testing.assert_array_equal(results[i]["kl_per_feature"][0],
                                      want["kl_per_feature"][i])


def test_batcher_coalesces_into_shared_buckets(engine, rows):
    """Concurrent single-row requests actually share micro-batches (the
    whole point of the batcher): with 8 clients and max_wait to spare,
    dispatches must number well below requests."""
    from dib_tpu.telemetry.metrics import MetricsRegistry

    registry = MetricsRegistry()
    batcher = MicroBatcher(engine, max_batch=8, max_wait_ms=50.0,
                           registry=registry)
    threads = [
        threading.Thread(target=lambda i=i: batcher(rows[i], timeout_s=30.0))
        for i in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    batcher.close()
    snapshot = registry.snapshot()
    assert snapshot["counters"]["serve.requests.ok"] == 8
    assert snapshot["counters"]["serve.batches"] < 8
    assert snapshot["histograms"]["serve.batch_rows"]["max"] > 1


class _SlowEngine:
    """Engine stub with a controllable stall (timeout/backpressure tests
    must not depend on real dispatch being slow)."""

    feature_width = 4
    max_bucket = 8

    def __init__(self, stall_s: float = 0.0):
        self.stall_s = stall_s
        self.release = threading.Event()

    def bucket_for(self, n: int) -> int:
        return 8

    def predict(self, x):
        if self.stall_s:
            time.sleep(self.stall_s)
        return {"prediction": np.asarray(x)[:, :1]}

    encode = predict


def test_batcher_request_timeout(engine, rows):
    """A request whose deadline passes while queued is completed with
    RequestTimeout and never dispatched."""
    slow = _SlowEngine(stall_s=0.3)
    batcher = MicroBatcher(slow, max_batch=1, max_wait_ms=0.0)
    # first request occupies the worker for ~0.3s...
    first = batcher.submit(np.zeros(4, np.float32), timeout_s=30.0)
    # ...second expires in the queue behind it
    second = batcher.submit(np.zeros(4, np.float32), timeout_s=0.01)
    assert first.result(10.0) is not None
    with pytest.raises(RequestTimeout):
        second.result(10.0)
    batcher.close()


def test_batcher_client_side_wait_timeout():
    slow = _SlowEngine(stall_s=0.5)
    batcher = MicroBatcher(slow, max_batch=1, max_wait_ms=0.0)
    request = batcher.submit(np.zeros(4, np.float32))
    with pytest.raises(RequestTimeout):
        request.result(0.01)    # result not ready within the client wait
    batcher.close()


def test_batcher_queue_full_backpressure():
    from dib_tpu.telemetry.metrics import MetricsRegistry

    registry = MetricsRegistry()
    slow = _SlowEngine(stall_s=0.2)
    batcher = MicroBatcher(slow, max_batch=1, max_wait_ms=0.0, max_queue=2,
                           registry=registry)
    submitted = [batcher.submit(np.zeros(4, np.float32))
                 for _ in range(2)]
    with pytest.raises(QueueFullError):
        for _ in range(8):   # worker may drain one; the bound must hold
            batcher.submit(np.zeros(4, np.float32))
            time.sleep(0)
    # shed load is VISIBLE: rejected requests land in the metrics
    assert registry.snapshot()["counters"]["serve.requests.rejected"] >= 1
    batcher.close()
    for request in submitted:
        request.result(10.0)


def test_batcher_fill_capped_at_one_for_oversize_requests(engine, rows, bundle):
    """A single request larger than the top bucket chunks inside the
    engine; the recorded fill ratio must stay an honest <= 1 fraction of
    the padded capacity actually allocated."""
    from dib_tpu.telemetry.metrics import MetricsRegistry

    registry = MetricsRegistry()
    batcher = MicroBatcher(engine, max_batch=8, max_wait_ms=0.0,
                           registry=registry)
    big = np.asarray(bundle.x_valid[:19], np.float32)   # top bucket is 8
    batcher(big, timeout_s=30.0)
    fills = registry.snapshot()["histograms"]["serve.batch_fill"]
    assert 0 < fills["max"] <= 1.0
    # 19 rows -> chunks 8+8+3 padded to 8+8+4 = 20 allocated rows
    assert fills["max"] == pytest.approx(19 / 20)
    batcher.close()


def test_batcher_rejects_malformed_at_submit(engine):
    batcher = MicroBatcher(engine, max_batch=4, max_wait_ms=0.0)
    with pytest.raises(ValueError, match="width"):
        batcher.submit(np.zeros(3, np.float32))
    with pytest.raises(ValueError, match="non-finite"):
        batcher.submit(np.full(engine.feature_width, np.nan, np.float32))
    with pytest.raises(ValueError, match="op"):
        batcher.submit(np.zeros(engine.feature_width, np.float32), op="nope")
    batcher.close()


class _FaultyEngine:
    """Fails any batch containing a poisoned row — per-request isolation
    must shield batch-mates."""

    feature_width = 4
    max_bucket = 8

    def bucket_for(self, n: int) -> int:
        return 8

    def predict(self, x):
        if np.any(np.asarray(x) > 100.0):
            raise RuntimeError("poisoned row")
        return {"prediction": np.asarray(x)[:, :1]}

    encode = predict


def test_batcher_error_isolation(monkeypatch):
    """One failing request in a coalesced batch must not fail its
    batch-mates: the batch is retried per-request, and only the guilty
    request carries the error."""
    batcher = MicroBatcher(_FaultyEngine(), max_batch=8, max_wait_ms=50.0)
    good1 = batcher.submit(np.ones(4, np.float32), timeout_s=30.0)
    bad = batcher.submit(np.full(4, 999.0, np.float32), timeout_s=30.0)
    good2 = batcher.submit(np.full(4, 2.0, np.float32), timeout_s=30.0)
    assert good1.result(10.0)["prediction"][0][0] == 1.0
    assert good2.result(10.0)["prediction"][0][0] == 2.0
    with pytest.raises(RuntimeError, match="poisoned"):
        bad.result(10.0)
    batcher.close()


def test_batcher_close_rejects_new_and_fails_queued():
    slow = _SlowEngine(stall_s=0.2)
    batcher = MicroBatcher(slow, max_batch=1, max_wait_ms=0.0)
    batcher.submit(np.zeros(4, np.float32))
    batcher.close()
    with pytest.raises(BatcherClosed):
        batcher.submit(np.zeros(4, np.float32))


def test_batcher_multirow_requests_split_correctly(engine, rows):
    batcher = MicroBatcher(engine, max_batch=8, max_wait_ms=1.0)
    want = engine.predict(rows[:5])
    got = batcher(rows[:5], timeout_s=30.0)
    np.testing.assert_array_equal(got["prediction"], want["prediction"])
    batcher.close()


# ---------------------------------------------------------------- replicas
def _entry(engine, index, beta_end=None):
    return ReplicaEntry(engine, MicroBatcher(engine, max_wait_ms=0.0),
                        index, beta_end=beta_end)


def test_router_round_robin(engine):
    router = ReplicaRouter([_entry(engine, 0), _entry(engine, 1),
                            _entry(engine, 2)])
    picks = [router.route().index for _ in range(6)]
    assert picks == [0, 1, 2, 0, 1, 2]
    router.close()


def test_router_beta_nearest_log(engine):
    router = ReplicaRouter([
        _entry(engine, 0, beta_end=0.01),
        _entry(engine, 1, beta_end=0.1),
        _entry(engine, 2, beta_end=1.0),
    ])
    assert router.route(beta=0.012).index == 0
    # log-space nearest: 0.32 is closer to 0.1 than to 1.0 in log β
    assert router.route(beta=0.31).index == 1
    assert router.route(beta=5.0).index == 2
    router.close()


def test_router_beta_requires_labels(engine):
    router = ReplicaRouter([_entry(engine, 0)])
    with pytest.raises(ValueError, match="label"):
        router.route(beta=0.5)
    router.close()


def test_router_from_sweep_serves_each_member(bundle, model):
    """β-sweep serving: each member's engine returns that member's params'
    outputs (bit-identical to the unstacked replica state)."""
    from dib_tpu.parallel import BetaSweepTrainer
    from dib_tpu.train import TrainConfig

    config = TrainConfig(batch_size=32, num_pretraining_epochs=1,
                         num_annealing_epochs=1, steps_per_epoch=1,
                         max_val_points=64)
    sweep = BetaSweepTrainer(model, bundle, config, 1e-4, [0.1, 1.0])
    keys = jax.random.split(jax.random.key(5), 2)
    states, _ = sweep.init(keys)
    router = ReplicaRouter.from_sweep(sweep, states, batch_buckets=(1, 4),
                                      max_wait_ms=0.0)
    assert [e.beta_end for e in router.entries] == [
        pytest.approx(0.1), pytest.approx(1.0)]
    x = np.asarray(bundle.x_valid[:2], np.float32)
    for r, entry in enumerate(router.entries):
        state_r = sweep.replica_state(states, r)
        want = InferenceEngine(model, state_r.params["model"],
                               batch_buckets=(4,)).predict(x)
        got = entry.batcher(x, timeout_s=30.0)
        np.testing.assert_array_equal(got["prediction"], want["prediction"])
    router.close()
