"""Profiling helpers: timers block on device work and report correctly."""

import jax
import jax.numpy as jnp
import numpy as np

from dib_tpu.utils import PhaseTimer, device_trace, steps_per_second, timed_blocked


@jax.jit
def _work(x):
    return jnp.sum(x @ x.T)


def test_phase_timer_accumulates_and_reports():
    timer = PhaseTimer()
    x = jnp.ones((64, 64))
    for _ in range(3):
        with timer.phase("matmul") as p:
            p.block_on(_work(x))
    with timer.phase("host"):
        np.zeros(10)
    report = timer.report()
    assert report["matmul"]["count"] == 3
    assert report["host"]["count"] == 1
    assert report["matmul"]["total_s"] >= 0.0
    assert abs(report["matmul"]["mean_s"] * 3 - report["matmul"]["total_s"]) < 1e-2
    json_str = timer.report_json()
    assert "matmul" in json_str


def test_timed_blocked_returns_result():
    x = jnp.ones((32, 32))
    out, dt = timed_blocked(_work, x)
    assert float(out) == 32.0 * 32.0 * 32.0
    assert dt > 0.0


def test_steps_per_second():
    x = jnp.ones((16, 16))
    rate, times = steps_per_second(_work, x, repeats=2, warmup=1)
    assert rate > 0.0 and len(times) == 2


def test_device_trace_noop_and_real(tmp_path):
    with device_trace(None):
        pass                                       # no-op path
    with device_trace(str(tmp_path / "trace")):
        jax.block_until_ready(_work(jnp.ones((8, 8))))
    # the profiler must have written something under the logdir
    assert any((tmp_path / "trace").rglob("*"))
