"""Multi-tenant study-fleet tests (ISSUE 20, docs/scheduling.md):
deficit-weighted fair share, admission control, per-tenant quotas, the
per-job circuit breaker, priority load shedding (``starved`` parking and
the watchdog's parked-pool gate), the multi-writer ``refresh`` path the
submit-only deployment rests on, the fleet-mode StudyController, the
multi-tenant telemetry rollup + SLO rows, and the committed
CHAOS_FLEET_STUDY.json / STUDY_FLEET_CPU.json artifact contracts.

Everything here is host-side and fast: fake runners, an injectable
clock, synthetic event streams. The real-training fleet paths (SIGKILL
chaos, the three-study demo) live in scripts/chaos_fleet_study.py and
scripts/study_fleet_demo.py, whose committed records these tests pin.
"""

from __future__ import annotations

import copy
import importlib.util
import json
import math
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from dib_tpu.sched import (  # noqa: E402
    JobSpec,
    Scheduler,
    WorkerPool,
    read_journal,
)
from dib_tpu.sched.cli import sched_main  # noqa: E402
from dib_tpu.sched.scheduler import (  # noqa: E402
    AdmissionRejected,
    FleetPolicy,
    TenantPolicy,
    parked_snapshot,
)
from dib_tpu.telemetry import EventWriter, runtime_manifest  # noqa: E402
from dib_tpu.telemetry.summary import (  # noqa: E402
    scheduler_rollup,
    telemetry_main,
)

_LN2 = math.log(2.0)


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _sched(tmp_path, name="fleet", policy=None, clock=None, **kwargs):
    return Scheduler(str(tmp_path / name), policy=policy,
                     clock=clock or time.time, **kwargs)


def _tenant_of(s, lease) -> str:
    unit = s.unit(lease.unit_id)["unit"]
    return s.status()["jobs"][unit.job_id]["tenant"]


# --------------------------------------------------------------- fair share
def test_fair_share_alternates_between_equal_tenants(tmp_path):
    """Equal-weight tenants split the fleet's attention 1:1 even when one
    submitted its whole backlog first — the anti-starvation core."""
    s = _sched(tmp_path)
    s.submit(JobSpec(betas=(0.1, 0.2, 0.3, 0.4), tenant="greedy"))
    s.submit(JobSpec(betas=(1.0, 2.0, 3.0, 4.0), tenant="polite"))
    order = [_tenant_of(s, s.acquire(f"w{i}")) for i in range(6)]
    # first grant breaks the 0-service tie FIFO (greedy enqueued first),
    # then the deficit ledger alternates strictly
    assert order == ["greedy", "polite", "greedy", "polite",
                     "greedy", "polite"]
    s.close()


def test_fair_share_weight_skews_service(tmp_path):
    """A weight-3 tenant accrues ~3x the service of a weight-1 tenant
    over a long acquire sequence."""
    policy = FleetPolicy(tenants={"heavy": TenantPolicy(weight=3.0),
                                  "light": TenantPolicy(weight=1.0)})
    s = _sched(tmp_path, policy=policy)
    s.submit(JobSpec(betas=tuple(float(i + 1) for i in range(12)),
                     tenant="heavy"))
    s.submit(JobSpec(betas=tuple(float(i + 1) for i in range(12)),
                     tenant="light"))
    grants = [_tenant_of(s, s.acquire(f"w{i}")) for i in range(8)]
    assert grants.count("heavy") == 6 and grants.count("light") == 2
    s.close()


def test_single_tenant_degenerates_to_global_fifo(tmp_path):
    s = _sched(tmp_path)
    s.submit(JobSpec(betas=(0.1, 1.0), seeds=(0, 1)))
    got = [s.acquire("w").unit_id for _ in range(4)]
    assert got == sorted(got)          # submission order, untouched
    s.close()


def test_tenant_max_leases_quota_caps_concurrency(tmp_path):
    """A tenant at its concurrent-lease quota is skipped — the other
    tenant drains; nothing is granted past the cap."""
    policy = FleetPolicy(tenants={"capped": TenantPolicy(max_leases=1)})
    s = _sched(tmp_path, policy=policy)
    s.submit(JobSpec(betas=(0.1, 0.2, 0.3), tenant="capped"))
    s.submit(JobSpec(betas=(1.0,), tenant="free"))
    first = s.acquire("w0")
    assert _tenant_of(s, first) == "capped"
    # capped is at quota: the next grants go to the other tenant, then dry
    second = s.acquire("w1")
    assert _tenant_of(s, second) == "free"
    assert s.acquire("w2") is None
    # completing the capped unit frees the quota slot
    assert s.complete(first, {"ok": 1}) is True
    third = s.acquire("w3")
    assert third is not None and _tenant_of(s, third) == "capped"
    s.close()


# ---------------------------------------------------------------- admission
def test_admission_reject_fleet_bound_is_journaled(tmp_path):
    policy = FleetPolicy(max_pending_units=3, admission_retry_s=7.5)
    s = _sched(tmp_path, policy=policy)
    s.submit(JobSpec(betas=(0.1, 0.2), tenant="a"))
    with pytest.raises(AdmissionRejected) as err:
        s.submit(JobSpec(betas=(1.0, 2.0), tenant="b"))
    assert err.value.tenant == "b"
    assert err.value.retry_after_s == 7.5
    records, _ = read_journal(s.directory)
    rejects = [r for r in records if r.get("kind") == "admission"]
    assert len(rejects) == 1 and rejects[0]["tenant"] == "b"
    assert s.status()["tenants"]["b"]["admission_rejected"] == 1
    # a fitting submit is still admitted
    s.submit(JobSpec(betas=(5.0,), tenant="b"))
    s.close()


def test_admission_reject_tenant_bound_spares_other_tenants(tmp_path):
    policy = FleetPolicy(
        tenants={"bounded": TenantPolicy(max_pending=2)})
    s = _sched(tmp_path, policy=policy)
    s.submit(JobSpec(betas=(0.1, 0.2), tenant="bounded"))
    with pytest.raises(AdmissionRejected):
        s.submit(JobSpec(betas=(0.3,), tenant="bounded"))
    # the bound is per-tenant: an unbounded tenant sails through
    s.submit(JobSpec(betas=tuple(float(i + 1) for i in range(8)),
                     tenant="open"))
    s.close()


def test_admission_rejects_survive_replay(tmp_path):
    policy = FleetPolicy(max_pending_units=1)
    s = _sched(tmp_path, policy=policy)
    s.submit(JobSpec(betas=(0.1,), tenant="a"))
    for _ in range(2):
        with pytest.raises(AdmissionRejected):
            s.submit(JobSpec(betas=(1.0,), tenant="b"))
    s.close()
    replayed = _sched(tmp_path)
    assert replayed.status()["tenants"]["b"]["admission_rejected"] == 2
    replayed.close()


# ------------------------------------------------------------------ breaker
def _fail_once(s, worker="w"):
    lease = s.acquire(worker)
    assert lease is not None
    return s.fail(lease, "poisoned")


def test_breaker_trips_probes_and_resets(tmp_path):
    """threshold consecutive failures quarantine the job; after the
    probe horizon ONE half-open probe is granted; its success closes
    the breaker durably (journaled reset)."""
    clock = Clock()
    policy = FleetPolicy(breaker_threshold=2, breaker_probe_after_s=30.0)
    s = _sched(tmp_path, policy=policy, clock=clock, backoff_base_s=0.0)
    s.submit(JobSpec(betas=(0.5,), retry_budget=10, tenant="mallory"))
    assert _fail_once(s) == "requeued"
    assert _fail_once(s) == "requeued"
    records, _ = read_journal(s.directory)
    trips = [r for r in records if r.get("kind") == "breaker"
             and r.get("action") == "trip"]
    assert len(trips) == 1 and trips[0]["consecutive"] == 2
    # quarantined: no grant inside the horizon
    assert s.acquire("w") is None
    clock.t += 31.0
    probe = s.acquire("w")
    assert probe is not None
    # the probe is exclusive: no second unit of the job leaks out
    assert s.acquire("w2") is None
    assert s.complete(probe, {"ok": 1}) is True
    records, _ = read_journal(s.directory)
    actions = [r["action"] for r in records if r.get("kind") == "breaker"]
    assert actions == ["trip", "probe", "reset"]
    s.close()


def test_breaker_failed_probe_retrips(tmp_path):
    clock = Clock()
    policy = FleetPolicy(breaker_threshold=2, breaker_probe_after_s=10.0)
    s = _sched(tmp_path, policy=policy, clock=clock, backoff_base_s=0.0)
    s.submit(JobSpec(betas=(0.5,), retry_budget=10))
    _fail_once(s)
    _fail_once(s)
    clock.t += 11.0
    probe = s.acquire("w")
    assert probe is not None
    assert s.fail(probe, "still poisoned") == "requeued"
    records, _ = read_journal(s.directory)
    actions = [r["action"] for r in records if r.get("kind") == "breaker"]
    assert actions == ["trip", "probe", "trip"]   # immediate re-trip
    assert s.acquire("w") is None                 # quarantined again
    s.close()


# ----------------------------------------------------------------- shedding
def test_set_capacity_parks_low_priority_and_clears(tmp_path):
    """Half the workers gone: the low class parks (``starved``), the
    high class drains, and the floor clears once the high class is
    terminal — zero lost units in either class."""
    s = _sched(tmp_path)
    s.submit(JobSpec(betas=(0.1, 0.2), tenant="filler", priority=0))
    s.submit(JobSpec(betas=(1.0, 2.0), tenant="urgent", priority=1))
    out = s.set_capacity(1, 2)
    assert out["floor"] == 1 and out["starved"] == 2
    status = s.status()
    assert status["tenants"]["filler"]["starved"] == 2
    assert status["counts"]["pending"] == 4       # parked still pending
    # only the high class is grantable
    for _ in range(2):
        lease = s.acquire("w")
        assert _tenant_of(s, lease) == "urgent"
        assert s.complete(lease, {"ok": 1}) is True
    assert s.acquire("w") is None and s.parked_only()
    # high class terminal -> the same reassessment clears the floor
    out = s.set_capacity(1, 2)
    assert out["floor"] is None and out["starved"] == 0
    records, _ = read_journal(s.directory)
    floors = [r["floor"] for r in records if r.get("kind") == "shed"]
    assert floors == [1, None]
    lease = s.acquire("w")
    assert lease is not None and _tenant_of(s, lease) == "filler"
    s.close()


def test_single_priority_class_never_parks(tmp_path):
    s = _sched(tmp_path)
    s.submit(JobSpec(betas=(0.1, 0.2), tenant="only"))
    out = s.set_capacity(1, 4)
    assert out["floor"] is None and out["starved"] == 0
    assert not s.parked_only()
    s.close()


def test_parked_snapshot_matches_live_state(tmp_path):
    """The watchdog's journal-only view agrees with the live scheduler:
    an all-parked queue is visible WITHOUT opening a writer — the
    terminal-progress gate that keeps a degraded fleet budget-free."""
    s = _sched(tmp_path)
    s.submit(JobSpec(betas=(0.1, 0.2), priority=0))
    s.submit(JobSpec(betas=(1.0,), priority=1))
    s.set_capacity(1, 2)
    lease = s.acquire("w")
    assert s.complete(lease, {"ok": 1}) is True
    s.close()
    snap = parked_snapshot(
        os.path.join(str(tmp_path / "fleet"), "journal.jsonl"))
    assert snap["nonterminal"] == 2
    assert snap["parked"] == 2 and snap["floor"] == 1


def test_pool_exits_promptly_when_everything_is_parked(tmp_path):
    """A bounded pool over an all-parked queue exits without burning its
    duration busy-polling, reporting ``parked`` so the watchdog's
    relaunch stays budget-free (the ISSUE-20 idle-fleet fix)."""
    s = _sched(tmp_path)
    s.submit(JobSpec(betas=(0.1, 0.2), priority=0))
    s.submit(JobSpec(betas=(1.0,), priority=1))
    s.set_capacity(1, 2)
    lease = s.acquire("w")
    s.complete(lease, {"ok": 1})
    assert s.parked_only()

    def runner(unit, heartbeat=None):
        raise AssertionError("parked units must never run")

    pool = WorkerPool(s, runner, num_workers=1, poll_s=0.01)
    t0 = time.time()
    out = pool.run(duration_s=30.0)
    assert time.time() - t0 < 10.0     # exited early, not at duration
    assert out["parked"] is True and out["starved"] == 2
    assert out["completed"] == 0 and out["drained"] is False
    s.close()


# ------------------------------------------------------------- multi-writer
def test_refresh_folds_foreign_writer_without_double_lease(tmp_path):
    """Two Scheduler instances share one journal (a submit-only
    controller and the fleet pool): refresh folds the peer's records and
    the lease guard holds across writers."""
    a = _sched(tmp_path, name="shared")
    b = Scheduler(str(tmp_path / "shared"))
    job = a.submit(JobSpec(betas=(0.1, 1.0), tenant="alice"))
    assert b.refresh() > 0
    assert b.status()["counts"]["pending"] == 2
    lease = b.acquire("pool-w0")
    a.refresh()
    # the peer sees the lease: the unit is not grantable twice
    assert a.status()["counts"]["leased"] == 1
    assert a.acquire("ctl-w0") is not None         # the OTHER unit
    assert a.acquire("ctl-w1") is None
    assert b.complete(lease, {"ok": 1}) is True
    a.refresh()
    assert a.status()["counts"]["done"] == 1
    assert a.status()["jobs"][job]["tenant"] == "alice"
    a.close()
    b.close()


# ------------------------------------------------------------------- CLI
def test_cli_policy_set_and_overbound_submit_exits_75(tmp_path, capsys):
    from dib_tpu.train.preempt import PREEMPT_EXIT_CODE

    d = str(tmp_path / "cli-fleet")
    assert sched_main(["policy", "--sched-dir", d, "--max-pending", "2",
                       "--admission-retry-s", "3.0",
                       "--tenant", "greedy=1:2:2"]) == 0
    shown = json.loads(capsys.readouterr().out)["policy"]
    assert shown["max_pending_units"] == 2
    assert shown["tenants"]["greedy"] == {
        "weight": 1.0, "max_leases": 2, "max_pending": 2}
    assert sched_main(["submit", "--sched-dir", d, "--betas", "0.1", "1.0",
                       "--tenant", "greedy"]) == 0
    capsys.readouterr()
    rc = sched_main(["submit", "--sched-dir", d, "--betas", "5.0",
                     "--tenant", "greedy"])
    assert rc == PREEMPT_EXIT_CODE
    reject = json.loads(capsys.readouterr().out)
    assert reject["rejected"] is True and reject["tenant"] == "greedy"
    assert reject["retry_after_s"] == 3.0


def test_cli_status_renders_tenant_rows(tmp_path, capsys):
    d = str(tmp_path / "cli-status")
    assert sched_main(["submit", "--sched-dir", d, "--betas", "0.1",
                       "--tenant", "alice", "--study", "s-1"]) == 0
    capsys.readouterr()
    assert sched_main(["status", "--sched-dir", d]) == 0
    out = capsys.readouterr().out
    assert "alice" in out


# ------------------------------------------------------- study fleet mode
def _write_history(base_dir: str, unit) -> dict:
    """Synthetic single-transition KL history at the fleet's unit dir —
    the _FakeSchedRunner shape from test_study.py."""
    x = (math.log10(unit.beta) - math.log10(0.3)) / 0.15
    kl_nats = np.asarray([1.0 / (1.0 + math.exp(4.0 * x))])
    udir = os.path.join(base_dir, "units", unit.unit_id.replace("/", "__"))
    os.makedirs(udir, exist_ok=True)
    path = os.path.join(udir, "history.npz")
    np.savez(path, kl_per_feature=(kl_nats / _LN2)[None, :],
             beta=np.asarray([unit.beta]), loss=np.asarray([0.1]),
             val_loss=np.asarray([0.1]))
    return {"beta": float(unit.beta), "seed": int(unit.seed),
            "history_path": path}


def test_study_fleet_mode_submits_polls_and_rebinds(tmp_path):
    """Submit-only end to end, in process: a stay-alive fleet pool
    thread drains what a fleet-bound StudyController submits; the
    controller converges without ever running a unit itself; the fleet
    binding is journaled so a bare resume re-enters fleet mode."""
    from dib_tpu.study.controller import StudyConfig, StudyController
    from dib_tpu.study.journal import read_study_journal

    fleet_dir = str(tmp_path / "fleet-live")
    fleet_sched = Scheduler(fleet_dir, lease_s=10.0)
    pool = WorkerPool(
        fleet_sched, lambda unit, heartbeat=None:
        _write_history(fleet_dir, unit),
        num_workers=2, poll_s=0.01, reap_every_s=0.05, stay_alive=True,
        idle_max_s=0.05)
    pool_thread = threading.Thread(
        target=pool.run, kwargs={"duration_s": 60.0}, daemon=True)
    pool_thread.start()
    study_dir = str(tmp_path / "study-fleet")
    config = StudyConfig(
        grid_start=0.01, grid_stop=10.0, grid_num=4, seeds=(0,),
        threshold_nats=0.5, tolerance_decades=0.2, min_refine_rounds=1,
        max_rounds=5, max_units=40, refine_num=4)
    try:
        controller = StudyController(
            study_dir, config=config, fleet=fleet_dir, tenant="alice",
            priority=1, poll_s=0.02)
        state = controller.run()
    finally:
        pool._stop.set()
        pool_thread.join(timeout=10.0)
    assert state["verdict"]["verdict"] == "converged"
    # the fleet binding is journaled with the study's fleet identity
    records, _ = read_study_journal(study_dir)
    bindings = [r for r in records if r.get("kind") == "fleet"]
    assert len(bindings) == 1
    assert bindings[0]["sched_dir"] == os.path.abspath(fleet_dir)
    assert bindings[0]["tenant"] == "alice"
    # every fleet job of this study carries the tenant/study identity
    fleet_status = Scheduler(fleet_dir)
    jobs = fleet_status.status()["jobs"]
    study_jobs = [j for j in jobs.values() if j["tenant"] == "alice"]
    assert study_jobs and all(j["status"] == "done" for j in study_jobs)
    fleet_status.close()
    # a flag-free resume rebinds from the journal (journal wins)
    resumed = StudyController(study_dir)
    resumed.replay()
    assert resumed.fleet == os.path.abspath(fleet_dir)
    assert resumed.tenant == "alice" and resumed.priority == 1


# ------------------------------------------------------------ rollup + SLO
def _granted(writer, tenant, wait_s, unit="j/u0"):
    writer.lease(unit=unit, action="granted", worker="w", lease="l",
                 job_id="j", expires_s=5.0, queue_wait_s=wait_s,
                 attempt=1, tenant=tenant)


def test_scheduler_rollup_builds_tenant_block():
    events = [
        {"type": "job", "action": "submitted", "job_id": "j1", "units": 2,
         "tenant": "a"},
        {"type": "job", "action": "submitted", "job_id": "j2", "units": 1,
         "tenant": "b"},
        {"type": "job", "action": "submitted", "job_id": "j3", "units": 1,
         "tenant": "c"},
        {"type": "job", "action": "rejected", "job_id": "admission:b",
         "tenant": "b", "units": 4},
        {"type": "lease", "action": "granted", "queue_wait_s": 0.5,
         "tenant": "a"},
        {"type": "lease", "action": "granted", "queue_wait_s": 1.0,
         "tenant": "b"},
        {"type": "lease", "action": "granted", "queue_wait_s": 2.0,
         "tenant": "c"},
        {"type": "job", "action": "unit_done", "job_id": "j1",
         "tenant": "a"},
    ]
    out = scheduler_rollup(events)
    assert out["tenants"]["a"]["jobs"] == 1
    assert out["tenants"]["a"]["units"] == 2
    assert out["tenants"]["a"]["units_done"] == 1
    assert out["tenants"]["b"]["admission_rejected"] == 1
    assert out["admission_reject_frac"] == pytest.approx(0.25, abs=1e-4)
    # nearest-rank median of p99s [0.5, 1.0, 2.0] is 1.0
    assert out["tenant_wait_p99_ratio"] == pytest.approx(2.0)


def test_scheduler_rollup_single_tenant_omits_fleet_keys():
    out = scheduler_rollup([
        {"type": "job", "action": "submitted", "job_id": "j", "units": 1},
        {"type": "lease", "action": "granted", "queue_wait_s": 0.5},
    ])
    assert "tenants" not in out
    assert "admission_reject_frac" not in out
    assert "tenant_wait_p99_ratio" not in out


def _fleet_stream(tmp_path, name, *, starving: bool, rejects: int) -> str:
    d = str(tmp_path / name)
    writer = EventWriter(d, run_id=name)
    writer.run_start(runtime_manifest(device_info=False))
    writer.job(job_id="j", action="submitted", units=3, tenant="a")
    writer.job(job_id="k", action="submitted", units=3, tenant="b")
    writer.job(job_id="l", action="submitted", units=3, tenant="c")
    for _ in range(rejects):
        writer.job(job_id="admission:c", action="rejected", tenant="c",
                   units=4, reason="queue full", retry_after_s=5.0)
    _granted(writer, "a", 0.1)
    _granted(writer, "b", 0.1)
    _granted(writer, "c", 50.0 if starving else 0.12)
    writer.run_end(status="ok")
    writer.close()
    return d


def test_slo_fleet_rows_gate_streams(tmp_path):
    """sched_starvation_ceiling pages on a starving tenant;
    sched_admission_reject_ceiling warns on sustained rejects; a fair
    multi-tenant stream passes both."""
    slo = os.path.join(REPO, "SLO.json")
    clean = _fleet_stream(tmp_path, "clean", starving=False, rejects=0)
    assert telemetry_main(["check", clean, "--slo", slo,
                           "--no-write"]) == 0

    starved = _fleet_stream(tmp_path, "starved", starving=True, rejects=0)
    assert telemetry_main(["check", starved, "--slo", slo,
                           "--no-write"]) == 1
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "check", starved,
         "--slo", slo, "--no-write"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=120)
    assert proc.returncode == 1
    assert "sched_starvation_ceiling" in proc.stdout

    flooded = _fleet_stream(tmp_path, "flooded", starving=False,
                            rejects=2)
    assert telemetry_main(["check", flooded, "--slo", slo,
                           "--no-write"]) == 1


# ----------------------------------------------------------- artifacts
ARTIFACT_CHAOS = os.path.join(REPO, "CHAOS_FLEET_STUDY.json")
ARTIFACT_DEMO = os.path.join(REPO, "STUDY_FLEET_CPU.json")


@pytest.fixture(scope="module")
def checker():
    spec = importlib.util.spec_from_file_location(
        "check_run_artifacts",
        os.path.join(REPO, "scripts", "check_run_artifacts.py"))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _problems(checker, tmp_path, record, name="ARTIFACT.json"):
    path = str(tmp_path / name)
    with open(path, "w") as f:
        json.dump(record, f)
    return checker.check_file(path)


def test_committed_fleet_artifacts_validate(checker):
    assert checker.check_file(ARTIFACT_CHAOS) == []
    assert checker.check_file(ARTIFACT_DEMO) == []


def test_committed_chaos_record_covers_the_drill_matrix():
    with open(ARTIFACT_CHAOS) as f:
        record = json.load(f)
    assert record["quick"] is False and record["all_passed"] is True
    drills = {d["drill"] for d in record["matrix"]}
    assert drills >= {"fleet_kill_resume", "greedy_flood_fairness",
                      "controller_kill_adopt", "worker_loss_degrade",
                      "breaker_trip_probe"}
    for row in record["matrix"]:
        assert row["zero_lost_units"] is True
        assert row["no_double_execution"] is True
        assert row["bit_identical_histories"] is True


def test_chaos_fleet_record_rejects_broken_shapes(checker, tmp_path):
    with open(ARTIFACT_CHAOS) as f:
        good = json.load(f)
    # a full record missing a required drill is rejected
    broken = copy.deepcopy(good)
    broken["matrix"] = [d for d in broken["matrix"]
                        if d["drill"] != "breaker_trip_probe"]
    assert any("breaker_trip_probe" in p
               for p in _problems(checker, tmp_path, broken))
    # an unasserted invariant is rejected
    broken = copy.deepcopy(good)
    broken["matrix"][0]["no_double_execution"] = False
    assert any("no_double_execution" in p
               for p in _problems(checker, tmp_path, broken))
    # a fairness ratio past the committed SLO budget is rejected
    broken = copy.deepcopy(good)
    for row in broken["matrix"]:
        if row["drill"] == "greedy_flood_fairness":
            row["fairness_ratio"] = 99.0
    assert any("fairness_ratio" in p
               for p in _problems(checker, tmp_path, broken))


def test_committed_demo_meets_the_fleet_acceptance():
    with open(ARTIFACT_DEMO) as f:
        record = json.load(f)
    assert record["metric"] == "study_fleet_demo"
    assert len(record["studies"]) >= 3
    assert sum(1 for s in record["studies"] if s["autopilot"]) >= 1
    assert all(s["verdict"] in ("converged", "no_transitions")
               for s in record["studies"])
    assert len({s["tenant"] for s in record["studies"]}) >= 3
    assert record["admission_reject_frac"] <= 0.01
    assert record.get("tenant_wait_p99_ratio", 0.0) <= 10.0


def test_study_fleet_demo_rejects_broken_shapes(checker, tmp_path):
    with open(ARTIFACT_DEMO) as f:
        good = json.load(f)
    # fewer than 3 studies
    broken = copy.deepcopy(good)
    broken["studies"] = broken["studies"][:2]
    assert any(">= 3" in p for p in _problems(checker, tmp_path, broken))
    # no autopilot-submitted study
    broken = copy.deepcopy(good)
    for s in broken["studies"]:
        s["autopilot"] = False
    assert any("autopilot" in p
               for p in _problems(checker, tmp_path, broken))
    # a dirty verdict
    broken = copy.deepcopy(good)
    broken["studies"][0]["verdict"] = "unconverged"
    assert any("verdict" in p for p in _problems(checker, tmp_path, broken))
    # admission rejects past the committed budget
    broken = copy.deepcopy(good)
    broken["admission_reject_frac"] = 0.5
    assert any("admission_reject_frac" in p
               for p in _problems(checker, tmp_path, broken))
    # a starving tenant ratio past the committed budget
    broken = copy.deepcopy(good)
    broken["tenant_wait_p99_ratio"] = 50.0
    assert any("tenant_wait_p99_ratio" in p
               for p in _problems(checker, tmp_path, broken))


# --------------------------------------------------------------- lint cov
def test_fleet_modules_stay_lint_covered():
    """Satellite: the thread-heavy fleet modules stay inside the
    host-sync/thread-shared-state lint perimeter, findings-free."""
    from dib_tpu.analysis import run_passes
    from dib_tpu.analysis.passes.host_sync import HostSyncPass

    for rel in ("dib_tpu/sched/scheduler.py", "dib_tpu/sched/pool.py",
                "dib_tpu/study/controller.py", "dib_tpu/autopilot/loop.py"):
        assert rel in HostSyncPass.target_modules
    files = [(os.path.join(REPO, rel), rel) for rel in (
        "dib_tpu/sched/scheduler.py", "dib_tpu/sched/pool.py",
        "dib_tpu/sched/cli.py", "dib_tpu/study/controller.py",
        "dib_tpu/study/cli.py", "dib_tpu/autopilot/loop.py",
        "dib_tpu/train/watchdog.py")]
    findings = run_passes(
        root=REPO, select=["host-sync", "thread-shared-state"],
        files=files)
    assert findings == [], [f.format() for f in findings]
