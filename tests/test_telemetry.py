"""Telemetry subsystem: event stream, summaries, regression gate, overhead.

Covers the durability contract (truncated-final-line tolerance from a
killed writer, concurrent supervisor+worker appends), the event schema
round-trip, ``summarize`` totals against a fixture stream, ``compare``
exit codes (the perf gate), process-index filtering, and the acceptance
bound that telemetry costs < 2% of boolean-workload steps/s.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dib_tpu.telemetry import (
    SCHEMA_VERSION,
    ChunkPhaseHooks,
    EventWriter,
    MetricsRegistry,
    compare,
    config_fingerprint,
    finalize_open_writers,
    read_events,
    runtime_manifest,
    summarize,
    telemetry_main,
    write_metrics,
)
from dib_tpu.train.hooks import TimedHook
from dib_tpu.utils.profiling import PhaseTimer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_fixture_run(directory, *, chunks=3, steps=100, seconds=2.0,
                      process_index=0, mitigations=0, run_id="fixture-run"):
    """A synthetic but schema-true run: known totals for summarize()."""
    with EventWriter(directory, run_id=run_id,
                     process_index=process_index) as w:
        w.run_start({
            "git_sha": "a" * 40,
            "device_kind": "cpu",
            "device_count": 1,
            "config_hash": config_fingerprint({"lr": 1e-3}),
        })
        for i in range(chunks):
            w.chunk(epoch=i + 1, steps=steps, seconds=seconds,
                    loss=1.0 - 0.1 * i, val_loss=1.1 - 0.1 * i,
                    beta=0.1 * (i + 1),
                    kl_per_feature=[0.5, 0.25, 0.25])
        w.mi_bounds(epoch=chunks, lower_bits=[0.8, 0.1], upper_bits=[0.9, 0.2])
        for _ in range(mitigations):
            w.mitigation(mtype="stall_kill", chunk_s=99.0)
        w.run_end(status="ok")
    return os.path.join(directory, "events.jsonl")


# ===================================================================== events
def test_event_schema_round_trip(tmp_path):
    path = write_fixture_run(str(tmp_path))
    events = list(read_events(path))
    # envelope on every line
    for e in events:
        assert e["v"] == SCHEMA_VERSION
        assert e["run"] == "fixture-run"
        assert e["proc"] == 0
        assert isinstance(e["t"], float) and isinstance(e["mono"], float)
    assert [e["type"] for e in events] == (
        ["run_start"] + ["chunk"] * 3 + ["mi_bounds", "run_end"]
    )
    # per-writer sequence numbers are gapless and ordered
    assert [e["seq"] for e in events] == list(range(len(events)))
    chunk = events[1]
    assert chunk["steps"] == 100 and chunk["seconds"] == 2.0
    assert chunk["steps_per_s"] == pytest.approx(50.0)
    assert chunk["kl_per_feature"] == [0.5, 0.25, 0.25]
    assert events[0]["manifest"]["git_sha"] == "a" * 40


def test_numpy_payloads_serialize(tmp_path):
    with EventWriter(str(tmp_path)) as w:
        w.chunk(epoch=np.int64(1), steps=np.int32(10), seconds=np.float64(1.0),
                kl_per_feature=np.arange(3, dtype=np.float32),
                loss=np.float32(0.5))
    (event,) = read_events(str(tmp_path))
    assert event["epoch"] == 1 and event["steps"] == 10
    assert event["kl_per_feature"] == [0.0, 1.0, 2.0]
    assert event["loss"] == pytest.approx(0.5)


def test_truncated_final_line_tolerated(tmp_path):
    """A killed writer leaves at most a torn FINAL line; reads survive it."""
    path = write_fixture_run(str(tmp_path))
    with open(path, "ab") as f:
        f.write(b'{"v": 1, "run": "fixture-run", "se')  # kill mid-append
    with pytest.warns(UserWarning, match="torn event line"):
        events = list(read_events(path))
    assert len(events) == 6  # the torn line is dropped, nothing else
    assert events[-1]["type"] == "run_end"
    # summarize over the torn file works too
    assert summarize(path)["total_steps"] == 300


def test_torn_interior_line_skipped_with_warning(tmp_path):
    """A watchdog kill tears a line MID-file (the supervisor and relaunched
    worker keep appending after it): the rest must stay readable."""
    path = write_fixture_run(str(tmp_path))
    raw = open(path, "rb").read().split(b"\n")
    raw[1] = b'{"v": 1, "run": "fixture-run", "se'  # SIGKILL mid-write
    with open(path, "wb") as f:
        f.write(b"\n".join(raw))
    with pytest.warns(UserWarning, match="torn event line"):
        events = list(read_events(path))
    assert len(events) == 5  # only the torn chunk line is lost
    assert events[-1]["type"] == "run_end"
    assert summarize(path)["total_steps"] == 200


def test_context_exit_emits_error_run_end(tmp_path):
    """A run that starts inside a `with` block and dies on an exception
    still ends its stream with run_end(status='error') — a crashed run is
    never indistinguishable from one still in flight."""
    with pytest.raises(RuntimeError):
        with EventWriter(str(tmp_path), run_id="r") as w:
            w.run_start({"config_hash": "x"})
            w.chunk(epoch=1, steps=10, seconds=1.0)
            raise RuntimeError("sweep diverged")
    events = list(read_events(str(tmp_path)))
    assert events[-1]["type"] == "run_end"
    assert events[-1]["status"] == "error"
    assert "RuntimeError: sweep diverged" in events[-1]["error"]
    assert summarize(str(tmp_path))["status"] == "error"


def test_finalize_open_writers(tmp_path):
    """Entry points' crash-path insurance: any started-but-unended stream
    gets a terminal record and its fd is closed; idempotent."""
    finalize_open_writers()  # clear any stray from earlier tests
    w = EventWriter(str(tmp_path), run_id="r")
    w.run_start({"config_hash": "x"})
    assert finalize_open_writers(error="OOM") == [w.path]
    assert finalize_open_writers() == []  # nothing left open
    events = list(read_events(str(tmp_path)))
    assert events[-1]["type"] == "run_end"
    assert events[-1]["status"] == "error" and events[-1]["error"] == "OOM"


def test_open_writer_convention(tmp_path):
    """None -> default dir, '' -> disabled, explicit dir wins; disabled
    also when the default itself is unset."""
    from dib_tpu.telemetry import open_writer

    w = open_writer(None, str(tmp_path / "default"))
    assert w is not None and w.path.startswith(str(tmp_path / "default"))
    w.close()
    w = open_writer(str(tmp_path / "explicit"), str(tmp_path / "default"))
    assert w is not None and w.path.startswith(str(tmp_path / "explicit"))
    w.close()
    assert open_writer("", str(tmp_path / "default")) is None
    assert open_writer(None, None) is None


def test_shared_run_id_single_process():
    from dib_tpu.telemetry import shared_run_id

    rid = shared_run_id()
    assert isinstance(rid, str) and "-" in rid and len(rid) > 10


def test_shared_run_id_env_pin(monkeypatch):
    """The watchdog supervisor pins DIB_TELEMETRY_RUN_ID so its mitigation
    events and every worker relaunch share ONE run id — otherwise --run-id
    scoping would drop the mitigations the reliability gate counts."""
    from dib_tpu.telemetry import shared_run_id

    monkeypatch.setenv("DIB_TELEMETRY_RUN_ID", "pinned-run")
    assert shared_run_id() == "pinned-run"


def test_finalize_skips_never_started_writers(tmp_path):
    """A writer opened but never run_start-ed has no forensics to point
    at: finalize closes it silently instead of logging an empty stream."""
    finalize_open_writers()  # clear strays
    w = EventWriter(str(tmp_path), run_id="r")
    assert finalize_open_writers(error="boom") == []
    assert w._fd is None  # closed all the same


def test_timed_hook_skips_and_names_through_adapters(tmp_path):
    """The phantom-invocation guard and name attribution must see through
    fan-out adapters (the CLI sweep path wraps PerReplicaHook around a
    combined-hook adapter of Every-gated hooks), not just Every."""
    from dib_tpu.cli import _CombinedHooks
    from dib_tpu.parallel.sweep import PerReplicaHook
    from dib_tpu.train.hooks import Every

    calls = []

    class Inner:
        def __call__(self, trainer, state, epoch):
            calls.append(epoch)

    fanout = PerReplicaHook(lambda r: _CombinedHooks([Every(100, Inner())]))
    with EventWriter(str(tmp_path), run_id="r") as w:
        timed = TimedHook(fanout, w)
        assert timed.name == "Inner"       # not PerReplicaHook/_CombinedHooks
        timed(None, None, 50)              # cadence miss: no phantom event
        assert not timed.seconds
    hook_events = [e for e in read_events(str(tmp_path))
                   if e["type"] == "hook"]
    assert hook_events == []


def test_timed_hook_getattr_no_recursion():
    """Attribute probes on a TimedHook whose __init__ hasn't run (pickle's
    __setstate__ lookup) must raise AttributeError, not recurse forever."""
    bare = TimedHook.__new__(TimedHook)
    with pytest.raises(AttributeError):
        bare.hook
    with pytest.raises(AttributeError):
        bare.__setstate__


def test_summarize_status_incomplete_without_run_end(tmp_path):
    """No terminal record for the last launch (SIGKILL / in flight) must
    surface as status='incomplete', never an earlier launch's 'ok'."""
    with EventWriter(str(tmp_path), run_id="r") as w:
        w.run_start({"config_hash": config_fingerprint({"lr": 1e-3})})
        w.chunk(epoch=1, steps=10, seconds=1.0)
    assert summarize(str(tmp_path))["status"] == "incomplete"
    # a finished first launch must not mask an unfinished relaunch
    write_fixture_run(str(tmp_path), run_id="r2")
    with EventWriter(str(tmp_path), run_id="r3") as w:
        w.run_start({"config_hash": config_fingerprint({"lr": 1e-3})})
        w.chunk(epoch=1, steps=10, seconds=1.0)
    assert summarize(str(tmp_path))["status"] == "incomplete"


def test_concurrent_writers_share_one_file(tmp_path):
    """Worker + watchdog supervisor append to the same events.jsonl."""
    worker = EventWriter(str(tmp_path), run_id="r", process_index=0)
    supervisor = EventWriter(str(tmp_path), run_id="r", process_index=0,
                             tags={"src": "supervisor"})
    worker.chunk(epoch=1, steps=10, seconds=1.0)
    supervisor.mitigation(mtype="stall_kill")
    worker.chunk(epoch=2, steps=10, seconds=1.0)
    worker.close()
    supervisor.close()
    events = list(read_events(str(tmp_path)))
    assert [e["type"] for e in events] == ["chunk", "mitigation", "chunk"]
    assert events[1]["tags"] == {"src": "supervisor"}
    # each writer keeps its own gapless sequence
    assert [e["seq"] for e in events if "tags" not in e] == [0, 1]


def test_process_index_filtering(tmp_path):
    write_fixture_run(str(tmp_path), process_index=0, chunks=2)
    write_fixture_run(str(tmp_path), process_index=1, chunks=3,
                      run_id="fixture-run-p1")
    assert len(list(read_events(str(tmp_path), process_index=1,
                                types=("chunk",)))) == 3
    assert len(list(read_events(str(tmp_path), process_index=0,
                                types=("chunk",)))) == 2
    assert summarize(str(tmp_path), process_index=0)["total_steps"] == 200
    assert summarize(str(tmp_path), process_index=1)["total_steps"] == 300
    assert summarize(str(tmp_path))["processes"] == [0, 1]


def test_summarize_run_id_filter(tmp_path):
    """A reused telemetry dir accumulates runs (bench's
    DIB_BENCH_TELEMETRY_DIR); run_id scopes the summary to one of them."""
    write_fixture_run(str(tmp_path), chunks=2, run_id="run-a")
    write_fixture_run(str(tmp_path), chunks=3, run_id="run-b")
    assert summarize(str(tmp_path), run_id="run-a")["total_steps"] == 200
    assert summarize(str(tmp_path), run_id="run-b")["total_steps"] == 300


def test_summarize_rejects_non_stream(tmp_path, capsys):
    """A bench one-liner or arbitrary JSON is not an event stream: clear
    error instead of a KeyError or an all-None garbage summary."""
    bogus = tmp_path / "BENCH.json"
    bogus.write_text(json.dumps({"metric": "sweep_minutes", "value": 1.0}))
    with pytest.raises(ValueError, match="none carry an event 'type'"):
        summarize(str(bogus))
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    with pytest.raises(ValueError, match="no telemetry events"):
        summarize(str(empty))
    # CLI: bad operand is exit 2, distinct from the regression verdict (1)
    assert telemetry_main(["summarize", str(bogus)]) == 2
    assert "not a telemetry stream" in capsys.readouterr().err


def test_compare_accepts_bench_line(tmp_path, capsys):
    """bench.py embeds its run's summary under a 'telemetry' key; such a
    line is a first-class compare operand."""
    run = write_fixture_run(str(tmp_path / "run"))
    bench_line = tmp_path / "BENCH.json"
    bench_line.write_text(json.dumps(
        {"metric": "sweep_minutes", "value": 1.0,
         "telemetry": summarize(run)}))
    assert telemetry_main(["compare", str(bench_line), str(run)]) == 0
    capsys.readouterr()


def test_summarize_warns_on_blended_configs(tmp_path):
    """Two invocations with DIFFERENT configs appended to one dir blend
    into garbage totals — summarize must say so (scope with run_id)."""
    with EventWriter(str(tmp_path), run_id="a") as w:
        w.run_start({"config_hash": config_fingerprint({"lr": 1e-3})})
        w.chunk(epoch=1, steps=10, seconds=1.0)
        w.run_end(status="ok")
    with EventWriter(str(tmp_path), run_id="b") as w:
        w.run_start({"config_hash": config_fingerprint({"lr": 1e-2})})
        w.chunk(epoch=1, steps=10, seconds=1.0)
        w.run_end(status="ok")
    with pytest.warns(UserWarning, match="distinct config hashes"):
        s = summarize(str(tmp_path))
    assert s["runs"] == ["a", "b"]
    # scoped: no warning, and totals cover one run only
    s = summarize(str(tmp_path), run_id="b")
    assert s["total_steps"] == 10 and "runs" not in s


def test_cli_run_id_scoping(tmp_path, capsys):
    """`--run-id` / `--run-id-a/-b` expose run scoping on the CLI, so the
    documented gate can reproduce bench's in-process scoped summary."""
    write_fixture_run(str(tmp_path), chunks=2, run_id="run-a")
    write_fixture_run(str(tmp_path), chunks=3, seconds=9.0, run_id="run-b")
    assert telemetry_main(["summarize", str(tmp_path),
                           "--run-id", "run-a"]) == 0
    assert json.loads(capsys.readouterr().out)["total_steps"] == 200
    # run-b is 3x slower: scoped compare must gate on it, self-compare not
    assert telemetry_main(["compare", str(tmp_path), str(tmp_path),
                           "--run-id-a", "run-a",
                           "--run-id-b", "run-b"]) == 1
    capsys.readouterr()
    assert telemetry_main(["compare", str(tmp_path), str(tmp_path),
                           "--run-id-a", "run-a",
                           "--run-id-b", "run-a"]) == 0


def test_summarize_multihost_counts_one_process(tmp_path):
    """SPMD: every process emits chunk events for the SAME training, so
    unfiltered totals must come from one process, not the sum."""
    write_fixture_run(str(tmp_path), process_index=0, chunks=2)
    write_fixture_run(str(tmp_path), process_index=1, chunks=2,
                      run_id="fixture-run-p1")
    s = summarize(str(tmp_path))
    assert s["total_steps"] == 200          # not 400
    assert s["launches"] == 1               # not 2
    assert s["steps_per_s"] == pytest.approx(50.0)
    assert s["processes"] == [0, 1]         # presence stays global


def test_runtime_manifest_provenance():
    manifest = runtime_manifest(config={"lr": 1e-3}, extra={"seed": 7})
    # the repo is a git checkout: the manifest must carry its SHA
    assert isinstance(manifest["git_sha"], str) and len(manifest["git_sha"]) == 40
    assert manifest["versions"]["jax"]
    assert manifest["device_count"] >= 1 and manifest["device_kind"]
    assert manifest["config_hash"] == config_fingerprint({"lr": 1e-3})
    assert manifest["seed"] == 7


def test_config_fingerprint_stable_and_discriminating():
    assert config_fingerprint({"a": 1, "b": 2}) == config_fingerprint(
        {"b": 2, "a": 1})
    assert config_fingerprint({"a": 1}) != config_fingerprint({"a": 2})


# ==================================================================== summary
def test_summarize_known_totals(tmp_path):
    path = write_fixture_run(str(tmp_path), chunks=3, steps=100, seconds=2.0,
                             mitigations=2)
    s = summarize(path)
    assert s["metric"] == "run_telemetry_summary"
    assert s["unit"] == "steps_per_s"
    assert s["total_steps"] == 300
    assert s["total_chunk_s"] == pytest.approx(6.0)
    assert s["steps_per_s"] == pytest.approx(50.0)
    # steady state drops each launch's first (compile-laden) chunk
    assert s["steady_steps_per_s"] == pytest.approx(200 / 4.0)
    assert s["num_chunks"] == 3 and s["launches"] == 1
    assert s["git_sha"] == "a" * 40
    assert s["final_loss"] == pytest.approx(0.8)
    assert s["final_total_kl"] == pytest.approx(1.0)
    assert s["final_mi_lower_bits_mean"] == pytest.approx(0.45)
    assert s["mitigations"] == {"stall_kill": 2}
    assert s["mitigations_total"] == 2
    assert s["status"] == "ok"


def test_compare_gates_and_directions():
    base = {"steps_per_s": 100.0, "final_loss": 1.0, "mitigations_total": 0}
    # 1% slower: inside the default 5% threshold
    ok, regressed = compare(base, dict(base, steps_per_s=99.0))
    assert not regressed and not ok["fields"]["steps_per_s"]["regressed"]
    # 20% slower: gate fires
    _, regressed = compare(base, dict(base, steps_per_s=80.0))
    assert regressed
    # loss regresses UP, not down
    _, regressed = compare(base, dict(base, final_loss=0.5))
    assert not regressed
    _, regressed = compare(base, dict(base, final_loss=1.5))
    assert regressed
    # ANY extra mitigation regresses, regardless of threshold
    _, regressed = compare(base, dict(base, mitigations_total=1))
    assert regressed
    # faster + fewer problems never regresses
    _, regressed = compare(
        dict(base, mitigations_total=3),
        dict(base, steps_per_s=200.0, mitigations_total=0))
    assert not regressed


def test_compare_gates_per_replica_lists_on_mean():
    """Sweep summaries carry [R] lists for final losses; the gate must not
    silently skip them."""
    base = {"final_loss": [1.0, 1.0, 1.0], "steps_per_s": 100.0}
    report, regressed = compare(base, dict(base, final_loss=[2.0, 2.1, 1.9]))
    assert regressed
    assert report["fields"]["final_loss"]["gated_on"] == "mean"
    _, regressed = compare(base, dict(base, final_loss=[1.0, 1.01, 0.99]))
    assert not regressed
    # unusable sides are reported as ungated, never crash
    report, regressed = compare(base, dict(base, final_loss="broken"))
    assert not regressed
    assert report["fields"]["final_loss"]["gated"] is False


def test_nonfinite_values_stay_strict_json_and_regress(tmp_path):
    """A diverged run (loss=NaN) must (a) write strict JSON any parser can
    read and (b) REGRESS in compare, not slip through an ungated row."""
    with EventWriter(str(tmp_path / "bad")) as w:
        w.chunk(epoch=1, steps=100, seconds=2.0, loss=float("nan"),
                kl_per_feature=[float("inf"), 0.5])
    raw = open(str(tmp_path / "bad" / "events.jsonl")).read()
    json.loads(raw, parse_constant=lambda c: pytest.fail(
        f"bare {c} token written"))
    (event,) = read_events(str(tmp_path / "bad"))
    assert event["loss"] == "NaN"
    assert event["kl_per_feature"] == ["Infinity", 0.5]

    s_bad = summarize(str(tmp_path / "bad"))
    assert s_bad["final_loss"] == "NaN"   # summary is strict JSON too
    json.dumps(s_bad, allow_nan=False)

    write_fixture_run(str(tmp_path / "good"))
    report, regressed = compare(summarize(str(tmp_path / "good")), s_bad)
    assert regressed
    assert report["fields"]["final_loss"]["reason"] == "candidate non-finite"
    # a non-finite BASELINE cannot gate, and must not crash
    _, regressed = compare(s_bad, summarize(str(tmp_path / "good")))
    assert not regressed


def test_compare_flags_config_mismatch():
    report, _ = compare({"config_hash": "aaaa", "steps_per_s": 1.0},
                        {"config_hash": "bbbb", "steps_per_s": 1.0})
    assert "not like-for-like" in report["note"]


def test_telemetry_main_exit_codes(tmp_path, capsys):
    a = tmp_path / "a"
    b = tmp_path / "b"
    write_fixture_run(str(a), seconds=2.0)
    write_fixture_run(str(b), seconds=4.0)  # half the steps/s: regression

    assert telemetry_main(["summarize", str(a)]) == 0
    record = json.loads(capsys.readouterr().out)
    assert record["steps_per_s"] == pytest.approx(50.0)

    assert telemetry_main(["compare", str(a), str(a)]) == 0
    capsys.readouterr()
    assert telemetry_main(["compare", str(a), str(b)]) == 1
    out = capsys.readouterr()
    assert json.loads(out.out)["regressed"] is True
    assert "REGRESSION" in out.err
    # a generous threshold lets the same diff pass
    assert telemetry_main(["compare", str(a), str(b),
                           "--threshold", "0.6"]) == 0


def test_cli_compare_gate_subprocess(tmp_path):
    """The acceptance gate end-to-end: `python -m dib_tpu telemetry compare`
    exits nonzero on an injected steps/s regression."""
    a = tmp_path / "a"
    b = tmp_path / "b"
    write_fixture_run(str(a), seconds=2.0)
    write_fixture_run(str(b), seconds=3.0)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ok = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "compare",
         str(a), str(a)],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env)
    assert ok.returncode == 0, ok.stderr[-2000:]
    bad = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "compare",
         str(a), str(b)],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env)
    assert bad.returncode == 1, bad.stderr[-2000:]
    assert json.loads(bad.stdout)["fields"]["steps_per_s"]["regressed"]


# ==================================================================== metrics
def test_metrics_registry_snapshot():
    reg = MetricsRegistry()
    reg.counter("steps").inc(50)
    reg.counter("steps").inc(25)
    reg.gauge("beta").set(0.3)
    hist = reg.histogram("chunk_s")
    for v in (1.0, 2.0, 3.0, 4.0):
        hist.record(v)
    snap = reg.snapshot()
    assert snap["counters"]["steps"] == 75
    assert snap["gauges"]["beta"] == pytest.approx(0.3)
    h = snap["histograms"]["chunk_s"]
    assert h["count"] == 4 and h["sum"] == pytest.approx(10.0)
    assert h["min"] == 1.0 and h["max"] == 4.0 and h["mean"] == 2.5
    assert h["p50"] == 3.0  # upper-median convention on the window
    with pytest.raises(ValueError):
        reg.counter("steps").inc(-1)


def test_write_metrics_single_process(tmp_path):
    reg = MetricsRegistry()
    reg.counter("chunks").inc()
    reg.gauge("beta").set(0.5)
    with EventWriter(str(tmp_path)) as w:
        assert write_metrics(reg, w) is True
    (event,) = read_events(str(tmp_path), types=("metrics",))
    (snap,) = event["snapshots"]
    assert snap["proc"] == 0
    assert snap["counters.chunks"] == 1.0
    assert snap["gauges.beta"] == 0.5


# ====================================================================== hooks
def test_timed_hook_measures_and_forwards(tmp_path):
    calls = []

    class Inner:
        records = ["sentinel"]

        def __call__(self, trainer, state, epoch):
            calls.append(epoch)

    with EventWriter(str(tmp_path)) as w:
        timed = TimedHook(Inner(), telemetry=w)
        timed(None, None, 5)
        timed(None, None, 10)
    assert calls == [5, 10]
    assert len(timed.seconds) == 2
    assert timed.records == ["sentinel"]  # attribute passthrough
    events = list(read_events(str(tmp_path), types=("hook",)))
    assert [e["epoch"] for e in events] == [5, 10]
    assert all(e["name"] == "Inner" for e in events)


def test_timed_hook_records_time_of_raising_hook(tmp_path):
    def bad_hook(trainer, state, epoch):
        raise RuntimeError("boom")

    with EventWriter(str(tmp_path)) as w:
        timed = TimedHook(bad_hook, telemetry=w, name="bad")
        with pytest.raises(RuntimeError):
            timed(None, None, 1)
    assert len(timed.seconds) == 1
    assert [e["name"] for e in read_events(str(tmp_path))] == ["bad"]


def test_timed_hook_names_unwrap_cadence_adapter(tmp_path):
    """Every instrumentation hook arrives wrapped as Every(n, hook); the
    event must name the inner hook or all time charges to 'Every'."""
    from dib_tpu.train.hooks import Every

    class MIHook:
        def __call__(self, trainer, state, epoch):
            pass

    with EventWriter(str(tmp_path)) as w:
        timed = TimedHook(Every(5, MIHook()), telemetry=w)
        timed(None, None, 5)
    assert timed.name == "MIHook"
    (event,) = read_events(str(tmp_path), types=("hook",))
    assert event["name"] == "MIHook"


def test_timed_hook_skips_non_firing_cadence_epochs(tmp_path):
    """Every(100, hook) at a gcd-50 chunk boundary fires nothing — no
    phantom ~0 s 'hook' event may dilute the hook's statistics."""
    from dib_tpu.train.hooks import Every

    calls = []
    with EventWriter(str(tmp_path)) as w:
        timed = TimedHook(Every(100, lambda t, s, e: calls.append(e)),
                          telemetry=w)
        timed(None, None, 50)    # cadence miss: silent
        timed(None, None, 100)   # fires
    assert calls == [100]
    assert len(timed.seconds) == 1
    events = list(read_events(str(tmp_path), types=("hook",)))
    assert [e["epoch"] for e in events] == [100]


def test_chunk_phase_hooks_unknown_baseline_skips_first_event(tmp_path):
    """A resumed run's restore epoch is unknown before fitting: the first
    interval is timed but NOT emitted (an epoch-0 baseline would count the
    pre-restore epochs as trained and inflate the gated steps/s)."""
    with EventWriter(str(tmp_path)) as w:
        phases = ChunkPhaseHooks(telemetry=w, steps_per_epoch=50,
                                 baseline_known=False)
        phases.start()  # re-anchors the clock, does NOT anchor the baseline
        states = np.zeros(2)
        phases.pre(None, states, 125)   # resumed from epoch 100: ambiguous
        phases.post(None, states, 125)
        phases.pre(None, states, 150)   # delta from 125: attributable
        phases.post(None, states, 150)
    chunks = list(read_events(str(tmp_path), types=("chunk",)))
    assert [c["epoch"] for c in chunks] == [150]
    assert chunks[0]["steps"] == 25 * 50
    # both intervals were still timed
    assert len(phases.timer.intervals["chunk"]) == 2


def test_chunk_phase_hooks_split_phases(tmp_path):
    with EventWriter(str(tmp_path)) as w:
        phases = ChunkPhaseHooks(telemetry=w, steps_per_epoch=50)
        phases.start()
        states = np.zeros(2)  # block_until_ready accepts host arrays
        phases.pre(None, states, 25)
        phases.post(None, states, 25)
        phases.pre(None, states, 50)
        phases.post(None, states, 50)
    timer = phases.timer
    assert len(timer.intervals["chunk"]) == 2
    assert len(timer.intervals["instrumentation"]) == 2
    chunks = list(read_events(str(tmp_path), types=("chunk",)))
    assert [c["epoch"] for c in chunks] == [25, 50]
    # steps derive from the epoch delta: 25 epochs x 50, then 25 x 50
    assert [c["steps"] for c in chunks] == [1250, 1250]
    hooks = list(read_events(str(tmp_path), types=("hook",)))
    assert all(h["name"] == "checkpoint_instrumentation" for h in hooks)


def test_watchdog_mirrors_mitigations_onto_event_stream(tmp_path):
    """supervise(telemetry=...) lands each mitigation on the stream AS IT
    HAPPENS, so a run killed mid-flight still carries its kill record."""
    import textwrap

    from dib_tpu.train.watchdog import WatchdogConfig, supervise

    worker = tmp_path / "worker.py"
    marker = str(tmp_path / "crashed_once")
    worker.write_text(textwrap.dedent(f"""
        import os, sys
        marker = {marker!r}
        if not os.path.exists(marker):
            open(marker, "w").close()
            sys.exit(3)              # simulated tunnel crash
        sys.exit(0)
    """))
    hb = str(tmp_path / "hb.json")
    with EventWriter(str(tmp_path), process_index=0,
                     tags={"src": "supervisor"}) as w:
        result = supervise([sys.executable, str(worker)], hb,
                           WatchdogConfig(poll_s=0.05, max_restarts=2),
                           telemetry=w)
    assert result["returncode"] == 0
    events = list(read_events(str(tmp_path), types=("mitigation",)))
    assert [e["mtype"] for e in events] == ["crash_restart"]
    assert events[0]["returncode"] == 3
    assert events[0]["tags"] == {"src": "supervisor"}
    # the mirrored list still behaves as the report's plain list
    assert [m["type"] for m in result["mitigations"]] == ["crash_restart"]


# ================================================================== overhead
def test_boolean_workload_telemetry_overhead_under_2pct(tmp_path):
    """Acceptance bound: PhaseTimer-measured steps/s with telemetry enabled
    within 2% of disabled on the boolean workload.

    Paired same-run design: back-to-back A/B fits on this host jitter by
    ~±13% (measured), two orders of magnitude above the overhead being
    bounded, so differencing two noisy wall-clocks cannot certify 2%.
    Instead both sides come from the SAME instrumented run: the disabled
    steps/s is the PhaseTimer-measured chunk wall-clock alone; the enabled
    steps/s adds the per-chunk emission cost (the only code the telemetry
    path inserts between chunks), measured directly on the run's own
    payload with real file writes.
    """
    import time

    import jax

    from dib_tpu.telemetry.events import device_memory_stats
    from dib_tpu.workloads.boolean import (
        BooleanTrainer,
        BooleanWorkloadConfig,
        fetch_boolean_circuit,
    )

    config = BooleanWorkloadConfig(num_steps=300, mi_every=100)
    trainer = BooleanTrainer(fetch_boolean_circuit(), config)
    trainer.fit(jax.random.key(0))  # compile warmup, unmeasured

    with EventWriter(str(tmp_path / "run")) as w:
        trainer.fit(jax.random.key(1), telemetry=w)
    chunks = list(read_events(str(tmp_path / "run"), types=("chunk",)))
    mi = list(read_events(str(tmp_path / "run"), types=("mi_bounds",)))
    spans = list(read_events(str(tmp_path / "run"), types=("span",)))
    assert len(chunks) == 3
    assert all(c["steps_per_s"] > 0 for c in chunks)
    # min: host contention noise is strictly one-sided (only ever slows)
    chunk_s = min(c["seconds"] for c in chunks)

    heartbeats = list(read_events(str(tmp_path / "run"),
                                  types=("heartbeat",)))
    boundary = [h for h in heartbeats if h["phase"] == "boundary"]
    assert len(boundary) == 3   # one per chunk, main thread

    # Per-chunk emission cost on the run's OWN payload: one chunk event,
    # one mi_bounds event, the two span events (chunk + mi_bounds), AND
    # the heartbeat traffic a chunk interval admits — the boundary beat
    # plus the mid-chunk daemon beats one chunk's wall-clock buys at the
    # default DIB_HEARTBEAT_S (the spans+heartbeats-enabled bound of the
    # acceptance criteria) — through a real EventWriter.
    from dib_tpu.telemetry.events import host_memory_stats
    from dib_tpu.telemetry.hooks import heartbeat_interval_s

    mid_beats_per_chunk = max(
        int(chunk_s / max(heartbeat_interval_s(), 1e-9)), 0) + 1
    reps = 200
    with EventWriter(str(tmp_path / "cost")) as w:
        t0 = time.perf_counter()
        for i in range(reps):
            w.chunk(epoch=chunks[0]["epoch"], steps=chunks[0]["steps"],
                    seconds=chunks[0]["seconds"], beta=chunks[0]["beta"],
                    loss=chunks[0]["loss"],
                    kl_per_feature=chunks[0]["kl_per_feature"],
                    memory=device_memory_stats(),
                    host_memory=host_memory_stats())
            w.mi_bounds(epoch=mi[0]["epoch"],
                        lower_bits=mi[0]["lower_bits"],
                        upper_bits=mi[0]["upper_bits"])
            for template in spans[:2]:
                w.span(name=template["name"], path=template["path"],
                       span_id=2 * i, parent_id=None,
                       seconds=template["seconds"])
            w.heartbeat(beat=2 * i, epoch=chunks[0]["epoch"],
                        phase="boundary",
                        intervals_s=boundary[-1].get("intervals_s") or [])
            for j in range(mid_beats_per_chunk):
                w.heartbeat(beat=2 * i + 1 + j, epoch=chunks[0]["epoch"],
                            phase="chunk",
                            interval_s=heartbeat_interval_s(),
                            phase_elapsed_s=1.234)
        emit_s = (time.perf_counter() - t0) / reps

    ratio = chunk_s / (chunk_s + emit_s)
    assert ratio >= 0.98, (
        f"telemetry overhead exceeds 2%: chunk {chunk_s * 1e3:.1f} ms, "
        f"emission {emit_s * 1e3:.3f} ms/chunk (steps/s ratio {ratio:.4f})"
    )


# ============================================================== CLI smoke run
def test_workload_cli_emits_event_stream(tmp_path, capsys):
    """The acceptance smoke run, in-process: a boolean workload run leaves
    an events.jsonl whose run_start manifest carries git SHA + device info
    and whose chunk records carry steps/s and per-feature KL."""
    from dib_tpu.cli import workload_main

    rc = workload_main([
        "boolean", "--telemetry-dir", str(tmp_path),
        "--set", "num_steps=40", "--set", "mi_every=20",
        "--set", "integration_hidden=(32,)", "--set", "batch_size=64",
    ])
    assert rc == 0
    result = json.loads(capsys.readouterr().out)
    assert result["final_accuracy"] >= 0.0

    events = list(read_events(str(tmp_path)))
    manifest = events[0]["manifest"]
    assert events[0]["type"] == "run_start"
    assert manifest["git_sha"] and manifest["device_kind"]
    assert manifest["workload"] == "boolean"
    assert manifest["config"]["num_steps"] == 40
    chunks = [e for e in events if e["type"] == "chunk"]
    assert len(chunks) == 2
    for c in chunks:
        assert c["steps_per_s"] > 0
        assert len(c["kl_per_feature"]) == 10  # one per circuit input
    assert any(e["type"] == "mi_bounds" for e in events)
    # end-of-fit metrics rollup (chunk-time histogram, step counter)
    (metrics,) = [e for e in events if e["type"] == "metrics"]
    assert metrics["snapshots"][0]["counters.steps"] == 40.0
    assert events[-1]["type"] == "run_end"

    s = summarize(str(tmp_path))
    assert s["total_steps"] == 40
    assert s["git_sha"] == manifest["git_sha"]
    assert s["metrics"]["histograms.chunk_s.count"] == 2.0


# ===================================================== heartbeat coverage
def test_summarize_heartbeat_coverage_and_silent_gap_gate(tmp_path):
    """summarize reports heartbeat coverage (count, boundary beats, max
    silent gap incl. the run_start/run_end edges) and compare gates on a
    silent-gap regression; streams WITHOUT heartbeats stay ungated
    instead of faking a zero gap."""
    a = tmp_path / "a"
    with EventWriter(str(a), run_id="hb-a") as w:
        w.run_start({"config_hash": "x"})
        base = w.emit("heartbeat", beat=1, epoch=0, phase="boundary",
                      intervals_s=[])["t"]
        for i, dt in enumerate((1.0, 2.0, 1.0)):
            base += dt
            record = {"beat": i + 2, "epoch": i, "phase": "chunk",
                      "interval_s": 1.0}
            w.emit("heartbeat", **record)
            # rewrite t: synthetic gaps without sleeping
        w.chunk(epoch=3, steps=30, seconds=3.0, loss=1.0)
        w.run_end(status="ok")
    # patch wall-clocks directly for deterministic gaps: 1s, 5s, 1s
    lines = [json.loads(line) for line in
             open(a / "events.jsonl").read().splitlines()]
    t0 = 1000.0
    stamps = {1: t0, 2: t0 + 1.0, 3: t0 + 6.0, 4: t0 + 7.0}
    beats_seen = 0
    for event in lines:
        if event["type"] == "run_start":
            event["t"] = t0
        elif event["type"] == "heartbeat":
            beats_seen += 1
            event["t"] = stamps[beats_seen]
        else:
            event["t"] = t0 + 7.5
    with open(a / "events.jsonl", "w") as f:
        for event in lines:
            f.write(json.dumps(event) + "\n")

    s = summarize(str(a))
    assert s["heartbeats"]["count"] == 4
    assert s["heartbeats"]["boundary_beats"] == 1
    assert s["heartbeats"]["interval_s"] == 1.0
    assert s["heartbeats"]["max_gap_s"] == pytest.approx(5.0)
    assert s["heartbeat_max_gap_s"] == pytest.approx(5.0)

    # candidate whose worst silent gap doubled: gated as a regression
    b_summary = dict(s, heartbeat_max_gap_s=10.0)
    report, regressed = compare(s, b_summary, threshold=0.05)
    assert regressed
    assert report["fields"]["heartbeat_max_gap_s"]["regressed"]

    # no heartbeats on either side: explicitly ungated, not zero-gap
    plain = tmp_path / "plain"
    write_fixture_run(str(plain))
    sp = summarize(str(plain))
    assert "heartbeats" not in sp
    report, regressed = compare(sp, sp)
    assert report["fields"]["heartbeat_max_gap_s"]["gated"] is False
    assert not regressed


def test_fit_emits_heartbeats_with_boundary_intervals(tmp_path, monkeypatch):
    """DIBTrainer.fit under telemetry: boundary beats at every chunk with
    trailing intervals (the watchdog's stall clock), mid-chunk beats from
    the daemon thread at the configured interval."""
    import jax

    from dib_tpu.data import get_dataset
    from dib_tpu.models import DistributedIBModel
    from dib_tpu.train import DIBTrainer, TrainConfig

    monkeypatch.setenv("DIB_HEARTBEAT_S", "0.05")
    bundle = get_dataset("boolean_circuit")
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(16,), integration_hidden=(16,),
        output_dim=bundle.output_dimensionality,
        embedding_dim=4, use_positional_encoding=False,
        output_activation=bundle.output_activation,
    )
    config = TrainConfig(num_pretraining_epochs=0, num_annealing_epochs=9,
                         batch_size=32, max_val_points=64)
    trainer = DIBTrainer(model, bundle, config)
    with EventWriter(str(tmp_path)) as w:
        trainer.fit(jax.random.key(0), hook_every=3, telemetry=w)
        w.run_end(status="ok")
    beats = list(read_events(str(tmp_path), types=("heartbeat",)))
    boundary = [b for b in beats if b["phase"] == "boundary"]
    assert len(boundary) == 3                   # one per chunk
    assert [b["beat"] for b in beats] == sorted(b["beat"] for b in beats)
    # trailing intervals grow with the boundaries; first includes compile
    assert len(boundary[0]["intervals_s"]) == 1
    assert len(boundary[-1]["intervals_s"]) == 3
    s = summarize(str(tmp_path))
    assert s["heartbeats"]["boundary_beats"] == 3
    assert s["heartbeats"]["max_gap_s"] >= 0.0
    # chunk events carry their epoch count (the live MFU gauge's scale)
    chunks = list(read_events(str(tmp_path), types=("chunk",)))
    assert all(c["epochs"] == 3 for c in chunks)


def test_sweep_fit_emits_heartbeats(tmp_path, monkeypatch):
    """BetaSweepTrainer.fit shares the same heartbeat recorder."""
    import jax

    from dib_tpu.data import get_dataset
    from dib_tpu.models import DistributedIBModel
    from dib_tpu.parallel import BetaSweepTrainer
    from dib_tpu.train import TrainConfig

    monkeypatch.setenv("DIB_HEARTBEAT_S", "0")   # boundary beats only
    bundle = get_dataset("boolean_circuit")
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(8,),
        output_dim=bundle.output_dimensionality,
        embedding_dim=4, use_positional_encoding=False,
        output_activation=bundle.output_activation,
    )
    config = TrainConfig(num_pretraining_epochs=0, num_annealing_epochs=4,
                         batch_size=32, max_val_points=64)
    sweep = BetaSweepTrainer(model, bundle, config, 1e-4, [0.1, 1.0])
    keys = jax.random.split(jax.random.key(0), 2)
    with EventWriter(str(tmp_path)) as w:
        sweep.fit(keys, hook_every=2, telemetry=w)
        w.run_end(status="ok")
    beats = list(read_events(str(tmp_path), types=("heartbeat",)))
    assert [b["phase"] for b in beats] == ["boundary", "boundary"]
    assert beats[-1]["epoch"] == 4
