"""The mesh-engine bench artifact contract (ISSUE 13).

BENCH_MESH_CPU.json is the committed evidence the shard_map sweep engine
and the mesh-shape-portable checkpoints rest on: a serial-parity row
(shard_map replica == ``DIBTrainer``, bit for bit) plus
reshard-on-restore round-trips at widths {R/2, 1, 2R}, each continued
and compared bit-identically against the uninterrupted width-R run.
These tests pin the record's per-row schema
(``scripts/check_run_artifacts.py:_check_mesh_bench``), the
zero-parity-failure gate (SLO.json ``mesh_reshard_parity_failures_max``
— evaluated directly by ``telemetry check BENCH_MESH_CPU.json``), and
the seeded fleet-registry history.
"""

import copy
import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "BENCH_MESH_CPU.json")


@pytest.fixture(scope="module")
def checker():
    spec = importlib.util.spec_from_file_location(
        "check_run_artifacts",
        os.path.join(REPO, "scripts", "check_run_artifacts.py"))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


@pytest.fixture(scope="module")
def committed():
    with open(ARTIFACT) as f:
        return json.load(f)


def test_committed_mesh_artifact_validates(checker):
    assert os.path.exists(ARTIFACT), (
        "BENCH_MESH_CPU.json missing — run `python scripts/bench_mesh.py "
        "--out BENCH_MESH_CPU.json` and commit the record")
    assert checker.check_file(ARTIFACT) == []


def test_committed_record_is_green(committed):
    assert committed["metric"] == "mesh_reshard_bench"
    assert committed["unit"] == "parity_failures"
    assert committed["value"] == committed["parity_failures"] == 0
    assert committed["all_parity_ok"] is True
    rows = {r["scenario"]: r for r in committed["rows"]}
    assert "serial_parity" in rows
    # shrink, carve-out AND grow are all in the committed sweep
    saved = max(r["saved_width"] for r in committed["rows"])
    restored = {r["restored_width"] for r in committed["rows"]}
    assert restored >= {saved // 2, 1, 2 * saved}
    assert all(r["bit_identical"] for r in committed["rows"])


def test_checker_rejects_broken_shapes(checker, committed):
    def problems_of(record):
        problems: list[str] = []
        checker.check_record(record, problems)
        return problems

    broken = copy.deepcopy(committed)
    broken["rows"][1]["bit_identical"] = False
    probs = problems_of(broken)
    assert any("bit-identical" in p for p in probs)
    assert any("disagrees" in p for p in probs)

    no_serial = copy.deepcopy(committed)
    no_serial["rows"] = [r for r in no_serial["rows"]
                         if r["scenario"] != "serial_parity"]
    assert any("serial_parity" in p for p in problems_of(no_serial))

    no_reshard = copy.deepcopy(committed)
    no_reshard["rows"] = [r for r in no_reshard["rows"]
                          if r["saved_width"] == r["restored_width"]]
    assert any("width different" in p for p in problems_of(no_reshard))

    bad_engine = copy.deepcopy(committed)
    bad_engine["rows"][0]["engine"] = "pmap"
    assert any("engine" in p for p in problems_of(bad_engine))


def test_slo_gate_exit_codes(tmp_path, committed):
    """`telemetry check` on the committed record is green; a record with
    a parity failure trips the page-severity rule at rc 1."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    ok = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "check", ARTIFACT,
         "--slo", os.path.join(REPO, "SLO.json")],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert ok.returncode == 0, ok.stdout + ok.stderr

    bad = copy.deepcopy(committed)
    bad["parity_failures"] = bad["value"] = 1
    bad["all_parity_ok"] = False
    bad_path = tmp_path / "BENCH_MESH_BAD.json"
    bad_path.write_text(json.dumps(bad))
    trip = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "check",
         str(bad_path), "--slo", os.path.join(REPO, "SLO.json")],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert trip.returncode == 1, trip.stdout + trip.stderr
    report = json.loads(trip.stdout)
    violated = [r for r in report["rules"]
                if r.get("status") == "violated"]
    assert [r["rule"] for r in violated] == [
        "mesh_reshard_parity_failures_max"]


def test_registry_seeded_with_mesh_history():
    entries = []
    with open(os.path.join(REPO, "runs", "index.jsonl")) as f:
        for line in f:
            line = line.strip()
            if line:
                entries.append(json.loads(line))
    mesh = [e for e in entries if e.get("metric") == "mesh_reshard_bench"]
    assert mesh, "runs/index.jsonl must carry the seeded mesh bench entry"
    assert mesh[-1]["value"] == 0
    assert mesh[-1]["parity_failures"] == 0
    drills = [e for e in entries
              if e.get("metric") == "fault_drill_matrix"]
    # the refreshed 14-drill record (sweep_member_backfill included)
    assert drills[-1]["value"] == 14 and drills[-1]["all_passed"] is True
