"""Closed-loop study engine tests (dib_tpu/study, docs/study.md).

The decision core is unit-driven on SYNTHETIC unit histories — no
training anywhere near the policy tests: transition clusters localize,
flat runs yield a clean no-transitions verdict, conflicting multi-seed
transitions WIDEN the bracket instead of faking convergence, journal
replay survives a torn final line, and budget exhaustion stops with an
explicit unconverged verdict. The tier-1 end-to-end smoke runs a tiny
boolean study through the REAL CLI and checks the converged journal,
the rollup, and the ensemble-banded report HTML.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys

import numpy as np
import pytest

from dib_tpu.study.controller import (
    StudyConfig,
    StudyController,
    aggregate_brackets,
    channel_crossings,
    curvature_centers,
    ensemble_band_nats,
    estimate_from_bracket,
    plan_refinement,
)
from dib_tpu.study.journal import (
    STUDY_JOURNAL_FILENAME,
    StudyJournal,
    fold_study,
    read_study_journal,
)

_LN2 = math.log(2.0)


# ---------------------------------------------------------- synthetic data
def _synthetic_kl(beta: float, centers: dict[int, float],
                  channels: int = 4, width: float = 0.15,
                  noise: float = 0.0, seed: int = 0) -> np.ndarray:
    """A per-channel KL curve with a sharp sigmoid transition at each
    channel's center β (log space): high (~1 nat) below, ~0 above —
    the info-plane shape the detector exists for."""
    rng = np.random.default_rng(seed * 7919 + int(beta * 1e6) % 104729)
    out = np.zeros(channels)
    for c in range(channels):
        center = centers.get(c)
        if center is None:
            out[c] = 1.0   # never compressed
        else:
            x = (math.log10(beta) - math.log10(center)) / width
            out[c] = 1.0 / (1.0 + math.exp(4.0 * x))
        if noise:
            out[c] = max(out[c] + rng.normal(0.0, noise), 0.0)
    return out


def _curve(betas, centers, **kw):
    return [(b, _synthetic_kl(b, centers, **kw)) for b in betas]


class _FakeSchedRunner:
    """Unit runner double for controller tests: writes the synthetic
    per-channel KL history npz the real TrainingUnitRunner would, with
    per-seed disagreement injectable via ``seed_centers``."""

    def __init__(self, base_dir: str, centers: dict[int, float],
                 seed_centers: dict[int, dict[int, float]] | None = None,
                 channels: int = 4):
        self.base_dir = base_dir
        self.centers = centers
        self.seed_centers = seed_centers or {}
        self.channels = channels
        self.calls: list[tuple[float, int]] = []

    def __call__(self, unit, heartbeat=None) -> dict:
        if heartbeat is not None:
            heartbeat()
        self.calls.append((unit.beta, unit.seed))
        centers = self.seed_centers.get(unit.seed, self.centers)
        kl_nats = _synthetic_kl(unit.beta, centers,
                                channels=self.channels)
        udir = os.path.join(self.base_dir, "units",
                            unit.unit_id.replace("/", "__"))
        os.makedirs(udir, exist_ok=True)
        path = os.path.join(udir, "history.npz")
        # the runner persists BITS (to_bits); unit_points converts back
        np.savez(path, kl_per_feature=(kl_nats / _LN2)[None, :],
                 beta=np.asarray([unit.beta]),
                 loss=np.asarray([0.1]), val_loss=np.asarray([0.1]))
        return {"beta": float(unit.beta), "seed": int(unit.seed),
                "history_path": path}


def _drain_with(runner):
    """An injectable drain: run every pending unit synchronously."""

    def drain(scheduler):
        while True:
            lease = scheduler.acquire("fake-worker")
            if lease is None:
                if scheduler.drained():
                    return
                continue
            unit = scheduler.unit(lease.unit_id)["unit"]
            scheduler.complete(lease, runner(unit))

    return drain


def _run_fake_study(tmp_path, config, centers, seed_centers=None,
                    channels=4, telemetry=None):
    sdir = str(tmp_path / "study")
    runner = _FakeSchedRunner(sdir, centers, seed_centers,
                              channels=channels)
    controller = StudyController(sdir, config=config,
                                 telemetry=telemetry)
    state = controller.run(drain=_drain_with(runner))
    return sdir, controller, state, runner


# ------------------------------------------------------------ policy units
def test_channel_crossings_brackets_the_transition():
    betas = [0.01, 0.1, 1.0, 10.0]
    crossings = channel_crossings(
        _curve(betas, {0: 0.3, 1: 3.0}, channels=3), threshold_nats=0.5)
    assert crossings[0] == (0.1, 1.0)
    assert crossings[1] == (1.0, 10.0)
    assert 2 not in crossings          # never compressed, no bracket


def test_channel_crossings_takes_the_last_crossing():
    # a noisy dip through the threshold before the real transition must
    # not win: the surviving crossing is the last one
    kl = {0.01: [1.0], 0.1: [0.3], 0.3: [0.8], 1.0: [0.1]}
    curve = [(b, np.asarray(v)) for b, v in kl.items()]
    assert channel_crossings(curve, 0.5)[0] == (0.3, 1.0)


def test_flat_curves_have_no_crossings():
    betas = [0.01, 0.1, 1.0, 10.0]
    assert channel_crossings(
        _curve(betas, {}, channels=3), threshold_nats=0.5) == {}


def test_aggregate_brackets_widens_on_seed_conflict():
    merged = aggregate_brackets([{0: (0.1, 1.0)}, {0: (1.0, 10.0)}])
    assert merged[0] == (0.1, 10.0)
    est = estimate_from_bracket(*merged[0])
    assert est == pytest.approx(1.0)


def test_plan_refinement_interior_points_only():
    brackets = {0: (0.1, 1.0)}
    new = plan_refinement(brackets, num=4, already=[0.01, 0.1, 1.0, 10.0])
    assert new, "refinement must add interior points"
    assert all(0.1 < b < 1.0 for b in new)
    # endpoints were already trained — never re-bought
    assert all(abs(b - 0.1) > 1e-3 and abs(b - 1.0) > 1e-3 for b in new)


def test_plan_refinement_merges_overlapping_brackets():
    merged = plan_refinement({0: (0.1, 1.0), 1: (0.5, 5.0)}, num=4,
                             already=[])
    spread = plan_refinement({0: (0.1, 1.0), 1: (50.0, 500.0)}, num=4,
                             already=[])
    assert all(0.1 <= b <= 5.0 for b in merged)
    assert any(b > 40 for b in spread) and any(b < 2 for b in spread)


def test_ensemble_band_needs_two_seeds():
    pts0 = {0.1: np.asarray([1.0]), 1.0: np.asarray([0.0])}
    assert ensemble_band_nats({0: pts0}, {0: (0.1, 1.0)}) is None
    pts1 = {0.1: np.asarray([0.8]), 1.0: np.asarray([0.1])}
    band = ensemble_band_nats({0: pts0, 1: pts1}, {0: (0.1, 1.0)})
    assert band == pytest.approx(0.2)


def test_curvature_centers_find_the_bend():
    betas = [10 ** (x / 4.0) for x in range(-8, 9)]
    pts = [(b, 1.0 / (1.0 + (b / 1.0) ** 2)) for b in betas]
    centers = curvature_centers(pts)
    assert centers and all(0.05 < c < 20 for c in centers)
    assert curvature_centers([(1.0, 0.5), (2.0, 0.4)]) == []


# -------------------------------------------------------- journal replay
def test_journal_replay_after_torn_final_line(tmp_path):
    d = str(tmp_path)
    with StudyJournal(d) as j:
        j.append("config", spec={"max_units": 8})
        j.append("round", round=0, betas=[0.1, 1.0], seeds=[0], units=2,
                 job_name="study:x:r0", budget_spent_after=2)
        j.append("submitted", round=0, job_id="job-0000")
        j.append("round_done", round=0, estimates={"0": 0.3},
                 brackets={"0": [0.1, 1.0]}, deltas_decades={"0": None})
    path = os.path.join(d, STUDY_JOURNAL_FILENAME)
    with open(path, "ab") as f:   # a writer killed mid-append
        f.write(b'{"kind": "verdict", "verd')
    records, torn = read_study_journal(d)
    assert torn == 1
    state = fold_study(records)
    assert state["verdict"] is None          # torn record never replays
    assert state["config"] == {"max_units": 8}
    assert state["rounds"][0]["done"] is True
    assert state["rounds"][0]["job_id"] == "job-0000"
    assert state["budget_spent"] == 2
    # a fresh journal SEALS the torn line: its first append must not
    # glue onto the dead writer's half-record
    with StudyJournal(d) as j:
        j.append("verdict", verdict="converged", rounds=1,
                 budget_spent=2)
    records, torn = read_study_journal(d)
    assert torn == 1
    assert fold_study(records)["verdict"]["verdict"] == "converged"


# ------------------------------------------------------- controller loops
def _tiny_config(**kw) -> StudyConfig:
    base = dict(grid_start=0.01, grid_stop=10.0, grid_num=4,
                seeds=(0,), threshold_nats=0.5, tolerance_decades=0.2,
                min_refine_rounds=1, max_rounds=5, max_units=40,
                refine_num=4)
    base.update(kw)
    return StudyConfig(**base)


def test_controller_converges_on_a_clean_transition(tmp_path):
    sdir, controller, state, runner = _run_fake_study(
        tmp_path, _tiny_config(), centers={0: 0.3, 1: 2.0})
    verdict = state["verdict"]
    assert verdict["verdict"] == "converged"
    done = [r for r in state["rounds"] if r["done"]]
    assert len(done) >= 2                      # at least one refinement
    est = {int(c): float(v) for c, v in verdict["estimates"].items()}
    # the estimate localized the planted transition within its bracket
    assert abs(math.log10(est[0]) - math.log10(0.3)) < 0.5
    assert abs(math.log10(est[1]) - math.log10(2.0)) < 0.5
    # budget accounting matches the scheduler journal exactly
    status = controller.status()
    assert status["budget_spent"] == status["scheduler"]["units_submitted"]
    assert status["scheduler"]["jobs"] == len(done)
    # deltas shrink round over round (the refinement is doing work)
    deltas = [max(v for v in r["deltas_decades"].values()
                  if v is not None)
              for r in done[1:] if r.get("deltas_decades")]
    assert deltas and deltas[-1] <= 0.2


def test_controller_flat_run_yields_no_transitions(tmp_path):
    sdir, _, state, runner = _run_fake_study(
        tmp_path, _tiny_config(), centers={})
    assert state["verdict"]["verdict"] == "no_transitions"
    # exactly the initial grid was spent — nothing refined on nothing
    assert state["budget_spent"] == 4
    assert len(runner.calls) == 4


def test_conflicting_seeds_widen_bracket_not_false_convergence(tmp_path):
    # seed 0 sees the transition at 0.1, seed 1 at 5.0 — a study that
    # averaged instead of widening would converge on a β neither seed
    # supports. The widened bracket spans both and the ensemble band
    # stays wide.
    config = _tiny_config(seeds=(0, 1), max_rounds=3, max_units=30,
                          tolerance_decades=0.05, min_refine_rounds=1)
    sdir, _, state, _ = _run_fake_study(
        tmp_path, config, centers={0: 0.1},
        seed_centers={0: {0: 0.1}, 1: {0: 5.0}})
    done = [r for r in state["rounds"] if r["done"]]
    lo, hi = done[-1]["brackets"]["0"]
    assert lo <= 0.15 and hi >= 3.0, "bracket must span both seeds"
    assert done[-1]["band_nats"] is not None
    assert done[-1]["band_nats"] > 0.3
    # with the tight tolerance the conflicted study must NOT converge —
    # it burns its round budget and says so
    assert state["verdict"]["verdict"] == "unconverged"


def test_band_floor_convergence(tmp_path):
    # agreeing seeds: the across-seed band is ~0, so the band-floor
    # criterion converges even while the delta path is locked out by an
    # unreachable min_refine_rounds
    config = _tiny_config(seeds=(0, 1), min_refine_rounds=5,
                          band_floor_nats=0.05, max_rounds=4,
                          max_units=60)
    sdir, _, state, _ = _run_fake_study(
        tmp_path, config, centers={0: 0.3})
    assert state["verdict"]["verdict"] == "converged"
    assert "band" in state["verdict"]["reason"]


def test_budget_exhaustion_stops_cleanly_unconverged(tmp_path):
    # unit budget fits round 0 (4 units) plus ONE partial refinement;
    # the impossible tolerance means it can never converge — the study
    # must stop with an explicit unconverged verdict, never overspend
    # min_refine_rounds=99 locks the convergence verdict out
    # structurally, so the unit budget is what must stop the study
    config = _tiny_config(min_refine_rounds=99, max_units=6,
                          max_rounds=10)
    sdir, controller, state, runner = _run_fake_study(
        tmp_path, config, centers={0: 0.3})
    assert state["verdict"]["verdict"] == "unconverged"
    assert "budget" in state["verdict"]["reason"]
    assert state["budget_spent"] <= 6
    assert len(runner.calls) == state["budget_spent"]


def test_all_units_failing_is_unconverged_not_a_null_result(tmp_path):
    """Every unit failing terminally must NOT read as a flat info plane:
    no data is a training failure (unconverged, evidence in the reason),
    never a clean 'no_transitions' scientific null."""
    sdir = str(tmp_path / "study")

    def drain(scheduler):
        while not scheduler.drained():
            lease = scheduler.acquire("fake-worker")
            if lease is None:
                continue
            scheduler.fail(lease, "train spec is broken")

    controller = StudyController(
        sdir, config=_tiny_config(retry_budget=0))
    state = controller.run(drain=drain)
    assert state["verdict"]["verdict"] == "unconverged"
    assert "training failure" in state["verdict"]["reason"]
    assert state["verdict"]["estimates"] == {}


def test_progress_counts_are_not_double_counted_across_rounds(tmp_path):
    """The progress follower keeps ONE stream offset across rounds — a
    fresh follower per drain would re-read the whole stream and report
    8 + (8+N) + ... instead of the true outcome count."""
    from dib_tpu.telemetry import EventWriter

    sdir = str(tmp_path / "study")
    writer = EventWriter(sdir, run_id="study-progress")
    runner = _FakeSchedRunner(sdir, {0: 0.3})

    real_drain = _drain_with(runner)

    def drain(scheduler):
        # the real _drain wraps pool.run() with the follower thread;
        # here we run the follower machinery explicitly around the
        # synchronous drain so the counting path is the production one
        import threading

        stop = threading.Event()
        t = threading.Thread(target=controller._progress_follower,
                             args=(stop,))
        t.start()
        try:
            real_drain(scheduler)
        finally:
            stop.set()
            t.join(timeout=10.0)

    controller = StudyController(sdir, config=_tiny_config(),
                                 telemetry=writer)
    state = controller.run(drain=drain)
    writer.run_end(status="ok")
    writer.close()
    assert state["verdict"]["verdict"] == "converged"
    assert len([r for r in state["rounds"] if r["done"]]) >= 2
    assert controller.progress()["units_done"] == state["budget_spent"]


def test_round_budget_exhaustion_unconverged(tmp_path):
    config = _tiny_config(min_refine_rounds=99, max_rounds=2,
                          max_units=60)
    sdir, _, state, _ = _run_fake_study(tmp_path, config,
                                        centers={0: 0.3})
    assert state["verdict"]["verdict"] == "unconverged"
    assert "round budget" in state["verdict"]["reason"]
    assert len([r for r in state["rounds"] if r["done"]]) == 2


def test_study_events_and_rollup(tmp_path):
    from dib_tpu.telemetry import EventWriter, summarize

    sdir = str(tmp_path / "study")
    writer = EventWriter(sdir, run_id="study-test")
    runner = _FakeSchedRunner(sdir, {0: 0.3})
    controller = StudyController(sdir, config=_tiny_config(),
                                 telemetry=writer)
    state = controller.run(drain=_drain_with(runner))
    writer.run_end(status="ok")
    writer.close()
    assert state["verdict"]["verdict"] == "converged"
    summary = summarize(sdir)
    study = summary["study"]
    assert study["rounds"] == len(
        [r for r in state["rounds"] if r["done"]])
    assert study["units_submitted"] == state["budget_spent"]
    assert study["units_done"] == state["budget_spent"]
    assert study["verdict"] == "converged"
    assert study["rounds_over_budget"] == 0
    assert study["unconverged_full_budget"] == 0
    assert study["estimates"]
    # the scheduler rollup rides the same stream
    assert summary["scheduler"]["units"]["done"] == state["budget_spent"]


def test_unconverged_rollup_trips_the_slo_gate(tmp_path):
    from dib_tpu.telemetry import EventWriter, summarize

    sdir = str(tmp_path / "study")
    writer = EventWriter(sdir, run_id="study-test")
    runner = _FakeSchedRunner(sdir, {0: 0.3})
    controller = StudyController(
        sdir, config=_tiny_config(min_refine_rounds=99, max_rounds=2,
                                  max_units=60),
        telemetry=writer)
    controller.run(drain=_drain_with(runner))
    writer.run_end(status="ok")
    writer.close()
    study = summarize(sdir)["study"]
    assert study["verdict"] == "unconverged"
    assert study["unconverged_full_budget"] == 1


# ------------------------------------------------- exactly-once resume
def test_resume_submits_unacked_intent_exactly_once(tmp_path):
    """SIGKILL between the round's journal append and the scheduler
    submit (simulated by building exactly that journal state): the
    resumed controller must submit the decided round once — and a
    SECOND resume must adopt, never resubmit."""
    from dib_tpu.sched.journal import read_journal

    sdir = str(tmp_path / "study")
    config = _tiny_config()
    runner = _FakeSchedRunner(sdir, {0: 0.3})
    os.makedirs(sdir, exist_ok=True)
    with StudyJournal(sdir) as j:
        j.append("config", spec=config.to_dict())
        j.append("round", round=0, betas=config.initial_betas(),
                 seeds=[0], units=4, job_name="study:study:r0",
                 budget_spent_after=4)
        # no "submitted" ack — the decided-but-unsubmitted crash window
    controller = StudyController(sdir, telemetry=None)
    state = controller.run(drain=_drain_with(runner))
    assert state["verdict"] is not None
    records, _ = read_journal(sdir)
    names = [(r.get("spec") or {}).get("name") for r in records
             if r.get("kind") == "job"]
    assert names.count("study:study:r0") == 1, \
        "the decided round must be submitted exactly once"


def test_resume_adopts_submitted_but_unacked_job(tmp_path):
    """SIGKILL between the scheduler submit and the journal ack: the
    scheduler journal already has the round's job — the resumed
    controller must ADOPT it, not resubmit (zero duplicate units)."""
    from dib_tpu.sched.journal import read_journal
    from dib_tpu.sched.scheduler import JobSpec, Scheduler

    sdir = str(tmp_path / "study")
    config = _tiny_config()
    runner = _FakeSchedRunner(sdir, {0: 0.3})
    os.makedirs(sdir, exist_ok=True)
    betas = config.initial_betas()
    with StudyJournal(sdir) as j:
        j.append("config", spec=config.to_dict())
        j.append("round", round=0, betas=betas, seeds=[0],
                 units=len(betas), job_name="study:study:r0",
                 budget_spent_after=len(betas))
    scheduler = Scheduler(sdir)
    scheduler.submit(JobSpec(betas=tuple(betas), seeds=(0,),
                             name="study:study:r0"))
    scheduler.close()
    # ... and the controller died before appending "submitted"
    controller = StudyController(sdir, telemetry=None)
    state = controller.run(drain=_drain_with(runner))
    assert state["verdict"] is not None
    records, _ = read_journal(sdir)
    names = [(r.get("spec") or {}).get("name") for r in records
             if r.get("kind") == "job"]
    assert names.count("study:study:r0") == 1, \
        "adoption must not resubmit the already-submitted round"
    units = [r for r in records if r.get("kind") == "unit"]
    assert len(units) == sum(r.get("units") or 0
                             for r in state["rounds"])


# --------------------------------------------------------- watch seeding
def test_watch_centers_from_a_finished_stream(tmp_path):
    from dib_tpu.study.controller import watch_centers
    from dib_tpu.telemetry import EventWriter

    run_dir = str(tmp_path / "run")
    with EventWriter(run_dir, run_id="watched") as w:
        w.run_start({"mode": "train"})
        w.transition(channel=0, epoch=4, direction="down", beta=0.7)
        w.transition(channel=2, epoch=9, direction="down", beta=4.2)
        w.run_end(status="ok")
    centers = watch_centers(run_dir)
    assert centers == [0.7, 4.2]
    config = StudyConfig(centers=tuple(centers))
    betas = config.initial_betas()
    assert all(0.3 < b < 10.0 for b in betas)


# ---------------------------------------------------------------- report
def _assert_html_sane(content: str) -> None:
    from html.parser import HTMLParser

    class Balance(HTMLParser):
        VOID = {"meta", "br", "hr", "img", "input", "link", "circle",
                "line", "polyline", "polygon", "path", "rect"}

        def __init__(self):
            super().__init__(convert_charrefs=True)
            self.stack: list[str] = []
            self.errors: list[str] = []

        def handle_starttag(self, tag, attrs):
            if tag not in self.VOID:
                self.stack.append(tag)

        def handle_endtag(self, tag):
            if tag in self.VOID:
                return
            if not self.stack or self.stack[-1] != tag:
                self.errors.append(f"mismatched </{tag}>")
            else:
                self.stack.pop()

    parser = Balance()
    parser.feed(content)
    assert not parser.errors, parser.errors
    assert not parser.stack, f"unclosed tags: {parser.stack}"
    lowered = content.lower()
    for marker in ("http://", "https://", "src=", "@import"):
        assert marker not in lowered, f"external resource: {marker}"


def test_study_report_renders_band_and_annotations(tmp_path):
    from dib_tpu.study.report import render_study_report, study_record

    config = _tiny_config(seeds=(0, 1), max_units=60)
    sdir, _, state, _ = _run_fake_study(
        tmp_path, config, centers={0: 0.3, 1: 2.0},
        seed_centers={0: {0: 0.28, 1: 2.0}, 1: {0: 0.33, 1: 2.1}})
    assert state["verdict"]["verdict"] == "converged"
    content = render_study_report(sdir)
    _assert_html_sane(content)
    assert "Distributed information plane" in content
    assert 'fill="var(--band)"' in content      # the ensemble band
    assert "transition β ≈" in content          # annotated estimates
    assert "stroke-dasharray" in content        # the vline annotation
    record = study_record(sdir)
    assert record["metric"] == "beta_study"
    assert record["verdict"] == "converged"
    assert record["scheduler_journal"]["consistent"] is True
    assert record["study"]["rounds_over_budget"] == 0


# ------------------------------------------------------- tier-1 e2e smoke
def test_study_cli_end_to_end_smoke(tmp_path):
    """Tiny boolean study through the REAL CLI: converged journal,
    exactly-once accounting, rollup on the stream, and the report HTML
    rendering the ensemble band."""
    sdir = str(tmp_path / "study_e2e")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("DIB_STUDY_FAULT", None)
    run = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "study", "run",
         "--study-dir", sdir,
         "--grid", "0.03", "30", "4", "--seeds", "0", "1",
         "--threshold-nats", "0.1", "--tolerance-decades", "0.35",
         # the 4-point grid's cells are a full decade wide, so a
         # one-interval seed disagreement is >= 1 decade by
         # construction — size the localization gate to the grid
         "--max-bracket-decades", "2.0",
         "--min-refine-rounds", "1", "--max-rounds", "3",
         "--max-units", "24", "--refine-num", "3",
         "--set", "steps_per_epoch=16",
         "--set", "num_annealing_epochs=20",
         "--set", "batch_size=128", "--set", "chunk_epochs=11"],
        env=env, capture_output=True, text=True, timeout=600)
    assert run.returncode == 0, run.stderr[-2000:]
    status = json.loads(run.stdout.strip().splitlines()[-1])
    assert status["verdict"]["verdict"] == "converged"
    assert status["budget_spent"] == \
        status["scheduler"]["units_submitted"]

    report = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "study", "report",
         "--study-dir", sdir,
         "--json-out", os.path.join(sdir, "record.json")],
        env=env, capture_output=True, text=True, timeout=300)
    assert report.returncode == 0, report.stderr[-2000:]
    with open(os.path.join(sdir, "study_report.html")) as f:
        content = f.read()
    _assert_html_sane(content)
    assert 'fill="var(--band)"' in content
    with open(os.path.join(sdir, "record.json")) as f:
        record = json.load(f)
    assert record["verdict"] == "converged"
    assert record["scheduler_journal"]["consistent"] is True

    stat = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "study", "status",
         "--study-dir", sdir, "--json"],
        env=env, capture_output=True, text=True, timeout=120)
    assert stat.returncode == 0
    assert json.loads(stat.stdout)["verdict"]["verdict"] == "converged"


# --------------------------------------------------- committed artifacts
def _repo_path(name: str) -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), name)


def test_committed_study_cpu_record_contract():
    """STUDY_CPU.json: converged with >= 2 refinement rounds, final
    deltas under tolerance, budget consistent with the scheduler
    journal — the acceptance evidence, pinned."""
    with open(_repo_path("STUDY_CPU.json")) as f:
        record = json.load(f)
    assert record["metric"] == "beta_study"
    assert record["verdict"] == "converged"
    refinements = [r for r in record["rounds"] if r["round"] >= 1]
    assert len(refinements) >= 2
    deltas = [v for v in refinements[-1]["deltas_decades"].values()
              if v is not None]
    assert deltas and max(deltas) <= record["tolerance_decades"]
    assert record["scheduler_journal"]["consistent"] is True
    assert record["study"]["rounds_over_budget"] == 0
    assert record["study"]["unconverged_full_budget"] == 0


def test_committed_chaos_study_record_contract():
    """CHAOS_STUDY.json: all three drills green with the exactly-once
    invariants asserted per row and zero duplicate submissions."""
    with open(_repo_path("CHAOS_STUDY.json")) as f:
        record = json.load(f)
    assert record["metric"] == "chaos_study_matrix"
    assert record["all_passed"] is True
    assert record["duplicate_submissions"] == 0
    drills = {d["drill"]: d for d in record["matrix"]}
    assert set(drills) >= {"intent_kill", "submit_ack_kill",
                           "torn_journal"}
    for d in drills.values():
        assert d["ok"] is True
        assert d["exactly_once_submission"] is True
        assert d["zero_duplicate_units"] is True
        assert d["zero_lost_rounds"] is True
    for name in ("intent_kill", "submit_ack_kill"):
        assert drills[name]["killed_by_sigkill"] is True
        assert drills[name]["fault_detected"] is True
    assert drills["submit_ack_kill"]["kill_window_state"][
        "jobs_under_open_round_names"] == 1
    assert drills["intent_kill"]["kill_window_state"][
        "jobs_under_open_round_names"] == 0
