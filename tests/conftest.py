"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the standard JAX fake-backend idiom)
so pjit sharding and collectives are exercised without TPU hardware. This must
be set before JAX initializes its backends, hence the env mutation at import
time (pytest imports conftest before test modules import jax).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # hard override: the ambient env pins axon (TPU)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# sitecustomize pre-imports jax with JAX_PLATFORMS=axon baked into jax.config,
# so the env mutation above is too late for the platform choice — override the
# already-read config value directly (backends have not initialized yet).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: this box has a single CPU core, so avoiding
# recompiles across pytest runs matters more than anything else. Use a
# CPU-specific dir — the ambient cache dir holds AOT results from the remote
# TPU compile service whose CPU-feature flags mismatch this host.
os.environ["JAX_COMPILATION_CACHE_DIR"] = "/root/.cache/jax_comp_cache_cpu"
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.2")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
# Tests that drive the CLI entry points (main()/workload_main()) must not
# redirect the process-global cache config to the shared TPU cache dir —
# the CPU dir above stays authoritative for the whole pytest process.
os.environ["DIB_COMPILE_CACHE"] = ""

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import warnings  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

if jax.default_backend() == "cpu":
    # Buffer donation (run_chunk) is a TPU/GPU optimization the CPU backend
    # ignores with this warning. Scoped to CPU on purpose: on accelerator CI
    # the warning must stay visible — it is the only signal that donation
    # stopped being applied.
    warnings.filterwarnings(
        "ignore", message="Some donated buffers were not usable"
    )


def pytest_collection_modifyitems(config, items):
    # Tier split (VERDICT round 1: the full suite cannot finish in 10 min on
    # this 1-core box). Everything not explicitly @pytest.mark.slow is the
    # smoke tier: `pytest -m smoke` must stay green under ~2 min here.
    for item in items:
        if "slow" not in item.keywords:
            item.add_marker(pytest.mark.smoke)


@pytest.fixture
def rng():
    return np.random.default_rng(0)
