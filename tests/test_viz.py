"""Visualization artifact tests: files render and have sane content."""

import numpy as np

from dib_tpu.viz import (
    save_distributed_info_plane,
    save_compression_matrix,
    compression_matrix,
    save_info_maps,
    density_mask,
)


def test_info_plane_renders(tmp_path, rng):
    kl = np.abs(rng.normal(size=(200, 4)))
    loss = np.abs(rng.normal(size=200))
    path = save_distributed_info_plane(kl, loss, str(tmp_path), entropy_y=1.0)
    assert path.endswith("distributed_info_plane.png")
    import os

    assert os.path.getsize(path) > 1000


def test_compression_matrix_properties(rng):
    mus = rng.normal(size=(12, 4)).astype(np.float32)
    logvars = rng.normal(scale=0.3, size=(12, 4)).astype(np.float32)
    mat = compression_matrix(mus, logvars)
    assert mat.shape == (12, 12)
    np.testing.assert_allclose(np.diagonal(mat), 1.0, atol=1e-4)  # self-overlap
    assert np.all(mat >= 0) and np.all(mat <= 1 + 1e-6)
    np.testing.assert_allclose(mat, mat.T, rtol=1e-4, atol=1e-5)


def test_compression_matrix_discrete_render(tmp_path, rng):
    # binary feature: < 10 unique values -> histogram marginals path
    raw = np.repeat([-1.0, 1.0], 16)
    mus = np.stack([raw * 3, raw * 0], -1).astype(np.float32)
    logvars = np.zeros_like(mus)
    out = save_compression_matrix(mus, logvars, raw, str(tmp_path / "c.png"), "feat")
    import os

    assert os.path.getsize(out) > 1000


def test_compression_matrix_continuous_render(tmp_path, rng):
    raw = rng.normal(size=300)
    mus = np.stack([raw, raw**2], -1).astype(np.float32)
    logvars = np.zeros_like(mus) - 1
    out = save_compression_matrix(
        mus, logvars, raw, str(tmp_path / "c2.png"), max_number_to_display=64
    )
    import os

    assert os.path.getsize(out) > 1000


def test_info_maps_and_density_mask(tmp_path, rng):
    g = 10
    grids = [np.abs(rng.normal(size=(g, g, 2))) for _ in range(2)]
    xx, yy = np.meshgrid(np.linspace(-3, 3, g), np.linspace(-3, 3, g))
    probes = np.stack([xx, yy], -1).reshape(-1, 2)
    # per-bin RIGHT edges, the ProbeGridHook convention (edges[1:])
    g_r_bins = np.linspace(0, 3, 20)[1:]               # 19 bins
    g_r = np.concatenate([np.zeros(5), np.ones(14)])   # empty core r < ~0.79
    mask = density_mask(probes, g_r, g_r_bins, g)
    assert np.isnan(mask[g // 2, g // 2])  # excluded-volume core masked
    # corner (radius ~4.2) lies beyond the outermost occupied bin (r=3):
    # out-of-support probes have divergent LOO uppers and must be masked
    assert np.isnan(mask[0, 0])
    # a supported mid-ring probe (x~1.0, y~0.33, radius ~1.05) stays
    assert mask[g // 2, int(g * 0.65)] == 1.0
    # interior empty bins between occupied shells must NOT extend the core
    g_r_gap = np.concatenate([np.zeros(5), np.ones(4), np.zeros(3), np.ones(7)])
    mask_gap = density_mask(probes, g_r_gap, g_r_bins, g)
    assert np.isnan(mask_gap[g // 2, g // 2])
    np.testing.assert_array_equal(np.isnan(mask_gap), np.isnan(mask))
    # trailing empty bins pull the outer cutoff in: r ~2.33 probes now
    # outside support (last occupied right edge ~2.2) must be masked
    g_r_trail = np.concatenate([np.zeros(5), np.ones(9), np.zeros(5)])
    mask_trail = density_mask(probes, g_r_trail, g_r_bins, g)
    assert np.isnan(mask_trail[g // 2, g - 1])         # x=3.0
    assert np.isnan(mask_trail[g // 2, int(g * 0.85)])  # x~2.33
    # full-edges arrays are rejected loudly (ambiguous convention)
    import pytest

    with pytest.raises(ValueError, match="RIGHT edges"):
        density_mask(probes, g_r, np.linspace(0, 3, 20), g)
    out = save_info_maps(grids, str(tmp_path / "maps.png"), masks=[mask, mask], titles=["A", "B"])
    import os

    assert os.path.getsize(out) > 1000
