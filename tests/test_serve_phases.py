"""Request anatomy (docs/observability.md "Request anatomy"): the
per-phase latency clock, the fleet-mergeable native histogram buckets,
and the `serve top` dashboard.

The contracts ISSUE-17 must prove:

  - **the exact-sum invariant**: on REAL asyncio requests, a request
    span's ``phases`` sum to its end-to-end ``seconds`` to rounding —
    uncached (all 8 phases), cached (no queue/batch), 429 and shed
    (admission-terminated) each carry exactly the phases they traversed;
  - **bucket-merge bit-identity**: two workers' native bucket vectors
    summed index-wise yield the SAME quantile as one combined stream —
    the property `serve top` and the fleet Prometheus merge rest on;
  - **Prometheus exposition**: the `_hist` family's cumulative
    ``_bucket`` samples are consistent with ``_count``/``_sum`` and the
    ``+Inf`` bucket is always emitted;
  - **`serve top --once`** renders a live 2-worker prefork fleet through
    the real CLI;
  - **the committed-record schema**: `check_run_artifacts` rejects a
    serve_phase_anatomy record whose phase sums no longer telescope or
    whose cumulative buckets are non-monotone.
"""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.models import DistributedIBModel
from dib_tpu.serve import (
    DIBServer,
    InferenceEngine,
    MicroBatcher,
    ModelZoo,
    ReplicaEntry,
    ReplicaRouter,
    TenantQuotas,
)
from dib_tpu.serve.server import _PhaseClock
from dib_tpu.telemetry import (
    EventWriter,
    MetricsRegistry,
    Tracer,
    read_events,
    runtime_manifest,
)
from dib_tpu.telemetry.events import REQUEST_PHASES
from dib_tpu.telemetry.metrics import (
    BUCKET_BOUNDS,
    Histogram,
    MetricsRegistry as _Registry,
    bucket_counts,
    bucket_quantile,
    prometheus_text,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# span seconds round to 6 decimals and phases to 9, so the telescoped
# sum can differ from seconds by a few 1e-7 — never more
_SUM_TOL = 2e-6


@pytest.fixture(scope="module")
def bundle():
    return get_dataset("boolean_circuit")


@pytest.fixture(scope="module")
def model(bundle):
    return DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )


@pytest.fixture(scope="module")
def params(bundle, model):
    x0 = np.asarray(bundle.x_train[:4], np.float32)
    return model.init(jax.random.key(0), x0, jax.random.key(1))


def _post(url: str, payload: dict, headers: dict | None = None):
    request = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(request, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def _stack(model, params, run_dir, quotas=None, admission_limit=None,
           response_capacity=None):
    writer = EventWriter(run_dir)
    writer.run_start(runtime_manifest(extra={"mode": "serve"}))
    tracer = Tracer(writer)
    registry = MetricsRegistry()
    engine = InferenceEngine(model, params, batch_buckets=(1, 4),
                             telemetry=writer, registry=registry)
    batcher = MicroBatcher(engine, max_batch=4, max_wait_ms=1.0,
                           tracer=tracer, registry=registry)
    router = ReplicaRouter([ReplicaEntry(engine, batcher, 0)])
    zoo = ModelZoo.single(router, response_capacity=response_capacity,
                          telemetry=writer, registry=registry)
    server = DIBServer(zoo, port=0, telemetry=writer, registry=registry,
                       tracer=tracer, quotas=quotas,
                       admission_limit=admission_limit).start()
    return server, registry


def _request_spans(run_dir):
    return [e for e in read_events(run_dir)
            if e["type"] == "span" and e["name"] == "request"]


# --------------------------------------------------- the exact-sum invariant
def test_phases_sum_exactly_to_seconds_across_request_variants(
        model, params, bundle, tmp_path):
    """Real asyncio requests, four outcomes — uncached ok, cached ok,
    quota 429 — each span's phases telescope to its end-to-end seconds,
    and each variant carries exactly the phases it traversed."""
    run_dir = str(tmp_path / "phases_run")
    server, registry = _stack(
        model, params, run_dir,
        quotas=TenantQuotas(rate=0.25, burst=2.0),
        response_capacity=64)
    try:
        rows = np.asarray(bundle.x_valid[:4], np.float32)
        # two distinct-input requests for tenant a (burst=2 admits both)
        assert _post(server.url + "/v1/predict",
                     {"x": rows[0].tolist(), "tenant": "a"})[0] == 200
        assert _post(server.url + "/v1/predict",
                     {"x": rows[1].tolist(), "tenant": "a"})[0] == 200
        # burst spent -> deterministic 429
        assert _post(server.url + "/v1/predict",
                     {"x": rows[2].tolist(), "tenant": "a"})[0] == 429
        # repeat of rows[0] from a fresh tenant -> response-cache hit
        status, payload = _post(server.url + "/v1/predict",
                                {"x": rows[0].tolist(), "tenant": "b"})
        assert status == 200 and payload.get("cached") is True
    finally:
        server.close()

    spans = _request_spans(run_dir)
    assert len(spans) == 4
    for span in spans:
        phases = span["phases"]
        assert set(phases) <= set(REQUEST_PHASES)
        assert all(v >= 0 for v in phases.values())
        diff = abs(sum(phases.values()) - span["seconds"])
        assert diff <= _SUM_TOL, \
            f"{span['status']}: phase sum off by {diff:.2e}s"

    by_status = {}
    for span in spans:
        by_status.setdefault(
            (span["status"], bool(span.get("cached"))), span)
    # uncached ok traverses the full pipeline
    assert set(by_status[("ok", False)]["phases"]) == set(REQUEST_PHASES)
    # a cache hit never queues or batches (answered on the event loop)
    assert set(by_status[("ok", True)]["phases"]) == \
        {"read", "parse", "admission", "dispatch", "serialize", "write"}
    # a 429 stops at admission
    assert set(by_status[("quota", False)]["phases"]) == \
        {"read", "parse", "admission", "serialize", "write"}

    # per-phase histograms landed on /metrics with native buckets
    hists = registry.snapshot()["histograms"]
    for phase in REQUEST_PHASES:
        hist = hists[f"serve.phase.{phase}"]
        assert hist["count"] >= 1
        assert any(k.startswith("le_") for k in hist)


def test_shed_request_carries_admission_terminated_phases(
        model, params, tmp_path):
    """A 503 shed by the in-flight bound stops at admission — and a
    duck-typed replacement batcher (no server_span kwarg) falls back to
    batcher-owned spans without ever double-emitting."""

    class _SlowBatcher:
        def __init__(self, inner):
            self.inner = inner

        def is_alive(self):
            return True

        def close(self):
            self.inner.close()

        def submit(self, x, op, timeout_s=None, tenant=None):
            time.sleep(0.4)
            return self.inner.submit(x, op, timeout_s=timeout_s,
                                     tenant=tenant)

    run_dir = str(tmp_path / "shed_run")
    writer = EventWriter(run_dir)
    writer.run_start(runtime_manifest(extra={"mode": "serve"}))
    tracer = Tracer(writer)
    engine = InferenceEngine(model, params, batch_buckets=(1,))
    batcher = _SlowBatcher(MicroBatcher(engine, max_wait_ms=0.0,
                                        tracer=tracer))
    router = ReplicaRouter([ReplicaEntry(engine, batcher, 0)])
    server = DIBServer(router, port=0, admission_limit=1, tracer=tracer,
                       telemetry=writer,
                       registry=MetricsRegistry()).start()
    try:
        row = [0.0] * engine.feature_width
        results = []

        def client():
            results.append(_post(server.url + "/v1/predict", {"x": row}))

        threads = [threading.Thread(target=client) for _ in range(3)]
        threads[0].start()
        time.sleep(0.15)
        for t in threads[1:]:
            t.start()
        for t in threads:
            t.join(timeout=30)
        codes = sorted(status for status, _ in results)
        assert codes[0] == 200 and codes[-1] == 503
    finally:
        server.close()

    spans = _request_spans(run_dir)
    shed = [s for s in spans if s["status"] == "shed"]
    assert shed, "no shed span recorded"
    for span in shed:
        assert set(span["phases"]) == \
            {"read", "parse", "admission", "serialize", "write"}
        assert abs(sum(span["phases"].values()) - span["seconds"]) \
            <= _SUM_TOL
    # the duck-typed batcher kept span ownership for dispatched
    # requests: exactly one span per request, no doubles
    ok = [s for s in spans if s["status"] == "ok"]
    assert len(ok) == len([r for r in results if r[0] == 200])
    assert all("phases" not in s for s in ok), \
        "legacy batcher-owned spans must not fabricate phases"


# ------------------------------------------------- native histogram buckets
def test_bucket_merge_is_bit_identical_to_combined_stream():
    """THE fleet-merge contract: two workers' bucket vectors summed
    index-wise give the same p50/p90/p99 as one histogram that saw every
    value — exact, not approximate, because the bounds are fixed
    fleet-wide."""
    rng = np.random.default_rng(17)
    worker_a, worker_b, combined = Histogram(), Histogram(), Histogram()
    for i, value in enumerate(rng.lognormal(-6.0, 2.0, size=4001)):
        (worker_a if i % 2 else worker_b).record(float(value))
        combined.record(float(value))
    merged = [a + b for a, b in zip(
        bucket_counts(worker_a.snapshot()),
        bucket_counts(worker_b.snapshot()))]
    reference = bucket_counts(combined.snapshot())
    assert merged == reference
    for q in (0.5, 0.9, 0.99):
        assert bucket_quantile(merged, q) == bucket_quantile(reference, q)


def test_bucket_bounds_are_fixed_and_log_spaced():
    assert len(BUCKET_BOUNDS) == 65
    assert BUCKET_BOUNDS[0] == pytest.approx(1e-6)
    assert BUCKET_BOUNDS[-1] == pytest.approx(100.0)
    ratios = [b / a for a, b in zip(BUCKET_BOUNDS, BUCKET_BOUNDS[1:])]
    assert all(r == pytest.approx(10 ** 0.125) for r in ratios)


def test_prometheus_native_histogram_exposition():
    """The `_hist` family: cumulative `_bucket` lines, `+Inf` ALWAYS
    emitted (and equal to `_count`), `_hist_sum`/`_hist_count` agreeing
    with the summary family — on both a populated and an EMPTY
    histogram."""
    registry = _Registry()
    hist = registry.histogram("serve.request_latency_s")
    for value in (0.001, 0.002, 0.004, 0.008, 5.0, 1000.0):
        hist.record(value)
    registry.histogram("serve.phase.parse")   # empty
    text = prometheus_text(registry.snapshot())
    lines = text.splitlines()

    assert "# TYPE dib_serve_request_latency_s_hist histogram" in lines
    bucket_lines = [l for l in lines if
                    l.startswith("dib_serve_request_latency_s_hist_bucket")]
    counts = [float(l.rsplit(" ", 1)[1]) for l in bucket_lines]
    assert counts == sorted(counts), "buckets must be cumulative"
    assert bucket_lines[-1].startswith(
        'dib_serve_request_latency_s_hist_bucket{le="+Inf"}')
    assert counts[-1] == 6.0
    assert "dib_serve_request_latency_s_hist_count 6" in text
    assert "dib_serve_request_latency_s_count 6" in text
    # the 1000.0 value overflows the last bound: +Inf strictly exceeds
    # the largest finite bucket
    finite = [l for l in bucket_lines if '+Inf' not in l]
    assert float(finite[-1].rsplit(" ", 1)[1]) == 5.0
    # an empty histogram still exposes the +Inf bucket at 0
    assert 'dib_serve_phase_parse_hist_bucket{le="+Inf"} 0' in text


# ------------------------------------------------------- phase-clock overhead
def test_phase_clock_overhead_under_2pct_of_request_latency(
        model, params, tmp_path):
    """Paired, same-run bound: a full clock cycle (8 stamps + the phases
    rollup) must cost < 2% of the MEASURED p50 request latency on this
    host — the stamping rides the existing <2% telemetry budget."""
    run_dir = str(tmp_path / "overhead_run")
    server, _ = _stack(model, params, run_dir)
    latencies = []
    try:
        row = [0.0] * server.router.entries[0].engine.feature_width
        for _ in range(30):
            t0 = time.perf_counter()   # timing-ok: host-side HTTP latency, no jitted call in the interval
            assert _post(server.url + "/v1/predict", {"x": row})[0] == 200
            latencies.append(time.perf_counter() - t0)   # timing-ok: host-side HTTP latency, no jitted call in the interval
    finally:
        server.close()
    p50 = sorted(latencies)[len(latencies) // 2]

    n = 2000
    t0 = time.perf_counter()   # timing-ok: host-side microbenchmark, no jitted call in the interval
    for _ in range(n):
        clock = _PhaseClock(time.perf_counter())   # timing-ok: the measured workload itself
        for phase in REQUEST_PHASES:
            clock.stamp(phase)
        clock.phases()
    per_request = (time.perf_counter() - t0) / n   # timing-ok: host-side microbenchmark, no jitted call in the interval
    assert per_request < 0.02 * p50, \
        f"clock cycle {per_request * 1e6:.1f}µs vs p50 {p50 * 1e3:.2f}ms"


# ----------------------------------------------- rollup and regression gate
def test_serving_rollup_phases_and_compare_gate(model, params, bundle,
                                                tmp_path):
    """`summarize` rolls span phases into serving.phases (count/p50/p99/
    mean/share, shares summing to 1), and `compare` gates a per-phase
    p99 regression — but not sub-floor µs jitter."""
    from dib_tpu.telemetry import summarize
    from dib_tpu.telemetry.summary import compare

    run_dir = str(tmp_path / "rollup_run")
    server, _ = _stack(model, params, run_dir)
    try:
        rows = np.asarray(bundle.x_valid[:8], np.float32)
        for i in range(8):
            assert _post(server.url + "/v1/predict",
                         {"x": rows[i].tolist()})[0] == 200
    finally:
        server.close()

    summary = summarize(run_dir)
    phases = summary["serving"]["phases"]
    assert set(phases) == set(REQUEST_PHASES)
    for stats in phases.values():
        assert stats["count"] == 8
        assert stats["p99_ms"] >= stats["p50_ms"] >= 0
        assert 0 <= stats["share"] <= 1
    assert sum(s["share"] for s in phases.values()) == \
        pytest.approx(1.0, abs=0.01)

    # a 3x parse-p99 blowup past the 0.1 ms floor regresses...
    import copy
    worse = copy.deepcopy(summary)
    worse["serving"]["phases"]["parse"]["p99_ms"] = \
        max(phases["parse"]["p99_ms"] * 3, 1.0)
    report, regressed = compare(summary, worse)
    assert regressed
    assert report["fields"]["serving_phase_parse_p99_ms"]["regressed"]
    # ...while a large RELATIVE move inside the 0.1 ms absolute floor
    # is jitter, not a page
    tiny_a, tiny_b = copy.deepcopy(summary), copy.deepcopy(summary)
    tiny_a["serving"]["phases"]["parse"]["p99_ms"] = 0.01
    tiny_b["serving"]["phases"]["parse"]["p99_ms"] = 0.05
    report, _ = compare(tiny_a, tiny_b)
    assert not report["fields"]["serving_phase_parse_p99_ms"]["regressed"]


# ------------------------------------------------------------- serve top
def _load_loadgen():
    spec = importlib.util.spec_from_file_location(
        "serve_loadgen", os.path.join(REPO, "scripts", "serve_loadgen.py"))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_serve_top_once_renders_live_prefork_fleet(tmp_path):
    """`python -m dib_tpu serve top --once` against a REAL 2-worker
    prefork fleet through the CLI: rc 0, both workers seen, the
    fleet-merged end-to-end and per-phase rows render with data."""
    lg = _load_loadgen()
    ckpt_dir, _, _ = lg._train_tiny_checkpoint(6)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "dib_tpu", "serve",
         "--checkpoint_dir", ckpt_dir, *lg._TINY_ARCH_FLAGS,
         "--prefork", "2", "--port", "0",
         "--buckets", "1", "8", "--max_batch", "8",
         "--outdir", str(tmp_path / "fleet")],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=REPO, env=env)
    try:
        hello = json.loads(proc.stdout.readline())
        url = hello["serving"]
        with urllib.request.urlopen(url + "/healthz", timeout=30) as resp:
            width = json.loads(resp.read())["feature_width"]
        row = [0.0] * width
        for i in range(12):
            status, _ = _post(url + "/v1/predict",
                              {"x": [float(i)] + row[1:]})
            assert status == 200

        top = subprocess.run(
            [sys.executable, "-m", "dib_tpu", "serve", "top",
             "--url", url, "--workers", "2", "--once"],
            capture_output=True, text=True, timeout=120, cwd=REPO,
            env=env)
        assert top.returncode == 0, top.stderr
        frame = top.stdout
        assert "dib serve top" in frame
        assert "2/2 worker(s) seen" in frame
        assert "fleet end-to-end" in frame
        for phase in REQUEST_PHASES:
            assert phase in frame
        # the merged end-to-end histogram saw every request
        assert "n=12" in frame
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_serve_top_reports_failure_when_no_fleet(tmp_path):
    """No fleet behind the URL: one frame, honest empty render, rc 1."""
    top = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "serve", "top",
         "--url", "http://127.0.0.1:9", "--workers", "1", "--once"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert top.returncode == 1
    assert "no /metrics sample yet" in top.stdout


# ------------------------------------------- committed-record schema checks
def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_run_artifacts",
        os.path.join(REPO, "scripts", "check_run_artifacts.py"))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _good_phase_record():
    phases = {
        name: {"count": 100, "mean_ms": 0.5, "p50_ms": 0.4, "p99_ms": 1.0}
        for name in REQUEST_PHASES
    }
    return {
        "metric": "serve_phase_anatomy", "unit": "ms",
        "mode": "open_sweep", "value": 1.0,
        "parse_p99_ms": 1.0, "serialize_p99_ms": 1.0,
        "parse_serialize_share": 0.25,
        "rows": [{
            "target_rate": 400.0, "requests_sent": 100, "ok": 100,
            "phases": phases,
            "e2e_server": {"count": 100, "mean_ms": 4.0, "p50_ms": 3.5,
                           "p99_ms": 8.0},
            "phase_sum_ms": 4.0,
            "e2e_cumulative_buckets": [0, 10, 50, 100],
        }],
    }


def test_check_run_artifacts_accepts_wellformed_phase_record():
    checker = _load_checker()
    problems: list = []
    checker._check_serve_phases_bench(_good_phase_record(), problems)
    assert problems == []


@pytest.mark.parametrize("mutate, expect", [
    (lambda r: r["rows"][0].update(phase_sum_ms=5.0),
     "not within 5%"),
    (lambda r: r["rows"][0].update(e2e_cumulative_buckets=[0, 50, 30, 100]),
     "monotone"),
    (lambda r: r["rows"][0].update(e2e_cumulative_buckets=[0, 10, 50, 99]),
     "disagree"),
    (lambda r: r["rows"][0]["phases"].update(
        warp={"count": 1, "mean_ms": 1.0, "p50_ms": 1.0, "p99_ms": 1.0}),
     "REQUEST_PHASES"),
    (lambda r: r["rows"][0]["phases"]["parse"].update(p99_ms=float("nan")),
     "finite"),
    (lambda r: r.update(parse_p99_ms=None), "parse_p99_ms"),
    (lambda r: r.update(parse_serialize_share=1.7), "fraction"),
    (lambda r: r.update(rows=[]), "non-empty"),
])
def test_check_run_artifacts_rejects_broken_phase_records(mutate, expect):
    checker = _load_checker()
    record = _good_phase_record()
    mutate(record)
    problems: list = []
    checker._check_serve_phases_bench(record, problems)
    assert problems, f"mutation expecting {expect!r} went undetected"
    assert any(expect in p for p in problems), problems


def test_committed_phase_bench_passes_schema_and_slo():
    """The committed BENCH_SERVE_PHASES_CPU.json validates per-row and
    clears the phase SLO ceilings through `telemetry check`."""
    path = os.path.join(REPO, "BENCH_SERVE_PHASES_CPU.json")
    record = json.load(open(path))
    checker = _load_checker()
    problems: list = []
    checker._check_serve_phases_bench(record, problems)
    assert problems == [], problems
    from dib_tpu.telemetry.slo import check_run

    report = check_run(path, os.path.join(REPO, "SLO.json"), write=False)
    assert report["violations"] == 0, report
