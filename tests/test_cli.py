"""CLI trainer: flag surface, artifact production, sweep path, InfoNCE path."""

import json
import os

import numpy as np
import pytest

from dib_tpu.cli import build_parser, run


def make_args(tmp_path, *extra):
    argv = [
        "train",
        "--dataset", "boolean_circuit",
        "--artifact_outdir", str(tmp_path),
        "--number_pretraining_epochs", "5",
        "--number_annealing_epochs", "10",
        "--batch_size", "64",
        "--feature_encoder_architecture", "16",
        "--integration_network_architecture", "32",
        "--feature_embedding_dimension", "4",
        "--max_val_points", "256",
        *extra,
    ]
    return build_parser().parse_args(argv)


def test_parser_defaults_match_reference_surface():
    args = build_parser().parse_args([])
    # reference train.py defaults (train.py:12-74)
    assert args.dataset == "boolean_circuit"
    assert args.learning_rate == 3e-4
    assert args.beta_start == 1e-4 and args.beta_end == 3.0
    assert args.number_pretraining_epochs == 1000
    assert args.number_annealing_epochs == 10000
    assert args.batch_size == 128
    assert args.feature_encoder_architecture == [128, 128]
    assert args.integration_network_architecture == [256, 256]
    assert args.number_positional_encoding_frequencies == 5
    assert args.infonce_shared_dimensionality == 64
    assert args.infonce_similarity == "l2"
    assert args.use_positional_encoding is True
    # boolean flags are real booleans, not the reference's broken type=bool
    args2 = build_parser().parse_args(["--no-use_positional_encoding", "--ib"])
    assert args2.use_positional_encoding is False and args2.ib is True


@pytest.mark.slow
def test_cli_train_produces_artifacts(tmp_path):
    args = make_args(tmp_path, "--info_bounds_frequency", "5")
    summary = run(args)
    assert summary["dataset"] == "boolean_circuit"
    assert os.path.exists(tmp_path / "history.npz")
    assert os.path.exists(tmp_path / "distributed_info_plane.png")
    assert os.path.exists(tmp_path / "info_bounds.npz")
    hist = np.load(tmp_path / "history.npz")
    assert hist["beta"].shape == (15,)
    assert hist["kl_per_feature"].shape == (15, 10)
    bounds = np.load(tmp_path / "info_bounds.npz")
    assert bounds["bounds_bits"].shape[1:] == (10, 2)
    assert np.isfinite(summary["final_val_loss"])
    json.dumps(summary)  # summary must be JSON-serializable


@pytest.mark.slow
def test_cli_vanilla_ib_single_bottleneck(tmp_path):
    args = make_args(tmp_path, "--ib")
    summary = run(args)
    hist = np.load(tmp_path / "history.npz")
    assert hist["kl_per_feature"].shape == (15, 1)   # one joint bottleneck


@pytest.mark.slow
def test_cli_sweep_path(tmp_path):
    args = make_args(tmp_path, "--sweep_beta_ends", "0.1", "1.0",
                     "--sweep_repeats", "2")
    summary = run(args)
    assert summary["num_replicas"] == 4
    assert len(summary["final_val_loss"]) == 4
    for r in range(4):
        assert os.path.exists(tmp_path / f"history_replica{r}.npz")
        assert os.path.exists(tmp_path / f"distributed_info_plane_replica{r}.png")


@pytest.mark.slow
def test_cli_infonce_path(tmp_path):
    args = make_args(
        tmp_path, "--infonce_loss",
        "--infonce_shared_dimensionality", "8",
        "--infonce_y_encoder_architecture", "16",
    )
    summary = run(args)
    assert np.isfinite(summary["final_val_loss"])
    assert os.path.exists(tmp_path / "history.npz")


@pytest.mark.slow
def test_cli_workload_boolean_tiny(capsys):
    from dib_tpu.cli import main

    rc = main([
        "workload", "boolean",
        "--set", "num_steps=40", "--set", "mi_every=20",
        "--set", "batch_size=64",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "history" in summary


def test_cli_workload_rejects_unknown_field():
    from dib_tpu.cli import main

    with pytest.raises(SystemExit):
        main(["workload", "boolean", "--set", "not_a_field=1"])


def test_cli_workload_rejects_mesh_override():
    # 'mesh' takes a jax.sharding.Mesh and cannot be expressed as a --set
    # literal; a coerced string would fail deep inside the workload
    from dib_tpu.cli import main

    with pytest.raises(SystemExit):
        main(["workload", "chaos_state_sweep", "--set", "mesh=beta2"])


def test_bare_string_protocols_wrapped(monkeypatch, tmp_path):
    # protocols="GradualQuench" (e.g. from --set coercion or a Python API
    # caller) must run ONE protocol, not iterate character-by-character
    import dib_tpu.workloads.amorphous as am

    calls = []

    def fake_workload(key, config=None, outdir=None, protocol=None, **kw):
        calls.append(protocol)
        return {"protocol": protocol}

    monkeypatch.setattr(am, "run_amorphous_workload", fake_workload)
    result = am.run_amorphous_protocols(
        0, protocols="GradualQuench", outdir=str(tmp_path)
    )
    assert calls == ["GradualQuench"]
    assert set(result) == {"GradualQuench"}


def test_cli_checkpoint_resume(tmp_path):
    """--checkpoint_dir saves on the cadence and a re-invocation continues
    the run instead of restarting (crash-resumable long runs; SURVEY
    section 5 checkpoint/resume exposed through the CLI)."""
    ckpt = str(tmp_path / "ckpt")
    args = make_args(tmp_path, "--checkpoint_dir", ckpt,
                     "--checkpoint_frequency", "5")
    summary1 = run(args)
    assert "resumed_from_epoch" not in summary1
    assert os.path.isdir(ckpt) and os.listdir(ckpt)

    # second invocation with a LONGER budget resumes at the saved epoch
    args2 = make_args(tmp_path, "--checkpoint_dir", ckpt,
                      "--checkpoint_frequency", "5",
                      "--number_annealing_epochs", "20")
    summary2 = run(args2)
    assert summary2["resumed_from_epoch"] == 15


def test_save_info_bounds_merges_on_resume(tmp_path):
    """ADVICE round 3 (cli.py:281): a resumed run's info_bounds npz must
    keep the pre-crash trajectory, not silently overwrite it."""
    from dib_tpu.cli import _save_info_bounds

    path = str(tmp_path / "info_bounds.npz")
    _save_info_bounds(path, [2, 4], np.zeros((2, 3, 2)))
    with np.load(path) as d:
        assert d["epochs"].tolist() == [2, 4]
        assert "resumed_from_epoch" not in d

    # resumed segment starts after the crash point: earlier records prepended
    _save_info_bounds(path, [6, 8], np.ones((2, 3, 2)), resumed_from=4)
    with np.load(path) as d:
        assert d["epochs"].tolist() == [2, 4, 6, 8]
        assert int(d["resumed_from_epoch"]) == 4
        np.testing.assert_array_equal(d["bounds_bits"][:2], 0.0)
        np.testing.assert_array_equal(d["bounds_bits"][2:], 1.0)

    # overlap (hook re-recorded epoch 4 post-resume): no duplicate epochs
    _save_info_bounds(path, [4, 10], np.full((2, 3, 2), 2.0), resumed_from=2)
    with np.load(path) as d:
        assert d["epochs"].tolist() == [2, 4, 10]


@pytest.mark.slow
def test_cli_resume_preserves_info_bounds_trajectory(tmp_path):
    """End-to-end: --info_bounds_frequency + checkpoint resume yields ONE
    npz spanning both segments (ADVICE round 3)."""
    ckpt = str(tmp_path / "ckpt")
    base = ["--checkpoint_dir", ckpt, "--checkpoint_frequency", "5",
            "--info_bounds_frequency", "5"]
    run(make_args(tmp_path, *base))
    first = np.load(tmp_path / "info_bounds.npz")["epochs"].tolist()
    assert first == [5, 10, 15]

    summary2 = run(make_args(tmp_path, *base,
                             "--number_annealing_epochs", "20"))
    assert summary2["resumed_from_epoch"] == 15
    with np.load(tmp_path / "info_bounds.npz") as d:
        assert d["epochs"].tolist() == [5, 10, 15, 20, 25]
        assert int(d["resumed_from_epoch"]) == 15


@pytest.mark.slow
def test_cli_sweep_checkpoint_resume(tmp_path):
    """--checkpoint_dir on the SWEEP path: stacked [R, ...] checkpoint saved
    on the cadence; a re-invocation with a longer budget resumes every
    replica at the saved epoch (code review round 3: the flag must not be
    silently inert on sweeps). With --info_bounds_frequency, each replica's
    bounds npz must splice the pre-crash trajectory on resume, like the
    serial path (ADVICE round 3)."""
    ckpt = str(tmp_path / "ckpt")
    base = ["--sweep_beta_ends", "0.1", "1.0",
            "--checkpoint_dir", ckpt, "--checkpoint_frequency", "5",
            "--info_bounds_frequency", "5"]
    summary1 = run(make_args(tmp_path, *base))
    assert "resumed_from_epoch" not in summary1
    assert summary1["num_replicas"] == 2
    assert os.path.isdir(ckpt) and os.listdir(ckpt)
    assert np.load(tmp_path / "info_bounds_replica0.npz")["epochs"].tolist() \
        == [5, 10, 15]

    summary2 = run(make_args(tmp_path, *base,
                             "--number_annealing_epochs", "20"))
    assert summary2["resumed_from_epoch"] == 15
    assert len(summary2["final_val_loss"]) == 2
    for r in range(2):
        with np.load(tmp_path / f"info_bounds_replica{r}.npz") as d:
            assert d["epochs"].tolist() == [5, 10, 15, 20, 25]
            assert int(d["resumed_from_epoch"]) == 15


def test_subcommand_after_flags_exits_2_naming_flag(capsys):
    """ISSUE 3 satellite: a subcommand parsed from a non-leading position
    is a usage error — exit code 2 (argparse convention), with the flag
    that displaced it NAMED in the message."""
    from dib_tpu.cli import main

    for command in ("telemetry", "workload", "serve"):
        rc = main(["--seed", "1", command])
        assert rc == 2
        err = capsys.readouterr().err
        assert f"'{command}' subcommand must come first" in err
        assert "'--seed'" in err
        assert f"python -m dib_tpu {command}" in err


def test_subcommand_ordering_error_via_subprocess():
    """The exit code survives the real entry point (`python -m dib_tpu`)."""
    import subprocess
    import sys as _sys

    proc = subprocess.run(
        [_sys.executable, "-m", "dib_tpu", "--seed", "1", "telemetry"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 2
    assert "subcommand must come first" in proc.stderr


def test_serve_parser_shares_model_flag_surface():
    """`dib_tpu serve` must accept the SAME model/architecture flags as
    train (it rebuilds the checkpointed architecture from them), plus its
    serving knobs."""
    from dib_tpu.cli import serve_parser

    args = serve_parser().parse_args([
        "--checkpoint_dir", "/tmp/ck",
        "--dataset", "boolean_circuit",
        "--feature_encoder_architecture", "16",
        "--integration_network_architecture", "32",
        "--feature_embedding_dimension", "4",
        "--port", "0", "--buckets", "1", "8",
        "--max_batch", "16", "--max_wait_ms", "3",
    ])
    assert args.checkpoint_dir == "/tmp/ck"
    assert args.feature_encoder_architecture == [16]
    assert args.buckets == [1, 8]
    assert args.max_batch == 16
    # train-side defaults shared via _add_model_flags stay aligned
    train_args = build_parser().parse_args([])
    for flag in ("dataset", "activation_fn", "feature_embedding_dimension",
                 "use_positional_encoding",
                 "number_positional_encoding_frequencies", "compute_dtype"):
        assert getattr(serve_parser().parse_args(
            ["--checkpoint_dir", "x"]), flag) == getattr(train_args, flag)


def test_serve_requires_checkpoint_dir():
    from dib_tpu.cli import serve_parser

    with pytest.raises(SystemExit):
        serve_parser().parse_args([])
