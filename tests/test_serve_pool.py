"""Multi-process replica pool: worker death degrades, probes respawn
(docs/serving.md "The multi-process replica pool").

The PR 4 ejection drill shape, re-proven for PROCESSES: SIGKILL a worker
subprocess mid-service and every client call still answers 200 off the
surviving replica (zero client-visible 5xx), the dead replica ejects,
and the re-admission probe respawns the subprocess and brings the pool
back to full width.
"""

import json
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.models import DistributedIBModel
from dib_tpu.serve import DIBServer, WorkerDiedError, pool_router
from dib_tpu.telemetry import MetricsRegistry


@pytest.fixture(scope="module")
def bundle():
    return get_dataset("boolean_circuit")


@pytest.fixture(scope="module")
def model(bundle):
    return DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )


@pytest.fixture(scope="module")
def params(bundle, model):
    x0 = np.asarray(bundle.x_train[:4], np.float32)
    return model.init(jax.random.key(0), x0, jax.random.key(1))


def _post(url: str, payload: dict):
    request = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(request, timeout=60) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def test_pool_worker_death_degrades_then_probe_respawns(
        model, params, bundle):
    """One long test (worker spawns are the expensive part): healthy pool
    serves bit-identically to an in-process engine; a SIGKILLed worker
    yields ZERO client-visible 5xx while the survivor carries the load;
    the probe respawns the dead process and re-admits the replica."""
    from dib_tpu.serve import InferenceEngine

    registry = MetricsRegistry()
    router = pool_router(
        model, params, num_workers=2, batch_buckets=(1, 4),
        max_wait_ms=1.0, eject_after=1,
        probe_after_s=0.0,       # no background thread: probes are manual
        probe_timeout_s=60.0,    # a respawn IS slow; the probe waits it out
        registry=registry,
    )
    server = DIBServer(router, port=0, registry=registry).start()
    try:
        rows = np.asarray(bundle.x_valid[:4], np.float32)
        width = rows.shape[1]

        # ---- healthy pool: results identical to an in-process engine
        want = InferenceEngine(model, params,
                               batch_buckets=(1, 4)).predict(rows)
        for i in range(4):
            status, payload = _post(server.url + "/v1/predict",
                                    {"x": rows[i].tolist()})
            assert status == 200
            np.testing.assert_allclose(payload["prediction"][0],
                                       want["prediction"][i], rtol=1e-6)
        # both subprocess replicas took traffic (round-robin)
        pids = {router.entries[i].engine.pid for i in range(2)}
        assert len(pids) == 2 and all(p for p in pids)

        # ---- SIGKILL worker 0 mid-service
        victim = router.entries[0].engine
        victim.kill()

        # every call during degradation still answers 200: the dead
        # replica's failure marks it and the request retries on the
        # survivor — zero client-visible 5xx
        codes = []

        def client(i):
            status, _ = _post(server.url + "/v1/predict",
                              {"x": rows[i % 4].tolist()})
            codes.append(status)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert codes == [200] * 8
        assert router.entries[0].ejected
        status, health = urllib.request.urlopen(
            server.url + "/healthz", timeout=30).status, None
        assert status == 200   # still serviceable on the survivor

        # ---- probe-driven respawn: the ejected entry's probe dispatch
        # relaunches the subprocess, and a fresh interpreter + engine
        # re-admits it
        readmitted = router.probe_ejected(force=True)
        assert readmitted == 1
        assert not router.entries[0].ejected
        assert victim.respawns == 1
        assert victim.pid not in (None,) and victim.alive()
        # the respawned worker serves bit-identically
        status, payload = _post(server.url + "/v1/predict",
                                {"x": rows[0].tolist()})
        assert status == 200
        np.testing.assert_allclose(payload["prediction"][0],
                                   want["prediction"][0], rtol=1e-6)
    finally:
        server.close()


def test_worker_spec_rejects_dead_worker_without_respawn(model, params):
    """respawn=False is the hard-degradation mode: a dead worker stays a
    WorkerDiedError (the router ejects it permanently)."""
    from dib_tpu.serve.pool import WorkerReplica, worker_spec

    spec = worker_spec(model, params, batch_buckets=(1,))
    worker = WorkerReplica(spec, respawn=False)
    try:
        worker.wait_ready(120.0)
        out = worker.predict(np.zeros(worker.feature_width, np.float32))
        assert out["prediction"].shape == (1, 1)
        worker.kill()
        with pytest.raises(WorkerDiedError):
            worker.predict(np.zeros(worker.feature_width, np.float32))
    finally:
        worker.close()
