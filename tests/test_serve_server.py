"""HTTP serving surface + the tier-1 CPU serving smoke test.

The smoke test is the CI gate the serving subsystem ships behind: an
in-process server, a handful of concurrent requests through the REAL
batcher, then assertions that the latency events landed on the run's
events.jsonl and that ``telemetry summarize`` / ``telemetry report``
accept the stream — a serving run dir is a first-class telemetry run.
"""

import json
import os
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.models import DistributedIBModel
from dib_tpu.serve import (
    DIBServer,
    InferenceEngine,
    MicroBatcher,
    ReplicaEntry,
    ReplicaRouter,
)
from dib_tpu.telemetry import (
    EventWriter,
    MetricsRegistry,
    Tracer,
    read_events,
    runtime_manifest,
    summarize,
)


@pytest.fixture(scope="module")
def bundle():
    return get_dataset("boolean_circuit")


@pytest.fixture(scope="module")
def model(bundle):
    return DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )


@pytest.fixture(scope="module")
def params(bundle, model):
    x0 = np.asarray(bundle.x_train[:4], np.float32)
    return model.init(jax.random.key(0), x0, jax.random.key(1))


def _post(url: str, payload: dict) -> tuple[int, dict]:
    request = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(request, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def _get(url: str) -> tuple[int, dict]:
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def _serving_stack(model, params, run_dir=None, beta_ends=(None,)):
    """An in-process server over `len(beta_ends)` entries sharing params."""
    writer = registry = tracer = None
    if run_dir is not None:
        writer = EventWriter(run_dir)
        writer.run_start(runtime_manifest(extra={"mode": "serve"}))
        registry = MetricsRegistry()
        tracer = Tracer(writer)
    entries = []
    for i, beta_end in enumerate(beta_ends):
        engine = InferenceEngine(model, params, batch_buckets=(1, 4),
                                 telemetry=writer, registry=registry,
                                 beta_end=beta_end)
        batcher = MicroBatcher(engine, max_batch=4, max_wait_ms=1.0,
                               tracer=tracer, registry=registry)
        entries.append(ReplicaEntry(engine, batcher, i, beta_end=beta_end))
    router = ReplicaRouter(entries)
    server = DIBServer(router, port=0, telemetry=writer,
                       registry=registry).start()
    return server, registry


def test_serving_smoke_end_to_end(model, params, bundle, tmp_path):
    """THE serving CI gate (ISSUE 3 satellite): in-process server, real
    batcher, concurrent requests; latency events land on events.jsonl;
    summarize and report both accept the serving stream."""
    run_dir = str(tmp_path / "serve_run")
    server, registry = _serving_stack(model, params, run_dir=run_dir)
    rows = np.asarray(bundle.x_valid[:6], np.float32)
    statuses = []

    def client(i):
        status, payload = _post(server.url + "/v1/predict",
                                {"x": rows[i].tolist()})
        statuses.append((status, payload))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert [s for s, _ in statuses] == [200] * 6
    # responses carry the served quantities
    for _, payload in statuses:
        assert len(payload["prediction"]) == 1
        assert len(payload["kl_per_feature"][0]) == model.num_features
    status, enc = _post(server.url + "/v1/encode", {"x": rows[0].tolist()})
    assert status == 200 and "mus" in enc and "logvars" in enc

    # graceful shutdown writes the final metrics rollup + run_end
    server.close()

    events = list(read_events(run_dir))
    types = [e["type"] for e in events]
    assert types[0] == "run_start" and types[-1] == "run_end"
    compiles = [e for e in events if e["type"] == "compile"]
    assert {c["name"] for c in compiles} == {"serve.predict", "serve.encode"}
    assert all(c["cache"] == "aot" for c in compiles)
    spans = [e for e in events if e["type"] == "span"]
    request_spans = [e for e in spans if e["name"] == "request"]
    batch_spans = [e for e in spans if e["name"] == "batch"]
    assert len(request_spans) == 7          # 6 predicts + 1 encode
    assert all(e["status"] == "ok" and e["seconds"] >= 0
               for e in request_spans)
    assert batch_spans and all(0 < e["fill"] <= 1 for e in batch_spans)
    # every request was served by some batch (coalescing itself is pinned
    # deterministically in test_serve.py::test_batcher_coalesces_...)
    assert len(batch_spans) <= len(request_spans)
    assert sum(e["rows"] for e in batch_spans) == 7
    assert any(e["type"] == "metrics" for e in events)

    # `telemetry summarize` accepts the stream and rolls up serving stats
    summary = summarize(run_dir)
    assert summary["status"] == "ok"
    serving = summary["serving"]
    assert serving["requests"] == 7
    assert serving["statuses"] == {"ok": 7}
    assert serving["request_p99_ms"] >= serving["request_p50_ms"] >= 0
    assert serving["batches"] == len(batch_spans)

    # and `telemetry report` renders the serving run dir
    from dib_tpu.telemetry.report import write_report

    out = write_report(run_dir)
    assert os.path.exists(out) and os.path.getsize(out) > 1000


def test_http_error_mapping(model, params):
    server, _ = _serving_stack(model, params)
    try:
        width = server.router.entries[0].engine.feature_width
        # wrong width -> 400 with the validation message
        status, payload = _post(server.url + "/v1/predict",
                                {"x": [1.0, 2.0]})
        assert status == 400 and "width" in payload["error"]
        # missing x -> 400
        status, _ = _post(server.url + "/v1/predict", {"rows": [1.0]})
        assert status == 400
        # non-finite payload -> 400 (isolated at submit, never dispatched)
        status, payload = _post(server.url + "/v1/predict",
                                {"x": [float("nan")] * width})
        assert status == 400 and "non-finite" in payload["error"]
        # unknown routes -> 404
        status, _ = _post(server.url + "/v1/nope", {"x": [0.0] * width})
        assert status == 404
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            urllib.request.urlopen(server.url + "/nope", timeout=30)
        assert excinfo.value.code == 404
        # malformed JSON body -> 400
        request = urllib.request.Request(
            server.url + "/v1/predict", data=b"{not json",
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            urllib.request.urlopen(request, timeout=30)
        assert excinfo.value.code == 400
    finally:
        server.close()


def test_healthz_and_metrics_surface(model, params):
    server, _ = _serving_stack(model, params)
    try:
        status, health = _get(server.url + "/healthz")
        assert status == 200 and health["status"] == "ok"
        assert health["feature_width"] == sum(
            model.feature_dimensionalities)
        assert health["buckets"] == [1, 4]
        width = health["feature_width"]
        _post(server.url + "/v1/predict", {"x": [0.0] * width})
        status, metrics = _get(server.url + "/metrics")
        assert status == 200
        # no registry attached in this stack -> permitted empty; with one
        # the counters appear (covered by the smoke test's metrics event)
        assert isinstance(metrics, dict)
    finally:
        server.close()


def test_beta_routing_over_http(model, params):
    """A client asking for "the model at β≈x" reaches the replica whose
    annealing endpoint is log-nearest."""
    server, _ = _serving_stack(model, params, beta_ends=(0.01, 1.0))
    try:
        width = server.router.entries[0].engine.feature_width
        row = [0.0] * width
        status, payload = _post(server.url + "/v1/predict",
                                {"x": row, "beta": 0.02})
        assert status == 200 and payload["replica"]["beta_end"] == 0.01
        status, payload = _post(server.url + "/v1/predict",
                                {"x": row, "beta": 3.0})
        assert status == 200 and payload["replica"]["beta_end"] == 1.0
        status, payload = _post(server.url + "/v1/predict",
                                {"x": row, "beta": "high"})
        assert status == 400
    finally:
        server.close()


def test_loadgen_closed_loop_against_live_server(model, params):
    """The load generator's client loop drives a real server and records
    finite latencies (full self-contained mode is exercised by the
    committed artifact; this keeps the client path under CI)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_loadgen",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "serve_loadgen.py"),
    )
    loadgen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(loadgen)

    server, _ = _serving_stack(model, params)
    try:
        width = server.router.entries[0].engine.feature_width
        stats = loadgen.run_closed_loop(server.url, width,
                                        duration_s=0.5, concurrency=2)
        assert len(stats.latencies) > 0
        assert stats.errors == 0
        assert all(s >= 0 for s in stats.latencies)
    finally:
        server.close()


def test_engine_from_checkpoint_roundtrip(model, bundle, tmp_path):
    """Serve-side checkpoint loading: restore + manifest verification +
    bit-identical predictions from the restored params; an engine built
    with MISMATCHED architecture flags fails with the actionable
    manifest error, not a deep pytree mismatch."""
    from dib_tpu.train import (
        CheckpointHook,
        DIBCheckpointer,
        DIBTrainer,
        TrainConfig,
    )

    config = TrainConfig(batch_size=32, num_pretraining_epochs=2,
                         num_annealing_epochs=2, steps_per_epoch=1,
                         max_val_points=64)
    trainer = DIBTrainer(model, bundle, config)
    ckpt_dir = str(tmp_path / "ck")
    ckpt = DIBCheckpointer(ckpt_dir)
    state, _ = trainer.fit(jax.random.key(3), hooks=[CheckpointHook(ckpt)],
                           hook_every=4)
    ckpt.close()

    engine = InferenceEngine.from_checkpoint(trainer, ckpt_dir,
                                             batch_buckets=(1, 4))
    direct = InferenceEngine(model, jax.device_get(state.params["model"]),
                             batch_buckets=(1, 4))
    x = np.asarray(bundle.x_valid[:3], np.float32)
    np.testing.assert_array_equal(engine.predict(x)["prediction"],
                                  direct.predict(x)["prediction"])

    wrong_model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(12,), integration_hidden=(16,),   # wrong width
        output_dim=1, embedding_dim=2,
    )
    wrong_trainer = DIBTrainer(wrong_model, bundle, config)
    with pytest.raises(ValueError, match="param structure"):
        InferenceEngine.from_checkpoint(wrong_trainer, ckpt_dir,
                                        batch_buckets=(1,))


def test_metrics_prometheus_content_negotiation(model, params, bundle,
                                                tmp_path):
    """/metrics content-negotiates: JSON by default, Prometheus text
    exposition for text/plain Accept headers or ?format=prometheus —
    with counters/gauges/summaries carrying the dib_ prefix."""
    server, registry = _serving_stack(model, params,
                                      run_dir=str(tmp_path / "serve"))
    try:
        # drive one request so real serving counters exist
        row = np.asarray(bundle.x_valid[0], np.float32).tolist()
        status, _ = _post(server.url + "/v1/predict", {"x": row})
        assert status == 200

        # default stays JSON (unchanged surface)
        status, snapshot = _get(server.url + "/metrics")
        assert status == 200
        assert "counters" in snapshot

        def fetch_text(url, accept=None):
            request = urllib.request.Request(url)
            if accept:
                request.add_header("Accept", accept)
            with urllib.request.urlopen(request, timeout=30) as resp:
                return (resp.status, resp.headers.get("Content-Type"),
                        resp.read().decode())

        for url, accept in (
            (server.url + "/metrics", "text/plain;version=0.0.4"),
            (server.url + "/metrics?format=prometheus", None),
        ):
            status, ctype, text = fetch_text(url, accept)
            assert status == 200
            assert ctype.startswith("text/plain")
            assert "# TYPE dib_serve_requests_ok counter" in text
            assert "dib_serve_requests_ok 1" in text
            # latency histogram maps to a summary with quantile samples
            assert "# TYPE dib_serve_request_latency_s summary" in text
            assert 'dib_serve_request_latency_s{quantile="0.99"}' in text
            assert "dib_serve_request_latency_s_count 1" in text

        # an Accept that prefers JSON keeps JSON even with text/* present
        status, ctype, text = fetch_text(
            server.url + "/metrics", "application/json, text/plain")
        assert ctype.startswith("application/json")
        assert json.loads(text)
    finally:
        server.close()


def test_prometheus_text_renderer_shapes():
    from dib_tpu.telemetry.metrics import prometheus_text

    registry = MetricsRegistry()
    registry.counter("serve.requests.ok").inc(3)
    registry.gauge("queue.depth").set(2.0)
    hist = registry.histogram("latency_s")
    for v in (0.1, 0.2, 0.3):
        hist.record(v)
    text = prometheus_text(registry.snapshot())
    assert "# TYPE dib_serve_requests_ok counter" in text
    assert "dib_serve_requests_ok 3" in text
    assert "dib_queue_depth 2" in text
    assert 'dib_latency_s{quantile="0.5"} 0.2' in text
    assert "dib_latency_s_sum 0.6" in text
    assert "dib_latency_s_count 3" in text
    assert "dib_latency_s_max 0.3" in text
    assert text.endswith("\n")


def test_prometheus_counters_keep_full_precision():
    """Review hardening: a 7-digit counter must not be exposed in %g
    scientific form (scraped rate()/increase() would drift)."""
    from dib_tpu.telemetry.metrics import prometheus_text

    registry = MetricsRegistry()
    registry.counter("serve.requests.ok").inc(1234567)
    text = prometheus_text(registry.snapshot())
    assert "dib_serve_requests_ok 1234567\n" in text
    assert "e+06" not in text
