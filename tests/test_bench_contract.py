"""bench.py output contract: ALWAYS one parseable JSON line, rc 0.

Round 1 burned its perf round on a dead TPU tunnel producing rc=1 and no
JSON; the parent/child redesign must never regress to that. The degraded
path is cheap to pin (budget too small to probe -> immediate fallback to
the committed cache); the measurement path is covered by driving bench.py
on hardware.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_bench(env_extra):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)     # parent never initializes a backend
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=120, env=env,
    )


def test_degraded_output_is_parseable_json():
    proc = run_bench({"DIB_BENCH_TOTAL_BUDGET_S": "1"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = proc.stdout.strip().splitlines()
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    record = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in record, f"missing {key!r}"
    assert record["degraded"] in ("no_device", "measurement_failed")
    # the committed cache backs the degraded record with a real number
    assert record["value"] is not None
    assert record["unit"] == "minutes"


def test_degraded_without_cache_still_parses():
    proc = run_bench({"DIB_BENCH_TOTAL_BUDGET_S": "1", "DIB_BENCH_FRESH": "1"})
    assert proc.returncode == 0
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert record["value"] is None
    assert "no cached measurement" in record["detail"]


def test_cache_file_is_committed_and_coherent():
    with open(os.path.join(REPO, "BENCH_CACHE.json")) as f:
        cached = json.load(f)
    assert cached["metric"] == "amorphous_set_transformer_beta_sweep_projected"
    assert cached["value"] > 0
    assert cached["vs_baseline"] == pytest.approx(cached["value"] / 10.0, rel=0.01)
