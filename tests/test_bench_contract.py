"""bench.py output contract: ALWAYS one parseable JSON line, rc 0.

Round 1 burned its perf round on a dead TPU tunnel producing rc=1 and no
JSON; the parent/child redesign must never regress to that. The degraded
path is cheap to pin (budget too small to probe -> immediate fallback to
the committed cache); the measurement path is covered by driving bench.py
on hardware.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_bench(env_extra):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)     # parent never initializes a backend
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=120, env=env,
    )


def test_degraded_output_is_parseable_json():
    proc = run_bench({"DIB_BENCH_TOTAL_BUDGET_S": "1"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = proc.stdout.strip().splitlines()
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    record = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in record, f"missing {key!r}"
    assert record["degraded"] in ("no_device", "measurement_failed")
    # the committed cache backs the degraded record with a real number
    assert record["value"] is not None
    assert record["unit"] == "minutes"


def test_degraded_without_cache_still_parses():
    proc = run_bench({"DIB_BENCH_TOTAL_BUDGET_S": "1", "DIB_BENCH_FRESH": "1"})
    assert proc.returncode == 0
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert record["value"] is None
    assert "no cached measurement" in record["detail"]


def test_cache_file_is_committed_and_coherent():
    with open(os.path.join(REPO, "BENCH_CACHE.json")) as f:
        cached = json.load(f)
    assert cached["metric"] == "amorphous_set_transformer_beta_sweep_projected"
    assert cached["value"] > 0
    assert cached["vs_baseline"] == pytest.approx(cached["value"] / 10.0, rel=0.01)
    # The cache must carry the CURRENT writer's MFU semantics — a cache from
    # an older bench.py (different keys / HLO-based headline mfu) would be
    # republished verbatim on every degraded run (code review round 3).
    assert "flops_per_step_model" in cached
    sys.path.insert(0, REPO)
    import bench
    from dib_tpu.models import PerParticleDIBModel

    model = PerParticleDIBModel(num_particles=50, compute_dtype="bfloat16")
    expect = bench.analytic_model_flops_per_step(model, bench.BENCH_BATCH_SIZE)
    assert cached["flops_per_step_model"] == pytest.approx(expect, rel=1e-6)
    peak = bench.peak_tflops_for(cached["device_kind"])
    assert cached["mfu"] == pytest.approx(
        expect * cached["steps_per_s"] / 1e12 / peak, abs=2e-4
    )


def test_analytic_model_flops_are_plausible():
    # The headline MFU divides analytic model matmul FLOPs by chip peak; a
    # silent unit slip (per-particle vs per-batch, fwd vs fwd+bwd) would be
    # invisible in the JSON, so pin the magnitude for the paper config.
    sys.path.insert(0, REPO)
    import bench
    from dib_tpu.models import PerParticleDIBModel

    model = PerParticleDIBModel(num_particles=50)
    flops = bench.analytic_model_flops_per_step(model, bench.BENCH_BATCH_SIZE)
    # 6 blocks x 12 heads x key_dim 128 over 50 particles at batch 32,
    # fwd+bwd: order 10 GFLOP. Bracket generously but exclude the failure
    # modes above (they are each >= 3x off).
    assert 5e9 < flops < 1e11, flops
    assert bench.analytic_model_flops_per_step(model, 64) == pytest.approx(
        2.0 * flops, rel=1e-6
    )


def test_all_committed_run_artifacts_validate():
    # Shared schema over EVERY committed BENCH_*/NORTHSTAR_* artifact —
    # the full checker lives in scripts/check_run_artifacts.py (also
    # standalone: `python scripts/check_run_artifacts.py`).
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from check_run_artifacts import check_all

    results = check_all(REPO)
    assert results, "no run artifacts found at repo root"
    bad = {path: probs for path, probs in results.items() if probs}
    assert not bad, f"artifact schema violations: {bad}"


def test_artifact_checker_rejects_malformed_records(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from check_run_artifacts import check_file

    cases = {
        # null value with no degraded/error/breakdown explanation
        "BENCH_hole.json": {"metric": "m", "unit": "minutes", "value": None},
        # a number nothing downstream can parse back
        "BENCH_nan.json": '{"metric": "m", "unit": "s", "value": NaN}',
        # neither a metric record nor a driver capture
        "NORTHSTAR_shape.json": {"something": "else"},
        # unparseable timestamp
        "BENCH_when.json": {"metric": "m", "unit": "s", "value": 1.0,
                            "measured_at": "yesterday-ish"},
    }
    for name, record in cases.items():
        path = tmp_path / name
        path.write_text(record if isinstance(record, str)
                        else json.dumps(record))
        assert check_file(str(path)), f"{name} should have been rejected"

    ok = tmp_path / "BENCH_ok.json"
    ok.write_text(json.dumps(
        {"metric": "m", "unit": "minutes", "value": 1.5,
         "vs_baseline": 0.15, "measured_at": "2026-08-02T00:00:00Z"}))
    assert check_file(str(ok)) == []


def test_save_cache_refreshes_when_env_matches_defaults(tmp_path, monkeypatch):
    # ADVICE round 2: exporting the DEFAULT values must not block the cache
    # refresh — only effectively non-default configurations may.
    import importlib

    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench

    env_vars = ("DIB_BENCH_REPLICAS", "DIB_BENCH_MEASURE_EPOCHS",
                "DIB_BENCH_STEPS_PER_EPOCH")
    try:
        monkeypatch.setenv("DIB_BENCH_REPLICAS", "8")
        monkeypatch.setenv("DIB_BENCH_MEASURE_EPOCHS", "6")
        monkeypatch.setenv("DIB_BENCH_STEPS_PER_EPOCH", "50")
        bench = importlib.reload(bench)   # re-read env into module constants
        monkeypatch.setattr(bench, "CACHE_PATH", str(tmp_path / "cache.json"))
        bench.save_cache({"metric": bench.METRIC, "value": 1.0})
        assert os.path.exists(bench.CACHE_PATH)

        monkeypatch.setenv("DIB_BENCH_REPLICAS", "2")
        bench = importlib.reload(bench)
        monkeypatch.setattr(bench, "CACHE_PATH", str(tmp_path / "cache2.json"))
        bench.save_cache({"metric": bench.METRIC, "value": 1.0})
        assert not os.path.exists(bench.CACHE_PATH)
    finally:
        # monkeypatch teardown restores the env but NOT the reloaded module:
        # restore it here even when an assertion above fails, or the stale
        # constants (NUM_REPLICAS=2) cascade into later tests.
        for var in env_vars:
            monkeypatch.delenv(var, raising=False)
        importlib.reload(bench)


def test_probe_retry_loop_capped_with_structured_failure(monkeypatch, capsys):
    """ISSUE 3 satellite: a dead tunnel must not burn the whole budget on
    identical probe hangs — the retry loop caps at
    DIB_BENCH_MAX_PROBE_ATTEMPTS consecutive probe failures — and the
    degraded record carries a machine-readable ``probe_failure`` field
    instead of free-text-only tail noise (BENCH_r05)."""
    import importlib

    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench

    bench = importlib.reload(bench)
    probes = []

    def fake_probe(timeout_s):
        probes.append(timeout_s)
        return f"probe hung > {timeout_s}s (tunnel down?)"

    monkeypatch.setenv("DIB_BENCH_MAX_PROBE_ATTEMPTS", "3")
    # budget large enough for MANY probes: only the cap can stop the loop
    monkeypatch.setenv("DIB_BENCH_TOTAL_BUDGET_S", "100000")
    monkeypatch.setattr(bench, "probe_device", fake_probe)
    monkeypatch.setattr(bench, "load_cache", lambda: None)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    bench.parent_main()

    assert len(probes) == 3          # capped, not budget-bound
    record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert record["degraded"] == "no_device"
    failure = record["probe_failure"]
    assert failure["consecutive_probe_failures"] == 3
    assert failure["max_probe_attempts"] == 3
    assert failure["device_ever_up"] is False
    assert "tunnel down" in failure["last_reason"]


def test_probe_failure_field_in_budget_degraded_record():
    """The structured field is present on the budget-exhausted path too."""
    proc = run_bench({"DIB_BENCH_TOTAL_BUDGET_S": "1"})
    assert proc.returncode == 0
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "probe_failure" in record
    assert record["probe_failure"]["attempts"] >= 1
