"""Amorphous set-transformer workload: probe grids, g(r) masks, end-to-end runs."""

import jax
import numpy as np
import pytest

from dib_tpu.workloads.amorphous import (
    AmorphousWorkloadConfig,
    pair_correlation,
    probe_features_for_type,
    probe_grid_positions,
    run_amorphous_sweep,
    run_amorphous_workload,
)

TINY_MODEL = dict(
    encoder_hidden=(16,), embedding_dim=4, num_blocks=1, num_heads=2,
    key_dim=8, ff_hidden=(8,), head_hidden=(16,),
)


def tiny_config(**kw):
    defaults = dict(
        num_steps=40, batch_size=8, eval_every=20, probe_every=20,
        number_particles=12, grid_side=6, grid_extent=6.0,
        probe_data_batch=64, mi_eval_batch_size=64, mi_eval_batches=1,
        warmup_steps=5,
    )
    defaults.update(kw)
    return AmorphousWorkloadConfig(**defaults)


def test_probe_grid_positions_and_features():
    pos = probe_grid_positions(5, 2.0)
    assert pos.shape == (25, 2)
    assert pos.min() == -2.0 and pos.max() == 2.0
    feats = probe_features_for_type(pos, 1)
    assert feats.shape == (25, 12)
    # type one-hot occupies the last two columns
    assert np.all(feats[:, 10] == 1.0) and np.all(feats[:, 11] == 0.0)
    feats2 = probe_features_for_type(pos, 2)
    assert np.all(feats2[:, 10] == 0.0) and np.all(feats2[:, 11] == 1.0)


def test_pair_correlation_excluded_core():
    # particles uniform in an annulus r in [2, 4]: g(r) must be ~0 inside r<2
    rng = np.random.default_rng(0)
    n_sets, p = 64, 30
    r = np.sqrt(rng.uniform(4.0, 16.0, size=(n_sets, p)))
    theta = rng.uniform(0, 2 * np.pi, size=(n_sets, p))
    sets = np.zeros((n_sets, p, 12), np.float32)
    sets[..., 4] = r  # radius column
    g_r, edges = pair_correlation(sets, num_bins=32, max_radius=5.0)
    inner = edges[1:] < 1.9
    outer = (edges[1:] > 2.2) & (edges[1:] < 3.8)
    assert g_r[inner].max() == 0.0
    assert g_r[outer].min() > 0.0


@pytest.mark.slow
def test_run_amorphous_workload_tiny(tmp_path):
    cfg = tiny_config()
    result = run_amorphous_workload(
        key=0, config=cfg, outdir=str(tmp_path), model_overrides=TINY_MODEL,
        num_synthetic_neighborhoods=64,
    )
    hist = result["history"]
    assert hist.beta.shape == (40,)
    assert hist.kl_per_feature.shape == (40, 12)
    assert np.isfinite(hist.loss).all()
    # MI bounds recorded at the eval cadence, one per particle slot
    assert result["mi_bounds_bits"].shape[1] == 12
    assert result["mi_bounds_bits"].shape[2] == 2
    # probe maps rendered and stored
    assert len(result["probe_grids"]) >= 1
    grids = next(iter(result["probe_grids"].values()))
    assert len(grids) == 2 and grids[0].shape == (6, 6, 2)
    # sandwich ordering holds pointwise on the probe grid
    assert np.all(grids[0][..., 0] <= grids[0][..., 1] + 1e-5)
    assert (tmp_path / "distributed_info_plane.png").exists()


@pytest.mark.slow
def test_run_amorphous_sweep_tiny(tmp_path):
    cfg = tiny_config()
    result = run_amorphous_sweep(
        key=0, config=cfg, beta_ends=[1e-2, 1e-1], num_repeats=2,
        outdir=str(tmp_path), steps_per_epoch=10, model_overrides=TINY_MODEL,
        num_synthetic_neighborhoods=64,
    )
    assert len(result["records"]) == 4
    assert result["beta_ends"].shape == (4,)
    for record in result["records"]:
        assert record.beta.shape == (4,)           # 40 steps / 10 per epoch
        assert np.isfinite(record.loss).all()
    # replicas sharing an endpoint but differing in seed must differ
    r0, r1 = result["records"][0], result["records"][1]
    assert not np.allclose(r0.loss, r1.loss)
    # endpoint grid is repeated pairwise
    assert result["beta_ends"][0] == result["beta_ends"][1]
    assert len(result["info_plane_paths"]) == 4


@pytest.mark.slow
def test_protocol_loop_runs_both(tmp_path):
    from dib_tpu.workloads import run_amorphous_protocols

    cfg = tiny_config(
        num_steps=6, eval_every=3, probe_every=0, number_particles=6,
        warmup_steps=0,
    )
    results = run_amorphous_protocols(
        key=0, config=cfg, outdir=str(tmp_path),
        model_overrides=TINY_MODEL,
        num_synthetic_neighborhoods=64,
    )
    assert set(results) == {"GradualQuench", "RapidQuench"}
    for protocol, res in results.items():
        assert res["bundle"].extras["protocol"] == protocol
        assert (tmp_path / protocol / "distributed_info_plane.png").exists()
    # independent surrogate data per protocol
    a = results["GradualQuench"]["bundle"].x_train
    b = results["RapidQuench"]["bundle"].x_train
    assert not np.array_equal(a, b)
