"""The async-serving bench artifact contract (ISSUE 10).

BENCH_SERVE_ASYNC_CPU.json is the committed evidence the serving rebuild
rests on: an open-loop rate sweep through the real ``python -m dib_tpu
serve`` prefork stack, headline = best sustained uncached rate whose p99
held the committed SLO ceiling. These tests pin the record's schema
(per-row mode/target_rate/p99/cache counters via
``scripts/check_run_artifacts.py``), the >= 3x-baseline floor, and the
fleet-registry idiom (registration ONLY under an explicit runs root; the
committed ``runs/index.jsonl`` carries the seeded serving history).
"""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")
ARTIFACT = os.path.join(REPO, "BENCH_SERVE_ASYNC_CPU.json")


def _load(script):
    spec = importlib.util.spec_from_file_location(
        script, os.path.join(SCRIPTS, script + ".py"))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


@pytest.fixture(scope="module")
def checker():
    return _load("check_run_artifacts")


@pytest.fixture(scope="module")
def loadgen():
    return _load("serve_loadgen")


@pytest.fixture(scope="module")
def committed():
    with open(ARTIFACT) as f:
        return json.load(f)


def test_committed_sweep_artifact_validates(checker):
    assert checker.check_file(ARTIFACT) == []


def test_committed_sweep_meets_the_3x_acceptance(committed):
    assert committed["metric"] == "serve_async_loadgen_sweep"
    assert committed["value"] >= 3 * committed["baseline_req_per_s"]
    assert committed["latency_ms"]["p99"] <= 20.0
    # the cached path is reported SEPARATELY from the uncached headline
    assert committed["cached_req_per_s"] > 0
    assert committed["response_cache_hit_frac"] >= 0.9
    # well-behaved tenant mix: 429s bounded (here: none)
    assert committed["quota_rejected_frac"] <= 0.01
    uncached = [r for r in committed["rows"] if not r["cached"]]
    assert all(r["cache"]["response_hits"] == 0 for r in uncached), \
        "uncached rows rode the response cache — the headline is tainted"


def test_checker_rejects_broken_sweep_shapes(checker, committed):
    def problems_of(mutate):
        record = json.loads(json.dumps(committed))
        mutate(record)
        problems: list[str] = []
        checker.check_record(record, problems)
        return problems

    def drop_cache(r):
        for row in r["rows"]:
            del row["cache"]

    def no_compliant(r):
        for row in r["rows"]:
            row["within_slo"] = False

    def below_floor(r):
        r["value"] = 500.0

    def closed_row(r):
        r["rows"][0]["mode"] = "closed"

    def no_baseline(r):
        del r["baseline_req_per_s"]

    assert any("cache" in p for p in problems_of(drop_cache))
    assert any("never demonstrates" in p for p in problems_of(no_compliant))
    assert any("serve_req_per_s_floor" in p for p in problems_of(below_floor))
    assert any("'mode'" in p for p in problems_of(closed_row))
    assert any("baseline_req_per_s" in p for p in problems_of(no_baseline))
    assert checker.check_file(ARTIFACT) == []   # the committed one is clean


def test_loadgen_registers_only_under_explicit_root(
        loadgen, tmp_path, monkeypatch):
    """The register_drill_record idiom: no explicit root (flag or
    DIB_RUNS_ROOT) -> NOTHING is written (ad-hoc runs must not grow the
    committed ./runs index); an explicit root gets the bench entry."""
    record = {"metric": "serve_async_loadgen_sweep", "unit": "req_per_s",
              "value": 1500.0, "mode": "open_sweep", "target_rate": 1600.0,
              "speedup_vs_baseline": 4.05,
              "measured_at": "2026-08-03T00:00:00Z"}
    monkeypatch.delenv("DIB_RUNS_ROOT", raising=False)
    monkeypatch.chdir(tmp_path)
    loadgen._register_bench(record, None)
    assert not os.path.exists(tmp_path / "runs" / "index.jsonl")

    root = tmp_path / "fleet"
    loadgen._register_bench(record, str(root))
    lines = (root / "index.jsonl").read_text().splitlines()
    entry = json.loads(lines[-1])
    assert entry["kind"] == "bench"
    assert entry["metric"] == "serve_async_loadgen_sweep"
    assert entry["value"] == 1500.0
    assert entry["speedup_vs_baseline"] == 4.05

    from dib_tpu.telemetry.registry import validate_index_entry

    assert validate_index_entry(entry) == []

    # the env-var spelling works too
    monkeypatch.setenv("DIB_RUNS_ROOT", str(root))
    loadgen._register_bench(record, None)
    assert len((root / "index.jsonl").read_text().splitlines()) == 2


def test_committed_registry_carries_the_serving_history():
    """`telemetry runs trajectory` over the committed ./runs shows the
    seeded async-serving measurement."""
    from dib_tpu.telemetry.registry import RunRegistry

    bench = RunRegistry(os.path.join(REPO, "runs")).bench_history()
    serving = [e for e in bench
               if e.get("metric") == "serve_async_loadgen_sweep"]
    assert serving, "runs/index.jsonl is missing the seeded serving entry"
    assert serving[-1]["value"] >= 1110.0
    assert serving[-1]["seeded_from"] == "BENCH_SERVE_ASYNC_CPU.json"


def test_sweep_row_generator_is_distinct(loadgen):
    """The uncached sweep's inputs must be pairwise distinct (a collision
    would silently measure the response cache)."""
    rows = [tuple(loadgen._row(i, 10)) for i in range(5000)]
    assert len(set(rows)) == len(rows)
