"""Drift autopilot (``dib_tpu/autopilot``, docs/streaming.md "Closed
loop"): the pure decision/replay layer (config journaling, fold,
schedule building, canonical applies), the weighted round-0 placement
the drift studies seed with, the rollup the SLO rules read, the zoo's
advisory β-routing surface — and the acceptance path: a scripted drift
carried drift→study→re-anneal→routing through the REAL CLI.
"""

import importlib.util
import json
import math
import os
import subprocess
import sys
import types

import pytest

from dib_tpu.autopilot import (
    AUTOPILOT_FILENAME,
    AutopilotConfig,
    DriftAutopilot,
    autopilot_journal_path,
    autopilot_status,
    build_reanneal_schedule,
    build_routing_metadata,
    fold_autopilot,
    write_json_atomic,
)
from dib_tpu.sched.journal import JobJournal
from dib_tpu.telemetry.summary import autopilot_rollup

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ==================================================================== config
def test_autopilot_config_validation_and_roundtrip():
    config = AutopilotConfig(cooldown_rounds=7, breaker_threshold=2,
                             breaker_probe_after=5, margin_decades=0.5,
                             study={"max_units": 20, "seeds": [0]})
    assert AutopilotConfig.from_dict(config.to_dict()) == config
    # unknown keys are dropped (forward-compatible journals)
    assert AutopilotConfig.from_dict(
        {**config.to_dict(), "later_knob": 1}) == config
    with pytest.raises(ValueError, match="cooldown_rounds"):
        AutopilotConfig(cooldown_rounds=-1)
    with pytest.raises(ValueError, match="breaker_threshold"):
        AutopilotConfig(breaker_threshold=0)
    with pytest.raises(ValueError, match="breaker_probe_after"):
        AutopilotConfig(breaker_probe_after=-2)
    with pytest.raises(ValueError, match="margin_decades"):
        AutopilotConfig(margin_decades=0.0)


# ====================================================================== fold
def test_fold_autopilot_replays_decision_chain_and_breaker():
    records = [
        {"kind": "config", "spec": {"cooldown_rounds": 1}},
        {"kind": "intent", "round": 2, "study_id": "drift-r0002"},
        {"kind": "submitted", "round": 2},
        {"kind": "verdict", "round": 2, "verdict": "error"},
        {"kind": "apply_skip", "round": 2},
        {"kind": "intent", "round": 3},
        {"kind": "verdict", "round": 3, "verdict": "error"},
        {"kind": "breaker", "action": "trip"},
        {"kind": "skip", "round": 4, "reason": "breaker_open"},
        {"kind": "skip", "round": 5, "reason": "breaker_open"},
        {"kind": "breaker", "action": "reset"},
        {"kind": "intent", "round": 9},
        {"kind": "verdict", "round": 9, "verdict": "converged"},
        {"kind": "apply_intent", "round": 9},
        {"kind": "applied", "round": 9},
    ]
    state = fold_autopilot(records)
    assert state["config"] == {"cooldown_rounds": 1}
    assert sorted(state["drifts"]) == [2, 3, 4, 5, 9]
    assert state["last_intent_round"] == 9
    # the two errors counted, the reset zeroed, converged kept it at 0
    assert state["breaker"] == {"open": False, "trips": 1, "resets": 1,
                                "consecutive": 0, "skips_since_trip": 0}
    # round 9 closed its full chain; round 3 never applied
    assert set(state["drifts"][9]) == {"intent", "verdict", "apply_intent",
                                       "applied"}
    assert "applied" not in state["drifts"][3]


def test_fold_autopilot_resume_window_and_skip_pacing():
    """An intent with no terminal record is the round a restart resumes
    into; breaker_open skips pace the half-open probe until the next
    intent zeroes the pacer."""
    state = fold_autopilot([
        {"kind": "breaker", "action": "trip"},
        {"kind": "skip", "round": 4, "reason": "breaker_open"},
        {"kind": "skip", "round": 5, "reason": "breaker_open"},
        {"kind": "intent", "round": 6, "study_id": "drift-r0006"},
        {"kind": "submitted", "round": 6},
    ])
    assert state["breaker"]["open"] is True
    assert state["breaker"]["skips_since_trip"] == 0   # probe intent reset
    assert set(state["drifts"][6]) == {"intent", "submitted"}


# ===================================================================== apply
def test_build_reanneal_schedule_margin_math_and_none_cases():
    schedule = build_reanneal_schedule(
        {"0": 0.3, "1": 3.0}, drift_round=7, study_id="drift-r0007",
        margin_decades=0.25)
    assert schedule["drift_round"] == 7
    assert schedule["study_id"] == "drift-r0007"
    want_floor = 10 ** (math.log10(0.3) - 0.25)
    assert schedule["beta_floor"] == pytest.approx(want_floor, rel=1e-6)
    assert list(schedule["estimates"]) == ["0", "1"]
    # nothing applicable -> None, never an empty schedule
    assert build_reanneal_schedule({}, drift_round=1, study_id="s",
                                   margin_decades=0.25) is None
    assert build_reanneal_schedule(
        {"0": 0.0, "1": float("nan"), "2": None}, drift_round=1,
        study_id="s", margin_decades=0.25) is None
    # non-finite estimates are filtered, not propagated
    only_good = build_reanneal_schedule(
        {"0": float("inf"), "1": 0.5}, drift_round=1, study_id="s",
        margin_decades=0.25)
    assert list(only_good["estimates"]) == ["1"]


def test_build_routing_metadata_sorted_and_none():
    routing = build_routing_metadata({"10": 1.0, "2": 0.25},
                                     drift_round=3, study_id="s")
    assert list(routing["transition_betas"]) == ["10", "2"]
    assert routing["transition_betas"]["2"] == 0.25
    assert build_routing_metadata({"0": -1.0}, drift_round=3,
                                  study_id="s") is None


def test_write_json_atomic_canonical_bytes(tmp_path):
    """Two applies of the same journaled payload (any key order) write
    IDENTICAL bytes — the bit-identity invariant the chaos suite's
    apply_kill drill compares across processes."""
    a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    write_json_atomic(a, {"beta_floor": 0.1, "estimates": {"1": 2.0}})
    write_json_atomic(b, {"estimates": {"1": 2.0}, "beta_floor": 0.1})
    blob_a, blob_b = open(a, "rb").read(), open(b, "rb").read()
    assert blob_a == blob_b
    assert blob_a.endswith(b"\n")
    with pytest.raises(ValueError):
        write_json_atomic(a, {"x": float("nan")})


def test_reanneal_rewind_epoch_inverts_the_ramp_and_clamps():
    from dib_tpu.stream.online import reanneal_rewind_epoch

    config = types.SimpleNamespace(num_pretraining_epochs=4,
                                   num_annealing_epochs=10,
                                   beta_start=0.01, beta_end=10.0)
    # log-midpoint of the ramp -> halfway through the annealing epochs
    mid = 10 ** ((math.log10(0.01) + math.log10(10.0)) / 2)
    assert reanneal_rewind_epoch({"beta_floor": mid}, config) == 4 + 5
    # floor at/below beta_start, absent, or junk -> full re-anneal
    for schedule in ({"beta_floor": 0.01}, {"beta_floor": 0.001},
                     {"beta_floor": None}, {}):
        assert reanneal_rewind_epoch(schedule, config) == 4
    # floor at/above beta_end still leaves one annealing epoch
    assert reanneal_rewind_epoch({"beta_floor": 10.0}, config) == 4 + 9
    degenerate = types.SimpleNamespace(num_pretraining_epochs=2,
                                       num_annealing_epochs=0,
                                       beta_start=0.1, beta_end=0.1)
    assert reanneal_rewind_epoch({"beta_floor": 1.0}, degenerate) == 2


# ==================================================================== rollup
def test_autopilot_rollup_counts_duplicates_and_latency():
    events = [
        {"type": "autopilot", "action": "intent", "round": 2},
        {"type": "autopilot", "action": "submitted", "round": 2},
        {"type": "autopilot", "action": "verdict", "round": 2},
        {"type": "autopilot", "action": "applied", "round": 2,
         "drift_to_apply_s": 12.0},
        {"type": "autopilot", "action": "skip", "round": 3,
         "reason": "cooldown"},
        {"type": "autopilot", "action": "skip", "round": 4,
         "reason": "cooldown"},
        # a SECOND intent on round 2: the exactly-once breach the
        # page-severity SLO rule gates
        {"type": "autopilot", "action": "intent", "round": 2},
        {"type": "breaker", "action": "trip"},
        {"type": "breaker", "action": "reset"},
    ]
    rollup = autopilot_rollup(events)
    assert rollup["intents"] == 2
    assert rollup["applied"] == 1
    assert rollup["duplicate_studies"] == 1
    assert rollup["skip_reasons"] == {"cooldown": 2}
    assert rollup["breaker_trips"] == 1
    assert rollup["breaker_resets"] == 1
    assert rollup["breaker_open"] == 0          # reset came last
    assert rollup["drift_to_apply_p99_s"] == pytest.approx(12.0)
    assert rollup["last_applied_round"] == 2
    # ordinary runs carry no autopilot plane at all
    assert autopilot_rollup([{"type": "metrics"}]) is None


# ============================================================ status / reset
def _journal(autopilot_dir, *records):
    os.makedirs(autopilot_dir, exist_ok=True)
    with JobJournal(autopilot_dir, filename=AUTOPILOT_FILENAME) as journal:
        for kind, fields in records:
            journal.append(kind, **fields)


def test_autopilot_status_and_operator_breaker_reset(tmp_path):
    stream_dir = str(tmp_path / "stream")
    autopilot_dir = str(tmp_path / "stream" / "autopilot")
    _journal(
        autopilot_dir,
        ("config", {"spec": AutopilotConfig().to_dict()}),
        ("intent", {"round": 2, "study_id": "drift-r0002"}),
        ("verdict", {"round": 2, "verdict": "error"}),
        ("apply_skip", {"round": 2}),
        ("breaker", {"action": "trip"}),
        ("skip", {"round": 3, "reason": "breaker_open"}),
    )
    status = autopilot_status(autopilot_dir)
    assert status["drifts_decided"] == 2
    assert status["studies"] == 1 and status["applied"] == 0
    assert status["skip_reasons"] == {"breaker_open": 1}
    assert status["breaker"]["open"] is True
    assert status["journal_torn"] == 0

    pilot = DriftAutopilot(stream_dir, autopilot_dir)
    assert pilot.reset_breaker(via="operator") is True
    assert autopilot_status(autopilot_dir)["breaker"]["open"] is False
    # idempotent: a closed breaker is a no-op, not a second reset record
    assert pilot.reset_breaker(via="operator") is False
    assert autopilot_status(autopilot_dir)["breaker"]["resets"] == 1


def test_reconfigure_replaces_the_journaled_study_spec(tmp_path):
    """The breaker-recovery operator path: a journaled (broken) config
    must NOT shadow the --reconfigure one — the replayed journal wins
    only on plain restarts."""
    stream_dir = str(tmp_path / "stream")
    broken = AutopilotConfig(study={"max_units": 1})
    DriftAutopilot(stream_dir, config=broken).ensure_config()
    good = AutopilotConfig(study={"max_units": 20})
    # a plain restart keeps the journaled spec...
    state = DriftAutopilot(stream_dir, config=good).ensure_config()
    assert state["config"]["study"] == {"max_units": 1}
    # ...reconfigure replaces it durably
    state = DriftAutopilot(stream_dir, config=good).ensure_config(
        reconfigure=True)
    assert state["config"]["study"] == {"max_units": 20}
    pilot = DriftAutopilot(stream_dir)
    assert pilot.ensure_config()["config"]["study"] == {"max_units": 20}


# ============================================= weighted round-0 (satellite)
def test_weighted_point_allocation_contract():
    from dib_tpu.study.controller import weighted_point_allocation

    assert weighted_point_allocation([], 10) == []
    # weights FOCUS a fixed budget: the total never changes
    counts = weighted_point_allocation([3.0, 1.0], 8, floor=2)
    assert sum(counts) == 8
    assert counts[0] > counts[1] >= 2
    # non-positive weights fall back to an equal split
    assert weighted_point_allocation([0.0, -1.0, float("nan")], 7,
                                     floor=1) == [3, 2, 2]
    # deterministic remainder ties (replayed decisions re-allocate
    # identically)
    assert (weighted_point_allocation([1.0, 1.0, 1.0], 10)
            == weighted_point_allocation([1.0, 1.0, 1.0], 10))
    # the floor is a floor even when the budget undershoots it
    assert weighted_point_allocation([1.0, 100.0], 1, floor=1) == [1, 1]


def test_plan_refinement_band_widths_focus_the_same_budget():
    from dib_tpu.study.controller import plan_refinement

    brackets = {0: (0.1, 0.2), 1: (1.0, 8.0)}

    def inside(points, span):
        lo, hi = span
        return [b for b in points if lo <= b <= hi]

    equal = plan_refinement(brackets, 4, [])
    assert len(inside(equal, brackets[0])) == len(inside(equal, brackets[1]))
    # channel 1's band is far wider (ensemble-uncertain): it gets the
    # denser grid, channel 0 keeps its floor, the total stays put
    weighted = plan_refinement(brackets, 4, [],
                               band_widths={0: 0.01, 1: 0.9})
    assert len(weighted) == len(equal)
    assert len(inside(weighted, brackets[1])) > len(inside(weighted,
                                                           brackets[0]))
    assert len(inside(weighted, brackets[0])) >= 3
    # partial band coverage must NOT reweight (a missing measurement
    # never starves a bracket)
    partial = plan_refinement(brackets, 4, [], band_widths={1: 0.9})
    assert (len(inside(partial, brackets[0]))
            == len(inside(partial, brackets[1])))
    # already-trained points are never re-bought
    assert all(abs(b - w) > 1e-9 for w in plan_refinement(
        brackets, 4, list(equal)) for b in equal)


def test_initial_betas_apportions_by_center_weight():
    from dib_tpu.study.controller import StudyConfig

    flat = StudyConfig(centers=(0.1, 2.0), refine_num=4)
    weighted = StudyConfig(centers=(0.1, 2.0),
                           center_weights=(5.0, 1.0), refine_num=4)

    def near(points, center):
        return [b for b in points
                if abs(math.log10(b / center)) <= 0.51]

    flat_grid, weighted_grid = flat.initial_betas(), weighted.initial_betas()
    # same FIXED total, denser where the harvest's evidence is strongest
    assert len(weighted_grid) == len(flat_grid) == 8
    assert len(near(weighted_grid, 0.1)) > len(near(weighted_grid, 2.0))
    assert len(near(weighted_grid, 2.0)) >= 2
    assert len(near(flat_grid, 0.1)) == len(near(flat_grid, 2.0))


def test_watch_seed_harvests_transitions_and_curvature(tmp_path):
    from dib_tpu.study.controller import watch_seed
    from dib_tpu.telemetry.events import EventWriter

    run_dir = str(tmp_path / "run")
    betas = [0.05, 0.1, 0.3, 0.5, 1.0, 3.0, 10.0]
    # an MI series with a hard bend at beta=0.5: curvature peaks there
    values = [2.0, 2.0, 2.0, 2.0, 0.2, 0.1, 0.1]
    with EventWriter(run_dir, run_id="seed") as writer:
        writer.run_start({"mode": "stream"})
        for epoch, (beta, val) in enumerate(zip(betas, values)):
            writer.mi_bounds(epoch=epoch, beta=beta, lower_bits=val)
        writer.transition(channel=0, epoch=3, direction="down", beta=0.5)
        writer.transition(channel=1, epoch=5, direction="down", beta=3.0)
        writer.run_end(status="ok")
    centers, weights = watch_seed(run_dir)
    assert centers == sorted(centers)
    by_center = dict(zip(centers, weights))
    # the double-evidence β (transition + curvature peak) accumulates
    # past a transition-only one
    assert 0.5 in by_center and 3.0 in by_center
    assert by_center[0.5] > by_center[3.0] >= 1.0
    assert all(w > 0 for w in weights)


# ================================================== zoo routing (satellite)
class _StubRouter:
    entries = ()

    def close(self):
        pass


def test_zoo_set_routing_describe_and_unknown_model():
    from dib_tpu.serve import ModelZoo

    zoo = ModelZoo()
    zoo.register("m", _StubRouter())
    assert "routing" not in zoo.describe()[0]
    metadata = {"drift_round": 2, "study_id": "drift-r0002",
                "transition_betas": {"0": 0.3}}
    zoo.set_routing("m", metadata)
    row = next(r for r in zoo.describe() if r["model"] == "m")
    assert row["routing"]["transition_betas"] == {"0": 0.3}
    # advisory only: clearing works, unknown models are loud
    zoo.set_routing("m", None)
    assert "routing" not in zoo.describe()[0]
    with pytest.raises(KeyError, match="ghost"):
        zoo.set_routing("ghost", metadata)


# ============================================================== e2e (CLI)
def _load_chaos_module():
    spec = importlib.util.spec_from_file_location(
        "chaos_autopilot",
        os.path.join(REPO, "scripts", "chaos_autopilot.py"))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


@pytest.mark.fault
def test_autopilot_closed_loop_cli_end_to_end(tmp_path):
    """The acceptance drill in tier 1: a scripted mid-stream drift,
    `stream run` + `stream autopilot` through the real CLI (separate
    processes sharing only the journals), ending in an applied
    re-anneal schedule, β-routing metadata the zoo serves, and a clean
    status surface."""
    module = _load_chaos_module()
    stream_dir = str(tmp_path / "stream")
    module._build_stream(stream_dir, rounds=module.SINGLE_ROUNDS,
                         drifts=module.SINGLE_DRIFTS)
    drift_rounds = module._drift_rounds(stream_dir)
    assert drift_rounds, "scripted drift was not detected"
    proc = module._autopilot(stream_dir, cooldown=100)
    assert proc.returncode == 0, proc.stderr[-2000:]

    # the loop closed: exactly one study, applied, invariants clean
    inv = module._invariants(stream_dir)
    assert inv["intents"] == 1 and inv["applies"] == 1
    assert inv["exactly_once_study"] and inv["apply_bit_identical"]
    assert module._verdict_of(stream_dir, drift_rounds[0]) == "converged"

    # the trainer-facing apply: a rewindable schedule below the lowest
    # refreshed transition-β
    from dib_tpu.stream.online import load_reanneal_schedule
    schedule = load_reanneal_schedule(stream_dir)
    assert schedule["drift_round"] == drift_rounds[0]
    assert schedule["estimates"]
    assert schedule["beta_floor"] < min(
        float(v) for v in schedule["estimates"].values())

    # the serving-facing apply: routing metadata the zoo attaches
    from dib_tpu.serve import ModelZoo
    from dib_tpu.stream.deployer import load_routing
    routing = load_routing(stream_dir)
    assert routing["study_id"] == schedule["study_id"]
    assert routing["transition_betas"]
    zoo = ModelZoo()
    zoo.register("m", _StubRouter())
    zoo.set_routing("m", routing)
    assert zoo.describe()[0]["routing"]["drift_round"] == drift_rounds[0]

    # the operator surface: stream status --json carries all three planes
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    status_proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "stream", "status",
         "--stream-dir", stream_dir, "--autopilot-dir",
         os.path.join(stream_dir, "autopilot"), "--json"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert status_proc.returncode == 0, status_proc.stderr[-2000:]
    snapshot = json.loads(status_proc.stdout)
    assert snapshot["reanneal"]["beta_floor"] == schedule["beta_floor"]
    assert snapshot["routing"]["drift_round"] == drift_rounds[0]
    assert snapshot["autopilot"]["applied"] == 1
    assert snapshot["autopilot"]["breaker"]["open"] is False
