"""End-to-end slice: boolean circuit -> DistributedIBModel -> beta-annealed
jitted training -> MI bounds, validated against the exact truth-table oracle
(SURVEY.md section 7, milestone 6).

Uses a small 3-input circuit (Fig. S1a) and short schedules so the test runs
in seconds on CPU while still exercising every layer.
"""

import jax
import numpy as np
import pytest

from dib_tpu.data import get_dataset, FIG_S1_CIRCUITS, exact_subset_informations
from dib_tpu.models import DistributedIBModel
from dib_tpu.train import DIBTrainer, TrainConfig, InfoPerFeatureHook
from dib_tpu.ops.entropy import LN2


@pytest.fixture(scope="module")
def small_circuit_bundle():
    return get_dataset("boolean_circuit", circuit_specification=FIG_S1_CIRCUITS[0])


def test_bundle_contract(small_circuit_bundle):
    b = small_circuit_bundle
    assert b.x_train.shape == (8, 3)          # 2^3 truth table
    assert set(np.unique(b.x_train)) == {-1.0, 1.0}
    assert b.number_features == 3
    assert b.loss == "bce" and b.loss_is_info_based
    assert 0.0 < b.extras["entropy_y_bits"] <= 1.0


def test_exact_subset_oracle(small_circuit_bundle):
    """Exact MI oracle sanity: full-input subset carries all of H(Y)."""
    b = small_circuit_bundle
    infos = exact_subset_informations(b.extras["truth_table"], 3)
    assert infos[(0, 1, 2)] == pytest.approx(b.extras["entropy_y_bits"], abs=1e-9)
    assert all(v <= b.extras["entropy_y_bits"] + 1e-9 for v in infos.values())


@pytest.fixture(scope="module")
def trained(small_circuit_bundle):
    bundle = small_circuit_bundle
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(32,),
        integration_hidden=(64, 64),
        output_dim=1,
        embedding_dim=4,
    )
    config = TrainConfig(
        learning_rate=1e-3,
        batch_size=64,
        beta_start=1e-4,
        beta_end=2.0,
        num_pretraining_epochs=250,
        num_annealing_epochs=250,
        steps_per_epoch=2,
        max_val_points=8,
    )
    trainer = DIBTrainer(model, bundle, config)
    hook = InfoPerFeatureHook(evaluation_batch_size=256, number_evaluation_batches=2)
    state, history = trainer.fit(
        jax.random.key(0), hooks=[hook], hook_every=125
    )
    return trainer, state, history, hook


@pytest.mark.slow
def test_training_learns_circuit(trained):
    trainer, state, history, hook = trained
    entropy_y = trainer.bundle.extras["entropy_y_bits"]
    # By the end of pretraining (tiny beta) the model must fit the circuit:
    # task BCE (bits) well below H(Y) means real predictive information.
    h = history.to_bits()
    assert h.loss[230:260].min() < 0.3 * entropy_y
    assert h.metric[230:260].max() > 0.9  # train accuracy


@pytest.mark.slow
def test_history_semantics(trained):
    _, _, history, _ = trained
    assert history.beta.shape == (500,)
    # beta flat during pretraining, then rising
    np.testing.assert_allclose(history.beta[:250], history.beta[0], rtol=1e-5)
    assert history.beta[-1] > history.beta[0] * 1000
    # KL should collapse as beta ramps up hard
    assert history.total_kl[-1] < 0.25 * history.total_kl[250]
    # loss series is the task loss only (no beta*KL mixed in):
    assert np.all(history.loss >= -1e-5)


@pytest.mark.slow
def test_mi_bounds_hook_sane(trained):
    trainer, state, history, hook = trained
    bounds = hook.bounds_bits                   # [T, F, 2]
    assert bounds.shape[1] == 3 and bounds.shape[2] == 2
    # each feature is 1 bit max; bounds ordered and within [~0, ~1]
    assert np.all(bounds[..., 0] <= bounds[..., 1] + 1e-4)
    assert np.all(bounds <= 1.1)
    assert np.all(bounds >= -0.1)


@pytest.mark.slow
def test_mi_hook_batched_matches_per_feature(trained):
    """The hook's vmapped all-features fast path agrees with independent
    per-feature mi_sandwich_bounds calls on the same state (independent
    batch/noise draws -> statistical tolerance)."""
    import jax
    import jax.numpy as jnp

    from dib_tpu.ops.info_bounds import mi_sandwich_bounds
    from dib_tpu.train.hooks import InfoPerFeatureHook

    trainer, state, history, _ = trained
    hook = InfoPerFeatureHook(evaluation_batch_size=256,
                              number_evaluation_batches=4, seed=7)
    hook(trainer, state, epoch=0)
    fast = np.asarray(hook.records[0]["bounds"])          # [F, 2] nats

    for f in range(trainer.num_features):
        data = jnp.asarray(trainer.feature_data(f))
        lower, upper = mi_sandwich_bounds(
            lambda batch, f=f: trainer.encode_feature(state, f, batch),
            data, jax.random.key(100 + f),
            evaluation_batch_size=256, number_evaluation_batches=4,
        )
        # independent batch/noise draws: measured deviation ~0.05 nats at
        # this config; 0.15 leaves ~3x headroom against unlucky seeds
        assert fast[f, 0] == pytest.approx(float(lower), abs=0.15)
        assert fast[f, 1] == pytest.approx(float(upper), abs=0.15)


def test_permutation_batch_sampling_trains(small_circuit_bundle):
    """batch_sampling='permutation' (one epoch-gather instead of per-step
    gathers, VERDICT round 3 item 4a) must train equivalently: finite
    history, same shapes, and a trajectory that actually differs from
    replacement sampling (different batch order) while converging to a
    comparable loss."""
    import jax

    bundle = small_circuit_bundle
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(16,), integration_hidden=(32,), output_dim=1,
        embedding_dim=2,
    )

    def train(sampling):
        config = TrainConfig(
            learning_rate=3e-3, batch_size=16, beta_start=1e-4, beta_end=1e-4,
            num_pretraining_epochs=40, num_annealing_epochs=0,
            steps_per_epoch=3,          # 48 rows/epoch > 8-row dataset:
            max_val_points=8,           # exercises the tiled-permutation path
            batch_sampling=sampling,
        )
        _, history = DIBTrainer(model, bundle, config).fit(jax.random.key(0))
        return history.to_bits()

    perm, repl = train("permutation"), train("replacement")
    assert np.isfinite(perm.loss).all() and np.isfinite(perm.kl_per_feature).all()
    assert perm.loss.shape == repl.loss.shape
    assert not np.allclose(perm.loss, repl.loss)       # different batch order
    # both fit the tiny circuit to a similar level by the end
    assert perm.loss[-5:].mean() < repl.loss[-5:].mean() + 0.2

    with pytest.raises(ValueError, match="batch_sampling"):
        config = TrainConfig(batch_sampling="bogus", num_pretraining_epochs=1,
                             num_annealing_epochs=0, max_val_points=8)
        DIBTrainer(model, bundle, config).fit(jax.random.key(0))


def test_mi_hook_invalidates_cache_across_trainers(small_circuit_bundle):
    """Regression (ADVICE round 2 / VERDICT round 3 item 6): one hook
    instance reused across trainers with DIFFERENT bundles must re-upload
    the new validation rows, not measure bounds on the first trainer's
    cached device rows."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dib_tpu.train.hooks import InfoPerFeatureHook, _all_features_bounds_fn

    bundle_a = small_circuit_bundle
    # same schema, different validation rows: the stale-cache bug would
    # silently measure bundle_a's rows with trainer_b's params
    bundle_b = dataclasses.replace(
        bundle_a, x_valid=-np.asarray(bundle_a.x_valid)[:4]
    )
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle_a.feature_dimensionalities),
        encoder_hidden=(16,), integration_hidden=(16,), output_dim=1,
        embedding_dim=2,
    )
    config = TrainConfig(batch_size=8, num_pretraining_epochs=1,
                         num_annealing_epochs=1, steps_per_epoch=1,
                         max_val_points=8)
    trainer_a = DIBTrainer(model, bundle_a, config)
    trainer_b = DIBTrainer(model, bundle_b, config)
    state_a, _ = trainer_a.fit(jax.random.key(0), num_epochs=1)
    state_b, _ = trainer_b.fit(jax.random.key(1), num_epochs=1)

    hook = InfoPerFeatureHook(evaluation_batch_size=64,
                              number_evaluation_batches=2, seed=0)
    hook(trainer_a, state_a, epoch=1)
    hook(trainer_b, state_b, epoch=1)          # must invalidate cached rows

    # replica-match the hook's key chain: first call consumed one split
    key = jax.random.key(0)
    key, _ = jax.random.split(key)
    _, k_second = jax.random.split(key)
    fn = _all_features_bounds_fn(model, 64, 2, None)
    lower, upper = fn(state_b.params["model"]
                      if "model" in state_b.params else state_b.params,
                      jnp.asarray(bundle_b.x_valid), k_second)
    expected = [(float(a), float(b)) for a, b in zip(lower, upper)]
    assert hook.records[1]["bounds"] == pytest.approx(expected, abs=1e-6)


@pytest.mark.slow
def test_ib_mode_single_bottleneck(small_circuit_bundle):
    bundle = small_circuit_bundle.as_vanilla_ib()
    assert bundle.feature_dimensionalities == [3]
    model = DistributedIBModel(
        feature_dimensionalities=(3,),
        encoder_hidden=(16,),
        integration_hidden=(16,),
        output_dim=1,
        embedding_dim=4,
    )
    config = TrainConfig(
        batch_size=8, num_pretraining_epochs=3, num_annealing_epochs=3,
        steps_per_epoch=1, max_val_points=8,
    )
    trainer = DIBTrainer(model, bundle, config)
    state, history = trainer.fit(jax.random.key(1))
    assert history.kl_per_feature.shape == (6, 1)
