"""End-to-end slice: boolean circuit -> DistributedIBModel -> beta-annealed
jitted training -> MI bounds, validated against the exact truth-table oracle
(SURVEY.md section 7, milestone 6).

Uses a small 3-input circuit (Fig. S1a) and short schedules so the test runs
in seconds on CPU while still exercising every layer.
"""

import jax
import numpy as np
import pytest

from dib_tpu.data import get_dataset, FIG_S1_CIRCUITS, exact_subset_informations
from dib_tpu.models import DistributedIBModel
from dib_tpu.train import DIBTrainer, TrainConfig, InfoPerFeatureHook
from dib_tpu.ops.entropy import LN2


@pytest.fixture(scope="module")
def small_circuit_bundle():
    return get_dataset("boolean_circuit", circuit_specification=FIG_S1_CIRCUITS[0])


def test_bundle_contract(small_circuit_bundle):
    b = small_circuit_bundle
    assert b.x_train.shape == (8, 3)          # 2^3 truth table
    assert set(np.unique(b.x_train)) == {-1.0, 1.0}
    assert b.number_features == 3
    assert b.loss == "bce" and b.loss_is_info_based
    assert 0.0 < b.extras["entropy_y_bits"] <= 1.0


def test_exact_subset_oracle(small_circuit_bundle):
    """Exact MI oracle sanity: full-input subset carries all of H(Y)."""
    b = small_circuit_bundle
    infos = exact_subset_informations(b.extras["truth_table"], 3)
    assert infos[(0, 1, 2)] == pytest.approx(b.extras["entropy_y_bits"], abs=1e-9)
    assert all(v <= b.extras["entropy_y_bits"] + 1e-9 for v in infos.values())


@pytest.fixture(scope="module")
def trained(small_circuit_bundle):
    bundle = small_circuit_bundle
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(32,),
        integration_hidden=(64, 64),
        output_dim=1,
        embedding_dim=4,
    )
    config = TrainConfig(
        learning_rate=1e-3,
        batch_size=64,
        beta_start=1e-4,
        beta_end=2.0,
        num_pretraining_epochs=250,
        num_annealing_epochs=250,
        steps_per_epoch=2,
        max_val_points=8,
    )
    trainer = DIBTrainer(model, bundle, config)
    hook = InfoPerFeatureHook(evaluation_batch_size=256, number_evaluation_batches=2)
    state, history = trainer.fit(
        jax.random.key(0), hooks=[hook], hook_every=125
    )
    return trainer, state, history, hook


@pytest.mark.slow
def test_training_learns_circuit(trained):
    trainer, state, history, hook = trained
    entropy_y = trainer.bundle.extras["entropy_y_bits"]
    # By the end of pretraining (tiny beta) the model must fit the circuit:
    # task BCE (bits) well below H(Y) means real predictive information.
    h = history.to_bits()
    assert h.loss[230:260].min() < 0.3 * entropy_y
    assert h.metric[230:260].max() > 0.9  # train accuracy


@pytest.mark.slow
def test_history_semantics(trained):
    _, _, history, _ = trained
    assert history.beta.shape == (500,)
    # beta flat during pretraining, then rising
    np.testing.assert_allclose(history.beta[:250], history.beta[0], rtol=1e-5)
    assert history.beta[-1] > history.beta[0] * 1000
    # KL should collapse as beta ramps up hard
    assert history.total_kl[-1] < 0.25 * history.total_kl[250]
    # loss series is the task loss only (no beta*KL mixed in):
    assert np.all(history.loss >= -1e-5)


@pytest.mark.slow
def test_mi_bounds_hook_sane(trained):
    trainer, state, history, hook = trained
    bounds = hook.bounds_bits                   # [T, F, 2]
    assert bounds.shape[1] == 3 and bounds.shape[2] == 2
    # each feature is 1 bit max; bounds ordered and within [~0, ~1]
    assert np.all(bounds[..., 0] <= bounds[..., 1] + 1e-4)
    assert np.all(bounds <= 1.1)
    assert np.all(bounds >= -0.1)


@pytest.mark.slow
def test_mi_hook_batched_matches_per_feature(trained):
    """The hook's vmapped all-features fast path agrees with independent
    per-feature mi_sandwich_bounds calls on the same state (independent
    batch/noise draws -> statistical tolerance)."""
    import jax
    import jax.numpy as jnp

    from dib_tpu.ops.info_bounds import mi_sandwich_bounds
    from dib_tpu.train.hooks import InfoPerFeatureHook

    trainer, state, history, _ = trained
    hook = InfoPerFeatureHook(evaluation_batch_size=256,
                              number_evaluation_batches=4, seed=7)
    hook(trainer, state, epoch=0)
    fast = np.asarray(hook.records[0]["bounds"])          # [F, 2] nats

    for f in range(trainer.num_features):
        data = jnp.asarray(trainer.feature_data(f))
        lower, upper = mi_sandwich_bounds(
            lambda batch, f=f: trainer.encode_feature(state, f, batch),
            data, jax.random.key(100 + f),
            evaluation_batch_size=256, number_evaluation_batches=4,
        )
        # independent batch/noise draws: measured deviation ~0.05 nats at
        # this config; 0.15 leaves ~3x headroom against unlucky seeds
        assert fast[f, 0] == pytest.approx(float(lower), abs=0.15)
        assert fast[f, 1] == pytest.approx(float(upper), abs=0.15)


@pytest.mark.slow
def test_ib_mode_single_bottleneck(small_circuit_bundle):
    bundle = small_circuit_bundle.as_vanilla_ib()
    assert bundle.feature_dimensionalities == [3]
    model = DistributedIBModel(
        feature_dimensionalities=(3,),
        encoder_hidden=(16,),
        integration_hidden=(16,),
        output_dim=1,
        embedding_dim=4,
    )
    config = TrainConfig(
        batch_size=8, num_pretraining_epochs=3, num_annealing_epochs=3,
        steps_per_epoch=1, max_val_points=8,
    )
    trainer = DIBTrainer(model, bundle, config)
    state, history = trainer.fit(jax.random.key(1))
    assert history.kl_per_feature.shape == (6, 1)
