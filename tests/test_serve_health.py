"""Serve-stack self-healing: ejection, probe re-admission, truthful healthz.

The serve half of the fault-drill matrix (docs/robustness.md), in-process
and fast: a FlakyEngine replica must be ejected after consecutive
failures WITHOUT failing client calls (the router retries on healthy
replicas), re-admitted by probe once healed, and ``/healthz`` must stop
lying — 503 + detail when no replica can carry a request (all ejected, or
the batcher worker thread died).
"""

import json
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.models import DistributedIBModel
from dib_tpu.serve import (
    DIBServer,
    InferenceEngine,
    MicroBatcher,
    NoHealthyReplicaError,
    ReplicaEntry,
    ReplicaRouter,
)
from dib_tpu.faults import FlakyEngine, InjectedReplicaFault, kill_batcher_worker
from dib_tpu.telemetry import EventWriter, read_events, runtime_manifest

pytestmark = pytest.mark.fault


@pytest.fixture(scope="module")
def bundle():
    return get_dataset("boolean_circuit")


@pytest.fixture(scope="module")
def model(bundle):
    return DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )


@pytest.fixture(scope="module")
def params(bundle, model):
    x0 = np.asarray(bundle.x_train[:4], np.float32)
    return model.init(jax.random.key(0), x0, jax.random.key(1))


def _post(url: str, payload: dict) -> tuple[int, dict]:
    request = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(request, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def _get(url: str) -> tuple[int, dict]:
    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def _router(model, params, sick=None, num=2, run_dir=None, **kwargs):
    writer = None
    if run_dir is not None:
        writer = EventWriter(run_dir)
        writer.run_start(runtime_manifest(extra={"mode": "serve"}))
    entries, flaky = [], None
    for i in range(num):
        engine = InferenceEngine(model, params, batch_buckets=(1, 4))
        if i == 0 and sick is not None:
            engine = flaky = FlakyEngine(engine, telemetry=writer,
                                         replica=0, **sick)
        entries.append(ReplicaEntry(
            engine, MicroBatcher(engine, max_batch=4, max_wait_ms=0.5), i))
    kwargs.setdefault("probe_after_s", 0.0)   # deterministic: no thread
    router = ReplicaRouter(entries, telemetry=writer, **kwargs)
    return router, flaky, writer


# ------------------------------------------------------------- router unit
def test_consecutive_failures_eject_and_probe_readmits(model, params, tmp_path):
    run_dir = str(tmp_path / "run")
    router, flaky, writer = _router(model, params,
                                    sick={"fail_next": 100},
                                    eject_after=3, run_dir=run_dir)
    entry = router.entries[0]
    for _ in range(3):
        router.report_failure(entry, InjectedReplicaFault("x"))
    assert entry.ejected and entry.consecutive_failures == 3
    # routing skips the ejected entry entirely
    picks = {router.route().index for _ in range(6)}
    assert picks == {1}
    # a failing probe keeps it ejected; a healed probe re-admits
    assert router.probe_ejected(force=True) == 0
    assert entry.ejected
    flaky.heal()
    assert router.probe_ejected(force=True) == 1
    assert not entry.ejected and entry.consecutive_failures == 0
    router.close()
    writer.close()
    mits = [e["mtype"] for e in read_events(run_dir)
            if e["type"] == "mitigation"]
    assert mits == ["replica_ejected", "replica_readmitted"]


def test_intermittent_failures_do_not_eject(model, params):
    """Only CONSECUTIVE failures eject — a success resets the count, so a
    transient blip never takes a replica out."""
    router, _, _ = _router(model, params, sick={"fail_next": 0},
                           eject_after=3)
    entry = router.entries[0]
    for _ in range(5):
        router.report_failure(entry, RuntimeError("blip"))
        router.report_success(entry)
    assert not entry.ejected
    router.close()


def test_all_ejected_raises_no_healthy(model, params):
    router, _, _ = _router(model, params, num=2, eject_after=1)
    for entry in router.entries:
        router.report_failure(entry, RuntimeError("dead"))
    with pytest.raises(NoHealthyReplicaError):
        router.route()
    router.close()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_route_skips_dead_batcher_entries(model, params):
    """Routing must agree with /healthz: an entry whose batcher worker
    died is unserviceable — requests routed there would sit undrained
    until their deadline (code review finding)."""
    router, _, _ = _router(model, params, num=2)
    kill_batcher_worker(router.entries[0].batcher)
    picks = {router.route().index for _ in range(6)}
    assert picks == {1}
    kill_batcher_worker(router.entries[1].batcher)
    with pytest.raises(NoHealthyReplicaError):
        router.route()
    router.close()


def test_beta_routing_skips_ejected(model, params):
    entries = []
    for i, beta_end in enumerate((0.01, 1.0)):
        engine = InferenceEngine(model, params, batch_buckets=(1, 4))
        entries.append(ReplicaEntry(
            engine, MicroBatcher(engine, max_wait_ms=0.0), i,
            beta_end=beta_end))
    router = ReplicaRouter(entries, eject_after=1, probe_after_s=0.0)
    assert router.route(beta=0.02).index == 0
    router.report_failure(entries[0], RuntimeError("sick"))
    # nearest HEALTHY label now wins
    assert router.route(beta=0.02).index == 1
    router.report_failure(entries[1], RuntimeError("sick"))
    with pytest.raises(NoHealthyReplicaError):
        router.route(beta=0.02)
    router.close()


def test_timeouts_never_eject_the_last_serviceable_replica(model, params):
    """Timeout-class failures can be systemic (a load spike hits every
    replica), so they must degrade to 504s — never convert into a hard
    503 outage by ejecting the last serviceable replica (code review
    finding)."""
    from dib_tpu.serve import RequestTimeout

    router, _, _ = _router(model, params, num=2, eject_after=2)
    a, b = router.entries
    for _ in range(3):
        router.report_failure(a, RequestTimeout("slow"))
    assert a.ejected                         # others existed: eject fine
    for _ in range(5):
        router.report_failure(b, RequestTimeout("slow"))
    assert not b.ejected                     # the LAST one stays in service
    assert router.serviceable()
    # a non-timeout failure on the last replica still ejects (it is
    # genuinely broken, not merely slow)
    for _ in range(2):
        router.report_failure(b, RuntimeError("device error"))
    assert b.ejected
    router.close()


def test_queue_expiry_timeouts_do_not_mark_the_replica(model, params):
    """A deadline that expired while the request was STILL QUEUED is
    backpressure, not replica sickness (code review finding): it must not
    count toward ejection, while an in-flight dispatch timeout must."""
    from dib_tpu.serve import DIBServer, RequestTimeout

    class QueueExpiryBatcher:
        def is_alive(self):
            return True

        def close(self):
            pass

        def __call__(self, x, op, timeout_s=None):
            error = RequestTimeout("request timed out in queue")
            error.in_queue = True
            raise error

    class FakeEngine:
        feature_width = 4
        num_features = 1
        buckets = (1,)

    entry = ReplicaEntry(FakeEngine(), QueueExpiryBatcher(), 0)
    router = ReplicaRouter([entry], eject_after=1, probe_after_s=0.0)
    server = DIBServer(router, port=0).start()
    try:
        for _ in range(3):
            status, _ = server.handle_post("/v1/predict",
                                           {"x": [0.0] * 4,
                                            "timeout_s": 0.2})
            assert status == 504
        assert entry.consecutive_failures == 0
        assert not entry.ejected
    finally:
        server.close()


def test_retry_loop_shares_one_deadline_budget(model, params):
    """Retries across replicas must fit inside the client's ONE timeout_s
    (code review finding): each attempt gets the remaining budget, and an
    exhausted budget returns 504 instead of visiting every replica with a
    fresh full timeout."""
    import time as _time

    from dib_tpu.serve import DIBServer

    calls = []

    class FakeBatcher:
        def __init__(self, delay):
            self.delay = delay

        def is_alive(self):
            return True

        def close(self):
            pass

        def __call__(self, x, op, timeout_s=None):
            calls.append(round(timeout_s, 3))
            _time.sleep(self.delay)
            raise RuntimeError("engine fault")

    class FakeEngine:
        feature_width = 4
        num_features = 1
        buckets = (1,)

    entries = [ReplicaEntry(FakeEngine(), FakeBatcher(0.3), i)
               for i in range(3)]
    router = ReplicaRouter(entries, eject_after=10, probe_after_s=0.0)
    # started: close() calls httpd.shutdown(), which blocks forever unless
    # serve_forever is running
    server = DIBServer(router, port=0).start()
    try:
        status, payload = server.handle_post(
            "/v1/predict", {"x": [0.0] * 4, "timeout_s": 0.5})
    finally:
        server.close()
    assert status == 504
    assert "deadline" in payload["error"]
    assert len(calls) == 2                  # 3rd attempt never started
    assert calls[0] <= 0.5 and calls[1] < calls[0]


def test_slow_probe_does_not_readmit(model, params):
    """A replica ejected for being slow must not flap back in through an
    unbounded probe (code review finding): a probe dispatch slower than
    probe_timeout_s counts as failed."""
    import time as _time

    router, flaky, _ = _router(model, params, sick={"delay_s": 0.3},
                               eject_after=1, probe_timeout_s=0.1)
    entry = router.entries[0]
    router.report_failure(entry, RuntimeError("timeout"))
    assert entry.ejected
    assert router.probe_ejected(force=True) == 0
    assert entry.ejected
    assert "probe_timeout_s" in entry.last_error
    # the maintenance thread was NOT wedged by the slow probe: the probe
    # ran on a disposable thread; wait for it to drain, heal, re-probe
    flaky.heal()
    deadline = _time.monotonic() + 5.0
    while entry.probe_inflight and _time.monotonic() < deadline:
        _time.sleep(0.02)
    assert router.probe_ejected(force=True) == 1
    assert not entry.ejected
    router.close()


def test_probe_thread_readmits_in_background(model, params):
    """The periodic probe path (not force): an ejected replica that healed
    comes back without anyone calling probe_ejected()."""
    router, flaky, _ = _router(model, params, sick={"fail_next": 100},
                               eject_after=1, probe_after_s=0.1)
    entry = router.entries[0]
    router.report_failure(entry, InjectedReplicaFault("x"))
    assert entry.ejected
    flaky.heal()
    deadline = threading.Event()
    for _ in range(100):
        if not entry.ejected:
            break
        deadline.wait(0.05)
    assert not entry.ejected, "probe thread never re-admitted the replica"
    router.close()


# --------------------------------------------------------- HTTP end-to-end
def test_sick_replica_never_fails_client_calls(model, params, tmp_path):
    """THE serve drill acceptance: with a healthy replica available, a
    sick one produces ZERO client-visible 5xx — requests retry onto the
    healthy replica and the sick one is ejected."""
    run_dir = str(tmp_path / "run")
    router, flaky, writer = _router(model, params,
                                    sick={"fail_next": 1000},
                                    eject_after=3, run_dir=run_dir)
    server = DIBServer(router, port=0, telemetry=writer).start()
    try:
        width = router.entries[0].engine.feature_width
        row = [0.0] * width
        statuses = [_post(server.url + "/v1/predict", {"x": row})[0]
                    for _ in range(12)]
        assert statuses == [200] * 12
        assert router.entries[0].ejected
        # the healthy replica carried everything after ejection
        status, health = _get(server.url + "/healthz")
        assert status == 200 and health["healthy_replicas"] == 1
    finally:
        server.close()
    events = list(read_events(run_dir))
    assert any(e["type"] == "fault" and e["kind"] == "replica_error"
               for e in events)
    assert any(e.get("mtype") == "replica_ejected" for e in events)


def test_healthz_503_when_all_replicas_ejected(model, params, tmp_path):
    run_dir = str(tmp_path / "run")
    router, flaky, writer = _router(model, params,
                                    sick={"fail_next": 1000}, num=1,
                                    eject_after=2, run_dir=run_dir)
    server = DIBServer(router, port=0, telemetry=writer).start()
    try:
        width = router.entries[0].engine.feature_width
        row = [0.0] * width
        # two failed requests reach eject_after=2 on the only replica
        codes = [_post(server.url + "/v1/predict", {"x": row})[0]
                 for _ in range(2)]
        assert codes == [503, 503]     # the only replica failed each one
        assert router.entries[0].ejected
        status, health = _get(server.url + "/healthz")
        assert status == 503
        assert health["status"] == "unhealthy"
        assert "ejected" in health["detail"]
        assert health["feature_width"] == width   # surface stays present
        # recovery: heal + probe → healthz healthy again, with an event edge
        flaky.heal()
        router.probe_ejected(force=True)
        status, health = _get(server.url + "/healthz")
        assert status == 200 and health["status"] == "ok"
    finally:
        server.close()
    mits = [e["mtype"] for e in read_events(run_dir)
            if e["type"] == "mitigation"]
    assert "serving_unhealthy" in mits and "serving_recovered" in mits


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_healthz_503_when_batcher_thread_dies(model, params, tmp_path):
    run_dir = str(tmp_path / "run")
    router, _, writer = _router(model, params, num=1, run_dir=run_dir)
    server = DIBServer(router, port=0, telemetry=writer).start()
    try:
        status, _ = _get(server.url + "/healthz")
        assert status == 200
        assert kill_batcher_worker(router.entries[0].batcher,
                                   telemetry=writer)
        assert not router.entries[0].batcher.is_alive()
        status, health = _get(server.url + "/healthz")
        assert status == 503
        assert "batcher" in health["detail"]
        # self-healing: the maintenance tick revives the dead worker and
        # the server carries requests again
        assert router.probe_ejected(force=True) == 0   # nothing ejected...
        assert router.entries[0].batcher.is_alive()    # ...but revived
        status, health = _get(server.url + "/healthz")
        assert status == 200 and health["status"] == "ok"
        width = router.entries[0].engine.feature_width
        status, _ = _post(server.url + "/v1/predict", {"x": [0.0] * width})
        assert status == 200
    finally:
        server.close()
    events = list(read_events(run_dir))
    assert any(e["type"] == "fault" and e["kind"] == "batcher_crash"
               for e in events)
    mits = [e["mtype"] for e in events if e["type"] == "mitigation"]
    assert "batcher_restarted" in mits


def test_request_timeout_counts_toward_ejection(model, params):
    """A slow replica fails by deadline: 504s mark it, ejection follows,
    later requests go healthy-only."""
    router, flaky, _ = _router(model, params, sick={"delay_s": 0.5},
                               eject_after=2)
    server = DIBServer(router, port=0).start()
    try:
        width = router.entries[0].engine.feature_width
        row = [0.0] * width
        statuses = [_post(server.url + "/v1/predict",
                          {"x": row, "timeout_s": 0.2})[0]
                    for _ in range(8)]
        assert router.entries[0].ejected
        assert statuses.count(504) >= 2          # the slow replica's marks
        assert not any(s in (500, 503) for s in statuses)
        assert all(s == 200
                   for s in [_post(server.url + "/v1/predict",
                                   {"x": row})[0] for _ in range(3)])
    finally:
        server.close()


def test_client_errors_never_mark_the_replica(model, params):
    """400s are the CLIENT's fault: no failure count, no ejection."""
    router, _, _ = _router(model, params, num=1, eject_after=1)
    server = DIBServer(router, port=0).start()
    try:
        width = router.entries[0].engine.feature_width
        status, _ = _post(server.url + "/v1/predict",
                          {"x": [0.0] * (width + 1)})
        assert status == 400
        status, _ = _post(server.url + "/v1/predict",
                          {"x": [float("nan")] * width})
        assert status == 400
        assert router.entries[0].consecutive_failures == 0
        assert not router.entries[0].ejected
    finally:
        server.close()
