"""The chaos-suite artifact contract + the scheduler-under-load drills.

Fast tier (``-m fault``): the committed ``CHAOS_SCHED.json`` must exist,
validate against the artifact schema (per-row scheduler invariants
included), cover every drill, and show all of them passing — the "zero
lost units / no double-execution / bit-identical per-β histories"
guarantees docs/robustness.md cites are only as good as the committed
evidence. The in-process drill half (real training units under worker
kills, lease theft, preemption, torn journals) re-runs in tier 1; the
full matrix with the subprocess ``pool_kill`` drill is ``@slow``.
"""

import importlib.util
import json
import os
import sys

import pytest

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "CHAOS_SCHED.json")

EXPECTED_DRILLS = {
    "worker_kill", "lease_expire", "preempt", "journal_torn", "pool_kill",
}
QUICK_DRILLS = EXPECTED_DRILLS - {"pool_kill"}
INVARIANTS = ("zero_lost_units", "no_double_execution",
              "bit_identical_histories")


def _load_chaos_module():
    spec = importlib.util.spec_from_file_location(
        "chaos_suite", os.path.join(REPO, "scripts", "chaos_suite.py"))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_committed_chaos_artifact_validates():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from check_run_artifacts import check_file

    assert os.path.exists(ARTIFACT), (
        "CHAOS_SCHED.json missing — run `python scripts/chaos_suite.py "
        "--out CHAOS_SCHED.json` and commit the record")
    assert check_file(ARTIFACT) == []


def test_committed_chaos_matrix_is_complete_and_green():
    with open(ARTIFACT) as f:
        record = json.load(f)
    assert record["metric"] == "chaos_sched_matrix"
    assert record["unit"] == "drills_passed"
    drills = {d["drill"]: d for d in record["matrix"]}
    assert set(drills) == EXPECTED_DRILLS
    failed = [name for name, d in drills.items() if not d["ok"]]
    assert not failed, f"committed chaos record shows failures: {failed}"
    assert record["all_passed"] is True
    assert record["value"] == record["total"] == len(EXPECTED_DRILLS)
    # the committed record must be the FULL matrix, not a --quick run
    assert record["quick"] is False
    # every drill holds all three scheduler invariants
    for name, d in drills.items():
        for invariant in INVARIANTS:
            assert d[invariant] is True, (name, invariant)


def test_committed_chaos_evidence_detection_and_recovery():
    """The stream-side join (telemetry summarize) must agree with the
    suite's own bookkeeping: every injected scheduler fault detected AND
    recovered, and the journal's double-execution guard visibly fired in
    the drills that provoke stale leases."""
    with open(ARTIFACT) as f:
        record = json.load(f)
    for d in record["matrix"]:
        faults = (d.get("evidence") or {}).get("faults") or {}
        assert faults.get("undetected") == [], d["drill"]
        assert faults.get("detected") == faults.get("injected"), d["drill"]
        assert faults.get("recovered") == faults.get("injected"), d["drill"]
    by_name = {d["drill"]: d for d in record["matrix"]}
    # the stale holder in lease_expire must have been REJECTED, not lost
    sched = by_name["lease_expire"]["evidence"]["scheduler"]
    assert sched["leases_rejected"] >= 1
    assert sched["leases_expired"] >= 1
    # preemption re-queued lease-free: no retry burned
    assert by_name["preempt"]["retries_burned"] == 0
    # the torn journal was actually replayed around
    assert by_name["journal_torn"]["replayed_torn"] == 1


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_quick_chaos_matrix_end_to_end(tmp_path):
    """Run the in-process chaos drills for real in tier 1: real training
    units through a real pool under worker kills, lease theft,
    preemption, and a torn journal — all three invariants must hold."""
    module = _load_chaos_module()
    record = module.run_chaos(workdir=str(tmp_path), quick=True,
                              log=lambda m: None)
    failed = [d for d in record["matrix"] if not d["ok"]]
    assert not failed, json.dumps(failed, indent=1, default=str)[:4000]
    assert {d["drill"] for d in record["matrix"]} == QUICK_DRILLS
    assert record["all_passed"]


@pytest.mark.slow
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_full_chaos_matrix_end_to_end(tmp_path):
    """The full matrix including the subprocess pool_kill drill."""
    module = _load_chaos_module()
    record = module.run_chaos(workdir=str(tmp_path), quick=False,
                              log=lambda m: None)
    failed = [d for d in record["matrix"] if not d["ok"]]
    assert not failed, json.dumps(failed, indent=1, default=str)[:4000]
    assert record["all_passed"]


def test_chaos_registers_in_fleet_registry(tmp_path):
    """Satellite: drill records land in the fleet registry under an
    explicit runs root, so `telemetry runs trajectory` carries the
    robustness history."""
    module = _load_chaos_module()
    with open(ARTIFACT) as f:
        record = json.load(f)
    root = str(tmp_path / "runs")
    module._register(record, root, log=lambda m: None)
    from dib_tpu.telemetry.registry import RunRegistry, validate_index_entry

    entries = RunRegistry(root).bench_history()
    assert len(entries) == 1
    assert entries[0]["metric"] == "chaos_sched_matrix"
    assert entries[0]["all_passed"] is True
    assert validate_index_entry(entries[0]) == []
    # ... and NOT without one (the committed index must not grow from
    # ad-hoc local runs)
    os.environ.pop("DIB_RUNS_ROOT", None)
    module._register(record, None, log=lambda m: None)
    assert len(RunRegistry(root).bench_history()) == 1


def test_fault_drill_registers_in_fleet_registry(tmp_path):
    """Same satellite for scripts/fault_drill.py."""
    spec = importlib.util.spec_from_file_location(
        "fault_drill", os.path.join(REPO, "scripts", "fault_drill.py"))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    with open(os.path.join(REPO, "FAULT_DRILL.json")) as f:
        record = json.load(f)
    root = str(tmp_path / "runs")
    module.register_record(record, root, log=lambda m: None)
    from dib_tpu.telemetry.registry import RunRegistry

    entries = RunRegistry(root).bench_history()
    assert len(entries) == 1 and entries[0]["metric"] == "fault_drill_matrix"


def test_committed_registry_carries_robustness_history():
    """The committed runs/index.jsonl is seeded with the drill + chaos
    evidence records, so the registry is not blind to robustness."""
    from dib_tpu.telemetry.registry import RunRegistry

    metrics = {e.get("metric") for e in
               RunRegistry(os.path.join(REPO, "runs")).bench_history()}
    assert "fault_drill_matrix" in metrics
    assert "chaos_sched_matrix" in metrics
