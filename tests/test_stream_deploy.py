"""Deployer: journal tailing, exactly-once catch-up across every crash
window, canary-gated promotion, rollback (``dib_tpu/stream/deployer.py``,
docs/streaming.md "Promotion and rollback").

The deploy journal is the exactly-once ledger. A deployer can die in
three windows and each has a pinned recovery:

  - AFTER a deploy record landed: the restart preloads the processed set
    from the journal and never re-promotes (no double promotion);
  - BETWEEN the reload and its record: the restart re-runs an IDEMPOTENT
    reload of the same checkpoint — the journal still ends with at most
    one record per publish;
  - BEFORE anything: plain catch-up in publish order, none skipped.

The canary gate: a poisoned (NaN-params) published checkpoint is rolled
back and the previous checkpoint keeps answering — bit-for-bit.
"""

import json
import os
import shutil
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.models import DistributedIBModel
from dib_tpu.serve import DIBServer, InferenceEngine, ModelZoo
from dib_tpu.stream.deployer import (
    Deployer,
    deploys_path,
    read_deploys,
    stream_status,
)
from dib_tpu.stream.online import (
    OnlineConfig,
    OnlineDIBTrainer,
    read_publishes,
)
from dib_tpu.train import DIBCheckpointer, DIBTrainer, TrainConfig

WINDOW, STRIDE, CHUNK_EPOCHS, BATCH = 32, 8, 1, 16


@pytest.fixture(scope="module")
def bundle():
    return get_dataset("boolean_circuit")


@pytest.fixture(scope="module")
def model(bundle):
    return DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )


def _config():
    return TrainConfig(batch_size=BATCH, num_pretraining_epochs=1,
                       num_annealing_epochs=2)


@pytest.fixture(scope="module")
def published_stream(model, bundle, tmp_path_factory):
    """One stream dir with three real publishes, trained once and shared
    read-only; tests that mutate copy it first."""
    stream_dir = tmp_path_factory.mktemp("stream")
    online = OnlineConfig(window=WINDOW, stride=STRIDE,
                          chunk_epochs=CHUNK_EPOCHS, publish_every=1,
                          rounds=3, seed=0)
    OnlineDIBTrainer(model, bundle, _config(), online,
                     str(stream_dir)).run(jax.random.key(0))
    records, torn = read_publishes(str(stream_dir))
    assert torn == 0 and len(records) == 3
    return str(stream_dir)


def _template(model, bundle):
    return DIBTrainer(model, bundle, _config())


def _deployer(model, bundle, stream_dir, deploy_dir, **kwargs):
    zoo = kwargs.pop("zoo", None) or ModelZoo(exec_capacity=8,
                                              response_capacity=16)
    return zoo, Deployer(str(stream_dir), str(deploy_dir),
                         _template(model, bundle), zoo,
                         router_kwargs=dict(batch_buckets=(1, 8)),
                         **kwargs)


def _expected(model, bundle, stream_dir, rows):
    """{publish_id: prediction | None(poisoned)} over the journal."""
    out = {}
    for rec in read_publishes(str(stream_dir))[0]:
        template = _template(model, bundle)
        ckpt = DIBCheckpointer(os.path.join(str(stream_dir), rec["path"]))
        try:
            state, _, _ = ckpt.restore(template)
        finally:
            ckpt.close()
        engine = InferenceEngine(template.model, state.params["model"],
                                 batch_buckets=(1, 8))
        prediction = np.asarray(engine.predict(rows)["prediction"])
        out[rec["publish_id"]] = (prediction if np.all(np.isfinite(prediction))
                                  else None)
    return out


def _serve_once(zoo, rows):
    server = DIBServer(zoo)
    try:
        status, payload = server.handle_post(
            "/v1/predict", {"x": [[float(v) for v in r] for r in rows]})
    finally:
        server.close()   # never started: releases the socket + the zoo
    assert status == 200
    return np.asarray(payload["prediction"])


def test_catch_up_is_exactly_once_and_restart_safe(
        model, bundle, published_stream, tmp_path):
    """Catch-up promotes each publish once, in order; a second pass and
    a restarted deployer (records already journaled) promote nothing
    again."""
    deploy_dir = tmp_path / "deploy"
    zoo, deployer = _deployer(model, bundle, published_stream, deploy_dir)
    with deployer:
        assert deployer.catch_up() == 3
        assert deployer.catch_up() == 0          # idempotent second pass
        assert deployer.status()["promoted"] == 3

    records, torn = read_deploys(str(deploy_dir))
    assert torn == 0
    assert [r["action"] for r in records] == ["promoted"] * 3
    assert [r["publish_index"] for r in records] == [0, 1, 2]

    # the restart window AFTER a record landed: never re-promoted
    zoo2, restarted = _deployer(model, bundle, published_stream,
                                deploy_dir)
    with restarted:
        assert restarted.catch_up() == 0
        assert restarted.status()["promoted"] == 3   # from the journal
    assert len(read_deploys(str(deploy_dir))[0]) == 3

    status = stream_status(published_stream, str(deploy_dir))
    assert status["pending"] == 0
    assert status["lost_publishes"] == 0
    assert status["double_promotions"] == 0
    zoo.close()
    zoo2.close()


def test_restart_between_reload_and_record_is_idempotent(
        model, bundle, published_stream, tmp_path):
    """The kill window between ``ModelZoo.reload`` and the journal
    append: the restart re-runs the reload of the same checkpoint and
    the journal ends with exactly one record per publish — and the fleet
    answers from the final checkpoint."""
    deploy_dir = tmp_path / "deploy"
    zoo, deployer = _deployer(model, bundle, published_stream, deploy_dir)
    with deployer:
        deployer.catch_up()

    # simulate the crash: the LAST reload happened but its record never
    # landed (SIGKILL between the swap and the append)
    records, _ = read_deploys(str(deploy_dir))
    with open(deploys_path(str(deploy_dir))) as f:
        lines = f.readlines()
    with open(deploys_path(str(deploy_dir)), "w") as f:
        f.writelines(lines[:-1])

    zoo2, restarted = _deployer(model, bundle, published_stream,
                                deploy_dir)
    with restarted:
        assert restarted.catch_up() == 1     # exactly the undecided one
        assert restarted.catch_up() == 0
        rows = np.asarray(bundle.x_valid[:4], np.float32)
        served = _serve_once(zoo2, rows)

    records, _ = read_deploys(str(deploy_dir))
    by_publish = {}
    for rec in records:
        by_publish[rec["publish_id"]] = by_publish.get(rec["publish_id"],
                                                       0) + 1
    assert all(count == 1 for count in by_publish.values()), \
        "at most one deploy record per publish across the crash window"
    status = stream_status(published_stream, str(deploy_dir))
    assert status["lost_publishes"] == 0
    assert status["double_promotions"] == 0

    expected = _expected(model, bundle, published_stream, rows)
    final = read_publishes(published_stream)[0][-1]["publish_id"]
    np.testing.assert_allclose(served, expected[final], rtol=1e-6)
    zoo.close()


def test_canary_failure_rolls_back_previous_keeps_answering(
        model, bundle, published_stream, tmp_path):
    """A poisoned (NaN-params) checkpoint published through the real
    protocol is rolled back by the canary gate; the previous checkpoint
    keeps answering bit-for-bit, and the rollback is durably recorded."""
    stream_dir = tmp_path / "stream"
    shutil.copytree(published_stream, stream_dir)
    _publish_poison(model, bundle, str(stream_dir))

    deploy_dir = tmp_path / "deploy"
    zoo, deployer = _deployer(model, bundle, stream_dir, deploy_dir)
    with deployer:
        assert deployer.catch_up() == 4
        status = deployer.status()
        assert status["promoted"] == 3 and status["rollbacks"] == 1
        rows = np.asarray(bundle.x_valid[:4], np.float32)
        served = _serve_once(zoo, rows)

    records, _ = read_deploys(str(deploy_dir))
    assert [r["action"] for r in records] == ["promoted"] * 3 \
        + ["rolled_back"]
    assert "non-finite" in records[-1]["error"]

    expected = _expected(model, bundle, str(stream_dir), rows)
    assert expected["pub-poison"] is None
    last_good = [pid for pid, out in expected.items()
                 if out is not None][-1]
    np.testing.assert_allclose(served, expected[last_good], rtol=1e-6)

    status = stream_status(str(stream_dir), str(deploy_dir))
    assert status["pending"] == 0 and status["double_promotions"] == 0


def test_unrestorable_publish_is_gated_like_a_failed_canary(
        model, bundle, published_stream, tmp_path):
    """A publish record whose checkpoint bytes cannot restore (wrong
    architecture, torn by an outside force) rolls back instead of
    wedging the tail loop."""
    stream_dir = tmp_path / "stream"
    shutil.copytree(published_stream, stream_dir)
    records, _ = read_publishes(str(stream_dir))
    shutil.rmtree(stream_dir / records[-1]["path"])
    (stream_dir / records[-1]["path"]).mkdir()   # exists, but empty

    deploy_dir = tmp_path / "deploy"
    zoo, deployer = _deployer(model, bundle, stream_dir, deploy_dir)
    with deployer:
        assert deployer.catch_up() == 3
        status = deployer.status()
        assert status["promoted"] == 2 and status["rollbacks"] == 1
    zoo.close()
    out = read_deploys(str(deploy_dir))[0][-1]
    assert out["action"] == "rolled_back"
    assert "restore failed" in out["error"]


def _publish_poison(model, bundle, stream_dir: str) -> None:
    """Publish a NaN-params checkpoint through the REAL protocol (stage,
    fsync, rename, journal) — a trainer whose model diverged between the
    divergence guard's boundaries."""
    from dib_tpu.sched.journal import JobJournal
    from dib_tpu.stream.online import (
        CHECKPOINTS_DIRNAME,
        PUBLISHES_FILENAME,
        STAGING_DIRNAME,
        _fsync_tree,
    )

    last = read_publishes(stream_dir)[0][-1]
    template = _template(model, bundle)
    ckpt = DIBCheckpointer(os.path.join(stream_dir, last["path"]))
    try:
        state, history, key = ckpt.restore(template)
    finally:
        ckpt.close()
    poisoned = state._replace(
        params=jax.tree.map(lambda a: jnp.full_like(a, jnp.nan),
                            state.params))
    step = int(last["step"]) + CHUNK_EPOCHS
    rel = os.path.join(CHECKPOINTS_DIRNAME, "pub-poison")
    staging = os.path.join(stream_dir, STAGING_DIRNAME, "pub-poison")
    out = DIBCheckpointer(staging, max_to_keep=1)
    try:
        out.save(step, poisoned, history, key, chunk_size=CHUNK_EPOCHS)
    finally:
        out.close()
    _fsync_tree(staging)
    os.replace(staging, os.path.join(stream_dir, rel))
    journal = JobJournal(stream_dir, filename=PUBLISHES_FILENAME)
    try:
        journal.append("publish", publish_id="pub-poison",
                       index=int(last["index"]) + 1, step=step,
                       round=int(last["round"]) + 1, path=rel,
                       beta=float(last.get("beta") or 0.0),
                       chunk_epochs=CHUNK_EPOCHS,
                       source=last.get("source"), drifts=0, baseline=None)
    finally:
        journal.close()


def test_deploy_events_land_on_the_telemetry_stream(
        model, bundle, published_stream, tmp_path):
    """Promotions and rollbacks are visible to `telemetry summarize`:
    the streaming rollup reports them with the journal invariants."""
    from dib_tpu.telemetry import EventWriter, summarize

    stream_dir = tmp_path / "stream"
    shutil.copytree(published_stream, stream_dir)
    _publish_poison(model, bundle, str(stream_dir))

    run_dir = tmp_path / "deploy"
    writer = EventWriter(str(run_dir))
    writer.run_start({"mode": "stream_deploy"})
    zoo, deployer = _deployer(model, bundle, stream_dir, run_dir,
                              telemetry=writer)
    with deployer:
        deployer.catch_up()
    zoo.close()
    writer.run_end(status="ok")
    writer.close()

    summary = summarize(str(run_dir))
    assert summary["mode"] == "stream_deploy"
    streaming = summary["streaming"]
    assert streaming["deploys"] == 4
    assert streaming["promoted"] == 3
    assert streaming["rollbacks"] == 1
    assert streaming["lost_publishes"] == 0
    assert streaming["double_promotions"] == 0
    assert streaming["publish_to_serve_p99_s"] >= 0
    # the rollback is also a mitigation (the canary gate firing)
    assert summary["mitigations"].get("canary_rollback") == 1


def test_restart_with_decided_journal_warm_restores_the_fleet(
        model, bundle, published_stream, tmp_path):
    """A deployer restarted when EVERY publish is already decided
    re-registers the newest promoted checkpoint from the journal: the
    fleet answers immediately (the always-on contract) instead of
    serving nothing until the trainer's next publish — and NO new deploy
    record lands, because rebuilding in-memory state is not a promotion
    decision and a second record would read as a double promotion."""
    deploy_dir = tmp_path / "deploy"
    zoo, deployer = _deployer(model, bundle, published_stream, deploy_dir)
    with deployer:
        assert deployer.catch_up() == 3
    zoo.close()

    zoo2, restarted = _deployer(model, bundle, published_stream,
                                deploy_dir)
    with restarted:
        assert restarted.catch_up() == 0          # nothing undecided
        rows = np.asarray(bundle.x_valid[:4], np.float32)
        served = _serve_once(zoo2, rows)          # ...yet it answers

    assert len(read_deploys(str(deploy_dir))[0]) == 3   # no new record
    expected = _expected(model, bundle, published_stream, rows)
    final = read_publishes(published_stream)[0][-1]["publish_id"]
    np.testing.assert_allclose(served, expected[final], rtol=1e-6)
    status = stream_status(published_stream, str(deploy_dir))
    assert status["double_promotions"] == 0
    assert status["lost_publishes"] == 0


def test_swap_failure_is_gated_once_and_tail_continues(
        model, bundle, published_stream, tmp_path):
    """A zoo swap that raises — the one promotion step ``_process`` does
    not gate itself — is decided as rolled_back: the tail neither dies
    nor wedges retrying the same record, later publishes still promote,
    and a restart never re-decides it."""
    deploy_dir = tmp_path / "deploy"
    zoo, deployer = _deployer(model, bundle, published_stream, deploy_dir)
    real_reload, calls = zoo.reload, {"n": 0}

    def flaky_reload(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("swap infrastructure hiccup")
        return real_reload(*args, **kwargs)

    zoo.reload = flaky_reload
    with deployer:
        assert deployer.catch_up() == 3
        status = deployer.status()
        assert status["promoted"] == 2 and status["rollbacks"] == 1
    zoo.close()

    records, _ = read_deploys(str(deploy_dir))
    assert [r["action"] for r in records] == [
        "promoted", "rolled_back", "promoted"]
    assert "deploy failed" in records[1]["error"]

    zoo2, restarted = _deployer(model, bundle, published_stream,
                                deploy_dir)
    with restarted:
        assert restarted.catch_up() == 0
    zoo2.close()
    assert len(read_deploys(str(deploy_dir))[0]) == 3
    status = stream_status(published_stream, str(deploy_dir))
    assert status["double_promotions"] == 0


def test_malformed_publish_record_is_decided_exactly_once(
        model, bundle, published_stream, tmp_path):
    """A parseable journal record WITHOUT ``publish_id`` (a foreign
    writer broke the trainer's contract) gets one durable rolled_back
    decision under a deterministic fallback identity — later polls that
    re-read a grown journal must not re-decide it, or the deploy journal
    grows one duplicate per publish forever."""
    from dib_tpu.sched.journal import JobJournal
    from dib_tpu.stream.online import PUBLISHES_FILENAME

    stream_dir = tmp_path / "stream"
    shutil.copytree(published_stream, stream_dir)
    journal = JobJournal(str(stream_dir), filename=PUBLISHES_FILENAME)
    try:
        journal.append("publish", index=99, step=99,
                       path="checkpoints/nowhere")
    finally:
        journal.close()

    deploy_dir = tmp_path / "deploy"
    zoo, deployer = _deployer(model, bundle, stream_dir, deploy_dir)
    with deployer:
        assert deployer.catch_up() == 4
        # grow the publish journal so the next poll re-parses it (the
        # idle short-circuit would otherwise mask a re-decide bug)
        journal = JobJournal(str(stream_dir), filename=PUBLISHES_FILENAME)
        try:
            journal.append("publish", publish_id="pub-gone", index=4,
                           step=9, path="checkpoints/also-nowhere")
        finally:
            journal.close()
        assert deployer.catch_up() == 1        # only the NEW record
    zoo.close()

    records, _ = read_deploys(str(deploy_dir))
    assert len(records) == 5
    by_publish = {}
    for rec in records:
        by_publish[rec["publish_id"]] = by_publish.get(
            rec["publish_id"], 0) + 1
    assert all(c == 1 for c in by_publish.values()), \
        "one decision per record, malformed included"


def test_tail_loop_survives_append_failure_and_retries(
        model, bundle, published_stream, tmp_path):
    """The one failure class that escapes ``catch_up`` — the deploy
    journal append itself failing — lands as a durable mitigation and
    the NEXT poll retries the undecided records: the idle short-circuit
    must not treat the failed pass's journal size as 'done'."""
    from dib_tpu.telemetry import EventWriter, summarize

    run_dir = tmp_path / "deploy"
    writer = EventWriter(str(run_dir))
    writer.run_start({"mode": "stream_deploy"})
    zoo, deployer = _deployer(model, bundle, published_stream, run_dir,
                              telemetry=writer, poll_s=0.05)
    real_append, calls = deployer._journal.append, {"n": 0}

    def flaky_append(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] <= 2:     # the decision append AND its rollback
            raise OSError("disk went away")
        return real_append(*args, **kwargs)

    deployer._journal.append = flaky_append
    deployer.start()
    deadline = time.monotonic() + 180.0
    while time.monotonic() < deadline:
        if deployer.status()["promoted"] == 3:
            break
        time.sleep(0.05)
    deployer.close()
    zoo.close()
    writer.run_end(status="ok")
    writer.close()

    records, _ = read_deploys(str(run_dir))
    assert [r["action"] for r in records] == ["promoted"] * 3
    summary = summarize(str(run_dir))
    assert summary["mitigations"].get("deployer_tail_error") == 1
    status = stream_status(published_stream, str(run_dir))
    assert status["lost_publishes"] == 0
    assert status["double_promotions"] == 0


def test_idle_poll_short_circuits_without_reparsing(
        model, bundle, published_stream, tmp_path, monkeypatch):
    """An unchanged publish journal costs the idle poll one stat, never
    a full re-parse — an always-on deployer polls forever, so the idle
    path must be O(1) in journal length."""
    import dib_tpu.stream.deployer as deployer_mod

    real, calls = deployer_mod.read_publishes, {"n": 0}

    def counting(stream_dir):
        calls["n"] += 1
        return real(stream_dir)

    monkeypatch.setattr(deployer_mod, "read_publishes", counting)
    deploy_dir = tmp_path / "deploy"
    zoo, deployer = _deployer(model, bundle, published_stream, deploy_dir)
    with deployer:
        assert deployer.catch_up() == 3
        after_first = calls["n"]
        assert deployer.catch_up() == 0
        assert deployer.catch_up() == 0
        assert calls["n"] == after_first
    zoo.close()


def test_telemetry_write_failure_never_escapes_a_decided_record(
        model, bundle, published_stream, tmp_path):
    """The journal append is the decision; telemetry is best-effort
    AFTER it. An events.jsonl write error on a decided record must not
    escape _record — it would land in catch_up's guard and append a
    SECOND (rolled_back) decision for a publish that promoted fine."""
    class BrokenTelemetry:
        def deploy(self, **kw):
            raise OSError("events.jsonl: no space left on device")

        def mitigation(self, **kw):
            raise OSError("events.jsonl: no space left on device")

    deploy_dir = tmp_path / "deploy"
    zoo, deployer = _deployer(model, bundle, published_stream, deploy_dir,
                              telemetry=BrokenTelemetry())
    with deployer:
        assert deployer.catch_up() == 3
        assert deployer.catch_up() == 0          # idle, nothing re-decided
    zoo.close()

    records = read_deploys(str(deploy_dir))[0]
    assert [r["action"] for r in records] == ["promoted"] * 3
    status = stream_status(published_stream, str(deploy_dir))
    assert status["double_promotions"] == 0
    assert status["rollbacks"] == 0


def test_failure_after_decision_is_not_redecided(
        model, bundle, published_stream, tmp_path, monkeypatch):
    """catch_up's poisoned-record guard decides ONLY undecided records:
    an error raised after _process journaled its decision (any
    post-append failure) must not append a contradicting rollback."""
    deploy_dir = tmp_path / "deploy"
    zoo, deployer = _deployer(model, bundle, published_stream, deploy_dir)
    real_process = deployer._process

    def process_then_boom(rec):
        real_process(rec)
        raise RuntimeError("failure after the journal append")

    monkeypatch.setattr(deployer, "_process", process_then_boom)
    with deployer:
        deployer.catch_up()
    zoo.close()

    records = read_deploys(str(deploy_dir))[0]
    assert [r["action"] for r in records] == ["promoted"] * 3
    status = stream_status(published_stream, str(deploy_dir))
    assert status["double_promotions"] == 0


def test_partial_view_rollup_anchors_lost_publishes_at_the_oldest_seen(
        tmp_path):
    """A deployer restarted with a FRESH telemetry dir only carries
    deploy events for publishes decided this launch (say indices 7, 8);
    indices below the view were decided in the prior launch's stream.
    Counting them as lost would page stream_lost_publish_max falsely —
    the gap count anchors at min(index) in view, where a real skip
    still shows (7 then 9 without 8)."""
    from dib_tpu.telemetry.summary import streaming_rollup

    def deploy_event(index):
        return {"type": "deploy", "action": "promoted",
                "publish_id": f"pub-{index}", "index": index,
                "latency_s": 0.25}

    partial = streaming_rollup([deploy_event(7), deploy_event(8)])
    assert partial["lost_publishes"] == 0
    gapped = streaming_rollup([deploy_event(7), deploy_event(9)])
    assert gapped["lost_publishes"] == 1

    # the journal-based view (stream status CLI) uses the same anchor
    deploy_dir = tmp_path / "deploy"
    deploy_dir.mkdir()
    with open(deploys_path(str(deploy_dir)), "w") as fh:
        for index in (7, 8):
            fh.write(json.dumps({
                "kind": "deploy", "publish_id": f"pub-{index}",
                "action": "promoted", "publish_index": index}) + "\n")
    stream_dir = tmp_path / "stream"
    stream_dir.mkdir()
    status = stream_status(str(stream_dir), str(deploy_dir))
    assert status["lost_publishes"] == 0
