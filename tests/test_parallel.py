"""Mesh + beta-sweep parallelism tests on the virtual 8-device CPU mesh.

These are the distributed tests the reference does not have (SURVEY.md
section 4): sharding and collectives are exercised through real pjit
partitioning over ``--xla_force_host_platform_device_count=8`` devices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from dib_tpu.data import get_dataset
from dib_tpu.models import DistributedIBModel
from dib_tpu.parallel import (
    BetaSweepTrainer,
    factor_devices,
    make_sweep_mesh,
    replica_sharding,
)
from dib_tpu.train import DIBTrainer, TrainConfig


def tiny_model(bundle):
    return DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(16,),
        integration_hidden=(32,),
        output_dim=bundle.output_dimensionality,
        embedding_dim=4,
        output_activation=bundle.output_activation,
    )


@pytest.fixture(scope="module")
def bundle():
    return get_dataset("boolean_circuit", number_inputs=6, seed=1)


CFG = TrainConfig(
    batch_size=64,
    beta_start=1e-3,
    beta_end=1.0,
    num_pretraining_epochs=2,
    num_annealing_epochs=6,
    steps_per_epoch=2,
    max_val_points=128,
)


# ---------------------------------------------------------------- mesh utils
def test_make_sweep_mesh_shapes():
    mesh = make_sweep_mesh(4, 2)
    assert mesh.shape == {"beta": 4, "data": 2}
    mesh = make_sweep_mesh()          # all devices on the sweep axis
    assert mesh.shape["beta"] == len(jax.devices())
    with pytest.raises(ValueError):
        make_sweep_mesh(16, 16)


def test_factor_devices():
    assert factor_devices(8) == (4, 2)
    assert factor_devices(7) == (7, 1)
    assert factor_devices(1) == (1, 1)


# ------------------------------------------------------------- sweep trainer
def test_sweep_matches_serial_trainer(bundle):
    """One sweep replica == the serial trainer, exactly (same keys/endpoints)."""
    model = tiny_model(bundle)
    key = jax.random.key(7)

    serial = DIBTrainer(model, bundle, CFG)
    _, hist_serial = serial.fit(key)

    sweep = BetaSweepTrainer(
        model, bundle, CFG, beta_starts=CFG.beta_start, beta_ends=CFG.beta_end
    )
    _, records = sweep.fit(jnp.stack([key]))

    np.testing.assert_allclose(records[0].beta, hist_serial.beta, rtol=1e-6)
    np.testing.assert_allclose(
        records[0].kl_per_feature, hist_serial.kl_per_feature, rtol=2e-4, atol=1e-6
    )
    np.testing.assert_allclose(records[0].loss, hist_serial.loss, rtol=2e-4, atol=1e-6)


def test_sweep_on_mesh_runs_and_shards(bundle):
    """4x2 mesh: 4 beta replicas x 2-way batch sharding, one jitted program."""
    model = tiny_model(bundle)
    mesh = make_sweep_mesh(4, 2)
    betas_end = jnp.asarray([0.03, 0.1, 0.3, 1.0])
    sweep = BetaSweepTrainer(
        model, bundle, CFG, beta_starts=1e-3, beta_ends=betas_end, mesh=mesh
    )
    keys = jax.random.split(jax.random.key(0), 4)
    states, records = sweep.fit(keys)

    # replica axis really is sharded over the beta mesh axis
    leaf = jax.tree.leaves(states.params)[0]
    assert leaf.sharding.spec == replica_sharding(mesh).spec
    assert len(records) == 4
    for r, rec in enumerate(records):
        assert rec.beta.shape == (CFG.num_epochs,)
        # beta is recorded at epoch START, so the last record sits at progress
        # (num_epochs - 1 - pre) / anneal on replica r's own log ramp
        progress = (CFG.num_epochs - 1 - CFG.num_pretraining_epochs) / (
            CFG.num_annealing_epochs
        )
        expected = CFG.beta_start * (betas_end[r] / CFG.beta_start) ** progress
        np.testing.assert_allclose(rec.beta[-1], expected, rtol=1e-4)
    # each replica annealed toward ITS OWN endpoint
    assert records[0].beta[-1] < records[-1].beta[-1]


def test_sweep_mesh_matches_no_mesh(bundle):
    """Sharding must not change the math: mesh vs no-mesh, same keys."""
    model = tiny_model(bundle)
    keys = jax.random.split(jax.random.key(3), 2)
    ends = jnp.asarray([0.1, 1.0])

    plain = BetaSweepTrainer(model, bundle, CFG, 1e-3, ends)
    _, rec_plain = plain.fit(keys, num_epochs=4)

    mesh = make_sweep_mesh(2, 2)
    sharded = BetaSweepTrainer(model, bundle, CFG, 1e-3, ends, mesh=mesh)
    _, rec_shard = sharded.fit(keys, num_epochs=4)

    for a, b in zip(rec_plain, rec_shard):
        np.testing.assert_allclose(a.loss, b.loss, rtol=5e-4, atol=1e-5)
        np.testing.assert_allclose(
            a.kl_per_feature, b.kl_per_feature, rtol=5e-4, atol=1e-5
        )


def test_sweep_higher_beta_lower_kl(bundle):
    """Physics sanity across the grid: stronger bottlenecks compress more."""
    model = tiny_model(bundle)
    cfg = TrainConfig(
        batch_size=64, beta_start=1e-3, beta_end=1.0,
        num_pretraining_epochs=10, num_annealing_epochs=60,
        steps_per_epoch=4, max_val_points=128, learning_rate=3e-3,
    )
    mesh = make_sweep_mesh(4, 2)
    # repeated-endpoint replicas differ only by seed; distinct endpoints order KL
    ends = jnp.asarray([0.01, 0.1, 1.0, 10.0])
    sweep = BetaSweepTrainer(model, bundle, cfg, 1e-3, ends, mesh=mesh)
    keys = jax.random.split(jax.random.key(11), 4)
    _, records = sweep.fit(keys)
    final_kl = np.asarray([r.total_kl[-5:].mean() for r in records])
    assert final_kl[0] > final_kl[-1], final_kl


def test_per_replica_hook_adapts_serial_hooks(bundle, tmp_path):
    """Serial hooks (MI bounds, compression matrices) run inside a sweep via
    PerReplicaHook, each replica getting its own instance and beta label."""
    from dib_tpu.parallel import PerReplicaHook
    from dib_tpu.train import CompressionMatrixHook, InfoPerFeatureHook

    model = tiny_model(bundle)
    mesh = make_sweep_mesh(2, 2)
    sweep = BetaSweepTrainer(
        model, bundle, CFG, 1e-3, jnp.asarray([0.1, 1.0]), mesh=mesh
    )
    info_hooks: dict[int, InfoPerFeatureHook] = {}

    def make_info(r):
        info_hooks[r] = InfoPerFeatureHook(64, 1, seed=r)
        return info_hooks[r]

    hooks = [
        PerReplicaHook(make_info),
        PerReplicaHook(lambda r: CompressionMatrixHook(str(tmp_path / f"r{r}"))),
    ]
    keys = jax.random.split(jax.random.key(5), 2)
    sweep.fit(keys, num_epochs=4, hooks=hooks, hook_every=2)

    assert set(info_hooks) == {0, 1}
    for hook in info_hooks.values():
        assert hook.bounds_bits.shape == (2, bundle.number_features, 2)
    pngs = sorted(p.name for p in (tmp_path / "r1").glob("*.png"))
    assert len(pngs) == 2 * bundle.number_features
    # replica 1's beta label comes from ITS endpoints (end=1.0), not replica 0's
    assert any("log10beta_" in p for p in pngs)


def test_sweep_validates_divisibility(bundle):
    model = tiny_model(bundle)
    mesh = make_sweep_mesh(4, 2)
    with pytest.raises(ValueError, match="not divisible"):
        BetaSweepTrainer(model, bundle, CFG, 1e-3, jnp.ones((6,)), mesh=mesh)
    bad_cfg = TrainConfig(batch_size=63)
    with pytest.raises(ValueError, match="batch_size"):
        BetaSweepTrainer(model, bundle, bad_cfg, 1e-3, jnp.ones((4,)), mesh=mesh)
    with pytest.raises(ValueError, match="replica keys"):
        sweep = BetaSweepTrainer(model, bundle, CFG, 1e-3, jnp.ones((4,)))
        sweep.fit(jax.random.split(jax.random.key(0), 3))


@pytest.mark.slow
def test_infonce_sweep_path(tmp_path):
    """The contrastive (InfoNCE) training path composes with the beta sweep:
    replicas carry both the model and the Y-encoder, sharded over 'beta'."""
    from dib_tpu.models import YEncoder

    bundle = get_dataset(
        "double_pendulum", num_trajectories=12, regenerate=True,
        data_path=str(tmp_path),
    )
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(16,), integration_hidden=(16,),
        output_dim=16, embedding_dim=4,
    )
    y_encoder = YEncoder(hidden=(16,), shared_dim=16)
    config = TrainConfig(
        batch_size=32, beta_start=1e-4, beta_end=1e-2,
        num_pretraining_epochs=1, num_annealing_epochs=3,
        steps_per_epoch=2, max_val_points=64,
    )
    mesh = make_sweep_mesh(2, 1, devices=jax.devices()[:2])
    sweep = BetaSweepTrainer(
        model, bundle, config, 1e-4, jnp.asarray([1e-2, 1e-1]),
        mesh=mesh, y_encoder=y_encoder,
    )
    # IDENTICAL keys for both replicas: the only cross-replica difference is
    # the beta endpoint, so differing KL trajectories prove the per-replica
    # endpoints are actually routed (not broadcast).
    same = jnp.stack([jax.random.key(0), jax.random.key(0)])
    states, records = sweep.fit(same)
    assert len(records) == 2
    for r in records:
        assert np.isfinite(r.loss).all() and np.isfinite(r.val_loss).all()
    assert not np.allclose(records[0].total_kl, records[1].total_kl)


def test_sweep_native_hooks_match_serial(bundle, tmp_path):
    """SweepInfoPerFeatureHook / SweepCompressionHook measure all replicas in
    one dispatch; their numbers must EXACTLY match the serial per-replica
    path on the same params and PRNG keys (same kernel, same key tree)."""
    from dib_tpu.parallel import SweepCompressionHook, SweepInfoPerFeatureHook
    from dib_tpu.train.hooks import _all_features_bounds_fn

    model = tiny_model(bundle)
    sweep = BetaSweepTrainer(model, bundle, CFG, 1e-3, jnp.asarray([0.1, 1.0]))
    info = SweepInfoPerFeatureHook(64, 2, seed=7)
    comp = SweepCompressionHook(str(tmp_path), features=(0, 2))
    keys = jax.random.split(jax.random.key(5), 2)
    states, _ = sweep.fit(keys, num_epochs=4, hooks=[info, comp], hook_every=2)

    assert info.epochs.tolist() == [2, 4]
    assert info.bounds_bits(0).shape == (2, bundle.number_features, 2)
    # replica-matched serial evaluation with the hook's own key chain
    key0 = jax.random.key(7)
    key1, k_call1 = jax.random.split(key0)
    replica_keys = jax.random.split(k_call1, 2)
    serial_fn = _all_features_bounds_fn(model, 64, 2, None)
    params_r0 = jax.tree.map(lambda a: a[0], states.params["model"])
    lower, upper = serial_fn(
        params_r0, jnp.asarray(bundle.x_valid), replica_keys[0]
    )
    # epoch-2 bounds were measured on the epoch-2 params, not the final ones;
    # re-measure final-state bounds for the comparison instead
    info2 = SweepInfoPerFeatureHook(64, 2, seed=7)
    info2(sweep, states, 4)
    np.testing.assert_allclose(
        info2.records[0]["bounds"][0, :, 0], np.asarray(lower), rtol=1e-5
    )
    np.testing.assert_allclose(
        info2.records[0]["bounds"][0, :, 1], np.asarray(upper), rtol=1e-5
    )

    # compression schemes: npz contents equal the per-replica encode, and
    # render() emits the serial hook's filename scheme
    import glob

    schemes = sorted(glob.glob(str(tmp_path / "schemes" / "*.npz")))
    assert len(schemes) == 2 * 2                   # 2 checkpoints x 2 features
    # final-state (epoch-4) schemes only: `states` holds the END params, so
    # only those npzs can be compared against a fresh encode (ADVICE round 3:
    # select them explicitly — lexical order puts epoch 2 first)
    final_schemes = [p for p in schemes if int(np.load(p)["epoch"]) == 4]
    assert len(final_schemes) == 2                 # one per feature
    for path in final_schemes:
        data = np.load(path)
        r1_mus, _ = sweep.encode_feature(
            states, 1, int(data["feature"]),
            jnp.asarray(sweep.base.feature_data(int(data["feature"]))),
        )
        np.testing.assert_allclose(data["mus"][1], np.asarray(r1_mus), rtol=1e-5)
    pngs = comp.render(bundle)
    assert len(pngs) == 2 * 2 * 2                  # x 2 replicas
    assert all("log10beta_" in p for p in pngs)
    assert any("replica1" in p for p in pngs)
