"""β-aware boundary anomaly detection + the anomaly-rollback machinery
(ISSUE 14, docs/robustness.md "Numerical integrity").

Contracts pinned here:

  - the detector fires on non-finite values unconditionally, on finite
    spikes only past the robust-z threshold, never before ``min_points``
    clean deltas exist in the current β phase, and never on a KL/loss
    IMPROVEMENT (one-sided scoring — an info-plane KL collapse is the
    physics, not a fault);
  - a ``sdc`` plan fault (finite param corruption) is detected at the
    next boundary, rolled back through the existing checkpoint
    machinery, and the finished history is BIT-IDENTICAL to an
    uninterrupted baseline — with durable ``anomaly`` events and an
    ``anomaly_rollback`` mitigation on the stream;
  - a rollback target that REPRODUCES the anomaly (a checkpoint written
    during an anomalous window) is quarantined and the rollback retries
    older, instead of raising "deterministic divergence" over a
    poisoned step;
  - an anomalous sweep member rides the per-replica quarantine: healed
    and spliced back bit-identically when the replay comes back clean,
    EJECTED when its restore source stays poisoned.
"""

import os
import warnings

import jax
import numpy as np
import pytest

from dib_tpu.data import get_dataset
from dib_tpu.models import DistributedIBModel
from dib_tpu.train import (
    BoundaryAnomalyDetector,
    CheckpointHook,
    DIBCheckpointer,
    DIBTrainer,
    TrainConfig,
)
from dib_tpu.train.anomaly import boundary_channels

pytestmark = pytest.mark.fault

PRE, ANNEAL, CHUNK = 2, 18, 2


@pytest.fixture(scope="module")
def bundle():
    return get_dataset("boolean_circuit")


def make_trainer(bundle):
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=bundle.output_dimensionality, embedding_dim=2,
    )
    return DIBTrainer(model, bundle, TrainConfig(
        batch_size=64, beta_start=1e-4, beta_end=1.0,
        num_pretraining_epochs=PRE, num_annealing_epochs=ANNEAL,
        steps_per_epoch=2, max_val_points=128,
    ))


# --------------------------------------------------------- detector units
def _prime(det, values, start_epoch=4, step=2, channel="loss"):
    for i, v in enumerate(values):
        assert det.observe(start_epoch + i * step, {channel: v}) == []


def test_clean_decay_never_fires():
    det = BoundaryAnomalyDetector(num_pretraining_epochs=2)
    losses = [1.0, 0.9, 0.82, 0.75, 0.7, 0.66, 0.63, 0.61]
    _prime(det, losses)


def test_spike_fires_and_never_joins_the_window():
    det = BoundaryAnomalyDetector(num_pretraining_epochs=2)
    _prime(det, [1.0, 0.9, 0.82, 0.75, 0.7, 0.66])
    findings = det.observe(16, {"loss": 50.0})
    assert len(findings) == 1
    f = findings[0]
    assert f.kind == "spike" and f.channel == "loss"
    assert f.phase == "anneal" and f.zscore > f.threshold
    # the anomalous value never contaminated the yardstick: the same
    # spike at the next boundary still fires
    assert det.observe(18, {"loss": 50.0})
    # and the clean continuation is accepted
    assert det.observe(18, {"loss": 0.63}) == []


def test_nonfinite_fires_unconditionally_even_with_no_history():
    det = BoundaryAnomalyDetector(num_pretraining_epochs=2)
    findings = det.observe(2, {"loss": float("nan")})
    assert [f.kind for f in findings] == ["nonfinite"]
    findings = det.observe(4, {"val_loss": float("inf")})
    assert [f.kind for f in findings] == ["nonfinite"]


def test_min_points_guard_and_phase_reset():
    det = BoundaryAnomalyDetector(num_pretraining_epochs=10)
    # three pretrain boundaries -> only 2 deltas, below min_points: even
    # a huge jump is observation-only
    _prime(det, [1.0, 0.9, 0.8], start_epoch=2, step=4)
    assert det.observe(14, {"loss": 1e6}) == []   # 1e6 at a fresh phase
    # anneal phase starts its OWN window: pretrain deltas don't judge it
    assert det.phase(14) == "anneal"
    _prime(det, [2.0, 1.8, 1.65, 1.5, 1.4], start_epoch=16, step=2)
    assert det.observe(26, {"loss": 500.0})


def test_kl_collapse_is_one_sided_clean():
    """A sharp KL drop is an info-plane transition — the thing the repo
    measures — and must NEVER be anomalous; the same-magnitude jump UP
    is."""
    det = BoundaryAnomalyDetector(num_pretraining_epochs=2)
    _prime(det, [3.0, 2.9, 2.85, 2.8, 2.76, 2.73], channel="kl/0")
    # transition: KL collapses by 100x the trailing delta — clean
    assert det.observe(16, {"kl/0": 0.05}) == []
    # corruption: KL jumps up by the same magnitude — fires
    assert det.observe(16, {"kl/0": 5.5})


def test_param_norm_is_two_sided():
    det = BoundaryAnomalyDetector(num_pretraining_epochs=2)
    _prime(det, [10.0, 10.2, 10.35, 10.5, 10.6, 10.7],
           channel="param_norm")
    assert det.observe(16, {"param_norm": 0.01})   # zeroed tensor
    assert det.observe(16, {"param_norm": 400.0})  # inflated tensor


def test_rewind_drops_post_rollback_observations():
    det = BoundaryAnomalyDetector(num_pretraining_epochs=2)
    _prime(det, [1.0, 0.9, 0.82, 0.75, 0.7, 0.66, 0.63])
    det.rewind(12)
    # entries past epoch 12 dropped: the replay re-observes them
    assert det.observe(14, {"loss": 0.66}) == []
    assert det.observe(16, {"loss": 0.63}) == []


def test_boundary_channels_shape():
    row = {"loss": np.float32(0.5), "val_loss": np.float32(0.6),
           "kl_per_feature": np.asarray([0.1, 0.2, 0.3], np.float32)}
    channels = boundary_channels(row, param_norm=12.5)
    assert channels == {"loss": pytest.approx(0.5),
                        "val_loss": pytest.approx(0.6),
                        "kl/0": pytest.approx(0.1),
                        "kl/1": pytest.approx(0.2),
                        "kl/2": pytest.approx(0.3),
                        "param_norm": 12.5}


# --------------------------------------------------- serial fit rollback
def test_sdc_fault_anomaly_rollback_is_bit_identical(bundle, tmp_path):
    from dib_tpu.faults import FaultPlan
    from dib_tpu.telemetry import EventWriter, read_events

    ckpt = DIBCheckpointer(str(tmp_path / "base"))
    try:
        _, base = make_trainer(bundle).fit(
            jax.random.key(0), hooks=[CheckpointHook(ckpt)],
            hook_every=CHUNK)
    finally:
        ckpt.close()

    outdir = tmp_path / "sdc"
    ckpt = DIBCheckpointer(str(outdir / "ck"))
    try:
        with EventWriter(str(outdir), run_id="anomaly-test") as writer, \
                warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            _, victim = make_trainer(bundle).fit(
                jax.random.key(0), hooks=[CheckpointHook(ckpt)],
                hook_every=CHUNK, telemetry=writer,
                fault_plan=FaultPlan.parse("sdc@chunk8:4"))
    finally:
        ckpt.close()

    assert any("anomalous (finite-SDC-shaped)" in str(w.message)
               for w in caught)
    events = list(read_events(str(outdir)))
    anomalies = [e for e in events if e.get("type") == "anomaly"]
    assert anomalies and all(e["kind"] == "spike" for e in anomalies)
    assert all(e["phase"] == "anneal" for e in anomalies)
    mits = [e["mtype"] for e in events if e.get("type") == "mitigation"]
    assert mits.count("anomaly_rollback") == 1
    assert "divergence_rollback" not in mits
    for field in ("beta", "kl_per_feature", "loss", "val_loss"):
        assert np.array_equal(getattr(base, field), getattr(victim, field))
    # the integrity rollup carries the story for the SLO gate
    from dib_tpu.telemetry import summarize

    integrity = summarize(str(outdir))["integrity"]
    assert integrity["anomaly_rollbacks"] == 1
    assert integrity["anomalies"] == len(anomalies)


class _PoisonOnceRestore:
    """Checkpointer proxy whose FIRST restore_latest_intact hands back a
    finitely-corrupted state — the 'checkpoint written during an
    anomalous window' shape: restoring it reproduces the anomaly."""

    def __init__(self, ckpt, factor=4.0):
        self._ckpt = ckpt
        self._factor = factor
        self.poisoned = 0

    def restore_latest_intact(self, *args, **kwargs):
        from dib_tpu.faults import scale_params

        state, history, key = self._ckpt.restore_latest_intact(
            *args, **kwargs)
        if self.poisoned == 0:
            self.poisoned += 1
            state = state._replace(
                params=scale_params(state.params, self._factor))
        return state, history, key

    def __getattr__(self, attr):
        return getattr(self._ckpt, attr)


def test_recurring_anomaly_quarantines_the_rollback_target(
        bundle, tmp_path):
    """The poisoned-target escalation: when the restored checkpoint
    reproduces the anomaly, that step is QUARANTINED and the rollback
    retries from an older step — the fit completes bit-identically
    instead of raising over a poisoned step."""
    from dib_tpu.faults import FaultPlan
    from dib_tpu.telemetry import EventWriter, read_events

    ckpt = DIBCheckpointer(str(tmp_path / "base"))
    try:
        _, base = make_trainer(bundle).fit(
            jax.random.key(0), hooks=[CheckpointHook(ckpt)],
            hook_every=CHUNK)
    finally:
        ckpt.close()

    outdir = tmp_path / "poisoned"
    real = DIBCheckpointer(str(outdir / "ck"))
    wrapper = _PoisonOnceRestore(real)
    try:
        with EventWriter(str(outdir), run_id="quarantine-test") as writer, \
                warnings.catch_warnings():
            warnings.simplefilter("ignore")
            _, victim = make_trainer(bundle).fit(
                jax.random.key(0), hooks=[CheckpointHook(wrapper)],
                hook_every=CHUNK, telemetry=writer,
                fault_plan=FaultPlan.parse("sdc@chunk8:4"))
    finally:
        real.close()

    assert wrapper.poisoned == 1
    events = list(read_events(str(outdir)))
    quars = [e for e in events if e.get("type") == "quarantine"]
    assert len(quars) == 1
    assert quars[0]["step"] == 16
    assert "anomalous window" in quars[0]["reason"]
    assert os.path.isdir(os.path.join(str(outdir / "ck"),
                                      "quarantine", "16"))
    mits = [e["mtype"] for e in events if e.get("type") == "mitigation"]
    assert mits.count("anomaly_rollback") == 2   # original + retry
    for field in ("beta", "kl_per_feature", "loss", "val_loss"):
        assert np.array_equal(getattr(base, field), getattr(victim, field))


def test_quarantine_budget_exhaustion_raises_deterministic(
        bundle, tmp_path):
    """A restore source that stays poisoned past the quarantine budget
    is genuinely deterministic and must raise, not consume the whole
    checkpoint history."""
    from dib_tpu.faults import FaultPlan

    outdir = tmp_path / "always_poisoned"
    real = DIBCheckpointer(str(outdir / "ck"))

    class _AlwaysPoison(_PoisonOnceRestore):
        def restore_latest_intact(self, *args, **kwargs):
            from dib_tpu.faults import scale_params

            state, history, key = self._ckpt.restore_latest_intact(
                *args, **kwargs)
            self.poisoned += 1
            return state._replace(
                params=scale_params(state.params, self._factor)), \
                history, key

    wrapper = _AlwaysPoison(real)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with pytest.raises(RuntimeError,
                               match="deterministic"):
                make_trainer(bundle).fit(
                    jax.random.key(0), hooks=[CheckpointHook(wrapper)],
                    hook_every=CHUNK,
                    fault_plan=FaultPlan.parse("sdc@chunk8:4"))
    finally:
        real.close()
    # budget: 2 quarantines -> 3 poisoned restores, then the raise
    assert wrapper.poisoned == 3


# ------------------------------------------------------ sweep anomalies
def test_replica_sdc_heals_member_bit_identically(bundle, tmp_path):
    """A finite-garbage member lane rides the per-replica quarantine:
    healed by the original-width replay, spliced back bit-identically,
    neighbor untouched."""
    from dib_tpu.faults import FaultPlan
    from dib_tpu.parallel import BetaSweepTrainer
    from dib_tpu.telemetry import EventWriter, read_events

    def make_sweep():
        model = DistributedIBModel(
            feature_dimensionalities=tuple(
                bundle.feature_dimensionalities),
            encoder_hidden=(8,), integration_hidden=(16,),
            output_dim=bundle.output_dimensionality, embedding_dim=2,
        )
        return BetaSweepTrainer(
            model, bundle, TrainConfig(
                batch_size=64, beta_start=1e-4,
                num_pretraining_epochs=PRE, num_annealing_epochs=ANNEAL,
                steps_per_epoch=2, max_val_points=128),
            1e-4, [0.5, 1.0],
        )

    keys = jax.random.split(jax.random.key(0), 2)
    ckpt = DIBCheckpointer(str(tmp_path / "base"))
    try:
        _, base_records = make_sweep().fit(
            keys, hooks=[CheckpointHook(ckpt)], hook_every=CHUNK)
    finally:
        ckpt.close()

    outdir = tmp_path / "victim"
    ckpt = DIBCheckpointer(str(outdir / "ck"))
    try:
        with EventWriter(str(outdir), run_id="sweep-sdc") as writer, \
                warnings.catch_warnings():
            warnings.simplefilter("ignore")
            _, victim_records = make_sweep().fit(
                keys, hooks=[CheckpointHook(ckpt)], hook_every=CHUNK,
                telemetry=writer,
                fault_plan=FaultPlan.parse("replica_sdc@chunk8:1"))
    finally:
        ckpt.close()

    events = list(read_events(str(outdir)))
    anomalies = [e for e in events if e.get("type") == "anomaly"]
    assert anomalies and all(e.get("replica") == 1 for e in anomalies)
    mits = [e for e in events if e.get("type") == "mitigation"]
    rollbacks = [m for m in mits if m["mtype"] == "anomaly_rollback"]
    assert len(rollbacks) == 1 and rollbacks[0]["replica"] == 1
    assert not any(m["mtype"] == "replica_ejected" for m in mits)
    for r in range(2):
        for field in ("beta", "kl_per_feature", "loss", "val_loss"):
            assert np.array_equal(getattr(base_records[r], field),
                                  getattr(victim_records[r], field)), \
                f"member {r} field {field}"
        assert victim_records[r].ejected is False
