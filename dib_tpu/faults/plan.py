"""Deterministic, seeded fault plans: what breaks, where, on purpose.

Every mitigation this framework ships — the stall watchdog, checkpoint
resume, the divergence rollback, serve-replica ejection — exists because a
real failure mode burned a run (VERDICT rounds 4-5). But until this module
each of them was validated only by whatever faults the tunneled hardware
happened to throw: "the watchdog has never faced a real stall" on demand.
A :class:`FaultPlan` makes failure a first-class, reproducible input:

    DIB_FAULT_PLAN=stall@chunk3:45s,kill@chunk5,nan@chunk7

Each spec is ``kind@chunkN[:ARG]`` — fire fault ``kind`` at the N-th fit
chunk boundary (1-based, counted per process launch). The training loop
applies due specs at its chunk boundaries (``train/loop.py``), emits a
``fault`` event on the run's events.jsonl for every injection (drills are
auditable: injected vs detected vs recovered is computable from the
stream, see ``telemetry/summary.py:faults_rollup``), and marks the spec
fired in ``state_dir`` so a fault survives its own consequences exactly
once — a worker SIGKILLed at chunk 5 and relaunched from its checkpoint
must not kill itself at chunk 5 again, forever.

The registry below names every fault kind the drill matrix covers. Only
the ``train``-scoped kinds are injectable through the plan grammar (they
fire inside a fit); checkpoint corruption and serve faults are injected
programmatically by ``scripts/fault_drill.py`` and the tests through
:mod:`dib_tpu.faults.inject` / :mod:`dib_tpu.faults.serve`.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Sequence

__all__ = ["FAULT_KINDS", "FaultPlan", "FaultSpec"]

PLAN_ENV = "DIB_FAULT_PLAN"
STATE_DIR_ENV = "DIB_FAULT_STATE_DIR"

# kind -> (scope, arg meaning or None, description). Scope "train" = plan-
# grammar injectable at fit chunk boundaries; "checkpoint"/"serve"/"http" =
# injected via dib_tpu.faults.inject / dib_tpu.faults.serve by drills.
FAULT_KINDS: dict[str, tuple[str, str | None, str]] = {
    "stall": ("train", "seconds",
              "simulated device stall: sleep inside the heartbeat-visible "
              "window so the watchdog's trailing-median timeout fires"),
    "kill": ("train", None,
             "SIGKILL the worker process at the boundary (after its "
             "checkpoint hook ran) — the crash-restart path"),
    "nan": ("train", None,
            "poison one param leaf with NaN so the next chunk's loss/KL "
            "are non-finite — the divergence-rollback path"),
    "inf": ("train", None,
            "poison one param leaf with +Inf (same detector as 'nan')"),
    "ckpt_truncate": ("checkpoint", None,
                      "truncate the largest file of the latest Orbax step "
                      "dir (torn write / partial flush)"),
    "ckpt_bitflip_manifest": ("checkpoint", None,
                              "flip one byte of dib_manifest.json (bit rot "
                              "/ torn manifest write)"),
    "replica_error": ("serve", "count",
                      "a serve replica whose dispatches raise — the "
                      "consecutive-failure ejection path"),
    "replica_slow": ("serve", "seconds",
                     "a serve replica whose dispatches sleep past request "
                     "deadlines — ejection via timeout failures"),
    "batcher_crash": ("serve", None,
                      "kill a micro-batcher's worker thread — the truthful "
                      "/healthz 503 path"),
    "http_malformed": ("http", None,
                       "invalid JSON / wrong-width rows / dropped "
                       "connections against the HTTP server"),
    "replica_nan": ("train", "replica",
                    "poison ONE sweep member's params slice with NaN — "
                    "the per-replica divergence quarantine / ejection "
                    "path (sweep fits; arg = replica index)"),
    "preempt": ("train", None,
                "SIGTERM own process at the boundary — the cooperative "
                "preemption path: chunk-aligned checkpoint, 'preempted' "
                "run status, distinct exit the watchdog relaunches "
                "without backoff"),
    "desync": ("multihost", None,
               "one host arrives at the chunk barrier with a stale "
               "(run_id, chunk, git_sha) — the desync guard names it "
               "instead of hanging; injected by the drill harness"),
    "sdc": ("train", "scale",
            "silent data corruption: scale every param leaf by a FINITE "
            "factor so the next boundary's metrics are garbage but never "
            "NaN — the β-aware anomaly-rollback path (train/anomaly.py); "
            "arg = the scale factor"),
    "replica_sdc": ("train", "replica",
                    "finite SDC on ONE sweep member: scale member r's "
                    "param slices so its lane goes anomalous without a "
                    "NaN — the per-replica anomaly quarantine/ejection "
                    "path (arg = replica index; the scale factor is "
                    "faults.inject.SDC_SCALE)"),
    "ckpt_bitflip_payload": ("checkpoint", None,
                             "flip ONE BIT in a retained step's payload "
                             "bytes (structure intact, bytes wrong) — "
                             "the content-digest / scrub detection path "
                             "(manifest schema v3)"),
    "sched_worker_kill": ("sched", "chunk",
                          "kill one pool worker dead mid-unit (no release, "
                          "no fail — its lease just goes silent): the "
                          "pool degrades to N-1 and the reaper steals the "
                          "unit for a live worker, which resumes it from "
                          "its newest intact checkpoint"),
    "lease_expire": ("sched", None,
                     "force a held lease past its deadline while the "
                     "holder still runs — the work-stealing path: a live "
                     "worker re-leases the unit, and the stale holder's "
                     "renewal/completion is REJECTED (no double-execution)"),
    "journal_torn": ("sched", None,
                     "tear the scheduler journal mid-append (the SIGKILL-"
                     "mid-write shape) — replay on scheduler restart skips "
                     "the torn line, recovers the queue, and surfaces a "
                     "journal_recovered mitigation"),
}

# Plan-grammar kinds whose ARG is mandatory (the others default sensibly).
_ARG_REQUIRED = ("stall", "replica_nan", "sdc", "replica_sdc")

_SPEC_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@chunk(?P<chunk>\d+)(?::(?P<arg>[\d.]+)s?)?$"
)


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One planned injection: ``kind`` at the ``chunk``-th fit boundary."""

    kind: str
    chunk: int
    arg: float | None
    raw: str

    @property
    def marker(self) -> str:
        """Filename marking this spec fired (state survives SIGKILL).

        The arg participates so two same-kind specs at one boundary with
        different args (e.g. two replica_nan targets) fire independently.
        """
        suffix = "" if self.arg is None else f"_{self.arg:g}"
        return f"fault_fired_{self.kind}_chunk{self.chunk}{suffix}"


class FaultPlan:
    """A parsed, once-only-per-spec fault schedule.

    ``state_dir``: where fired-markers persist. Without one, fired state is
    in-memory only — fine for in-process drills, but a plan that SIGKILLs
    its own process NEEDS a directory or the relaunch re-fires it.
    """

    def __init__(self, specs: Sequence[FaultSpec], state_dir: str | None = None):
        self.specs = list(specs)
        self.state_dir = state_dir
        self._fired_memory: set[str] = set()
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)

    # ------------------------------------------------------------- parsing
    @classmethod
    def parse(cls, text: str, state_dir: str | None = None) -> "FaultPlan":
        specs = []
        for token in filter(None, (t.strip() for t in text.split(","))):
            m = _SPEC_RE.match(token)
            if m is None:
                raise ValueError(
                    f"Unparseable fault spec {token!r}; expected "
                    "kind@chunkN[:SECONDSs], e.g. stall@chunk3:45s"
                )
            kind = m.group("kind")
            if kind not in FAULT_KINDS:
                raise ValueError(
                    f"Unknown fault kind {kind!r}; known kinds: "
                    f"{sorted(FAULT_KINDS)}"
                )
            scope, arg_name, _ = FAULT_KINDS[kind]
            if scope != "train":
                raise ValueError(
                    f"Fault kind {kind!r} has scope {scope!r} — it is "
                    "injected by the drill harness (dib_tpu.faults."
                    "inject/serve), not through the chunk-boundary plan "
                    "grammar"
                )
            arg = m.group("arg")
            if kind in _ARG_REQUIRED and arg is None:
                example = ("stall@chunk3:45s" if kind == "stall"
                           else f"{kind}@chunk3:1")
                raise ValueError(
                    f"Fault spec {token!r} needs an argument "
                    f"({arg_name}), e.g. {example}"
                )
            specs.append(FaultSpec(
                kind=kind, chunk=int(m.group("chunk")),
                arg=float(arg) if arg is not None else None, raw=token,
            ))
        return cls(specs, state_dir=state_dir)

    @classmethod
    def from_env(cls, state_dir: str | None = None) -> "FaultPlan | None":
        """The env-driven entry point (``DIB_FAULT_PLAN``); None when unset.

        ``DIB_FAULT_STATE_DIR`` overrides the caller's ``state_dir`` (the
        drill harness pins one so fired-markers survive worker relaunches).
        """
        text = os.environ.get(PLAN_ENV, "")
        if not text:
            return None
        return cls.parse(text, state_dir=os.environ.get(STATE_DIR_ENV) or state_dir)

    # ----------------------------------------------------------- firing
    def fired(self, spec: FaultSpec) -> bool:
        if spec.marker in self._fired_memory:
            return True
        if self.state_dir:
            return os.path.exists(os.path.join(self.state_dir, spec.marker))
        return False

    def mark_fired(self, spec: FaultSpec) -> None:
        """Record the spec as fired BEFORE executing it — a kill fault must
        leave its marker behind or the relaunched worker repeats it."""
        self._fired_memory.add(spec.marker)
        if self.state_dir:
            path = os.path.join(self.state_dir, spec.marker)
            with open(path, "w") as f:
                f.write(spec.raw + "\n")

    def due(self, chunk_index: int) -> list[FaultSpec]:
        """Not-yet-fired specs scheduled for this (1-based) boundary."""
        return [s for s in self.specs
                if s.chunk == chunk_index and not self.fired(s)]

    def __bool__(self) -> bool:
        return bool(self.specs)

    def __repr__(self) -> str:
        return f"FaultPlan({', '.join(s.raw for s in self.specs)})"
