"""Deterministic fault injection: prove every recovery path on demand.

See ``docs/robustness.md``. The pieces:

  - :mod:`dib_tpu.faults.plan` — the ``DIB_FAULT_PLAN`` grammar
    (``stall@chunk3:45s,kill@chunk5,nan@chunk7``), the fault-kind registry,
    and once-only fired-state that survives the faults' own kills.
  - :mod:`dib_tpu.faults.inject` — train-scope executors applied at fit
    chunk boundaries (stall / kill / nan / inf) and checkpoint corruption
    (truncated step dir, bit-flipped manifest).
  - :mod:`dib_tpu.faults.serve` — serve-scope injectors: a
    :class:`FlakyEngine` replica that fails or crawls on schedule, and a
    batcher-worker crash.

Every injection lands as a ``fault`` event on the run's events.jsonl;
``python -m dib_tpu telemetry summarize`` joins faults with the
mitigations they provoked into an injected/detected/recovered rollup, and
``scripts/fault_drill.py`` runs the whole matrix end to end on CPU.
"""

from dib_tpu.faults.inject import (
    SDC_SCALE,
    PoisonedReplicaRestore,
    apply_due_train_faults,
    corrupt_checkpoint,
    expire_lease,
    poison_params,
    poison_replica_params,
    scale_params,
    scale_replica_params,
    tear_journal,
)
from dib_tpu.faults.plan import FAULT_KINDS, FaultPlan, FaultSpec
from dib_tpu.faults.serve import (
    FlakyEngine,
    InjectedReplicaFault,
    kill_batcher_worker,
)

__all__ = [
    "FAULT_KINDS",
    "SDC_SCALE",
    "FaultPlan",
    "FaultSpec",
    "FlakyEngine",
    "InjectedReplicaFault",
    "PoisonedReplicaRestore",
    "apply_due_train_faults",
    "corrupt_checkpoint",
    "expire_lease",
    "kill_batcher_worker",
    "poison_params",
    "poison_replica_params",
    "scale_params",
    "scale_replica_params",
    "tear_journal",
]
